#!/usr/bin/env python
"""Online-model-lifecycle smoke for the nightly suite
(docs/serving.md "Online model lifecycle").

Two scenarios, end to end against real replica processes:

1. **Swap under traffic.**  Serve a base model from a 2-replica fleet,
   drive sustained client traffic, continuation-train a candidate on
   fresh rows, gate it, and hot-swap it in (with a shadow phase).
   Assert ZERO dropped/failed requests across the swap, post-swap
   predictions bitwise-stable, and a gate-rejected follow-up cycle
   leaving those bits untouched.  The p99 of requests issued during the
   swap window is printed next to steady-state p99 (recorded, not
   gated — this host is time-shared).

2. **Kill mid-swap.**  Replay the cycle in a subprocess with a
   ``lifecycle.swap`` kill fault installed: the manager dies (hard
   ``os._exit``) after the candidate is loaded onto replicas but BEFORE
   the durable ``set_active`` commit.  Assert the store manifest still
   names the incumbent and a RESTARTED fleet over the same store serves
   the incumbent's exact bits.

Usage: JAX_PLATFORMS=cpu python scripts/lifecycle_smoke.py [n_replicas] [reqs]
"""
import os
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

N_CLIENTS = 4
BATCH = 64
KILL_EXIT = 43  # faults.py FaultSpec.exit_code default


def _data(seed, n=3000, f=8):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


PARAMS = {"objective": "binary:logistic", "max_depth": 3,
          "eval_metric": "logloss", "seed": 7}


def _publish_base(store_dir):
    """Deterministic base model -> store v1 (shared by both scenarios and
    the kill-replay child, which re-derives nothing from the parent)."""
    import xgboost_tpu as xtb
    from xgboost_tpu.serving import ModelStore

    X, y = _data(seed=20)
    base = xtb.train(PARAMS, xtb.DMatrix(X[:2000], label=y[:2000]), 4,
                     verbose_eval=False)
    st = ModelStore(store_dir)
    st.publish("m", base)
    st.set_active("m", 1)
    return X, y, base


def swap_under_traffic(workdir, n_replicas, total_requests) -> int:
    from xgboost_tpu.lifecycle import LifecycleConfig, LifecycleManager
    from xgboost_tpu.serving import ServingFleet

    store_dir = os.path.join(workdir, "store")
    X, y, base = _publish_base(store_dir)
    Xq = X[:BATCH]

    lats = []  # (t_issued, latency)
    lats_lock = threading.Lock()
    errors, stop = [], threading.Event()

    with ServingFleet(store_dir=store_dir, n_replicas=n_replicas,
                      cache_dir=os.path.join(workdir, "cache"),
                      warmup_buckets=(BATCH,)) as fleet:
        ref1 = fleet.predict("m", Xq, timeout=120)

        def client(tid):
            # continuous until stopped: every issued request must complete
            # (a dropped one surfaces as an exception -> errors)
            try:
                while not stop.is_set():
                    t0 = time.perf_counter()
                    fleet.predict("m", Xq, timeout=600)
                    with lats_lock:
                        lats.append((t0, time.perf_counter() - t0))
            except BaseException as e:
                errors.append(f"client{tid}: {e!r}")

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(N_CLIENTS)]
        for t in threads:
            t.start()

        mgr = LifecycleManager(fleet, "m", config=LifecycleConfig(
            rounds_per_cycle=3, checkpoint_dir=os.path.join(workdir, "ckpt"),
            shadow_fraction=0.25, shadow_min_pairs=2))
        t_swap0 = time.perf_counter()
        rep = mgr.run_cycle((X[2000:], y[2000:]),
                            eval_window=(X[:2000], y[:2000]))
        t_swap1 = time.perf_counter()
        if not rep.swapped:
            errors.append(f"cycle did not swap: {rep.decision}")
        elif (rep.shadow or {}).get("pairs", 0) < 2:
            errors.append(f"shadow phase never scored: {rep.shadow}")
        if rep.swapped:
            out = fleet.predict("m", Xq, timeout=120)
            if np.array_equal(out, ref1):
                errors.append("post-swap predictions identical to incumbent "
                              "(swap did not take)")
            for _ in range(3):
                if not np.array_equal(fleet.predict("m", Xq, timeout=120),
                                      out):
                    errors.append("post-swap predictions NOT bitwise-stable")
                    break
            # gate-rejected follow-up cycle must leave the new bits alone
            from xgboost_tpu.lifecycle import GateConfig
            rej = LifecycleManager(fleet, "m", config=LifecycleConfig(
                rounds_per_cycle=1, gate=GateConfig(min_improvement=1e9)))
            rep2 = rej.run_cycle((X[2000:], y[2000:]))
            if rep2.swapped or rep2.decision.reason != "metric":
                errors.append(f"reject cycle misbehaved: {rep2.decision}")
            elif not np.array_equal(fleet.predict("m", Xq, timeout=120), out):
                errors.append("gate-rejected cycle disturbed serving bits")

        stop.set()
        for t in threads:
            t.join(900)
        if any(t.is_alive() for t in threads):
            errors.append("clients never finished")

    done = len(lats)
    during = [dt for (t0, dt) in lats if t_swap0 <= t0 <= t_swap1]
    steady = [dt for (t0, dt) in lats if t0 < t_swap0 or t0 > t_swap1]
    p99_d = float(np.percentile(during, 99)) if during else 0.0
    p99_s = float(np.percentile(steady, 99)) if steady else 0.0
    print(f"lifecycle swap-under-traffic: {done} requests completed, zero "
          f"failed, through a hot swap ({len(during)} issued during the "
          f"{t_swap1 - t_swap0:.2f}s cycle); p99 during={p99_d * 1e3:.1f}ms "
          f"steady={p99_s * 1e3:.1f}ms; shadow pairs="
          f"{(rep.shadow or {}).get('pairs', 0)}")
    if errors:
        print(f"FAIL: {errors[:5]}", file=sys.stderr)
        return 1
    if done < total_requests:
        print(f"FAIL: only {done}/{total_requests} requests flowed — not "
              f"enough traffic to exercise the swap", file=sys.stderr)
        return 1
    return 0


def kill_replay_child(store_dir) -> None:
    """Child body: drive a cycle with a lifecycle.swap KILL installed.
    os._exit fires after load/shadow, before the durable commit."""
    from xgboost_tpu.lifecycle import LifecycleConfig, LifecycleManager
    from xgboost_tpu.reliability import faults
    from xgboost_tpu.serving import ServingFleet

    X, y = _data(seed=20)
    with ServingFleet(store_dir=store_dir, n_replicas=1,
                      warmup_buckets=(BATCH,)) as fleet:
        fleet.predict("m", X[:BATCH], timeout=120)  # serving for real
        faults.install([{"site": "lifecycle.swap", "kind": "kill"}])
        mgr = LifecycleManager(fleet, "m",
                               config=LifecycleConfig(rounds_per_cycle=2))
        mgr.run_cycle((X[2000:], y[2000:]),
                      eval_window=(X[:2000], y[:2000]))
    print("UNREACHABLE: kill fault never fired", file=sys.stderr)
    sys.exit(2)


def kill_mid_swap(workdir, n_replicas) -> int:
    from xgboost_tpu.serving import ModelStore, ServingFleet

    store_dir = os.path.join(workdir, "killstore")
    X, y, base = _publish_base(store_dir)
    import xgboost_tpu as xtb

    Xq = X[:BATCH]
    ref = base.predict(xtb.DMatrix(Xq))

    child = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--kill-child",
         store_dir],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        start_new_session=True, timeout=600)
    if child.returncode != KILL_EXIT:
        print(f"FAIL: kill child exited {child.returncode}, expected "
              f"{KILL_EXIT} (the lifecycle.swap kill)", file=sys.stderr)
        return 1

    st = ModelStore(store_dir)
    if st.active_version("m") != 1:
        print(f"FAIL: manifest moved to v{st.active_version('m')} despite "
              f"dying before the commit", file=sys.stderr)
        return 1
    # the crash contract: a RESTARTED fleet over the same store serves the
    # incumbent's exact bits
    with ServingFleet(store_dir=store_dir, n_replicas=n_replicas,
                      warmup_buckets=(BATCH,)) as fleet:
        out = fleet.predict("m", Xq, timeout=120)
    if not np.array_equal(out, ref):
        print("FAIL: restarted fleet does not serve the incumbent's bits",
              file=sys.stderr)
        return 1
    print(f"lifecycle kill-mid-swap: child died at the seam (exit "
          f"{KILL_EXIT}), manifest still v1, restarted fleet serves the "
          f"incumbent bitwise")
    return 0


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--kill-child":
        kill_replay_child(sys.argv[2])
        return 2  # unreachable

    n_replicas = int(sys.argv[1]) if len(sys.argv) > 1 else 2
    reqs = int(sys.argv[2]) if len(sys.argv) > 2 else 80

    workdir = tempfile.mkdtemp(prefix="xtb_lifecycle_smoke_")
    rc = swap_under_traffic(workdir, n_replicas, reqs)
    rc = rc or kill_mid_swap(workdir, n_replicas)
    if rc == 0:
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)
        print("lifecycle smoke OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
