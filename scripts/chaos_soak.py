#!/usr/bin/env python
"""Composed-fault chaos soak (docs/reliability.md "Integrity & chaos").

Nightly entry point for ``xgboost_tpu.reliability.chaos``: run seeded
multi-fault episodes round-robin across the scenario templates under a
wall-clock budget, check every invariant (no hang, no silent wrong bits,
fault accounting, no dropped requests, flight dump per death), finish
with a replay of the first episode's seed (schedule AND outcome must be
bit-for-bit identical), and write the full report to
``bench_out/CHAOS_SOAK.json``.  Exit 0 only when every episode is green
and the replay matched.

Usage::

    python scripts/chaos_soak.py --budget-s 120 --seed $NIGHTLY_SEED
    python scripts/chaos_soak.py --replay extmem 123456   # one red episode
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="composed-fault chaos soak")
    ap.add_argument("--budget-s", type=float, default=120.0,
                    help="soak wall-clock budget in seconds")
    ap.add_argument("--seed", type=int, default=20260804,
                    help="master seed (episode seeds derive from it)")
    ap.add_argument("--min-episodes", type=int, default=20,
                    help="minimum episodes even if the budget runs dry "
                         "(cheap scenarios fill the tail)")
    ap.add_argument("--scenarios", default="",
                    help="comma-separated scenario subset (default: all)")
    ap.add_argument("--out", default="bench_out/CHAOS_SOAK.json")
    ap.add_argument("--replay", nargs=2, metavar=("SCENARIO", "SEED"),
                    help="replay ONE episode by (scenario, seed) and "
                         "print its report — the red-episode repro path")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from xgboost_tpu.reliability import chaos

    if args.replay:
        scenario, seed = args.replay[0], int(args.replay[1])
        rep = chaos.run_episode(scenario, seed)
        print(json.dumps(rep.to_json(), indent=1))
        print(f"[chaos] replay {scenario}/{seed}: "
              f"{'GREEN' if rep.ok else 'RED'} in {rep.seconds:.1f}s")
        return 0 if rep.ok else 1

    scenarios = ([s for s in args.scenarios.split(",") if s]
                 if args.scenarios else None)
    report = chaos.soak(args.seed, budget_s=args.budget_s,
                        min_episodes=args.min_episodes,
                        scenarios=scenarios)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=1)
    for ep in report["episodes"]:
        status = "green" if ep["ok"] else "RED"
        bad = {k: v for k, v in ep["invariants"].items() if v != "ok"}
        print(f"[chaos] {ep['scenario']:<10} seed={ep['seed']:<12} "
              f"{status:<5} {ep['seconds']:6.1f}s "
              f"faults={len(ep['plan']['faults'])}"
              + (f"  {bad}" if bad else ""))
    rp = report["replay"]
    if rp is not None:
        print(f"[chaos] replay {rp['scenario']}/{rp['seed']}: schedule "
              f"{'==' if rp['schedule_identical'] else '!='} outcome "
              f"{'==' if rp['outcome_identical'] else '!='}")
    print(f"[chaos] {report['green']} green / {report['red']} red in "
          f"{report['wall_s']:.1f}s (budget {args.budget_s}s, "
          f"{report['downgraded']} budget downgrades) -> {args.out}")
    if not report["ok"]:
        for ep in report["episodes"]:
            if not ep["ok"]:
                print(f"[chaos] repro: python scripts/chaos_soak.py "
                      f"--replay {ep['scenario']} {ep['seed']}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
