#!/usr/bin/env python
"""Observability overhead guard -> BENCH_OBS.json.

Measures the wall-clock cost of the distributed telemetry plane's
always-on work — registry snapshot + JSON serialization + merged-registry
ingest (the per-round / per-interval shipping a training rank or fleet
replica pays) PLUS the sampling wall profiler armed at its default rate
(telemetry/profiler.py, `XGBOOST_TPU_PROF_HZ`) — on the higgs ladder
config shape (binary:logistic, 28 features, max_depth=8, eta=0.3,
max_bin=256, 5 rounds; rows = 11M * BENCH_OBS_SCALE).

Two legs, each timed observability-OFF then observability-ON:

- **train**: `xtb.train` bare (profiler stopped) vs with
  `TelemetryCallback(enable_spans=False)` + a per-round snapshot ship
  (the tracker-channel cadence) + the profiler sampling at DEFAULT_HZ.
  Spans stay off in both legs — they are a separate opt-in; this guard
  isolates the default-on plane.
- **serve**: a closed loop of direct engine predicts vs the same loop
  shipping on the replica cadence (`XGBOOST_TPU_TELEMETRY_INTERVAL`)
  with the profiler armed, the `/metrics` scrape endpoint running and
  scraped once mid-leg.

Convention matches bench_serve.py: every timed section repeats
``BENCH_OBS_REPS`` times (default 3) and reports the MINIMUM wall
(min-of-N estimates the code's actual cost on a time-shared host).
The guard fails (exit 1) when the shipping-on overhead exceeds
``BENCH_OBS_MAX_PCT`` (default 5%) on either leg.

Usage:  python scripts/bench_obs.py [out.json]   (default BENCH_OBS.json)
        BENCH_OBS_SCALE (default 0.02 -> 220k rows), BENCH_OBS_REPS,
        BENCH_OBS_MAX_PCT, BENCH_OBS_ROUNDS (default 5)
"""
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

HIGGS = dict(cols=28, objective="binary:logistic", max_depth=8, eta=0.3,
             max_bin=256)


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


def make_higgs(scale: float, seed: int = 0):
    rng = np.random.default_rng(seed)
    R = int(11_000_000 * scale)
    X = rng.normal(size=(R, HIGGS["cols"])).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] * X[:, 2] > 0).astype(np.float32)
    return X, y


def _ship_once(merged, source):
    """The per-ship work a worker/replica pays: snapshot + JSON encode
    (what goes on the wire) + driver-side ingest."""
    from xgboost_tpu.telemetry import distributed

    payload = distributed.snapshot_payload()
    json.dumps(payload)  # the wire bytes a real ship serializes
    merged.ingest_payload(source, payload)


def _set_profiler(on: bool) -> None:
    """ON legs sample at the default rate (what a fresh process runs);
    OFF legs have the sampler fully stopped (XGBOOST_TPU_PROF_HZ=0)."""
    from xgboost_tpu.telemetry import profiler

    if on:
        profiler.start(hz=profiler.DEFAULT_HZ)
    else:
        profiler.stop()


def bench_train(X, y, rounds, reps):
    import xgboost_tpu as xtb
    from xgboost_tpu import telemetry
    from xgboost_tpu.telemetry import distributed

    params = {"objective": HIGGS["objective"],
              "max_depth": HIGGS["max_depth"], "eta": HIGGS["eta"],
              "max_bin": HIGGS["max_bin"]}
    d = xtb.DMatrix(X, label=y)
    merged = distributed.MergedRegistry()

    class _ShippingCallback(telemetry.TelemetryCallback):
        def after_iteration(self, model, epoch, evals_log):
            out = super().after_iteration(model, epoch, evals_log)
            _ship_once(merged, "rank0")
            return out

    def run(shipping: bool) -> float:
        cb = ([_ShippingCallback(enable_spans=False)] if shipping else None)
        _set_profiler(shipping)
        t0 = time.perf_counter()
        xtb.train(params, d, rounds, callbacks=cb, verbose_eval=False)
        dt = time.perf_counter() - t0
        _set_profiler(False)
        return dt

    run(False)  # warm the compile caches once; both legs measure steady
    # interleaved off/on reps: host-noise bursts hit both legs equally
    # instead of biasing whichever leg ran during the burst
    offs, ons = [], []
    for _ in range(reps):
        offs.append(run(False))
        ons.append(run(True))
    return min(offs), min(ons)


def bench_serve(X, y, reps, batch=256):
    """Closed predict loop for a FIXED duration per rep (long enough to
    amortize several ship intervals); reports walls normalized to the
    off-leg's request count so the two legs compare like-for-like."""
    import xgboost_tpu as xtb
    from xgboost_tpu.serving import ServeConfig, ServingEngine
    from xgboost_tpu.telemetry import distributed

    params = {"objective": HIGGS["objective"], "max_depth": 6,
              "eta": HIGGS["eta"], "max_bin": HIGGS["max_bin"]}
    bst = xtb.train(params, xtb.DMatrix(X[:50_000], label=y[:50_000]), 5,
                    verbose_eval=False)
    eng = ServingEngine(ServeConfig(use_batcher=False))
    eng.add_model("m", bst)
    Xq = X[:batch]
    eng.predict("m", Xq, direct=True)  # warm the serve program
    merged = distributed.MergedRegistry()
    interval = distributed.ship_interval()
    leg_s = max(_env_float("BENCH_OBS_LEG_S", 4.0), 2.0 * interval)
    srv = distributed.MetricsServer(0, merged=merged).start()
    try:
        def run(shipping: bool) -> float:
            """requests/second over one fixed-duration leg."""
            last = time.monotonic()
            n = 0
            _set_profiler(shipping)
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < leg_s:
                eng.predict("m", Xq, direct=True)
                n += 1
                if shipping:
                    now = time.monotonic()
                    if now - last >= interval:
                        last = now
                        _ship_once(merged, "replica0")
            rate = n / (time.perf_counter() - t0)
            _set_profiler(False)
            return rate

        # one scrape mid-bench, like a live Prometheus target
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10).read()
        offs, ons = [], []
        for _ in range(reps):
            offs.append(run(False))
            ons.append(run(True))
        # best rate per leg -> equivalent wall for the same request count
        off_rate, on_rate = max(offs), max(ons)
        return 1.0 / off_rate, 1.0 / on_rate
    finally:
        srv.close()
        eng.close()


def main(out_path: str) -> int:
    # the overhead gate measures the witness-OFF configuration: unless
    # the operator armed lockdep on purpose, the raw C lock factories
    # must be in place — merged-but-unarmed lockdep patches nothing and
    # therefore cannot move these walls
    from xgboost_tpu.reliability import lockdep

    if not lockdep.enabled():
        import _thread
        import threading

        assert threading.Lock is _thread.allocate_lock, \
            "lockdep disarmed but threading.Lock is not the raw factory"
        print("bench_obs: lockdep witness off, raw lock factories verified")

    scale = _env_float("BENCH_OBS_SCALE", 0.02)
    reps = max(1, int(_env_float("BENCH_OBS_REPS", 3)))
    rounds = max(1, int(_env_float("BENCH_OBS_ROUNDS", 5)))
    max_pct = _env_float("BENCH_OBS_MAX_PCT", 5.0)

    X, y = make_higgs(scale)
    print(f"bench_obs: higgs config at scale {scale} "
          f"({len(X):,} rows x {X.shape[1]}), {rounds} rounds, "
          f"min-of-{reps}")

    t_off, t_on = bench_train(X, y, rounds, reps)
    s_off, s_on = bench_serve(X, y, reps)

    def pct(off, on):
        return 100.0 * (on - off) / off if off > 0 else 0.0

    report = {
        "config": {"name": "higgs_binary", "scale": scale,
                   "rows": int(len(X)), "rounds": rounds,
                   **{k: v for k, v in HIGGS.items()}},
        "reps": reps,
        "threshold_pct": max_pct,
        "train": {"off_s": t_off, "on_s": t_on,
                  "overhead_pct": pct(t_off, t_on)},
        "serve": {"off_s_per_request": s_off, "on_s_per_request": s_on,
                  "overhead_pct": pct(s_off, s_on)},
    }
    worst = max(report["train"]["overhead_pct"],
                report["serve"]["overhead_pct"])
    report["pass"] = bool(worst <= max_pct)
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    print(f"train: off {t_off:.3f}s on {t_on:.3f}s "
          f"({report['train']['overhead_pct']:+.2f}%)")
    print(f"serve: off {s_off * 1e3:.3f}ms/req on {s_on * 1e3:.3f}ms/req "
          f"({report['serve']['overhead_pct']:+.2f}%)")
    print(f"wrote {out_path}; worst overhead {worst:+.2f}% "
          f"(threshold {max_pct}%)")
    if not report["pass"]:
        print("bench_obs: FAIL — telemetry shipping overhead exceeds "
              "threshold", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "BENCH_OBS.json"))
