"""Bit-packed Ellpack experiment (VERDICT r2 missing #4 / next #7).

The reference packs bin indices to ceil(log2(n_bins)) bits in HBM
(src/common/compressed_iterator.h, src/data/ellpack_page.cuh:26); this repo
stores u8/u16.  Question: would 4-bit packing (max_bin<=16) pay on the TPU
hist kernel?

Measures build_histogram at max_bin 256/64/16 with (a) the resident u8
layout and (b) a simulated 4-bit packed layout (two bins per byte, unpacked
with shift/mask on the fly before the one-hot matmul — exactly what a
packed kernel would do).  Run on CPU XLA for the shape of the answer and on
the TPU chip (python scripts/bitpack_bench.py, no JAX_PLATFORMS override)
for the real number; results go into docs/bitpack.md.
"""
import functools
import json
import sys

import jax

if "--tpu" not in sys.argv:
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

sys.path.insert(0, __file__.rsplit("/", 2)[0])
from bench import _median_time as timed  # noqa: E402 — shared timing helper
from xgboost_tpu.ops.histogram import _hist_accumulate  # noqa: E402
from xgboost_tpu.ops.histogram import build_histogram  # noqa: E402

R, F = 1 << 20, 28
N_NODES = 8


def _unpack4(packed):
    """(R, F/2) u8 -> (R, F) u8: two 4-bit bins per byte."""
    lo = packed & 0xF
    hi = packed >> 4
    return jnp.stack([lo, hi], axis=-1).reshape(packed.shape[0], -1)


@functools.partial(jax.jit, static_argnames=("n_bin",))
def _packed_hist(packed, gp, pos, *, n_bin):
    """ONE XLA program: unpack fused ahead of the one-hot matmul — what a
    packed kernel would do (no (R, F) u8 round-trip through HBM)."""
    return _hist_accumulate(_unpack4(packed), gp, pos, 0, N_NODES, n_bin,
                            2048, 1)


def native_section(rng):
    """Round-7 re-measurement (docs/bitpack.md): the scalar 2026-07 numbers
    could not answer what a VECTOR unpack does to the packed-4-bit
    roofline.  This times the native row-sweep hist kernel (the production
    CPU path since the FFI revival) on the resident u8 layout vs the
    packed two-bins-per-byte layout whose nibble unpack is fused into the
    AVX2 index-prep (native/xtb_simd.h xtb_hist_sweep_p4_avx2), at both
    simd levels, nthread=1 (the per-core roofline the decision is about).
    """
    from xgboost_tpu.utils import native

    lib = native.load_native()
    if lib is None:
        return {"native": "unavailable"}
    out = {"simd": native.simd_info()}
    native.set_nthread(1)
    gp = np.ascontiguousarray(rng.normal(size=(R, 2)), np.float32)
    pos = np.ascontiguousarray(rng.integers(0, N_NODES, size=R), np.int32)

    for B in (256, 16):
        bins = np.ascontiguousarray(
            rng.integers(0, B, size=(R, F)), np.uint8)
        hist = np.empty((N_NODES, F, B, 2), np.float32)

        def u8():
            lib.xtb_hist_f32_u8(bins.ctypes.data, gp.ctypes.data,
                                pos.ctypes.data, R, F, B, 0, N_NODES, 1, 2,
                                hist.ctypes.data)

        for level in ("scalar", "auto"):
            native.set_simd(level)
            out[f"native_u8_B{B}_{level}_s"] = round(timed(u8), 5)
        if B <= 16:
            packed = np.ascontiguousarray(
                bins[:, 0::2] | (bins[:, 1::2] << 4))
            hist_p = np.empty_like(hist)

            def p4():
                lib.xtb_hist_packed4(packed.ctypes.data, gp.ctypes.data,
                                     pos.ctypes.data, R, F, B, 0, N_NODES,
                                     1, hist_p.ctypes.data)

            for level in ("scalar", "auto"):
                native.set_simd(level)
                out[f"native_packed4_B{B}_{level}_s"] = round(timed(p4), 5)
            np.testing.assert_array_equal(hist_p, hist)  # layouts agree
            vec = out[f"native_u8_B{B}_auto_s"]
            out[f"native_packed4_B{B}_vector_speedup"] = round(
                vec / out[f"native_packed4_B{B}_auto_s"], 3)
    native.set_simd("auto")
    native.set_nthread(0)
    return out


def main():
    rng = np.random.default_rng(0)
    gp = jnp.asarray(rng.normal(size=(R, 2)).astype(np.float32))
    pos = jnp.asarray(rng.integers(0, N_NODES, size=R).astype(np.int32))
    results = {"platform": jax.devices()[0].platform, "rows": R,
               "features": F, "n_nodes": N_NODES}
    for B in (256, 64, 16):
        bins_np = rng.integers(0, B, size=(R, F)).astype(np.uint8)
        bins = jnp.asarray(bins_np)
        t_u8 = timed(lambda: build_histogram(
            bins, gp, pos, node0=0, n_nodes=N_NODES, n_bin=B))
        results[f"u8_B{B}_s"] = round(t_u8, 5)
        if B <= 16:
            packed_np = (bins_np[:, 0::2] | (bins_np[:, 1::2] << 4))
            packed = jnp.asarray(packed_np)
            t_p4 = timed(lambda: _packed_hist(packed, gp, pos, n_bin=B))
            results[f"packed4_B{B}_s"] = round(t_p4, 5)
            results[f"packed4_B{B}_speedup"] = round(t_u8 / t_p4, 3)
        # HBM-traffic roofline: bins bytes per level vs matmul FLOPs
        results[f"flops_per_bins_byte_B{B}"] = 2 * B * N_NODES * 2
    if jax.devices()[0].platform == "cpu":
        results.update(native_section(rng))
    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
