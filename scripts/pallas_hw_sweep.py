"""Validate + tune the Pallas histogram kernel on real TPU hardware.

Compares ops/hist_pallas.build_histogram_pallas against the XLA einsum
reference (ops/histogram.build_histogram) for parity and speed at
HIGGS-bench shapes, sweeping row-tile / feature-group sizes.

Run:  python scripts/pallas_hw_sweep.py [rows]
Writes results as JSON lines to stderr-readable stdout.
"""
from __future__ import annotations

import sys
import time

import numpy as np


def bench_one(fn, *args, iters=5, **kw):
    out = fn(*args, **kw)
    out.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args, **kw)
    out.block_until_ready()
    return (time.perf_counter() - t0) / iters, out


def main():
    import jax
    import jax.numpy as jnp

    import xgboost_tpu.ops.hist_pallas as hp
    from xgboost_tpu.ops.histogram import build_histogram

    R = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    F, B = 28, 256
    rng = np.random.default_rng(0)
    bins = jnp.asarray(rng.integers(0, B, size=(R, F), dtype=np.int32))
    gpair = jnp.asarray(rng.normal(size=(R, 2)).astype(np.float32))

    dev = jax.devices()[0]
    print(f"device={dev} R={R} F={F} B={B}", flush=True)

    for n_nodes, depth in [(8, 3), (32, 6)]:
        pos = jnp.asarray(
            rng.integers(n_nodes - 1, 2 * n_nodes - 1, size=R, dtype=np.int32)
        )
        node0, stride = n_nodes - 1, 1

        t_ein, h_ref = bench_one(
            build_histogram, bins, gpair, pos,
            node0=node0, n_nodes=n_nodes, n_bin=B,
        )
        print(f"[N={n_nodes}] einsum: {t_ein*1e3:.1f} ms", flush=True)

        configs = [(0, 0)]  # autotuned (choose_tiles)
        configs += [(t, fg) for t in (256, 512, 1024, 2048)
                    for fg in (1, 2, 4, 8, 16)]
        for row_tile, fg in configs:
            label = f"T={row_tile} FG={fg}" if row_tile else "autotune"
            try:
                t, h = bench_one(
                    hp.build_histogram_pallas, bins, gpair, pos,
                    node0=node0, n_nodes=n_nodes, n_bin=B,
                    row_tile=row_tile, feat_group=fg,
                )
            except Exception as e:  # noqa: BLE001
                print(f"[N={n_nodes}] pallas {label}: "
                      f"FAIL {type(e).__name__}: {str(e)[:120]}", flush=True)
                continue
            ok = bool(jnp.allclose(h, h_ref, atol=1e-3, rtol=1e-5))
            print(
                f"[N={n_nodes}] pallas {label}: "
                f"{t*1e3:.1f} ms  parity={'OK' if ok else 'MISMATCH'}  "
                f"speedup={t_ein/t:.2f}x",
                flush=True,
            )


if __name__ == "__main__":
    main()
