"""Roofline attribution for the native kernels -> BENCH_ROOFLINE.json.

Answers "is this kernel compute-bound or memory-bound, and how far from
the host's ceiling is it?" from the per-invocation XtbKernelPerf counters
(native/xtb_kernels.h): every kernel invocation records wall ns, rdtsc
cycles, modeled bytes touched, and modeled flops.  This script

1. measures the host's achievable memory bandwidth ONCE with a
   STREAM-style triad (``a[i] = b[i] + s*c[i]``) run through the same
   ParallelFor pool the kernels use (utils/native.stream_triad) —
   best-of-N over arrays far larger than LLC, 12 bytes/element by the
   STREAM convention (two reads + one write, no RFO accounting);
2. runs >=2 BASELINE ladder configs (bench_ladder shapes, scaled) through
   train + predict twice — once on the f32 ``hist`` path and once with
   ``deterministic_histogram=1`` (the quantised ``hist_q`` path) — so the
   four headline kernels (hist, hist_q, split, predict) all execute;
3. emits per-kernel achieved GB/s, GFLOP/s, arithmetic intensity
   (flops/byte), and % of the measured peak into BENCH_ROOFLINE.json.

Reading the rows: a kernel whose intensity is below the machine balance
(peak GFLOP/s / peak GB/s) lives on the bandwidth roof — its %-of-peak
bandwidth is the number to improve; one above it is compute-bound.  The
byte/flop models are documented next to each kernel's XtbKernelPerf
scope in native/xtb_kernels.h.

Usage:  python scripts/bench_roofline.py [out.json] [--quick]
  --quick: small rows / few rounds / smaller triad — the nightly smoke
  (scripts/nightly_suite.sh); full mode writes the committed file.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_ladder import FULL_CONFIGS, _host_fingerprint, make_data  # noqa: E402

# the four kernels the roofline exists to attribute; missing rows fail
# the run so the nightly catches an instrumentation regression
REQUIRED_KERNELS = ("hist", "hist_q", "split", "predict")

PERF_KEYS = ("invocations", "wall_ns", "cycles", "bytes", "flops")


def measure_peak(quick: bool) -> dict:
    """Best-of-N STREAM triad bandwidth through the native pool.

    12 bytes move per element (read b, read c, write a — the STREAM
    convention; actual traffic with write-allocate is higher, which makes
    this a conservative peak and kernel %-of-peak slightly flattering)."""
    from xgboost_tpu.utils import native

    n = 1 << (22 if quick else 24)  # 16M/64M floats: far beyond LLC
    reps = 3 if quick else 7
    rng = np.random.default_rng(0)
    b = rng.random(n, dtype=np.float32)
    c = rng.random(n, dtype=np.float32)
    a = np.zeros(n, dtype=np.float32)
    native.stream_triad(b, c, 3.0, a)  # warm: faults pages, spins pool up
    best_gbs, used_native = 0.0, True
    for _ in range(reps):
        t0 = time.perf_counter()
        used_native = native.stream_triad(b, c, 3.0, a)
        dt = time.perf_counter() - t0
        best_gbs = max(best_gbs, 12.0 * n / dt / 1e9)
    return {
        "stream_triad_gbs": round(best_gbs, 2),
        "n_floats": n, "reps": reps,
        "native_pool": bool(used_native),
        "nthread": native.get_nthread(),
        "bytes_model": "12*n (STREAM triad: 2 reads + 1 write, no RFO)",
    }


def _kernel_totals() -> dict:
    from xgboost_tpu.utils import native

    out = {}
    for name, k in native.pool_stats()["kernels"].items():
        out[name] = {key: int(k.get(key, 0)) for key in PERF_KEYS}
    return out


def _delta(before: dict, after: dict) -> dict:
    out = {}
    for name, k in after.items():
        prev = before.get(name, {})
        d = {key: k[key] - int(prev.get(key, 0)) for key in PERF_KEYS}
        if d["invocations"] > 0:
            out[name] = d
    return out


def _kernel_rows(deltas: dict, peak_gbs: float) -> dict:
    rows = {}
    for name, d in sorted(deltas.items()):
        wall_ns = max(d["wall_ns"], 1)
        gbs = d["bytes"] / wall_ns          # bytes/ns == GB/s
        gflops = d["flops"] / wall_ns       # flops/ns == GFLOP/s
        rows[name] = {
            "invocations": d["invocations"],
            "wall_ms": round(d["wall_ns"] / 1e6, 3),
            "cycles": d["cycles"],
            "bytes": d["bytes"],
            "flops": d["flops"],
            "achieved_gbs": round(gbs, 3),
            "achieved_gflops": round(gflops, 3),
            "intensity_flops_per_byte": round(d["flops"] / max(d["bytes"],
                                                               1), 4),
            "pct_of_peak_bw": (round(100.0 * gbs / peak_gbs, 1)
                               if peak_gbs else None),
        }
    return rows


def run_config(cfg: dict, scale: float, rounds: int, peak_gbs: float) -> dict:
    import xgboost_tpu as xtb

    R, X, y, groups = make_data(cfg, scale)
    d = xtb.DMatrix(X, label=y)
    if groups is not None:
        d.set_group(groups)
    p = {"objective": cfg["objective"], **cfg["params"]}
    if cfg["kind"] == "multi":
        p["num_class"] = cfg["classes"]
    pq = {**p, "deterministic_histogram": 1}

    # warm both program variants (XLA compile, ellpack build, pool spin-up)
    # so the measured region is steady-state kernel execution
    bst = xtb.train(p, d, 1, verbose_eval=False)
    np.asarray(bst.predict(d))
    xtb.train(pq, d, 1, verbose_eval=False)

    before = _kernel_totals()
    t0 = time.perf_counter()
    bst = xtb.train(p, d, rounds, verbose_eval=False)       # hist + split
    xtb.train(pq, d, rounds, verbose_eval=False)            # hist_q + split
    np.asarray(bst.predict(d))                              # predict
    wall = time.perf_counter() - t0
    deltas = _delta(before, _kernel_totals())

    return {
        "config": cfg["name"], "rows": R, "cols": cfg["cols"],
        "scale": scale, "rounds": rounds,
        "objective": cfg["objective"], "wall_s": round(wall, 2),
        "kernels": _kernel_rows(deltas, peak_gbs),
    }


def main(argv) -> int:
    quick = "--quick" in argv
    paths = [a for a in argv if not a.startswith("--")]
    out_path = paths[0] if paths else "BENCH_ROOFLINE.json"

    import jax

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    from xgboost_tpu.utils import native

    if native.load_native() is None:  # pragma: no cover - no toolchain
        print("bench_roofline: native kernels unavailable on this host; "
              "nothing to attribute", flush=True)
        return 0

    scale = float(os.environ.get(
        "ROOFLINE_SCALE", "0.001" if quick else "0.02"))
    rounds = int(os.environ.get("ROOFLINE_ROUNDS", "3" if quick else "5"))

    peak = measure_peak(quick)
    print(f"[peak] STREAM triad {peak['stream_triad_gbs']} GB/s "
          f"(n={peak['n_floats']}, best of {peak['reps']}, "
          f"nthread={peak['nthread']})", flush=True)

    configs = []
    for cfg in FULL_CONFIGS[:2]:  # higgs-like binary + covertype multiclass
        row = run_config(cfg, scale, rounds, peak["stream_triad_gbs"])
        configs.append(row)
        print(f"[{row['config']}] rows={row['rows']} "
              f"rounds={rounds} wall={row['wall_s']}s", flush=True)
        for name, k in row["kernels"].items():
            print(f"  {name:10s} {k['achieved_gbs']:8.2f} GB/s "
                  f"({k['pct_of_peak_bw']:5.1f}% peak)  "
                  f"{k['achieved_gflops']:8.2f} GFLOP/s  "
                  f"intensity={k['intensity_flops_per_byte']:.3f} f/B  "
                  f"wall={k['wall_ms']:.1f}ms x{k['invocations']}",
                  flush=True)

    rc = 0
    for row in configs:
        missing = [k for k in REQUIRED_KERNELS if k not in row["kernels"]]
        if missing:
            print(f"bench_roofline: FAIL — config {row['config']} never "
                  f"ran kernels {missing} (instrumentation or dispatch "
                  f"regression)", flush=True)
            rc = 1

    doc = {
        "host": _host_fingerprint(),
        "platform": jax.devices()[0].platform,
        "quick": quick,
        "peak": peak,
        "configs": configs,
    }
    with open(out_path, "w") as fh:
        json.dump(doc, fh, indent=1)
    print(f"bench_roofline: wrote {out_path}", flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
