"""Microbench level_step components at ladder shapes (CPU).

Times the jitted hist build, split evaluation and position update separately
at covertype (58k x 54, B=257) and HIGGS-slice (1.1M x 28) shapes, so the
ladder gap (BENCH_LADDER.json) can be attributed before optimising.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np


def bench(fn, *args, n=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    from xgboost_tpu.ops.histogram import build_histogram
    from xgboost_tpu.ops.split import SplitParams, evaluate_splits

    for name, R, F, B in [("covertype", 58368, 54, 257),
                          ("higgs", 1101824, 28, 257)]:
        rng = np.random.default_rng(0)
        bins = jnp.asarray(rng.integers(0, B - 1, size=(R, F)), jnp.int32)
        gpair = jnp.asarray(rng.normal(size=(R, 2)), jnp.float32)
        print(f"== {name}: R={R} F={F} B={B}")
        for depth in (0, 3, 7):
            N = 1 << depth
            node0 = N - 1
            pos = jnp.asarray(
                rng.integers(node0, node0 + N, size=R), jnp.int32)
            t = bench(lambda b=bins, g=gpair, p=pos, n0=node0, nn=N:
                      build_histogram(b, g, p, node0=n0, n_nodes=nn, n_bin=B))
            print(f"  hist  d={depth} N={N}: {t*1e3:8.2f} ms")
            # subtraction-trick variant: half the nodes, stride 2
            if depth > 0:
                t = bench(lambda b=bins, g=gpair, p=pos, n0=node0, nn=N // 2:
                          build_histogram(b, g, p, node0=n0, n_nodes=nn,
                                          n_bin=B, stride=2))
                print(f"  hist- d={depth} N={N//2} s2: {t*1e3:8.2f} ms")
        # split eval at the widest level
        params = SplitParams(eta=0.3, lambda_=1.0, alpha=0.0, gamma=0.0,
                             min_child_weight=1.0, max_delta_step=0.0,
                             monotone=None, max_cat_to_onehot=4)
        for N in (128, 256):
            hist = jnp.asarray(rng.normal(size=(N, F, B, 2)), jnp.float32)
            totals = jnp.asarray(hist.sum(axis=(1, 2)) / F)
            n_bins = jnp.full(F, B - 1, jnp.int32)
            fmask = jnp.ones((N, F), bool)
            bounds = jnp.stack([jnp.full(N, -jnp.inf), jnp.full(N, jnp.inf)],
                               axis=1).astype(jnp.float32)
            t = bench(lambda h=hist, tt=totals, nb=n_bins, fm=fmask, bd=bounds:
                      evaluate_splits(h, tt, nb, params, fm, bd))
            print(f"  split N={N}: {t*1e3:8.2f} ms")
        # position update
        from xgboost_tpu.tree.grow import _update_positions
        from xgboost_tpu.ops.split import BestSplit

        N = 128
        node0 = N - 1
        pos = jnp.asarray(rng.integers(node0, node0 + N, size=R), jnp.int32)
        best = BestSplit(
            feature=jnp.zeros(N, jnp.int32), bin=jnp.full(N, 100, jnp.int32),
            gain=jnp.ones(N, jnp.float32), default_left=jnp.ones(N, bool),
            left_sum=jnp.zeros((N, 2), jnp.float32),
            right_sum=jnp.zeros((N, 2), jnp.float32),
            left_weight=jnp.zeros(N, jnp.float32),
            right_weight=jnp.zeros(N, jnp.float32),
            is_cat=jnp.zeros(N, bool), cat_set=jnp.zeros((N, B), bool))
        can = jnp.ones(N, bool)
        f = jax.jit(lambda b, p: _update_positions(b, p, best, can, node0, N,
                                                   B, False))
        t = bench(f, bins, pos)
        print(f"  posupd N={N}: {t*1e3:8.2f} ms")


if __name__ == "__main__":
    main()
