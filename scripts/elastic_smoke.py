#!/usr/bin/env python
"""Elastic-training smoke for the nightly suite (docs/reliability.md
§ Elastic training).

Three legs over a tracker-rendezvous CPU run:

1. **Shrink**: 4 workers, the fault plan kills rank 2 entering round 3;
   the survivors regroup and FINISH at world 3 — no restart — producing a
   valid model, with the shard map in the final checkpoint recording the
   3-way ownership.
2. **Determinism**: the same fault plan run twice must produce
   bitwise-identical model bytes (the elastic determinism contract: a
   rescaled run is reproducible given the same death schedule).
3. **Absorb**: same kill, but the launcher respawns one replacement
   worker; it connects to the tracker, is absorbed at a round boundary
   with the shard map restored from the checkpoint, and the run finishes
   with the final checkpoint back at world 4.

Usage: JAX_PLATFORMS=cpu python scripts/elastic_smoke.py [workers] [rounds]
"""
import functools
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# NOTE: no argv parsing at module level — the spawned workers re-import
# this module (launcher mod_dir) with THEIR OWN argv; every per-run knob
# travels through functools.partial kwargs instead.
PARAMS = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
          "max_bin": 32}
N_ROWS = 2400


def worker(rank, world, *, ckpt_dir, out_path, rounds, num_shards):
    import numpy as np

    import xgboost_tpu as xtb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(N_ROWS, 6)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)

    def data_fn(shard_map, rank, world):
        # shard s = rows s::num_shards — any worker can materialize any
        # shard (the elastic contract: shards are globally loadable)
        shards = shard_map.shards_of(rank)
        rows = np.sort(np.concatenate(
            [np.arange(s, N_ROWS, shard_map.num_shards) for s in shards]))
        return xtb.DMatrix(X[rows], label=y[rows])

    cfg = xtb.ElasticConfig(data_fn, ckpt_dir, num_shards=num_shards)
    bst = xtb.train(PARAMS, None, rounds, elastic=cfg, verbose_eval=False)
    from xgboost_tpu import collective

    # every survivor could write: the killed worker may have been rank 0's
    # original holder; whoever ends up rank 0 owns the artifact
    if collective.get_rank() == 0 and out_path:
        with open(out_path, "wb") as fh:
            fh.write(bytes(bst.save_raw()))


def _run(tag, *, workers, rounds, num_shards, ckpt_dir, out_path,
         fault_plan=None, max_respawns=0):
    import json

    from xgboost_tpu.launcher import run_distributed

    print(f"[elastic_smoke] {tag}: {workers} workers, {rounds} rounds"
          + (f", respawns={max_respawns}" if max_respawns else ""),
          flush=True)
    run_distributed(
        functools.partial(worker, ckpt_dir=ckpt_dir, out_path=out_path,
                          rounds=rounds, num_shards=num_shards),
        num_workers=workers, platform="cpu", timeout=900,
        rendezvous="tracker", elastic=True,
        fault_plan=json.dumps(fault_plan) if fault_plan else None,
        max_respawns=max_respawns)


def main() -> int:
    from xgboost_tpu.reliability import latest_checkpoint

    WORKERS = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    ROUNDS = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    KILL_RANK, KILL_ROUND = min(2, WORKERS - 1), 3
    NUM_SHARDS = 2 * WORKERS

    # pickle the worker under its importable module name, not __main__ —
    # the spawned children re-import it from scripts/ (launcher mod_dir)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import elastic_smoke as _mod

    global worker
    worker = _mod.worker

    # `at` pins the death to the FIRST pass over round KILL_ROUND: after
    # the regroup a (different) worker holds rank KILL_RANK and re-runs
    # the same round — without the invocation matcher the plan would kill
    # it too, every regroup, until the world collapsed
    plan = {"faults": [{"site": "train.round", "kind": "kill",
                        "rank": KILL_RANK, "round": KILL_ROUND,
                        "at": KILL_ROUND, "exit_code": 43}]}
    tmp = tempfile.mkdtemp(prefix="xtb_elastic_smoke_")
    try:
        kw = dict(workers=WORKERS, rounds=ROUNDS, num_shards=NUM_SHARDS)
        # -- leg 1: shrink to WORKERS-1 and finish ------------------------
        ckpt_a = os.path.join(tmp, "ckpt_a")
        out_a = os.path.join(tmp, "a.ubj")
        _run("shrink", ckpt_dir=ckpt_a, out_path=out_a, fault_plan=plan,
             **kw)
        model_a = open(out_a, "rb").read()
        st = latest_checkpoint(ckpt_a)
        if st is None or st.round != ROUNDS:
            raise SystemExit(f"shrink run did not complete: {st}")
        if st.world != WORKERS - 1 or st.shard_map["world"] != WORKERS - 1:
            raise SystemExit(
                f"final checkpoint world {st.world} != {WORKERS - 1}: the "
                "survivors did not regroup")
        print(f"[elastic_smoke] shrink OK: finished at world {st.world}, "
              f"{len(model_a)} model bytes")

        # -- leg 2: bitwise reproducibility under the same plan -----------
        ckpt_b = os.path.join(tmp, "ckpt_b")
        out_b = os.path.join(tmp, "b.ubj")
        _run("replay", ckpt_dir=ckpt_b, out_path=out_b, fault_plan=plan,
             **kw)
        model_b = open(out_b, "rb").read()
        if model_a != model_b:
            raise SystemExit(
                "DETERMINISM FAILURE: two elastic runs under the same "
                f"fault plan differ ({len(model_a)} vs {len(model_b)} "
                "bytes)")
        print(f"[elastic_smoke] determinism OK: identical bytes across "
              f"replayed fault plan")

        # -- leg 3: absorb a replacement at a round boundary --------------
        # pace the rounds (pure-delay faults change no bits) so the
        # replacement's cold start reliably lands before the final round
        absorb_plan = {"faults": plan["faults"] + [
            {"site": "train.round", "kind": "delay", "seconds": 1.5,
             "times": 1000}]}
        ckpt_c = os.path.join(tmp, "ckpt_c")
        out_c = os.path.join(tmp, "c.ubj")
        _run("absorb", ckpt_dir=ckpt_c, out_path=out_c,
             fault_plan=absorb_plan, max_respawns=1, **kw)
        model_c = open(out_c, "rb").read()
        st = latest_checkpoint(ckpt_c)
        if st is None or st.round != ROUNDS:
            raise SystemExit(f"absorb run did not complete: {st}")
        if not model_c:
            raise SystemExit("absorb run produced no model")
        # the replacement joined mid-run: the final shard map must be back
        # at full world, restored/rebalanced through the checkpoint
        if st.shard_map["world"] != WORKERS:
            raise SystemExit(
                f"absorb run finished at world {st.shard_map['world']}, "
                f"expected {WORKERS} (replacement not absorbed)")
        print(f"[elastic_smoke] absorb OK: finished back at world "
              f"{st.shard_map['world']}, {len(model_c)} model bytes")
        print(f"[elastic_smoke] OK: shrink + determinism + absorb "
              f"({WORKERS} workers, {ROUNDS} rounds)")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
