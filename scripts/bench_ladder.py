"""BASELINE ladder configs #2-#4 vs the reference oracle on identical data.

Runs the three headline training configs from BASELINE.md — HIGGS-class
binary (11M x 28), covertype-class multiclass (581k x 54, 7 classes), and
MSLR-class ranking (30k+ queries) — through BOTH this framework and the
reference oracle (/root/oracle_build, hist method), on the SAME synthetic
stand-in arrays (zero-egress image: the real datasets cannot be fetched;
shapes, sparsity and label structure mirror them).  Records wall-clock and
quality (AUC / merror / ndcg@10 computed by ONE metric implementation —
ours, oracle-parity-tested — over both models' predictions) into
BENCH_LADDER.json.

Scale: `LADDER_SCALE` (fraction of full rows, default 0.05 on CPU / 1.0 on
TPU) bounds single-core CPU runtime; the recorded rows are what actually
ran, and `scale` says how far from the full shape that is.  The TPU
watcher runs this at full scale in its final stage.

Usage:  python scripts/bench_ladder.py [out.json]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ORACLE_PKG = "/root/oracle_build/pkg"

FULL_CONFIGS = [
    # BASELINE.md ladder #2: HIGGS 11M x 28, binary:logistic, AUC
    dict(name="higgs_binary", rows=11_000_000, cols=28, kind="binary",
         objective="binary:logistic", metric="auc", rounds=5,
         params=dict(max_depth=8, eta=0.3, max_bin=256)),
    # ladder #3: covertype 581k x 54, 7 classes, multi:softprob, merror
    dict(name="covertype_softprob", rows=581_012, cols=54, kind="multi",
         classes=7, objective="multi:softprob", metric="merror", rounds=5,
         params=dict(max_depth=8, eta=0.3, max_bin=256)),
    # ladder #4: MSLR-WEB30K 3.77M docs / 31k queries, rank:ndcg, ndcg@10
    dict(name="mslr_ndcg", rows=3_771_125, cols=136, kind="rank",
         groups=31_531, objective="rank:ndcg", metric="ndcg@10", rounds=5,
         params=dict(max_depth=8, eta=0.3, max_bin=256)),
    # ladder #5 slice: Criteo-class out-of-core — OUR side streams zstd
    # pages (ExtMemQuantileDMatrix); the oracle trains in-memory on the
    # same rows (its extmem needs a disk cache pass; quality is the
    # comparable axis here, scale the honest caveat)
    dict(name="criteo_extmem", rows=1_000_000_000, cols=39, kind="extmem",
         objective="binary:logistic", metric="auc", rounds=5,
         params=dict(max_depth=8, eta=0.3, max_bin=256)),
]


def make_data(cfg, scale: float, seed: int = 0):
    rng = np.random.default_rng(seed)
    if cfg["kind"] == "extmem":
        # bounded stand-in: page count scales, page size fixed; cap keeps
        # the 1-core CPU run finite (watcher sets a bigger cap on TPU)
        cap = max(int(os.environ.get("LADDER_EXTMEM_CAP", "262144")),
                  65536)  # below one page the row floor would hit zero
        R = int(min(max(cfg["rows"] * scale, 64 * 1024), cap))
        R = (R // 65536) * 65536
        F = cfg["cols"]
        X = rng.normal(size=(R, F)).astype(np.float32)
        X[rng.random((R, F)) < 0.25] = np.nan  # Criteo-like sparsity
        lin = (np.nan_to_num(X[:, 0]) * 1.2 - np.nan_to_num(X[:, 1])
               + 0.5 * np.nan_to_num(X[:, 2]) * np.nan_to_num(X[:, 3]))
        y = (lin + rng.normal(scale=0.5, size=R) > 0).astype(np.float32)
        return R, X, y, None
    R = max(int(cfg["rows"] * scale), 10_000)
    F = cfg["cols"]
    X = rng.normal(size=(R, F)).astype(np.float32)
    X[rng.random((R, F)) < 0.02] = np.nan  # HIGGS-like light missingness
    lin = (np.nan_to_num(X[:, 0]) * 1.2 - np.nan_to_num(X[:, 1])
           + 0.5 * np.nan_to_num(X[:, 2]) * np.nan_to_num(X[:, 3]))
    if cfg["kind"] == "binary":
        y = (lin + rng.normal(scale=0.5, size=R) > 0).astype(np.float32)
        return R, X, y, None
    if cfg["kind"] == "multi":
        K = cfg["classes"]
        z = lin + rng.normal(scale=0.5, size=R)
        y = np.clip(((z - z.min()) / (np.ptp(z) + 1e-9) * K).astype(np.int64),
                    0, K - 1).astype(np.float32)
        return R, X, y, None
    # ranking: ~120 docs/query like MSLR; graded 0-4 relevance
    G = max(int(cfg["groups"] * scale), 100)
    sizes = rng.integers(40, 200, size=G)
    R = int(sizes.sum())
    X = rng.normal(size=(R, cfg["cols"])).astype(np.float32)
    rel = np.clip((X[:, 0] + 0.5 * rng.normal(size=R) + 2.0).astype(np.int64),
                  0, 4).astype(np.float32)
    return R, X, rel, sizes.astype(np.int64)


def eval_quality(metric, preds, y, group_sizes):
    from xgboost_tpu.metric import create_metric

    fn, _name = create_metric(metric)  # returns (callable, resolved name)
    kw = {}
    if group_sizes is not None:
        kw["group_ptr"] = np.concatenate([[0], np.cumsum(group_sizes)])
    return float(fn(np.asarray(preds), np.asarray(y, np.float64), **kw))


# nthread values for the host-parallelism scaling sweep (satellite of the
# ParallelFor PR): 1 / 4 / all-cores ("0" resolves the default).  Override
# with LADDER_NTHREAD="1,2,0"; LADDER_NTHREAD="" disables the sweep (the
# headline run always uses all cores and records what it used).
def _sweep_nthreads():
    raw = os.environ.get("LADDER_NTHREAD", "1,4,0")
    out = []
    for tok in raw.split(","):
        tok = tok.strip()
        if tok:
            out.append(int(tok))
    return out


# simd levels for the lane-width scaling sweep (round 7): each level
# re-runs the warmed program at nthread=1 — the per-core roofline the SIMD
# work targets — plus one all-cores vector run to show the SIMD and
# threading wins COMPOSE.  Results are bitwise level-invariant
# (docs/native_threading.md), so the sweep times identical outputs.
# Override with LADDER_SIMD="scalar,auto"; LADDER_SIMD="" disables.
def _sweep_simd():
    raw = os.environ.get("LADDER_SIMD", "scalar,auto")
    levels = [tok.strip() for tok in raw.split(",") if tok.strip()]
    from xgboost_tpu.utils import native

    for lvl in levels:  # typos fail HERE, not mid-ladder after a config ran
        native.set_simd(lvl)
    native.set_simd("auto")
    return levels


# LADDER_REPS=N takes the MINIMUM of N runs per sweep point (default 1).
# On time-shared bench hosts single-shot walls swing 2-3x with scheduler
# noise; min-of-N is the standard estimator for the code's actual cost.
def _reps() -> int:
    return max(1, int(os.environ.get("LADDER_REPS", "1")))


def _timed_min(fn) -> float:
    best = float("inf")
    for _ in range(_reps()):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run_ours(cfg, X, y, group_sizes):
    import xgboost_tpu as xtb

    if cfg["kind"] == "extmem":
        from xgboost_tpu.data.extmem import DataIter, ExtMemQuantileDMatrix

        page = 65536

        class Pages(DataIter):
            def __init__(self):
                super().__init__()
                self._i = 0

            def next(self, input_data):
                if self._i * page >= len(y):
                    return 0
                lo = self._i * page
                input_data(data=X[lo:lo + page], label=y[lo:lo + page])
                self._i += 1
                return 1

            def reset(self):
                self._i = 0

        d = ExtMemQuantileDMatrix(Pages(),
                                  max_bin=cfg["params"]["max_bin"])
    else:
        d = xtb.DMatrix(X, label=y)
    if group_sizes is not None:
        d.set_group(group_sizes)
    p = {"objective": cfg["objective"], **cfg["params"]}
    if cfg["kind"] == "multi":
        p["num_class"] = cfg["classes"]
    # warm the jit cache (and the ellpack build) so the timed run measures
    # steady-state boosting, not XLA compilation — the reference's kernels
    # are AOT, so this is the like-for-like comparison
    xtb.train(p, d, 1, verbose_eval=False)
    t0 = time.perf_counter()
    bst = xtb.train(p, d, cfg["rounds"], verbose_eval=False)
    # predictions force full materialization (train is async under jit)
    preds = np.asarray(bst.predict(d))
    dt = time.perf_counter() - t0

    # nthread scaling sweep over the SAME warmed program cache: pool width
    # is not a jit cache key (results are bitwise nthread-invariant,
    # docs/native_threading.md), so each re-run times only the native
    # kernels at a different width.  The width rides the params dict — the
    # same plumbing XGBoosterSetParam("nthread") uses.
    from xgboost_tpu.utils import native

    def train_predict(params):
        b2 = xtb.train(params, d, cfg["rounds"], verbose_eval=False)
        np.asarray(b2.predict(d))

    scaling = {}
    for n in _sweep_nthreads():
        wall = _timed_min(lambda: train_predict({**p, "nthread": n}))
        scaling[f"nthread={n if n > 0 else 'all'}"] = dict(
            wall_s=round(wall, 2), effective=native.get_nthread())

    # lane-width sweep over the same warmed cache: simd level is applied
    # inside the native kernels at execution time, so flipping it re-times
    # the identical program with different (identical-output) bodies.  The
    # pool width must ride the params dict like the nthread sweep above —
    # train() re-applies the params' width, so a bare set_nthread(1) here
    # would be silently reset to all cores at the first configure.
    simd_scaling = {}
    for level in _sweep_simd():
        eff = native.set_simd(level)
        wall = _timed_min(lambda: train_predict({**p, "nthread": 1}))
        simd_scaling[f"{level}@nthread=1"] = dict(
            wall_s=round(wall, 2), effective=eff)
    if simd_scaling:
        native.set_simd("auto")
        wall = _timed_min(lambda: train_predict({**p, "nthread": 0}))
        simd_scaling["auto@nthread=all"] = dict(
            wall_s=round(wall, 2), effective=native.get_simd())
    native.set_simd("auto")
    native.set_nthread(0)  # back to the defaults for the next config
    return dt, preds, scaling, simd_scaling


def run_oracle(cfg, X, y, group_sizes):
    sys.path.insert(0, ORACLE_PKG)
    import xgboost as xgb  # the oracle build

    d = xgb.DMatrix(X, label=y, missing=np.nan)
    if group_sizes is not None:
        d.set_group(group_sizes)
    p = {"objective": cfg["objective"], "tree_method": "hist",
         "nthread": os.cpu_count(), **cfg["params"]}
    if cfg["kind"] == "multi":
        p["num_class"] = cfg["classes"]
    t0 = time.perf_counter()
    bst = xgb.train(p, d, num_boost_round=cfg["rounds"])
    preds = np.asarray(bst.predict(d))
    dt = time.perf_counter() - t0
    return dt, preds


def main() -> None:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "BENCH_LADDER.json"
    # When the oracle build is unavailable (this container has no
    # /root/reference checkout to rebuild it from), fall back to the PRIOR
    # ladder file's oracle wall/quality per config — valid as a comparison
    # only when rows/scale/platform match, which we check, and labeled with
    # its provenance in the emitted row.
    prior_oracle = {}
    if os.path.exists(out_path):
        try:
            with open(out_path) as fh:
                for row in json.load(fh):
                    if row.get("oracle_wall_s") is not None:
                        prior_oracle[row["config"]] = row
        except Exception:  # noqa: BLE001 - a corrupt prior file is not fatal
            prior_oracle = {}
    import jax

    # sitecustomize freezes jax_platforms=axon at interpreter startup; the
    # env var alone cannot override it post-import (tests/conftest.py has
    # the same rule).  Never touch the tunnel unless explicitly asked.
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    platform = jax.devices()[0].platform
    scale = float(os.environ.get("LADDER_SCALE",
                                 "1.0" if platform == "tpu" else "0.05"))
    rows_out = []
    for cfg in FULL_CONFIGS:
        R, X, y, groups = make_data(cfg, scale)
        print(f"[{cfg['name']}] rows={R} cols={cfg['cols']} "
              f"rounds={cfg['rounds']} scale={scale}", flush=True)
        ours_s, ours_pred, scaling, simd_scaling = run_ours(cfg, X, y, groups)
        ours_q = eval_quality(cfg["metric"], ours_pred, y, groups)
        print(f"  ours:   {ours_s:8.1f}s  {cfg['metric']}={ours_q:.5f}  "
              f"scaling={scaling}  simd={simd_scaling}", flush=True)
        try:
            orc_s, orc_pred = run_oracle(cfg, X, y, groups)
            orc_q = eval_quality(cfg["metric"], orc_pred, y, groups)
            print(f"  oracle: {orc_s:8.1f}s  {cfg['metric']}={orc_q:.5f}",
                  flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"  oracle FAILED: {e!r}", flush=True)
            orc_s, orc_q = None, None
        oracle_source = "fresh"
        note = None
        if orc_s is None:
            prev = prior_oracle.get(cfg["name"])
            if (prev and prev.get("rows") == R
                    and prev.get("platform") == platform):
                orc_s = prev["oracle_wall_s"]
                orc_q = prev.get("oracle_quality")
                oracle_source = "archived (oracle build unavailable)"
                note = ("oracle walls are from the archived run's HOST, "
                        "which may differ from this one — "
                        "speed_vs_oracle is cross-host and indicative "
                        "only; the like-for-like signal on this host is "
                        "nthread_scaling")
                print(f"  oracle: {orc_s:8.1f}s  [archived numbers — "
                      f"same rows/platform, possibly different host]",
                      flush=True)
        from xgboost_tpu.utils import native as _native

        rows_out.append(dict(
            config=cfg["name"], rows=R, cols=cfg["cols"],
            full_rows=cfg["rows"], scale=scale, rounds=cfg["rounds"],
            objective=cfg["objective"], metric=cfg["metric"],
            platform=platform,
            nthread=_native.get_nthread(), cores=os.cpu_count(),
            simd=_native.simd_info(), sweep_reps=_reps(),
            ours_wall_s=round(ours_s, 2), ours_quality=round(ours_q, 6),
            nthread_scaling=scaling,
            simd_scaling=simd_scaling,
            oracle_wall_s=None if orc_s is None else round(orc_s, 2),
            oracle_quality=None if orc_q is None else round(orc_q, 6),
            oracle_source=oracle_source,
            **({"note": note} if note else {}),
            speed_vs_oracle=(None if orc_s is None
                             else round(orc_s / ours_s, 4)),
        ))
        with open(out_path, "w") as fh:  # checkpoint after each config
            json.dump(rows_out, fh, indent=1)
    print(json.dumps({"ladder": rows_out}), flush=True)


if __name__ == "__main__":
    main()
