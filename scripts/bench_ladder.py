"""BASELINE ladder configs #2-#4 vs the reference oracle on identical data.

Runs the three headline training configs from BASELINE.md — HIGGS-class
binary (11M x 28), covertype-class multiclass (581k x 54, 7 classes), and
MSLR-class ranking (30k+ queries) — through BOTH this framework and the
reference oracle (/root/oracle_build, hist method), on the SAME synthetic
stand-in arrays (zero-egress image: the real datasets cannot be fetched;
shapes, sparsity and label structure mirror them).  Records wall-clock and
quality (AUC / merror / ndcg@10 computed by ONE metric implementation —
ours, oracle-parity-tested — over both models' predictions) into
BENCH_LADDER.json.

Scale: `LADDER_SCALE` (fraction of full rows, default 0.05 on CPU / 1.0 on
TPU) bounds single-core CPU runtime; the recorded rows are what actually
ran, and `scale` says how far from the full shape that is.  The TPU
watcher runs this at full scale in its final stage.

Usage:  python scripts/bench_ladder.py [out.json]
"""
from __future__ import annotations

import hashlib
import json
import os
import platform as _platform
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ORACLE_PKG = "/root/oracle_build/pkg"

_HOST_FP = None


def _host_fingerprint() -> dict:
    """What makes a wall-clock number comparable: core count, arch, and
    the SIMD capability set.  Stamped on every ladder row; any path that
    compares rows across files (archived-oracle reuse, --diff) must
    refuse when the ids differ — a cross-host wall ratio is not a
    regression signal, it is two different machines."""
    global _HOST_FP
    if _HOST_FP is None:
        from xgboost_tpu.utils import native as _native

        simd = _native.simd_info()
        info = dict(cores=os.cpu_count(), machine=_platform.machine(),
                    cpu_flags=sorted(simd.get("cpu_flags", [])),
                    lanes=simd.get("lanes"))
        blob = json.dumps(info, sort_keys=True).encode()
        info["id"] = hashlib.sha256(blob).hexdigest()[:12]
        _HOST_FP = info
    return _HOST_FP


def diff_main(old_path: str, new_path: str) -> int:
    """Compare two ladder files config-by-config; refuses (exit 2) when
    any compared pair was produced on different hosts."""
    with open(old_path) as fh:
        old = {r["config"]: r for r in json.load(fh)}
    with open(new_path) as fh:
        new = {r["config"]: r for r in json.load(fh)}
    rc = 0
    for name in sorted(set(old) & set(new)):
        a, b = old[name], new[name]
        ha, hb = a.get("host"), b.get("host")
        if not ha or not hb or ha.get("id") != hb.get("id"):
            print(f"[{name}] REFUSED: rows are from different hosts "
                  f"({(ha or {}).get('id', 'unstamped')} vs "
                  f"{(hb or {}).get('id', 'unstamped')}) — wall-clock "
                  f"deltas across hosts are not comparable")
            rc = 2
            continue
        wa, wb = a.get("ours_wall_s"), b.get("ours_wall_s")
        if wa and wb:
            print(f"[{name}] ours_wall_s {wa} -> {wb} "
                  f"({(wb - wa) / wa * 100.0:+.1f}%)  quality "
                  f"{a.get('ours_quality')} -> {b.get('ours_quality')}")
    return rc

FULL_CONFIGS = [
    # BASELINE.md ladder #2: HIGGS 11M x 28, binary:logistic, AUC
    dict(name="higgs_binary", rows=11_000_000, cols=28, kind="binary",
         objective="binary:logistic", metric="auc", rounds=5,
         params=dict(max_depth=8, eta=0.3, max_bin=256)),
    # ladder #3: covertype 581k x 54, 7 classes, multi:softprob, merror
    dict(name="covertype_softprob", rows=581_012, cols=54, kind="multi",
         classes=7, objective="multi:softprob", metric="merror", rounds=5,
         params=dict(max_depth=8, eta=0.3, max_bin=256)),
    # ladder #4: MSLR-WEB30K 3.77M docs / 31k queries, rank:ndcg, ndcg@10
    dict(name="mslr_ndcg", rows=3_771_125, cols=136, kind="rank",
         groups=31_531, objective="rank:ndcg", metric="ndcg@10", rounds=5,
         params=dict(max_depth=8, eta=0.3, max_bin=256)),
    # ladder #5 slice: Criteo-class out-of-core — OUR side streams zstd
    # pages (ExtMemQuantileDMatrix); the oracle trains in-memory on the
    # same rows (its extmem needs a disk cache pass; quality is the
    # comparable axis here, scale the honest caveat)
    dict(name="criteo_extmem", rows=1_000_000_000, cols=39, kind="extmem",
         objective="binary:logistic", metric="auc", rounds=5,
         params=dict(max_depth=8, eta=0.3, max_bin=256)),
]


def make_data(cfg, scale: float, seed: int = 0):
    rng = np.random.default_rng(seed)
    if cfg["kind"] == "extmem":
        # bounded stand-in: page count scales, page size fixed; cap keeps
        # the 1-core CPU run finite (watcher sets a bigger cap on TPU)
        cap = max(int(os.environ.get("LADDER_EXTMEM_CAP", "262144")),
                  65536)  # below one page the row floor would hit zero
        R = int(min(max(cfg["rows"] * scale, 64 * 1024), cap))
        R = (R // 65536) * 65536
        F = cfg["cols"]
        X = rng.normal(size=(R, F)).astype(np.float32)
        X[rng.random((R, F)) < 0.25] = np.nan  # Criteo-like sparsity
        lin = (np.nan_to_num(X[:, 0]) * 1.2 - np.nan_to_num(X[:, 1])
               + 0.5 * np.nan_to_num(X[:, 2]) * np.nan_to_num(X[:, 3]))
        y = (lin + rng.normal(scale=0.5, size=R) > 0).astype(np.float32)
        return R, X, y, None
    R = max(int(cfg["rows"] * scale), 10_000)
    F = cfg["cols"]
    X = rng.normal(size=(R, F)).astype(np.float32)
    X[rng.random((R, F)) < 0.02] = np.nan  # HIGGS-like light missingness
    lin = (np.nan_to_num(X[:, 0]) * 1.2 - np.nan_to_num(X[:, 1])
           + 0.5 * np.nan_to_num(X[:, 2]) * np.nan_to_num(X[:, 3]))
    if cfg["kind"] == "binary":
        y = (lin + rng.normal(scale=0.5, size=R) > 0).astype(np.float32)
        return R, X, y, None
    if cfg["kind"] == "multi":
        K = cfg["classes"]
        z = lin + rng.normal(scale=0.5, size=R)
        y = np.clip(((z - z.min()) / (np.ptp(z) + 1e-9) * K).astype(np.int64),
                    0, K - 1).astype(np.float32)
        return R, X, y, None
    # ranking: ~120 docs/query like MSLR; graded 0-4 relevance
    G = max(int(cfg["groups"] * scale), 100)
    sizes = rng.integers(40, 200, size=G)
    R = int(sizes.sum())
    X = rng.normal(size=(R, cfg["cols"])).astype(np.float32)
    rel = np.clip((X[:, 0] + 0.5 * rng.normal(size=R) + 2.0).astype(np.int64),
                  0, 4).astype(np.float32)
    return R, X, rel, sizes.astype(np.int64)


def eval_quality(metric, preds, y, group_sizes):
    from xgboost_tpu.metric import create_metric

    fn, _name = create_metric(metric)  # returns (callable, resolved name)
    kw = {}
    if group_sizes is not None:
        kw["group_ptr"] = np.concatenate([[0], np.cumsum(group_sizes)])
    return float(fn(np.asarray(preds), np.asarray(y, np.float64), **kw))


# nthread values for the host-parallelism scaling sweep (satellite of the
# ParallelFor PR): 1 / 4 / all-cores ("0" resolves the default).  Override
# with LADDER_NTHREAD="1,2,0"; LADDER_NTHREAD="" disables the sweep (the
# headline run always uses all cores and records what it used).
def _sweep_nthreads():
    raw = os.environ.get("LADDER_NTHREAD", "1,4,0")
    out = []
    for tok in raw.split(","):
        tok = tok.strip()
        if tok:
            out.append(int(tok))
    return out


# simd levels for the lane-width scaling sweep (round 7): each level
# re-runs the warmed program at nthread=1 — the per-core roofline the SIMD
# work targets — plus one all-cores vector run to show the SIMD and
# threading wins COMPOSE.  Results are bitwise level-invariant
# (docs/native_threading.md), so the sweep times identical outputs.
# Override with LADDER_SIMD="scalar,auto"; LADDER_SIMD="" disables.
def _sweep_simd():
    raw = os.environ.get("LADDER_SIMD", "scalar,auto")
    levels = [tok.strip() for tok in raw.split(",") if tok.strip()]
    from xgboost_tpu.utils import native

    for lvl in levels:  # typos fail HERE, not mid-ladder after a config ran
        native.set_simd(lvl)
    native.set_simd("auto")
    return levels


# LADDER_REPS=N takes the MINIMUM of N runs per sweep point (default 1).
# On time-shared bench hosts single-shot walls swing 2-3x with scheduler
# noise; min-of-N is the standard estimator for the code's actual cost.
def _reps() -> int:
    return max(1, int(os.environ.get("LADDER_REPS", "1")))


def _timed_min(fn) -> float:
    best = float("inf")
    for _ in range(_reps()):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run_ours(cfg, X, y, group_sizes):
    import xgboost_tpu as xtb

    if cfg["kind"] == "extmem":
        from xgboost_tpu.data.extmem import DataIter, ExtMemQuantileDMatrix

        page = 65536

        class Pages(DataIter):
            def __init__(self):
                super().__init__()
                self._i = 0

            def next(self, input_data):
                if self._i * page >= len(y):
                    return 0
                lo = self._i * page
                input_data(data=X[lo:lo + page], label=y[lo:lo + page])
                self._i += 1
                return 1

            def reset(self):
                self._i = 0

        d = ExtMemQuantileDMatrix(Pages(),
                                  max_bin=cfg["params"]["max_bin"])
    else:
        d = xtb.DMatrix(X, label=y)
    if group_sizes is not None:
        d.set_group(group_sizes)
    p = {"objective": cfg["objective"], **cfg["params"]}
    if cfg["kind"] == "multi":
        p["num_class"] = cfg["classes"]
    # warm the jit cache (and the ellpack build) so the timed run measures
    # steady-state boosting, not XLA compilation — the reference's kernels
    # are AOT, so this is the like-for-like comparison
    xtb.train(p, d, 1, verbose_eval=False)
    t0 = time.perf_counter()
    bst = xtb.train(p, d, cfg["rounds"], verbose_eval=False)
    # predictions force full materialization (train is async under jit)
    preds = np.asarray(bst.predict(d))
    dt = time.perf_counter() - t0

    # nthread scaling sweep over the SAME warmed program cache: pool width
    # is not a jit cache key (results are bitwise nthread-invariant,
    # docs/native_threading.md), so each re-run times only the native
    # kernels at a different width.  The width rides the params dict — the
    # same plumbing XGBoosterSetParam("nthread") uses.
    from xgboost_tpu.utils import native

    def train_predict(params):
        b2 = xtb.train(params, d, cfg["rounds"], verbose_eval=False)
        np.asarray(b2.predict(d))

    scaling = {}
    for n in _sweep_nthreads():
        wall = _timed_min(lambda: train_predict({**p, "nthread": n}))
        scaling[f"nthread={n if n > 0 else 'all'}"] = dict(
            wall_s=round(wall, 2), effective=native.get_nthread())

    # lane-width sweep over the same warmed cache: simd level is applied
    # inside the native kernels at execution time, so flipping it re-times
    # the identical program with different (identical-output) bodies.  The
    # pool width must ride the params dict like the nthread sweep above —
    # train() re-applies the params' width, so a bare set_nthread(1) here
    # would be silently reset to all cores at the first configure.
    simd_scaling = {}
    for level in _sweep_simd():
        eff = native.set_simd(level)
        wall = _timed_min(lambda: train_predict({**p, "nthread": 1}))
        simd_scaling[f"{level}@nthread=1"] = dict(
            wall_s=round(wall, 2), effective=eff)
    if simd_scaling:
        native.set_simd("auto")
        wall = _timed_min(lambda: train_predict({**p, "nthread": 0}))
        simd_scaling["auto@nthread=all"] = dict(
            wall_s=round(wall, 2), effective=native.get_simd())
    native.set_simd("auto")
    native.set_nthread(0)  # back to the defaults for the next config
    return dt, preds, scaling, simd_scaling


def run_oracle(cfg, X, y, group_sizes):
    sys.path.insert(0, ORACLE_PKG)
    import xgboost as xgb  # the oracle build

    d = xgb.DMatrix(X, label=y, missing=np.nan)
    if group_sizes is not None:
        d.set_group(group_sizes)
    p = {"objective": cfg["objective"], "tree_method": "hist",
         "nthread": os.cpu_count(), **cfg["params"]}
    if cfg["kind"] == "multi":
        p["num_class"] = cfg["classes"]
    t0 = time.perf_counter()
    bst = xgb.train(p, d, num_boost_round=cfg["rounds"])
    preds = np.asarray(bst.predict(d))
    dt = time.perf_counter() - t0
    return dt, preds


def main() -> None:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "BENCH_LADDER.json"
    # When the oracle build is unavailable (this container has no
    # /root/reference checkout to rebuild it from), fall back to the PRIOR
    # ladder file's oracle wall/quality per config — valid as a comparison
    # only when rows/scale/platform match, which we check, and labeled with
    # its provenance in the emitted row.
    prior_oracle = {}
    if os.path.exists(out_path):
        try:
            with open(out_path) as fh:
                for row in json.load(fh):
                    if row.get("oracle_wall_s") is not None:
                        prior_oracle[row["config"]] = row
        except Exception:  # noqa: BLE001 - a corrupt prior file is not fatal
            prior_oracle = {}
    import jax

    # sitecustomize freezes jax_platforms=axon at interpreter startup; the
    # env var alone cannot override it post-import (tests/conftest.py has
    # the same rule).  Never touch the tunnel unless explicitly asked.
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    platform = jax.devices()[0].platform
    scale = float(os.environ.get("LADDER_SCALE",
                                 "1.0" if platform == "tpu" else "0.05"))
    rows_out = []
    for cfg in FULL_CONFIGS:
        R, X, y, groups = make_data(cfg, scale)
        print(f"[{cfg['name']}] rows={R} cols={cfg['cols']} "
              f"rounds={cfg['rounds']} scale={scale}", flush=True)
        ours_s, ours_pred, scaling, simd_scaling = run_ours(cfg, X, y, groups)
        ours_q = eval_quality(cfg["metric"], ours_pred, y, groups)
        print(f"  ours:   {ours_s:8.1f}s  {cfg['metric']}={ours_q:.5f}  "
              f"scaling={scaling}  simd={simd_scaling}", flush=True)
        try:
            orc_s, orc_pred = run_oracle(cfg, X, y, groups)
            orc_q = eval_quality(cfg["metric"], orc_pred, y, groups)
            print(f"  oracle: {orc_s:8.1f}s  {cfg['metric']}={orc_q:.5f}",
                  flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"  oracle FAILED: {e!r}", flush=True)
            orc_s, orc_q = None, None
        oracle_source = "fresh"
        note = None
        if orc_s is None:
            prev = prior_oracle.get(cfg["name"])
            prev_host = (prev or {}).get("host") or {}
            if (prev and prev.get("rows") == R
                    and prev.get("platform") == platform
                    and prev_host.get("id") != _host_fingerprint()["id"]):
                # a cross-host oracle wall is not a baseline — refuse it
                # loudly rather than mix hosts into speed_vs_oracle
                print(f"  oracle: archived numbers REFUSED — host "
                      f"{prev_host.get('id', 'unstamped')} != this host "
                      f"{_host_fingerprint()['id']}", flush=True)
                prev = None
            if (prev and prev.get("rows") == R
                    and prev.get("platform") == platform):
                orc_s = prev["oracle_wall_s"]
                orc_q = prev.get("oracle_quality")
                oracle_source = "archived (oracle build unavailable)"
                note = ("oracle walls are archived from an earlier run "
                        "on THIS host (fingerprint-matched) — "
                        "like-for-like, but from an older session")
                print(f"  oracle: {orc_s:8.1f}s  [archived numbers — "
                      f"same rows/platform/host]", flush=True)
        from xgboost_tpu.utils import native as _native

        rows_out.append(dict(
            config=cfg["name"], rows=R, cols=cfg["cols"],
            full_rows=cfg["rows"], scale=scale, rounds=cfg["rounds"],
            objective=cfg["objective"], metric=cfg["metric"],
            platform=platform, host=_host_fingerprint(),
            nthread=_native.get_nthread(), cores=os.cpu_count(),
            simd=_native.simd_info(), sweep_reps=_reps(),
            ours_wall_s=round(ours_s, 2), ours_quality=round(ours_q, 6),
            nthread_scaling=scaling,
            simd_scaling=simd_scaling,
            oracle_wall_s=None if orc_s is None else round(orc_s, 2),
            oracle_quality=None if orc_q is None else round(orc_q, 6),
            oracle_source=oracle_source,
            **({"note": note} if note else {}),
            speed_vs_oracle=(None if orc_s is None
                             else round(orc_s / ours_s, 4)),
        ))
        with open(out_path, "w") as fh:  # checkpoint after each config
            json.dump(rows_out, fh, indent=1)
    print(json.dumps({"ladder": rows_out}), flush=True)


# ---------------------------------------------------------------------------
# Out-of-core full-scale rows (ISSUE 12 / ROADMAP item 2): --extmem appends
#   extmem_scaling     — paged vs resident at EQUAL scale, prefetch on/off,
#                        world 1/2 (min-of-N, honest host-bound notes)
#   higgs_full         — the committed full-scale HIGGS-11M 100+-round CPU
#                        number, warmup amortized honestly (the wall
#                        INCLUDES XLA compile + ellpack build)
#   criteo_extmem_40m  — Criteo-shaped sparse/categorical ~40M+ rows,
#                        paged, peak RSS recorded vs the resident-matrix
#                        size it avoids
# Each row runs in a fresh subprocess so peak-RSS numbers are clean.
# ---------------------------------------------------------------------------

EXTMEM_ROW_NAMES = ("extmem_scaling", "higgs_full", "criteo_extmem_40m")


def _peak_rss_mb() -> float:
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def _extmem_counters():
    from xgboost_tpu.data import extmem

    ins = extmem.instruments()
    return {"decode_s": ins[0].get(), "wait_s": ins[1].get(),
            "overlap_s": ins[2].get(), "pages": ins[3].get()}


def _counter_delta(before):
    now = _extmem_counters()
    return {k: round(now[k] - before[k], 3) for k in before}


def _scaling_page(shard: int, rows: int, cols: int):
    rng = np.random.default_rng(9000 + shard)
    X = rng.normal(size=(rows, cols)).astype(np.float32)
    X[rng.random(X.shape) < 0.02] = np.nan
    y = (np.nan_to_num(X[:, 0]) * 1.2 - np.nan_to_num(X[:, 1])
         + 0.5 * np.nan_to_num(X[:, 2]) * np.nan_to_num(X[:, 3])
         + rng.normal(scale=0.5, size=rows) > 0).astype(np.float32)
    return X, y


def _scaling_iter_cls(n_pages: int, page_rows: int, cols: int):
    import xgboost_tpu as xtb

    class Pages(xtb.DataIter):
        def __init__(self, shards):
            super().__init__()
            self._shards, self._i = list(shards), 0

        def reset(self):
            self._i = 0

        def next(self, input_data):
            if self._i >= len(self._shards):
                return 0
            X, y = _scaling_page(self._shards[self._i], page_rows, cols)
            input_data(data=X, label=y)
            self._i += 1
            return 1

    return Pages


def _scaling_world2_worker(rank, world, *, n_pages, page_rows, cols, params,
                           rounds, out_dir):
    import xgboost_tpu as xtb

    Pages = _scaling_iter_cls(n_pages, page_rows, cols)

    def data_fn(smap, rank, world):
        return Pages(smap.shards_of(rank))

    cfg = xtb.ExtMemConfig(data_fn, num_shards=n_pages,
                           max_bin=params["max_bin"])
    # build the paged matrix ONCE: the timed wall must match the world-1
    # legs (train + predict over already-ingested pages), not re-pay
    # ingest per call
    d, _evals = cfg.build()
    xtb.train(params, d, 1, verbose_eval=False)  # warm the jit cache
    t0 = time.perf_counter()
    bst = xtb.train(params, d, rounds, verbose_eval=False)
    np.asarray(bst.predict(d))
    wall = time.perf_counter() - t0
    with open(os.path.join(out_dir, f"w{rank}.wall"), "w") as fh:
        fh.write(str(wall))


def bench_row_extmem_scaling() -> dict:
    """Paged-vs-resident at equal scale.  The paged legs run with the host
    page cache DISABLED (XTB_EXTMEM_HOST_CACHE_MB=0) so every level pays
    the real stage cost — that is the streaming regime the prefetch
    pipeline exists for; with the default cache budget the pages of this
    size are simply resident after round 1 and the legs converge."""
    import functools
    import tempfile

    import xgboost_tpu as xtb

    scale = float(os.environ.get("LADDER_EXTMEM_SCALE", "1.0"))
    n_pages, cols = 16, 28
    page_rows = max(int(65536 * scale), 4096)
    rounds = 5
    params = {"objective": "binary:logistic", "max_depth": 8, "eta": 0.3,
              "max_bin": 256}
    Pages = _scaling_iter_cls(n_pages, page_rows, cols)

    os.environ["XTB_EXTMEM_HOST_CACHE_MB"] = "0"
    d_ext = xtb.ExtMemQuantileDMatrix(Pages(range(n_pages)), max_bin=256)

    gen = [_scaling_page(s, page_rows, cols) for s in range(n_pages)]
    X = np.concatenate([p[0] for p in gen])
    y = np.concatenate([p[1] for p in gen])
    del gen
    d_res = xtb.DMatrix(X, label=y)

    def timed_leg(d, extra):
        p = {**params, **extra}
        xtb.train(p, d, 1, verbose_eval=False)  # warm the jit cache
        before = _extmem_counters()

        def once():
            bst = xtb.train(p, d, rounds, verbose_eval=False)
            np.asarray(bst.predict(d))

        wall = _timed_min(once)
        return wall, _counter_delta(before)

    legs = {}
    wall, _ = timed_leg(d_res, {})
    legs["resident_world1"] = dict(wall_s=round(wall, 2))
    wall, ctr = timed_leg(d_ext, {"_extmem_prefetch": "1"})
    legs["paged_world1_prefetch"] = dict(wall_s=round(wall, 2), extmem=ctr)
    wall, ctr = timed_leg(d_ext, {"_extmem_prefetch": "0"})
    legs["paged_world1_noprefetch"] = dict(wall_s=round(wall, 2), extmem=ctr)
    del d_ext, X, y, d_res

    # world 2 over the tracker relay: per-worker steady-state walls (the
    # workers time their own warmed runs; spawn/rendezvous excluded).
    # Pickle the worker under its importable module name, not __main__ —
    # the spawned children re-import it from scripts/ (launcher mod_dir).
    from xgboost_tpu.launcher import run_distributed

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import bench_ladder as _mod

    with tempfile.TemporaryDirectory(prefix="xtb_lad_w2_") as tmp:
        run_distributed(
            functools.partial(
                _mod._scaling_world2_worker, n_pages=n_pages,
                page_rows=page_rows, cols=cols, params=params,
                rounds=rounds, out_dir=tmp),
            num_workers=2, platform="cpu", timeout=1800,
            rendezvous="tracker")
        walls = [float(open(os.path.join(tmp, f"w{r}.wall")).read())
                 for r in range(2)]
    legs["paged_world2_prefetch"] = dict(
        wall_s=round(max(walls), 2), per_worker=[round(w, 2) for w in walls])

    return dict(
        config="extmem_scaling", rows=n_pages * page_rows, cols=cols,
        pages=n_pages, page_rows=page_rows, scale=scale, rounds=rounds,
        platform="cpu", cores=os.cpu_count(), sweep_reps=_reps(),
        host_cache_mb=0, legs=legs,
        note=("paged legs re-stage every page each level (host cache "
              "disabled) — the streaming regime; world-2 walls are "
              "per-worker steady state over the socket relay on ONE "
              "host, so they measure composition overhead, not "
              "scale-out"),
    )


def bench_row_higgs_full() -> dict:
    import xgboost_tpu as xtb

    rows = int(float(os.environ.get("LADDER_FULL_ROWS", "11000000")))
    rounds = int(os.environ.get("LADDER_FULL_ROUNDS", "100"))
    cfg = dict(name="higgs_full", rows=rows, cols=28, kind="binary",
               objective="binary:logistic", metric="auc", rounds=rounds,
               params=dict(max_depth=8, eta=0.3, max_bin=256))
    R, X, y, _ = make_data(cfg, 1.0)
    t0 = time.perf_counter()
    d = xtb.DMatrix(X, label=y)
    p = {"objective": cfg["objective"], **cfg["params"]}
    bst = xtb.train(p, d, rounds, verbose_eval=False)
    preds = np.asarray(bst.predict(d))
    wall = time.perf_counter() - t0
    q = eval_quality("auc", preds, y, None)
    return dict(
        config="higgs_full", rows=R, cols=28, full_rows=rows, scale=1.0,
        rounds=rounds, objective=cfg["objective"], metric="auc",
        platform="cpu", cores=os.cpu_count(),
        ours_wall_s=round(wall, 2), ours_quality=round(q, 6),
        peak_rss_mb=round(_peak_rss_mb(), 1),
        note=("full-scale in-memory run; the wall INCLUDES sketch + "
              "ellpack build + XLA compile (one-shot costs amortized "
              "honestly over the 100-round run, no warmup subtraction)"),
    )


def bench_row_criteo_extmem() -> dict:
    import gc

    import xgboost_tpu as xtb

    n_pages = int(os.environ.get("LADDER_CRITEO_PAGES", "64"))
    page_rows = int(os.environ.get("LADDER_CRITEO_PAGE_ROWS", "655360"))
    rounds = 5
    n_num, n_cat = 13, 26
    cols = n_num + n_cat
    n_cats = 100
    # max_bin 128 keeps page codes in u8 (129 symbols incl. the missing
    # sentinel; 256 would tip the pages into int16 and double the store),
    # and the host/device page-cache budget is the documented RSS bound
    # knob (docs/extmem.md) — hot pages stay cached, the rest re-stage
    max_bin = int(os.environ.get("LADDER_CRITEO_MAX_BIN", "128"))
    os.environ.setdefault("XTB_EXTMEM_HOST_CACHE_MB", "512")

    def page(shard: int):
        rng = np.random.default_rng(7000 + shard)
        X = np.empty((page_rows, cols), np.float32)
        X[:, :n_num] = rng.normal(size=(page_rows, n_num))
        X[:, :n_num][rng.random((page_rows, n_num)) < 0.2] = np.nan
        # skewed categorical codes, Criteo-style head-heavy vocabulary
        X[:, n_num:] = np.minimum(
            rng.geometric(0.08, size=(page_rows, n_cat)) - 1, n_cats - 1)
        lin = (np.nan_to_num(X[:, 0]) * 1.2 - np.nan_to_num(X[:, 1])
               + 0.5 * np.nan_to_num(X[:, 2]) * np.nan_to_num(X[:, 3])
               + 0.3 * (X[:, n_num] == 0))
        y = (lin + rng.normal(scale=0.5, size=page_rows) > 0
             ).astype(np.float32)
        return X, y

    ftypes = ["q"] * n_num + ["c"] * n_cat

    class Pages(xtb.DataIter):
        def __init__(self):
            super().__init__()
            self._i = 0

        def reset(self):
            self._i = 0

        def next(self, input_data):
            if self._i >= n_pages:
                return 0
            X, y = page(self._i)
            input_data(data=X, label=y, feature_types=ftypes)
            self._i += 1
            return 1

    rows = n_pages * page_rows
    resident_mb = rows * cols * 4 / 2**20
    t0 = time.perf_counter()
    d = xtb.ExtMemQuantileDMatrix(Pages(), max_bin=max_bin,
                                  enable_categorical=True)
    ingest_wall = time.perf_counter() - t0
    gc.collect()
    paged_mb = sum(getattr(p, "nbytes_compressed", p.nbytes)
                   for p in d._pages) / 2**20
    params = {"objective": "binary:logistic", "max_depth": 8, "eta": 0.3,
              "max_bin": max_bin}
    before = _extmem_counters()
    t0 = time.perf_counter()
    bst = xtb.train(params, d, rounds, verbose_eval=False)
    preds = np.asarray(bst.predict(d))
    train_wall = time.perf_counter() - t0
    gc.collect()
    # AUC on a deterministic 1/8 stride sample: the metric's f64 buffers
    # over all 40M+ rows would add ~700 MB to the very peak this row
    # exists to bound
    q = eval_quality("auc", preds[::8],
                     np.asarray(d.info.label[::8], np.float64), None)
    peak = _peak_rss_mb()
    return dict(
        config="criteo_extmem_40m", rows=rows, cols=cols, pages=n_pages,
        page_rows=page_rows, categorical_cols=n_cat, scale=1.0,
        rounds=rounds, objective="binary:logistic",
        metric="auc@stride8", max_bin=max_bin,
        platform="cpu", cores=os.cpu_count(),
        host_cache_mb=float(os.environ["XTB_EXTMEM_HOST_CACHE_MB"]),
        ingest_wall_s=round(ingest_wall, 2),
        ours_wall_s=round(train_wall, 2), ours_quality=round(q, 6),
        peak_rss_mb=round(peak, 1), resident_matrix_mb=round(resident_mb, 1),
        paged_store_mb=round(paged_mb, 1),
        rss_bounded=bool(peak < resident_mb),
        extmem=_counter_delta(before),
        note=("pages synthesized on the fly (never materialized "
              "together); peak RSS covers interpreter + jax runtime + "
              "binned u8 pages + the 512 MB page-cache budget + per-row "
              "training state, and must stay below the f32 "
              "resident-matrix size the paged path avoids (an in-memory "
              "run would hold that matrix AND its binned pages); "
              "max_bin=128 keeps page codes u8; zstd absent in this "
              "container, so pages are uncompressed (paged_store_mb "
              "would shrink further with zstandard installed)"),
    )


def extmem_main(out_path: str) -> None:
    """Run the out-of-core rows, each in a fresh subprocess (clean RSS),
    merging into the existing ladder file by config name."""
    import subprocess
    import tempfile

    only = [t for t in os.environ.get("LADDER_EXTMEM_ONLY", "").split(",")
            if t.strip()]
    rows = []
    if os.path.exists(out_path):
        with open(out_path) as fh:
            rows = json.load(fh)
    for name in EXTMEM_ROW_NAMES:
        if only and name not in only:
            continue
        with tempfile.NamedTemporaryFile(suffix=".json") as tmp:
            print(f"[extmem ladder] {name} ...", flush=True)
            t0 = time.perf_counter()
            subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--row", name,
                 tmp.name],
                check=True, env={**os.environ, "JAX_PLATFORMS": "cpu"})
            with open(tmp.name) as fh:
                row = json.load(fh)
        print(f"[extmem ladder] {name} done in "
              f"{time.perf_counter() - t0:.0f}s", flush=True)
        rows = [r for r in rows if r.get("config") != name] + [row]
        with open(out_path, "w") as fh:  # checkpoint after each row
            json.dump(rows, fh, indent=1)


def _row_main(name: str, out_path: str) -> None:
    import jax

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    fn = {"extmem_scaling": bench_row_extmem_scaling,
          "higgs_full": bench_row_higgs_full,
          "criteo_extmem_40m": bench_row_criteo_extmem}[name]
    row = fn()
    row["host"] = _host_fingerprint()
    with open(out_path, "w") as fh:
        json.dump(row, fh, indent=1)
    print(json.dumps(row, indent=1), flush=True)


if __name__ == "__main__":
    if "--row" in sys.argv:
        i = sys.argv.index("--row")
        _row_main(sys.argv[i + 1], sys.argv[i + 2])
    elif "--diff" in sys.argv:
        i = sys.argv.index("--diff")
        sys.exit(diff_main(sys.argv[i + 1], sys.argv[i + 2]))
    elif "--extmem" in sys.argv:
        args = [a for a in sys.argv[1:] if not a.startswith("--")]
        extmem_main(args[0] if args else "BENCH_LADDER.json")
    else:
        main()
