"""Profile the ladder covertype config (58k x 54, 7 classes) on CPU.

Coarse wall-clock attribution of one ladder run: where do the 30s go?
Usage: python scripts/profile_covertype.py [--cprofile]
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

from scripts.bench_ladder import FULL_CONFIGS, make_data


def main():
    cfg = FULL_CONFIGS[1]
    R, X, y, groups = make_data(cfg, 0.1)
    print(f"rows={R} cols={cfg['cols']} classes={cfg['classes']}")

    import xgboost_tpu as xtb

    p = {"objective": cfg["objective"], "num_class": cfg["classes"],
         **cfg["params"]}

    t0 = time.perf_counter()
    d = xtb.DMatrix(X, label=y)
    t1 = time.perf_counter()
    print(f"DMatrix build: {t1 - t0:.2f}s")

    # warmup (compile)
    xtb.train(p, d, 1, verbose_eval=False)
    t2 = time.perf_counter()
    print(f"warmup round (compile): {t2 - t1:.2f}s")

    if "--cprofile" in sys.argv:
        import cProfile
        import pstats

        pr = cProfile.Profile()
        pr.enable()
        bst = xtb.train(p, d, cfg["rounds"], verbose_eval=False)
        np.asarray(bst.predict(d))
        pr.disable()
        st = pstats.Stats(pr)
        st.sort_stats("cumulative").print_stats(40)
    else:
        t3 = time.perf_counter()
        bst = xtb.train(p, d, cfg["rounds"], verbose_eval=False)
        t4 = time.perf_counter()
        print(f"train 5 rounds: {t4 - t3:.2f}s")
        preds = np.asarray(bst.predict(d))
        t5 = time.perf_counter()
        print(f"predict: {t5 - t4:.2f}s")


if __name__ == "__main__":
    main()
