"""Profile the ladder covertype config (58k x 54, 7 classes) on CPU.

Coarse wall-clock attribution of one ladder run: where do the 30s go?
Emits a chrome://tracing-compatible JSONL span trace alongside the
timings (docs/observability.md) — per-level grow spans, gradient, eval,
and every XLA compile — plus a per-phase summary from the telemetry
histogram.

Usage: python scripts/profile_covertype.py [--cprofile] [--trace PATH]
       (default trace path: covertype_trace.jsonl in the CWD)
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

from scripts.bench_ladder import FULL_CONFIGS, make_data


def main():
    cfg = FULL_CONFIGS[1]
    R, X, y, groups = make_data(cfg, 0.1)
    print(f"rows={R} cols={cfg['cols']} classes={cfg['classes']}")

    import xgboost_tpu as xtb
    from xgboost_tpu import telemetry

    trace_path = "covertype_trace.jsonl"
    if "--trace" in sys.argv:
        i = sys.argv.index("--trace") + 1
        if i >= len(sys.argv) or sys.argv[i].startswith("--"):
            sys.exit("--trace requires a path argument")
        trace_path = sys.argv[i]
    telemetry.trace.configure(trace_path)
    telemetry.enable()

    p = {"objective": cfg["objective"], "num_class": cfg["classes"],
         **cfg["params"]}

    t0 = time.perf_counter()
    d = xtb.DMatrix(X, label=y)
    t1 = time.perf_counter()
    print(f"DMatrix build: {t1 - t0:.2f}s")

    # warmup (compile)
    with telemetry.compile_delta() as warm:
        xtb.train(p, d, 1, verbose_eval=False)
    t2 = time.perf_counter()
    print(f"warmup round (compile): {t2 - t1:.2f}s  "
          f"[{warm.count} XLA compiles]")

    if "--cprofile" in sys.argv:
        import cProfile
        import pstats

        pr = cProfile.Profile()
        pr.enable()
        bst = xtb.train(p, d, cfg["rounds"], verbose_eval=False)
        np.asarray(bst.predict(d))
        pr.disable()
        st = pstats.Stats(pr)
        st.sort_stats("cumulative").print_stats(40)
    else:
        t3 = time.perf_counter()
        with telemetry.compile_delta() as steady:
            bst = xtb.train(p, d, cfg["rounds"], verbose_eval=False)
        t4 = time.perf_counter()
        print(f"train 5 rounds: {t4 - t3:.2f}s  "
              f"[{steady.count} XLA compiles]")
        preds = np.asarray(bst.predict(d))
        t5 = time.perf_counter()
        print(f"predict: {t5 - t4:.2f}s")

    print("\nper-phase attribution (cumulative, incl. warmup):")
    for name, tot in sorted(telemetry.phase_totals().items(),
                            key=lambda kv: -kv[1]["seconds"]):
        print(f"  {name:<32} {tot['seconds']:8.3f}s  "
              f"{tot['count']:6d} calls")
    telemetry.trace.flush()
    print(f"\ntrace: {trace_path}  "
          "(jq -s '{traceEvents: .}' -> chrome://tracing)")


if __name__ == "__main__":
    main()
