#!/usr/bin/env python
"""Real-ENOSPC resource-degradation smoke (docs/reliability.md "Resource
pressure & graceful degradation").

Leg 1 (twin): train N rounds with checkpoints on a normal directory —
the fault-free reference bytes.

Leg 2 (tiny disk): mount a tmpfs sized to ~1.8x the final model (so the
keep-last-K snapshot set CANNOT fit) and train the same run with its
checkpoint directory on it.  The kernel returns genuine ENOSPC from
write()/fsync() mid-commit; the checkpoint ladder must prune-and-retry,
then skip, and the run must finish with BITWISE-identical model bytes,
``xtb_resource_degraded_total{subsystem="checkpoint"}`` >= 1, classified
ENOSPC errors in ``xtb_resource_errors_total``, and — the atomicity
half — every checkpoint that DID commit on the full disk scrubs clean
(a torn write may never surface under a final name).

Without root or a working ``mount`` (not a container privilege
everywhere), the leg downgrades to the injected ``disk_full`` fault kind
at the same seam and says so loudly — the fault-injection twin of the
same ladder.

Usage:  python scripts/resource_smoke.py [rounds]
"""
import os
import subprocess
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _try_mount_tmpfs(path: str, size_bytes: int) -> bool:
    if os.geteuid() != 0:
        return False
    os.makedirs(path, exist_ok=True)
    try:
        subprocess.run(["mount", "-t", "tmpfs", "-o",
                        f"size={size_bytes}", "tmpfs", path],
                       check=True, capture_output=True, timeout=30)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _umount(path: str) -> None:
    subprocess.run(["umount", "-l", path], check=False,
                   capture_output=True, timeout=30)


def main() -> int:
    import tempfile

    import numpy as np

    import xgboost_tpu as xtb
    from xgboost_tpu.reliability import faults, resources
    from xgboost_tpu.reliability.checkpoint import (CheckpointCallback,
                                                    scrub_dir)
    from xgboost_tpu.telemetry.registry import get_registry

    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    rng = np.random.default_rng(0)
    X = rng.normal(size=(4000, 12)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    params = {"objective": "binary:logistic", "max_depth": 5, "eta": 0.3,
              "max_bin": 64}

    def train(ckpt_dir):
        cb = CheckpointCallback(ckpt_dir, interval=1, keep_last=3)
        bst = xtb.train(dict(params), xtb.DMatrix(X, label=y), rounds,
                        callbacks=[cb], verbose_eval=False)
        return bytes(bst.save_raw()), cb

    # ---- leg 1: fault-free twin on a roomy disk
    with tempfile.TemporaryDirectory(prefix="xtb_res_twin_") as td:
        twin_bytes, _ = train(os.path.join(td, "ck"))
        one_ckpt = max(os.path.getsize(os.path.join(td, "ck", f))
                       for f in os.listdir(os.path.join(td, "ck")))
    print(f"[resource_smoke] twin: {rounds} rounds, model "
          f"{len(twin_bytes)} B, newest checkpoint {one_ckpt} B")

    # ---- leg 2: the same run against a disk that cannot hold keep-last-3
    tiny_size = int(one_ckpt * 1.8)
    mnt = tempfile.mkdtemp(prefix="xtb_res_tiny_")
    real_disk = _try_mount_tmpfs(mnt, tiny_size)
    if not real_disk:
        print("[resource_smoke] NOTE: cannot mount tmpfs (not root, or "
              "mount unavailable) — downgrading to the injected "
              "disk_full kind at checkpoint.write")
        faults.install({"faults": [
            {"site": "checkpoint.write", "kind": "disk_full",
             "round": max(2, rounds // 2), "times": 2}]})
    deg0 = 0.0
    fam = get_registry().get("xtb_resource_degraded_total")
    if fam is not None:
        deg0 = sum(c.value for v, c in fam.collect()
                   if v == ("checkpoint",))
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            tiny_bytes, cb = train(os.path.join(mnt, "ck"))
        scrub = scrub_dir(os.path.join(mnt, "ck"))
    finally:
        faults.clear()
        if real_disk:
            _umount(mnt)

    fam = get_registry().get("xtb_resource_degraded_total")
    degraded = sum(c.value for v, c in fam.collect()
                   if v == ("checkpoint",)) - deg0
    efam = get_registry().get("xtb_resource_errors_total")
    enospc = sum(c.value for v, c in (efam.collect() if efam else ())
                 if v and v[0] == "ENOSPC")
    loud = sum(1 for w in caught if "degraded" in str(w.message))

    print(f"[resource_smoke] tiny disk ({tiny_size} B, "
          f"{'real tmpfs' if real_disk else 'injected ENOSPC'}): "
          f"run finished; ladder steps={degraded:.0f} "
          f"skipped_rounds={cb.skipped_rounds} "
          f"ENOSPC classified={enospc:.0f} loud warnings={loud}")
    print(f"[resource_smoke] scrub on the full disk: "
          f"{len(scrub['valid'])} valid / {len(scrub['corrupt'])} corrupt")

    ok = True
    if tiny_bytes != twin_bytes:
        print("FAIL: model bytes diverged under disk pressure — "
              "degradation changed the math", file=sys.stderr)
        ok = False
    if degraded < 1:
        print("FAIL: no checkpoint ladder step was counted "
              "(xtb_resource_degraded_total)", file=sys.stderr)
        ok = False
    if scrub["corrupt"]:
        print(f"FAIL: ENOSPC left torn committed checkpoints: "
              f"{scrub['corrupt']}", file=sys.stderr)
        ok = False
    if real_disk and enospc < 1:
        print("FAIL: real ENOSPC was never classified into "
              "xtb_resource_errors_total", file=sys.stderr)
        ok = False
    if loud < 1:
        print("FAIL: degradation happened silently (no RuntimeWarning)",
              file=sys.stderr)
        ok = False
    print(f"[resource_smoke] {'OK' if ok else 'FAILED'}: bitwise parity "
          f"{'held' if tiny_bytes == twin_bytes else 'BROKEN'} across "
          f"the {'real' if real_disk else 'injected'}-ENOSPC run")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
