"""Generate golden models from the reference oracle build.

One model per family — binary, multi:softprob, dart, gblinear, categorical,
multi-target (vector leaf), rank:ndcg, survival:aft — trained by the REAL
reference (/root/oracle_build) on small deterministic data, saved as JSON
under tests/data/models/ together with the training data and the oracle's
own predictions.  tests/test_golden_models.py loads each committed model
and pins predict parity, so model-format compatibility with released
reference versions is tested WITHOUT needing the oracle at test time
(reference: tests/python/test_model_compatibility.py + generate_models.py).

Run (oracle required):  python scripts/gen_golden_models.py
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "data", "models")

GEN = r"""
import json, sys
import numpy as np
sys.path.insert(0, "/root/oracle_build/pkg")
import xgboost as xgb

out_dir = sys.argv[1]
rng = np.random.default_rng(7)
R, F = 500, 6
X = rng.normal(size=(R, F)).astype(np.float32)
X[rng.random((R, F)) < 0.1] = np.nan
ybin = (np.nan_to_num(X[:, 0]) + 0.3 * np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
ymult = np.clip((np.nan_to_num(X[:, 0]) + 2.0).astype(np.int64), 0, 3).astype(np.float32)
yreg = (np.nan_to_num(X[:, 0]) * 2 + np.nan_to_num(X[:, 2])).astype(np.float32)

np.save(out_dir + "/golden_X.npy", X)

def save(name, params, label, extra_dm=None, n_rounds=5, multi_target=False):
    kw = dict(label=label) if not multi_target else dict(label=label)
    d = xgb.DMatrix(X, missing=np.nan, **kw)
    if extra_dm:
        extra_dm(d)
    bst = xgb.train(params, d, num_boost_round=n_rounds)
    bst.save_model(f"{out_dir}/{name}.json")
    pred = bst.predict(d, output_margin=True)
    np.save(f"{out_dir}/{name}_margin.npy", np.asarray(pred, np.float32))
    print(name, "ok")

save("binary", {"objective": "binary:logistic", "max_depth": 4,
                "eta": 0.3, "tree_method": "hist"}, ybin)
save("multiclass", {"objective": "multi:softprob", "num_class": 4,
                    "max_depth": 3, "eta": 0.3, "tree_method": "hist"}, ymult)
save("dart", {"booster": "dart", "objective": "binary:logistic",
              "max_depth": 3, "eta": 0.3, "rate_drop": 0.0,
              "tree_method": "hist"}, ybin)
save("gblinear", {"booster": "gblinear", "objective": "reg:squarederror",
                  "eta": 0.5, "lambda": 0.1}, yreg, n_rounds=8)
save("rank_ndcg", {"objective": "rank:ndcg", "max_depth": 3, "eta": 0.3,
                   "tree_method": "hist"},
     np.clip(ybin * 3 + ymult, 0, 4),
     extra_dm=lambda d: d.set_group([50] * (R // 50)))

# categorical: pandas categorical column
import pandas as pd
df = pd.DataFrame({
    "a": pd.Categorical(rng.integers(0, 5, R)),
    "b": X[:, 1], "c": X[:, 2]})
dc = xgb.DMatrix(df, label=ybin, enable_categorical=True, missing=np.nan)
bst = xgb.train({"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
                 "tree_method": "hist"}, dc, num_boost_round=5)
bst.save_model(out_dir + "/categorical.json")
np.save(out_dir + "/categorical_margin.npy",
        np.asarray(bst.predict(dc, output_margin=True), np.float32))
df.to_parquet(out_dir + "/categorical_X.parquet")
print("categorical ok")

# multi-target vector-leaf
ymt = np.stack([yreg, -yreg * 0.5], axis=1)
dmt = xgb.DMatrix(X, label=ymt, missing=np.nan)
bst = xgb.train({"objective": "reg:squarederror", "max_depth": 3,
                 "eta": 0.3, "tree_method": "hist",
                 "multi_strategy": "multi_output_tree"}, dmt,
                num_boost_round=4)
bst.save_model(out_dir + "/multitarget.json")
np.save(out_dir + "/multitarget_margin.npy",
        np.asarray(bst.predict(dmt, output_margin=True), np.float32))
print("multitarget ok")

# survival AFT
ylo = np.abs(yreg) + 1.0
yhi = ylo + np.where(rng.random(R) < 0.3, np.inf, 0.5)
da = xgb.DMatrix(X, missing=np.nan)
da.set_float_info("label_lower_bound", ylo)
da.set_float_info("label_upper_bound", yhi)
bst = xgb.train({"objective": "survival:aft", "max_depth": 3, "eta": 0.3,
                 "aft_loss_distribution": "normal",
                 "aft_loss_distribution_scale": 1.0,
                 "tree_method": "hist"}, da, num_boost_round=4)
bst.save_model(out_dir + "/aft.json")
np.save(out_dir + "/aft_margin.npy",
        np.asarray(bst.predict(da, output_margin=True), np.float32))
np.save(out_dir + "/aft_bounds.npy", np.stack([ylo, yhi]))
print("aft ok")

np.save(out_dir + "/golden_labels.npy",
        np.stack([ybin, ymult, yreg]))
with open(out_dir + "/MANIFEST.json", "w") as fh:
    json.dump({"oracle_version": xgb.__version__,
               "models": ["binary", "multiclass", "dart", "gblinear",
                          "rank_ndcg", "categorical", "multitarget",
                          "aft"]}, fh, indent=1)
print("manifest ok, oracle", xgb.__version__)
"""


def main():
    os.makedirs(OUT, exist_ok=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PYTHONPATH", None)
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as fh:
        fh.write(GEN)
        path = fh.name
    subprocess.run([sys.executable, path, OUT], check=True, env=env)
    os.unlink(path)
    print("golden models written to", OUT)


if __name__ == "__main__":
    main()
