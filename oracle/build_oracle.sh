#!/bin/bash
# Build the reference dmlc/xgboost as a CPU-only oracle for parity testing.
#
# The reference repo ships an empty dmlc-core submodule and this environment
# has no network, so the build uses the from-scratch dmlc API shim in
# oracle/dmlc_shim/ (see its headers for the covered surface).
#
# Outputs (all outside the reference tree, which stays untouched):
#   /root/oracle_build/build/lib/libxgboost.so   — the oracle C library
#   /root/oracle_build/pkg/xgboost/                  — shadow python package
#     (per-file symlinks into /root/reference/python-package/xgboost plus a
#      real lib/ dir holding the .so, which libpath.py picks up first)
#
# Usage:  bash oracle/build_oracle.sh   (idempotent; ~40 min cold on 1 core)
#         then: PYTHONPATH=/root/oracle_build/pkg python -c "import xgboost"
set -euo pipefail

REF=/root/reference
SHIM=$(cd "$(dirname "$0")/dmlc_shim" && pwd)
BUILD=/root/oracle_build/build
PKG=/root/oracle_build/pkg

mkdir -p "$BUILD"
cd "$BUILD"
if [ ! -f build.ninja ]; then
  cmake "$REF" -GNinja \
    -DCMAKE_BUILD_TYPE=Release \
    -DUSE_CUDA=OFF -DUSE_NCCL=OFF -DUSE_OPENMP=ON \
    -DBUILD_WITH_SYSTEM_DMLC=ON "-Ddmlc_DIR=$SHIM/cmake"
fi
ninja

# the reference CMake pins its library output inside the source tree; move
# the artifact out and leave the reference pristine
if [ -d "$REF/lib" ]; then
  mkdir -p "$BUILD/lib"
  for f in "$REF"/lib/libxgboost.so.*; do
    [ -f "$f" ] && [ ! -L "$f" ] && mv "$f" "$BUILD/lib/"
  done
  rm -rf "$REF/lib"
  ln -sf "$(ls "$BUILD"/lib/libxgboost.so.* | head -1)" "$BUILD/lib/libxgboost.so"
fi

# shadow python package: symlink every package file, add a real lib/ with
# the shared library where libpath.py looks first
rm -rf "$PKG"
mkdir -p "$PKG/xgboost/lib"
for f in "$REF"/python-package/xgboost/* ; do
  ln -s "$f" "$PKG/xgboost/$(basename "$f")"
done
ln -s "$BUILD/lib/libxgboost.so" "$PKG/xgboost/lib/libxgboost.so"

PYTHONPATH="$PKG" python - <<'EOF'
import xgboost, numpy as np
print("oracle xgboost", xgboost.__version__, "at", xgboost.__file__)
X = np.random.default_rng(0).normal(size=(100, 4))
y = (X[:, 0] > 0).astype(float)
bst = xgboost.train({"objective": "binary:logistic", "max_depth": 3,
                     "verbosity": 0}, xgboost.DMatrix(X, label=y), 5)
p = bst.predict(xgboost.DMatrix(X))
assert p.shape == (100,) and np.isfinite(p).all()
print("oracle smoke train/predict OK")
EOF
echo "oracle ready: PYTHONPATH=$PKG"
