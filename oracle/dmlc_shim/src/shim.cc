/* Out-of-line pieces of the dmlc shim (oracle build): local-file Stream,
 * and a LIBSVM text parser behind Parser<uint32_t>::Create.
 */
#include <dmlc/data.h>
#include <dmlc/io.h>
#include <dmlc/logging.h>

#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <memory>
#include <string>
#include <vector>

namespace dmlc {

namespace {

class LocalFileStream : public SeekStream {
 public:
  LocalFileStream(const char* path, const char* flag) {
    std::string mode;
    for (const char* f = flag; *f; ++f) {
      if (*f == 'r' || *f == 'w' || *f == 'a' || *f == '+') mode += *f;
    }
    mode += 'b';
    fp_ = std::fopen(path, mode.c_str());
  }
  ~LocalFileStream() override {
    if (fp_) std::fclose(fp_);
  }
  bool ok() const { return fp_ != nullptr; }
  size_t Read(void* ptr, size_t size) override {
    return std::fread(ptr, 1, size, fp_);
  }
  size_t Write(const void* ptr, size_t size) override {
    size_t n = std::fwrite(ptr, 1, size, fp_);
    CHECK_EQ(n, size) << "short write";
    return n;
  }
  void Seek(size_t pos) override {
    std::fseek(fp_, static_cast<long>(pos), SEEK_SET);  // NOLINT
  }
  size_t Tell() override { return static_cast<size_t>(std::ftell(fp_)); }

 private:
  std::FILE* fp_{nullptr};
};

std::string StripProtocol(const char* uri) {
  io::URI parsed(uri);
  CHECK(parsed.protocol.empty() || parsed.protocol == "file://")
      << "dmlc shim Stream only supports local files, got: " << uri;
  return parsed.protocol.empty() ? parsed.name : parsed.host + parsed.name;
}

}  // namespace

Stream* Stream::Create(const char* uri, const char* flag, bool allow_null) {
  auto path = StripProtocol(uri);
  auto fs = std::make_unique<LocalFileStream>(path.c_str(), flag);
  if (!fs->ok()) {
    if (allow_null) return nullptr;
    LOG(FATAL) << "Failed to open \"" << path << "\" with flag " << flag;
  }
  return fs.release();
}

SeekStream* SeekStream::CreateForRead(const char* uri, bool allow_null) {
  auto path = StripProtocol(uri);
  auto fs = std::make_unique<LocalFileStream>(path.c_str(), "r");
  if (!fs->ok()) {
    if (allow_null) return nullptr;
    LOG(FATAL) << "Failed to open \"" << path << "\" for read";
  }
  return fs.release();
}

namespace {

/* LIBSVM text parser: "label [qid:q] idx:val idx:val ...".  Single batch of
 * the whole (partition of the) file — the reference's FileAdapter streams
 * whatever batch granularity the parser provides.
 */
class LibSVMParser : public Parser<uint32_t, real_t> {
 public:
  LibSVMParser(const std::string& path, unsigned part_index,
               unsigned num_parts)
      : path_(path), part_(part_index), nparts_(num_parts) {}

  void BeforeFirst() override { at_end_ = false; }

  bool Next() override {
    if (at_end_) return false;
    Load();
    at_end_ = true;
    return block_.size > 0;
  }

  const RowBlock<uint32_t, real_t>& Value() const override { return block_; }
  size_t BytesRead() const override { return bytes_read_; }

 private:
  void Load() {
    if (loaded_) {
      FillBlock();
      return;
    }
    std::ifstream ifs(path_);
    CHECK(ifs) << "Failed to open " << path_;
    // partition by line count: part k takes lines with (line % nparts) == k
    std::string line;
    size_t lineno = 0;
    offset_.push_back(0);
    while (std::getline(ifs, line)) {
      bytes_read_ += line.size() + 1;
      size_t ln = lineno++;
      if (nparts_ > 1 && (ln % nparts_) != part_) continue;
      const char* p = line.c_str();
      char* end = nullptr;
      // skip blank / comment lines
      while (*p == ' ' || *p == '\t') ++p;
      if (*p == '\0' || *p == '#') continue;
      float lbl = std::strtof(p, &end);
      CHECK_NE(p, end) << "Malformed libsvm line: " << line;
      p = end;
      label_.push_back(lbl);
      while (true) {
        while (*p == ' ' || *p == '\t') ++p;
        if (*p == '\0' || *p == '#') break;
        if (std::strncmp(p, "qid:", 4) == 0) {
          p += 4;
          qid_.push_back(std::strtoull(p, &end, 10));
          p = end;
          continue;
        }
        char* colon = nullptr;
        unsigned long idx = std::strtoul(p, &colon, 10);  // NOLINT
        CHECK(colon && *colon == ':') << "Malformed libsvm pair in: " << line;
        p = colon + 1;
        float val = std::strtof(p, &end);
        p = end;
        index_.push_back(static_cast<uint32_t>(idx));
        value_.push_back(val);
      }
      offset_.push_back(index_.size());
    }
    loaded_ = true;
    FillBlock();
  }

  void FillBlock() {
    block_.size = label_.size();
    block_.offset = offset_.data();
    block_.label = label_.data();
    block_.weight = nullptr;
    block_.qid = qid_.size() == label_.size() ? qid_.data() : nullptr;
    block_.index = index_.data();
    block_.value = value_.data();
  }

  std::string path_;
  unsigned part_, nparts_;
  bool at_end_{false}, loaded_{false};
  size_t bytes_read_{0};
  std::vector<size_t> offset_;
  std::vector<float> label_, value_;
  std::vector<uint64_t> qid_;
  std::vector<uint32_t> index_;
  RowBlock<uint32_t, real_t> block_;
};

std::string StripFormatArgs(const std::string& uri) {
  // dmlc URIs may carry "?format=libsvm&..." suffixes
  return uri.substr(0, uri.find('?'));
}

}  // namespace

template <>
Parser<uint32_t, real_t>* Parser<uint32_t, real_t>::Create(
    const char* uri, unsigned part_index, unsigned num_parts,
    const char* type) {
  std::string t(type);
  CHECK(t == "auto" || t == "libsvm")
      << "dmlc shim parser supports libsvm only, got: " << t;
  auto path = StripProtocol(StripFormatArgs(uri).c_str());
  return new LibSVMParser(path, part_index, num_parts);
}

}  // namespace dmlc
