# find_package(dmlc) config for the shim: builds the shim sources into a
# static lib and exposes it as target `dmlc` (the reference's CMakeLists
# links `dmlc` directly when BUILD_WITH_SYSTEM_DMLC=ON).
if(TARGET dmlc)
  return()
endif()

get_filename_component(_dmlc_shim_root "${CMAKE_CURRENT_LIST_DIR}/.." ABSOLUTE)

add_library(dmlc STATIC "${_dmlc_shim_root}/src/shim.cc")
target_include_directories(dmlc PUBLIC "${_dmlc_shim_root}/include")
target_compile_features(dmlc PUBLIC cxx_std_14)
set_property(TARGET dmlc PROPERTY POSITION_INDEPENDENT_CODE ON)

set(dmlc_FOUND TRUE)
set(dmlc-LIBRARIES dmlc)
set(dmlc_LIBRARIES dmlc)
set(dmlc_INCLUDE_DIRS "${_dmlc_shim_root}/include")
