/* Endianness helpers (dmlc shim for the oracle build). */
#ifndef DMLC_ENDIAN_H_
#define DMLC_ENDIAN_H_

#include <cstddef>
#include <cstdint>
#include <cstring>

#include "./base.h"

#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
#define DMLC_LITTLE_ENDIAN 1
#else
#define DMLC_LITTLE_ENDIAN 0
#endif

/*! \brief whether serialization can skip endian swap (little-endian host) */
#define DMLC_IO_NO_ENDIAN_SWAP DMLC_LITTLE_ENDIAN

namespace dmlc {

/*! \brief in-place byte swap of n elements of size elem_bytes */
inline void ByteSwap(void* data, size_t elem_bytes, size_t num_elems) {
  auto* d = static_cast<unsigned char*>(data);
  for (size_t i = 0; i < num_elems; ++i) {
    for (size_t j = 0; j < elem_bytes / 2; ++j) {
      unsigned char t = d[i * elem_bytes + j];
      d[i * elem_bytes + j] = d[i * elem_bytes + elem_bytes - 1 - j];
      d[i * elem_bytes + elem_bytes - 1 - j] = t;
    }
  }
}

/*! \brief value byte swap */
template <typename T>
inline T ByteSwap(T v) {
  T ret = v;
  ByteSwap(&ret, sizeof(T), 1);
  return ret;
}

}  // namespace dmlc

#endif  // DMLC_ENDIAN_H_
