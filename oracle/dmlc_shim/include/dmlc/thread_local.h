/* Thread-local singleton store (dmlc shim for the oracle build). */
#ifndef DMLC_THREAD_LOCAL_H_
#define DMLC_THREAD_LOCAL_H_

namespace dmlc {

template <typename T>
class ThreadLocalStore {
 public:
  static T* Get() {
    static thread_local T inst;
    return &inst;
  }
};

}  // namespace dmlc

#endif  // DMLC_THREAD_LOCAL_H_
