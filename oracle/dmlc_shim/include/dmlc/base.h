/* Minimal from-scratch reimplementation of the dmlc-core public API surface
 * that the reference xgboost sources compile against.  Written for the
 * oracle build only (the reference repo ships an empty dmlc-core submodule
 * and this environment has no network access).  Covers exactly the symbols
 * the reference uses — see oracle/README.md for the inventory.
 */
#ifndef DMLC_BASE_H_
#define DMLC_BASE_H_

#include <cassert>  // transitively expected by reference headers via dmlc
#include <chrono>   // (ditto)
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#ifndef DMLC_USE_CXX11
#define DMLC_USE_CXX11 1
#endif

#if defined(__GNUC__) || defined(__clang__)
#define DMLC_ATTRIBUTE_UNUSED __attribute__((unused))
#else
#define DMLC_ATTRIBUTE_UNUSED
#endif

#ifndef DMLC_CXX11_THREAD_LOCAL
#define DMLC_CXX11_THREAD_LOCAL 1
#endif

#ifndef DMLC_LOG_FATAL_THROW
#define DMLC_LOG_FATAL_THROW 1
#endif

#define DMLC_STRINGIZE_DETAIL(x) #x
#define DMLC_STRINGIZE(x) DMLC_STRINGIZE_DETAIL(x)

/* Type-trait declaration used by parameter/serializer machinery. */
#define DMLC_DECLARE_TRAITS(Trait, Type, Value)            \
  template <>                                              \
  struct Trait<Type> {                                     \
    static const bool value = Value;                       \
  }

#include <type_traits>

namespace dmlc {

using index_t = unsigned;
using real_t = float;

/*! \brief POD trait, specializable via DMLC_DECLARE_TRAITS */
template <typename T>
struct is_pod {
  static const bool value =
      std::is_trivial<T>::value && std::is_standard_layout<T>::value;
};

/*! \brief safe data-pointer access for possibly-empty containers */
template <typename T>
inline T* BeginPtr(std::vector<T>& vec) {  // NOLINT
  return vec.empty() ? nullptr : &vec[0];
}
template <typename T>
inline const T* BeginPtr(const std::vector<T>& vec) {
  return vec.empty() ? nullptr : &vec[0];
}
inline char* BeginPtr(std::string& str) {  // NOLINT
  return str.empty() ? nullptr : &str[0];
}
inline const char* BeginPtr(const std::string& str) {
  return str.empty() ? nullptr : &str[0];
}

}  // namespace dmlc

#endif  // DMLC_BASE_H_
