/* Stream abstraction (dmlc shim for the oracle build): binary Stream with
 * templated Read/Write of PODs / strings / vectors, SeekStream, local-file
 * Stream::Create, std::istream/ostream adapters, and io::URI parsing.
 */
#ifndef DMLC_IO_H_
#define DMLC_IO_H_

#include <cstdint>
#include <cstring>
#include <istream>
#include <map>
#include <ostream>
#include <streambuf>
#include <string>
#include <type_traits>
#include <utility>
#include <vector>

#include "./base.h"
#include "./logging.h"

namespace dmlc {

class Stream {
 public:
  virtual ~Stream() = default;
  /*! \brief read up to size bytes, returns bytes actually read */
  virtual size_t Read(void* ptr, size_t size) = 0;
  /*! \brief write size bytes, returns bytes written */
  virtual size_t Write(const void* ptr, size_t size) = 0;

  /*! \brief open a stream for a local path ("r"/"w"/"a"; binary always) */
  static Stream* Create(const char* uri, const char* flag,
                        bool allow_null = false);

  // ---- typed helpers (serializer) ----
  template <typename T>
  inline void Write(const T& data);
  template <typename T>
  inline bool Read(T* out_data);

  /*! \brief write raw little-endian array */
  template <typename T>
  inline void WriteArray(const T* data, size_t num) {
    Write(static_cast<const void*>(data), sizeof(T) * num);
  }
  template <typename T>
  inline bool ReadArray(T* data, size_t num) {
    return Read(static_cast<void*>(data), sizeof(T) * num) ==
           sizeof(T) * num;
  }
};

/*! \brief seekable stream */
class SeekStream : public Stream {
 public:
  ~SeekStream() override = default;
  virtual void Seek(size_t pos) = 0;
  virtual size_t Tell() = 0;
  static SeekStream* CreateForRead(const char* uri, bool allow_null = false);
};

/*! \brief interface of objects that can serialize themselves */
class Serializable {
 public:
  virtual ~Serializable() = default;
  virtual void Save(Stream* fo) const = 0;
  virtual void Load(Stream* fi) = 0;
};

namespace serializer {

template <typename T, typename Enable = void>
struct Handler;

/* PODs: raw bytes */
template <typename T>
struct Handler<T, std::enable_if_t<std::is_trivially_copyable<T>::value &&
                                   !std::is_pointer<T>::value>> {
  static void Write(Stream* strm, const T& data) {
    strm->Write(&data, sizeof(T));
  }
  static bool Read(Stream* strm, T* data) {
    return strm->Read(data, sizeof(T)) == sizeof(T);
  }
};

template <>
struct Handler<std::string, void> {
  static void Write(Stream* strm, const std::string& data) {
    uint64_t sz = data.length();
    strm->Write(&sz, sizeof(sz));
    if (sz) strm->Write(data.data(), sz);
  }
  static bool Read(Stream* strm, std::string* data) {
    uint64_t sz;
    if (strm->Read(&sz, sizeof(sz)) != sizeof(sz)) return false;
    data->resize(sz);
    return sz == 0 || strm->Read(&(*data)[0], sz) == sz;
  }
};

template <typename T>
struct Handler<std::vector<T>,
               std::enable_if_t<std::is_trivially_copyable<T>::value>> {
  static void Write(Stream* strm, const std::vector<T>& data) {
    uint64_t sz = data.size();
    strm->Write(&sz, sizeof(sz));
    if (sz) strm->Write(data.data(), sz * sizeof(T));
  }
  static bool Read(Stream* strm, std::vector<T>* data) {
    uint64_t sz;
    if (strm->Read(&sz, sizeof(sz)) != sizeof(sz)) return false;
    data->resize(sz);
    return sz == 0 ||
           strm->Read(data->data(), sz * sizeof(T)) == sz * sizeof(T);
  }
};

template <typename T>
struct Handler<std::vector<T>,
               std::enable_if_t<!std::is_trivially_copyable<T>::value>> {
  static void Write(Stream* strm, const std::vector<T>& data) {
    uint64_t sz = data.size();
    strm->Write(&sz, sizeof(sz));
    for (const auto& v : data) Handler<T>::Write(strm, v);
  }
  static bool Read(Stream* strm, std::vector<T>* data) {
    uint64_t sz;
    if (strm->Read(&sz, sizeof(sz)) != sizeof(sz)) return false;
    data->resize(sz);
    for (auto& v : *data) {
      if (!Handler<T>::Read(strm, &v)) return false;
    }
    return true;
  }
};

template <typename K, typename V>
struct Handler<std::pair<K, V>, void> {
  static void Write(Stream* strm, const std::pair<K, V>& data) {
    Handler<K>::Write(strm, data.first);
    Handler<V>::Write(strm, data.second);
  }
  static bool Read(Stream* strm, std::pair<K, V>* data) {
    return Handler<K>::Read(strm, &data->first) &&
           Handler<V>::Read(strm, &data->second);
  }
};

template <typename K, typename V>
struct Handler<std::map<K, V>, void> {
  static void Write(Stream* strm, const std::map<K, V>& data) {
    uint64_t sz = data.size();
    strm->Write(&sz, sizeof(sz));
    for (const auto& kv : data) {
      Handler<K>::Write(strm, kv.first);
      Handler<V>::Write(strm, kv.second);
    }
  }
  static bool Read(Stream* strm, std::map<K, V>* data) {
    uint64_t sz;
    if (strm->Read(&sz, sizeof(sz)) != sizeof(sz)) return false;
    data->clear();
    for (uint64_t i = 0; i < sz; ++i) {
      std::pair<K, V> kv;
      if (!Handler<K>::Read(strm, &kv.first)) return false;
      if (!Handler<V>::Read(strm, &kv.second)) return false;
      data->emplace(std::move(kv));
    }
    return true;
  }
};

}  // namespace serializer

template <typename T>
inline void Stream::Write(const T& data) {
  serializer::Handler<T>::Write(this, data);
}
template <typename T>
inline bool Stream::Read(T* out_data) {
  return serializer::Handler<T>::Read(this, out_data);
}

// ---- std::iostream adapters over Stream ----
namespace io {

/*! \brief minimal URI parse: [protocol://][host]/path */
struct URI {
  std::string protocol;
  std::string host;
  std::string name;
  URI() = default;
  explicit URI(const char* uri) {
    const char* p = std::strstr(uri, "://");
    if (p == nullptr) {
      name = uri;
    } else {
      protocol = std::string(uri, p - uri + 3);
      const char* h = p + 3;
      const char* path = std::strchr(h, '/');
      if (path == nullptr) {
        host = h;
      } else {
        host = std::string(h, path - h);
        name = path;
      }
    }
  }
  std::string str() const { return protocol + host + name; }
};

class StreamBufAdapter : public std::streambuf {
 public:
  explicit StreamBufAdapter(Stream* stream) : stream_(stream) {}

 protected:
  int_type underflow() override {
    size_t n = stream_->Read(buffer_, sizeof(buffer_));
    if (n == 0) return traits_type::eof();
    setg(buffer_, buffer_, buffer_ + n);
    return traits_type::to_int_type(*gptr());
  }
  int_type overflow(int_type c) override {
    if (c != traits_type::eof()) {
      char ch = traits_type::to_char_type(c);
      stream_->Write(&ch, 1);
    }
    return c;
  }
  std::streamsize xsputn(const char* s, std::streamsize n) override {
    stream_->Write(s, n);
    return n;
  }

 private:
  Stream* stream_;
  char buffer_[4096];
};

}  // namespace io

/*! \brief std::istream reading from a dmlc::Stream */
class istream : public std::basic_istream<char> {  // NOLINT
 public:
  explicit istream(Stream* stream, size_t buf_size = 4096)
      : std::basic_istream<char>(nullptr), buf_(stream) {
    (void)buf_size;
    this->rdbuf(&buf_);
  }

 private:
  io::StreamBufAdapter buf_;
};

/*! \brief std::ostream writing to a dmlc::Stream */
class ostream : public std::basic_ostream<char> {  // NOLINT
 public:
  explicit ostream(Stream* stream, size_t buf_size = 4096)
      : std::basic_ostream<char>(nullptr), buf_(stream) {
    (void)buf_size;
    this->rdbuf(&buf_);
  }

 private:
  io::StreamBufAdapter buf_;
};

}  // namespace dmlc

#endif  // DMLC_IO_H_
