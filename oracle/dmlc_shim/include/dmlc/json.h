/* Minimal JSON reader (dmlc shim for the oracle build).  The reference uses
 * dmlc::JSONReader once, to parse graphviz kwargs of shape
 * map<string, map<string, string>> (tree_model.cc GraphvizGenerator).
 * Values may be strings, numbers, or booleans; all are surfaced as strings.
 */
#ifndef DMLC_JSON_H_
#define DMLC_JSON_H_

#include <cctype>
#include <istream>
#include <map>
#include <sstream>
#include <string>

#include "./logging.h"

namespace dmlc {

class JSONReader {
 public:
  explicit JSONReader(std::istream* is) : is_(is) {}

  template <typename T>
  void Read(T* out) {
    ReadValue(out);
  }

 private:
  std::istream* is_;

  int PeekNonSpace() {
    int c = is_->peek();
    while (c != EOF && std::isspace(c)) {
      is_->get();
      c = is_->peek();
    }
    return c;
  }
  void Expect(char want) {
    int c = PeekNonSpace();
    if (c != want) {
      throw Error(std::string("JSON parse error: expected '") + want + "'");
    }
    is_->get();
  }

  void ReadValue(std::string* out) {
    int c = PeekNonSpace();
    if (c == '"') {
      is_->get();
      std::ostringstream os;
      while ((c = is_->get()) != EOF && c != '"') {
        if (c == '\\') {
          int e = is_->get();
          switch (e) {
            case 'n': os << '\n'; break;
            case 't': os << '\t'; break;
            case '"': os << '"'; break;
            case '\\': os << '\\'; break;
            default: os << static_cast<char>(e);
          }
        } else {
          os << static_cast<char>(c);
        }
      }
      *out = os.str();
    } else {  // bare token: number / true / false / null
      std::ostringstream os;
      while ((c = is_->peek()) != EOF && c != ',' && c != '}' && c != ']' &&
             !std::isspace(c)) {
        os << static_cast<char>(is_->get());
      }
      *out = os.str();
    }
  }

  template <typename V>
  void ReadValue(std::map<std::string, V>* out) {
    out->clear();
    Expect('{');
    if (PeekNonSpace() == '}') {
      is_->get();
      return;
    }
    while (true) {
      std::string key;
      ReadValue(&key);
      Expect(':');
      V val;
      ReadValue(&val);
      (*out)[key] = val;
      int c = PeekNonSpace();
      if (c == ',') {
        is_->get();
        continue;
      }
      Expect('}');
      break;
    }
  }
};

}  // namespace dmlc

#endif  // DMLC_JSON_H_
