/* Common utilities (dmlc shim for the oracle build): OMPException collects
 * exceptions thrown inside OpenMP regions and rethrows them on the host
 * thread, plus a string splitter.
 */
#ifndef DMLC_COMMON_H_
#define DMLC_COMMON_H_

#include <exception>
#include <mutex>
#include <sstream>
#include <string>
#include <utility>
#include <vector>

#include "./logging.h"

namespace dmlc {

inline std::vector<std::string> Split(const std::string& s, char delim) {
  std::vector<std::string> ret;
  std::string item;
  std::istringstream is(s);
  while (std::getline(is, item, delim)) {
    ret.push_back(item);
  }
  return ret;
}

/*! \brief exception trampoline across OpenMP parallel regions */
class OMPException {
 public:
  template <typename Function, typename... Parameters>
  void Run(Function f, Parameters... params) {
    try {
      f(params...);
    } catch (std::exception&) {  // covers dmlc::Error (: runtime_error)
      std::lock_guard<std::mutex> lock(mutex_);
      if (!caught_) {
        caught_ = std::current_exception();
      }
    }
  }

  void Rethrow() {
    if (caught_) {
      std::exception_ptr ex = caught_;
      caught_ = nullptr;
      std::rethrow_exception(ex);
    }
  }

 private:
  std::exception_ptr caught_{nullptr};
  std::mutex mutex_;
};

}  // namespace dmlc

#endif  // DMLC_COMMON_H_
