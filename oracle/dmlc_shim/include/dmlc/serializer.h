/* serializer handlers live in io.h in this shim (dmlc shim, oracle build) */
#ifndef DMLC_SERIALIZER_H_
#define DMLC_SERIALIZER_H_
#include "./io.h"
#endif  // DMLC_SERIALIZER_H_
