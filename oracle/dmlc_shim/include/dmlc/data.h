/* Data iteration / text parsing (dmlc shim for the oracle build).
 * Provides DataIter, RowBlock, and a Parser with a functional LIBSVM text
 * parser behind Parser<uint32_t>::Create (format "auto"/"libsvm").
 */
#ifndef DMLC_DATA_H_
#define DMLC_DATA_H_

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "./base.h"
#include "./logging.h"

namespace dmlc {

/*! \brief pull-style data iterator */
template <typename DType>
class DataIter {
 public:
  virtual ~DataIter() = default;
  virtual void BeforeFirst() = 0;
  virtual bool Next() = 0;
  virtual const DType& Value() const = 0;
};

/*! \brief one CSR batch of parsed rows */
template <typename IndexType, typename DType = real_t>
struct RowBlock {
  size_t size{0};
  const size_t* offset{nullptr};
  const DType* label{nullptr};
  const DType* weight{nullptr};
  const uint64_t* qid{nullptr};
  const IndexType* field{nullptr};
  const IndexType* index{nullptr};
  const DType* value{nullptr};
};

/*! \brief text data parser; Create opens a local libsvm file */
template <typename IndexType, typename DType = real_t>
class Parser : public DataIter<RowBlock<IndexType, DType>> {
 public:
  ~Parser() override = default;
  /*! \brief bytes consumed so far (progress reporting) */
  virtual size_t BytesRead() const = 0;
  static Parser<IndexType, DType>* Create(const char* uri, unsigned part_index,
                                          unsigned num_parts,
                                          const char* type);
};

}  // namespace dmlc

#endif  // DMLC_DATA_H_
