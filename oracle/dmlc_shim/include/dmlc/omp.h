/* OpenMP helpers (dmlc shim for the oracle build). */
#ifndef DMLC_OMP_H_
#define DMLC_OMP_H_

#if defined(_OPENMP)
#include <omp.h>
#else
inline int omp_get_thread_num() { return 0; }
inline int omp_get_num_threads() { return 1; }
inline int omp_get_max_threads() { return 1; }
inline int omp_get_num_procs() { return 1; }
inline int omp_in_parallel() { return 0; }
inline void omp_set_num_threads(int) {}
#endif

namespace dmlc {
/* loop index types for OpenMP-parallel loops */
using omp_uint = unsigned;
using omp_ulong = unsigned long;  // NOLINT
}  // namespace dmlc

#endif  // DMLC_OMP_H_
