/* Wall-clock timer (dmlc shim for the oracle build). */
#ifndef DMLC_TIMER_H_
#define DMLC_TIMER_H_

#include <chrono>

namespace dmlc {

inline double GetTime() {
  return std::chrono::duration<double>(
             std::chrono::high_resolution_clock::now().time_since_epoch())
      .count();
}

}  // namespace dmlc

#endif  // DMLC_TIMER_H_
