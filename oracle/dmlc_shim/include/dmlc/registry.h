/* Global factory registry (dmlc shim for the oracle build). */
#ifndef DMLC_REGISTRY_H_
#define DMLC_REGISTRY_H_

#include <map>
#include <string>
#include <utility>
#include <vector>

#include "./base.h"
#include "./logging.h"

namespace dmlc {

template <typename EntryType>
class Registry {
 public:
  /*! \brief singleton, defined by DMLC_REGISTRY_ENABLE in one TU */
  static Registry* Get();

  static const std::vector<const EntryType*>& List() { return Get()->list_; }

  static std::vector<std::string> ListAllNames() {
    auto& fmap = Get()->fmap_;
    std::vector<std::string> names;
    names.reserve(fmap.size());
    for (const auto& kv : fmap) names.push_back(kv.first);
    return names;
  }

  static const EntryType* Find(const std::string& name) {
    auto& fmap = Get()->fmap_;
    auto it = fmap.find(name);
    return it == fmap.end() ? nullptr : it->second;
  }

  inline EntryType& AddAlias(const std::string& key_name,
                             const std::string& alias) {
    EntryType* e = fmap_.at(key_name);
    if (fmap_.count(alias)) {
      CHECK_EQ(e, fmap_.at(alias)) << "Trying to register alias " << alias
                                   << " for key " << key_name
                                   << " but " << alias << " is taken";
    } else {
      fmap_[alias] = e;
    }
    return *e;
  }

  inline EntryType& __REGISTER__(const std::string& name) {  // NOLINT
    CHECK_EQ(fmap_.count(name), 0U) << name << " already registered";
    auto* e = new EntryType();
    e->name = name;
    fmap_[name] = e;
    list_.push_back(e);
    return *e;
  }

  inline EntryType& __REGISTER_OR_GET__(const std::string& name) {  // NOLINT
    auto it = fmap_.find(name);
    if (it != fmap_.end()) return *it->second;
    return __REGISTER__(name);
  }

  ~Registry() {
    for (auto* e : list_) delete e;
  }

 private:
  std::vector<const EntryType*> list_;
  std::map<std::string, EntryType*> fmap_;
};

/*!
 * \brief base class of a registry entry carrying a factory function.
 *  EntryType uses CRTP; FunctionType is the factory signature.
 */
template <typename EntryType, typename FunctionType>
class FunctionRegEntryBase {
 public:
  std::string name;
  std::string description;
  std::vector<std::pair<std::string, std::string>> arguments;
  FunctionType body;
  std::string return_type;

  inline EntryType& set_body(FunctionType body_) {
    this->body = body_;
    return this->self();
  }
  inline EntryType& describe(const std::string& d) {
    this->description = d;
    return this->self();
  }
  inline EntryType& add_argument(const std::string& arg_name,
                                 const std::string& type,
                                 const std::string& d) {
    arguments.emplace_back(arg_name, type + " — " + d);
    return this->self();
  }
  inline EntryType& add_arguments(
      const std::vector<std::pair<std::string, std::string>>& args) {
    arguments.insert(arguments.end(), args.begin(), args.end());
    return this->self();
  }
  inline EntryType& set_return_type(const std::string& t) {
    return_type = t;
    return this->self();
  }

 protected:
  inline EntryType& self() { return *static_cast<EntryType*>(this); }
};

}  // namespace dmlc

/*! \brief instantiate the registry singleton for EntryType (one TU) */
#define DMLC_REGISTRY_ENABLE(EntryType)                   \
  template <>                                             \
  ::dmlc::Registry<EntryType>* ::dmlc::Registry<EntryType>::Get() { \
    static ::dmlc::Registry<EntryType> inst;              \
    return &inst;                                         \
  }

#define DMLC_REGISTRY_REGISTER(EntryType, EntryTypeName, Name)         \
  static DMLC_ATTRIBUTE_UNUSED EntryType& __make_##EntryTypeName##_##Name##__ = \
      ::dmlc::Registry<EntryType>::Get()->__REGISTER__(#Name)

/* file tags: in full dmlc-core these force linkage of registration TUs when
 * static-linking; a shared-library build keeps all TUs, so they are no-ops
 * beyond declaring/calling a dummy symbol. */
#define DMLC_REGISTRY_FILE_TAG(UniqueTag) \
  int __dmlc_registry_file_tag_##UniqueTag##__() { return 0; }

#define DMLC_REGISTRY_LINK_TAG(UniqueTag)                          \
  int __dmlc_registry_file_tag_##UniqueTag##__();                  \
  static int DMLC_ATTRIBUTE_UNUSED __reg_file_tag_##UniqueTag##__ = \
      __dmlc_registry_file_tag_##UniqueTag##__()

#endif  // DMLC_REGISTRY_H_
