/* Parameter reflection DSL (dmlc shim for the oracle build).
 *
 * Provides the dmlc::Parameter<T> CRTP base plus the DMLC_DECLARE_PARAMETER /
 * DMLC_DECLARE_FIELD / DMLC_DECLARE_ALIAS / DMLC_REGISTER_PARAMETER macros,
 * with the exact protected FieldEntry surface the reference's
 * include/xgboost/parameter.h enum-class specialization subclasses
 * (is_enum_, default_value_, has_default_, Set, add_enum, Init).
 *
 * Field access works through byte offsets from the declaring instance, so a
 * manager built once per parameter type can set fields on any instance.
 */
#ifndef DMLC_PARAMETER_H_
#define DMLC_PARAMETER_H_

#include <algorithm>
#include <cmath>
#include <cstddef>
#include <cstdint>
#include <iostream>
#include <limits>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <type_traits>
#include <utility>
#include <vector>

#include "./base.h"
#include "./logging.h"

namespace dmlc {

struct ParamError : public Error {
  explicit ParamError(const std::string& s) : Error(s) {}
};

/*! \brief field metadata for help/dump */
struct ParamFieldInfo {
  std::string name;
  std::string type;
  std::string type_info_str;
  std::string description;
};

namespace parameter {

/*! \brief polymorphic accessor for one declared field */
class FieldAccessEntry {
 public:
  virtual ~FieldAccessEntry() = default;
  /*! \brief set field on instance at head from string */
  virtual void Set(void* head, const std::string& value) const = 0;
  /*! \brief read field on instance at head as string */
  virtual std::string GetStringValue(const void* head) const = 0;
  /*! \brief set field to its default; throw if it has none */
  virtual void SetDefault(void* head) const = 0;
  virtual ParamFieldInfo GetFieldInfo() const = 0;

  bool has_default_{false};
  std::string key_;
  std::string description_;
};

class ParamManager;

/*! \brief typed field entry (generic arithmetic / string) */
template <typename TEntry, typename DType>
class FieldEntryBase : public FieldAccessEntry {
 public:
  void Set(void* head, const std::string& value) const override {
    std::istringstream is(value);
    DType tmp;
    if (!(is >> tmp)) {
      throw ParamError("Invalid value \"" + value + "\" for parameter \"" +
                       key_ + "\"");
    }
    this->self().Check(tmp);
    this->Ref(head) = tmp;
  }
  std::string GetStringValue(const void* head) const override {
    std::ostringstream os;
    os << this->CRef(head);
    return os.str();
  }
  void SetDefault(void* head) const override {
    if (!has_default_) {
      throw ParamError("Required parameter \"" + key_ + "\" is not set");
    }
    this->Ref(head) = default_value_;
  }
  ParamFieldInfo GetFieldInfo() const override {
    ParamFieldInfo info;
    info.name = key_;
    info.type = "param";
    info.description = description_;
    return info;
  }

  TEntry& set_default(const DType& v) {
    default_value_ = v;
    has_default_ = true;
    return this->self_mut();
  }
  TEntry& describe(const std::string& d) {
    description_ = d;
    return this->self_mut();
  }
  void Init(const std::string& key, void* head, DType& ref) {  // NOLINT
    key_ = key;
    offset_ = reinterpret_cast<char*>(&ref) - reinterpret_cast<char*>(head);
  }
  void Check(const DType&) const {}

 protected:
  DType& Ref(void* head) const {
    return *reinterpret_cast<DType*>(static_cast<char*>(head) + offset_);
  }
  const DType& CRef(const void* head) const {
    return *reinterpret_cast<const DType*>(
        static_cast<const char*>(head) + offset_);
  }
  const TEntry& self() const { return *static_cast<const TEntry*>(this); }
  TEntry& self_mut() { return *static_cast<TEntry*>(this); }

  ptrdiff_t offset_{0};
  DType default_value_{};
};

/*! \brief arithmetic entry: adds range checking */
template <typename TEntry, typename DType>
class FieldEntryNumeric : public FieldEntryBase<TEntry, DType> {
 public:
  TEntry& set_lower_bound(DType v) {
    lower_ = v;
    has_lower_ = true;
    return this->self_mut();
  }
  TEntry& set_upper_bound(DType v) {
    upper_ = v;
    has_upper_ = true;
    return this->self_mut();
  }
  TEntry& set_range(DType lo, DType hi) {
    set_lower_bound(lo);
    return set_upper_bound(hi);
  }
  void Check(const DType& v) const {
    if ((has_lower_ && v < lower_) || (has_upper_ && v > upper_)) {
      std::ostringstream os;
      os << "value " << v << " for parameter \"" << this->key_
         << "\" exceeds bound [";
      if (has_lower_) os << lower_; else os << "-inf";
      os << ", ";
      if (has_upper_) os << upper_; else os << "inf";
      os << "]";
      throw ParamError(os.str());
    }
  }
  void Set(void* head, const std::string& value) const override {
    DType cast{};
    std::istringstream is(value);
    is >> cast;
    if (is.fail() || !is.eof()) {
      // fallback accepts "1e3"-style for integral fields; long double keeps
      // 64-bit integers (e.g. a SIZE_MAX default) exact through the round trip
      std::istringstream is2(value);
      long double tmp;
      if (!(is2 >> tmp) ||
          (std::is_integral<DType>::value && tmp != std::floor(tmp))) {
        // reject "6.5" for an int field, like real dmlc; the fallback only
        // admits integral-valued scientific notation ("1e3")
        throw ParamError("Invalid value \"" + value + "\" for parameter \"" +
                         this->key_ + "\"");
      }
      cast = static_cast<DType>(tmp);
    }
    this->Check(cast);
    this->Ref(head) = cast;
  }

 protected:
  bool has_lower_{false}, has_upper_{false};
  DType lower_{}, upper_{};
};

/* generic entry: any type with istream>>/ostream<< operators (e.g. the
 * reference's ParamArray fields) */
template <typename DType, typename Enable = void>
class FieldEntry : public FieldEntryBase<FieldEntry<DType, Enable>, DType> {};

template <typename DType>
class FieldEntry<DType,
                 std::enable_if_t<std::is_arithmetic<DType>::value &&
                                  !std::is_same<DType, bool>::value>>
    : public FieldEntryNumeric<FieldEntry<DType>, DType> {};

/*! \brief int entry with optional enum-string mapping (subclassed by the
 *  reference's DECLARE_FIELD_ENUM_CLASS) */
template <>
class FieldEntry<int, void> : public FieldEntryNumeric<FieldEntry<int>, int> {
 public:
  FieldEntry<int>& add_enum(const std::string& key, int value) {
    enum_map_[key] = value;
    enum_back_[value] = key;
    is_enum_ = true;
    return *this;
  }
  void Set(void* head, const std::string& value) const override {
    if (is_enum_) {
      // strings only, rejected before any mutation (real dmlc rejects raw
      // numerics for enum fields too)
      auto it = enum_map_.find(value);
      if (it == enum_map_.end()) {
        std::ostringstream os;
        os << "Invalid value \"" << value << "\" for parameter \""
           << this->key_ << "\". Valid values: {";
        for (const auto& kv : enum_map_) os << kv.first << ", ";
        os << "}";
        throw ParamError(os.str());
      }
      this->Ref(head) = it->second;
      return;
    }
    FieldEntryNumeric<FieldEntry<int>, int>::Set(head, value);
  }
  std::string GetStringValue(const void* head) const override {
    if (is_enum_) {
      auto it = enum_back_.find(this->CRef(head));
      if (it != enum_back_.end()) return it->second;
    }
    return FieldEntryNumeric<FieldEntry<int>, int>::GetStringValue(head);
  }

 protected:
  bool is_enum_{false};
  std::map<std::string, int> enum_map_;
  std::map<int, std::string> enum_back_;
};

template <>
class FieldEntry<bool, void> : public FieldEntryBase<FieldEntry<bool>, bool> {
 public:
  void Set(void* head, const std::string& value) const override {
    std::string v = value;
    std::transform(v.begin(), v.end(), v.begin(), ::tolower);
    if (v == "true" || v == "1") {
      this->Ref(head) = true;
    } else if (v == "false" || v == "0") {
      this->Ref(head) = false;
    } else {
      throw ParamError("Invalid boolean \"" + value + "\" for parameter \"" +
                       key_ + "\"");
    }
  }
  std::string GetStringValue(const void* head) const override {
    return this->CRef(head) ? "1" : "0";
  }
};

template <>
class FieldEntry<std::string, void>
    : public FieldEntryBase<FieldEntry<std::string>, std::string> {
 public:
  void Set(void* head, const std::string& value) const override {
    this->Ref(head) = value;  // whole string, including spaces
  }
  std::string GetStringValue(const void* head) const override {
    return this->CRef(head);
  }
};

/*! \brief per-type manager: declared fields + aliases */
class ParamManager {
 public:
  ~ParamManager() {
    for (auto& kv : entries_) delete kv.second;
  }
  FieldAccessEntry* Find(const std::string& key) const {
    auto it = entries_.find(key);
    if (it != entries_.end()) return it->second;
    auto al = aliases_.find(key);
    if (al != aliases_.end()) {
      auto it2 = entries_.find(al->second);
      if (it2 != entries_.end()) return it2->second;
    }
    return nullptr;
  }
  void AddEntry(const std::string& key, FieldAccessEntry* e) {
    if (entries_.count(key)) {
      delete e;
      return;  // re-declare (multiple singleton races) is a no-op
    }
    entries_[key] = e;
    order_.push_back(key);
  }
  void AddAlias(const std::string& field, const std::string& alias) {
    aliases_[alias] = field;
  }
  void SetDefaults(void* head) const {
    for (const auto& k : order_) entries_.at(k)->SetDefault(head);
  }
  const std::vector<std::string>& Order() const { return order_; }
  const std::map<std::string, FieldAccessEntry*>& Entries() const {
    return entries_;
  }
  std::string name;

 private:
  std::map<std::string, FieldAccessEntry*> entries_;
  std::map<std::string, std::string> aliases_;
  std::vector<std::string> order_;
};

template <typename PType>
struct ParamManagerSingleton {
  ParamManager manager;
  explicit ParamManagerSingleton(const std::string& param_name) {
    PType param;
    manager.name = param_name;
    param.__DECLARE__(this);
  }
};

}  // namespace parameter

/*! \brief CRTP base of all parameter structs */
template <typename PType>
struct Parameter {
 public:
  template <typename Container>
  inline void Init(const Container& kwargs) {
    RunUpdate(kwargs, /*init=*/true, /*allow_unknown=*/false, nullptr);
  }
  template <typename Container>
  inline std::vector<std::pair<std::string, std::string>> InitAllowUnknown(
      const Container& kwargs) {
    std::vector<std::pair<std::string, std::string>> unknown;
    RunUpdate(kwargs, /*init=*/true, /*allow_unknown=*/true, &unknown);
    return unknown;
  }
  template <typename Container>
  inline std::vector<std::pair<std::string, std::string>> UpdateAllowUnknown(
      const Container& kwargs) {
    std::vector<std::pair<std::string, std::string>> unknown;
    RunUpdate(kwargs, /*init=*/false, /*allow_unknown=*/true, &unknown);
    return unknown;
  }
  /*! \brief all fields rendered to strings */
  inline std::map<std::string, std::string> __DICT__() const {
    std::map<std::string, std::string> ret;
    auto* m = PType::__MANAGER__();
    const void* head = static_cast<const void*>(self());
    for (const auto& kv : m->Entries()) {
      ret[kv.first] = kv.second->GetStringValue(head);
    }
    return ret;
  }
  inline std::vector<ParamFieldInfo> __FIELDS__() const {
    std::vector<ParamFieldInfo> ret;
    auto* m = PType::__MANAGER__();
    for (const auto& k : m->Order()) {
      ret.push_back(m->Entries().at(k)->GetFieldInfo());
    }
    return ret;
  }

 protected:
  /* helper used by the DMLC_DECLARE_FIELD macro expansion */
  template <typename DType>
  inline parameter::FieldEntry<DType>& DECLARE(
      parameter::ParamManagerSingleton<PType>* manager, const std::string& key,
      DType& ref) {  // NOLINT
    auto* e = new parameter::FieldEntry<DType>();
    e->Init(key, static_cast<void*>(this), ref);
    manager->manager.AddEntry(key, e);
    return *e;
  }

 private:
  const PType* self() const { return static_cast<const PType*>(this); }
  PType* self_mut() { return static_cast<PType*>(this); }

  template <typename Container>
  void RunUpdate(const Container& kwargs, bool init, bool allow_unknown,
                 std::vector<std::pair<std::string, std::string>>* unknown) {
    auto* m = PType::__MANAGER__();
    void* head = static_cast<void*>(self_mut());
    if (init) {
      // defaults first so unmentioned optional fields are well-defined;
      // required fields must appear in kwargs
      for (const auto& key : m->Order()) {
        auto* e = m->Find(key);
        if (e->has_default_) {
          e->SetDefault(head);
        } else {
          bool provided = false;
          for (const auto& kv : kwargs) {
            if (m->Find(kv.first) == e) {
              provided = true;
              break;
            }
          }
          if (!provided) e->SetDefault(head);  // throws "required"
        }
      }
    }
    for (const auto& kv : kwargs) {
      auto* e = m->Find(kv.first);
      if (e == nullptr) {
        if (!allow_unknown) {
          throw ParamError("Unknown parameter \"" + kv.first + "\"");
        }
        if (unknown) unknown->emplace_back(kv.first, kv.second);
        continue;
      }
      e->Set(head, kv.second);
    }
  }
};

}  // namespace dmlc

#define DMLC_DECLARE_PARAMETER(PType)                          \
  static ::dmlc::parameter::ParamManager* __MANAGER__();       \
  inline void __DECLARE__(                                     \
      ::dmlc::parameter::ParamManagerSingleton<PType>* manager)

#define DMLC_DECLARE_FIELD(FieldName) \
  this->DECLARE(manager, #FieldName, FieldName)

#define DMLC_DECLARE_ALIAS(FieldName, AliasName) \
  manager->manager.AddAlias(#FieldName, #AliasName)

#define DMLC_REGISTER_PARAMETER(PType)                                     \
  ::dmlc::parameter::ParamManager* PType::__MANAGER__() {                  \
    static ::dmlc::parameter::ParamManagerSingleton<PType> inst(#PType);   \
    return &inst.manager;                                                  \
  }                                                                        \
  static DMLC_ATTRIBUTE_UNUSED ::dmlc::parameter::ParamManager&            \
      __make__##PType##ParamManager__ = (*PType::__MANAGER__())

#endif  // DMLC_PARAMETER_H_
