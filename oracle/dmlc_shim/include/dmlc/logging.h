/* glog-style logging/check macros for the oracle build (dmlc shim).
 * LogMessageFatal throws dmlc::Error so the reference's C API boundary
 * (XGB_API_BEGIN/END catching dmlc::Error) works unchanged.
 */
#ifndef DMLC_LOGGING_H_
#define DMLC_LOGGING_H_

#include <cstdio>
#include <cstdlib>
#include <ctime>
#include <iostream>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>

#include "./base.h"

namespace dmlc {

/*! \brief exception thrown by LOG(FATAL) / failed CHECKs */
struct Error : public std::runtime_error {
  explicit Error(const std::string& s) : std::runtime_error(s) {}
};

class DateLogger {
 public:
  const char* HumanDate() {
    std::time_t t = std::time(nullptr);
    std::tm now{};
#if defined(_WIN32)
    localtime_s(&now, &t);
#else
    localtime_r(&t, &now);
#endif
    std::snprintf(buffer_, sizeof(buffer_), "%02d:%02d:%02d", now.tm_hour,
                  now.tm_min, now.tm_sec);
    return buffer_;
  }

 private:
  char buffer_[16];
};

class LogMessage {
 public:
  LogMessage(const char* file, int line) {
    log_stream_ << "[" << DateLogger().HumanDate() << "] " << file << ":"
                << line << ": ";
  }
  ~LogMessage() { std::cerr << log_stream_.str() << std::endl; }
  std::ostream& stream() { return log_stream_; }

 protected:
  std::ostringstream log_stream_;

 private:
  LogMessage(const LogMessage&) = delete;
  void operator=(const LogMessage&) = delete;
};

/*! \brief fatal message: collects the stream and throws dmlc::Error */
class LogMessageFatal {
 public:
  LogMessageFatal(const char* file, int line) {
    log_stream_ << file << ":" << line << ": ";
  }
  std::ostream& stream() { return log_stream_; }
  ~LogMessageFatal() noexcept(false) {
#if DMLC_LOG_FATAL_THROW
    throw Error(log_stream_.str());
#else
    std::cerr << log_stream_.str() << std::endl;
    std::abort();
#endif
  }

 private:
  std::ostringstream log_stream_;
  LogMessageFatal(const LogMessageFatal&) = delete;
  void operator=(const LogMessageFatal&) = delete;
};

/*! \brief customized logging target (the reference redirects this to its
 *  ConsoleLogger in src/logging.cc via DMLC_LOG_CUSTOMIZE) */
class CustomLogMessage {
 public:
  CustomLogMessage(const char* file, int line) {
    log_stream_ << "[" << DateLogger().HumanDate() << "] " << file << ":"
                << line << ": ";
  }
  ~CustomLogMessage() { Log(log_stream_.str()); }
  std::ostream& stream() { return log_stream_; }
  /*! \brief implemented by the client (src/logging.cc in the reference) */
  static void Log(const std::string& msg);

 protected:
  std::ostringstream log_stream_;
};

#if defined(DMLC_LOG_CUSTOMIZE) && DMLC_LOG_CUSTOMIZE
using LogMessageInfo = CustomLogMessage;
#else
using LogMessageInfo = LogMessage;
#endif

/*! \brief helper so `CHECK(x) << ...` has a sink when the check passes */
class LogMessageVoidify {
 public:
  void operator&(std::ostream&) {}
};

}  // namespace dmlc

#if defined(__GNUC__) || defined(__clang__)
#define DMLC_EXPECT_TRUE(x) __builtin_expect(!!(x), 1)
#define DMLC_EXPECT_FALSE(x) __builtin_expect(!!(x), 0)
#else
#define DMLC_EXPECT_TRUE(x) (x)
#define DMLC_EXPECT_FALSE(x) (x)
#endif

#define CHECK(x)                                            \
  if (DMLC_EXPECT_FALSE(!(x)))                              \
  ::dmlc::LogMessageFatal(__FILE__, __LINE__).stream()      \
      << "Check failed: " #x << ": "

#define CHECK_BINARY_OP(op, x, y)                           \
  if (DMLC_EXPECT_FALSE(!((x)op(y))))                       \
  ::dmlc::LogMessageFatal(__FILE__, __LINE__).stream()      \
      << "Check failed: " #x " " #op " " #y << ": "

#define CHECK_LT(x, y) CHECK_BINARY_OP(<, x, y)
#define CHECK_GT(x, y) CHECK_BINARY_OP(>, x, y)
#define CHECK_LE(x, y) CHECK_BINARY_OP(<=, x, y)
#define CHECK_GE(x, y) CHECK_BINARY_OP(>=, x, y)
#define CHECK_EQ(x, y) CHECK_BINARY_OP(==, x, y)
#define CHECK_NE(x, y) CHECK_BINARY_OP(!=, x, y)
#define CHECK_NOTNULL(x)                                                     \
  ((x) == nullptr                                                            \
   ? (::dmlc::LogMessageFatal(__FILE__, __LINE__).stream()                   \
          << "Check notnull: " #x << ": ",                                   \
      (x))                                                                   \
   : (x))

#if defined(NDEBUG)
#define DCHECK(x) \
  while (false) CHECK(x)
#define DCHECK_LT(x, y) \
  while (false) CHECK_LT(x, y)
#define DCHECK_GT(x, y) \
  while (false) CHECK_GT(x, y)
#define DCHECK_LE(x, y) \
  while (false) CHECK_LE(x, y)
#define DCHECK_GE(x, y) \
  while (false) CHECK_GE(x, y)
#define DCHECK_EQ(x, y) \
  while (false) CHECK_EQ(x, y)
#define DCHECK_NE(x, y) \
  while (false) CHECK_NE(x, y)
#else
#define DCHECK(x) CHECK(x)
#define DCHECK_LT(x, y) CHECK_LT(x, y)
#define DCHECK_GT(x, y) CHECK_GT(x, y)
#define DCHECK_LE(x, y) CHECK_LE(x, y)
#define DCHECK_GE(x, y) CHECK_GE(x, y)
#define DCHECK_EQ(x, y) CHECK_EQ(x, y)
#define DCHECK_NE(x, y) CHECK_NE(x, y)
#endif

#define LOG_FATAL ::dmlc::LogMessageFatal(__FILE__, __LINE__)
#define LOG_ERROR ::dmlc::LogMessage(__FILE__, __LINE__)
#define LOG_WARNING ::dmlc::LogMessage(__FILE__, __LINE__)
#define LOG_INFO ::dmlc::LogMessageInfo(__FILE__, __LINE__)
#define LOG_DEBUG LOG_INFO

#ifndef LOG
#define LOG(severity) LOG_##severity.stream()
#endif

#define LOG_IF(severity, condition) \
  !(condition) ? (void)0 : ::dmlc::LogMessageVoidify() & LOG(severity)

#endif  // DMLC_LOGGING_H_
