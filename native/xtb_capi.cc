// C ABI for xgboost_tpu — the entry point for non-Python bindings.
//
// Reference: include/xgboost/c_api.h (the XGB_DLL surface) and
// src/c_api/c_api.cc.  The reference marshals C buffers into its C++
// Learner; here the runtime boundary is the same C ABI, but the compute
// engine is the JAX package, reached through an embedded CPython
// interpreter (xgboost_tpu/capi_glue.py holds the Python half).  Handles
// are strong PyObject references; every call holds the GIL and converts
// Python exceptions into the XGBGetLastError contract (c_api_error.h).
//
// CONCURRENCY CONTRACT (dispatch-lock contract, checked by xtblint XTB2xx —
// docs/static_analysis.md): every entry point still takes the embedded
// interpreter's GIL while it executes Python, but the GIL is NOT the
// serializer any more — jax releases it for the duration of each compiled
// XLA execution, and the native kernels those executions dispatch are
// internally multi-threaded (native/xtb_kernels.h ParallelFor).  What keeps
// the ABI safe across those release windows is a process-wide
// reader/writer dispatch lock:
//
//   - API_BEGIN_READ()  — read-only Booster entry points (the predict
//     family, save/dump/attr getters).  SHARED lock: N host threads
//     predicting through this library overlap their XLA compute and run at
//     multi-thread throughput (tests/test_c_api.py
//     test_concurrent_predict_parallel_throughput).
//   - API_BEGIN_MUT()   — Booster mutators (train/boost/set-param/load/
//     reset/attr setters + EvalOneIter, which rewrites the pinned eval
//     buffer).  EXCLUSIVE lock: mutation stays fully serialized against
//     both other mutators and in-flight reads.
//   - API_BEGIN()       — handle-local creation/ingestion (DMatrix, proxy,
//     tracker, collective).  GIL only: these never share learner state, and
//     the DataIter callback path re-enters the ABI (a dispatch lock here
//     would self-deadlock XGDMatrixCreateFromCallback).
//
// Lock order is dispatch-lock BEFORE GIL, always: a reader/writer never
// blocks on the dispatch lock while holding the GIL, so the GIL-release
// windows inside Python cannot deadlock against a waiting mutator.
// Prediction result buffers pin per (handle, caller thread) on the glue
// side (capi_glue.py), the reference's XGBAPIThreadLocalEntry convention,
// so concurrent readers of one handle never free each other's returns.
// The reference's C API serves concurrent predict from one learner via a
// thread-safe Learner (src/c_api/c_api.cc); batching-style concurrency
// remains the job of xgboost_tpu.serving.ServingEngine (docs/serving.md).
//
// Build: native/Makefile (links libpython via python3-config --embed).

#include <Python.h>

#include <dlfcn.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <shared_mutex>
#include <string>

#define XTB_DLL extern "C" __attribute__((visibility("default")))

typedef void* DMatrixHandle;
typedef void* BoosterHandle;
typedef uint64_t bst_ulong;

namespace {

thread_local std::string g_last_error;

std::once_flag g_init_flag;
PyObject* g_glue = nullptr;  // xgboost_tpu.capi_glue module

void InitPython() {
  std::call_once(g_init_flag, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // release the GIL the embedded init leaves held, so every API call's
      // PyGILState_Ensure/Release pair actually acquires and drops it —
      // otherwise a second host thread deadlocks forever on its first call
      PyEval_SaveThread();
    }
  });
}

// RAII GIL hold that works both embedded and when loaded into a live
// interpreter (the ctypes test path).
class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

void CaptureError() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "unknown error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// Directory that holds the xgboost_tpu package: the parent of the directory
// containing this shared object (native/ lives inside the repo root).
std::string PackageRoot() {
  Dl_info info;
  if (dladdr(reinterpret_cast<void*>(&PackageRoot), &info) == 0 ||
      info.dli_fname == nullptr) {
    return "";
  }
  std::string p(info.dli_fname);
  auto slash = p.rfind('/');
  if (slash == std::string::npos) return "";
  p.erase(slash);  // strip libxtb_capi.so -> .../native
  slash = p.rfind('/');
  if (slash == std::string::npos) return "";
  p.erase(slash);  // strip native -> repo root
  return p;
}

PyObject* Glue() {
  if (g_glue == nullptr) {
    g_glue = PyImport_ImportModule("xgboost_tpu.capi_glue");
    if (g_glue == nullptr) {
      // Embedded interpreters launched from an arbitrary cwd won't have the
      // package on sys.path; locate it relative to this shared object.
      std::string root = PackageRoot();
      if (!root.empty()) {
        PyErr_Clear();
        PyObject* sys_path = PySys_GetObject("path");  // borrowed
        PyObject* dir = PyUnicode_FromString(root.c_str());
        if (sys_path != nullptr && dir != nullptr) {
          PyList_Append(sys_path, dir);
        }
        Py_XDECREF(dir);
        g_glue = PyImport_ImportModule("xgboost_tpu.capi_glue");
      }
    }
  }
  return g_glue;  // nullptr with a pending Python error on failure
}

// Call glue.<method>(fmt-args); returns a NEW reference or nullptr.
PyObject* CallGlue(const char* method, const char* fmt, ...) {
  PyObject* mod = Glue();
  if (mod == nullptr) return nullptr;
  PyObject* fn = PyObject_GetAttrString(mod, method);
  if (fn == nullptr) return nullptr;
  va_list va;
  va_start(va, fmt);
  PyObject* args = Py_VaBuildValue(fmt, va);
  va_end(va);
  if (args == nullptr) {
    Py_DECREF(fn);
    return nullptr;
  }
  PyObject* ret = PyObject_CallObject(fn, args);
  Py_DECREF(args);
  Py_DECREF(fn);
  return ret;
}

// Process-wide reader/writer dispatch lock (see the CONCURRENCY CONTRACT
// above).  One lock for all boosters: per-handle locks would buy nothing —
// the embedded interpreter is shared anyway — and a single rwlock keeps the
// acquire order trivially deadlock-free.
std::shared_mutex g_dispatch_rw;

}  // namespace

#define API_BEGIN()  \
  InitPython();      \
  Gil gil;           \
  try {

// read-only Booster entry: shared dispatch lock, acquired BEFORE the GIL
#define API_BEGIN_READ()                                   \
  std::shared_lock<std::shared_mutex> rw_(g_dispatch_rw);  \
  API_BEGIN()

// mutating Booster entry: exclusive dispatch lock, acquired BEFORE the GIL
#define API_BEGIN_MUT()                                    \
  std::unique_lock<std::shared_mutex> rw_(g_dispatch_rw);  \
  API_BEGIN()
#define API_END()                               \
  }                                             \
  catch (...) {                                 \
    g_last_error = "unexpected C++ exception";  \
    return -1;                                  \
  }
#define FAIL_IF_NULL(obj) \
  if ((obj) == nullptr) { \
    CaptureError();       \
    return -1;            \
  }

XTB_DLL const char* XGBGetLastError() { return g_last_error.c_str(); }

XTB_DLL int XGBoostVersion(int* major, int* minor, int* patch) {
  if (major) *major = 3;
  if (minor) *minor = 1;
  if (patch) *patch = 0;
  return 0;
}

// ---------------------------------------------------------------- DMatrix
XTB_DLL int XGDMatrixCreateFromMat(const float* data, bst_ulong nrow,
                                   bst_ulong ncol, float missing,
                                   DMatrixHandle* out) {
  API_BEGIN();
  PyObject* d = CallGlue("dmatrix_from_mat", "(KKKd)",
                         (unsigned long long)(uintptr_t)data,
                         (unsigned long long)nrow, (unsigned long long)ncol,
                         (double)missing);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixCreateFromCSREx(const bst_ulong* indptr,
                                     const unsigned* indices,
                                     const float* data, bst_ulong nindptr,
                                     bst_ulong nelem, bst_ulong num_col,
                                     DMatrixHandle* out) {
  API_BEGIN();
  PyObject* d = CallGlue("dmatrix_from_csr", "(KKKKKK)",
                         (unsigned long long)(uintptr_t)indptr,
                         (unsigned long long)(uintptr_t)indices,
                         (unsigned long long)(uintptr_t)data,
                         (unsigned long long)nindptr,
                         (unsigned long long)nelem,
                         (unsigned long long)num_col);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixSetFloatInfo(DMatrixHandle handle, const char* field,
                                  const float* array, bst_ulong len) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_set_float_info", "(OsKK)",
                         (PyObject*)handle, field,
                         (unsigned long long)(uintptr_t)array,
                         (unsigned long long)len);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixSetUIntInfo(DMatrixHandle handle, const char* field,
                                 const unsigned* array, bst_ulong len) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_set_uint_info", "(OsKK)",
                         (PyObject*)handle, field,
                         (unsigned long long)(uintptr_t)array,
                         (unsigned long long)len);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixNumRow(DMatrixHandle handle, bst_ulong* out) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_num_row", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  *out = (bst_ulong)PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixNumCol(DMatrixHandle handle, bst_ulong* out) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_num_col", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  *out = (bst_ulong)PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixFree(DMatrixHandle handle) {
  API_BEGIN();
  Py_XDECREF((PyObject*)handle);
  return 0;
  API_END();
}

// ---------------------------------------------------------------- Booster
XTB_DLL int XGBoosterCreate(const DMatrixHandle dmats[], bst_ulong len,
                            BoosterHandle* out) {
  API_BEGIN();
  PyObject* list = PyList_New((Py_ssize_t)len);
  FAIL_IF_NULL(list);
  for (bst_ulong i = 0; i < len; ++i) {
    PyObject* o = (PyObject*)dmats[i];
    Py_INCREF(o);
    PyList_SET_ITEM(list, (Py_ssize_t)i, o);
  }
  PyObject* b = CallGlue("booster_create", "(O)", list);
  Py_DECREF(list);
  FAIL_IF_NULL(b);
  *out = b;
  return 0;
  API_END();
}

XTB_DLL int XGBoosterFree(BoosterHandle handle) {
  API_BEGIN();
  Py_XDECREF((PyObject*)handle);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterSetParam(BoosterHandle handle, const char* name,
                              const char* value) {
  API_BEGIN_MUT();
  PyObject* r = CallGlue("booster_set_param", "(Oss)", (PyObject*)handle,
                         name, value);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterUpdateOneIter(BoosterHandle handle, int iter,
                                   DMatrixHandle dtrain) {
  API_BEGIN_MUT();
  PyObject* r = CallGlue("booster_update_one_iter", "(OiO)",
                         (PyObject*)handle, iter, (PyObject*)dtrain);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterBoostOneIter(BoosterHandle handle, DMatrixHandle dtrain,
                                  float* grad, float* hess, bst_ulong len) {
  API_BEGIN_MUT();
  PyObject* r = CallGlue("booster_boost_one_iter", "(OOKKK)",
                         (PyObject*)handle, (PyObject*)dtrain,
                         (unsigned long long)(uintptr_t)grad,
                         (unsigned long long)(uintptr_t)hess,
                         (unsigned long long)len);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterEvalOneIter(BoosterHandle handle, int iter,
                                 DMatrixHandle dmats[],
                                 const char* evnames[], bst_ulong len,
                                 const char** out_result) {
  API_BEGIN_MUT();
  PyObject* dl = PyList_New((Py_ssize_t)len);
  FAIL_IF_NULL(dl);
  PyObject* nl = PyList_New((Py_ssize_t)len);
  if (nl == nullptr) {
    Py_DECREF(dl);
    CaptureError();
    return -1;
  }
  for (bst_ulong i = 0; i < len; ++i) {
    PyObject* o = (PyObject*)dmats[i];
    Py_INCREF(o);
    PyList_SET_ITEM(dl, (Py_ssize_t)i, o);
    PyObject* name = PyUnicode_FromString(evnames[i]);
    if (name == nullptr) {  // e.g. invalid UTF-8 from the C caller
      Py_DECREF(dl);
      Py_DECREF(nl);
      CaptureError();
      return -1;
    }
    PyList_SET_ITEM(nl, (Py_ssize_t)i, name);
  }
  PyObject* r = CallGlue("booster_eval_one_iter", "(OiOO)",
                         (PyObject*)handle, iter, dl, nl);
  Py_DECREF(dl);
  Py_DECREF(nl);
  FAIL_IF_NULL(r);
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  // the bytes object is pinned on the booster by the glue; this borrowed
  // view stays valid until the next eval call on the same handle
  *out_result = buf;
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterPredict(BoosterHandle handle, DMatrixHandle dmat,
                             int option_mask, unsigned ntree_limit,
                             int training, bst_ulong* out_len,
                             const float** out_result) {
  API_BEGIN_READ();
  PyObject* r = CallGlue("booster_predict", "(OOiIi)", (PyObject*)handle,
                         (PyObject*)dmat, option_mask, ntree_limit, training);
  FAIL_IF_NULL(r);
  unsigned long long n = 0, addr = 0;
  if (!PyArg_ParseTuple(r, "KK", &n, &addr)) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  Py_DECREF(r);
  *out_len = (bst_ulong)n;
  *out_result = (const float*)(uintptr_t)addr;  // pinned on the booster
  return 0;
  API_END();
}

XTB_DLL int XGBoosterSaveModel(BoosterHandle handle, const char* fname) {
  API_BEGIN_READ();
  PyObject* r = CallGlue("booster_save_model", "(Os)", (PyObject*)handle,
                         fname);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterLoadModel(BoosterHandle handle, const char* fname) {
  API_BEGIN_MUT();
  PyObject* r = CallGlue("booster_load_model", "(Os)", (PyObject*)handle,
                         fname);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterSaveModelToBuffer(BoosterHandle handle,
                                       const char* config, bst_ulong* out_len,
                                       const char** out_dptr) {
  API_BEGIN_READ();
  // config is '{"format": "json"|"ubj"}' (c_api.cc); default ubj
  const char* fmt = (config && std::strstr(config, "json")) ? "json" : "ubj";
  PyObject* r = CallGlue("booster_save_raw", "(Os)", (PyObject*)handle, fmt);
  FAIL_IF_NULL(r);
  unsigned long long n = 0;
  PyObject* bytes_obj = nullptr;
  if (!PyArg_ParseTuple(r, "KO", &n, &bytes_obj)) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t bn = 0;
  if (PyBytes_AsStringAndSize(bytes_obj, &buf, &bn) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  *out_len = (bst_ulong)n;
  *out_dptr = buf;  // pinned on the booster by the glue
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterLoadModelFromBuffer(BoosterHandle handle, const void* buf,
                                         bst_ulong len) {
  API_BEGIN_MUT();
  PyObject* r = CallGlue("booster_load_raw", "(OKK)", (PyObject*)handle,
                         (unsigned long long)(uintptr_t)buf,
                         (unsigned long long)len);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterGetAttr(BoosterHandle handle, const char* key,
                             const char** out, int* success) {
  API_BEGIN_READ();
  PyObject* r = CallGlue("booster_get_attr", "(Os)", (PyObject*)handle, key);
  FAIL_IF_NULL(r);
  if (r == Py_None) {
    *success = 0;
    *out = nullptr;
  } else {
    char* buf = nullptr;
    Py_ssize_t n = 0;
    if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
      Py_DECREF(r);
      CaptureError();
      return -1;
    }
    *success = 1;
    *out = buf;  // pinned on the booster by the glue
  }
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterSetAttr(BoosterHandle handle, const char* key,
                             const char* value) {
  API_BEGIN_MUT();
  PyObject* r = (value == nullptr)
                    ? CallGlue("booster_set_attr", "(OsO)", (PyObject*)handle,
                               key, Py_None)
                    : CallGlue("booster_set_attr", "(Oss)", (PyObject*)handle,
                               key, value);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterBoostedRounds(BoosterHandle handle, int* out) {
  API_BEGIN_READ();
  PyObject* r = CallGlue("booster_num_boosted_rounds", "(O)",
                         (PyObject*)handle);
  FAIL_IF_NULL(r);
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterGetNumFeature(BoosterHandle handle, bst_ulong* out) {
  API_BEGIN_READ();
  PyObject* r = CallGlue("booster_num_features", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  *out = (bst_ulong)PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

// Shared body for the categories-export pair (reference:
// include/xgboost/c_api.h XGBoosterGetCategories / XGDMatrixGetCategories,
// src/data/cat_container.h).  The reference returns an Arrow-C-schema
// struct; this ABI returns the mapping as a JSON object
// {"feature": [values...]} — "null" when no categorical features exist.
// The buffer is pinned on the handle: valid until the NEXT Get*Categories
// call on the same handle (which replaces it) or the handle is freed; no
// *Free call (the ret_str convention of XGBoosterEvalOneIter).
static int GetCategoriesImpl(const char* glue_method, void* handle,
                             const char** out_json) {
  PyObject* r = CallGlue(glue_method, "(O)", (PyObject*)handle);
  if (r == nullptr) {
    CaptureError();
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  *out_json = buf;  // pinned on the handle by the glue
  Py_DECREF(r);
  return 0;
}

XTB_DLL int XGBoosterGetCategories(BoosterHandle handle,
                                   const char** out_json) {
  API_BEGIN_READ();
  return GetCategoriesImpl("booster_get_categories", handle, out_json);
  API_END();
}

XTB_DLL int XGDMatrixGetCategories(DMatrixHandle handle,
                                   const char** out_json) {
  API_BEGIN();
  return GetCategoriesImpl("dmatrix_get_categories", handle, out_json);
  API_END();
}

// ====================================================================
// Round-3 surface expansion (reference c_api.h): array-interface
// ingestion, inplace predict, DataIter callbacks, dump/slice/feature
// info, config IO, global config, collective + tracker C API.

namespace {

// glue returned (len, addr-of-char**) — unpack into the caller's out params
int StrArrayResult(PyObject* r, bst_ulong* out_len, const char*** out) {
  unsigned long long n = 0, addr = 0;
  if (!PyArg_ParseTuple(r, "KK", &n, &addr)) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  Py_DECREF(r);
  *out_len = (bst_ulong)n;
  *out = (const char**)(uintptr_t)addr;
  return 0;
}

// glue returned (len, addr) of a pinned numeric buffer
template <typename T>
int ArrayResult(PyObject* r, bst_ulong* out_len, T const** out) {
  unsigned long long n = 0, addr = 0;
  if (!PyArg_ParseTuple(r, "KK", &n, &addr)) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  Py_DECREF(r);
  *out_len = (bst_ulong)n;
  *out = (T const*)(uintptr_t)addr;
  return 0;
}

// glue returned (shape_addr, dim, result_addr) for a prediction
int PredictResult(PyObject* r, bst_ulong const** out_shape, bst_ulong* out_dim,
                  float const** out_result) {
  unsigned long long shape_addr = 0, dim = 0, res_addr = 0;
  if (!PyArg_ParseTuple(r, "KKK", &shape_addr, &dim, &res_addr)) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  Py_DECREF(r);
  *out_shape = (bst_ulong const*)(uintptr_t)shape_addr;
  *out_dim = (bst_ulong)dim;
  *out_result = (float const*)(uintptr_t)res_addr;
  return 0;
}

// build a Python list[str] from char** (nullptr-safe)
PyObject* StrList(const char** strs, bst_ulong n) {
  PyObject* l = PyList_New((Py_ssize_t)n);
  if (l == nullptr) return nullptr;
  for (bst_ulong i = 0; i < n; ++i) {
    PyObject* s = PyUnicode_FromString(strs[i] ? strs[i] : "");
    if (s == nullptr) {
      Py_DECREF(l);
      return nullptr;
    }
    PyList_SET_ITEM(l, (Py_ssize_t)i, s);
  }
  return l;
}

}  // namespace

XTB_DLL int XGBuildInfo(char const** out) {
  API_BEGIN();
  PyObject* r = CallGlue("build_info", "()");
  FAIL_IF_NULL(r);
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  *out = buf;  // pinned module-globally by the glue
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBSetGlobalConfig(char const* config) {
  API_BEGIN();
  PyObject* r = CallGlue("set_global_config", "(s)", config);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBGetGlobalConfig(char const** out_config) {
  API_BEGIN();
  PyObject* r = CallGlue("get_global_config", "()");
  FAIL_IF_NULL(r);
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  *out_config = buf;
  Py_DECREF(r);
  return 0;
  API_END();
}

// log callback: stored for ABI completeness; Python-side logging writes to
// stderr directly (the reference registers it into its ConsoleLogger)
namespace {
void (*g_log_callback)(const char*) = nullptr;
}
XTB_DLL int XGBRegisterLogCallback(void (*callback)(const char*)) {
  g_log_callback = callback;
  return 0;
}

// ---------------------------------------------------------------- DMatrix
XTB_DLL int XGDMatrixCreateFromDense(char const* data, char const* config,
                                     DMatrixHandle* out) {
  API_BEGIN();
  PyObject* d = CallGlue("dmatrix_from_dense", "(ss)", data, config);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixCreateFromCSR(char const* indptr, char const* indices,
                                   char const* data, bst_ulong ncol,
                                   char const* config, DMatrixHandle* out) {
  API_BEGIN();
  PyObject* d = CallGlue("dmatrix_from_csr_ai", "(sssKs)", indptr, indices,
                         data, (unsigned long long)ncol, config);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixCreateFromMat_omp(const float* data, bst_ulong nrow,
                                       bst_ulong ncol, float missing,
                                       DMatrixHandle* out, int nthread) {
  // nthread is honored (it was name-only ABI compatibility before):
  // it configures the native ParallelFor pool, the analogue of the
  // reference's omp_set_num_threads scope (0/negative = default).
  API_BEGIN();
  PyObject* d = CallGlue("dmatrix_from_mat_nthread", "(KKKdi)",
                         (unsigned long long)(uintptr_t)data,
                         (unsigned long long)nrow, (unsigned long long)ncol,
                         (double)missing, nthread);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixCreateFromURI(char const* config, DMatrixHandle* out) {
  API_BEGIN();
  PyObject* d = CallGlue("dmatrix_from_uri", "(s)", config);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixSliceDMatrixEx(DMatrixHandle handle, const int* idxset,
                                    bst_ulong len, DMatrixHandle* out,
                                    int allow_groups) {
  API_BEGIN();
  PyObject* d = CallGlue("dmatrix_slice", "(OKKi)", (PyObject*)handle,
                         (unsigned long long)(uintptr_t)idxset,
                         (unsigned long long)len, allow_groups);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixSliceDMatrix(DMatrixHandle handle, const int* idxset,
                                  bst_ulong len, DMatrixHandle* out) {
  return XGDMatrixSliceDMatrixEx(handle, idxset, len, out, 0);
}

XTB_DLL int XGDMatrixSaveBinary(DMatrixHandle handle, const char* fname,
                                int silent) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_save_binary", "(Osi)", (PyObject*)handle,
                         fname, silent);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixSetStrFeatureInfo(DMatrixHandle handle, const char* field,
                                       const char** features,
                                       const bst_ulong size) {
  API_BEGIN();
  PyObject* l = StrList(features, size);
  FAIL_IF_NULL(l);
  PyObject* r = CallGlue("dmatrix_set_str_feature_info", "(OsO)",
                         (PyObject*)handle, field, l);
  Py_DECREF(l);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixGetStrFeatureInfo(DMatrixHandle handle, const char* field,
                                       bst_ulong* size,
                                       const char*** out_features) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_get_str_feature_info", "(Os)",
                         (PyObject*)handle, field);
  FAIL_IF_NULL(r);
  return StrArrayResult(r, size, out_features);
  API_END();
}

XTB_DLL int XGDMatrixGetFloatInfo(const DMatrixHandle handle,
                                  const char* field, bst_ulong* out_len,
                                  const float** out_dptr) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_get_float_info", "(Os)", (PyObject*)handle,
                         field);
  FAIL_IF_NULL(r);
  return ArrayResult<float>(r, out_len, out_dptr);
  API_END();
}

XTB_DLL int XGDMatrixGetUIntInfo(const DMatrixHandle handle, const char* field,
                                 bst_ulong* out_len,
                                 const unsigned** out_dptr) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_get_uint_info", "(Os)", (PyObject*)handle,
                         field);
  FAIL_IF_NULL(r);
  return ArrayResult<unsigned>(r, out_len, out_dptr);
  API_END();
}

XTB_DLL int XGDMatrixNumNonMissing(DMatrixHandle handle, bst_ulong* out) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_num_nonmissing", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  *out = (bst_ulong)PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixDataSplitMode(DMatrixHandle handle, bst_ulong* out) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_data_split_mode", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  *out = (bst_ulong)PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixGetDataAsCSR(DMatrixHandle const handle,
                                  char const* config, bst_ulong* out_indptr,
                                  unsigned* out_indices, float* out_data) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_get_data_as_csr", "(Os)", (PyObject*)handle,
                         config);
  FAIL_IF_NULL(r);
  unsigned long long ip = 0, ix = 0, va = 0, n_indptr = 0, nnz = 0;
  if (!PyArg_ParseTuple(r, "KKKKK", &ip, &ix, &va, &n_indptr, &nnz)) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  Py_DECREF(r);
  std::memcpy(out_indptr, (void*)(uintptr_t)ip, n_indptr * sizeof(bst_ulong));
  std::memcpy(out_indices, (void*)(uintptr_t)ix, nnz * sizeof(unsigned));
  std::memcpy(out_data, (void*)(uintptr_t)va, nnz * sizeof(float));
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixGetQuantileCut(DMatrixHandle const handle,
                                    char const* config,
                                    char const** out_indptr,
                                    char const** out_data) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_get_quantile_cut", "(Os)", (PyObject*)handle,
                         config);
  FAIL_IF_NULL(r);
  PyObject *ip = nullptr, *va = nullptr;
  if (!PyArg_ParseTuple(r, "OO", &ip, &va)) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  char *ipb = nullptr, *vab = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(ip, &ipb, &n) != 0 ||
      PyBytes_AsStringAndSize(va, &vab, &n) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  *out_indptr = ipb;  // pinned on the DMatrix by the glue
  *out_data = vab;
  Py_DECREF(r);
  return 0;
  API_END();
}

// -------------------------------------------- proxy + iterator callbacks
typedef void* DataIterHandle;
typedef int XGDMatrixCallbackNext(DataIterHandle iter);
typedef void DataIterResetCallback(DataIterHandle handle);

XTB_DLL int XGProxyDMatrixCreate(DMatrixHandle* out) {
  API_BEGIN();
  PyObject* p = CallGlue("proxy_create", "()");
  FAIL_IF_NULL(p);
  *out = p;
  return 0;
  API_END();
}

XTB_DLL int XGProxyDMatrixSetDataDense(DMatrixHandle handle,
                                       char const* data) {
  API_BEGIN();
  PyObject* r = CallGlue("proxy_set_dense", "(Os)", (PyObject*)handle, data);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGProxyDMatrixSetDataCSR(DMatrixHandle handle, char const* indptr,
                                     char const* indices, char const* data,
                                     bst_ulong ncol) {
  API_BEGIN();
  PyObject* r = CallGlue("proxy_set_csr", "(OsssK)", (PyObject*)handle, indptr,
                         indices, data, (unsigned long long)ncol);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixCreateFromCallback(DataIterHandle iter,
                                        DMatrixHandle proxy,
                                        DataIterResetCallback* reset,
                                        XGDMatrixCallbackNext* next,
                                        char const* config,
                                        DMatrixHandle* out) {
  API_BEGIN();
  PyObject* d = CallGlue("dmatrix_from_callback", "(KOKKs)",
                         (unsigned long long)(uintptr_t)iter, (PyObject*)proxy,
                         (unsigned long long)(uintptr_t)reset,
                         (unsigned long long)(uintptr_t)next, config);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGQuantileDMatrixCreateFromCallback(
    DataIterHandle iter, DMatrixHandle proxy, DataIterHandle ref,
    DataIterResetCallback* reset, XGDMatrixCallbackNext* next,
    char const* config, DMatrixHandle* out) {
  API_BEGIN();
  PyObject* refobj = ref ? (PyObject*)ref : Py_None;
  PyObject* d = CallGlue("quantile_dmatrix_from_callback", "(KOOKKs)",
                         (unsigned long long)(uintptr_t)iter, (PyObject*)proxy,
                         refobj, (unsigned long long)(uintptr_t)reset,
                         (unsigned long long)(uintptr_t)next, config);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGExtMemQuantileDMatrixCreateFromCallback(
    DataIterHandle iter, DMatrixHandle proxy, DataIterHandle ref,
    DataIterResetCallback* reset, XGDMatrixCallbackNext* next,
    char const* config, DMatrixHandle* out) {
  API_BEGIN();
  PyObject* refobj = ref ? (PyObject*)ref : Py_None;
  PyObject* d = CallGlue("extmem_quantile_dmatrix_from_callback", "(KOOKKs)",
                         (unsigned long long)(uintptr_t)iter, (PyObject*)proxy,
                         refobj, (unsigned long long)(uintptr_t)reset,
                         (unsigned long long)(uintptr_t)next, config);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

// ---------------------------------------------------------------- Booster
XTB_DLL int XGBoosterReset(BoosterHandle handle) {
  API_BEGIN_MUT();
  PyObject* r = CallGlue("booster_reset", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterSlice(BoosterHandle handle, int begin_layer,
                           int end_layer, int step, BoosterHandle* out) {
  API_BEGIN_READ();
  PyObject* b = CallGlue("booster_slice", "(Oiii)", (PyObject*)handle,
                         begin_layer, end_layer, step);
  FAIL_IF_NULL(b);
  *out = b;
  return 0;
  API_END();
}

XTB_DLL int XGBoosterTrainOneIter(BoosterHandle handle, DMatrixHandle dtrain,
                                  int iter, char const* grad,
                                  char const* hess) {
  API_BEGIN_MUT();
  PyObject* r = CallGlue("booster_train_one_iter", "(OOiss)",
                         (PyObject*)handle, (PyObject*)dtrain, iter, grad,
                         hess);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterPredictFromDMatrix(BoosterHandle handle,
                                        DMatrixHandle dmat,
                                        char const* config,
                                        bst_ulong const** out_shape,
                                        bst_ulong* out_dim,
                                        float const** out_result) {
  API_BEGIN_READ();
  PyObject* r = CallGlue("booster_predict_from_dmatrix", "(OOs)",
                         (PyObject*)handle, (PyObject*)dmat, config);
  FAIL_IF_NULL(r);
  return PredictResult(r, out_shape, out_dim, out_result);
  API_END();
}

XTB_DLL int XGBoosterPredictFromDense(BoosterHandle handle,
                                      char const* values, char const* config,
                                      DMatrixHandle m,
                                      bst_ulong const** out_shape,
                                      bst_ulong* out_dim,
                                      const float** out_result) {
  API_BEGIN_READ();
  PyObject* meta = m ? (PyObject*)m : Py_None;
  PyObject* r = CallGlue("booster_inplace_predict_dense", "(OssO)",
                         (PyObject*)handle, values, config, meta);
  FAIL_IF_NULL(r);
  return PredictResult(r, out_shape, out_dim, out_result);
  API_END();
}

XTB_DLL int XGBoosterPredictFromCSR(BoosterHandle handle, char const* indptr,
                                    char const* indices, char const* values,
                                    bst_ulong ncol, char const* config,
                                    DMatrixHandle m,
                                    bst_ulong const** out_shape,
                                    bst_ulong* out_dim,
                                    const float** out_result) {
  API_BEGIN_READ();
  PyObject* meta = m ? (PyObject*)m : Py_None;
  PyObject* r = CallGlue("booster_inplace_predict_csr", "(OsssKsO)",
                         (PyObject*)handle, indptr, indices, values,
                         (unsigned long long)ncol, config, meta);
  FAIL_IF_NULL(r);
  return PredictResult(r, out_shape, out_dim, out_result);
  API_END();
}

XTB_DLL int XGBoosterSerializeToBuffer(BoosterHandle handle,
                                       bst_ulong* out_len,
                                       const char** out_dptr) {
  API_BEGIN_READ();
  PyObject* r = CallGlue("booster_serialize", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  unsigned long long n = 0;
  PyObject* bytes_obj = nullptr;
  if (!PyArg_ParseTuple(r, "KO", &n, &bytes_obj)) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t bn = 0;
  if (PyBytes_AsStringAndSize(bytes_obj, &buf, &bn) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  *out_len = (bst_ulong)n;
  *out_dptr = buf;
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterUnserializeFromBuffer(BoosterHandle handle,
                                           const void* buf, bst_ulong len) {
  API_BEGIN_MUT();
  PyObject* r = CallGlue("booster_unserialize", "(OKK)", (PyObject*)handle,
                         (unsigned long long)(uintptr_t)buf,
                         (unsigned long long)len);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterSaveJsonConfig(BoosterHandle handle, bst_ulong* out_len,
                                    char const** out_str) {
  API_BEGIN_READ();
  PyObject* r = CallGlue("booster_save_json_config", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  unsigned long long n = 0;
  PyObject* bytes_obj = nullptr;
  if (!PyArg_ParseTuple(r, "KO", &n, &bytes_obj)) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t bn = 0;
  if (PyBytes_AsStringAndSize(bytes_obj, &buf, &bn) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  *out_len = (bst_ulong)n;
  *out_str = buf;
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterLoadJsonConfig(BoosterHandle handle,
                                    char const* config) {
  API_BEGIN_MUT();
  PyObject* r = CallGlue("booster_load_json_config", "(Os)", (PyObject*)handle,
                         config);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterDumpModelEx(BoosterHandle handle, const char* fmap,
                                 int with_stats, const char* format,
                                 bst_ulong* out_len,
                                 const char*** out_dump_array) {
  API_BEGIN_READ();
  PyObject* r = CallGlue("booster_dump_model", "(Osis)", (PyObject*)handle,
                         fmap ? fmap : "", with_stats,
                         format ? format : "text");
  FAIL_IF_NULL(r);
  return StrArrayResult(r, out_len, out_dump_array);
  API_END();
}

XTB_DLL int XGBoosterDumpModel(BoosterHandle handle, const char* fmap,
                               int with_stats, bst_ulong* out_len,
                               const char*** out_dump_array) {
  return XGBoosterDumpModelEx(handle, fmap, with_stats, "text", out_len,
                              out_dump_array);
}

XTB_DLL int XGBoosterDumpModelExWithFeatures(
    BoosterHandle handle, int fnum, const char** fname, const char** ftype,
    int with_stats, const char* format, bst_ulong* out_len,
    const char*** out_models) {
  API_BEGIN_READ();
  PyObject* names = StrList(fname, (bst_ulong)fnum);
  FAIL_IF_NULL(names);
  PyObject* types = StrList(ftype, (bst_ulong)fnum);
  if (types == nullptr) {
    Py_DECREF(names);
    CaptureError();
    return -1;
  }
  PyObject* r = CallGlue("booster_dump_model", "(OsisOO)", (PyObject*)handle,
                         "", with_stats, format ? format : "text", names,
                         types);
  Py_DECREF(names);
  Py_DECREF(types);
  FAIL_IF_NULL(r);
  return StrArrayResult(r, out_len, out_models);
  API_END();
}

XTB_DLL int XGBoosterDumpModelWithFeatures(BoosterHandle handle, int fnum,
                                           const char** fname,
                                           const char** ftype, int with_stats,
                                           bst_ulong* out_len,
                                           const char*** out_models) {
  return XGBoosterDumpModelExWithFeatures(handle, fnum, fname, ftype,
                                          with_stats, "text", out_len,
                                          out_models);
}

XTB_DLL int XGBoosterGetAttrNames(BoosterHandle handle, bst_ulong* out_len,
                                  const char*** out) {
  API_BEGIN_READ();
  PyObject* r = CallGlue("booster_get_attr_names", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  return StrArrayResult(r, out_len, out);
  API_END();
}

XTB_DLL int XGBoosterSetStrFeatureInfo(BoosterHandle handle,
                                       const char* field,
                                       const char** features,
                                       const bst_ulong size) {
  API_BEGIN_MUT();
  PyObject* l = StrList(features, size);
  FAIL_IF_NULL(l);
  PyObject* r = CallGlue("booster_set_str_feature_info", "(OsO)",
                         (PyObject*)handle, field, l);
  Py_DECREF(l);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterGetStrFeatureInfo(BoosterHandle handle,
                                       const char* field, bst_ulong* len,
                                       const char*** out_features) {
  API_BEGIN_READ();
  PyObject* r = CallGlue("booster_get_str_feature_info", "(Os)",
                         (PyObject*)handle, field);
  FAIL_IF_NULL(r);
  return StrArrayResult(r, len, out_features);
  API_END();
}

XTB_DLL int XGBoosterFeatureScore(BoosterHandle handle, const char* config,
                                  bst_ulong* out_n_features,
                                  char const*** out_features,
                                  bst_ulong* out_dim,
                                  bst_ulong const** out_shape,
                                  float const** out_scores) {
  API_BEGIN_READ();
  PyObject* r = CallGlue("booster_feature_score", "(Os)", (PyObject*)handle,
                         config);
  FAIL_IF_NULL(r);
  unsigned long long n = 0, feat_addr = 0, shape_addr = 0, dim = 0,
                     score_addr = 0;
  if (!PyArg_ParseTuple(r, "KKKKK", &n, &feat_addr, &shape_addr, &dim,
                        &score_addr)) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  Py_DECREF(r);
  *out_n_features = (bst_ulong)n;
  *out_features = (char const**)(uintptr_t)feat_addr;
  *out_dim = (bst_ulong)dim;
  *out_shape = (bst_ulong const*)(uintptr_t)shape_addr;
  *out_scores = (float const*)(uintptr_t)score_addr;
  return 0;
  API_END();
}

// ------------------------------------------------- collective + tracker
typedef void* TrackerHandle;

XTB_DLL int XGTrackerCreate(char const* config, TrackerHandle* handle) {
  API_BEGIN();
  PyObject* t = CallGlue("tracker_create", "(s)", config);
  FAIL_IF_NULL(t);
  *handle = t;
  return 0;
  API_END();
}

XTB_DLL int XGTrackerWorkerArgs(TrackerHandle handle, char const** args) {
  API_BEGIN();
  PyObject* r = CallGlue("tracker_worker_args", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  *args = buf;
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGTrackerRun(TrackerHandle handle, char const* config) {
  API_BEGIN();
  PyObject* r = CallGlue("tracker_run", "(Os)", (PyObject*)handle,
                         config ? config : "{}");
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGTrackerWaitFor(TrackerHandle handle, char const* config) {
  API_BEGIN();
  PyObject* r = CallGlue("tracker_wait_for", "(Os)", (PyObject*)handle,
                         config ? config : "{}");
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGTrackerFree(TrackerHandle handle) {
  API_BEGIN();
  PyObject* r = CallGlue("tracker_free", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);  // handle stays alive on failure so a retry is safe
  Py_XDECREF((PyObject*)handle);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGCommunicatorInit(char const* config) {
  API_BEGIN();
  PyObject* r = CallGlue("communicator_init", "(s)", config ? config : "{}");
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGCommunicatorFinalize(void) {
  API_BEGIN();
  PyObject* r = CallGlue("communicator_finalize", "()");
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGCommunicatorGetRank(void) {
  InitPython();
  Gil gil;
  PyObject* r = CallGlue("communicator_get_rank", "()");
  if (r == nullptr) {
    CaptureError();
    return 0;
  }
  int rank = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return rank;
}

XTB_DLL int XGCommunicatorGetWorldSize(void) {
  InitPython();
  Gil gil;
  PyObject* r = CallGlue("communicator_get_world_size", "()");
  if (r == nullptr) {
    CaptureError();
    return 1;
  }
  int n = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return n;
}

XTB_DLL int XGCommunicatorIsDistributed(void) {
  InitPython();
  Gil gil;
  PyObject* r = CallGlue("communicator_is_distributed", "()");
  if (r == nullptr) {
    CaptureError();
    return 0;
  }
  int v = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return v;
}

XTB_DLL int XGCommunicatorPrint(char const* message) {
  API_BEGIN();
  PyObject* r = CallGlue("communicator_print", "(s)", message);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGCommunicatorGetProcessorName(const char** name_str) {
  API_BEGIN();
  PyObject* r = CallGlue("communicator_get_processor_name", "()");
  FAIL_IF_NULL(r);
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  *name_str = buf;
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGCommunicatorBroadcast(void* send_receive_buffer, size_t size,
                                    int root) {
  API_BEGIN();
  PyObject* r = CallGlue("communicator_broadcast", "(KKi)",
                         (unsigned long long)(uintptr_t)send_receive_buffer,
                         (unsigned long long)size, root);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGCommunicatorAllreduce(void* send_receive_buffer, size_t count,
                                    int data_type, int op) {
  API_BEGIN();
  PyObject* r = CallGlue("communicator_allreduce", "(KKii)",
                         (unsigned long long)(uintptr_t)send_receive_buffer,
                         (unsigned long long)count, data_type, op);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

// -------------------- columnar / CSC / info-interface (round-3 tail)
XTB_DLL int XGDMatrixCreateFromColumnar(char const* data, char const* config,
                                        DMatrixHandle* out) {
  API_BEGIN();
  PyObject* d = CallGlue("dmatrix_from_columnar", "(ss)", data, config);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixCreateFromCSC(char const* indptr, char const* indices,
                                   char const* data, bst_ulong nrow,
                                   char const* config, DMatrixHandle* out) {
  API_BEGIN();
  PyObject* d = CallGlue("dmatrix_from_csc_ai", "(sssKs)", indptr, indices,
                         data, (unsigned long long)nrow, config);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGProxyDMatrixSetDataColumnar(DMatrixHandle handle,
                                          char const* data) {
  API_BEGIN();
  PyObject* r = CallGlue("proxy_set_columnar", "(Os)", (PyObject*)handle,
                         data);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterPredictFromColumnar(BoosterHandle handle,
                                         char const* values,
                                         char const* config, DMatrixHandle m,
                                         bst_ulong const** out_shape,
                                         bst_ulong* out_dim,
                                         const float** out_result) {
  API_BEGIN_READ();
  PyObject* meta = m ? (PyObject*)m : Py_None;
  PyObject* r = CallGlue("booster_inplace_predict_columnar", "(OssO)",
                         (PyObject*)handle, values, config, meta);
  FAIL_IF_NULL(r);
  return PredictResult(r, out_shape, out_dim, out_result);
  API_END();
}

XTB_DLL int XGDMatrixSetInfoFromInterface(DMatrixHandle handle,
                                          char const* field,
                                          char const* data) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_set_info_from_interface", "(Oss)",
                         (PyObject*)handle, field, data);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixSetDenseInfo(DMatrixHandle handle, const char* field,
                                  void const* data, bst_ulong size,
                                  int type) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_set_dense_info", "(OsKKi)",
                         (PyObject*)handle, field,
                         (unsigned long long)(uintptr_t)data,
                         (unsigned long long)size, type);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixGetInfoRef(DMatrixHandle handle, const char* field,
                                const char** out_array) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_get_info_ref", "(Os)", (PyObject*)handle,
                         field);
  FAIL_IF_NULL(r);
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  *out_array = buf;
  Py_DECREF(r);
  return 0;
  API_END();
}
