// C ABI for xgboost_tpu — the entry point for non-Python bindings.
//
// Reference: include/xgboost/c_api.h (the XGB_DLL surface) and
// src/c_api/c_api.cc.  The reference marshals C buffers into its C++
// Learner; here the runtime boundary is the same C ABI, but the compute
// engine is the JAX package, reached through an embedded CPython
// interpreter (xgboost_tpu/capi_glue.py holds the Python half).  Handles
// are strong PyObject references; every call holds the GIL and converts
// Python exceptions into the XGBGetLastError contract (c_api_error.h).
//
// Build: native/Makefile (links libpython via python3-config --embed).

#include <Python.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>

#define XTB_DLL extern "C" __attribute__((visibility("default")))

typedef void* DMatrixHandle;
typedef void* BoosterHandle;
typedef uint64_t bst_ulong;

namespace {

thread_local std::string g_last_error;

std::once_flag g_init_flag;
PyObject* g_glue = nullptr;  // xgboost_tpu.capi_glue module

void InitPython() {
  std::call_once(g_init_flag, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // release the GIL the embedded init leaves held, so every API call's
      // PyGILState_Ensure/Release pair actually acquires and drops it —
      // otherwise a second host thread deadlocks forever on its first call
      PyEval_SaveThread();
    }
  });
}

// RAII GIL hold that works both embedded and when loaded into a live
// interpreter (the ctypes test path).
class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

void CaptureError() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "unknown error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

PyObject* Glue() {
  if (g_glue == nullptr) {
    g_glue = PyImport_ImportModule("xgboost_tpu.capi_glue");
  }
  return g_glue;  // nullptr with a pending Python error on failure
}

// Call glue.<method>(fmt-args); returns a NEW reference or nullptr.
PyObject* CallGlue(const char* method, const char* fmt, ...) {
  PyObject* mod = Glue();
  if (mod == nullptr) return nullptr;
  PyObject* fn = PyObject_GetAttrString(mod, method);
  if (fn == nullptr) return nullptr;
  va_list va;
  va_start(va, fmt);
  PyObject* args = Py_VaBuildValue(fmt, va);
  va_end(va);
  if (args == nullptr) {
    Py_DECREF(fn);
    return nullptr;
  }
  PyObject* ret = PyObject_CallObject(fn, args);
  Py_DECREF(args);
  Py_DECREF(fn);
  return ret;
}

}  // namespace

#define API_BEGIN()  \
  InitPython();      \
  Gil gil;           \
  try {
#define API_END()                               \
  }                                             \
  catch (...) {                                 \
    g_last_error = "unexpected C++ exception";  \
    return -1;                                  \
  }
#define FAIL_IF_NULL(obj) \
  if ((obj) == nullptr) { \
    CaptureError();       \
    return -1;            \
  }

XTB_DLL const char* XGBGetLastError() { return g_last_error.c_str(); }

XTB_DLL int XGBoostVersion(int* major, int* minor, int* patch) {
  if (major) *major = 3;
  if (minor) *minor = 1;
  if (patch) *patch = 0;
  return 0;
}

// ---------------------------------------------------------------- DMatrix
XTB_DLL int XGDMatrixCreateFromMat(const float* data, bst_ulong nrow,
                                   bst_ulong ncol, float missing,
                                   DMatrixHandle* out) {
  API_BEGIN();
  PyObject* d = CallGlue("dmatrix_from_mat", "(KKKd)",
                         (unsigned long long)(uintptr_t)data,
                         (unsigned long long)nrow, (unsigned long long)ncol,
                         (double)missing);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixCreateFromCSREx(const bst_ulong* indptr,
                                     const unsigned* indices,
                                     const float* data, bst_ulong nindptr,
                                     bst_ulong nelem, bst_ulong num_col,
                                     DMatrixHandle* out) {
  API_BEGIN();
  PyObject* d = CallGlue("dmatrix_from_csr", "(KKKKKK)",
                         (unsigned long long)(uintptr_t)indptr,
                         (unsigned long long)(uintptr_t)indices,
                         (unsigned long long)(uintptr_t)data,
                         (unsigned long long)nindptr,
                         (unsigned long long)nelem,
                         (unsigned long long)num_col);
  FAIL_IF_NULL(d);
  *out = d;
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixSetFloatInfo(DMatrixHandle handle, const char* field,
                                  const float* array, bst_ulong len) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_set_float_info", "(OsKK)",
                         (PyObject*)handle, field,
                         (unsigned long long)(uintptr_t)array,
                         (unsigned long long)len);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixSetUIntInfo(DMatrixHandle handle, const char* field,
                                 const unsigned* array, bst_ulong len) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_set_uint_info", "(OsKK)",
                         (PyObject*)handle, field,
                         (unsigned long long)(uintptr_t)array,
                         (unsigned long long)len);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixNumRow(DMatrixHandle handle, bst_ulong* out) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_num_row", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  *out = (bst_ulong)PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixNumCol(DMatrixHandle handle, bst_ulong* out) {
  API_BEGIN();
  PyObject* r = CallGlue("dmatrix_num_col", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  *out = (bst_ulong)PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGDMatrixFree(DMatrixHandle handle) {
  API_BEGIN();
  Py_XDECREF((PyObject*)handle);
  return 0;
  API_END();
}

// ---------------------------------------------------------------- Booster
XTB_DLL int XGBoosterCreate(const DMatrixHandle dmats[], bst_ulong len,
                            BoosterHandle* out) {
  API_BEGIN();
  PyObject* list = PyList_New((Py_ssize_t)len);
  FAIL_IF_NULL(list);
  for (bst_ulong i = 0; i < len; ++i) {
    PyObject* o = (PyObject*)dmats[i];
    Py_INCREF(o);
    PyList_SET_ITEM(list, (Py_ssize_t)i, o);
  }
  PyObject* b = CallGlue("booster_create", "(O)", list);
  Py_DECREF(list);
  FAIL_IF_NULL(b);
  *out = b;
  return 0;
  API_END();
}

XTB_DLL int XGBoosterFree(BoosterHandle handle) {
  API_BEGIN();
  Py_XDECREF((PyObject*)handle);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterSetParam(BoosterHandle handle, const char* name,
                              const char* value) {
  API_BEGIN();
  PyObject* r = CallGlue("booster_set_param", "(Oss)", (PyObject*)handle,
                         name, value);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterUpdateOneIter(BoosterHandle handle, int iter,
                                   DMatrixHandle dtrain) {
  API_BEGIN();
  PyObject* r = CallGlue("booster_update_one_iter", "(OiO)",
                         (PyObject*)handle, iter, (PyObject*)dtrain);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterBoostOneIter(BoosterHandle handle, DMatrixHandle dtrain,
                                  float* grad, float* hess, bst_ulong len) {
  API_BEGIN();
  PyObject* r = CallGlue("booster_boost_one_iter", "(OOKKK)",
                         (PyObject*)handle, (PyObject*)dtrain,
                         (unsigned long long)(uintptr_t)grad,
                         (unsigned long long)(uintptr_t)hess,
                         (unsigned long long)len);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterEvalOneIter(BoosterHandle handle, int iter,
                                 DMatrixHandle dmats[],
                                 const char* evnames[], bst_ulong len,
                                 const char** out_result) {
  API_BEGIN();
  PyObject* dl = PyList_New((Py_ssize_t)len);
  FAIL_IF_NULL(dl);
  PyObject* nl = PyList_New((Py_ssize_t)len);
  if (nl == nullptr) {
    Py_DECREF(dl);
    CaptureError();
    return -1;
  }
  for (bst_ulong i = 0; i < len; ++i) {
    PyObject* o = (PyObject*)dmats[i];
    Py_INCREF(o);
    PyList_SET_ITEM(dl, (Py_ssize_t)i, o);
    PyObject* name = PyUnicode_FromString(evnames[i]);
    if (name == nullptr) {  // e.g. invalid UTF-8 from the C caller
      Py_DECREF(dl);
      Py_DECREF(nl);
      CaptureError();
      return -1;
    }
    PyList_SET_ITEM(nl, (Py_ssize_t)i, name);
  }
  PyObject* r = CallGlue("booster_eval_one_iter", "(OiOO)",
                         (PyObject*)handle, iter, dl, nl);
  Py_DECREF(dl);
  Py_DECREF(nl);
  FAIL_IF_NULL(r);
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  // the bytes object is pinned on the booster by the glue; this borrowed
  // view stays valid until the next eval call on the same handle
  *out_result = buf;
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterPredict(BoosterHandle handle, DMatrixHandle dmat,
                             int option_mask, unsigned ntree_limit,
                             int training, bst_ulong* out_len,
                             const float** out_result) {
  API_BEGIN();
  PyObject* r = CallGlue("booster_predict", "(OOiIi)", (PyObject*)handle,
                         (PyObject*)dmat, option_mask, ntree_limit, training);
  FAIL_IF_NULL(r);
  unsigned long long n = 0, addr = 0;
  if (!PyArg_ParseTuple(r, "KK", &n, &addr)) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  Py_DECREF(r);
  *out_len = (bst_ulong)n;
  *out_result = (const float*)(uintptr_t)addr;  // pinned on the booster
  return 0;
  API_END();
}

XTB_DLL int XGBoosterSaveModel(BoosterHandle handle, const char* fname) {
  API_BEGIN();
  PyObject* r = CallGlue("booster_save_model", "(Os)", (PyObject*)handle,
                         fname);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterLoadModel(BoosterHandle handle, const char* fname) {
  API_BEGIN();
  PyObject* r = CallGlue("booster_load_model", "(Os)", (PyObject*)handle,
                         fname);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterSaveModelToBuffer(BoosterHandle handle,
                                       const char* config, bst_ulong* out_len,
                                       const char** out_dptr) {
  API_BEGIN();
  // config is '{"format": "json"|"ubj"}' (c_api.cc); default ubj
  const char* fmt = (config && std::strstr(config, "json")) ? "json" : "ubj";
  PyObject* r = CallGlue("booster_save_raw", "(Os)", (PyObject*)handle, fmt);
  FAIL_IF_NULL(r);
  unsigned long long n = 0;
  PyObject* bytes_obj = nullptr;
  if (!PyArg_ParseTuple(r, "KO", &n, &bytes_obj)) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t bn = 0;
  if (PyBytes_AsStringAndSize(bytes_obj, &buf, &bn) != 0) {
    Py_DECREF(r);
    CaptureError();
    return -1;
  }
  *out_len = (bst_ulong)n;
  *out_dptr = buf;  // pinned on the booster by the glue
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterLoadModelFromBuffer(BoosterHandle handle, const void* buf,
                                         bst_ulong len) {
  API_BEGIN();
  PyObject* r = CallGlue("booster_load_raw", "(OKK)", (PyObject*)handle,
                         (unsigned long long)(uintptr_t)buf,
                         (unsigned long long)len);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterGetAttr(BoosterHandle handle, const char* key,
                             const char** out, int* success) {
  API_BEGIN();
  PyObject* r = CallGlue("booster_get_attr", "(Os)", (PyObject*)handle, key);
  FAIL_IF_NULL(r);
  if (r == Py_None) {
    *success = 0;
    *out = nullptr;
  } else {
    char* buf = nullptr;
    Py_ssize_t n = 0;
    if (PyBytes_AsStringAndSize(r, &buf, &n) != 0) {
      Py_DECREF(r);
      CaptureError();
      return -1;
    }
    *success = 1;
    *out = buf;  // pinned on the booster by the glue
  }
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterSetAttr(BoosterHandle handle, const char* key,
                             const char* value) {
  API_BEGIN();
  PyObject* r = (value == nullptr)
                    ? CallGlue("booster_set_attr", "(OsO)", (PyObject*)handle,
                               key, Py_None)
                    : CallGlue("booster_set_attr", "(Oss)", (PyObject*)handle,
                               key, value);
  FAIL_IF_NULL(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterBoostedRounds(BoosterHandle handle, int* out) {
  API_BEGIN();
  PyObject* r = CallGlue("booster_num_boosted_rounds", "(O)",
                         (PyObject*)handle);
  FAIL_IF_NULL(r);
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
  API_END();
}

XTB_DLL int XGBoosterGetNumFeature(BoosterHandle handle, bst_ulong* out) {
  API_BEGIN();
  PyObject* r = CallGlue("booster_num_features", "(O)", (PyObject*)handle);
  FAIL_IF_NULL(r);
  *out = (bst_ulong)PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return 0;
  API_END();
}
