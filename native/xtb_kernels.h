// Shared host-kernel bodies for xgboost_tpu's native runtime.
//
// Included by xtb_native.cc (plain C ABI for ctypes consumers and tests)
// and xtb_ffi.cc (XLA FFI handlers — the zero-copy path the jitted CPU
// training programs call).  Role analogue of the reference's CPU hist
// updater hot loops (src/common/hist_util.cc BuildHist,
// src/tree/hist/evaluate_splits.h EnumerateSplit), re-designed around the
// elementwise `pos` row routing used by the JAX growers instead of the
// reference's physical row partitions.
//
// Every hot kernel is multi-threaded through the ParallelFor pool below
// (the role of the reference's common/threading_utils.h ParallelFor over
// OpenMP) under a strict determinism contract: sharding axes are chosen so
// every output element receives its f32 adds in exactly the order the
// sequential kernel produces, which keeps results BITWISE IDENTICAL for
// every nthread — see docs/native_threading.md for the per-kernel scheme.
#ifndef XTB_KERNELS_H_
#define XTB_KERNELS_H_

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

// Runtime CPU-feature dispatch + every raw intrinsic in the project lives in
// the SIMD seam header (xtblint XTB601 rejects intrinsics anywhere else).
// Kernels below call the xtb_* dispatch wrappers; each has a scalar twin
// with identical per-element semantics, so scalar and vector builds stay
// bitwise-equal — see docs/native_threading.md for the per-kernel technique.
#include "xtb_simd.h"

// ===========================================================================
// ParallelFor pool.
//
// Persistent workers, lazy start, one in-flight region at a time.  A region
// splits [0, n) into at most nthread contiguous shards of >= grain elements;
// shards are CLAIMED dynamically (load balancing) but shard BOUNDARIES and
// each shard's internal iteration order are fixed, so any kernel whose
// shards write disjoint output (all of ours) is bitwise-reproducible for
// every thread count.  When a second caller dispatches while a region is in
// flight (concurrent C-API predict), it runs its range inline on its own
// thread instead of queueing — concurrent callers never serialize on the
// pool, they just don't multiply threads.
//
// Fault containment: a shard body that throws marks the region failed; the
// dispatcher then re-runs the WHOLE region inline (shard bodies are
// restart-idempotent: each (re)initialises the output it owns), which is
// the nthread=1 execution and therefore bitwise-correct.  An injected
// worker death (xtb_pool_kill_worker, the `native.parallel_for` fault
// seam) makes one worker exit before claiming shards; the dispatcher
// drains the remaining shards itself — no hang — and respawns the worker
// at the end of the region.
// ===========================================================================

enum XtbKernelId {
  XTB_K_HIST = 0,
  XTB_K_HIST_Q,
  XTB_K_SPLIT,
  XTB_K_PREDICT,
  XTB_K_LAMBDARANK,
  XTB_K_SKETCH,
  XTB_K_SHAP,
  XTB_K_ELLPACK,
  XTB_K_OTHER,
  XTB_K_COUNT,
};

inline const char* xtb_kernel_name_impl(int k) {
  static const char* kNames[XTB_K_COUNT] = {
      "hist", "hist_q", "split", "predict", "lambdarank",
      "sketch", "shap", "ellpack", "other"};
  return (k >= 0 && k < XTB_K_COUNT) ? kNames[k] : "";
}

// Region busy-seconds bucket bounds — MUST match
// telemetry/registry.py DEFAULT_BUCKETS (1e-4 * 4**i, i in 0..9) so the
// Python bridge can fold these counts straight into the registry histogram.
constexpr int kXtbPoolBuckets = 10;  // + 1 overflow slot in the arrays

struct XtbKernelStats {
  std::atomic<int64_t> regions{0};
  std::atomic<int64_t> busy_ns{0};
  std::atomic<int64_t> bucket[kXtbPoolBuckets + 1]{};
  // Whole-invocation perf accounting (XtbKernelPerf below): unlike the
  // region fields above, these cover inline executions too (S<=1 or a
  // busy pool run the body without a dispatched region), so the roofline
  // reporter sees every byte the kernel actually moved.
  std::atomic<int64_t> invocations{0};
  std::atomic<int64_t> wall_ns{0};
  std::atomic<int64_t> cycles{0};
  std::atomic<int64_t> bytes{0};
  std::atomic<int64_t> flops{0};
};

class XtbThreadPool {
 public:
  static XtbThreadPool& Get() {
    static XtbThreadPool* pool = new XtbThreadPool();  // never destroyed:
    // worker threads may outlive static destruction order in the embedding
    return *pool;
  }

  // n <= 0 resolves the default (XGBOOST_TPU_NTHREAD env, else hardware
  // concurrency).  Returns the effective thread count (callers + workers).
  int set_nthread(int n) {
    int eff = resolve(n);
    std::lock_guard<std::mutex> dispatch(dispatch_mu_);  // no region in flight
    if (eff != target()) {
      stop_workers();
      std::lock_guard<std::mutex> g(mu_);
      target_ = eff;
    }
    return eff;
  }

  int nthread() { return target(); }

  int alive_workers() { return alive_.load(std::memory_order_acquire); }

  // Fault seam (reliability/faults.py `native.parallel_for`): the next
  // parallel region loses one worker thread before it claims any shard.
  void kill_worker() { kill_requests_.fetch_add(1); }

  int64_t faults_total() { return faults_.load(); }
  int64_t regions_total() {
    int64_t t = 0;
    for (auto& s : stats_) t += s.regions.load();
    return t;
  }
  const XtbKernelStats& stats(int kernel) {
    return stats_[(kernel >= 0 && kernel < XTB_K_COUNT) ? kernel
                                                        : XTB_K_OTHER];
  }

  // One finished kernel invocation (XtbKernelPerf): wall time, cycle
  // delta, and the caller's byte/flop traffic model.
  void record_perf(int kernel, int64_t wall_ns, int64_t cycles,
                   int64_t bytes, int64_t flops) {
    auto& s = stats_[(kernel >= 0 && kernel < XTB_K_COUNT) ? kernel
                                                           : XTB_K_OTHER];
    s.invocations.fetch_add(1, std::memory_order_relaxed);
    s.wall_ns.fetch_add(wall_ns, std::memory_order_relaxed);
    s.cycles.fetch_add(cycles, std::memory_order_relaxed);
    s.bytes.fetch_add(bytes, std::memory_order_relaxed);
    s.flops.fetch_add(flops, std::memory_order_relaxed);
  }

  void parallel_for(int64_t n, int64_t grain, int kernel,
                    const std::function<void(int64_t, int64_t)>& fn) {
    if (n <= 0) return;
    if (grain < 1) grain = 1;
    int64_t max_shards = (n + grain - 1) / grain;
    int S = static_cast<int>(std::min<int64_t>(target(), max_shards));
    if (S <= 1) {
      fn(0, n);
      return;
    }
    // one region at a time; a busy pool means another caller owns the
    // workers right now — run inline rather than queue (concurrent
    // read-only predict callers each keep their own thread busy)
    if (!dispatch_mu_.try_lock()) {
      fn(0, n);
      return;
    }
    std::unique_lock<std::mutex> dispatch(dispatch_mu_, std::adopt_lock);
    ensure_workers();
    {
      std::lock_guard<std::mutex> g(mu_);
      // retire injected worker deaths at dispatch (not at worker wake):
      // small regions can drain before a sleeping worker ever wakes, and
      // the fault seam promises the NEXT region loses a worker
      retire_requests_ += kill_requests_.exchange(0);
      job_fn_ = &fn;
      job_n_ = n;
      job_shards_ = S;
      done_shards_.store(0, std::memory_order_relaxed);
      failed_.store(false, std::memory_order_relaxed);
      busy_ns_region_.store(0, std::memory_order_relaxed);
      ++generation_;
      // generation-tagged shard ticket: claims CAS against the tag, so a
      // worker that lingers past its region's completion can never claim
      // (or steal) a shard of the NEXT region with a dangling job pointer
      ticket_.store(generation_ << kShardBits, std::memory_order_release);
    }
    cv_.notify_all();
    run_shards(&fn, n, S, generation_);  // dispatcher is pool member 0
    {
      std::unique_lock<std::mutex> g(mu_);
      cv_done_.wait(g, [&] {
        return done_shards_.load(std::memory_order_acquire) >= job_shards_;
      });
      job_fn_ = nullptr;
    }
    bool failed = failed_.load(std::memory_order_acquire);
    if (failed) {
      faults_.fetch_add(1);
      fn(0, n);  // deterministic recovery: the nthread=1 execution
    }
    if (alive_.load() < target() - 1) ensure_workers();  // respawn the dead
    record(kernel, failed ? 0 : busy_ns_region_.load());
  }

 private:
  XtbThreadPool() : target_(resolve(0)) {}

  int target() {
    std::lock_guard<std::mutex> g(mu_);
    return target_;
  }

  static int resolve(int n) {
    if (n > 0) return std::min(n, 1024);
    const char* env = std::getenv("XGBOOST_TPU_NTHREAD");
    if (env && *env) {
      int v = std::atoi(env);
      if (v > 0) return std::min(v, 1024);
    }
    unsigned hw = std::thread::hardware_concurrency();
    return hw > 0 ? static_cast<int>(hw) : 1;
  }

  void record(int kernel, int64_t busy_ns) {
    auto& s = stats_[(kernel >= 0 && kernel < XTB_K_COUNT) ? kernel
                                                           : XTB_K_OTHER];
    s.regions.fetch_add(1, std::memory_order_relaxed);
    s.busy_ns.fetch_add(busy_ns, std::memory_order_relaxed);
    double sec = static_cast<double>(busy_ns) * 1e-9;
    int b = 0;
    double bound = 1e-4;  // DEFAULT_BUCKETS[0]; bounds quadruple per slot
    while (b < kXtbPoolBuckets && sec > bound) {
      bound *= 4.0;
      ++b;
    }
    s.bucket[b].fetch_add(1, std::memory_order_relaxed);
  }

  void run_shards(const std::function<void(int64_t, int64_t)>* fn, int64_t n,
                  int S, uint64_t gen) {
    const uint64_t tag = gen << kShardBits;
    for (;;) {
      uint64_t v = ticket_.load(std::memory_order_acquire);
      uint64_t s = v & ((uint64_t{1} << kShardBits) - 1);
      if ((v & ~((uint64_t{1} << kShardBits) - 1)) != tag ||
          s >= static_cast<uint64_t>(S)) {
        break;  // all shards claimed, or a newer region owns the ticket
      }
      if (!ticket_.compare_exchange_weak(v, v + 1,
                                         std::memory_order_acq_rel)) {
        continue;  // lost the claim race; re-read
      }
      int64_t b = n * static_cast<int64_t>(s) / S;
      int64_t e = n * (static_cast<int64_t>(s) + 1) / S;
      auto t0 = std::chrono::steady_clock::now();
      try {
        (*fn)(b, e);
      } catch (...) {
        failed_.store(true, std::memory_order_release);
      }
      busy_ns_region_.fetch_add(
          std::chrono::duration_cast<std::chrono::nanoseconds>(
              std::chrono::steady_clock::now() - t0).count(),
          std::memory_order_relaxed);
      if (done_shards_.fetch_add(1, std::memory_order_acq_rel) + 1 >= S) {
        std::lock_guard<std::mutex> g(mu_);
        cv_done_.notify_all();
      }
    }
  }

  void worker_loop() {
    uint64_t seen = 0;
    for (;;) {
      const std::function<void(int64_t, int64_t)>* fn;
      int64_t n;
      int S;
      {
        std::unique_lock<std::mutex> g(mu_);
        cv_.wait(g, [&] {
          return shutdown_ || retire_requests_ > 0 || generation_ != seen;
        });
        if (shutdown_) break;
        if (retire_requests_ > 0) {
          --retire_requests_;
          faults_.fetch_add(1);
          break;  // injected worker death: exit before claiming any shard
        }
        seen = generation_;
        fn = job_fn_;  // copied under mu_: a late wake after the region
        n = job_n_;    // completed sees nullptr and just re-arms
        S = job_shards_;
      }
      if (fn == nullptr) continue;
      run_shards(fn, n, S, seen);
    }
    alive_.fetch_sub(1, std::memory_order_acq_rel);
  }

  // callers hold dispatch_mu_
  void ensure_workers() {
    // reap exited threads first (injected deaths leave joinable husks)
    if (alive_.load(std::memory_order_acquire) <
        static_cast<int>(workers_.size())) {
      {
        std::lock_guard<std::mutex> g(mu_);
        shutdown_ = true;
      }
      cv_.notify_all();
      for (auto& t : workers_) t.join();
      workers_.clear();
      {
        std::lock_guard<std::mutex> g(mu_);
        shutdown_ = false;
      }
      alive_.store(0, std::memory_order_release);
    }
    while (static_cast<int>(workers_.size()) < target_ - 1) {
      workers_.emplace_back([this] { worker_loop(); });
      alive_.fetch_add(1, std::memory_order_acq_rel);
    }
  }

  // callers hold dispatch_mu_
  void stop_workers() {
    {
      std::lock_guard<std::mutex> g(mu_);
      shutdown_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
    workers_.clear();
    {
      std::lock_guard<std::mutex> g(mu_);
      shutdown_ = false;
    }
    alive_.store(0, std::memory_order_release);
  }

  std::mutex dispatch_mu_;  // serializes regions + worker lifecycle
  std::mutex mu_;           // guards job fields + cv state
  std::condition_variable cv_, cv_done_;
  std::vector<std::thread> workers_;
  int target_;
  bool shutdown_ = false;
  uint64_t generation_ = 0;
  const std::function<void(int64_t, int64_t)>* job_fn_ = nullptr;
  int64_t job_n_ = 0;
  int job_shards_ = 0;
  static constexpr int kShardBits = 20;  // shards per region < 2^20
  std::atomic<uint64_t> ticket_{0};      // (generation << 20) | next_shard
  std::atomic<int> done_shards_{0}, alive_{0};
  std::atomic<int> kill_requests_{0};
  int retire_requests_ = 0;  // guarded by mu_
  std::atomic<bool> failed_{false};
  std::atomic<int64_t> busy_ns_region_{0}, faults_{0};
  XtbKernelStats stats_[XTB_K_COUNT];
};

// The one entry point kernels use: run fn(begin, end) over [0, n) shards of
// >= grain elements on the shared pool (inline when single-shard/busy).
inline void xtb_parallel_for(int64_t n, int64_t grain, int kernel,
                             const std::function<void(int64_t, int64_t)>& fn) {
  XtbThreadPool::Get().parallel_for(n, grain, kernel, fn);
}

// RAII perf bracket a kernel impl opens as its first statement: wall time
// (steady_clock — the monotonic-clock contract), cycle delta
// (xtb_simd.h xtb_cycle_counter_impl: rdtsc / cntvct), and the caller's
// byte/flop traffic model, recorded into the pool's per-kernel stats on
// scope exit.  The byte models count algorithmic traffic only (operand
// reads once, output write + RFO read), not cache effects — the roofline
// reporter (scripts/bench_roofline.py) documents each model next to its
// achieved-GB/s row.
class XtbKernelPerf {
 public:
  XtbKernelPerf(int kernel, int64_t bytes, int64_t flops)
      : kernel_(kernel), bytes_(bytes), flops_(flops),
        t0_(std::chrono::steady_clock::now()),
        c0_(xtb_cycle_counter_impl()) {}
  ~XtbKernelPerf() {
    const int64_t ns =
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - t0_).count();
    const uint64_t c1 = xtb_cycle_counter_impl();
    XtbThreadPool::Get().record_perf(kernel_, ns,
                                     static_cast<int64_t>(c1 - c0_),
                                     bytes_, flops_);
  }
  XtbKernelPerf(const XtbKernelPerf&) = delete;
  XtbKernelPerf& operator=(const XtbKernelPerf&) = delete;

 private:
  int kernel_;
  int64_t bytes_, flops_;
  std::chrono::steady_clock::time_point t0_;
  uint64_t c0_;
};

// STREAM-like triad a[i] = b[i] + s*c[i] through the pool — the host
// peak-bandwidth probe the roofline reporter normalizes kernel achieved
// GB/s against.  Traffic follows the classic STREAM convention:
// 3 accesses x 4 bytes per element (two reads + one write), no
// write-allocate accounting.
inline void xtb_stream_triad_impl(const float* b, const float* c, float s,
                                  float* a, int64_t n) {
  XtbKernelPerf perf(XTB_K_OTHER, 12 * n, n);
  auto shard = [=](int64_t i0, int64_t i1) {
    for (int64_t i = i0; i < i1; ++i) a[i] = b[i] + s * c[i];
  };
  xtb_parallel_for(n, int64_t{1} << 15, XTB_K_OTHER, shard);
}

// Per-translation-unit C ABI over the pool (each .so carries its own pool
// instance; utils/native.py configures every loaded library).  Define
// XTB_DEFINE_POOL_ABI before including this header in exactly one TU per
// shared object.
#ifdef XTB_DEFINE_POOL_ABI
extern "C" {
int xtb_set_nthread(int n) { return XtbThreadPool::Get().set_nthread(n); }
int xtb_get_nthread() { return XtbThreadPool::Get().nthread(); }
int xtb_pool_alive_workers() { return XtbThreadPool::Get().alive_workers(); }
void xtb_pool_kill_worker() { XtbThreadPool::Get().kill_worker(); }
int64_t xtb_pool_faults_total() { return XtbThreadPool::Get().faults_total(); }
int64_t xtb_pool_regions_total() {
  return XtbThreadPool::Get().regions_total();
}
int xtb_pool_n_kernels() { return XTB_K_COUNT; }
// gcc emits the pool's inline static with STB_GNU_UNIQUE linkage, so
// multiple kernel .so's in one process usually SHARE one pool instance;
// utils/native.py dedupes stats by this id before aggregating
uint64_t xtb_pool_instance_id() {
  return reinterpret_cast<uint64_t>(&XtbThreadPool::Get());
}
const char* xtb_pool_kernel_name(int k) { return xtb_kernel_name_impl(k); }
// SIMD level control (native/xtb_simd.h): kernel output is bitwise
// level-independent, so these only pick which (identical) body runs.
// lvl: -1 auto (best detected), 0 scalar, 1 avx2, 2 neon; unsupported
// requests resolve to the detected level.  Returns the effective level.
int xtb_simd_set(int lvl) { return xtb_simd_set_impl(lvl); }
int xtb_simd_get() { return xtb_simd_active(); }
int xtb_simd_detected() { return xtb_simd_detect_impl(); }
int xtb_simd_lanes() { return xtb_simd_lanes_impl(xtb_simd_active()); }
const char* xtb_simd_name(int lvl) { return xtb_simd_level_name_impl(lvl); }
// out: [regions, busy_ns, bucket_0 .. bucket_10] (13 int64 slots)
void xtb_pool_kernel_stats(int kernel, int64_t* out) {
  const XtbKernelStats& s = XtbThreadPool::Get().stats(kernel);
  out[0] = s.regions.load();
  out[1] = s.busy_ns.load();
  for (int i = 0; i <= kXtbPoolBuckets; ++i) out[2 + i] = s.bucket[i].load();
}
// out: [invocations, wall_ns, cycles, bytes, flops] (5 int64 slots) —
// whole-invocation perf accounting (XtbKernelPerf; includes inline
// executions the region stats above never see)
void xtb_pool_kernel_perf(int kernel, int64_t* out) {
  const XtbKernelStats& s = XtbThreadPool::Get().stats(kernel);
  out[0] = s.invocations.load();
  out[1] = s.wall_ns.load();
  out[2] = s.cycles.load();
  out[3] = s.bytes.load();
  out[4] = s.flops.load();
}
// STREAM triad peak-bandwidth probe (scripts/bench_roofline.py)
void xtb_stream_triad(const float* b, const float* c, float s, float* a,
                      int64_t n) {
  xtb_stream_triad_impl(b, c, s, a, n);
}
}  // extern "C"
#endif  // XTB_DEFINE_POOL_ABI

// ---------------------------------------------------------------------------
// Gradient histogram build — one pass over all rows; each row's F adds land
// in its node's block (F*n_bin*C floats, cache-resident at bench shapes).
// stride=2 selects left children only (heap offsets 2j) for the subtraction
// trick; pos ids outside [node0, node0+stride*n_nodes) add nothing; a bin
// value >= n_bin is the missing sentinel.
//
// Threading: FEATURE-sharded.  Each shard sweeps all R rows but touches only
// its feature columns, so per output element (n, f, b, c) the f32 adds
// happen in global row order — bitwise identical to the sequential kernel
// (and to the XLA scatter formulation the parity tests pin) for EVERY
// nthread.  Row-sharded partial accumulators would be deterministic per
// thread count but not nthread-invariant: f32 partial-sum merges reassociate
// the adds.  The per-shard repeat of the pos decode is ~6 ops/row —
// negligible against the F/S adds it amortises.
//
// Vectorization (xtb_simd.h xtb_hist_row8): the C==2 inner feature loop
// loads 8 contiguous bins at once and computes destination indices + the
// in-range mask in vector registers; the (g, h) adds stay scalar in lane
// (= feature) order, preserving the sequential add order per output
// element.  Engaged only while the whole level's histogram
// (n_nodes * F * B * C floats) fits ~L2 — beyond that the adds are
// memory-bound and index prep just adds overhead (measured).  The
// cache-blocking restructures suggested by Chen & Guestrin KDD'16 §4 were
// measured on this layout and REJECTED: both a row-tiled feature-outer
// variant (0.24-0.54x) and a feature-major page mirror (0.24-0.61x) lose
// to this row sweep, because the elementwise pos routing already streams
// every operand sequentially and consecutive rows of one feature column
// serialize on the same histogram bucket while the row sweep gets free ILP
// across F independent columns — see docs/perf_r7.md for the numbers.
// ---------------------------------------------------------------------------
constexpr size_t kXtbHistVecL2 = size_t{4} << 20;  // vec index-prep cutoff

template <typename BinT>
inline void xtb_hist_build_impl(const BinT* bins, const float* gpair,
                                const int32_t* pos, int64_t R, int32_t F,
                                int32_t n_bin, int32_t node0, int32_t n_nodes,
                                int32_t stride, int32_t C, float* out) {
  const size_t node_sz = static_cast<size_t>(F) * n_bin * C;
  const size_t col_sz = static_cast<size_t>(n_bin) * C;
  // bytes: per row one bin row (F*BinT) + gpair (C*4) + pos (4); hist
  // output written once and RFO-read (2x); flops: C adds per (row, feat)
  XtbKernelPerf perf(
      XTB_K_HIST,
      R * (F * static_cast<int64_t>(sizeof(BinT)) + 4 * C + 4) +
          2 * static_cast<int64_t>(n_nodes) * node_sz * 4,
      R * static_cast<int64_t>(F) * C);
  const bool vec_row = C == 2 && xtb_simd_active() != XTB_SIMD_SCALAR &&
                       n_nodes * node_sz * sizeof(float) <= kXtbHistVecL2;
  auto shard = [=](int64_t f0, int64_t f1) {
    for (int32_t nd = 0; nd < n_nodes; ++nd) {
      memset(out + nd * node_sz + f0 * col_sz, 0,
             (f1 - f0) * col_sz * sizeof(float));
    }
#if XTB_SIMD_X86
    if (vec_row) {
      xtb_hist_sweep_avx2(bins, gpair, pos, R, F, f0, f1, n_bin, node0,
                          n_nodes, stride, node_sz, out);
      return;
    }
#else
    (void)vec_row;
#endif
    for (int64_t r = 0; r < R; ++r) {
      int32_t node;
      if (!xtb_pos_node(pos[r], node0, stride, n_nodes, &node)) continue;
      const BinT* br = bins + r * F;
      float* ob = out + node * node_sz;
      if (C == 2) {
        const float g = gpair[r * 2], h = gpair[r * 2 + 1];
        for (int64_t f = f0; f < f1; ++f) {
          int32_t b = static_cast<int32_t>(br[f]);
          if (b < n_bin) {
            float* p = ob + (static_cast<size_t>(f) * n_bin + b) * 2;
            p[0] += g;
            p[1] += h;
          }
        }
      } else {
        const float* gr = gpair + r * C;
        for (int64_t f = f0; f < f1; ++f) {
          int32_t b = static_cast<int32_t>(br[f]);
          if (b < n_bin) {
            float* p = ob + (static_cast<size_t>(f) * n_bin + b) * C;
            for (int32_t c = 0; c < C; ++c) p[c] += gr[c];
          }
        }
      }
    }
  };
  xtb_parallel_for(F, 1, XTB_K_HIST, shard);
}

// ---------------------------------------------------------------------------
// Quantised limb-histogram build: int8 signed base-256 limbs accumulated in
// int32 (ops/quantise.py layout: values (R, C*3) with C=2 channels x 3
// limbs).  Integer sums are exact and associative, so ANY accumulation
// order yields identical bits; the kernel still feature-shards (same scheme
// as the f32 path, zero extra allocations) rather than row-sharding into
// partial buffers.
// ---------------------------------------------------------------------------
template <typename BinT>
inline void xtb_hist_q_impl(const BinT* bins, const int8_t* limbs,
                            const int32_t* pos, int64_t R, int32_t F,
                            int32_t n_bin, int32_t node0, int32_t n_nodes,
                            int32_t stride, int32_t CL, int32_t* out) {
  const size_t node_sz = static_cast<size_t>(F) * n_bin * CL;
  const size_t col_sz = static_cast<size_t>(n_bin) * CL;
  // bytes: bins row + int8 limbs (CL) + pos per row; int32 hist written
  // + RFO-read; "flops" here are exact int32 limb adds
  XtbKernelPerf perf(
      XTB_K_HIST_Q,
      R * (F * static_cast<int64_t>(sizeof(BinT)) + CL + 4) +
          2 * static_cast<int64_t>(n_nodes) * node_sz * 4,
      R * static_cast<int64_t>(F) * CL);
  auto shard = [=](int64_t f0, int64_t f1) {
    for (int32_t nd = 0; nd < n_nodes; ++nd) {
      memset(out + nd * node_sz + f0 * col_sz, 0,
             (f1 - f0) * col_sz * sizeof(int32_t));
    }
    for (int64_t r = 0; r < R; ++r) {
      int32_t node;
      if (!xtb_pos_node(pos[r], node0, stride, n_nodes, &node)) continue;
      const BinT* br = bins + r * F;
      const int8_t* lr = limbs + r * CL;
      int32_t* ob = out + node * node_sz;
      for (int64_t f = f0; f < f1; ++f) {
        int32_t b = static_cast<int32_t>(br[f]);
        if (b < n_bin) {
          int32_t* p = ob + (static_cast<size_t>(f) * n_bin + b) * CL;
          for (int32_t c = 0; c < CL; ++c) p[c] += lr[c];
        }
      }
    }
  };
  xtb_parallel_for(F, 1, XTB_K_HIST_Q, shard);
}

// ---------------------------------------------------------------------------
// Split gain scan (numeric features, no monotone constraints) — one bin pass
// per (node, feature) instead of the XLA formulation's ~15 materialized
// (N,F,B) temporaries.  Mirrors ops/split.py evaluate_splits exactly: both
// missing directions scored, first-occurrence argmax in (feature, bin)
// order, same f32 arithmetic.
//
// Threading: NODE-sharded — each node's scan is self-contained and writes
// only its own output slots, so results are bitwise-identical to the
// sequential scan for every nthread.
// ---------------------------------------------------------------------------
inline float xtb_thr_l1(float g, float alpha) {
  float a = fabsf(g) - alpha;
  if (a < 0.0f) a = 0.0f;
  return g < 0.0f ? -a : a;
}

struct XtbGainParams {
  float lambda_, alpha, min_child_weight, max_delta_step;
};

inline float xtb_calc_gain(float G, float H, const XtbGainParams& p) {
  if (H <= 0.0f) return 0.0f;
  float t = xtb_thr_l1(G, p.alpha);
  if (p.max_delta_step == 0.0f) return t * t / (H + p.lambda_);
  float w = -t / (H + p.lambda_);
  if (w > p.max_delta_step) w = p.max_delta_step;
  if (w < -p.max_delta_step) w = -p.max_delta_step;
  return -(2.0f * t * w + (H + p.lambda_) * w * w);
}

inline void xtb_split_scan_impl(const float* hist, const float* totals,
                                const int32_t* n_bins, const uint8_t* fmask,
                                int32_t N, int32_t F, int32_t B, float lambda_,
                                float alpha, float min_child_weight,
                                float max_delta_step, float* out_gain,
                                int32_t* out_feat, int32_t* out_bin,
                                uint8_t* out_dleft, float* out_GL,
                                float* out_HL) {
  const float kEps = 1e-6f;
  const XtbGainParams p{lambda_, alpha, min_child_weight, max_delta_step};
  // bytes: the (N, F, B, 2) f32 histogram read once + small per-node
  // outputs; flops: ~24 per (node, feature, bin) — prefix adds + both
  // missing-direction gain evaluations
  XtbKernelPerf perf(
      XTB_K_SPLIT,
      static_cast<int64_t>(N) * F * B * 8 + static_cast<int64_t>(N) * 21,
      static_cast<int64_t>(N) * F * B * 24);
  // max_delta_step == 0 (the default) takes the vectorized candidate
  // evaluation: the glr/hlr prefix chains stay serial (the f32 adds keep
  // their sequential order), only the per-bin ELEMENTWISE gain math runs 8
  // bins at a time (xtb_simd.h xtb_split_eval) — per-lane IEEE-identical
  // to the scalar transcription, so scalar and vector builds match bitwise.
  // A scalar-level run keeps the original fused loop below: the buffered
  // two-pass shape only pays when a vector body consumes the buffers.
  const bool vec_eval =
      max_delta_step == 0.0f && xtb_simd_active() != XTB_SIMD_SCALAR;
  auto shard = [=](int64_t lo, int64_t hi) {
  static thread_local std::vector<float> glr_buf, hlr_buf, g2_buf, GLb, HLb;
  static thread_local std::vector<uint8_t> ok_buf, dl_buf;
  if (vec_eval) {
    glr_buf.resize(B);
    hlr_buf.resize(B);
    g2_buf.resize(B);
    GLb.resize(B);
    HLb.resize(B);
    ok_buf.resize(B);
    dl_buf.resize(B);
  }
  for (int32_t n = static_cast<int32_t>(lo); n < hi; ++n) {
    const float totG = totals[n * 2], totH = totals[n * 2 + 1];
    if (totG == 0.0f && totH == 0.0f) {
      // dead heap slot (padded shared level program): its histogram is
      // zeroed by construction (combine_sibling_hists masks non-alive
      // slots; the hist kernels memset), every candidate is invalid, and
      // the XLA all--inf fallback lands at (feature 0, bin 0) with zero
      // sums — emit that directly instead of walking F*B bins, so node
      // padding costs nothing in the scan
      out_gain[n] = -INFINITY;
      out_feat[n] = 0;
      out_bin[n] = 0;
      out_dleft[n] = 1;
      out_GL[n] = 0.0f;
      out_HL[n] = 0.0f;
      continue;
    }
    const float parent = xtb_calc_gain(totG, totH, p);
    float best_gain = -INFINITY, best_GL = 0.0f, best_HL = 0.0f;
    int32_t best_f = 0, best_b = 0;
    bool best_dl = true, any = false;
    for (int32_t f = 0; f < F; ++f) {
      if (!fmask[n * F + f]) continue;
      const int32_t nb = n_bins[f];
      const float* hf = hist + (static_cast<size_t>(n) * F + f) * B * 2;
      float gsum = 0.0f, hsum = 0.0f;
      for (int32_t b = 0; b < B; ++b) {
        gsum += hf[2 * b];
        hsum += hf[2 * b + 1];
      }
      const float missG = totG - gsum, missH = totH - hsum;
      const bool has_miss = fabsf(missH) > kEps;
      const int32_t bmax = nb < B ? nb : B;
      if (vec_eval) {
        float glr_acc = 0.0f, hlr_acc = 0.0f;
        for (int32_t b = 0; b < bmax; ++b) {  // serial prefix, exact order
          glr_acc += hf[2 * b];
          hlr_acc += hf[2 * b + 1];
          glr_buf[b] = glr_acc;
          hlr_buf[b] = hlr_acc;
          ok_buf[b] = (b < nb - 1) || (b == nb - 1 && has_miss) ? 1 : 0;
        }
        const XtbSplitEvalArgs a{totG, totH, missG, missH, parent,
                                 lambda_, alpha, min_child_weight};
        xtb_split_eval(glr_buf.data(), hlr_buf.data(), ok_buf.data(), bmax,
                       a, g2_buf.data(), dl_buf.data(), GLb.data(),
                       HLb.data());
        for (int32_t b = 0; b < bmax; ++b) {
          if (g2_buf[b] > best_gain) {
            best_gain = g2_buf[b];
            best_f = f;
            best_b = b;
            best_dl = dl_buf[b] != 0;
            best_GL = GLb[b];
            best_HL = HLb[b];
            any = true;
          }
        }
        continue;
      }
      float glr = 0.0f, hlr = 0.0f;
      for (int32_t b = 0; b < bmax; ++b) {
        glr += hf[2 * b];
        hlr += hf[2 * b + 1];
        const bool ok = (b < nb - 1) || (b == nb - 1 && has_miss);
        if (!ok) continue;
        float g2 = -INFINITY;
        bool dl2 = true;
        {  // missing -> right
          const float GR = totG - glr, HR = totH - hlr;
          if (hlr >= min_child_weight && HR >= min_child_weight &&
              hlr > 0.0f && HR > 0.0f) {
            g2 = xtb_calc_gain(glr, hlr, p) + xtb_calc_gain(GR, HR, p) -
                 parent;
            dl2 = false;
          }
        }
        const float gll = glr + missG, hll = hlr + missH;
        {  // missing -> left
          const float GR = totG - gll, HR = totH - hll;
          if (hll >= min_child_weight && HR >= min_child_weight &&
              hll > 0.0f && HR > 0.0f) {
            const float gl_gain = xtb_calc_gain(gll, hll, p) +
                                  xtb_calc_gain(GR, HR, p) - parent;
            if (gl_gain >= g2) {
              g2 = gl_gain;
              dl2 = true;
            }
          }
        }
        if (g2 > best_gain) {
          best_gain = g2;
          best_f = f;
          best_b = b;
          best_dl = dl2;
          best_GL = dl2 ? gll : glr;
          best_HL = dl2 ? hll : hlr;
          any = true;
        }
      }
    }
    if (!any) {
      // match the XLA argmax over an all -inf tensor: flat index 0 ->
      // (feature 0, bin 0), missing -> left
      const float* h0 = hist + static_cast<size_t>(n) * F * B * 2;
      float gsum = 0.0f, hsum = 0.0f;
      for (int32_t b = 0; b < B; ++b) {
        gsum += h0[2 * b];
        hsum += h0[2 * b + 1];
      }
      best_GL = h0[0] + (totG - gsum);
      best_HL = h0[1] + (totH - hsum);
    }
    out_gain[n] = best_gain;
    out_feat[n] = best_f;
    out_bin[n] = best_b;
    out_dleft[n] = best_dl ? 1 : 0;
    out_GL[n] = best_GL;
    out_HL[n] = best_HL;
  }
  };
  xtb_parallel_for(N, 1, XTB_K_SPLIT, shard);
}

// ---------------------------------------------------------------------------
// Ensemble margin prediction — rows outer, trees inner, so each row's f32
// adds happen in tree order (bitwise-identical to the XLA scan in
// ops/predict.py, which the prediction-cache continuation contract relies
// on) and each X row is read once while the small tree arrays stay hot.
// Mirrors ops/predict.py predict_margin_delta semantics exactly: fixed
// `depth` steps with stick-at-leaf, NaN -> default-left, categorical
// in-set -> right.  K_leaf == 1 adds the scalar leaf to column groups[t];
// K_leaf > 1 adds the leaf vector to all K columns (multi-target trees).
//
// Threading: ROW-block sharded — rows are independent and each shard owns
// its init memcpy + output rows, so every nthread is bitwise-identical.
//
// Vector path (numeric scalar-leaf ensembles): eight rows ride the AVX2
// lanes through one tree at a time (xtb_simd.h xtb_predict_raw_rows_avx2);
// per row the leaf adds still land in tree order, so lane-parallel ==
// scalar bitwise.  Categorical / vector-leaf ensembles and shard tails
// keep the scalar walk.
// ---------------------------------------------------------------------------
inline void xtb_predict_raw_impl(
    const float* X, int64_t R, int32_t F, const int32_t* feat,
    const float* thr, const uint8_t* dleft, const int32_t* left,
    const int32_t* right, const float* value, const int32_t* groups,
    int32_t T, int32_t M, int32_t depth, int32_t K, int32_t K_leaf,
    int32_t has_cat, const uint8_t* is_cat, const uint8_t* catm, int32_t Bc,
    const float* init, float* out) {
  // bytes: X streamed once, init read + out written (+RFO), the node
  // arrays (~21 B/node) read once; flops: one compare per level walked
  // plus K_leaf leaf adds, per (row, tree)
  XtbKernelPerf perf(
      XTB_K_PREDICT,
      static_cast<int64_t>(R) * F * 4 + static_cast<int64_t>(R) * K * 12 +
          static_cast<int64_t>(T) * M * 21,
      static_cast<int64_t>(R) * T * (depth + K_leaf));
  // the byte-wide dleft array is gathered with 32-bit reads on the vector
  // path; copy it into a 4-byte-padded scratch once per call
  std::shared_ptr<std::vector<uint8_t>> dl_pad;
  const bool vec_ok =
      xtb_simd_active() == XTB_SIMD_AVX2 && K_leaf == 1 && !has_cat &&
      R >= 16 &&
      static_cast<int64_t>(R) * F + F < (int64_t{1} << 31);
  if (vec_ok) {
    dl_pad = std::make_shared<std::vector<uint8_t>>(
        static_cast<size_t>(T) * M + 4);
    memcpy(dl_pad->data(), dleft, static_cast<size_t>(T) * M);
  }
  auto shard = [=](int64_t r0, int64_t r1) {
    memcpy(out + r0 * K, init + r0 * K,
           static_cast<size_t>(r1 - r0) * K * sizeof(float));
    int64_t done = 0;
#if XTB_SIMD_X86
    if (vec_ok && xtb_simd_active() == XTB_SIMD_AVX2) {
      done = xtb_predict_raw_rows_avx2(X, r0, r1, F, feat, thr,
                                       dl_pad->data(), left, right, value,
                                       groups, T, M, depth, K, out);
    }
#endif
    for (int64_t r = r0 + done; r < r1; ++r) {
      const float* xr = X + r * F;
      float* orow = out + r * K;
      for (int32_t t = 0; t < T; ++t) {
        const size_t base = static_cast<size_t>(t) * M;
        int32_t nid = 0;
        for (int32_t d = 0; d < depth; ++d) {
          const int32_t fi = feat[base + nid];
          if (fi < 0) break;
          const float x = xr[fi];
          const bool miss = std::isnan(x);
          bool gol;
          if (has_cat && is_cat[base + nid]) {
            const int32_t c = miss ? -1 : static_cast<int32_t>(x);
            const bool member =
                c >= 0 && c < Bc && catm[(base + nid) * Bc + c];
            gol = miss ? (dleft[base + nid] != 0) : !member;
          } else {
            gol = miss ? (dleft[base + nid] != 0) : (x < thr[base + nid]);
          }
          nid = gol ? left[base + nid] : right[base + nid];
        }
        if (K_leaf == 1) {
          orow[groups[t]] += value[base + nid];
        } else {
          const float* v = value + (base + nid) * K_leaf;
          for (int32_t k = 0; k < K_leaf; ++k) orow[k] += v[k];
        }
      }
    }
  };
  xtb_parallel_for(R, 256, XTB_K_PREDICT, shard);
}

// Binned variant (split_bins routing over an Ellpack page; sentinel
// b >= n_bin = missing) — ops/predict.py predict_margin_delta_binned.
// Same lane-per-row vector path as the raw kernel; sub-word bin gathers
// read up to 3 bytes past the addressed element, so the final 16 rows of
// the page always take the scalar walk (interior rows have the next row's
// bytes as slack).
template <typename BinT>
inline void xtb_predict_binned_impl(
    const BinT* bins, int64_t R, int32_t F, int32_t n_bin,
    const int32_t* feat, const int32_t* sbin, const uint8_t* dleft,
    const int32_t* left, const int32_t* right, const float* value,
    const int32_t* groups, int32_t T, int32_t M, int32_t depth, int32_t K,
    int32_t has_cat, const uint8_t* is_cat, const uint8_t* catm, int32_t Bc,
    const float* init, float* out) {
  // same model as the f32 walk with BinT-wide rows (binned ensembles are
  // scalar-leaf: one add per tree)
  XtbKernelPerf perf(
      XTB_K_PREDICT,
      static_cast<int64_t>(R) * F * static_cast<int64_t>(sizeof(BinT)) +
          static_cast<int64_t>(R) * K * 12 +
          static_cast<int64_t>(T) * M * 21,
      static_cast<int64_t>(R) * T * (depth + 1));
  std::shared_ptr<std::vector<uint8_t>> dl_pad;
  const bool vec_ok =
      xtb_simd_active() == XTB_SIMD_AVX2 && !has_cat && R >= 16 &&
      static_cast<int64_t>(R) * F * static_cast<int64_t>(sizeof(BinT)) +
              4 * sizeof(BinT) < (int64_t{1} << 31);
  if (vec_ok) {
    dl_pad = std::make_shared<std::vector<uint8_t>>(
        static_cast<size_t>(T) * M + 4);
    memcpy(dl_pad->data(), dleft, static_cast<size_t>(T) * M);
  }
  const int64_t r_vec_end = sizeof(BinT) == 4 ? R : std::max<int64_t>(R - 16, 0);
  auto shard = [=](int64_t r0, int64_t r1) {
    memcpy(out + r0 * K, init + r0 * K,
           static_cast<size_t>(r1 - r0) * K * sizeof(float));
    int64_t done = 0;
#if XTB_SIMD_X86
    if (vec_ok && xtb_simd_active() == XTB_SIMD_AVX2) {
      const int64_t vend = std::min(r1, r_vec_end);
      if (sizeof(BinT) == 1) {
        done = xtb_predict_binned_rows_avx2<1, 0xFF>(
            bins, r0, vend, F, n_bin, feat, sbin, dl_pad->data(), left,
            right, value, groups, T, M, depth, K, out);
      } else if (sizeof(BinT) == 2) {
        done = xtb_predict_binned_rows_avx2<2, 0xFFFF>(
            bins, r0, vend, F, n_bin, feat, sbin, dl_pad->data(), left,
            right, value, groups, T, M, depth, K, out);
      } else {
        done = xtb_predict_binned_rows_avx2<4, -1>(
            bins, r0, vend, F, n_bin, feat, sbin, dl_pad->data(), left,
            right, value, groups, T, M, depth, K, out);
      }
    }
#endif
    for (int64_t r = r0 + done; r < r1; ++r) {
      const BinT* br = bins + r * F;
      float* orow = out + r * K;
      for (int32_t t = 0; t < T; ++t) {
        const size_t base = static_cast<size_t>(t) * M;
        int32_t nid = 0;
        for (int32_t d = 0; d < depth; ++d) {
          const int32_t fi = feat[base + nid];
          if (fi < 0) break;
          const int32_t b = static_cast<int32_t>(br[fi]);
          bool gol;
          if (has_cat && is_cat[base + nid]) {
            gol = !(b < Bc && catm[(base + nid) * Bc + b]);
          } else {
            gol = b <= sbin[base + nid];
          }
          if (b >= n_bin) gol = dleft[base + nid] != 0;
          nid = gol ? left[base + nid] : right[base + nid];
        }
        orow[groups[t]] += value[base + nid];
      }
    }
  };
  xtb_parallel_for(R, 256, XTB_K_PREDICT, shard);
}

// ---------------------------------------------------------------------------
// LambdaMART top-k pair gradients (the reference's default pair method,
// lambdarank_obj.h MakePairs truncation branch + LambdaGrad:91).  Works
// directly on CSR query groups — no padded (G, k, S) pair tensors, so the
// CPU path skips the XLA formulation's hundreds of MB of masked
// intermediates per round.  Semantics mirror ops side-by-side
// (_lambda_gradients_topk in objective/ranking.py): stable sort by
// descending score, each of the top-k ranked docs pairs with every doc
// ranked below it, |delta ndcg|/idcg pair weight, optional score-diff
// normalization (skipped while all scores in the group are equal),
// hessian doubled, per-group log2(1+sum_lambda)/sum_lambda rescale.
//
// Threading: GROUP-sharded — each query group's gradient rows are exclusive
// to it (CSR), so shards write disjoint slices and every nthread is
// bitwise-identical to the sequential pass.
// ---------------------------------------------------------------------------
inline void xtb_lambdarank_topk_impl(
    const float* s, const float* y, const int32_t* gptr, int32_t n_groups,
    int64_t R, int32_t k, int32_t ndcg_weight, int32_t score_norm,
    int32_t group_norm, float* out_grad, float* out_hess) {
  memset(out_grad, 0, R * sizeof(float));
  memset(out_hess, 0, R * sizeof(float));
  auto shard = [=](int64_t glo, int64_t ghi) {
  std::vector<int32_t> order;
  std::vector<float> gain, disc, lam_acc, hess_acc;
  for (int32_t g = static_cast<int32_t>(glo); g < ghi; ++g) {
    const int32_t lo = gptr[g], hi = gptr[g + 1];
    const int32_t n = hi - lo;
    if (n <= 1) continue;
    order.resize(n);
    for (int32_t i = 0; i < n; ++i) order[i] = lo + i;
    std::stable_sort(order.begin(), order.end(),
                     [&](int32_t a, int32_t b) { return s[a] > s[b]; });
    gain.resize(n);
    disc.resize(n);
    for (int32_t i = 0; i < n; ++i) {
      gain[i] = exp2f(y[order[i]]) - 1.0f;
      disc[i] = 1.0f / log2f(2.0f + static_cast<float>(i));
    }
    // idcg over gains sorted descending
    std::vector<float> ideal(gain);
    std::sort(ideal.begin(), ideal.end(), std::greater<float>());
    float idcg = 0.0f;
    for (int32_t i = 0; i < n; ++i) idcg += ideal[i] * disc[i];
    if (idcg < 1e-10f) idcg = 1e-10f;
    const bool spread = s[order[0]] != s[order[n - 1]];

    lam_acc.assign(n, 0.0f);
    hess_acc.assign(n, 0.0f);
    float sum_lambda = 0.0f;
    const int32_t kk = k < n ? k : n;
    for (int32_t i = 0; i < kk; ++i) {
      const float si = s[order[i]], gi = gain[i];
      for (int32_t j = i + 1; j < n; ++j) {
        const float gj = gain[j];
        if (gi == gj) continue;
        const bool high_is_i = gi > gj;
        const float s_high = high_is_i ? si : s[order[j]];
        const float s_low = high_is_i ? s[order[j]] : si;
        const float sig = 1.0f / (1.0f + expf(-(s_high - s_low)));
        float delta = 1.0f;
        if (ndcg_weight) {
          delta = fabsf((gi - gj) * (disc[i] - disc[j])) / idcg;
        }
        if (score_norm && spread) {
          delta = delta / (fabsf(s_high - s_low) + 0.01f);
        }
        const float lam = (sig - 1.0f) * delta;  // high doc's gradient
        float h = sig * (1.0f - sig) * delta;
        if (h < 1e-16f) h = 1e-16f;
        h *= 2.0f;
        const float sgn_i = high_is_i ? 1.0f : -1.0f;
        lam_acc[i] += lam * sgn_i;
        lam_acc[j] -= lam * sgn_i;
        hess_acc[i] += h;
        hess_acc[j] += h;
        sum_lambda += -2.0f * lam;
      }
    }
    float norm = 1.0f;
    if (group_norm && sum_lambda > 0.0f) {
      float d = sum_lambda > 1e-16f ? sum_lambda : 1e-16f;
      norm = log2f(1.0f + sum_lambda) / d;
    }
    for (int32_t i = 0; i < n; ++i) {
      out_grad[order[i]] = lam_acc[i] * norm;
      out_hess[order[i]] = hess_acc[i] * norm;
    }
  }
  };
  xtb_parallel_for(n_groups, 4, XTB_K_LAMBDARANK, shard);
}

// ---------------------------------------------------------------------------
// Exact path-dependent TreeSHAP (Lundberg 2018) — the native twin of the
// host walk in interpret/__init__.py (_extend/_unwind/_unwound_sum), all-f64
// with identical operation order so the two implementations agree to the
// last ulp (the Makefile compiles with -ffp-contract=off to keep FMA
// contraction from reassociating on wider ISAs).  Scalar-leaf numeric trees
// only; categorical routing stays on the Python walk.
//
// Threading: ROW-sharded — each row's recursion is independent and writes
// its own (F+1) output slice, so every nthread is bitwise-identical.
// ---------------------------------------------------------------------------
struct XtbShapTree {
  const int32_t* left;
  const int32_t* right;
  const int32_t* feat;
  const double* thr;
  const uint8_t* dleft;
  const double* value;  // leaf value at leaves, 0 elsewhere
  const double* cover;  // sum_hessian clamped >= 1e-16
};

struct XtbShapScratch {
  // one path buffer per recursion level; level l copies level l-1 on entry
  std::vector<int32_t> feat;
  std::vector<double> zero, one, pw;
  int cap;  // entries per level

  explicit XtbShapScratch(int max_depth) : cap(max_depth + 3) {
    const int levels = max_depth + 3;
    feat.assign(static_cast<size_t>(levels) * cap, -1);
    zero.assign(static_cast<size_t>(levels) * cap, 0.0);
    one.assign(static_cast<size_t>(levels) * cap, 0.0);
    pw.assign(static_cast<size_t>(levels) * cap, 0.0);
  }
};

inline int xtb_shap_extend(int32_t* feat, double* zero, double* one,
                           double* pw, int length, double pz, double po,
                           int32_t pi) {
  feat[length] = pi;
  zero[length] = pz;
  one[length] = po;
  pw[length] = length == 0 ? 1.0 : 0.0;
  for (int i = length - 1; i >= 0; --i) {
    pw[i + 1] += po * pw[i] * (i + 1) / (length + 1);
    pw[i] = pz * pw[i] * (length - i) / (length + 1);
  }
  return length + 1;
}

inline int xtb_shap_unwind(int32_t* feat, double* zero, double* one,
                           double* pw, int length, int i) {
  length -= 1;
  const double po = one[i], pz = zero[i];
  double n = pw[length];
  for (int j = length - 1; j >= 0; --j) {
    if (po != 0.0) {
      double t = pw[j];
      pw[j] = n * (length + 1) / ((j + 1) * po);
      n = t - pw[j] * pz * (length - j) / (length + 1);
    } else {
      pw[j] = pw[j] * (length + 1) / (pz * (length - j));
    }
  }
  for (int j = i; j < length; ++j) {
    feat[j] = feat[j + 1];
    zero[j] = zero[j + 1];
    one[j] = one[j + 1];
  }
  return length;
}

inline double xtb_shap_unwound_sum(const double* zero, const double* one,
                                   const double* pw, int length, int i) {
  const double po = one[i], pz = zero[i];
  double total = 0.0;
  double n = pw[length - 1];
  for (int j = length - 2; j >= 0; --j) {
    if (po != 0.0) {
      double t = n * length / ((j + 1) * po);
      total += t;
      n = pw[j] - t * pz * (length - 1 - j) / length;
    } else {
      total += pw[j] * length / (pz * (length - 1 - j));
    }
  }
  return total;
}

inline void xtb_shap_recurse(const XtbShapTree& t, const double* x,
                             double* phi, int node, XtbShapScratch& s,
                             int level, int length, double pz, double po,
                             int32_t pi) {
  // copy the parent path into this level's buffer, then extend
  int32_t* feat = s.feat.data() + static_cast<size_t>(level) * s.cap;
  double* zero = s.zero.data() + static_cast<size_t>(level) * s.cap;
  double* one = s.one.data() + static_cast<size_t>(level) * s.cap;
  double* pw = s.pw.data() + static_cast<size_t>(level) * s.cap;
  if (level > 0) {
    const size_t off = static_cast<size_t>(level - 1) * s.cap;
    memcpy(feat, s.feat.data() + off, length * sizeof(int32_t));
    memcpy(zero, s.zero.data() + off, length * sizeof(double));
    memcpy(one, s.one.data() + off, length * sizeof(double));
    memcpy(pw, s.pw.data() + off, length * sizeof(double));
  }
  length = xtb_shap_extend(feat, zero, one, pw, length, pz, po, pi);
  const int32_t left = t.left[node], right = t.right[node];
  if (left < 0) {  // leaf
    const double v = t.value[node];
    for (int i = 1; i < length; ++i) {
      const double w = xtb_shap_unwound_sum(zero, one, pw, length, i);
      phi[feat[i]] += w * (one[i] - zero[i]) * v;
    }
    return;
  }
  const int32_t f = t.feat[node];
  const double xv = x[f];
  const bool miss = std::isnan(xv);
  const bool go_left = miss ? (t.dleft[node] != 0) : (xv < t.thr[node]);
  const int32_t hot = go_left ? left : right;
  const int32_t cold = go_left ? right : left;
  const double rj = t.cover[node];
  const double rh = t.cover[hot], rc = t.cover[cold];
  double iz = 1.0, io = 1.0;
  // if this feature is already on the path, undo its previous contribution
  int k = -1;
  for (int i = 1; i < length; ++i) {
    if (feat[i] == f) {
      k = i;
      break;
    }
  }
  if (k >= 0) {
    iz = zero[k];
    io = one[k];
    length = xtb_shap_unwind(feat, zero, one, pw, length, k);
  }
  xtb_shap_recurse(t, x, phi, hot, s, level + 1, length, iz * rh / rj, io, f);
  xtb_shap_recurse(t, x, phi, cold, s, level + 1, length, iz * rc / rj, 0.0,
                   f);
}

// out: (R, F+1) f64, feature columns accumulated in place (callers zero it
// and fill the bias column F with the tree expectation themselves, exactly
// like the Python walk).
inline void xtb_shap_values_impl(const double* X, int64_t R, int32_t F,
                                 const XtbShapTree& t, int32_t max_depth,
                                 double* out) {
  if (t.left[0] < 0) return;  // stump: all mass at the bias column
  auto shard = [=](int64_t r0, int64_t r1) {
    XtbShapScratch scratch(max_depth);
    for (int64_t r = r0; r < r1; ++r) {
      xtb_shap_recurse(t, X + r * F, out + r * (F + 1), 0, scratch, 0, 0,
                       1.0, 1.0, -1);
    }
  };
  xtb_parallel_for(R, 16, XTB_K_SHAP, shard);
}

// ---------------------------------------------------------------------------
// Ellpack page ingestion: bin a dense (R, F) f32 matrix against per-feature
// quantile cuts into local bin indices (data/ellpack.py build_ellpack's
// native fast path).  Semantics are EXACTLY the XLA formulation it replaces:
// bin = upper_bound(cuts_f, v) (== searchsorted side='right'), clamped into
// the top bin, NaN -> sentinel B.  The sweep is row-major — X is streamed
// once, sequentially, and the page is written sequentially, the
// prefetch-friendly layout the blocked hist kernels then consume.
//
// Threading: ROW-sharded — outputs are disjoint row slices and bin indices
// are integers, so every nthread (and ISA) is bitwise-identical.
// ---------------------------------------------------------------------------
template <typename BinT>
inline void xtb_ellpack_bin_impl(const float* X, int64_t R, int32_t F,
                                 const float* cut_values,
                                 const int32_t* cut_ptrs, int32_t B,
                                 BinT* out) {
  // bytes: X streamed once, page written (+RFO); flops: ~log2(B)
  // binary-search compares per element (5 covers max_bin 256 halvings
  // of the typical per-feature cut count)
  XtbKernelPerf perf(
      XTB_K_ELLPACK,
      static_cast<int64_t>(R) * F *
          (4 + 2 * static_cast<int64_t>(sizeof(BinT))),
      static_cast<int64_t>(R) * F * 5);
  auto shard = [=](int64_t r0, int64_t r1) {
    for (int64_t r = r0; r < r1; ++r) {
      const float* xr = X + r * F;
      BinT* orow = out + r * F;
      for (int32_t f = 0; f < F; ++f) {
        const float v = xr[f];
        if (std::isnan(v)) {
          orow[f] = static_cast<BinT>(B);
          continue;
        }
        const float* seg = cut_values + cut_ptrs[f];
        const int32_t nb = cut_ptrs[f + 1] - cut_ptrs[f];
        int32_t b = static_cast<int32_t>(
            std::upper_bound(seg, seg + nb, v) - seg);
        if (b > nb - 1) b = nb - 1;
        orow[f] = static_cast<BinT>(b);
      }
    }
  };
  xtb_parallel_for(R, 512, XTB_K_ELLPACK, shard);
}

// ---------------------------------------------------------------------------
// Sub-byte (4-bit) packed histogram — BENCH-ONLY kernel backing the
// docs/bitpack.md re-measurement (scripts/bitpack_bench.py --native): bins
// packed two per byte in (R, ceil(F/2)) u8, unpacked on the fly with the
// same vector gather the resident-u8 blocked path uses plus a shift/mask
// (the `vpgatherdd`-era roofline question the scalar 2026-07 measurement
// could not answer).  C == 2 only; NOT wired into training — adoption is
// decided by the bench numbers, see docs/bitpack.md.
// ---------------------------------------------------------------------------
inline void xtb_hist_packed4_impl(const uint8_t* packed, const float* gpair,
                                  const int32_t* pos, int64_t R, int32_t F,
                                  int32_t n_bin, int32_t node0,
                                  int32_t n_nodes, int32_t stride,
                                  float* out) {
  const int32_t Fp = (F + 1) / 2;  // bytes per packed row
  const size_t node_sz = static_cast<size_t>(F) * n_bin * 2;
  const size_t col_sz = static_cast<size_t>(n_bin) * 2;
  const bool vec_row = xtb_simd_active() != XTB_SIMD_SCALAR &&
                       n_nodes * node_sz * sizeof(float) <= kXtbHistVecL2;
  auto shard = [=](int64_t fp0, int64_t fp1) {
    // shard over packed BYTES so every shard starts nibble-aligned
    const int64_t f0 = fp0 * 2;
    const int64_t f1 = std::min<int64_t>(fp1 * 2, F);
    for (int32_t nd = 0; nd < n_nodes; ++nd) {
      memset(out + nd * node_sz + f0 * col_sz, 0,
             (f1 - f0) * col_sz * sizeof(float));
    }
#if XTB_SIMD_X86
    if (vec_row) {
      xtb_hist_sweep_p4_avx2(packed, gpair, pos, R, F, f0, f1, n_bin, node0,
                             n_nodes, stride, node_sz, out);
      return;
    }
#else
    (void)vec_row;
#endif
    for (int64_t r = 0; r < R; ++r) {
      int32_t node;
      if (!xtb_pos_node(pos[r], node0, stride, n_nodes, &node)) continue;
      const uint8_t* br = packed + r * Fp;
      float* ob = out + node * node_sz;
      const float g = gpair[r * 2], h = gpair[r * 2 + 1];
      for (int64_t f = f0; f < f1; ++f) {
        const int32_t b = (br[f >> 1] >> ((f & 1) * 4)) & 0xF;
        if (b < n_bin) {
          float* p = ob + (static_cast<size_t>(f) * n_bin + b) * 2;
          p[0] += g;
          p[1] += h;
        }
      }
    }
  };
  xtb_parallel_for(Fp, 1, XTB_K_HIST, shard);
}

#endif  // XTB_KERNELS_H_
