// Shared host-kernel bodies for xgboost_tpu's native runtime.
//
// Included by xtb_native.cc (plain C ABI for ctypes consumers and tests)
// and xtb_ffi.cc (XLA FFI handlers — the zero-copy path the jitted CPU
// training programs call).  Role analogue of the reference's CPU hist
// updater hot loops (src/common/hist_util.cc BuildHist,
// src/tree/hist/evaluate_splits.h EnumerateSplit), re-designed around the
// elementwise `pos` row routing used by the JAX growers instead of the
// reference's physical row partitions.
#ifndef XTB_KERNELS_H_
#define XTB_KERNELS_H_

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

// ---------------------------------------------------------------------------
// Gradient histogram build — one pass over all rows; each row's F adds land
// in its node's block (F*n_bin*C floats, cache-resident at bench shapes).
// stride=2 selects left children only (heap offsets 2j) for the subtraction
// trick; pos ids outside [node0, node0+stride*n_nodes) add nothing; a bin
// value >= n_bin is the missing sentinel.  Sequential row order ->
// deterministic within a topology (same contract as the XLA scatter path).
// ---------------------------------------------------------------------------
template <typename BinT>
inline void xtb_hist_build_impl(const BinT* bins, const float* gpair,
                                const int32_t* pos, int64_t R, int32_t F,
                                int32_t n_bin, int32_t node0, int32_t n_nodes,
                                int32_t stride, int32_t C, float* out) {
  const size_t node_sz = static_cast<size_t>(F) * n_bin * C;
  memset(out, 0, n_nodes * node_sz * sizeof(float));
  for (int64_t r = 0; r < R; ++r) {
    int32_t local = pos[r] - node0;
    if (local < 0) continue;
    int32_t node;
    if (stride == 2) {
      if (local & 1) continue;
      node = local >> 1;
    } else if (stride == 1) {
      node = local;
    } else {
      if (local % stride != 0) continue;
      node = local / stride;
    }
    if (node >= n_nodes) continue;
    const BinT* br = bins + r * F;
    float* ob = out + node * node_sz;
    if (C == 2) {
      const float g = gpair[r * 2], h = gpair[r * 2 + 1];
      for (int32_t f = 0; f < F; ++f) {
        int32_t b = static_cast<int32_t>(br[f]);
        if (b < n_bin) {
          float* p = ob + (static_cast<size_t>(f) * n_bin + b) * 2;
          p[0] += g;
          p[1] += h;
        }
      }
    } else {
      const float* gr = gpair + r * C;
      for (int32_t f = 0; f < F; ++f) {
        int32_t b = static_cast<int32_t>(br[f]);
        if (b < n_bin) {
          float* p = ob + (static_cast<size_t>(f) * n_bin + b) * C;
          for (int32_t c = 0; c < C; ++c) p[c] += gr[c];
        }
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Quantised limb-histogram build: int8 signed base-256 limbs accumulated in
// int32 (ops/quantise.py layout: values (R, C*3) with C=2 channels x 3
// limbs).  Integer sums are exact and associative, so ANY accumulation
// order yields identical bits — this kernel exists purely to give the
// deterministic_histogram contract the same row-pass speed as the f32
// path on CPU (the XLA int scatter it replaces is ~10x slower).
// ---------------------------------------------------------------------------
template <typename BinT>
inline void xtb_hist_q_impl(const BinT* bins, const int8_t* limbs,
                            const int32_t* pos, int64_t R, int32_t F,
                            int32_t n_bin, int32_t node0, int32_t n_nodes,
                            int32_t stride, int32_t CL, int32_t* out) {
  const size_t node_sz = static_cast<size_t>(F) * n_bin * CL;
  memset(out, 0, n_nodes * node_sz * sizeof(int32_t));
  for (int64_t r = 0; r < R; ++r) {
    int32_t local = pos[r] - node0;
    if (local < 0) continue;
    int32_t node;
    if (stride == 2) {
      if (local & 1) continue;
      node = local >> 1;
    } else if (stride == 1) {
      node = local;
    } else {
      if (local % stride != 0) continue;
      node = local / stride;
    }
    if (node >= n_nodes) continue;
    const BinT* br = bins + r * F;
    const int8_t* lr = limbs + r * CL;
    int32_t* ob = out + node * node_sz;
    for (int32_t f = 0; f < F; ++f) {
      int32_t b = static_cast<int32_t>(br[f]);
      if (b < n_bin) {
        int32_t* p = ob + (static_cast<size_t>(f) * n_bin + b) * CL;
        for (int32_t c = 0; c < CL; ++c) p[c] += lr[c];
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Split gain scan (numeric features, no monotone constraints) — one bin pass
// per (node, feature) instead of the XLA formulation's ~15 materialized
// (N,F,B) temporaries.  Mirrors ops/split.py evaluate_splits exactly: both
// missing directions scored, first-occurrence argmax in (feature, bin)
// order, same f32 arithmetic.
// ---------------------------------------------------------------------------
inline float xtb_thr_l1(float g, float alpha) {
  float a = fabsf(g) - alpha;
  if (a < 0.0f) a = 0.0f;
  return g < 0.0f ? -a : a;
}

struct XtbGainParams {
  float lambda_, alpha, min_child_weight, max_delta_step;
};

inline float xtb_calc_gain(float G, float H, const XtbGainParams& p) {
  if (H <= 0.0f) return 0.0f;
  float t = xtb_thr_l1(G, p.alpha);
  if (p.max_delta_step == 0.0f) return t * t / (H + p.lambda_);
  float w = -t / (H + p.lambda_);
  if (w > p.max_delta_step) w = p.max_delta_step;
  if (w < -p.max_delta_step) w = -p.max_delta_step;
  return -(2.0f * t * w + (H + p.lambda_) * w * w);
}

inline void xtb_split_scan_impl(const float* hist, const float* totals,
                                const int32_t* n_bins, const uint8_t* fmask,
                                int32_t N, int32_t F, int32_t B, float lambda_,
                                float alpha, float min_child_weight,
                                float max_delta_step, float* out_gain,
                                int32_t* out_feat, int32_t* out_bin,
                                uint8_t* out_dleft, float* out_GL,
                                float* out_HL) {
  const float kEps = 1e-6f;
  const XtbGainParams p{lambda_, alpha, min_child_weight, max_delta_step};
  for (int32_t n = 0; n < N; ++n) {
    const float totG = totals[n * 2], totH = totals[n * 2 + 1];
    if (totG == 0.0f && totH == 0.0f) {
      // dead heap slot (padded shared level program): its histogram is
      // zeroed by construction (combine_sibling_hists masks non-alive
      // slots; the hist kernels memset), every candidate is invalid, and
      // the XLA all--inf fallback lands at (feature 0, bin 0) with zero
      // sums — emit that directly instead of walking F*B bins, so node
      // padding costs nothing in the scan
      out_gain[n] = -INFINITY;
      out_feat[n] = 0;
      out_bin[n] = 0;
      out_dleft[n] = 1;
      out_GL[n] = 0.0f;
      out_HL[n] = 0.0f;
      continue;
    }
    const float parent = xtb_calc_gain(totG, totH, p);
    float best_gain = -INFINITY, best_GL = 0.0f, best_HL = 0.0f;
    int32_t best_f = 0, best_b = 0;
    bool best_dl = true, any = false;
    for (int32_t f = 0; f < F; ++f) {
      if (!fmask[n * F + f]) continue;
      const int32_t nb = n_bins[f];
      const float* hf = hist + (static_cast<size_t>(n) * F + f) * B * 2;
      float gsum = 0.0f, hsum = 0.0f;
      for (int32_t b = 0; b < B; ++b) {
        gsum += hf[2 * b];
        hsum += hf[2 * b + 1];
      }
      const float missG = totG - gsum, missH = totH - hsum;
      const bool has_miss = fabsf(missH) > kEps;
      float glr = 0.0f, hlr = 0.0f;
      const int32_t bmax = nb < B ? nb : B;
      for (int32_t b = 0; b < bmax; ++b) {
        glr += hf[2 * b];
        hlr += hf[2 * b + 1];
        const bool ok = (b < nb - 1) || (b == nb - 1 && has_miss);
        if (!ok) continue;
        float g2 = -INFINITY;
        bool dl2 = true;
        {  // missing -> right
          const float GR = totG - glr, HR = totH - hlr;
          if (hlr >= min_child_weight && HR >= min_child_weight &&
              hlr > 0.0f && HR > 0.0f) {
            g2 = xtb_calc_gain(glr, hlr, p) + xtb_calc_gain(GR, HR, p) -
                 parent;
            dl2 = false;
          }
        }
        const float gll = glr + missG, hll = hlr + missH;
        {  // missing -> left
          const float GR = totG - gll, HR = totH - hll;
          if (hll >= min_child_weight && HR >= min_child_weight &&
              hll > 0.0f && HR > 0.0f) {
            const float gl_gain = xtb_calc_gain(gll, hll, p) +
                                  xtb_calc_gain(GR, HR, p) - parent;
            if (gl_gain >= g2) {
              g2 = gl_gain;
              dl2 = true;
            }
          }
        }
        if (g2 > best_gain) {
          best_gain = g2;
          best_f = f;
          best_b = b;
          best_dl = dl2;
          best_GL = dl2 ? gll : glr;
          best_HL = dl2 ? hll : hlr;
          any = true;
        }
      }
    }
    if (!any) {
      // match the XLA argmax over an all -inf tensor: flat index 0 ->
      // (feature 0, bin 0), missing -> left
      const float* h0 = hist + static_cast<size_t>(n) * F * B * 2;
      float gsum = 0.0f, hsum = 0.0f;
      for (int32_t b = 0; b < B; ++b) {
        gsum += h0[2 * b];
        hsum += h0[2 * b + 1];
      }
      best_GL = h0[0] + (totG - gsum);
      best_HL = h0[1] + (totH - hsum);
    }
    out_gain[n] = best_gain;
    out_feat[n] = best_f;
    out_bin[n] = best_b;
    out_dleft[n] = best_dl ? 1 : 0;
    out_GL[n] = best_GL;
    out_HL[n] = best_HL;
  }
}

// ---------------------------------------------------------------------------
// Ensemble margin prediction — rows outer, trees inner, so each row's f32
// adds happen in tree order (bitwise-identical to the XLA scan in
// ops/predict.py, which the prediction-cache continuation contract relies
// on) and each X row is read once while the small tree arrays stay hot.
// Mirrors ops/predict.py predict_margin_delta semantics exactly: fixed
// `depth` steps with stick-at-leaf, NaN -> default-left, categorical
// in-set -> right.  K_leaf == 1 adds the scalar leaf to column groups[t];
// K_leaf > 1 adds the leaf vector to all K columns (multi-target trees).
// ---------------------------------------------------------------------------
inline void xtb_predict_raw_impl(
    const float* X, int64_t R, int32_t F, const int32_t* feat,
    const float* thr, const uint8_t* dleft, const int32_t* left,
    const int32_t* right, const float* value, const int32_t* groups,
    int32_t T, int32_t M, int32_t depth, int32_t K, int32_t K_leaf,
    int32_t has_cat, const uint8_t* is_cat, const uint8_t* catm, int32_t Bc,
    const float* init, float* out) {
  memcpy(out, init, static_cast<size_t>(R) * K * sizeof(float));
  for (int64_t r = 0; r < R; ++r) {
    const float* xr = X + r * F;
    float* orow = out + r * K;
    for (int32_t t = 0; t < T; ++t) {
      const size_t base = static_cast<size_t>(t) * M;
      int32_t nid = 0;
      for (int32_t d = 0; d < depth; ++d) {
        const int32_t fi = feat[base + nid];
        if (fi < 0) break;
        const float x = xr[fi];
        const bool miss = std::isnan(x);
        bool gol;
        if (has_cat && is_cat[base + nid]) {
          const int32_t c = miss ? -1 : static_cast<int32_t>(x);
          const bool member =
              c >= 0 && c < Bc && catm[(base + nid) * Bc + c];
          gol = miss ? (dleft[base + nid] != 0) : !member;
        } else {
          gol = miss ? (dleft[base + nid] != 0) : (x < thr[base + nid]);
        }
        nid = gol ? left[base + nid] : right[base + nid];
      }
      if (K_leaf == 1) {
        orow[groups[t]] += value[base + nid];
      } else {
        const float* v = value + (base + nid) * K_leaf;
        for (int32_t k = 0; k < K_leaf; ++k) orow[k] += v[k];
      }
    }
  }
}

// Binned variant (split_bins routing over an Ellpack page; sentinel
// b >= n_bin = missing) — ops/predict.py predict_margin_delta_binned.
template <typename BinT>
inline void xtb_predict_binned_impl(
    const BinT* bins, int64_t R, int32_t F, int32_t n_bin,
    const int32_t* feat, const int32_t* sbin, const uint8_t* dleft,
    const int32_t* left, const int32_t* right, const float* value,
    const int32_t* groups, int32_t T, int32_t M, int32_t depth, int32_t K,
    int32_t has_cat, const uint8_t* is_cat, const uint8_t* catm, int32_t Bc,
    const float* init, float* out) {
  memcpy(out, init, static_cast<size_t>(R) * K * sizeof(float));
  for (int64_t r = 0; r < R; ++r) {
    const BinT* br = bins + r * F;
    float* orow = out + r * K;
    for (int32_t t = 0; t < T; ++t) {
      const size_t base = static_cast<size_t>(t) * M;
      int32_t nid = 0;
      for (int32_t d = 0; d < depth; ++d) {
        const int32_t fi = feat[base + nid];
        if (fi < 0) break;
        const int32_t b = static_cast<int32_t>(br[fi]);
        bool gol;
        if (has_cat && is_cat[base + nid]) {
          gol = !(b < Bc && catm[(base + nid) * Bc + b]);
        } else {
          gol = b <= sbin[base + nid];
        }
        if (b >= n_bin) gol = dleft[base + nid] != 0;
        nid = gol ? left[base + nid] : right[base + nid];
      }
      orow[groups[t]] += value[base + nid];
    }
  }
}

// ---------------------------------------------------------------------------
// LambdaMART top-k pair gradients (the reference's default pair method,
// lambdarank_obj.h MakePairs truncation branch + LambdaGrad:91).  Works
// directly on CSR query groups — no padded (G, k, S) pair tensors, so the
// CPU path skips the XLA formulation's hundreds of MB of masked
// intermediates per round.  Semantics mirror ops side-by-side
// (_lambda_gradients_topk in objective/ranking.py): stable sort by
// descending score, each of the top-k ranked docs pairs with every doc
// ranked below it, |delta ndcg|/idcg pair weight, optional score-diff
// normalization (skipped while all scores in the group are equal),
// hessian doubled, per-group log2(1+sum_lambda)/sum_lambda rescale.
// ---------------------------------------------------------------------------
#include <algorithm>

inline void xtb_lambdarank_topk_impl(
    const float* s, const float* y, const int32_t* gptr, int32_t n_groups,
    int64_t R, int32_t k, int32_t ndcg_weight, int32_t score_norm,
    int32_t group_norm, float* out_grad, float* out_hess) {
  memset(out_grad, 0, R * sizeof(float));
  memset(out_hess, 0, R * sizeof(float));
  std::vector<int32_t> order;
  std::vector<float> gain, disc, lam_acc, hess_acc;
  for (int32_t g = 0; g < n_groups; ++g) {
    const int32_t lo = gptr[g], hi = gptr[g + 1];
    const int32_t n = hi - lo;
    if (n <= 1) continue;
    order.resize(n);
    for (int32_t i = 0; i < n; ++i) order[i] = lo + i;
    std::stable_sort(order.begin(), order.end(),
                     [&](int32_t a, int32_t b) { return s[a] > s[b]; });
    gain.resize(n);
    disc.resize(n);
    for (int32_t i = 0; i < n; ++i) {
      gain[i] = exp2f(y[order[i]]) - 1.0f;
      disc[i] = 1.0f / log2f(2.0f + static_cast<float>(i));
    }
    // idcg over gains sorted descending
    std::vector<float> ideal(gain);
    std::sort(ideal.begin(), ideal.end(), std::greater<float>());
    float idcg = 0.0f;
    for (int32_t i = 0; i < n; ++i) idcg += ideal[i] * disc[i];
    if (idcg < 1e-10f) idcg = 1e-10f;
    const bool spread = s[order[0]] != s[order[n - 1]];

    lam_acc.assign(n, 0.0f);
    hess_acc.assign(n, 0.0f);
    float sum_lambda = 0.0f;
    const int32_t kk = k < n ? k : n;
    for (int32_t i = 0; i < kk; ++i) {
      const float si = s[order[i]], gi = gain[i];
      for (int32_t j = i + 1; j < n; ++j) {
        const float gj = gain[j];
        if (gi == gj) continue;
        const bool high_is_i = gi > gj;
        const float s_high = high_is_i ? si : s[order[j]];
        const float s_low = high_is_i ? s[order[j]] : si;
        const float sig = 1.0f / (1.0f + expf(-(s_high - s_low)));
        float delta = 1.0f;
        if (ndcg_weight) {
          delta = fabsf((gi - gj) * (disc[i] - disc[j])) / idcg;
        }
        if (score_norm && spread) {
          delta = delta / (fabsf(s_high - s_low) + 0.01f);
        }
        const float lam = (sig - 1.0f) * delta;  // high doc's gradient
        float h = sig * (1.0f - sig) * delta;
        if (h < 1e-16f) h = 1e-16f;
        h *= 2.0f;
        const float sgn_i = high_is_i ? 1.0f : -1.0f;
        lam_acc[i] += lam * sgn_i;
        lam_acc[j] -= lam * sgn_i;
        hess_acc[i] += h;
        hess_acc[j] += h;
        sum_lambda += -2.0f * lam;
      }
    }
    float norm = 1.0f;
    if (group_norm && sum_lambda > 0.0f) {
      float d = sum_lambda > 1e-16f ? sum_lambda : 1e-16f;
      norm = log2f(1.0f + sum_lambda) / d;
    }
    for (int32_t i = 0; i < n; ++i) {
      out_grad[order[i]] = lam_acc[i] * norm;
      out_hess[order[i]] = hess_acc[i] * norm;
    }
  }
}

#endif  // XTB_KERNELS_H_
