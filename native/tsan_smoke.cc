// ThreadSanitizer smoke for the ParallelFor pool and the threaded kernel
// bodies (native/xtb_kernels.h).  Run by `make -C native tsan` from
// scripts/nightly_suite.sh.  Covers, under TSAN:
//
//   1. threaded f32 + quantised histogram builds, bitwise vs nthread=1;
//   2. threaded split scan + raw predict, bitwise vs nthread=1;
//   3. CONCURRENT predict callers (4 host threads sharing the pool — the
//      busy-pool inline-fallback path the narrowed C-API dispatch relies
//      on), each caller bitwise vs the sequential reference;
//   4. injected worker death (xtb_pool_kill_worker, the
//      `native.parallel_for` fault seam): region completes, results stay
//      correct, the pool respawns to full strength;
//   5. rapid-fire tiny regions (the ABA window between back-to-back
//      dispatches);
//   6. kernel perf-counter RAII (XtbKernelPerf -> record_perf) under
//      concurrent kernel callers WHILE a poller thread reads
//      xtb_pool_kernel_perf/xtb_pool_kernel_stats live — invocation
//      counts must stay monotone mid-flight and land exactly;
//   7. heartbeat-era mixed dispatch: hist / hist_q / split / predict /
//      tiny OTHER regions from six threads at once, with a heartbeat
//      thread polling pool liveness + every kernel's counters on a short
//      interval (the fleet heartbeat-loop traffic shape).
//
// Exits 0 + prints TSAN-SMOKE-OK when every check passes (TSAN itself
// fails the process on a detected race).
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

#define XTB_DEFINE_POOL_ABI
#include "xtb_kernels.h"

namespace {

constexpr int64_t R = 20000;
constexpr int32_t F = 13, B = 32, N = 8, C = 2;

struct Data {
  std::vector<uint8_t> bins;
  std::vector<float> gpair;
  std::vector<int32_t> pos;
};

Data make_data() {
  Data d;
  std::mt19937 rng(7);
  d.bins.resize(R * F);
  d.gpair.resize(R * C);
  d.pos.resize(R);
  for (auto& b : d.bins) b = static_cast<uint8_t>(rng() % (B + 1));
  std::normal_distribution<float> g;
  for (auto& v : d.gpair) v = g(rng);
  for (auto& p : d.pos) p = static_cast<int32_t>(rng() % (2 * N)) + N - 1;
  return d;
}

bool bitwise_eq(const float* a, const float* b, size_t n, const char* what) {
  if (memcmp(a, b, n * sizeof(float)) != 0) {
    fprintf(stderr, "FAIL: %s not bitwise identical\n", what);
    return false;
  }
  return true;
}

std::vector<float> run_hist(const Data& d) {
  std::vector<float> out(static_cast<size_t>(N) * F * B * C);
  xtb_hist_build_impl(d.bins.data(), d.gpair.data(), d.pos.data(), R, F, B,
                      N - 1, N, 1, C, out.data());
  return out;
}

std::vector<float> run_predict(const Data& d, const std::vector<int32_t>& feat,
                               const std::vector<float>& thr,
                               const std::vector<uint8_t>& dleft,
                               const std::vector<int32_t>& lr,
                               const std::vector<float>& value,
                               const std::vector<int32_t>& groups, int32_t T,
                               int32_t M) {
  std::vector<float> X(R * F), init(R, 0.5f), out(R);
  for (int64_t i = 0; i < R * F; ++i)
    X[i] = static_cast<float>(d.bins[i]) * 0.1f;
  std::vector<uint8_t> ic(static_cast<size_t>(T) * M, 0),
      cm(static_cast<size_t>(T) * M, 0);
  xtb_predict_raw_impl(X.data(), R, F, feat.data(), thr.data(), dleft.data(),
                       lr.data(), lr.data(), value.data(), groups.data(), T,
                       M, 4, 1, 1, 0, ic.data(), cm.data(), 1, init.data(),
                       out.data());
  return out;
}

}  // namespace

int main() {
  Data d = make_data();

  // --- 1. histogram: scalar nthread=1 reference vs threaded runs at BOTH
  // simd levels (scalar + best detected), all bitwise ---
  xtb_simd_set(XTB_SIMD_SCALAR);
  xtb_set_nthread(1);
  auto ref = run_hist(d);
  for (int lvl : {0, -1}) {
    xtb_simd_set(lvl);
    xtb_set_nthread(4);
    auto thr4 = run_hist(d);
    if (!bitwise_eq(ref.data(), thr4.data(), ref.size(),
                    lvl == 0 ? "hist nthread=4 scalar"
                             : "hist nthread=4 vector"))
      return 1;
  }

  // quantised limbs
  std::vector<int8_t> limbs(R * 6);
  std::mt19937 rng(11);
  for (auto& l : limbs) l = static_cast<int8_t>(rng() % 256 - 128);
  std::vector<int32_t> q1(static_cast<size_t>(N) * F * B * 6),
      q4(static_cast<size_t>(N) * F * B * 6);
  xtb_simd_set(XTB_SIMD_SCALAR);
  xtb_set_nthread(1);
  xtb_hist_q_impl(d.bins.data(), limbs.data(), d.pos.data(), R, F, B, N - 1,
                  N, 1, 6, q1.data());
  xtb_simd_set(-1);
  xtb_set_nthread(4);
  xtb_hist_q_impl(d.bins.data(), limbs.data(), d.pos.data(), R, F, B, N - 1,
                  N, 1, 6, q4.data());
  if (memcmp(q1.data(), q4.data(), q1.size() * sizeof(int32_t)) != 0) {
    fprintf(stderr, "FAIL: hist_q not bitwise identical\n");
    return 1;
  }

  // --- 2. split scan, bitwise ---
  std::vector<float> totals(N * 2);
  for (int32_t n = 0; n < N; ++n) {
    totals[n * 2] = 0.5f * n;
    totals[n * 2 + 1] = 1.0f + n;
  }
  std::vector<int32_t> nb(F, B);
  std::vector<uint8_t> fmask(static_cast<size_t>(N) * F, 1);
  auto run_split = [&](float* gain, int32_t* feat, int32_t* bin,
                       uint8_t* dl, float* GL, float* HL) {
    xtb_split_scan_impl(ref.data(), totals.data(), nb.data(), fmask.data(),
                        N, F, B, 1.0f, 0.0f, 1.0f, 0.0f, gain, feat, bin, dl,
                        GL, HL);
  };
  std::vector<float> g1(N), g4(N), GL1(N), GL4(N), HL1(N), HL4(N);
  std::vector<int32_t> f1(N), f4(N), b1(N), b4(N);
  std::vector<uint8_t> d1(N), d4(N);
  xtb_simd_set(XTB_SIMD_SCALAR);
  xtb_set_nthread(1);
  run_split(g1.data(), f1.data(), b1.data(), d1.data(), GL1.data(),
            HL1.data());
  xtb_simd_set(-1);
  xtb_set_nthread(4);
  run_split(g4.data(), f4.data(), b4.data(), d4.data(), GL4.data(),
            HL4.data());
  if (!bitwise_eq(g1.data(), g4.data(), N, "split gains") ||
      memcmp(f1.data(), f4.data(), N * sizeof(int32_t)) != 0) {
    return 1;
  }

  // --- 3. concurrent predict callers over the shared pool ---
  const int32_t T = 16, M = 31;
  std::vector<int32_t> feat(static_cast<size_t>(T) * M), lr(T * M);
  std::vector<float> thr(T * M), value(T * M);
  std::vector<uint8_t> dleft(T * M, 1);
  std::vector<int32_t> groups(T, 0);
  for (int32_t t = 0; t < T; ++t) {
    for (int32_t m = 0; m < M; ++m) {
      const size_t i = static_cast<size_t>(t) * M + m;
      feat[i] = (2 * m + 2 < M) ? (m % F) : -1;
      thr[i] = 1.5f + 0.01f * m;
      lr[i] = (2 * m + 1 < M) ? 2 * m + 1 : m;
      value[i] = 0.01f * (t + m);
    }
  }
  xtb_simd_set(XTB_SIMD_SCALAR);
  xtb_set_nthread(1);
  auto pref = run_predict(d, feat, thr, dleft, lr, value, groups, T, M);
  // concurrent callers run at the detected simd level: the lane-parallel
  // traversal shares the pool with the busy-pool inline fallback — the
  // exact interleaving the narrowed C-API dispatch relies on
  xtb_simd_set(-1);
  xtb_set_nthread(4);
  bool ok = true;
  std::vector<std::thread> callers;
  for (int c = 0; c < 4; ++c) {
    callers.emplace_back([&] {
      for (int it = 0; it < 3; ++it) {
        auto out = run_predict(d, feat, thr, dleft, lr, value, groups, T, M);
        if (memcmp(out.data(), pref.data(), out.size() * sizeof(float)) != 0)
          ok = false;
      }
    });
  }
  for (auto& t : callers) t.join();
  if (!ok) {
    fprintf(stderr, "FAIL: concurrent predict diverged\n");
    return 1;
  }

  // --- 4. injected worker death: completes, correct, respawns ---
  const int64_t faults0 = xtb_pool_faults_total();
  xtb_pool_kill_worker();
  auto after_kill = run_hist(d);
  if (!bitwise_eq(ref.data(), after_kill.data(), ref.size(),
                  "hist after worker kill"))
    return 1;
  if (xtb_pool_faults_total() <= faults0) {
    fprintf(stderr, "FAIL: injected worker death not recorded\n");
    return 1;
  }
  auto respawned = run_hist(d);  // next region must be back at strength
  if (!bitwise_eq(ref.data(), respawned.data(), ref.size(),
                  "hist after respawn") ||
      xtb_pool_alive_workers() != 3) {
    fprintf(stderr, "FAIL: pool did not respawn (alive=%d)\n",
            xtb_pool_alive_workers());
    return 1;
  }

  // --- 5. rapid-fire tiny regions: back-to-back dispatch is the ABA
  // window where a worker lingering past one region's completion must NOT
  // claim the next region's shards with a stale job pointer ---
  xtb_set_nthread(4);
  for (int it = 0; it < 2000; ++it) {
    std::vector<int64_t> sums(4, 0);
    xtb_parallel_for(4, 1, XTB_K_OTHER, [&](int64_t b, int64_t e) {
      for (int64_t i = b; i < e; ++i) sums[i] = i + it;
    });
    for (int64_t i = 0; i < 4; ++i) {
      if (sums[i] != i + it) {
        fprintf(stderr, "FAIL: rapid-fire region dropped shard %lld\n",
                static_cast<long long>(i));
        return 1;
      }
    }
  }

  // --- 6. perf-counter RAII under concurrent callers + live poller ---
  // Every kernel entry opens an XtbKernelPerf bracket whose dtor folds
  // [invocations, wall_ns, cycles, bytes, flops] into the shared stats
  // slot; a telemetry poller reads those slots with xtb_pool_kernel_perf
  // WHILE brackets are closing on other threads.  TSAN checks the
  // accounting atomics; we check the numbers: monotone mid-flight, and
  // exactly one invocation per kernel call once the writers join.
  const int NK = xtb_pool_n_kernels();
  std::vector<int64_t> perf0(5), perf_now(5), stats_now(13);
  xtb_pool_kernel_perf(XTB_K_HIST, perf0.data());
  const int64_t hist_inv0 = perf0[0];
  xtb_pool_kernel_perf(XTB_K_PREDICT, perf0.data());
  const int64_t pred_inv0 = perf0[0];

  constexpr int kPerfThreads = 4, kPerfIters = 4;
  std::atomic<bool> done{false};
  std::atomic<bool> perf_ok{true};
  std::thread poller([&] {
    std::vector<int64_t> last(NK, 0), p(5), s(13);
    for (int k = 0; k < NK; ++k) {
      xtb_pool_kernel_perf(k, p.data());
      last[k] = p[0];
    }
    while (!done.load(std::memory_order_acquire)) {
      for (int k = 0; k < NK; ++k) {
        xtb_pool_kernel_perf(k, p.data());
        xtb_pool_kernel_stats(k, s.data());
        // a live counter read may be mid-bracket, but never backwards
        // and never negative
        if (p[0] < last[k] || p[1] < 0 || p[3] < 0 || s[0] < 0 || s[1] < 0) {
          fprintf(stderr, "FAIL: perf counters went backwards (%s: %lld -> %lld)\n",
                  xtb_pool_kernel_name(k), static_cast<long long>(last[k]),
                  static_cast<long long>(p[0]));
          perf_ok.store(false);
        }
        last[k] = p[0];
      }
      std::this_thread::sleep_for(std::chrono::microseconds(200));
    }
  });
  {
    std::vector<std::thread> writers;
    for (int c = 0; c < kPerfThreads; ++c) {
      writers.emplace_back([&] {
        for (int it = 0; it < kPerfIters; ++it) {
          auto h = run_hist(d);
          if (memcmp(h.data(), ref.data(), h.size() * sizeof(float)) != 0)
            perf_ok.store(false);
          auto p = run_predict(d, feat, thr, dleft, lr, value, groups, T, M);
          if (memcmp(p.data(), pref.data(), p.size() * sizeof(float)) != 0)
            perf_ok.store(false);
        }
      });
    }
    for (auto& t : writers) t.join();
  }
  done.store(true, std::memory_order_release);
  poller.join();
  if (!perf_ok.load()) return 1;
  xtb_pool_kernel_perf(XTB_K_HIST, perf_now.data());
  const int64_t hist_calls = kPerfThreads * kPerfIters;
  if (perf_now[0] - hist_inv0 != hist_calls || perf_now[1] <= 0 ||
      perf_now[3] <= 0) {
    fprintf(stderr,
            "FAIL: hist perf bracket miscount (d_inv=%lld want %lld, "
            "wall=%lld, bytes=%lld)\n",
            static_cast<long long>(perf_now[0] - hist_inv0),
            static_cast<long long>(hist_calls),
            static_cast<long long>(perf_now[1]),
            static_cast<long long>(perf_now[3]));
    return 1;
  }
  xtb_pool_kernel_perf(XTB_K_PREDICT, perf_now.data());
  if (perf_now[0] - pred_inv0 != hist_calls) {
    fprintf(stderr, "FAIL: predict perf bracket miscount (d_inv=%lld)\n",
            static_cast<long long>(perf_now[0] - pred_inv0));
    return 1;
  }

  // --- 7. heartbeat-era mixed dispatch: six threads driving FOUR kernel
  // families through the one shared pool at once (hist + hist_q + split +
  // predict + tiny OTHER regions), while a heartbeat thread polls
  // liveness and every kernel's counters on a short interval — the
  // traffic shape a fleet heartbeat loop sees, where telemetry reads
  // race live perf-bracket closes and pool region turnover ---
  std::atomic<bool> hb_done{false};
  std::atomic<bool> mixed_ok{true};
  std::thread heartbeat([&] {
    std::vector<int64_t> p(5), s(13);
    while (!hb_done.load(std::memory_order_acquire)) {
      if (xtb_pool_alive_workers() < 1) {
        fprintf(stderr, "FAIL: heartbeat saw an empty pool\n");
        mixed_ok.store(false);
      }
      for (int k = 0; k < NK; ++k) {
        xtb_pool_kernel_perf(k, p.data());
        xtb_pool_kernel_stats(k, s.data());
      }
      (void)xtb_pool_regions_total();
      std::this_thread::sleep_for(std::chrono::microseconds(500));
    }
  });
  {
    std::vector<std::thread> traffic;
    for (int c = 0; c < 6; ++c) {
      traffic.emplace_back([&, c] {
        std::vector<float> g(N), GLo(N), HLo(N);
        std::vector<int32_t> fo(N), bo(N);
        std::vector<uint8_t> dlo(N);
        std::vector<int32_t> q(static_cast<size_t>(N) * F * B * 6);
        for (int it = 0; it < 3; ++it) {
          switch ((c + it) % 4) {
            case 0: {
              auto h = run_hist(d);
              if (memcmp(h.data(), ref.data(),
                         h.size() * sizeof(float)) != 0)
                mixed_ok.store(false);
              break;
            }
            case 1: {
              auto p = run_predict(d, feat, thr, dleft, lr, value, groups,
                                   T, M);
              if (memcmp(p.data(), pref.data(),
                         p.size() * sizeof(float)) != 0)
                mixed_ok.store(false);
              break;
            }
            case 2: {
              run_split(g.data(), fo.data(), bo.data(), dlo.data(),
                        GLo.data(), HLo.data());
              if (memcmp(g.data(), g1.data(), N * sizeof(float)) != 0)
                mixed_ok.store(false);
              break;
            }
            default: {
              xtb_hist_q_impl(d.bins.data(), limbs.data(), d.pos.data(), R,
                              F, B, N - 1, N, 1, 6, q.data());
              if (memcmp(q.data(), q1.data(),
                         q.size() * sizeof(int32_t)) != 0)
                mixed_ok.store(false);
              break;
            }
          }
          std::vector<int64_t> sums(4, 0);
          xtb_parallel_for(4, 1, XTB_K_OTHER, [&](int64_t b, int64_t e) {
            for (int64_t i = b; i < e; ++i) sums[i] = i;
          });
          for (int64_t i = 0; i < 4; ++i)
            if (sums[i] != i) mixed_ok.store(false);
        }
      });
    }
    for (auto& t : traffic) t.join();
  }
  hb_done.store(true, std::memory_order_release);
  heartbeat.join();
  if (!mixed_ok.load()) {
    fprintf(stderr, "FAIL: heartbeat-era mixed dispatch diverged\n");
    return 1;
  }

  printf("TSAN-SMOKE-OK regions=%lld simd=%s\n",
         static_cast<long long>(xtb_pool_regions_total()),
         xtb_simd_name(xtb_simd_detected()));
  return 0;
}
