// xtb_wire.cc — native rx hot loop for the fleet wire protocol.
//
// One fleet frame is <u32 header_len><u64 payload_len><u32 crc32> +
// header JSON + payload (serving/wire.py owns the contract; this file
// only moves the byte-level inner loop off the interpreter).  The pure
// Python reader pays a GIL release/reacquire per read syscall plus the
// interpreter's per-chunk bookkeeping — under a many-threaded sharded
// dispatcher the *reacquire* is the cost (lock convoy on the GIL).
// Routed through here, ONE ctypes call (one GIL release) covers the
// whole prefix read, and one more covers header+payload+CRC, so the
// dispatcher thread holds the GIL only to JSON-decode the tiny header.
//
// Contract parity with wire.py `recv_frame` (tests pin both paths):
//   - the cumulative slow-loris deadline starts at the FIRST prefix
//     byte (idle time between frames is free) and every partial read
//     checkpoints against it;
//   - CRC-32 is zlib-compatible (poly 0xEDB88320, init/final xor
//     0xFFFFFFFF) over header bytes then payload bytes;
//   - length-prefix bounds, fault seams, blackhole_rx re-loop and all
//     error classification stay in Python — this layer reports return
//     codes, it never decides policy.
//
// Deliberately dependency-free (no zlib link, no Python headers): the
// library loads into replicas and dispatchers alike, and poll()-based
// waiting keeps it correct for both blocking and non-blocking fds.
// Deadlines are absolute CLOCK_MONOTONIC seconds — the same clock
// CPython's time.monotonic() reads on Linux, so Python and native
// checkpoints interleave on one timeline.

#include <errno.h>
#include <poll.h>
#include <stdint.h>
#include <time.h>
#include <unistd.h>

namespace {

double mono_now() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<double>(ts.tv_sec) + static_cast<double>(ts.tv_nsec) * 1e-9;
}

// zlib-compatible CRC-32, slice-by-8: ~1 byte/cycle without any ISA
// assumptions, comfortably faster than the socket copy it rides behind.
struct CrcTables {
  uint32_t t[8][256];
  CrcTables() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int j = 1; j < 8; ++j)
        t[j][i] = (t[j - 1][i] >> 8) ^ t[0][t[j - 1][i] & 0xFF];
  }
};
const CrcTables kCrc;

uint32_t crc32_update(uint32_t crc, const unsigned char* p, uint64_t n) {
  crc = ~crc;
  while (n >= 8) {
    crc ^= static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
           (static_cast<uint32_t>(p[2]) << 16) | (static_cast<uint32_t>(p[3]) << 24);
    crc = kCrc.t[7][crc & 0xFF] ^ kCrc.t[6][(crc >> 8) & 0xFF] ^
          kCrc.t[5][(crc >> 16) & 0xFF] ^ kCrc.t[4][crc >> 24] ^
          kCrc.t[3][p[4]] ^ kCrc.t[2][p[5]] ^ kCrc.t[1][p[6]] ^ kCrc.t[0][p[7]];
    p += 8;
    n -= 8;
  }
  while (n--) crc = kCrc.t[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// Return codes shared by the read helpers (also the extern ABI):
//  >0 bytes read | 0 clean EOF | XTB_WIRE_DEADLINE | XTB_WIRE_IO
enum {
  XTB_WIRE_OK = 0,
  XTB_WIRE_EOF_BOUNDARY = 1,   // clean EOF before any frame byte
  XTB_WIRE_EOF_MID = -1,       // peer vanished inside a frame
  XTB_WIRE_DEADLINE = -2,      // cumulative frame budget exhausted
  XTB_WIRE_CRC = -6,           // frame CRC mismatch
  XTB_WIRE_IO = -7,            // read()/poll() hard error (see errno)
};

// One read attempt with EINTR retry and poll()-based waiting so a
// non-blocking fd (Python sockets with a timeout set anywhere in their
// past) behaves exactly like a blocking one.  deadline <= 0 disables
// the bound (poll blocks indefinitely).
long read_some(int fd, unsigned char* p, uint64_t n, double deadline) {
  for (;;) {
    ssize_t r = read(fd, p, static_cast<size_t>(n));
    if (r >= 0) return static_cast<long>(r);
    if (errno == EINTR) continue;
    if (errno == EAGAIN || errno == EWOULDBLOCK) {
      int timeout_ms = -1;
      if (deadline > 0.0) {
        double rem = deadline - mono_now();
        if (rem <= 0.0) return XTB_WIRE_DEADLINE;
        timeout_ms = static_cast<int>(rem * 1000.0) + 1;
      }
      pollfd pfd;
      pfd.fd = fd;
      pfd.events = POLLIN;
      pfd.revents = 0;
      int pr = poll(&pfd, 1, timeout_ms);
      if (pr < 0) {
        if (errno == EINTR) continue;
        return XTB_WIRE_IO;
      }
      if (pr == 0) return XTB_WIRE_DEADLINE;
      continue;  // readable (or HUP/ERR — the read() reports which)
    }
    return XTB_WIRE_IO;
  }
}

// Exactly n bytes or an error; every partial read is a checkpoint
// against the cumulative deadline (the slow-loris bound).
int read_exact(int fd, unsigned char* p, uint64_t n, double deadline) {
  uint64_t got = 0;
  while (got < n) {
    long r = read_some(fd, p + got, n - got, deadline);
    if (r == 0) return XTB_WIRE_EOF_MID;
    if (r < 0) return static_cast<int>(r);
    got += static_cast<uint64_t>(r);
    if (deadline > 0.0 && got < n && mono_now() >= deadline)
      return XTB_WIRE_DEADLINE;
  }
  return XTB_WIRE_OK;
}

uint32_t le32(const unsigned char* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) | (static_cast<uint32_t>(p[3]) << 24);
}

uint64_t le64(const unsigned char* p) {
  return static_cast<uint64_t>(le32(p)) | (static_cast<uint64_t>(le32(p + 4)) << 32);
}

}  // namespace

extern "C" {

// Read the 16-byte frame prefix.  Blocks indefinitely for the first
// byte (inter-frame idle is free); the moment it lands, the cumulative
// deadline is armed (budget_s <= 0 disables it) and returned through
// *deadline_out as absolute CLOCK_MONOTONIC seconds so the caller can
// thread the SAME clock into xtb_wire_read_body.
// Returns: 0 ok | 1 clean EOF at a frame boundary | -1 EOF mid-prefix |
// -2 deadline | -7 io error.
int xtb_wire_read_prefix(int fd, double budget_s, unsigned* hlen,
                         unsigned long long* plen, unsigned* crc,
                         double* deadline_out) {
  unsigned char pfx[16];
  long r = read_some(fd, pfx, 1, 0.0);
  if (r == 0) return XTB_WIRE_EOF_BOUNDARY;
  if (r < 0) return static_cast<int>(r);
  double deadline = budget_s > 0.0 ? mono_now() + budget_s : 0.0;
  *deadline_out = deadline;
  int rc = read_exact(fd, pfx + 1, sizeof(pfx) - 1, deadline);
  if (rc != XTB_WIRE_OK) return rc;
  *hlen = le32(pfx);
  *plen = le64(pfx + 4);
  *crc = le32(pfx + 12);
  return XTB_WIRE_OK;
}

// Read the n = header_len + payload_len frame body into buf and verify
// the prefix CRC over it.  deadline is the absolute value handed back
// by xtb_wire_read_prefix (0 = unbounded).
// Returns: 0 ok | -1 EOF mid-frame | -2 deadline | -6 CRC mismatch |
// -7 io error.
int xtb_wire_read_body(int fd, unsigned char* buf, unsigned long long n,
                       double deadline, unsigned expect_crc) {
  int rc = read_exact(fd, buf, n, deadline);
  if (rc != XTB_WIRE_OK) return rc;
  if (crc32_update(0, buf, n) != expect_crc) return XTB_WIRE_CRC;
  return XTB_WIRE_OK;
}

// zlib.crc32-compatible rolling CRC, exported so Python tests can pin
// the native table against the zlib reference byte-for-byte.
unsigned xtb_wire_crc32(unsigned crc, const unsigned char* p,
                        unsigned long long n) {
  return crc32_update(crc, p, n);
}

}  // extern "C"
