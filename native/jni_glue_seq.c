/* The JVM binding's exact C-ABI call sequence, driven from plain C.
 *
 * jvm-package/src/native/xgboost_tpu_jni.c cannot compile here (no JDK in
 * the image), so this program pins the contract it depends on: row-major
 * float ingest (JVM arrays need no transpose), label/weight float info,
 * GROUP as unsigned info with a ranking objective, per-round eval,
 * predict, and the ubj buffer round-trip used for spark checkpointing.
 * Run by tests/test_c_api.py::test_jni_glue_sequence.
 */
#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef void* DMatrixHandle;
typedef void* BoosterHandle;
typedef uint64_t bst_ulong;

extern const char* XGBGetLastError(void);
extern int XGDMatrixCreateFromMat(const float*, bst_ulong, bst_ulong, float,
                                  DMatrixHandle*);
extern int XGDMatrixSetFloatInfo(DMatrixHandle, const char*, const float*,
                                 bst_ulong);
extern int XGDMatrixSetUIntInfo(DMatrixHandle, const char*, const unsigned*,
                                bst_ulong);
extern int XGDMatrixNumRow(DMatrixHandle, bst_ulong*);
extern int XGDMatrixFree(DMatrixHandle);
extern int XGBoosterCreate(const DMatrixHandle[], bst_ulong, BoosterHandle*);
extern int XGBoosterFree(BoosterHandle);
extern int XGBoosterSetParam(BoosterHandle, const char*, const char*);
extern int XGBoosterUpdateOneIter(BoosterHandle, int, DMatrixHandle);
extern int XGBoosterEvalOneIter(BoosterHandle, int, DMatrixHandle[],
                                const char*[], bst_ulong, const char**);
extern int XGBoosterPredict(BoosterHandle, DMatrixHandle, int, unsigned, int,
                            bst_ulong*, const float**);
extern int XGBoosterSaveModelToBuffer(BoosterHandle, const char*, bst_ulong*,
                                      const char**);
extern int XGBoosterLoadModelFromBuffer(BoosterHandle, const void*,
                                        bst_ulong);
extern int XGBoosterSetAttr(BoosterHandle, const char*, const char*);
extern int XGBoosterGetAttr(BoosterHandle, const char*, const char**, int*);

#define CHECK(call)                                                   \
  do {                                                                \
    if ((call) != 0) {                                                \
      fprintf(stderr, "FAILED %s: %s\n", #call, XGBGetLastError());   \
      exit(1);                                                        \
    }                                                                 \
  } while (0)

enum { G = 24, DOCS = 25, F = 6, ROUNDS = 4 };

int main(void) {
  /* ranking setup: G query groups x DOCS docs, graded 0-3 relevance */
  enum { R = G * DOCS };
  static float data[(size_t)R * F];
  static float label[R];
  static float weight[R];
  static unsigned group[G];
  unsigned seed = 7;
  for (int i = 0; i < R; ++i) {
    for (int j = 0; j < F; ++j) {
      seed = seed * 1664525u + 1013904223u;
      data[(size_t)i * F + j] = ((float)(seed >> 8) / (1 << 24)) - 0.5f;
    }
    float s = data[(size_t)i * F];
    label[i] = s > 0.25f ? 3.0f : (s > 0.0f ? 2.0f : (s > -0.25f ? 1.0f : 0.0f));
    weight[i] = 1.0f;
  }
  for (int g = 0; g < G; ++g) group[g] = DOCS;

  DMatrixHandle d = NULL;
  CHECK(XGDMatrixCreateFromMat(data, R, F, NAN, &d));
  CHECK(XGDMatrixSetFloatInfo(d, "label", label, R));
  CHECK(XGDMatrixSetFloatInfo(d, "weight", weight, R));
  CHECK(XGDMatrixSetUIntInfo(d, "group", group, G));
  bst_ulong nr = 0;
  CHECK(XGDMatrixNumRow(d, &nr));
  if (nr != R) return 1;

  BoosterHandle bst = NULL;
  DMatrixHandle dmats[1] = {d};
  CHECK(XGBoosterCreate(dmats, 1, &bst));
  CHECK(XGBoosterSetParam(bst, "objective", "rank:ndcg"));
  CHECK(XGBoosterSetParam(bst, "max_depth", "3"));
  CHECK(XGBoosterSetParam(bst, "eta", "0.3"));
  CHECK(XGBoosterSetParam(bst, "eval_metric", "ndcg@5"));

  const char* names[1] = {"train"};
  const char* msg = NULL;
  double first = 0, last = 0;
  for (int it = 0; it < ROUNDS; ++it) {
    CHECK(XGBoosterUpdateOneIter(bst, it, d));
    CHECK(XGBoosterEvalOneIter(bst, it, dmats, names, 1, &msg));
    const char* p = strstr(msg, "ndcg@5:");
    if (p == NULL) {
      fprintf(stderr, "no ndcg@5 in eval: %s\n", msg);
      return 1;
    }
    double v = atof(p + 7);
    if (it == 0) first = v;
    last = v;
  }
  if (!(last > first) && !(last > 0.99)) {
    /* separable labels can saturate ndcg@5 at 1.0 after round one */
    fprintf(stderr, "ndcg did not improve: %f -> %f\n", first, last);
    return 1;
  }

  bst_ulong plen = 0;
  const float* preds = NULL;
  CHECK(XGBoosterPredict(bst, d, 0, 0, 0, &plen, &preds));
  if (plen != R) return 1;
  static float keep[R];
  memcpy(keep, preds, sizeof(keep));

  bst_ulong blen = 0;
  const char* buf = NULL;
  CHECK(XGBoosterSaveModelToBuffer(bst, "ubj", &blen, &buf));
  char* copy = (char*)malloc(blen);
  memcpy(copy, buf, blen);
  BoosterHandle b2 = NULL;
  CHECK(XGBoosterCreate(NULL, 0, &b2));
  CHECK(XGBoosterLoadModelFromBuffer(b2, copy, blen));
  free(copy);
  CHECK(XGBoosterPredict(b2, d, 0, 0, 0, &plen, &preds));
  for (bst_ulong i = 0; i < plen; ++i)
    if (preds[i] != keep[i]) return 1;

  /* early-stopping attrs (XGBoost.train earlyStoppingRounds path) */
  CHECK(XGBoosterSetAttr(bst, "best_iteration", "2"));
  CHECK(XGBoosterSetAttr(bst, "best_score", "0.9871"));
  const char* attr = NULL;
  int ok = 0;
  CHECK(XGBoosterGetAttr(bst, "best_iteration", &attr, &ok));
  if (!ok || strcmp(attr, "2") != 0) {
    fprintf(stderr, "attr round-trip failed\n");
    return 1;
  }
  CHECK(XGBoosterGetAttr(bst, "unset_attr", &attr, &ok));
  if (ok) return 1;

  CHECK(XGBoosterFree(b2));
  CHECK(XGBoosterFree(bst));
  CHECK(XGDMatrixFree(d));
  printf("JNI-GLUE-SEQ-OK ndcg %.4f->%.4f\n", first, last);
  return 0;
}
