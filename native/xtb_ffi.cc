// XLA FFI handlers for the native host kernels (CPU backend).
//
// The jitted CPU training programs reach these via jax.ffi.ffi_call —
// zero-copy XLA custom calls, the same mechanism the reference uses to hand
// work to its C++ updaters through the Python/C boundary (role analogue of
// src/c_api + updater dispatch; the kernels themselves are in
// xtb_kernels.h).  Built separately from libxtb_native.so because this
// translation unit needs the jaxlib FFI headers (make -C native ffi).
#include <cstdint>

#include "xla/ffi/api/ffi.h"

// this .so carries its own ParallelFor pool instance; exporting the pool C
// ABI here lets utils/native.py configure nthread + read pool stats on the
// library the jitted programs actually dispatch into
#define XTB_DEFINE_POOL_ABI
#include "xtb_kernels.h"

namespace ffi = xla::ffi;

// hist: (bins[R,F] u8|u16|i32, gpair[R,C] f32, pos[R] i32, node0[1] i32)
//       + attr stride -> out[N,F,B,C] f32
static ffi::Error XtbHistImpl(ffi::AnyBuffer bins,
                              ffi::Buffer<ffi::F32> gpair,
                              ffi::Buffer<ffi::S32> pos,
                              ffi::Buffer<ffi::S32> node0, int32_t stride,
                              ffi::ResultBuffer<ffi::F32> out) {
  auto bd = bins.dimensions();
  auto od = out->dimensions();
  if (bd.size() != 2 || od.size() != 4) {
    return ffi::Error::InvalidArgument("xtb_hist: bad ranks");
  }
  const int64_t R = bd[0];
  const int32_t F = static_cast<int32_t>(bd[1]);
  const int32_t N = static_cast<int32_t>(od[0]);
  const int32_t B = static_cast<int32_t>(od[2]);
  const int32_t C = static_cast<int32_t>(od[3]);
  const int32_t n0 = node0.typed_data()[0];
  switch (bins.element_type()) {
    case ffi::U8:
      xtb_hist_build_impl(
          static_cast<const uint8_t*>(bins.untyped_data()),
          gpair.typed_data(), pos.typed_data(), R, F, B, n0, N, stride, C,
          out->typed_data());
      break;
    case ffi::U16:
      xtb_hist_build_impl(
          static_cast<const uint16_t*>(bins.untyped_data()),
          gpair.typed_data(), pos.typed_data(), R, F, B, n0, N, stride, C,
          out->typed_data());
      break;
    case ffi::S16:
      xtb_hist_build_impl(
          static_cast<const int16_t*>(bins.untyped_data()),
          gpair.typed_data(), pos.typed_data(), R, F, B, n0, N, stride, C,
          out->typed_data());
      break;
    case ffi::S32:
      xtb_hist_build_impl(
          static_cast<const int32_t*>(bins.untyped_data()),
          gpair.typed_data(), pos.typed_data(), R, F, B, n0, N, stride, C,
          out->typed_data());
      break;
    default:
      return ffi::Error::InvalidArgument("xtb_hist: unsupported bin dtype");
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(XtbHist, XtbHistImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::AnyBuffer>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Attr<int32_t>("stride")
                                  .Ret<ffi::Buffer<ffi::F32>>());

// quantised limb hist: (bins[R,F], limbs[R,CL] i8, pos[R] i32, node0[1] i32)
//                      + attr stride -> out[N,F,B,CL] i32
static ffi::Error XtbHistQImpl(ffi::AnyBuffer bins,
                               ffi::Buffer<ffi::S8> limbs,
                               ffi::Buffer<ffi::S32> pos,
                               ffi::Buffer<ffi::S32> node0, int32_t stride,
                               ffi::ResultBuffer<ffi::S32> out) {
  auto bd = bins.dimensions();
  auto od = out->dimensions();
  if (bd.size() != 2 || od.size() != 4) {
    return ffi::Error::InvalidArgument("xtb_hist_q: bad ranks");
  }
  const int64_t R = bd[0];
  const int32_t F = static_cast<int32_t>(bd[1]);
  const int32_t N = static_cast<int32_t>(od[0]);
  const int32_t B = static_cast<int32_t>(od[2]);
  const int32_t CL = static_cast<int32_t>(od[3]);
  const int32_t n0 = node0.typed_data()[0];
#define XTB_HQ(TYPE)                                                       \
  xtb_hist_q_impl(static_cast<const TYPE*>(bins.untyped_data()),           \
                  limbs.typed_data(), pos.typed_data(), R, F, B, n0, N,    \
                  stride, CL, out->typed_data())
  switch (bins.element_type()) {
    case ffi::U8:
      XTB_HQ(uint8_t);
      break;
    case ffi::U16:
      XTB_HQ(uint16_t);
      break;
    case ffi::S16:
      XTB_HQ(int16_t);
      break;
    case ffi::S32:
      XTB_HQ(int32_t);
      break;
    default:
      return ffi::Error::InvalidArgument(
          "xtb_hist_q: unsupported bin dtype");
  }
#undef XTB_HQ
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(XtbHistQ, XtbHistQImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::AnyBuffer>()
                                  .Arg<ffi::Buffer<ffi::S8>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Attr<int32_t>("stride")
                                  .Ret<ffi::Buffer<ffi::S32>>());

// lambdarank top-k: (s[R] f32, y[R] f32, gptr[G+1] i32)
//                   + attrs (k, ndcg_weight, score_norm, group_norm)
//                   -> (grad[R] f32, hess[R] f32)
static ffi::Error XtbLambdaRankImpl(
    ffi::Buffer<ffi::F32> s, ffi::Buffer<ffi::F32> y,
    ffi::Buffer<ffi::S32> gptr, int32_t k, int32_t ndcg_weight,
    int32_t score_norm, int32_t group_norm,
    ffi::ResultBuffer<ffi::F32> grad, ffi::ResultBuffer<ffi::F32> hess) {
  const int64_t R = s.element_count();
  const int32_t G = static_cast<int32_t>(gptr.element_count()) - 1;
  if (G < 0 || y.element_count() != R) {
    return ffi::Error::InvalidArgument("xtb_lambdarank: bad shapes");
  }
  xtb_lambdarank_topk_impl(s.typed_data(), y.typed_data(),
                           gptr.typed_data(), G, R, k, ndcg_weight,
                           score_norm, group_norm, grad->typed_data(),
                           hess->typed_data());
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(XtbLambdaRank, XtbLambdaRankImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Attr<int32_t>("k")
                                  .Attr<int32_t>("ndcg_weight")
                                  .Attr<int32_t>("score_norm")
                                  .Attr<int32_t>("group_norm")
                                  .Ret<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());

// split: (hist[N,F,B,2] f32, totals[N,2] f32, n_bins[F] i32, fmask[N,F] u8)
//        + attrs (lam, alpha, mcw, mds)
//        -> (gain f32, feat i32, bin i32, dleft u8, GL f32, HL f32), each [N]
static ffi::Error XtbSplitImpl(
    ffi::Buffer<ffi::F32> hist, ffi::Buffer<ffi::F32> totals,
    ffi::Buffer<ffi::S32> n_bins, ffi::Buffer<ffi::U8> fmask, float lam,
    float alpha, float mcw, float mds, ffi::ResultBuffer<ffi::F32> gain,
    ffi::ResultBuffer<ffi::S32> feat, ffi::ResultBuffer<ffi::S32> bin,
    ffi::ResultBuffer<ffi::U8> dleft, ffi::ResultBuffer<ffi::F32> GL,
    ffi::ResultBuffer<ffi::F32> HL) {
  auto hd = hist.dimensions();
  if (hd.size() != 4 || hd[3] != 2) {
    return ffi::Error::InvalidArgument("xtb_split: bad hist shape");
  }
  const int32_t N = static_cast<int32_t>(hd[0]);
  const int32_t F = static_cast<int32_t>(hd[1]);
  const int32_t B = static_cast<int32_t>(hd[2]);
  xtb_split_scan_impl(hist.typed_data(), totals.typed_data(),
                      n_bins.typed_data(), fmask.typed_data(), N, F, B, lam,
                      alpha, mcw, mds, gain->typed_data(), feat->typed_data(),
                      bin->typed_data(), dleft->typed_data(),
                      GL->typed_data(), HL->typed_data());
  return ffi::Error::Success();
}

// predict (raw values): (X[R,F] f32, feat[T,M] i32, thr f32, dleft u8,
// left i32, right i32, value[T,M] or [T,M,K] f32, groups[T] i32,
// is_cat[T,M] u8, catm[T,M,Bc] u8, init[R,K] f32) + attrs (depth, has_cat)
// -> out[R,K] f32
static ffi::Error XtbPredictImpl(
    ffi::Buffer<ffi::F32> X, ffi::Buffer<ffi::S32> feat,
    ffi::Buffer<ffi::F32> thr, ffi::Buffer<ffi::U8> dleft,
    ffi::Buffer<ffi::S32> left, ffi::Buffer<ffi::S32> right,
    ffi::AnyBuffer value, ffi::Buffer<ffi::S32> groups,
    ffi::Buffer<ffi::U8> is_cat, ffi::Buffer<ffi::U8> catm,
    ffi::Buffer<ffi::F32> init, int32_t depth, int32_t has_cat,
    ffi::ResultBuffer<ffi::F32> out) {
  auto xd = X.dimensions();
  auto fd = feat.dimensions();
  auto od = out->dimensions();
  auto vd = value.dimensions();
  if (xd.size() != 2 || fd.size() != 2 || od.size() != 2 ||
      value.element_type() != ffi::F32) {
    return ffi::Error::InvalidArgument("xtb_predict: bad shapes");
  }
  const int64_t R = xd[0];
  const int32_t F = static_cast<int32_t>(xd[1]);
  const int32_t T = static_cast<int32_t>(fd[0]);
  const int32_t M = static_cast<int32_t>(fd[1]);
  const int32_t K = static_cast<int32_t>(od[1]);
  const int32_t K_leaf =
      vd.size() == 3 ? static_cast<int32_t>(vd[2]) : 1;
  const int32_t Bc =
      catm.dimensions().size() == 3
          ? static_cast<int32_t>(catm.dimensions()[2]) : 1;
  xtb_predict_raw_impl(
      X.typed_data(), R, F, feat.typed_data(), thr.typed_data(),
      dleft.typed_data(), left.typed_data(), right.typed_data(),
      static_cast<const float*>(value.untyped_data()), groups.typed_data(),
      T, M, depth, K, K_leaf, has_cat, is_cat.typed_data(),
      catm.typed_data(), Bc, init.typed_data(), out->typed_data());
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(XtbPredict, XtbPredictImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::U8>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Arg<ffi::AnyBuffer>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Arg<ffi::Buffer<ffi::U8>>()
                                  .Arg<ffi::Buffer<ffi::U8>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Attr<int32_t>("depth")
                                  .Attr<int32_t>("has_cat")
                                  .Ret<ffi::Buffer<ffi::F32>>());

// predict over a binned page: bins[R,F] u8|u16|i32 + sbin routing
static ffi::Error XtbPredictBinnedImpl(
    ffi::AnyBuffer bins, ffi::Buffer<ffi::S32> feat,
    ffi::Buffer<ffi::S32> sbin, ffi::Buffer<ffi::U8> dleft,
    ffi::Buffer<ffi::S32> left, ffi::Buffer<ffi::S32> right,
    ffi::Buffer<ffi::F32> value, ffi::Buffer<ffi::S32> groups,
    ffi::Buffer<ffi::U8> is_cat, ffi::Buffer<ffi::U8> catm,
    ffi::Buffer<ffi::F32> init, int32_t depth, int32_t has_cat,
    int32_t n_bin, ffi::ResultBuffer<ffi::F32> out) {
  auto bd = bins.dimensions();
  auto fd = feat.dimensions();
  auto od = out->dimensions();
  if (bd.size() != 2 || fd.size() != 2 || od.size() != 2) {
    return ffi::Error::InvalidArgument("xtb_predict_binned: bad shapes");
  }
  const int64_t R = bd[0];
  const int32_t F = static_cast<int32_t>(bd[1]);
  const int32_t T = static_cast<int32_t>(fd[0]);
  const int32_t M = static_cast<int32_t>(fd[1]);
  const int32_t K = static_cast<int32_t>(od[1]);
  const int32_t Bc =
      catm.dimensions().size() == 3
          ? static_cast<int32_t>(catm.dimensions()[2]) : 1;
#define XTB_PB(TYPE)                                                        \
  xtb_predict_binned_impl(static_cast<const TYPE*>(bins.untyped_data()), R, \
                          F, n_bin, feat.typed_data(), sbin.typed_data(),   \
                          dleft.typed_data(), left.typed_data(),            \
                          right.typed_data(), value.typed_data(),           \
                          groups.typed_data(), T, M, depth, K, has_cat,     \
                          is_cat.typed_data(), catm.typed_data(), Bc,       \
                          init.typed_data(), out->typed_data())
  switch (bins.element_type()) {
    case ffi::U8:
      XTB_PB(uint8_t);
      break;
    case ffi::U16:
      XTB_PB(uint16_t);
      break;
    case ffi::S16:
      XTB_PB(int16_t);
      break;
    case ffi::S32:
      XTB_PB(int32_t);
      break;
    default:
      return ffi::Error::InvalidArgument(
          "xtb_predict_binned: unsupported bin dtype");
  }
#undef XTB_PB
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(XtbPredictBinned, XtbPredictBinnedImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::AnyBuffer>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Arg<ffi::Buffer<ffi::U8>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Arg<ffi::Buffer<ffi::U8>>()
                                  .Arg<ffi::Buffer<ffi::U8>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Attr<int32_t>("depth")
                                  .Attr<int32_t>("has_cat")
                                  .Attr<int32_t>("n_bin")
                                  .Ret<ffi::Buffer<ffi::F32>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(XtbSplit, XtbSplitImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::S32>>()
                                  .Arg<ffi::Buffer<ffi::U8>>()
                                  .Attr<float>("lam")
                                  .Attr<float>("alpha")
                                  .Attr<float>("mcw")
                                  .Attr<float>("mds")
                                  .Ret<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::S32>>()
                                  .Ret<ffi::Buffer<ffi::S32>>()
                                  .Ret<ffi::Buffer<ffi::U8>>()
                                  .Ret<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());
