// SIMD dispatch seam for the native host kernels.
//
// This header is the ONLY translation-unit-visible home for raw vector
// intrinsics in the project (xtblint XTB601 rejects `_mm*`/`__m256`/NEON
// tokens anywhere else under native/).  Every entry point here carries a
// scalar fallback with IDENTICAL per-element semantics, and the vector
// bodies are written under the repo's bitwise determinism contract
// (docs/native_threading.md):
//
//   * elementwise-only float vector math (add/sub/mul/div/min/max/abs/
//     compare/blend) — per-lane IEEE-754 identical to the scalar ops, so
//     lanes equal the scalar loop bit for bit;
//   * NO FMA intrinsics and no reassociating horizontal reductions:
//     every f32 accumulation chain keeps the exact sequential element
//     order (the Makefile's -ffp-contract=off keeps the compiler from
//     contracting the scalar twins);
//   * integer lanes are exact, so integer kernels vectorize freely.
//
// Runtime CPU dispatch: the AVX2 bodies are compiled with a per-function
// `target("avx2")` attribute into every build, and selected at runtime via
// cpuid (`__builtin_cpu_supports`), so one .so runs on any x86-64 host.
// On aarch64, NEON is baseline and selected at compile time.  The active
// level is process-global, overridable by XGBOOST_TPU_SIMD
// (scalar|avx2|neon|auto) and the xtb_simd_set C ABI — the lane-width
// fuzz tests flip it to pin scalar == vector bitwise.
#ifndef XTB_SIMD_H_
#define XTB_SIMD_H_

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>

#if defined(__x86_64__) && defined(__GNUC__)
#define XTB_SIMD_X86 1
#include <immintrin.h>
#elif defined(__aarch64__)
#define XTB_SIMD_ARM 1
#include <arm_neon.h>
#endif

enum XtbSimdLevel {
  XTB_SIMD_SCALAR = 0,
  XTB_SIMD_AVX2 = 1,
  XTB_SIMD_NEON = 2,
};

inline const char* xtb_simd_level_name_impl(int lvl) {
  switch (lvl) {
    case XTB_SIMD_AVX2: return "avx2";
    case XTB_SIMD_NEON: return "neon";
    default: return "scalar";
  }
}

// Best level this host can run (cpuid on x86; NEON is baseline on aarch64).
inline int xtb_simd_detect_impl() {
#if XTB_SIMD_X86
  return __builtin_cpu_supports("avx2") ? XTB_SIMD_AVX2 : XTB_SIMD_SCALAR;
#elif XTB_SIMD_ARM
  return XTB_SIMD_NEON;
#else
  return XTB_SIMD_SCALAR;
#endif
}

// Raw cycle counter for the per-kernel perf accounting
// (xtb_kernels.h XtbKernelPerf -> xtb_native_kernel_cycles_total): TSC on
// x86-64 (invariant/constant-rate on every deployment target, so deltas
// across an invocation are meaningful), the virtual counter register on
// aarch64, 0 elsewhere (a 0 delta reads as "unavailable" downstream).
// Lives HERE because xtblint XTB601 confines raw intrinsics to this header.
inline uint64_t xtb_cycle_counter_impl() {
#if XTB_SIMD_X86
  return __builtin_ia32_rdtsc();
#elif XTB_SIMD_ARM
  uint64_t v;
  asm volatile("mrs %0, cntvct_el0" : "=r"(v));
  return v;
#else
  return 0;
#endif
}

inline int xtb_simd_resolve_impl(int requested) {
  const int det = xtb_simd_detect_impl();
  if (requested == XTB_SIMD_SCALAR) return XTB_SIMD_SCALAR;
  if (requested == det) return requested;
  return det;  // auto / unavailable request -> best available
}

inline int xtb_simd_env_level() {
  const char* env = std::getenv("XGBOOST_TPU_SIMD");
  if (env && *env) {
    if (!std::strcmp(env, "scalar") || !std::strcmp(env, "0")) {
      return XTB_SIMD_SCALAR;
    }
    if (!std::strcmp(env, "avx2")) return xtb_simd_resolve_impl(XTB_SIMD_AVX2);
    if (!std::strcmp(env, "neon")) return xtb_simd_resolve_impl(XTB_SIMD_NEON);
    if (std::strcmp(env, "auto") != 0) {
      // typos must be LOUD (set_simd raises on the Python side; a shared
      // library cannot, so warn) — silently running avx2 while the env
      // claims scalar would invalidate any benchmark or repro attempt
      std::fprintf(stderr,
                   "xtb_simd: unknown XGBOOST_TPU_SIMD=%s (expected "
                   "scalar|avx2|neon|auto); using detected best\n", env);
    }
  }
  return xtb_simd_detect_impl();  // auto
}

inline std::atomic<int>& xtb_simd_level_ref() {
  static std::atomic<int> level{xtb_simd_env_level()};
  return level;
}

// Results are bitwise level-independent, so flipping this mid-process is
// always safe; it only changes which (identical-output) body runs.
inline int xtb_simd_set_impl(int requested) {
  const int eff = requested < 0 ? xtb_simd_detect_impl()
                                : xtb_simd_resolve_impl(requested);
  xtb_simd_level_ref().store(eff, std::memory_order_relaxed);
  return eff;
}

inline int xtb_simd_active() {
  return xtb_simd_level_ref().load(std::memory_order_relaxed);
}

inline int xtb_simd_lanes_impl(int lvl) {
  return lvl == XTB_SIMD_AVX2 ? 8 : lvl == XTB_SIMD_NEON ? 4 : 1;
}

// ===========================================================================
// pos -> level-local node decode, shared by every hist kernel body (scalar
// kernels in xtb_kernels.h AND the AVX2 sweep bodies below): the routing
// semantics exist exactly once, so scalar/vector/u8/packed4 parity cannot
// drift.  Returns false when the row is outside this level's node range.
// ===========================================================================

inline bool xtb_pos_node(int32_t pos, int32_t node0, int32_t stride,
                         int32_t n_nodes, int32_t* node) {
  const int32_t local = pos - node0;
  if (local < 0) return false;
  int32_t n;
  if (stride == 2) {
    if (local & 1) return false;
    n = local >> 1;
  } else if (stride == 1) {
    n = local;
  } else {
    if (local % stride != 0) return false;
    n = local / stride;
  }
  if (n >= n_nodes) return false;
  *node = n;
  return true;
}

// ===========================================================================
// Histogram row vectorization (hist kernels, C == 2): load 8 contiguous
// bins of one row, compute the 8 destination indices and the in-range mask
// in vector registers, then do the 8 (g, h) adds SCALAR in lane order —
// lane order == feature order, so per output element the f32 adds keep the
// exact sequential order (the adds are to 8 *different* feature columns,
// so they could not collide anyway).  Only index prep vectorizes; this is
// deliberate: full scatter-adds would need conflict detection (AVX-512CD)
// and reassociation.  Row-blocked and column-major-mirror restructures
// were both measured SLOWER than this row sweep on the elementwise-pos
// Ellpack layout (see docs/perf_r7.md), so the row sweep stays.
//
// Contract: callers invoke the *_avx2 bodies only when xtb_simd_active()
// says AVX2 (hoisted per shard, not re-checked per row).  Returns features
// consumed (a multiple of 8); the caller's scalar loop finishes the rest.
// ===========================================================================

#if XTB_SIMD_X86
// Whole-shard sweep: the row loop (node decode, C == 2) lives inside the
// AVX2 function so the vector constants hoist once per shard, not per row.
// LOAD8 pulls 8 bins for features [f, f+8) of row pointer `br`.
#define XTB_HIST_SWEEP_BODY(LOAD8)                                          \
  const __m256i fstep = _mm256_setr_epi32(0, 1, 2, 3, 4, 5, 6, 7);          \
  const __m256i vnbin = _mm256_set1_epi32(n_bin);                           \
  alignas(32) int32_t idx[8];                                               \
  const int64_t nf8 = f0 + ((f1 - f0) & ~int64_t{7});                       \
  for (int64_t r = 0; r < R; ++r) {                                         \
    int32_t node;                                                           \
    if (!xtb_pos_node(pos[r], node0, stride, n_nodes, &node)) continue;     \
    const auto* br = bins + r * F;                                          \
    float* ob = out + node * node_sz;                                       \
    const float g = gpair[r * 2], h = gpair[r * 2 + 1];                     \
    float* obf = ob + f0 * 2 * n_bin;                                       \
    for (int64_t f = f0; f < nf8; f += 8) {                                 \
      const __m256i b = (LOAD8);                                            \
      const __m256i fidx = _mm256_add_epi32(                                \
          _mm256_set1_epi32(static_cast<int32_t>(f - f0)), fstep);          \
      const __m256i a = _mm256_slli_epi32(                                  \
          _mm256_add_epi32(_mm256_mullo_epi32(fidx, vnbin), b), 1);         \
      const int okm = _mm256_movemask_ps(                                   \
          _mm256_castsi256_ps(_mm256_cmpgt_epi32(vnbin, b)));               \
      _mm256_store_si256(reinterpret_cast<__m256i*>(idx), a);               \
      for (int k = 0; k < 8; ++k) {                                         \
        if (okm >> k & 1) {                                                 \
          float* p = obf + idx[k];                                          \
          p[0] += g;                                                        \
          p[1] += h;                                                        \
        }                                                                   \
      }                                                                     \
    }                                                                       \
    for (int64_t f = nf8; f < f1; ++f) {                                    \
      const int32_t b = static_cast<int32_t>(br[f]);                        \
      if (b < n_bin) {                                                      \
        float* p = ob + (static_cast<size_t>(f) * n_bin + b) * 2;           \
        p[0] += g;                                                          \
        p[1] += h;                                                          \
      }                                                                     \
    }                                                                       \
  }

#define XTB_HIST_SWEEP_DECL(BIN_T, LOAD8)                                   \
  __attribute__((target("avx2"))) inline void xtb_hist_sweep_avx2(          \
      const BIN_T* bins, const float* gpair, const int32_t* pos, int64_t R, \
      int32_t F, int64_t f0, int64_t f1, int32_t n_bin, int32_t node0,      \
      int32_t n_nodes, int32_t stride, size_t node_sz, float* out) {        \
    XTB_HIST_SWEEP_BODY(LOAD8)                                              \
  }

XTB_HIST_SWEEP_DECL(uint8_t, _mm256_cvtepu8_epi32(_mm_loadl_epi64(
    reinterpret_cast<const __m128i*>(br + f))))
XTB_HIST_SWEEP_DECL(uint16_t, _mm256_cvtepu16_epi32(_mm_loadu_si128(
    reinterpret_cast<const __m128i*>(br + f))))
XTB_HIST_SWEEP_DECL(int16_t, _mm256_cvtepi16_epi32(_mm_loadu_si128(
    reinterpret_cast<const __m128i*>(br + f))))
XTB_HIST_SWEEP_DECL(int32_t, _mm256_loadu_si256(
    reinterpret_cast<const __m256i*>(br + f)))
#undef XTB_HIST_SWEEP_DECL
#undef XTB_HIST_SWEEP_BODY

// 4-bit packed variant (bench-only, scripts/bitpack_bench.py): 4 packed
// bytes -> 8 nibble lanes via byte-duplicating shuffle + per-lane shift —
// the `vpgatherdd`-era shift/mask unpack, fused into the same index prep.
// Feature shards are nibble-aligned by the caller (f0 even).
__attribute__((target("avx2"))) inline void xtb_hist_sweep_p4_avx2(
    const uint8_t* packed, const float* gpair, const int32_t* pos, int64_t R,
    int32_t F, int64_t f0, int64_t f1, int32_t n_bin, int32_t node0,
    int32_t n_nodes, int32_t stride, size_t node_sz, float* out) {
  const int32_t Fp = (F + 1) / 2;
  const __m128i dup = _mm_setr_epi8(0, 0, 1, 1, 2, 2, 3, 3,
                                    -1, -1, -1, -1, -1, -1, -1, -1);
  const __m256i nib_shift = _mm256_setr_epi32(0, 4, 0, 4, 0, 4, 0, 4);
  const __m256i nib_mask = _mm256_set1_epi32(0xF);
  const __m256i fstep = _mm256_setr_epi32(0, 1, 2, 3, 4, 5, 6, 7);
  const __m256i vnbin = _mm256_set1_epi32(n_bin);
  alignas(32) int32_t idx[8];
  const int64_t nf8 = f0 + ((f1 - f0) & ~int64_t{7});
  for (int64_t r = 0; r < R; ++r) {
    int32_t node;
    if (!xtb_pos_node(pos[r], node0, stride, n_nodes, &node)) continue;
    const uint8_t* br = packed + r * Fp;
    float* ob = out + node * node_sz;
    const float g = gpair[r * 2], h = gpair[r * 2 + 1];
    float* obf = ob + f0 * 2 * n_bin;
    for (int64_t f = f0; f < nf8; f += 8) {
      int32_t w;
      memcpy(&w, br + (f >> 1), 4);
      const __m128i bytes = _mm_shuffle_epi8(_mm_cvtsi32_si128(w), dup);
      const __m256i b = _mm256_and_si256(
          _mm256_srlv_epi32(_mm256_cvtepu8_epi32(bytes), nib_shift),
          nib_mask);
      const __m256i fidx = _mm256_add_epi32(
          _mm256_set1_epi32(static_cast<int32_t>(f - f0)), fstep);
      const __m256i a = _mm256_slli_epi32(
          _mm256_add_epi32(_mm256_mullo_epi32(fidx, vnbin), b), 1);
      const int okm = _mm256_movemask_ps(
          _mm256_castsi256_ps(_mm256_cmpgt_epi32(vnbin, b)));
      _mm256_store_si256(reinterpret_cast<__m256i*>(idx), a);
      for (int k = 0; k < 8; ++k) {
        if (okm >> k & 1) {
          float* p = obf + idx[k];
          p[0] += g;
          p[1] += h;
        }
      }
    }
    for (int64_t f = nf8; f < f1; ++f) {
      const int32_t b = (br[f >> 1] >> ((f & 1) * 4)) & 0xF;
      if (b < n_bin) {
        float* p = ob + (static_cast<size_t>(f) * n_bin + b) * 2;
        p[0] += g;
        p[1] += h;
      }
    }
  }
}
#endif  // XTB_SIMD_X86

// ===========================================================================
// Split-scan candidate evaluation (xtb_split_scan_impl): given the serial
// prefix sums glr/hlr per bin (computed by the caller in exact sequential
// order), evaluate both missing-direction candidates per bin.  All math is
// elementwise, so the AVX2 body equals the scalar body lane for lane; the
// scalar body is a faithful transcription of the original in-loop code.
// Only the max_delta_step == 0 fast path is vectorized — callers keep the
// original scalar loop otherwise.
// ===========================================================================

struct XtbSplitEvalArgs {
  float totG, totH, missG, missH, parent;
  float lambda_, alpha, min_child_weight;
};

inline float xtb_gain_mds0(float G, float H, float lambda_, float alpha) {
  if (H <= 0.0f) return 0.0f;
  float a = fabsf(G) - alpha;
  if (a < 0.0f) a = 0.0f;
  return a * a / (H + lambda_);  // == t*t/(H+l): (-a)*(-a) is bitwise a*a
}

inline void xtb_split_eval_scalar(const float* glr, const float* hlr,
                                  const uint8_t* okb, int32_t b0, int32_t b1,
                                  const XtbSplitEvalArgs& a, float* g2_out,
                                  uint8_t* dl_out, float* GL_out,
                                  float* HL_out) {
  for (int32_t b = b0; b < b1; ++b) {
    if (!okb[b]) {
      g2_out[b] = -INFINITY;
      dl_out[b] = 1;
      GL_out[b] = glr[b];
      HL_out[b] = hlr[b];
      continue;
    }
    float g2 = -INFINITY;
    bool dl2 = true;
    {  // missing -> right
      const float GR = a.totG - glr[b], HR = a.totH - hlr[b];
      if (hlr[b] >= a.min_child_weight && HR >= a.min_child_weight &&
          hlr[b] > 0.0f && HR > 0.0f) {
        g2 = xtb_gain_mds0(glr[b], hlr[b], a.lambda_, a.alpha) +
             xtb_gain_mds0(GR, HR, a.lambda_, a.alpha) - a.parent;
        dl2 = false;
      }
    }
    const float gll = glr[b] + a.missG, hll = hlr[b] + a.missH;
    {  // missing -> left
      const float GR = a.totG - gll, HR = a.totH - hll;
      if (hll >= a.min_child_weight && HR >= a.min_child_weight &&
          hll > 0.0f && HR > 0.0f) {
        const float gl_gain = xtb_gain_mds0(gll, hll, a.lambda_, a.alpha) +
                              xtb_gain_mds0(GR, HR, a.lambda_, a.alpha) -
                              a.parent;
        if (gl_gain >= g2) {
          g2 = gl_gain;
          dl2 = true;
        }
      }
    }
    g2_out[b] = g2;
    dl_out[b] = dl2 ? 1 : 0;
    GL_out[b] = dl2 ? gll : glr[b];
    HL_out[b] = dl2 ? hll : hlr[b];
  }
}

#if XTB_SIMD_X86
__attribute__((target("avx2"))) inline __m256 xtb_gain_mds0_avx2(
    __m256 G, __m256 H, __m256 vlam, __m256 valpha) {
  const __m256 sign = _mm256_set1_ps(-0.0f);
  const __m256 zero = _mm256_setzero_ps();
  __m256 a = _mm256_sub_ps(_mm256_andnot_ps(sign, G), valpha);
  // scalar twin: `if (a < 0) a = 0` — a NaN `a` (inf gradients upstream)
  // must STAY NaN so the candidate loses `gain > best` exactly like the
  // scalar build.  maxps would quietly map NaN -> 0; blend on `a < 0`
  // (false for NaN) keeps the lane NaN.
  a = _mm256_blendv_ps(a, zero, _mm256_cmp_ps(a, zero, _CMP_LT_OQ));
  const __m256 q = _mm256_div_ps(_mm256_mul_ps(a, a), _mm256_add_ps(H, vlam));
  // H <= 0 -> 0 (invalid lanes may hold inf/nan; the blend discards them)
  const __m256 hpos = _mm256_cmp_ps(H, zero, _CMP_GT_OQ);
  return _mm256_and_ps(q, hpos);
}

__attribute__((target("avx2"))) inline void xtb_split_eval_avx2(
    const float* glr, const float* hlr, const uint8_t* okb, int32_t bmax,
    const XtbSplitEvalArgs& a, float* g2_out, uint8_t* dl_out, float* GL_out,
    float* HL_out) {
  const __m256 vtotG = _mm256_set1_ps(a.totG);
  const __m256 vtotH = _mm256_set1_ps(a.totH);
  const __m256 vmissG = _mm256_set1_ps(a.missG);
  const __m256 vmissH = _mm256_set1_ps(a.missH);
  const __m256 vparent = _mm256_set1_ps(a.parent);
  const __m256 vlam = _mm256_set1_ps(a.lambda_);
  const __m256 valpha = _mm256_set1_ps(a.alpha);
  const __m256 vmcw = _mm256_set1_ps(a.min_child_weight);
  const __m256 vninf = _mm256_set1_ps(-INFINITY);
  const __m256 vzero = _mm256_setzero_ps();
  int32_t b = 0;
  for (; b + 8 <= bmax; b += 8) {
    const __m256 vglr = _mm256_loadu_ps(glr + b);
    const __m256 vhlr = _mm256_loadu_ps(hlr + b);
    // missing -> right candidate
    const __m256 GR = _mm256_sub_ps(vtotG, vglr);
    const __m256 HR = _mm256_sub_ps(vtotH, vhlr);
    __m256 valid_r = _mm256_and_ps(
        _mm256_and_ps(_mm256_cmp_ps(vhlr, vmcw, _CMP_GE_OQ),
                      _mm256_cmp_ps(HR, vmcw, _CMP_GE_OQ)),
        _mm256_and_ps(_mm256_cmp_ps(vhlr, vzero, _CMP_GT_OQ),
                      _mm256_cmp_ps(HR, vzero, _CMP_GT_OQ)));
    const __m256 gain_r = _mm256_sub_ps(
        _mm256_add_ps(xtb_gain_mds0_avx2(vglr, vhlr, vlam, valpha),
                      xtb_gain_mds0_avx2(GR, HR, vlam, valpha)),
        vparent);
    __m256 g2 = _mm256_blendv_ps(vninf, gain_r, valid_r);
    // missing -> left candidate
    const __m256 gll = _mm256_add_ps(vglr, vmissG);
    const __m256 hll = _mm256_add_ps(vhlr, vmissH);
    const __m256 GR2 = _mm256_sub_ps(vtotG, gll);
    const __m256 HR2 = _mm256_sub_ps(vtotH, hll);
    const __m256 valid_l = _mm256_and_ps(
        _mm256_and_ps(_mm256_cmp_ps(hll, vmcw, _CMP_GE_OQ),
                      _mm256_cmp_ps(HR2, vmcw, _CMP_GE_OQ)),
        _mm256_and_ps(_mm256_cmp_ps(hll, vzero, _CMP_GT_OQ),
                      _mm256_cmp_ps(HR2, vzero, _CMP_GT_OQ)));
    const __m256 gain_l = _mm256_sub_ps(
        _mm256_add_ps(xtb_gain_mds0_avx2(gll, hll, vlam, valpha),
                      xtb_gain_mds0_avx2(GR2, HR2, vlam, valpha)),
        vparent);
    // dl2 = take_left || !valid_r  (scalar: dl2 starts true, right sets
    // false, a winning/tying left restores true)
    const __m256 take_left =
        _mm256_and_ps(valid_l, _mm256_cmp_ps(gain_l, g2, _CMP_GE_OQ));
    g2 = _mm256_blendv_ps(g2, gain_l, take_left);
    const __m256 dl = _mm256_or_ps(
        take_left, _mm256_xor_ps(valid_r, _mm256_castsi256_ps(
                                              _mm256_set1_epi32(-1))));
    // !ok bins are never candidates
    const __m256 ok = _mm256_castsi256_ps(_mm256_cmpgt_epi32(
        _mm256_cvtepu8_epi32(
            _mm_loadl_epi64(reinterpret_cast<const __m128i*>(okb + b))),
        _mm256_setzero_si256()));
    g2 = _mm256_blendv_ps(vninf, g2, ok);
    _mm256_storeu_ps(g2_out + b, g2);
    _mm256_storeu_ps(GL_out + b, _mm256_blendv_ps(vglr, gll, dl));
    _mm256_storeu_ps(HL_out + b, _mm256_blendv_ps(vhlr, hll, dl));
    const int m = _mm256_movemask_ps(dl);
    for (int k = 0; k < 8; ++k) dl_out[b + k] = (m >> k) & 1;
  }
  if (b < bmax) {
    xtb_split_eval_scalar(glr, hlr, okb, b, bmax, a, g2_out, dl_out, GL_out,
                          HL_out);
  }
}
#endif  // XTB_SIMD_X86

#if XTB_SIMD_ARM
inline float32x4_t xtb_gain_mds0_neon(float32x4_t G, float32x4_t H,
                                      float32x4_t vlam, float32x4_t valpha) {
  float32x4_t a = vsubq_f32(vabsq_f32(G), valpha);
  // blend on `a < 0` (false for NaN) so a NaN `a` stays NaN like the
  // scalar twin — see the AVX2 body's note
  a = vbslq_f32(vcltq_f32(a, vdupq_n_f32(0.0f)), vdupq_n_f32(0.0f), a);
  const float32x4_t q = vdivq_f32(vmulq_f32(a, a), vaddq_f32(H, vlam));
  const uint32x4_t hpos = vcgtq_f32(H, vdupq_n_f32(0.0f));
  return vreinterpretq_f32_u32(
      vandq_u32(vreinterpretq_u32_f32(q), hpos));
}

inline void xtb_split_eval_neon(const float* glr, const float* hlr,
                                const uint8_t* okb, int32_t bmax,
                                const XtbSplitEvalArgs& a, float* g2_out,
                                uint8_t* dl_out, float* GL_out,
                                float* HL_out) {
  const float32x4_t vtotG = vdupq_n_f32(a.totG);
  const float32x4_t vtotH = vdupq_n_f32(a.totH);
  const float32x4_t vmissG = vdupq_n_f32(a.missG);
  const float32x4_t vmissH = vdupq_n_f32(a.missH);
  const float32x4_t vparent = vdupq_n_f32(a.parent);
  const float32x4_t vlam = vdupq_n_f32(a.lambda_);
  const float32x4_t valpha = vdupq_n_f32(a.alpha);
  const float32x4_t vmcw = vdupq_n_f32(a.min_child_weight);
  const float32x4_t vninf = vdupq_n_f32(-INFINITY);
  const float32x4_t vzero = vdupq_n_f32(0.0f);
  int32_t b = 0;
  for (; b + 4 <= bmax; b += 4) {
    const float32x4_t vglr = vld1q_f32(glr + b);
    const float32x4_t vhlr = vld1q_f32(hlr + b);
    const float32x4_t GR = vsubq_f32(vtotG, vglr);
    const float32x4_t HR = vsubq_f32(vtotH, vhlr);
    const uint32x4_t valid_r = vandq_u32(
        vandq_u32(vcgeq_f32(vhlr, vmcw), vcgeq_f32(HR, vmcw)),
        vandq_u32(vcgtq_f32(vhlr, vzero), vcgtq_f32(HR, vzero)));
    const float32x4_t gain_r = vsubq_f32(
        vaddq_f32(xtb_gain_mds0_neon(vglr, vhlr, vlam, valpha),
                  xtb_gain_mds0_neon(GR, HR, vlam, valpha)),
        vparent);
    float32x4_t g2 = vbslq_f32(valid_r, gain_r, vninf);
    const float32x4_t gll = vaddq_f32(vglr, vmissG);
    const float32x4_t hll = vaddq_f32(vhlr, vmissH);
    const float32x4_t GR2 = vsubq_f32(vtotG, gll);
    const float32x4_t HR2 = vsubq_f32(vtotH, hll);
    const uint32x4_t valid_l = vandq_u32(
        vandq_u32(vcgeq_f32(hll, vmcw), vcgeq_f32(HR2, vmcw)),
        vandq_u32(vcgtq_f32(hll, vzero), vcgtq_f32(HR2, vzero)));
    const float32x4_t gain_l = vsubq_f32(
        vaddq_f32(xtb_gain_mds0_neon(gll, hll, vlam, valpha),
                  xtb_gain_mds0_neon(GR2, HR2, vlam, valpha)),
        vparent);
    const uint32x4_t take_left = vandq_u32(valid_l, vcgeq_f32(gain_l, g2));
    g2 = vbslq_f32(take_left, gain_l, g2);
    const uint32x4_t dl = vorrq_u32(take_left, vmvnq_u32(valid_r));
    uint32_t okw[4], dlw[4];
    for (int k = 0; k < 4; ++k) okw[k] = okb[b + k] ? ~0u : 0u;
    const uint32x4_t ok = vld1q_u32(okw);
    g2 = vbslq_f32(ok, g2, vninf);
    vst1q_f32(g2_out + b, g2);
    vst1q_f32(GL_out + b, vbslq_f32(dl, gll, vglr));
    vst1q_f32(HL_out + b, vbslq_f32(dl, hll, vhlr));
    vst1q_u32(dlw, dl);
    for (int k = 0; k < 4; ++k) dl_out[b + k] = dlw[k] ? 1 : 0;
  }
  if (b < bmax) {
    xtb_split_eval_scalar(glr, hlr, okb, b, bmax, a, g2_out, dl_out, GL_out,
                          HL_out);
  }
}
#endif  // XTB_SIMD_ARM

inline void xtb_split_eval(const float* glr, const float* hlr,
                           const uint8_t* okb, int32_t bmax,
                           const XtbSplitEvalArgs& a, float* g2_out,
                           uint8_t* dl_out, float* GL_out, float* HL_out) {
#if XTB_SIMD_X86
  if (xtb_simd_active() == XTB_SIMD_AVX2) {
    xtb_split_eval_avx2(glr, hlr, okb, bmax, a, g2_out, dl_out, GL_out,
                        HL_out);
    return;
  }
#elif XTB_SIMD_ARM
  if (xtb_simd_active() == XTB_SIMD_NEON) {
    xtb_split_eval_neon(glr, hlr, okb, bmax, a, g2_out, dl_out, GL_out,
                        HL_out);
    return;
  }
#endif
  xtb_split_eval_scalar(glr, hlr, okb, 0, bmax, a, g2_out, dl_out, GL_out,
                        HL_out);
}

// ===========================================================================
// Lane-per-row ensemble traversal (predict kernels).  Eight rows ride the
// vector lanes through one tree at a time: gathers fetch each lane's node
// fields, blends pick the child, frozen (leaf-reached) lanes keep their
// node id.  Per ROW, leaf values still accumulate in tree order — the same
// f32 add chain as the scalar loop — so outputs are bitwise identical.
//
// The raw variant gathers X as exact-width f32.  The binned variant (and
// the dleft byte array in both) use 32-bit gathers over sub-word elements,
// which read up to 3 bytes past the addressed element: callers pass
// `r_vec_end` <= the last row whose gathers stay in-bounds (buffer interior
// is always safe — the next row's data provides the slack; only the final
// rows of the whole buffer go scalar), and dleft is copied into a 4-byte
// padded scratch by the caller.
// Returns the number of rows consumed from r0 (a multiple of 8); the caller
// finishes the rest with the scalar loop.
// ===========================================================================

#if XTB_SIMD_X86
__attribute__((target("avx2"))) inline int64_t xtb_predict_raw_rows_avx2(
    const float* X, int64_t r0, int64_t r1, int32_t F, const int32_t* feat,
    const float* thr, const uint8_t* dleft_pad, const int32_t* left,
    const int32_t* right, const float* value, const int32_t* groups,
    int32_t T, int32_t M, int32_t depth, int32_t K, float* out) {
  const __m256i vzero = _mm256_setzero_si256();
  const __m256i lane_rows = _mm256_setr_epi32(0, 1, 2, 3, 4, 5, 6, 7);
  int64_t r = r0;
  for (; r + 8 <= r1; r += 8) {
    // per-lane base index into X: (r + lane) * F
    const __m256i xbase = _mm256_mullo_epi32(
        _mm256_add_epi32(_mm256_set1_epi32(static_cast<int32_t>(r)),
                         lane_rows),
        _mm256_set1_epi32(F));
    for (int32_t t = 0; t < T; ++t) {
      const size_t base = static_cast<size_t>(t) * M;
      const int32_t* featb = feat + base;
      const float* thrb = thr + base;
      const uint8_t* dlb = dleft_pad + base;
      const int32_t* lb = left + base;
      const int32_t* rb = right + base;
      __m256i nid = vzero;
      __m256i done = vzero;
      for (int32_t d = 0; d < depth; ++d) {
        const __m256i fi = _mm256_i32gather_epi32(featb, nid, 4);
        done = _mm256_or_si256(done, _mm256_cmpgt_epi32(vzero, fi));
        if (_mm256_movemask_epi8(done) == -1) break;
        const __m256i fi_safe = _mm256_andnot_si256(done, fi);
        const __m256 x = _mm256_i32gather_ps(
            X, _mm256_add_epi32(xbase, fi_safe), 4);
        const __m256 thrv = _mm256_i32gather_ps(thrb, nid, 4);
        const __m256i dlv = _mm256_and_si256(
            _mm256_i32gather_epi32(reinterpret_cast<const int*>(dlb), nid, 1),
            _mm256_set1_epi32(0xFF));
        const __m256 miss = _mm256_cmp_ps(x, x, _CMP_UNORD_Q);
        const __m256 lt = _mm256_cmp_ps(x, thrv, _CMP_LT_OQ);
        const __m256 dlm =
            _mm256_castsi256_ps(_mm256_cmpgt_epi32(dlv, vzero));
        const __m256i gol =
            _mm256_castps_si256(_mm256_blendv_ps(lt, dlm, miss));
        const __m256i lv = _mm256_i32gather_epi32(lb, nid, 4);
        const __m256i rv = _mm256_i32gather_epi32(rb, nid, 4);
        const __m256i nid_next = _mm256_blendv_epi8(rv, lv, gol);
        nid = _mm256_blendv_epi8(nid_next, nid, done);
      }
      const __m256 leaf = _mm256_i32gather_ps(value + base, nid, 4);
      float lv8[8];
      _mm256_storeu_ps(lv8, leaf);
      const int32_t g = groups[t];
      for (int k = 0; k < 8; ++k) out[(r + k) * K + g] += lv8[k];
    }
  }
  return r - r0;
}

template <int kSize, int kMask>
__attribute__((target("avx2"))) inline int64_t xtb_predict_binned_rows_avx2(
    const void* bins, int64_t r0, int64_t r_vec_end, int32_t F, int32_t n_bin,
    const int32_t* feat, const int32_t* sbin, const uint8_t* dleft_pad,
    const int32_t* left, const int32_t* right, const float* value,
    const int32_t* groups, int32_t T, int32_t M, int32_t depth, int32_t K,
    float* out) {
  const __m256i vzero = _mm256_setzero_si256();
  const __m256i lane_rows = _mm256_setr_epi32(0, 1, 2, 3, 4, 5, 6, 7);
  const __m256i vnbin = _mm256_set1_epi32(n_bin);
  int64_t r = r0;
  for (; r + 8 <= r_vec_end; r += 8) {
    const __m256i bbase = _mm256_mullo_epi32(
        _mm256_add_epi32(_mm256_set1_epi32(static_cast<int32_t>(r)),
                         lane_rows),
        _mm256_set1_epi32(F * kSize));
    for (int32_t t = 0; t < T; ++t) {
      const size_t base = static_cast<size_t>(t) * M;
      const int32_t* featb = feat + base;
      const int32_t* sbinb = sbin + base;
      const uint8_t* dlb = dleft_pad + base;
      const int32_t* lb = left + base;
      const int32_t* rb = right + base;
      __m256i nid = vzero;
      __m256i done = vzero;
      for (int32_t d = 0; d < depth; ++d) {
        const __m256i fi = _mm256_i32gather_epi32(featb, nid, 4);
        done = _mm256_or_si256(done, _mm256_cmpgt_epi32(vzero, fi));
        if (_mm256_movemask_epi8(done) == -1) break;
        const __m256i fi_safe = _mm256_andnot_si256(done, fi);
        __m256i b = _mm256_i32gather_epi32(
            reinterpret_cast<const int*>(bins),
            _mm256_add_epi32(
                bbase, _mm256_mullo_epi32(fi_safe,
                                          _mm256_set1_epi32(kSize))),
            1);
        if (kMask != -1) b = _mm256_and_si256(b, _mm256_set1_epi32(kMask));
        const __m256i sbv = _mm256_i32gather_epi32(sbinb, nid, 4);
        const __m256i dlv = _mm256_and_si256(
            _mm256_i32gather_epi32(reinterpret_cast<const int*>(dlb), nid, 1),
            _mm256_set1_epi32(0xFF));
        // gol = b <= sbin  ==  !(b > sbin)
        __m256i gol = _mm256_xor_si256(_mm256_cmpgt_epi32(b, sbv),
                                       _mm256_set1_epi32(-1));
        const __m256i miss = _mm256_xor_si256(
            _mm256_cmpgt_epi32(vnbin, b), _mm256_set1_epi32(-1));
        gol = _mm256_blendv_epi8(gol, _mm256_cmpgt_epi32(dlv, vzero), miss);
        const __m256i lv = _mm256_i32gather_epi32(lb, nid, 4);
        const __m256i rv = _mm256_i32gather_epi32(rb, nid, 4);
        const __m256i nid_next = _mm256_blendv_epi8(rv, lv, gol);
        nid = _mm256_blendv_epi8(nid_next, nid, done);
      }
      const __m256 leaf = _mm256_i32gather_ps(value + base, nid, 4);
      float lv8[8];
      _mm256_storeu_ps(lv8, leaf);
      const int32_t g = groups[t];
      for (int k = 0; k < 8; ++k) out[(r + k) * K + g] += lv8[k];
    }
  }
  return r - r0;
}
#endif  // XTB_SIMD_X86

#endif  // XTB_SIMD_H_
