// Native host runtime for xgboost_tpu.
//
// The reference keeps its data plumbing in C++ (text parsers in
// dmlc-core/src/data, CSR adapters src/data/adapter.h, GK quantile summaries
// src/common/quantile.h) while the device code does the math.  Same split
// here: JAX/XLA owns the TPU compute path; this library owns the host-side
// hot loops — libsvm/CSV parsing into CSR and a streaming weighted quantile
// summary (merge-prune, GK-style) used by the external-memory sketcher.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 dependency).
#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

// brings in the ParallelFor pool (+ its C ABI: xtb_set_nthread and friends)
// and the shared kernel bodies; the quantile summary below and the SHAP
// entry point thread through it
#define XTB_DEFINE_POOL_ABI
#include "xtb_kernels.h"

extern "C" {

// ---------------------------------------------------------------------------
// libsvm parser: "label [qid:q] idx:val idx:val ..." lines -> CSR
// ---------------------------------------------------------------------------
struct CSROut {
  std::vector<int64_t> indptr;
  std::vector<int32_t> indices;
  std::vector<float> values;
  std::vector<float> labels;
  std::vector<int64_t> qids;
  int32_t n_features = 0;
  bool has_qid = false;
};

static inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

void* xtb_parse_libsvm(const char* data, int64_t len) {
  auto* out = new CSROut();
  out->indptr.push_back(0);
  const char* p = data;
  const char* end = data + len;
  while (p < end) {
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!line_end) line_end = end;
    p = skip_ws(p, line_end);
    if (p < line_end && *p != '#') {
      char* next = nullptr;
      float label = strtof(p, &next);
      if (next == p) { p = line_end + 1; continue; }
      out->labels.push_back(label);
      p = next;
      while (p < line_end) {
        p = skip_ws(p, line_end);
        if (p >= line_end || *p == '#') break;
        // qid:N or idx:val
        if (line_end - p > 4 && strncmp(p, "qid:", 4) == 0) {
          out->has_qid = true;
          out->qids.push_back(strtoll(p + 4, &next, 10));
          p = next;
          continue;
        }
        long idx = strtol(p, &next, 10);
        if (next == p || next >= line_end || *next != ':') break;
        p = next + 1;
        float v = strtof(p, &next);
        if (next == p) break;
        p = next;
        out->indices.push_back(static_cast<int32_t>(idx));
        out->values.push_back(v);
        if (idx + 1 > out->n_features) out->n_features = idx + 1;
      }
      out->indptr.push_back(static_cast<int64_t>(out->indices.size()));
    }
    p = line_end + 1;
  }
  return out;
}

// ---------------------------------------------------------------------------
// CSV parser: numeric CSV (optional NaN blanks) -> dense row-major f32
// ---------------------------------------------------------------------------
struct DenseOut {
  std::vector<float> data;
  int64_t rows = 0;
  int32_t cols = 0;
};

void* xtb_parse_csv(const char* data, int64_t len, int skip_header) {
  auto* out = new DenseOut();
  const char* p = data;
  const char* end = data + len;
  if (skip_header && p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    p = nl ? nl + 1 : end;
  }
  std::vector<float> row;
  while (p < end) {
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!line_end) line_end = end;
    const char* content = skip_ws(p, line_end);
    if (content < line_end) {  // blank/whitespace-only lines never set cols
      row.clear();
      const char* q = p;
      while (q <= line_end) {
        const char* field_end = q;
        while (field_end < line_end && *field_end != ',') ++field_end;
        const char* f = skip_ws(q, field_end);
        if (f == field_end) {
          row.push_back(NAN);
        } else {
          char* next = nullptr;
          float v = strtof(f, &next);
          row.push_back(next == f ? NAN : v);
        }
        if (field_end >= line_end) break;
        q = field_end + 1;
      }
      if (out->cols == 0) out->cols = static_cast<int32_t>(row.size());
      // ragged rows are padded with NaN / truncated, never silently dropped
      row.resize(out->cols, NAN);
      out->data.insert(out->data.end(), row.begin(), row.end());
      out->rows += 1;
    }
    p = line_end + 1;
  }
  return out;
}

// ---- accessors / lifetime ----
int64_t xtb_csr_rows(void* h) { return static_cast<CSROut*>(h)->indptr.size() - 1; }
int64_t xtb_csr_nnz(void* h) { return static_cast<CSROut*>(h)->indices.size(); }
int32_t xtb_csr_cols(void* h) { return static_cast<CSROut*>(h)->n_features; }
int32_t xtb_csr_has_qid(void* h) { return static_cast<CSROut*>(h)->has_qid ? 1 : 0; }
int64_t xtb_csr_qid_count(void* h) { return static_cast<CSROut*>(h)->qids.size(); }
void xtb_csr_copy(void* h, int64_t* indptr, int32_t* indices, float* values,
                  float* labels, int64_t* qids) {
  auto* o = static_cast<CSROut*>(h);
  memcpy(indptr, o->indptr.data(), o->indptr.size() * sizeof(int64_t));
  memcpy(indices, o->indices.data(), o->indices.size() * sizeof(int32_t));
  memcpy(values, o->values.data(), o->values.size() * sizeof(float));
  memcpy(labels, o->labels.data(), o->labels.size() * sizeof(float));
  if (o->has_qid && qids) memcpy(qids, o->qids.data(), o->qids.size() * sizeof(int64_t));
}
void xtb_csr_free(void* h) { delete static_cast<CSROut*>(h); }

int64_t xtb_dense_rows(void* h) { return static_cast<DenseOut*>(h)->rows; }
int32_t xtb_dense_cols(void* h) { return static_cast<DenseOut*>(h)->cols; }
void xtb_dense_copy(void* h, float* dst) {
  auto* o = static_cast<DenseOut*>(h);
  memcpy(dst, o->data.data(), o->data.size() * sizeof(float));
}
void xtb_dense_free(void* h) { delete static_cast<DenseOut*>(h); }

// ---------------------------------------------------------------------------
// Streaming weighted quantile summary (GK-style merge-prune).
// One summary per feature; Push batches, Prune to a budget, query quantiles.
// Mirrors the role of WQuantileSketch (src/common/quantile.h:565) without
// copying its structure: entries keep (value, weight); prune resamples the
// weighted CDF at uniform ranks.
// ---------------------------------------------------------------------------
struct QuantileSummary {
  std::vector<std::pair<float, double>> entries;  // (value, weight), sorted
  size_t budget;
  double total = 0.0;

  explicit QuantileSummary(size_t b) : budget(b) {}

  // Shard-parallel sort + sequential fold of inplace_merges.  The (value,
  // weight) pair comparison is a total order up to EXACT duplicates, so the
  // merged sequence is element-for-element the std::sort result and every
  // downstream prune/query stays bitwise identical for any thread count.
  static void sort_batch(std::vector<std::pair<float, double>>* batch) {
    const int64_t n = static_cast<int64_t>(batch->size());
    if (n < (1 << 14)) {
      std::sort(batch->begin(), batch->end());
      return;
    }
    std::vector<std::pair<int64_t, int64_t>> runs;
    std::mutex runs_mu;
    xtb_parallel_for(n, 1 << 12, XTB_K_SKETCH,
                     [&](int64_t b, int64_t e) {
                       std::sort(batch->begin() + b, batch->begin() + e);
                       std::lock_guard<std::mutex> g(runs_mu);
                       runs.emplace_back(b, e);
                     });
    std::sort(runs.begin(), runs.end());
    for (size_t i = 1; i < runs.size(); ++i) {
      std::inplace_merge(batch->begin() + runs[0].first,
                         batch->begin() + runs[i].first,
                         batch->begin() + runs[i].second);
    }
  }

  void push(const float* vals, const float* wts, int64_t n) {
    std::vector<std::pair<float, double>> batch;
    batch.reserve(n);
    for (int64_t i = 0; i < n; ++i) {
      float v = vals[i];
      if (std::isnan(v)) continue;
      double w = wts ? static_cast<double>(wts[i]) : 1.0;
      if (w <= 0) continue;
      batch.emplace_back(v, w);
      total += w;
    }
    sort_batch(&batch);
    // merge two sorted runs
    std::vector<std::pair<float, double>> merged;
    merged.reserve(entries.size() + batch.size());
    std::merge(entries.begin(), entries.end(), batch.begin(), batch.end(),
               std::back_inserter(merged));
    entries.swap(merged);
    if (entries.size() > budget * 2) prune();
  }

  void prune() {
    if (entries.size() <= budget) return;
    // collapse duplicates, then resample the weighted CDF at uniform ranks
    std::vector<std::pair<float, double>> uniq;
    uniq.reserve(entries.size());
    for (auto& e : entries) {
      if (!uniq.empty() && uniq.back().first == e.first) {
        uniq.back().second += e.second;
      } else {
        uniq.push_back(e);
      }
    }
    if (uniq.size() <= budget) { entries.swap(uniq); return; }
    std::vector<double> cdf(uniq.size());
    double acc = 0;
    for (size_t i = 0; i < uniq.size(); ++i) { acc += uniq[i].second; cdf[i] = acc; }
    std::vector<std::pair<float, double>> pruned;
    pruned.reserve(budget);
    double prev_rank = 0.0;
    size_t j = 0;
    for (size_t k = 1; k <= budget; ++k) {
      double target = acc * static_cast<double>(k) / budget;
      while (j + 1 < uniq.size() && cdf[j] < target) ++j;
      double w = cdf[j] - prev_rank;
      if (w > 0 || pruned.empty() || pruned.back().first != uniq[j].first) {
        pruned.emplace_back(uniq[j].first, w > 0 ? w : 0.0);
      }
      prev_rank = cdf[j];
      if (j + 1 < uniq.size()) ++j;
      else break;
    }
    entries.swap(pruned);
  }

  void query(const double* qs, int n_q, float* out) {
    // no forced prune: an unpruned summary answers exactly (matches the
    // in-core inverted-CDF quantiles when the data fit in the budget)
    double acc = 0;
    std::vector<double> cdf(entries.size());
    for (size_t i = 0; i < entries.size(); ++i) { acc += entries[i].second; cdf[i] = acc; }
    for (int k = 0; k < n_q; ++k) {
      double target = qs[k] * acc;
      size_t lo = std::lower_bound(cdf.begin(), cdf.end(), target) - cdf.begin();
      if (lo >= entries.size()) lo = entries.empty() ? 0 : entries.size() - 1;
      out[k] = entries.empty() ? 0.0f : entries[lo].first;
    }
  }
};

// ---------------------------------------------------------------------------
// Exact TreeSHAP over one tree (xtb_kernels.h xtb_shap_values_impl): the
// native, row-parallel twin of interpret/__init__.py's host walk.  out is
// (R, F+1) f64, zeroed by the caller; the bias column stays untouched
// (Python fills the cover-weighted expectation, as the host walk does).
// ---------------------------------------------------------------------------
void xtb_shap_values(const double* X, int64_t R, int32_t F,
                     const int32_t* left, const int32_t* right,
                     const int32_t* feat, const double* thr,
                     const uint8_t* dleft, const double* value,
                     const double* cover, int32_t max_depth, double* out) {
  XtbShapTree t{left, right, feat, thr, dleft, value, cover};
  xtb_shap_values_impl(X, R, F, t, max_depth, out);
}

// ---------------------------------------------------------------------------
// Ellpack native ingestion (xtb_kernels.h xtb_ellpack_bin_impl): bin a dense
// f32 matrix against quantile cuts, bitwise-equal to the XLA searchsorted
// formulation in data/ellpack.py.  dtype_code: 0 = u8, 1 = i16, 2 = i32
// (ellpack.py _bin_dtype's ladder).
// ---------------------------------------------------------------------------
void xtb_ellpack_bin(const float* X, int64_t R, int32_t F,
                     const float* cut_values, const int32_t* cut_ptrs,
                     int32_t B, int32_t dtype_code, void* out) {
  switch (dtype_code) {
    case 0:
      xtb_ellpack_bin_impl(X, R, F, cut_values, cut_ptrs, B,
                           static_cast<uint8_t*>(out));
      break;
    case 1:
      xtb_ellpack_bin_impl(X, R, F, cut_values, cut_ptrs, B,
                           static_cast<int16_t*>(out));
      break;
    default:
      xtb_ellpack_bin_impl(X, R, F, cut_values, cut_ptrs, B,
                           static_cast<int32_t*>(out));
  }
}

// ---------------------------------------------------------------------------
// Bench/ctypes twins of the hist kernels (scripts/bitpack_bench.py --native):
// the resident-u8 layout vs the 4-bit packed layout, both through the same
// blocked + vector-gather machinery, so the bitpack decision compares
// layouts rather than dispatch overheads.
// ---------------------------------------------------------------------------
void xtb_hist_f32_u8(const uint8_t* bins, const float* gpair,
                     const int32_t* pos, int64_t R, int32_t F, int32_t n_bin,
                     int32_t node0, int32_t n_nodes, int32_t stride,
                     int32_t C, float* out) {
  xtb_hist_build_impl(bins, gpair, pos, R, F, n_bin, node0, n_nodes, stride,
                      C, out);
}

void xtb_hist_packed4(const uint8_t* packed, const float* gpair,
                      const int32_t* pos, int64_t R, int32_t F,
                      int32_t n_bin, int32_t node0, int32_t n_nodes,
                      int32_t stride, float* out) {
  xtb_hist_packed4_impl(packed, gpair, pos, R, F, n_bin, node0, n_nodes,
                        stride, out);
}

void* xtb_summary_new(int64_t budget) { return new QuantileSummary(budget); }
void xtb_summary_push(void* h, const float* vals, const float* wts, int64_t n) {
  static_cast<QuantileSummary*>(h)->push(vals, wts, n);
}
void xtb_summary_query(void* h, const double* qs, int32_t n_q, float* out) {
  static_cast<QuantileSummary*>(h)->query(qs, n_q, out);
}
double xtb_summary_total(void* h) { return static_cast<QuantileSummary*>(h)->total; }
void xtb_summary_free(void* h) { delete static_cast<QuantileSummary*>(h); }

}  // extern "C"
