/* The R binding's exact C-ABI call sequence, driven from plain C.
 *
 * r-package/src/xtb_R.c cannot be compiled here (no R toolchain in the
 * image), so this program pins the ABI contract it depends on: the same
 * functions, in the same order, with the same conversions (column-major
 * double input -> row-major float, group info as unsigned, buffer
 * save/load round-trip, text dump).  Run by
 * tests/test_c_api.py::test_r_glue_sequence.
 *
 *   gcc r_glue_seq.c -L. -lxtb_capi -o r_glue_seq
 *   PYTHONPATH=/root/repo LD_LIBRARY_PATH=. ./r_glue_seq
 */
#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef void* DMatrixHandle;
typedef void* BoosterHandle;
typedef uint64_t bst_ulong;

extern const char* XGBGetLastError(void);
extern int XGDMatrixCreateFromMat(const float*, bst_ulong, bst_ulong, float,
                                  DMatrixHandle*);
extern int XGDMatrixSetFloatInfo(DMatrixHandle, const char*, const float*,
                                 bst_ulong);
extern int XGDMatrixSetUIntInfo(DMatrixHandle, const char*, const unsigned*,
                                bst_ulong);
extern int XGDMatrixNumRow(DMatrixHandle, bst_ulong*);
extern int XGDMatrixNumCol(DMatrixHandle, bst_ulong*);
extern int XGDMatrixFree(DMatrixHandle);
extern int XGBoosterCreate(const DMatrixHandle[], bst_ulong, BoosterHandle*);
extern int XGBoosterFree(BoosterHandle);
extern int XGBoosterSetParam(BoosterHandle, const char*, const char*);
extern int XGBoosterUpdateOneIter(BoosterHandle, int, DMatrixHandle);
extern int XGBoosterEvalOneIter(BoosterHandle, int, DMatrixHandle[],
                                const char*[], bst_ulong, const char**);
extern int XGBoosterPredict(BoosterHandle, DMatrixHandle, int, unsigned, int,
                            bst_ulong*, const float**);
extern int XGBoosterSaveModelToBuffer(BoosterHandle, const char*, bst_ulong*,
                                      const char**);
extern int XGBoosterLoadModelFromBuffer(BoosterHandle, const void*,
                                        bst_ulong);
extern int XGBoosterDumpModelEx(BoosterHandle, const char*, int, const char*,
                                bst_ulong*, const char***);
extern int XGDMatrixGetFloatInfo(const DMatrixHandle, const char*,
                                 bst_ulong*, const float**);
extern int XGDMatrixSliceDMatrixEx(DMatrixHandle, const int*, bst_ulong,
                                   DMatrixHandle*, int);
extern int XGBoosterSetAttr(BoosterHandle, const char*, const char*);
extern int XGBoosterGetAttr(BoosterHandle, const char*, const char**, int*);

#define CHECK(call)                                                   \
  do {                                                                \
    if ((call) != 0) {                                                \
      fprintf(stderr, "FAILED %s: %s\n", #call, XGBGetLastError());   \
      exit(1);                                                        \
    }                                                                 \
  } while (0)

enum { R = 600, F = 5, ROUNDS = 6 };

int main(void) {
  /* R hands the glue a column-major double matrix; the glue transposes to
   * row-major float (xtb_R.c XTBDMatrixCreateFromMat_R) */
  static double colmajor[(size_t)R * F];
  static float rowmajor[(size_t)R * F];
  static float label[R];
  unsigned seed = 42;
  for (int j = 0; j < F; ++j)
    for (int i = 0; i < R; ++i) {
      seed = seed * 1664525u + 1013904223u;
      colmajor[(size_t)j * R + i] = ((double)(seed >> 8) / (1 << 24)) - 0.5;
    }
  for (int i = 0; i < R; ++i) {
    colmajor[(size_t)2 * R + i] = (i % 37 == 0) ? NAN : colmajor[2 * R + i];
    label[i] = colmajor[i] > 0.0 ? 1.0f : 0.0f; /* column 0 drives y */
  }
  for (int j = 0; j < F; ++j)
    for (int i = 0; i < R; ++i)
      rowmajor[(size_t)i * F + j] = (float)colmajor[(size_t)j * R + i];

  DMatrixHandle dtrain = NULL;
  CHECK(XGDMatrixCreateFromMat(rowmajor, R, F, NAN, &dtrain));
  CHECK(XGDMatrixSetFloatInfo(dtrain, "label", label, R));
  static float wts[R];
  for (int i = 0; i < R; ++i) wts[i] = 1.0f + (i % 3) * 0.25f;
  CHECK(XGDMatrixSetFloatInfo(dtrain, "weight", wts, R));
  bst_ulong nr = 0, nc = 0;
  CHECK(XGDMatrixNumRow(dtrain, &nr));
  CHECK(XGDMatrixNumCol(dtrain, &nc));
  if (nr != R || nc != F) {
    fprintf(stderr, "dim mismatch %llu x %llu\n",
            (unsigned long long)nr, (unsigned long long)nc);
    return 1;
  }

  BoosterHandle bst = NULL;
  DMatrixHandle dmats[1] = {dtrain};
  CHECK(XGBoosterCreate(dmats, 1, &bst));
  CHECK(XGBoosterSetParam(bst, "objective", "binary:logistic"));
  CHECK(XGBoosterSetParam(bst, "max_depth", "4"));
  CHECK(XGBoosterSetParam(bst, "eta", "0.3"));
  CHECK(XGBoosterSetParam(bst, "eval_metric", "logloss"));

  const char* names[1] = {"train"};
  const char* evalmsg = NULL;
  double first_ll = 0, last_ll = 0;
  for (int it = 0; it < ROUNDS; ++it) {
    CHECK(XGBoosterUpdateOneIter(bst, it, dtrain));
    CHECK(XGBoosterEvalOneIter(bst, it, dmats, names, 1, &evalmsg));
    const char* p = strstr(evalmsg, "logloss:");
    if (p == NULL) {
      fprintf(stderr, "no logloss in eval msg: %s\n", evalmsg);
      return 1;
    }
    double ll = atof(p + 8);
    if (it == 0) first_ll = ll;
    last_ll = ll;
  }
  if (!(last_ll < first_ll)) {
    fprintf(stderr, "logloss did not improve: %f -> %f\n", first_ll, last_ll);
    return 1;
  }

  bst_ulong plen = 0;
  const float* preds = NULL;
  CHECK(XGBoosterPredict(bst, dtrain, 0, 0, 0, &plen, &preds));
  if (plen != R) {
    fprintf(stderr, "predict len %llu\n", (unsigned long long)plen);
    return 1;
  }
  int err = 0;
  for (int i = 0; i < R; ++i) err += (preds[i] > 0.5f) != (label[i] > 0.5f);
  if (err > R / 10) {
    fprintf(stderr, "train error too high: %d/%d\n", err, R);
    return 1;
  }
  static float keep[R];
  memcpy(keep, preds, sizeof(keep));

  /* buffer round-trip (xgb.save.raw / xgb.load.raw path) */
  bst_ulong blen = 0;
  const char* buf = NULL;
  CHECK(XGBoosterSaveModelToBuffer(bst, "ubj", &blen, &buf));
  char* copy = (char*)malloc(blen);
  memcpy(copy, buf, blen);
  BoosterHandle bst2 = NULL;
  CHECK(XGBoosterCreate(NULL, 0, &bst2));
  CHECK(XGBoosterLoadModelFromBuffer(bst2, copy, blen));
  free(copy);
  CHECK(XGBoosterPredict(bst2, dtrain, 0, 0, 0, &plen, &preds));
  for (int i = 0; i < R; ++i)
    if (preds[i] != keep[i]) {
      fprintf(stderr, "round-trip mismatch at %d\n", i);
      return 1;
    }

  /* text dump (xgb.dump path) */
  bst_ulong dlen = 0;
  const char** dump = NULL;
  CHECK(XGBoosterDumpModelEx(bst, "", 0, "text", &dlen, &dump));
  if (dlen != ROUNDS || strstr(dump[0], "leaf") == NULL) {
    fprintf(stderr, "dump unexpected (%llu trees)\n",
            (unsigned long long)dlen);
    return 1;
  }

  /* --- the xgb.cv / setinfo / attr surface (r-package/R/xgb.cv.R) --- */

  /* getinfo round-trip */
  bst_ulong ln = 0;
  const float* lab = NULL;
  CHECK(XGDMatrixGetFloatInfo(dtrain, "label", &ln, &lab));
  if (ln != R || lab[0] != label[0]) {
    fprintf(stderr, "getinfo label mismatch\n");
    return 1;
  }

  /* fold slice (xgb.slice.DMatrix): odd rows as a validation fold */
  static int idx[R / 2];
  for (int i = 0; i < R / 2; ++i) idx[i] = 2 * i + 1;
  DMatrixHandle dfold = NULL;
  CHECK(XGDMatrixSliceDMatrixEx(dtrain, idx, R / 2, &dfold, 0));
  CHECK(XGDMatrixNumRow(dfold, &nr));
  if (nr != R / 2) {
    fprintf(stderr, "slice rows %llu\n", (unsigned long long)nr);
    return 1;
  }
  bst_ulong fln = 0;
  const float* flab = NULL;
  CHECK(XGDMatrixGetFloatInfo(dfold, "label", &fln, &flab));
  if (fln != R / 2 || flab[0] != label[1]) { /* meta info rode along */
    fprintf(stderr, "slice label mismatch\n");
    return 1;
  }

  /* repeated eval_metric SetParam appends (xgb.cv metrics vector) */
  BoosterHandle bcv = NULL;
  DMatrixHandle cvmats[2] = {dtrain, dfold};
  CHECK(XGBoosterCreate(cvmats, 2, &bcv));
  CHECK(XGBoosterSetParam(bcv, "objective", "binary:logistic"));
  CHECK(XGBoosterSetParam(bcv, "eval_metric", "logloss"));
  CHECK(XGBoosterSetParam(bcv, "eval_metric", "auc"));
  const char* cvnames[2] = {"train", "test"};
  CHECK(XGBoosterUpdateOneIter(bcv, 0, dtrain));
  CHECK(XGBoosterEvalOneIter(bcv, 0, cvmats, cvnames, 2, &evalmsg));
  if (strstr(evalmsg, "test-logloss:") == NULL ||
      strstr(evalmsg, "test-auc:") == NULL) {
    fprintf(stderr, "appended metrics missing in eval: %s\n", evalmsg);
    return 1;
  }

  /* best-iteration attrs (xgb.train early stopping) */
  CHECK(XGBoosterSetAttr(bcv, "best_iteration", "3"));
  const char* attr = NULL;
  int ok = 0;
  CHECK(XGBoosterGetAttr(bcv, "best_iteration", &attr, &ok));
  if (!ok || strcmp(attr, "3") != 0) {
    fprintf(stderr, "attr round-trip failed\n");
    return 1;
  }
  CHECK(XGBoosterGetAttr(bcv, "never_set", &attr, &ok));
  if (ok) {
    fprintf(stderr, "missing attr reported present\n");
    return 1;
  }

  CHECK(XGBoosterFree(bcv));
  CHECK(XGDMatrixFree(dfold));
  CHECK(XGBoosterFree(bst2));
  CHECK(XGBoosterFree(bst));
  CHECK(XGDMatrixFree(dtrain));
  printf("R-GLUE-SEQ-OK err=%d/%d logloss %.4f->%.4f\n", err, R, first_ll,
         last_ll);
  return 0;
}
