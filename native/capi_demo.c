/* Demo: train + predict through the xgboost_tpu C ABI from plain C
 * (reference: demo/c-api/basic/c-api-demo.c pattern).
 *
 *   gcc capi_demo.c -L. -lxtb_capi -o capi_demo
 *   PYTHONPATH=/root/repo LD_LIBRARY_PATH=. ./capi_demo
 */
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <stdint.h>
#include <stdlib.h>

typedef void* DMatrixHandle;
typedef void* BoosterHandle;
typedef uint64_t bst_ulong;

extern const char* XGBGetLastError(void);
extern int XGBoostVersion(int*, int*, int*);
extern int XGDMatrixCreateFromMat(const float*, bst_ulong, bst_ulong, float,
                                  DMatrixHandle*);
extern int XGDMatrixSetFloatInfo(DMatrixHandle, const char*, const float*,
                                 bst_ulong);
extern int XGDMatrixNumRow(DMatrixHandle, bst_ulong*);
extern int XGDMatrixFree(DMatrixHandle);
extern int XGBoosterCreate(const DMatrixHandle[], bst_ulong, BoosterHandle*);
extern int XGBoosterSetParam(BoosterHandle, const char*, const char*);
extern int XGBoosterUpdateOneIter(BoosterHandle, int, DMatrixHandle);
extern int XGBoosterEvalOneIter(BoosterHandle, int, DMatrixHandle[],
                                const char*[], bst_ulong, const char**);
extern int XGBoosterPredict(BoosterHandle, DMatrixHandle, int, unsigned, int,
                            bst_ulong*, const float**);
extern int XGBoosterSaveModel(BoosterHandle, const char*);
extern int XGBoosterLoadModel(BoosterHandle, const char*);
extern int XGBoosterFree(BoosterHandle);
extern int XGBoosterSaveJsonConfig(BoosterHandle, bst_ulong*, const char**);
extern int XGBoosterSerializeToBuffer(BoosterHandle, bst_ulong*,
                                      const char**);
extern int XGBoosterUnserializeFromBuffer(BoosterHandle, const void*,
                                          bst_ulong);
extern int XGBoosterPredictFromDense(BoosterHandle, const char*, const char*,
                                     DMatrixHandle, const bst_ulong**,
                                     bst_ulong*, const float**);
extern int XGBoosterDumpModelEx(BoosterHandle, const char*, int, const char*,
                                bst_ulong*, const char***);

#define CHECK(call)                                                   \
  do {                                                                \
    if ((call) != 0) {                                                \
      fprintf(stderr, "FAILED %s: %s\n", #call, XGBGetLastError());   \
      exit(1);                                                        \
    }                                                                 \
  } while (0)

int main(void) {
  int maj, min, patch;
  CHECK(XGBoostVersion(&maj, &min, &patch));
  printf("xgboost_tpu C API %d.%d.%d\n", maj, min, patch);

  enum { R = 400, F = 4 };
  static float data[R * F];
  static float label[R];
  unsigned s = 42;
  for (int i = 0; i < R; ++i) {
    float sum = 0.f;
    for (int j = 0; j < F; ++j) {
      s = s * 1664525u + 1013904223u;
      float v = (float)(s >> 8) / (float)(1 << 24) - 0.5f;
      data[i * F + j] = v;
      sum += v;
    }
    label[i] = sum > 0.f ? 1.0f : 0.0f;
  }

  DMatrixHandle dtrain;
  CHECK(XGDMatrixCreateFromMat(data, R, F, -999.0f, &dtrain));
  CHECK(XGDMatrixSetFloatInfo(dtrain, "label", label, R));
  bst_ulong nrow;
  CHECK(XGDMatrixNumRow(dtrain, &nrow));
  printf("rows: %llu\n", (unsigned long long)nrow);

  BoosterHandle booster;
  CHECK(XGBoosterCreate(&dtrain, 1, &booster));
  CHECK(XGBoosterSetParam(booster, "objective", "binary:logistic"));
  CHECK(XGBoosterSetParam(booster, "max_depth", "3"));
  CHECK(XGBoosterSetParam(booster, "eta", "0.3"));

  const char* names[1] = {"train"};
  DMatrixHandle sets[1] = {dtrain};
  for (int it = 0; it < 5; ++it) {
    CHECK(XGBoosterUpdateOneIter(booster, it, dtrain));
    const char* msg = NULL;
    CHECK(XGBoosterEvalOneIter(booster, it, sets, names, 1, &msg));
    printf("%s\n", msg);
  }

  bst_ulong len = 0;
  const float* preds = NULL;
  CHECK(XGBoosterPredict(booster, dtrain, 0, 0, 0, &len, &preds));
  printf("preds[0..2]: %f %f %f (n=%llu)\n", preds[0], preds[1], preds[2],
         (unsigned long long)len);

  CHECK(XGBoosterSaveModel(booster, "/tmp/capi_model.json"));
  BoosterHandle loaded;
  CHECK(XGBoosterCreate(NULL, 0, &loaded));
  CHECK(XGBoosterLoadModel(loaded, "/tmp/capi_model.json"));
  bst_ulong len2 = 0;
  const float* preds2 = NULL;
  CHECK(XGBoosterPredict(loaded, dtrain, 0, 0, 0, &len2, &preds2));
  int ok = len == len2;
  for (bst_ulong i = 0; ok && i < len; ++i) ok = preds[i] == preds2[i];
  printf("save/load predictions identical: %s\n", ok ? "yes" : "NO");

  /* round-3 surface: config IO, serialization, inplace predict, dump */
  bst_ulong clen = 0;
  const char* cstr = NULL;
  CHECK(XGBoosterSaveJsonConfig(booster, &clen, &cstr));
  int has_obj = strstr(cstr, "binary:logistic") != NULL;
  printf("json config carries objective: %s\n", has_obj ? "yes" : "NO");

  bst_ulong blen = 0;
  const char* blob = NULL;
  CHECK(XGBoosterSerializeToBuffer(booster, &blen, &blob));
  BoosterHandle restored;
  CHECK(XGBoosterCreate(NULL, 0, &restored));
  CHECK(XGBoosterUnserializeFromBuffer(restored, blob, blen));
  bst_ulong len3 = 0;
  const float* preds3 = NULL;
  CHECK(XGBoosterPredict(restored, dtrain, 0, 0, 0, &len3, &preds3));
  int ok2 = len == len3;
  for (bst_ulong i = 0; ok2 && i < len; ++i) ok2 = preds[i] == preds3[i];
  printf("serialize/unserialize predictions identical: %s\n",
         ok2 ? "yes" : "NO");
  CHECK(XGBoosterFree(restored));

  /* preds points at the handle's pinned buffer; the next predict on the
   * same handle invalidates it (reference thread-local entry semantics) */
  static float preds_copy[R];
  for (bst_ulong i = 0; i < len; ++i) preds_copy[i] = preds[i];

  char aif[256];
  snprintf(aif, sizeof(aif),
           "{\"data\": [%llu, true], \"shape\": [%d, %d], "
           "\"typestr\": \"<f4\", \"version\": 3}",
           (unsigned long long)(uintptr_t)data, R, F);
  bst_ulong const* pshape = NULL;
  bst_ulong pdim = 0;
  const float* ppreds = NULL;
  CHECK(XGBoosterPredictFromDense(booster, aif, "{\"type\": 0}", NULL,
                                  &pshape, &pdim, &ppreds));
  int ok3 = pdim == 1 && pshape[0] == (bst_ulong)R;
  for (bst_ulong i = 0; ok3 && i < len; ++i) ok3 = preds_copy[i] == ppreds[i];
  printf("inplace dense predict identical: %s\n", ok3 ? "yes" : "NO");

  bst_ulong ndump = 0;
  const char** dumps = NULL;
  CHECK(XGBoosterDumpModelEx(booster, "", 1, "json", &ndump, &dumps));
  printf("dumped %llu trees, tree0 starts: %.20s\n",
         (unsigned long long)ndump, dumps[0]);

  CHECK(XGBoosterFree(booster));
  CHECK(XGBoosterFree(loaded));
  CHECK(XGDMatrixFree(dtrain));
  if (!(ok && ok2 && ok3)) return 1;
  printf("C API DEMO OK\n");
  return 0;
}
