"""Multi-quantile / multi-expectile objectives + the pre/ams/expectile
metrics (reference: quantile_obj.cu, regression_obj.cu ExpectileRegression,
rank_metric.cc EvalPrecision/EvalAMS, elementwise_metric.cu ExpectileError)."""
import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.metric import create_metric


def test_multi_quantile_training():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 6)).astype(np.float32)
    y = (X[:, 0] * 2 + rng.normal(scale=1.0, size=2000)).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    alphas = [0.1, 0.5, 0.9]
    res = {}
    bst = xtb.train({"objective": "reg:quantileerror",
                     "quantile_alpha": alphas, "max_depth": 4, "eta": 0.3},
                    d, 15, evals=[(d, "t")], evals_result=res,
                    verbose_eval=False)
    p = bst.predict(d)
    assert p.shape == (2000, 3)
    # quantile ordering holds on average and empirical coverage is sane
    cov = [(y <= p[:, k]).mean() for k in range(3)]
    assert cov[0] < cov[1] < cov[2]
    assert abs(cov[0] - 0.1) < 0.1 and abs(cov[2] - 0.9) < 0.1
    assert res["t"]["quantile"][-1] < res["t"]["quantile"][0]


def test_multi_expectile_training():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(1500, 5)).astype(np.float32)
    y = (X[:, 0] + 0.5 * rng.normal(size=1500)).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    res = {}
    bst = xtb.train({"objective": "reg:expectileerror",
                     "expectile_alpha": [0.2, 0.5, 0.8], "max_depth": 4},
                    d, 15, evals=[(d, "t")], evals_result=res,
                    verbose_eval=False)
    p = bst.predict(d)
    assert p.shape == (1500, 3)
    # expectile direction: higher alpha -> higher prediction
    assert p[:, 0].mean() < p[:, 1].mean() < p[:, 2].mean()
    assert res["t"]["expectile"][-1] < res["t"]["expectile"][0]


def test_single_quantile_still_scalar():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(800, 4)).astype(np.float32)
    y = X[:, 0].astype(np.float32)
    bst = xtb.train({"objective": "reg:quantileerror", "quantile_alpha": 0.5,
                     "max_depth": 3}, xtb.DMatrix(X, label=y), 5,
                    verbose_eval=False)
    assert bst.predict(xtb.DMatrix(X)).ndim == 1


def test_precision_metric():
    fn, _ = create_metric("pre@3")
    preds = np.array([9, 8, 7, 6, 5, 4], np.float64)
    labels = np.array([1, 0, 1, 1, 0, 1], np.float64)
    # top-3 by pred = labels [1,0,1] -> 2/3
    assert abs(fn(preds, labels) - 2 / 3) < 1e-12
    # two groups
    gp = np.array([0, 3, 6])
    v = fn(preds, labels, group_ptr=gp)
    assert abs(v - ((2 / 3 + 2 / 3) / 2)) < 1e-12


def test_ams_metric():
    fn, _ = create_metric("ams@0.5")
    rng = np.random.default_rng(3)
    preds = rng.random(1000)
    labels = (preds + 0.3 * rng.random(1000) > 0.8).astype(np.float64)
    v = fn(preds, labels)
    assert v > 0.0 and np.isfinite(v)
    # informative ranking scores higher than random ranking
    v_rand = fn(rng.random(1000), labels)
    assert v > v_rand


def test_expectile_metric_matches_formula():
    fn, _ = create_metric("expectile@0.8")
    preds = np.array([1.0, 2.0, 3.0])
    labels = np.array([2.0, 2.0, 2.0])
    diff = preds - labels
    err = np.where(diff >= 0, 0.2, 0.8) * diff ** 2
    assert abs(fn(preds, labels) - err.mean()) < 1e-12


def test_generic_metric_on_multiquantile_model():
    """rmse (a non-alpha-aware metric) on a multi-quantile model broadcasts
    labels per level instead of crashing."""
    rng = np.random.default_rng(4)
    X = rng.normal(size=(600, 4)).astype(np.float32)
    y = X[:, 0].astype(np.float32)
    res = {}
    xtb.train({"objective": "reg:quantileerror", "quantile_alpha": [0.2, 0.8],
               "eval_metric": ["rmse", "quantile"], "max_depth": 3},
              xtb.DMatrix(X, label=y), 3, evals=[(xtb.DMatrix(X, label=y), "t")],
              evals_result=res, verbose_eval=False)
    assert np.isfinite(res["t"]["rmse"][-1])
    assert np.isfinite(res["t"]["quantile"][-1])


def test_untrained_metric_level_raises():
    from xgboost_tpu.metric import create_metric

    fn, _ = create_metric("quantile@0.25")
    preds = np.zeros((10, 3))
    with pytest.raises(ValueError, match="not trained"):
        fn(preds, np.zeros(10), alphas=[0.1, 0.5, 0.9])
