"""Online learning loop (docs/online.md): feedback join, drift
detection, scheduler policy, the shadow comparator's PSI/calibration
gates, and the closed loop end to end over a real replica fleet.
"""
import os
import time

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.lifecycle import LifecycleConfig, LifecycleManager
from xgboost_tpu.online import (DriftConfig, DriftDetector, FeedbackHub,
                                OnlineConfig, OnlineScheduler, WindowStore)
from xgboost_tpu.reliability import faults, resources
from xgboost_tpu.serving import ModelStore

PARAMS = {"objective": "binary:logistic", "max_depth": 3,
          "eval_metric": "logloss", "seed": 7}


def _data(seed=10, n=3000, f=8):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


def _train(X, y, rounds=4):
    return xtb.train(PARAMS, xtb.DMatrix(X, label=y), rounds,
                     verbose_eval=False)


def _rec(trace, tag=0, rows=4, model="m"):
    rng = np.random.default_rng(200 + tag)
    X = rng.standard_normal((rows, 3)).astype(np.float32)
    return {"model": model, "trace": trace, "X": X,
            "scores": rng.random(rows).astype(np.float32)}


# ============================================================ FeedbackHub

def test_hub_joins_in_either_order():
    hub = FeedbackHub(horizon_s=100.0, clock=lambda: 0.0)
    hub.offer(_rec("a-1"))
    assert hub.label("a-1", [1.0] * 4) is True       # label second
    assert hub.label("a-2", [0.0] * 4) is False      # label first
    hub.offer(_rec("a-2"))
    out = hub.drain()
    assert [r["trace"] for r in out] == ["a-1", "a-2"]
    np.testing.assert_array_equal(out[0]["y"], np.ones(4, np.float32))
    s = hub.stats()
    assert s["matched"] == 2 and s["dropped"] == {}
    assert s["pending_features"] == 0 and s["pending_labels"] == 0


def test_hub_expires_both_sides_past_horizon():
    now = [0.0]
    hub = FeedbackHub(horizon_s=10.0, clock=lambda: now[0])
    hub.offer(_rec("a-1"))
    hub.label("a-2", [1.0])
    now[0] = 20.0
    hub.offer(_rec("a-3"))       # any call sweeps the expired front
    assert hub.label("a-1", [1.0]) is False  # its features already expired
    s = hub.stats()
    assert s["dropped"]["expired"] == 2
    assert s["matched"] == 0


def test_hub_capacity_drops_oldest():
    hub = FeedbackHub(horizon_s=1e9, max_pending=2, clock=lambda: 0.0)
    for i in range(4):
        hub.offer(_rec(f"a-{i:x}"))
    s = hub.stats()
    assert s["pending_features"] == 2
    assert s["dropped"]["capacity"] == 2
    # the two newest survived
    assert hub.label("a-3", [1.0]) is True
    assert hub.label("a-0", [1.0]) is False


def test_hub_duplicates_and_untraced_counted():
    hub = FeedbackHub(horizon_s=100.0, clock=lambda: 0.0)
    hub.offer(_rec("a-1"))
    hub.offer(_rec("a-1"))            # replica reroute re-executed sample
    hub.offer({"model": "m", "X": np.ones((1, 2))})  # no trace
    hub.label("a-9", [1.0])
    hub.label("a-9", [1.0])           # duplicate label
    s = hub.stats()
    assert s["dropped"]["duplicate"] == 2
    assert s["dropped"]["untraced"] == 1


def test_hub_label_join_fault_seam_drops_label():
    faults.install([{"site": "online.label_join", "kind": "exception"}])
    try:
        hub = FeedbackHub(horizon_s=100.0, clock=lambda: 0.0)
        hub.offer(_rec("a-1"))
        assert hub.label("a-1", [1.0]) is False
        s = hub.stats()
        assert s["dropped"]["fault"] == 1
        assert s["pending_features"] == 1  # features still wait, unharmed
    finally:
        faults.clear()


# ========================================================== DriftDetector

def test_drift_self_primes_and_stays_quiet_on_same_distribution():
    det = DriftDetector(min_rows=32, current_rows=256)
    rng = np.random.default_rng(0)
    X = rng.standard_normal((40, 4))
    s = rng.random(40)
    det.observe(X, s)            # first min_rows become the reference
    assert det.has_reference()
    det.observe(X, s)            # identical traffic: every stat exactly 0
    rep = det.check()
    assert not rep.drifted and rep.triggers == []
    assert rep.stats["feature_ks"] == 0.0
    assert rep.stats["score_psi"] == pytest.approx(0.0, abs=1e-9)


def test_drift_trips_on_shift_and_rebase_resets():
    det = DriftDetector(min_rows=32, current_rows=256,
                        max_feature_ks=0.3)
    rng = np.random.default_rng(1)
    s = rng.random(64)
    Xs = rng.standard_normal((64, 4)) + 5.0
    det.set_reference(rng.standard_normal((64, 4)), s)
    det.observe(Xs, s)
    rep = det.check()
    assert rep.drifted and "feature_ks" in rep.triggers
    assert rep.stats["feature_ks"] > 0.9
    det.rebase()   # post-swap: the shifted traffic is the new normal
    det.observe(Xs, s)
    assert not det.check().drifted


def test_drift_needs_min_rows_both_sides():
    det = DriftDetector(min_rows=64, current_rows=256)
    rng = np.random.default_rng(2)
    det.set_reference(rng.standard_normal((128, 4)), rng.random(128))
    det.observe(rng.standard_normal((16, 4)) + 9.0, rng.random(16))
    rep = det.check()   # 16 current rows: tiny-sample KS is noise
    assert not rep.drifted and rep.stats == {}


# ============================================== PSI / calibration helpers

def test_psi_zero_for_identical_large_for_shifted():
    from xgboost_tpu.serving.fleet import _psi

    rng = np.random.default_rng(3)
    a = rng.random(2000).astype(np.float32)
    assert _psi(a, a.copy()) == pytest.approx(0.0, abs=1e-9)
    assert _psi(a, np.clip(a + 0.4, 0, 1)) > 0.25
    mild = _psi(a, np.clip(a + 0.02, 0, 1))
    assert 0.0 < mild < 0.25


def test_calibration_gap_detects_decile_bias():
    from xgboost_tpu.serving.fleet import _calibration_gap

    rng = np.random.default_rng(4)
    a = rng.random(1000).astype(np.float32)
    assert _calibration_gap(a, a.copy()) == pytest.approx(0.0)
    assert _calibration_gap(a, np.clip(a + 0.2, 0, 1)) >= 0.1
    # shape mismatch = no comparable pairing: defined as zero, the
    # mean-divergence failure counter owns that case
    assert _calibration_gap(a, a[:10]) == 0.0


# ==================================================== replica-side sampling

def test_sampling_is_deterministic_off_the_trace_rid():
    from xgboost_tpu.serving.replica import _sampled

    assert _sampled("abcd-10", 2) is True    # 0x10 % 2 == 0
    assert _sampled("abcd-11", 2) is False
    assert _sampled("ffff-10", 2) is True    # pid half never matters
    assert _sampled(None, 2) is False
    assert _sampled("garbage", 2) is False
    every = 4
    picks = [_sampled(f"aa-{rid:x}", every) for rid in range(64)]
    assert sum(picks) == 16  # exactly 1-in-N, not approximately


# ===================================================== scheduler policy

class _SinkFleet:
    def __init__(self):
        self.sampling = {}
        self.sink = None

    def set_sampling(self, model, every, timeout=None):
        self.sampling[model] = every

    def set_feedback_sink(self, sink):
        self.sink = sink


def test_scheduler_defers_on_rows_then_brownout_then_memory():
    resources.reset()
    try:
        fleet = _SinkFleet()
        sch = OnlineScheduler(fleet, "m", min_retrain_rows=100)
        sch.enable()
        assert fleet.sampling["m"] == sch.config.sample_every
        out = sch.maybe_retrain()
        assert (out["outcome"], out["reason"]) == ("deferred", "rows")
        gov = resources.get_governor()
        gov.degrade("overload", "test")
        out = sch.maybe_retrain(force=True)
        assert (out["outcome"], out["reason"]) == ("deferred", "brownout")
        gov.restore("overload")
        gov.degrade("memory", "test")
        gov.degrade("memory", "test")   # level 2: training must not start
        out = sch.maybe_retrain(force=True)
        assert (out["outcome"], out["reason"]) == ("deferred", "memory")
    finally:
        resources.reset()


def test_scheduler_idle_without_drift_and_fault_seam_spares_incumbent():
    resources.reset()
    fleet = _SinkFleet()
    sch = OnlineScheduler(fleet, "m", min_retrain_rows=32,
                          drift=DriftConfig(min_rows=16))
    rng = np.random.default_rng(5)
    X = rng.standard_normal((64, 4)).astype(np.float32)
    s = rng.random(64)
    sch.window.append(X, (X[:, 0] > 0).astype(np.float32))
    sch.detector.set_reference(X, s)
    sch.detector.observe(X, s)
    out = sch.maybe_retrain()
    assert out["outcome"] == "idle"   # same distribution: nothing to do
    faults.install([{"site": "online.retrain", "kind": "exception"}])
    try:
        out = sch.maybe_retrain(force=True)
        # the cycle never starts: no LifecycleManager was ever built
        assert out["outcome"] == "fault" and sch._mgr is None
    finally:
        faults.clear()


def test_scheduler_pump_fills_window_and_detector():
    fleet = _SinkFleet()
    sch = OnlineScheduler(fleet, "m", sample_every=1)
    sch.enable()
    for i in range(3):
        fleet.sink(_rec(f"a-{i:x}", tag=i, rows=8))
        assert sch.label(f"a-{i:x}", np.ones(8, np.float32))
    fleet.sink(_rec("b-1", tag=9, rows=8, model="other"))  # filtered
    assert sch.pump() == 24
    assert len(sch.window) == 24
    assert sch.hub.stats()["matched"] == 3


# ============================== shadow PSI / calibration lifecycle gates

class _ShadowStubFleet:
    """Stub recording control calls; shadow stats injectable per test."""

    def __init__(self, store, stats):
        self.store = store
        self.calls = []
        self._stats = stats
        self._versions = dict(store.serving_entries())
        for name, v in store.serving_entries():
            store.set_active(name, v)

    @property
    def store_dir(self):
        return self.store.dir

    def active_version(self, model):
        return self._versions.get(model)

    def load_version(self, model, version, timeout=None, trace=None):
        self.calls.append(("load", model, int(version)))
        return [{}]

    def activate_version(self, model, version, timeout=None, trace=None):
        self.store.set_active(model, int(version))
        self._versions[model] = int(version)
        self.calls.append(("activate", model, int(version)))
        return [{}]

    def retire_version(self, model, version, timeout=None, trace=None):
        self.calls.append(("retire", model, int(version)))
        return [{}]

    def set_shadow(self, model, version, fraction):
        self.calls.append(("set_shadow", model, int(version), fraction))

    def shadow_stats(self, model):
        return dict(self._stats)

    def clear_shadow(self, model):
        self.calls.append(("clear_shadow", model))
        return dict(self._stats)


_CLEAN_SHADOW = {"pairs": 5, "failures": 0, "mean_div": 0.0,
                 "max_div": 0.0, "mean_ks": 0.0, "max_ks": 0.0,
                 "mean_psi": 0.0, "max_psi": 0.0,
                 "mean_cal": 0.0, "max_cal": 0.0}


@pytest.mark.parametrize("stat,knob,bad", [
    ("max_psi", "shadow_max_psi", 0.8),
    ("max_cal", "shadow_max_calibration", 0.3),
])
def test_shadow_distribution_gates_reject_and_spare_incumbent(
        tmp_path, stat, knob, bad):
    X, y = _data()
    st = ModelStore(str(tmp_path / "store"))
    st.publish("m", _train(X[:2000], y[:2000]))
    fleet = _ShadowStubFleet(st, dict(_CLEAN_SHADOW, **{stat: bad}))
    mgr = LifecycleManager(fleet, "m", config=LifecycleConfig(
        rounds_per_cycle=2, shadow_fraction=0.5, shadow_min_pairs=1,
        **{knob: 0.1}))
    rep = mgr.run_cycle((X[2000:], y[2000:]),
                        eval_window=(X[:2000], y[:2000]))
    assert not rep.swapped and rep.decision.reason == "shadow"
    assert rep.shadow[stat] == pytest.approx(bad)
    ops = [c[0] for c in fleet.calls]
    assert ops == ["load", "set_shadow", "clear_shadow", "retire"]
    assert st.active_version("m") == 1


def test_shadow_distribution_gates_pass_within_threshold(tmp_path):
    X, y = _data()
    st = ModelStore(str(tmp_path / "store"))
    st.publish("m", _train(X[:2000], y[:2000]))
    fleet = _ShadowStubFleet(st, dict(_CLEAN_SHADOW))
    mgr = LifecycleManager(fleet, "m", config=LifecycleConfig(
        rounds_per_cycle=2, shadow_fraction=0.5, shadow_min_pairs=1,
        shadow_max_psi=0.25, shadow_max_calibration=0.1))
    rep = mgr.run_cycle((X[2000:], y[2000:]),
                        eval_window=(X[:2000], y[:2000]))
    assert rep.swapped and st.active_version("m") == 2


# =============================================== closed loop, real fleet

def test_online_closed_loop_end_to_end(tmp_path):
    """The acceptance loop: a real replica serves, feedback samples flow
    back, labels join by trace, shifted traffic trips drift, the
    scheduler retrains + hot-swaps, and serving matches the new active
    version — with zero dropped requests."""
    from xgboost_tpu.lifecycle import GateConfig
    from xgboost_tpu.serving import ServingFleet

    rng = np.random.default_rng(31)
    store_dir = str(tmp_path / "store")
    Xb, yb = _data(seed=20, n=800, f=6)
    st = ModelStore(store_dir)
    st.publish("m", _train(Xb, yb, rounds=3))
    st.set_active("m", 1)

    blocks = [rng.standard_normal((16, 6)).astype(np.float32)
              for _ in range(4)]
    blocks += [(rng.standard_normal((16, 6)) + 4.0).astype(np.float32)
               for _ in range(8)]

    with ServingFleet(store_dir=store_dir, n_replicas=1,
                      cache_dir=str(tmp_path / "cache"),
                      warmup_buckets=(16,)) as fleet:
        sch = OnlineScheduler(fleet, "m", config=OnlineConfig(
            sample_every=1, join_horizon_s=600.0, min_retrain_rows=64,
            window_rows=4096, page_rows=32,
            spool_dir=str(tmp_path / "window"),
            drift=DriftConfig(min_rows=32, max_feature_ks=0.3),
            lifecycle=LifecycleConfig(
                rounds_per_cycle=2,
                gate=GateConfig(min_improvement=-1e9))))
        sch.enable()
        traces = []
        for rows in blocks:
            fut = fleet.submit("m", rows)
            traces.append(fut.trace_id)
            fut.result(timeout=180)           # every request completes
        deadline = time.monotonic() + 60.0
        while (sch.hub.stats()["offered"] < len(blocks)
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert sch.hub.stats()["offered"] == len(blocks)
        for tr, rows in zip(traces, blocks):
            assert sch.label(
                tr, (rows[:, 0] - rows[:, 2] > 0).astype(np.float32))
        out = sch.step()
        assert out["pumped_rows"] == 16 * len(blocks)
        assert out["outcome"] == "swapped", out
        assert "feature_ks" in (out["drift"] or {})
        sch.disable()
        assert fleet.active_version("m") == 2
        Q = blocks[-1]
        served = np.asarray(fleet.predict("m", Q, timeout=120), np.float32)
        expected = ModelStore(store_dir).booster("m", 2).predict(
            xtb.DMatrix(Q))
        np.testing.assert_array_equal(
            served, np.asarray(expected, np.float32))
        s = sch.hub.stats()
        assert s["matched"] == len(blocks) and s["dropped"] == {}


# ======================================================= chaos scenario

@pytest.mark.slow
def test_chaos_online_episode_green_and_deterministic():
    from xgboost_tpu.reliability import chaos

    r1 = chaos.run_episode("online", 11)
    assert r1.ok, r1.invariants
    r2 = chaos.run_episode("online", 11)
    assert r2.ok
    assert r1.plan == r2.plan
    assert r1.artifacts["digest"] == r2.artifacts["digest"]
    assert r1.artifacts["completed"] == 18
