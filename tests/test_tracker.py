"""RabitTracker rendezvous + error fan-out (reference: tracker.cc
Bootstrap/CMD::kError, comm.cc:340 error watcher, tracker.py RabitTracker).
"""
import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from xgboost_tpu.tracker import RabitTracker, recv_msg, send_msg


def test_rendezvous_protocol_assigns_sorted_ranks():
    """Raw-socket fake workers: ranks assigned by host sort, world correct,
    coordinator shared."""
    tr = RabitTracker(n_workers=3, host_ip="127.0.0.1")
    tr.start()
    results = {}

    def worker(host_tag, idx):
        s = socket.create_connection(("127.0.0.1", tr.port), timeout=30)
        send_msg(s, {"cmd": "start", "host": host_tag})
        reply = recv_msg(s)
        if reply.get("coordinator") is None:
            # two-phase bootstrap: rank 0 hosts the jax coordinator and must
            # report its address before the tracker releases other ranks
            assert reply["rank"] == 0
            send_msg(s, {"cmd": "coordinator", "addr": "127.0.0.1:45678"})
            reply = dict(reply, coordinator="127.0.0.1:45678")
        results[idx] = (host_tag, reply)
        send_msg(s, {"cmd": "shutdown"})
        s.close()

    # connect in reverse host order to prove sorting
    threads = []
    for idx, tag in enumerate(["hostC", "hostA", "hostB"]):
        t = threading.Thread(target=worker, args=(tag, idx))
        t.start()
        threads.append(t)
        time.sleep(0.2)  # deterministic arrival order
    for t in threads:
        t.join(30)
    tr.wait_for(timeout=30)
    by_host = {tag: r for (tag, r) in results.values()}
    assert by_host["hostA"]["rank"] == 0
    assert by_host["hostB"]["rank"] == 1
    assert by_host["hostC"]["rank"] == 2
    coords = {r["coordinator"] for (_t, r) in results.values()}
    assert len(coords) == 1
    assert all(r["world"] == 3 for (_t, r) in results.values())
    tr.free()


def test_wait_for_raises_on_worker_error():
    tr = RabitTracker(n_workers=2, host_ip="127.0.0.1")
    tr.start()
    aborted = {}

    def ok_worker():
        s = socket.create_connection(("127.0.0.1", tr.port), timeout=30)
        send_msg(s, {"cmd": "start", "host": "a"})
        reply = recv_msg(s)
        # host "a" sorts first -> rank 0 -> must complete the coordinator
        # handshake or the tracker never releases rank 1
        assert reply["rank"] == 0 and reply["coordinator"] is None
        send_msg(s, {"cmd": "coordinator", "addr": "127.0.0.1:45678"})
        msg = recv_msg(s)  # blocks until the abort fan-out
        aborted["msg"] = msg
        s.close()

    def bad_worker():
        s = socket.create_connection(("127.0.0.1", tr.port), timeout=30)
        send_msg(s, {"cmd": "start", "host": "b"})
        recv_msg(s)
        time.sleep(0.3)
        send_msg(s, {"cmd": "error", "msg": "synthetic failure"})
        s.close()

    t1 = threading.Thread(target=ok_worker)
    t2 = threading.Thread(target=bad_worker)
    t1.start(); t2.start()
    with pytest.raises(RuntimeError, match="synthetic failure"):
        tr.wait_for(timeout=30)
    t1.join(30); t2.join(30)
    assert aborted["msg"]["cmd"] == "abort"
    tr.free()


TRAIN_CHILD = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
uri, port = sys.argv[1], int(sys.argv[2])

from xgboost_tpu import collective
# tracker mode: NO pre-assigned rank — the tracker hands one out
collective.init(dmlc_tracker_uri=uri, dmlc_tracker_port=port, dmlc_nworker=2)
rank = collective.get_rank()
assert collective.get_world_size() == 2

import numpy as np
import xgboost_tpu as xtb
rng = np.random.default_rng(0)
X = rng.normal(size=(2000, 6)).astype(np.float32)
y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
Xs, ys = X[rank::2], y[rank::2]
bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                 "max_bin": 32}, xtb.DMatrix(Xs, label=ys), 2,
                verbose_eval=False)
import hashlib
dump = "".join(bst.get_dump(dump_format="json"))
print("RESULT" + json.dumps({"rank": rank,
                             "hash": hashlib.md5(dump.encode()).hexdigest()}))
collective.finalize()
"""

ABORT_CHILD = r"""
import sys, time
import jax
jax.config.update("jax_platforms", "cpu")
uri, port, mode = sys.argv[1], int(sys.argv[2]), sys.argv[3]

from xgboost_tpu import collective
collective.init(dmlc_tracker_uri=uri, dmlc_tracker_port=port, dmlc_nworker=2)
if mode == "fail":
    time.sleep(1.0)
    collective.signal_error("boom")  # exits 1 after telling the tracker
else:
    time.sleep(900)  # hung worker: only the abort fan-out can end it
"""


@pytest.mark.slow
def test_tracker_mode_end_to_end_training():
    """Full flow: RabitTracker.start -> workers init via worker_args with no
    rank -> jax.distributed rendezvous through the tracker-supplied
    coordinator -> identical models -> wait_for returns."""
    tr = RabitTracker(n_workers=2, host_ip="127.0.0.1")
    tr.start()
    args = tr.worker_args()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, "-c", TRAIN_CHILD, str(args["dmlc_tracker_uri"]),
         str(args["dmlc_tracker_port"])],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for _ in range(2)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT")][-1]
        outs.append(json.loads(line[len("RESULT"):]))
    tr.wait_for(timeout=60)
    assert {o["rank"] for o in outs} == {0, 1}
    assert outs[0]["hash"] == outs[1]["hash"]
    tr.free()


@pytest.mark.slow
def test_error_fanout_kills_hung_worker():
    """One worker fails -> tracker aborts the other (which would otherwise
    sleep 300s) -> wait_for raises.  The reference's fail-fast elastic
    path end to end."""
    tr = RabitTracker(n_workers=2, host_ip="127.0.0.1")
    tr.start()
    args = tr.worker_args()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    t0 = time.time()
    hang = subprocess.Popen(
        [sys.executable, "-c", ABORT_CHILD, str(args["dmlc_tracker_uri"]),
         str(args["dmlc_tracker_port"]), "hang"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)
    fail = subprocess.Popen(
        [sys.executable, "-c", ABORT_CHILD, str(args["dmlc_tracker_uri"]),
         str(args["dmlc_tracker_port"]), "fail"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)
    with pytest.raises(RuntimeError, match="boom"):
        # generous ceiling: a loaded 1-core box needs ~2 min just for two
        # jax imports + distributed init; uncontended this fires in ~10s
        tr.wait_for(timeout=280)
    assert fail.wait(timeout=120) == 1
    rc = hang.wait(timeout=120)  # killed by the abort watcher, NOT the sleep
    assert rc == 255, rc
    assert time.time() - t0 < 600, "hung worker was not aborted promptly"
    tr.free()
