"""Device grower vs pure-numpy reference — the core correctness oracle
(the role of GPU↔CPU parity tests in the reference, SURVEY §4)."""
import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.data.ellpack import build_ellpack
from xgboost_tpu.data.quantile import sketch_dense
from xgboost_tpu.ops.split import SplitParams
from xgboost_tpu.testing.reference import grow_tree_np
from xgboost_tpu.tree.grow import HistTreeGrower


def _grow_both(X, gpair_np, max_depth=4, max_bin=16, **kw):
    import jax.numpy as jnp

    cuts = sketch_dense(X, max_bin, use_device=False)
    ell = build_ellpack(X, cuts, row_align=64)
    R, R_pad = ell.n_rows, ell.n_padded
    gp = np.zeros((R_pad, 2), np.float32)
    gp[:R] = gpair_np
    valid = jnp.arange(R_pad) < R

    params = SplitParams(
        eta=kw.get("eta", 0.3), gamma=kw.get("gamma", 0.0),
        min_child_weight=kw.get("min_child_weight", 1.0),
        lambda_=kw.get("lambda_", 1.0), alpha=kw.get("alpha", 0.0),
        max_delta_step=kw.get("max_delta_step", 0.0),
    )
    grower = HistTreeGrower(max_depth, params)
    state = grower.grow(ell.bins, jnp.asarray(gp), valid, ell.cuts_pad, ell.n_bins)
    dev = HistTreeGrower.to_host(state)

    bins_np = np.asarray(ell.bins)[:R]
    ref = grow_tree_np(
        bins_np, gpair_np.astype(np.float64), ell.bin_width,
        np.asarray(cuts.n_bins_array()), max_depth,
        lam=params.lambda_, alpha=params.alpha, mds=params.max_delta_step,
        min_child_weight=params.min_child_weight, gamma=params.gamma, eta=params.eta,
    )
    return dev, ref


@pytest.mark.parametrize("sparsity", [0.0, 0.3])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_tree_structure_matches_reference(seed, sparsity):
    rng = np.random.default_rng(seed)
    n, f = 400, 6
    X = rng.normal(size=(n, f)).astype(np.float32)
    if sparsity:
        X[rng.random((n, f)) < sparsity] = np.nan
    y = (X[:, 0] * 1.5 + np.nan_to_num(X[:, 1]) + 0.2 * rng.normal(size=n) > 0).astype(
        np.float32
    )
    p = 1.0 / (1.0 + np.exp(0.0))
    grad = (p - y).astype(np.float32)
    hess = np.full(n, p * (1 - p), np.float32)
    gpair = np.stack([grad, hess], axis=1)

    dev, ref = _grow_both(X, gpair, max_depth=4, max_bin=16)

    np.testing.assert_array_equal(dev.feat, ref["feat"])
    np.testing.assert_array_equal(dev.sbin, ref["sbin"])
    np.testing.assert_array_equal(dev.is_leaf, ref["is_leaf"])
    split_mask = ref["feat"] >= 0
    np.testing.assert_array_equal(dev.dleft[split_mask], ref["dleft"][split_mask])
    np.testing.assert_allclose(dev.leaf_val, ref["leaf_val"], rtol=1e-2, atol=5e-4)


@pytest.mark.parametrize(
    "kw",
    [
        dict(alpha=0.5),
        dict(min_child_weight=5.0),
        dict(gamma=1.0),
        dict(max_delta_step=0.5),
        dict(lambda_=10.0),
    ],
)
def test_regularizers_match_reference(kw):
    rng = np.random.default_rng(7)
    n, f = 300, 5
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = X[:, 0] - 2 * X[:, 2] + 0.1 * rng.normal(size=n)
    gpair = np.stack([-(y - 0.0), np.ones(n)], axis=1).astype(np.float32)

    dev, ref = _grow_both(X, gpair, max_depth=3, max_bin=12, **kw)
    np.testing.assert_array_equal(dev.feat, ref["feat"])
    np.testing.assert_array_equal(dev.sbin, ref["sbin"])
    np.testing.assert_allclose(dev.leaf_val, ref["leaf_val"], rtol=1e-2, atol=5e-4)


def test_leaf_positions_match_rows():
    rng = np.random.default_rng(3)
    n, f = 200, 4
    X = rng.normal(size=(n, f)).astype(np.float32)
    gpair = np.stack([rng.normal(size=n), np.ones(n)], axis=1).astype(np.float32)
    import jax.numpy as jnp

    from xgboost_tpu.tree.grow import leaf_margin_delta

    dev, ref = _grow_both(X, gpair, max_depth=3, max_bin=8)
    # every valid row must sit on a leaf whose numpy row set contains it
    # (reconstruct from ref rows_of)
    pos_expected = np.zeros(n, np.int64)
    for node, rows in ref["rows_of"].items():
        if ref["is_leaf"][node]:
            pos_expected[rows] = node
    # device pos is internal; verify via margin deltas instead
    delta_ref = ref["leaf_val"][pos_expected]
    # device margin delta
    cuts = None
    # regrow to capture state
    from xgboost_tpu.data.ellpack import build_ellpack
    from xgboost_tpu.data.quantile import sketch_dense
    from xgboost_tpu.ops.split import SplitParams
    from xgboost_tpu.tree.grow import HistTreeGrower

    cuts = sketch_dense(X, 8, use_device=False)
    ell = build_ellpack(X, cuts, row_align=64)
    gp = np.zeros((ell.n_padded, 2), np.float32)
    gp[:n] = gpair
    valid = jnp.arange(ell.n_padded) < n
    grower = HistTreeGrower(3, SplitParams(0.3, 0.0, 1.0, 1.0, 0.0, 0.0))
    state = grower.grow(ell.bins, jnp.asarray(gp), valid, ell.cuts_pad, ell.n_bins)
    delta_dev = np.asarray(leaf_margin_delta(state.pos, state.leaf_val))[:n]
    np.testing.assert_allclose(delta_dev, delta_ref, rtol=1e-2, atol=5e-4)


def test_padded_levels_parity_deep():
    """The shared padded interior program (compile-wall fix) must grow
    identical trees to per-depth programs at depth > 5 — on CPU the default
    flips to per-depth for speed, so pin the padded path explicitly."""
    import hashlib

    import xgboost_tpu as xtb
    from xgboost_tpu.data.dmatrix import DMatrix
    from xgboost_tpu.ops.split import SplitParams
    from xgboost_tpu.tree.grow import HistTreeGrower
    import jax.numpy as jnp

    rng = np.random.default_rng(9)
    X = rng.normal(size=(3000, 6)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.3 * rng.normal(size=3000) > 0).astype(np.float32)
    d = DMatrix(X, label=y)
    ell = d.ensure_ellpack(max_bin=32)
    bins = jnp.asarray(ell.bins)
    R = bins.shape[0]
    valid = jnp.arange(R) < 3000
    gp = np.zeros((R, 2), np.float32)
    gp[:3000, 0] = 0.5 - y
    gp[:3000, 1] = 0.25
    gp = jnp.asarray(gp)
    params = SplitParams(eta=0.3, gamma=0.0, min_child_weight=1.0,
                         lambda_=1.0, alpha=0.0, max_delta_step=0.0)

    args = (bins, gp, valid, jnp.asarray(ell.cuts_pad),
            jnp.asarray(ell.n_bins))
    t_pad = HistTreeGrower(7, params, padded_levels=True).grow(*args)
    t_per = HistTreeGrower(7, params, padded_levels=False).grow(*args)
    for name in ("feat", "sbin", "thr", "leaf_val", "is_leaf"):
        np.testing.assert_array_equal(np.asarray(getattr(t_pad, name)),
                                      np.asarray(getattr(t_per, name)),
                                      err_msg=name)
