"""End-to-end fault injection across real worker processes.

The acceptance contract (ISSUE 3 / docs/reliability.md): killing a worker
mid-training makes the SURVIVORS abort within the watcher timeout (tracker
EOF fan-out — a silent death must not wedge peers in a collective), and a
relaunch with ``resume_from=`` continues from the last good checkpoint to a
final model bitwise-equal (UBJSON bytes) to an uninterrupted run.
"""
import json
import os
import subprocess
import sys
import time

import pytest

from xgboost_tpu.tracker import RabitTracker

TRAIN_CHILD = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
uri, port = sys.argv[1], int(sys.argv[2])
ckpt_dir, out_path, resume = sys.argv[3], sys.argv[4], sys.argv[5] == "1"

from xgboost_tpu import collective
collective.init(dmlc_tracker_uri=uri, dmlc_tracker_port=port, dmlc_nworker=2)
rank = collective.get_rank()

import numpy as np
import xgboost_tpu as xtb

rng = np.random.default_rng(0)
X = rng.normal(size=(1600, 6)).astype(np.float32)
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
Xs, ys = X[rank::2], y[rank::2]          # disjoint shards

bst = xtb.train({"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
                 "max_bin": 32}, xtb.DMatrix(Xs, label=ys), 6,
                verbose_eval=False,
                callbacks=[xtb.CheckpointCallback(ckpt_dir, interval=1)],
                resume_from=ckpt_dir if resume else None)
if rank == 0 and out_path:
    with open(out_path, "wb") as fh:
        fh.write(bytes(bst.save_raw()))
collective.finalize()
print("DONE", rank, flush=True)
"""


def _run_pair(tmp_path, tag, *, ckpt_dir, out_name, resume, fault_plan=None,
              timeout=600):
    """Two tracker-rendezvoused workers; returns (tracker_error, rcs)."""
    tr = RabitTracker(n_workers=2, host_ip="127.0.0.1")
    tr.start()
    args = tr.worker_args()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    if fault_plan is not None:
        env["XGBOOST_TPU_FAULT_PLAN"] = json.dumps(fault_plan)
    else:
        env.pop("XGBOOST_TPU_FAULT_PLAN", None)
    out_path = str(tmp_path / out_name) if out_name else ""
    procs = [subprocess.Popen(
        [sys.executable, "-c", TRAIN_CHILD, str(args["dmlc_tracker_uri"]),
         str(args["dmlc_tracker_port"]), ckpt_dir, out_path, resume],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for _ in range(2)]
    outs = [p.communicate(timeout=timeout) for p in procs]
    tracker_error = None
    try:
        tr.wait_for(timeout=60)
    except (RuntimeError, TimeoutError) as e:
        tracker_error = e
    tr.free()
    rcs = [p.returncode for p in procs]
    for (_, err), rc in zip(outs, rcs):
        if fault_plan is None:
            assert rc == 0, f"[{tag}] worker failed (rc={rc}):\n{err[-3000:]}"
    return tracker_error, rcs


def test_kill_resume_parity_multiprocess(tmp_path):
    """Quick-tier acceptance: kill one worker at round 3 via the fault plan
    -> its peer is ABORTED by the tracker's EOF fan-out (no wedge); a
    relaunch resumes from the newest valid checkpoint and the final model
    bytes equal the uninterrupted run's."""
    ckpt_a = str(tmp_path / "ckpt_full")
    err, _ = _run_pair(tmp_path, "full", ckpt_dir=ckpt_a,
                       out_name="full.ubj", resume="0")
    assert err is None
    full = open(tmp_path / "full.ubj", "rb").read()

    # interrupted: whichever process drew rank 1 dies entering round 3
    ckpt_b = str(tmp_path / "ckpt_int")
    t0 = time.time()
    err, rcs = _run_pair(
        tmp_path, "interrupted", ckpt_dir=ckpt_b, out_name="", resume="0",
        fault_plan={"faults": [{"site": "train.round", "kind": "kill",
                                "rank": 1, "round": 3, "exit_code": 43}]})
    elapsed = time.time() - t0
    # the killed worker exits 43; the SURVIVOR must be aborted (255) by the
    # tracker fan-out — promptly, not after a collective timeout
    assert sorted(rcs) == [43, 255], rcs
    assert err is not None and "worker" in str(err)
    assert elapsed < 420, f"survivor abort took {elapsed:.0f}s"
    from xgboost_tpu.reliability import latest_checkpoint

    st = latest_checkpoint(ckpt_b)
    assert st is not None and 1 <= st.round <= 3

    # relaunch with the same command + resume_from: bitwise parity
    err, _ = _run_pair(tmp_path, "resume", ckpt_dir=ckpt_b,
                       out_name="resumed.ubj", resume="1")
    assert err is None
    resumed = open(tmp_path / "resumed.ubj", "rb").read()
    assert resumed == full, "kill/resume model differs from uninterrupted run"


FANOUT_CHILD = r"""
import sys, time
import jax
jax.config.update("jax_platforms", "cpu")
uri, port = sys.argv[1], int(sys.argv[2])
from xgboost_tpu import collective
collective.init(dmlc_tracker_uri=uri, dmlc_tracker_port=port, dmlc_nworker=3)
rank = collective.get_rank()
print("READY", rank, flush=True)
if rank == 1:
    time.sleep(1.0)
    collective.signal_error("deliberate failure rank1")  # exits 1
time.sleep(600)  # survivors: only the abort fan-out can end this
"""


@pytest.mark.slow
def test_signal_error_fanout_aborts_all_workers(tmp_path):
    """Satellite: one of THREE workers calls collective.signal_error; every
    other worker's watcher must exit the process within the timeout (the
    reference's comm.cc detached error watcher contract)."""
    tr = RabitTracker(n_workers=3, host_ip="127.0.0.1")
    tr.start()
    args = tr.worker_args()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("XGBOOST_TPU_FAULT_PLAN", None)
    procs = [subprocess.Popen(
        [sys.executable, "-c", FANOUT_CHILD, str(args["dmlc_tracker_uri"]),
         str(args["dmlc_tracker_port"])],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)
        for _ in range(3)]
    with pytest.raises(RuntimeError, match="deliberate failure rank1"):
        tr.wait_for(timeout=420)
    rcs = sorted(p.wait(timeout=180) for p in procs)
    # the failer sys.exit(1)s; BOTH survivors os._exit(255) on the abort
    assert rcs == [1, 255, 255], rcs
    tr.free()


@pytest.mark.slow
def test_dropped_tracker_connection_is_a_detected_fault(tmp_path):
    """A worker whose tracker connection drops right after rendezvous is
    treated as dead: the tracker fans the abort out to its peers."""
    tr = RabitTracker(n_workers=2, host_ip="127.0.0.1")
    tr.start()
    args = tr.worker_args()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["XGBOOST_TPU_FAULT_PLAN"] = json.dumps(
        {"faults": [{"site": "tracker.connected", "kind": "drop_connection",
                     "rank": 1}]})
    procs = [subprocess.Popen(
        [sys.executable, "-c", FANOUT_CHILD.replace("dmlc_nworker=3",
                                                    "dmlc_nworker=2"),
         str(args["dmlc_tracker_uri"]), str(args["dmlc_tracker_port"])],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)
        for _ in range(2)]
    with pytest.raises(RuntimeError, match="connection lost"):
        tr.wait_for(timeout=420)
    # rank 0 is aborted; rank 1 (channel-less) would sleep 600s — kill it.
    # Poll ALL workers against one shared deadline: rank assignment follows
    # connect order, so the sleeper may be procs[0] — a sequential
    # poll-then-kill loop would burn the whole deadline on it and never
    # look at the already-aborted peer.
    rcs = []
    remaining = list(procs)
    deadline = time.time() + 180
    while remaining and 255 not in rcs and time.time() < deadline:
        for p in list(remaining):
            rc = p.poll()
            if rc is not None:
                rcs.append(rc)
                remaining.remove(p)
        time.sleep(0.5)
    for p in remaining:
        p.kill()
        p.wait(timeout=30)
    assert 255 in rcs, rcs  # the worker with a live channel was aborted
    tr.free()
