"""Monotone / interaction constraints, max_leaves, adaptive leaves
(reference: tests/python/test_monotone_constraints.py,
test_interaction_constraints.py)."""
import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.testing.data import make_regression


def _monotone_data(n=800, seed=0):
    rng = np.random.default_rng(seed)
    x0 = rng.uniform(-3, 3, n)
    x1 = rng.uniform(-3, 3, n)
    y = 2 * x0 - 1.5 * x1 + 0.3 * np.sin(4 * x0) + 0.2 * rng.normal(size=n)
    X = np.stack([x0, x1], axis=1).astype(np.float32)
    return X, y.astype(np.float32)


def _check_monotone(bst, sign, feature, grid=30):
    """Predictions must be monotone in `feature` for any fixed other values."""
    rng = np.random.default_rng(1)
    base = rng.uniform(-3, 3, size=(20, 2)).astype(np.float32)
    xs = np.linspace(-3, 3, grid, dtype=np.float32)
    for row in base:
        pts = np.tile(row, (grid, 1))
        pts[:, feature] = xs
        p = bst.predict(xtb.DMatrix(pts))
        diffs = np.diff(p)
        if sign > 0:
            assert (diffs >= -1e-5).all(), diffs.min()
        else:
            assert (diffs <= 1e-5).all(), diffs.max()


def test_monotone_increasing_decreasing():
    X, y = _monotone_data()
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train(
        {"objective": "reg:squarederror", "max_depth": 4,
         "monotone_constraints": "(1,-1)", "eta": 0.5},
        d, 15, verbose_eval=False,
    )
    _check_monotone(bst, +1, 0)
    _check_monotone(bst, -1, 1)
    # and the unconstrained model does violate (sanity that the test can fail)
    bst2 = xtb.train({"objective": "reg:squarederror", "max_depth": 4, "eta": 0.5},
                     d, 15, verbose_eval=False)
    with pytest.raises(AssertionError):
        _check_monotone(bst2, +1, 0)


def test_interaction_constraints_respected():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(600, 4)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + X[:, 2] - X[:, 3] + 0.1 * rng.normal(size=600)).astype(
        np.float32
    )
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train(
        {"objective": "reg:squarederror", "max_depth": 4,
         "interaction_constraints": [[0, 1], [2, 3]]},
        d, 8, verbose_eval=False,
    )
    # every root-to-leaf path must use features from a single constraint set
    for tree in bst.trees:
        def rec(nid, used):
            if tree.left_children[nid] == -1:
                if used:
                    assert used <= {0, 1} or used <= {2, 3}, used
                return
            f = int(tree.split_indices[nid])
            rec(tree.left_children[nid], used | {f})
            rec(tree.right_children[nid], used | {f})
        rec(0, set())


def test_max_leaves_budget():
    X, y = make_regression(600, 8, seed=3)
    d = xtb.DMatrix(X, label=y)
    for policy in ("depthwise", "lossguide"):
        bst = xtb.train(
            {"objective": "reg:squarederror", "max_depth": 6, "max_leaves": 8,
             "grow_policy": policy},
            d, 3, verbose_eval=False,
        )
        for t in bst.trees:
            assert t.num_leaves <= 8, (policy, t.num_leaves)


def test_adaptive_leaf_mae():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(500, 4)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1]).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    res = {}
    xtb.train({"objective": "reg:absoluteerror", "max_depth": 4, "eta": 0.5},
              d, 25, evals=[(d, "t")], evals_result=res, verbose_eval=False)
    mae = res["t"]["mae"]
    assert mae[-1] < 0.25 * mae[0], mae[::6]


def test_quantile_objective_coverage():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(1500, 3)).astype(np.float32)
    y = (X[:, 0] + rng.normal(size=1500)).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    for alpha in (0.2, 0.8):
        bst = xtb.train(
            {"objective": "reg:quantileerror", "quantile_alpha": alpha,
             "max_depth": 4, "eta": 0.3},
            d, 40, verbose_eval=False,
        )
        p = bst.predict(d)
        cover = float((y <= p).mean())
        assert abs(cover - alpha) < 0.1, (alpha, cover)
