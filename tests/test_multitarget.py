"""Vector-leaf trees — multi_strategy="multi_output_tree"
(reference: tests/python/test_multi_target.py pattern; model schema
multi_target_tree_model.cc)."""
import numpy as np
import pytest

import xgboost_tpu as xtb


def _multi_data(seed=0, n=1500, f=8, k=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    W = rng.normal(size=(f, k)).astype(np.float32)
    Y = (X @ W + 0.1 * rng.normal(size=(n, k))).astype(np.float32)
    return X, Y


def test_multi_output_tree_trains_and_fits():
    X, Y = _multi_data()
    d = xtb.DMatrix(X, label=Y)
    params = {"objective": "reg:squarederror", "num_target": 3,
              "multi_strategy": "multi_output_tree", "max_depth": 5,
              "eta": 0.3, "eval_metric": "rmse"}
    res = {}
    bst = xtb.train(params, d, 20, evals=[(d, "t")], evals_result=res,
                    verbose_eval=False)
    p = bst.predict(d)
    assert p.shape == Y.shape
    # one vector tree per round
    assert len(bst.trees) == 20
    assert bst.trees[0].n_targets == 3
    rmse = float(np.sqrt(np.mean((p - Y) ** 2)))
    base = float(np.sqrt(np.mean((Y - Y.mean(0)) ** 2)))
    assert rmse < 0.5 * base, (rmse, base)
    assert res["t"]["rmse"][-1] < res["t"]["rmse"][0]


def test_multi_output_tree_close_to_one_per_target():
    """Vector-leaf and one-tree-per-target share the gain formulation, so on
    CORRELATED targets (where one split structure serves all outputs — the
    case multi_output_tree exists for) their fits land close (the reference's
    test_multi_target strategy-parity check)."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(1500, 8)).astype(np.float32)
    base = (X[:, 0] * 1.5 + X[:, 1] ** 2).astype(np.float32)
    scales = np.asarray([1.0, 0.8, 1.2], np.float32)
    Y = base[:, None] * scales[None, :] + 0.05 * rng.normal(
        size=(1500, 3)).astype(np.float32)
    d1 = xtb.DMatrix(X, label=Y)
    d2 = xtb.DMatrix(X, label=Y)
    common = {"objective": "reg:squarederror", "num_target": 3,
              "max_depth": 4, "eta": 0.3}
    b_multi = xtb.train({**common, "multi_strategy": "multi_output_tree"},
                        d1, 15, verbose_eval=False)
    b_per = xtb.train({**common, "multi_strategy": "one_output_per_tree"},
                      d2, 15, verbose_eval=False)
    pm = b_multi.predict(d1)
    pp = b_per.predict(d2)
    rm = np.sqrt(np.mean((pm - Y) ** 2))
    rp = np.sqrt(np.mean((pp - Y) ** 2))
    assert abs(rm - rp) < 0.25 * max(rm, rp), (rm, rp)


def test_multi_output_tree_save_load_roundtrip(tmp_path):
    X, Y = _multi_data(seed=5, n=600)
    d = xtb.DMatrix(X, label=Y)
    bst = xtb.train({"objective": "reg:squarederror", "num_target": 3,
                     "multi_strategy": "multi_output_tree", "max_depth": 4},
                    d, 5, verbose_eval=False)
    p = bst.predict(xtb.DMatrix(X))
    fn = str(tmp_path / "multi.json")
    bst.save_model(fn)
    b2 = xtb.Booster()
    b2.load_model(fn)
    p2 = b2.predict(xtb.DMatrix(X))
    np.testing.assert_array_equal(p, p2)
    # schema: vector-leaf fields present (multi_target_tree_model.cc SaveModel)
    import json

    with open(fn) as fh:
        m = json.load(fh)
    t0 = m["learner"]["gradient_booster"]["model"]["trees"][0]
    assert t0["tree_param"]["size_leaf_vector"] == "3"
    n_leaves = sum(1 for c in t0["left_children"] if c == -1)
    assert len(t0["leaf_weights"]) == n_leaves * 3
    assert len(t0["base_weights"]) == len(t0["left_children"]) * 3


def test_multi_output_softprob():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(1200, 6)).astype(np.float32)
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)
    d = xtb.DMatrix(X, label=y.astype(np.float32))
    bst = xtb.train({"objective": "multi:softprob", "num_class": 3,
                     "multi_strategy": "multi_output_tree", "max_depth": 4},
                    d, 10, verbose_eval=False)
    p = bst.predict(d)
    assert p.shape == (1200, 3)
    np.testing.assert_allclose(p.sum(1), 1.0, rtol=1e-5)
    acc = np.mean(np.argmax(p, 1) == y)
    assert acc > 0.8, acc


def test_multi_output_tree_unsupported_combos():
    X, Y = _multi_data(n=300)
    d = xtb.DMatrix(X, label=Y)
    with pytest.raises(NotImplementedError):
        xtb.train({"objective": "reg:squarederror", "num_target": 3,
                   "multi_strategy": "multi_output_tree", "max_depth": 3,
                   "booster": "dart"},
                  d, 2, verbose_eval=False)


def test_multi_output_tree_lossguide_max_leaves():
    """lossguide + max_leaves budget on vector-leaf trees: the leaf count is
    capped and the model still fits (driver.h grow-policy queue semantics)."""
    X, Y = _multi_data()
    d = xtb.DMatrix(X, label=Y)
    bst = xtb.train({"objective": "reg:squarederror", "num_target": 3,
                     "multi_strategy": "multi_output_tree", "max_depth": 6,
                     "grow_policy": "lossguide", "max_leaves": 8, "eta": 0.3},
                    d, 5, verbose_eval=False)
    for t in bst.trees:
        n_leaves = int(np.sum(t.left_children == -1))
        assert n_leaves <= 8
    p = bst.predict(d)
    rmse = float(np.sqrt(np.mean((p - Y) ** 2)))
    base = float(np.sqrt(np.mean((Y - Y.mean(0)) ** 2)))
    assert rmse < 0.9 * base


def test_multi_output_tree_mesh_matches_single(eight_devices):
    """Vector-leaf training over the 8-device mesh == single device
    (the multi-target AllReduceHist psum is deterministic)."""
    X, Y = _multi_data(n=1024)
    params = {"objective": "reg:squarederror", "num_target": 3,
              "multi_strategy": "multi_output_tree", "max_depth": 4,
              "eta": 0.3}
    b1 = xtb.train(params, xtb.DMatrix(X, label=Y), 4, verbose_eval=False)
    b8 = xtb.train({**params, "n_devices": 8}, xtb.DMatrix(X, label=Y), 4,
                   verbose_eval=False)
    p1, p8 = b1.predict(xtb.DMatrix(X)), b8.predict(xtb.DMatrix(X))
    np.testing.assert_allclose(p1, p8, rtol=5e-4, atol=1e-5)
    for t1, t8 in zip(b1.trees, b8.trees):
        np.testing.assert_array_equal(t1.split_indices, t8.split_indices)
        np.testing.assert_array_equal(t1.left_children, t8.left_children)
