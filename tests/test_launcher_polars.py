"""Launcher (dask-role) + polars adapter tests."""
import os
import sys

import numpy as np
import pytest

import xgboost_tpu as xtb


def test_polars_adapter():
    pl = pytest.importorskip("polars")
    rng = np.random.default_rng(0)
    n = 800
    df = pl.DataFrame({
        "x": rng.normal(size=n).astype(np.float32),
        "c": pl.Series(rng.choice(["a", "b", "c"], size=n),
                       dtype=pl.Categorical),
    })
    y = (df["x"].to_numpy() > 0).astype(np.float32)
    d = xtb.DMatrix(df, label=y, enable_categorical=True)
    assert d.num_col() == 2
    assert d.info.feature_types == ["q", "c"]
    assert d.cat_categories == {1: ["a", "b", "c"]}
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3}, d, 3,
                    verbose_eval=False)
    p = bst.predict(d)
    assert np.isfinite(p).all()
    # AUC sanity on the numeric signal
    order = p.argsort()
    assert y[order[-100:]].mean() > y[order[:100]].mean()


def test_arrow_adapter():
    pa = pytest.importorskip("pyarrow")
    rng = np.random.default_rng(0)
    n = 800
    x = rng.normal(size=n).astype(np.float32)
    x[::17] = np.nan
    cats = rng.choice(["a", "b", "c"], size=n)
    tab = pa.table({
        "x": pa.array(x),
        "i": pa.array(rng.integers(0, 5, size=n), type=pa.int32()),
        "c": pa.array(cats).dictionary_encode(),
    })
    y = (np.nan_to_num(x) > 0).astype(np.float32)
    d = xtb.DMatrix(tab, label=y, enable_categorical=True)
    assert d.num_col() == 3
    assert d.info.feature_types == ["q", "int", "c"]
    assert d.info.feature_names == ["x", "i", "c"]
    # arrow dictionaries keep first-appearance order; values round-trip
    assert sorted(d.cat_categories[2]) == ["a", "b", "c"]
    assert np.isnan(d.host_dense()[::17, 0]).all()
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3}, d, 3,
                    verbose_eval=False)
    p = bst.predict(d)
    assert np.isfinite(p).all()
    order = p.argsort()
    assert y[order[-100:]].mean() > y[order[:100]].mean()

    # custom missing sentinel must convert to NaN on the columnar path too
    t2 = pa.table({"x": pa.array([1.0, -999.0, 3.0], type=pa.float32())})
    d3 = xtb.DMatrix(t2, missing=-999.0)
    h = d3.host_dense()[:, 0]
    assert h[0] == 1.0 and np.isnan(h[1]) and h[2] == 3.0

    # ...but the sentinel must NOT touch categorical dictionary codes: a
    # sentinel of 0.0 may not wipe out category code 0
    t3 = pa.table({
        "x": pa.array([1.0, 0.0, 3.0], type=pa.float32()),
        "c": pa.array(["a", "b", "a"]).dictionary_encode(),
    })
    d4 = xtb.DMatrix(t3, missing=0.0, enable_categorical=True)
    h4 = d4.host_dense()
    assert np.isnan(h4[1, 0])          # numeric sentinel converted
    assert not np.isnan(h4[:, 1]).any()  # category codes untouched

    # RecordBatch goes through the same adapter
    rb = tab.to_batches()[0]
    d2 = xtb.DMatrix(rb, label=y[: rb.num_rows], enable_categorical=True)
    assert d2.num_col() == 3
    np.testing.assert_array_equal(
        np.isnan(d2.host_dense()), np.isnan(d.host_dense()[: rb.num_rows]))


def _launcher_worker(rank, world):
    import numpy as np

    import xgboost_tpu as xtb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 5)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    Xs, ys = X[rank::world], y[rank::world]
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "max_bin": 32}, xtb.DMatrix(Xs, label=ys), 2,
                    verbose_eval=False)
    out = os.environ["LAUNCHER_TEST_OUT"]
    with open(f"{out}.rank{rank}", "w") as fh:
        fh.write("".join(bst.get_dump()))


def test_run_distributed(tmp_path):
    from xgboost_tpu.launcher import run_distributed

    out = str(tmp_path / "dump")
    os.environ["LAUNCHER_TEST_OUT"] = out
    try:
        run_distributed(_launcher_worker, 2, platform="cpu", timeout=600)
    finally:
        os.environ.pop("LAUNCHER_TEST_OUT", None)
    d0 = open(out + ".rank0").read()
    d1 = open(out + ".rank1").read()
    assert d0 == d1 and len(d0) > 0
