"""Launcher (dask-role) + polars adapter tests."""
import os
import sys

import numpy as np
import pytest

import xgboost_tpu as xtb


def test_polars_adapter():
    pl = pytest.importorskip("polars")
    rng = np.random.default_rng(0)
    n = 800
    df = pl.DataFrame({
        "x": rng.normal(size=n).astype(np.float32),
        "c": pl.Series(rng.choice(["a", "b", "c"], size=n),
                       dtype=pl.Categorical),
    })
    y = (df["x"].to_numpy() > 0).astype(np.float32)
    d = xtb.DMatrix(df, label=y, enable_categorical=True)
    assert d.num_col() == 2
    assert d.info.feature_types == ["q", "c"]
    assert d.cat_categories == {1: ["a", "b", "c"]}
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3}, d, 3,
                    verbose_eval=False)
    p = bst.predict(d)
    assert np.isfinite(p).all()
    # AUC sanity on the numeric signal
    order = p.argsort()
    assert y[order[-100:]].mean() > y[order[:100]].mean()


def _launcher_worker(rank, world):
    import numpy as np

    import xgboost_tpu as xtb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 5)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    Xs, ys = X[rank::world], y[rank::world]
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "max_bin": 32}, xtb.DMatrix(Xs, label=ys), 2,
                    verbose_eval=False)
    out = os.environ["LAUNCHER_TEST_OUT"]
    with open(f"{out}.rank{rank}", "w") as fh:
        fh.write("".join(bst.get_dump()))


def test_run_distributed(tmp_path):
    from xgboost_tpu.launcher import run_distributed

    out = str(tmp_path / "dump")
    os.environ["LAUNCHER_TEST_OUT"] = out
    try:
        run_distributed(_launcher_worker, 2, platform="cpu", timeout=600)
    finally:
        os.environ.pop("LAUNCHER_TEST_OUT", None)
    d0 = open(out + ".rank0").read()
    d1 = open(out + ".rank1").read()
    assert d0 == d1 and len(d0) > 0
