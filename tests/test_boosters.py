"""Booster variants: gblinear, DART, num_parallel_tree
(reference: tests/python/test_linear.py, test_dart.py aspects of
tests/python/test_basic_models.py)."""
import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.testing.data import make_binary, make_regression


def test_gblinear_recovers_coefficients():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1000, 5)).astype(np.float32)
    true_w = np.array([1.0, -2.0, 0.5, 0.0, 3.0], np.float32)
    y = X @ true_w + 0.05 * rng.normal(size=1000).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"booster": "gblinear", "objective": "reg:squarederror",
                     "eta": 0.7, "lambda": 0.01}, d, 40, verbose_eval=False)
    np.testing.assert_allclose(bst.linear_weights[:, 0], true_w, atol=0.05)
    p = bst.predict(d)
    assert np.sqrt(np.mean((p - y) ** 2)) < 0.1


def test_gblinear_l1_sparsity():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(500, 10)).astype(np.float32)
    y = (2 * X[:, 0]).astype(np.float32)  # only feature 0 matters
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"booster": "gblinear", "objective": "reg:squarederror",
                     "eta": 0.7, "alpha": 5.0, "lambda": 0.0}, d, 40,
                    verbose_eval=False)
    w = bst.linear_weights[:, 0]
    assert abs(w[0]) > 0.5
    assert np.abs(w[1:]).max() < 0.05  # L1 zeroes the noise features


def test_gblinear_save_load_roundtrip(tmp_path):
    X, y = make_regression(300, 4, seed=2)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"booster": "gblinear", "objective": "reg:squarederror"},
                    d, 10, verbose_eval=False)
    f = str(tmp_path / "lin.json")
    bst.save_model(f)
    b2 = xtb.Booster()
    b2.load_model(f)
    np.testing.assert_allclose(b2.predict(d), bst.predict(d), rtol=1e-5)


def test_gblinear_shotgun_cyclic_matches_coord_descent():
    """shotgun runs the same CoordinateDelta chain as coord_descent when the
    selector visits features cyclically (the deterministic equivalence the
    reference's nthread=1 shotgun also has; updater_shotgun.cc:96)."""
    X, y = make_regression(600, 6, seed=21)
    d = xtb.DMatrix(X, label=y)

    def weights(params):
        bst = xtb.train({"booster": "gblinear",
                         "objective": "reg:squarederror", "eta": 0.5,
                         "lambda": 0.1, **params}, d, 8, verbose_eval=False)
        return bst.linear_weights

    np.testing.assert_array_equal(
        weights({"updater": "coord_descent"}),
        weights({"updater": "shotgun", "feature_selector": "cyclic"}))


def test_gblinear_shotgun_shuffle_deterministic_and_converges():
    rng = np.random.default_rng(22)
    X = rng.normal(size=(800, 6)).astype(np.float32)
    true_w = np.array([1.0, -2.0, 0.5, 0.0, 3.0, -1.0], np.float32)
    y = X @ true_w + 0.05 * rng.normal(size=800).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    params = {"booster": "gblinear", "objective": "reg:squarederror",
              "eta": 0.7, "lambda": 0.01, "updater": "shotgun", "seed": 7}

    def run():  # shotgun defaults to the shuffle selector (reference)
        bst = xtb.train(params, d, 40, verbose_eval=False)
        return bst.linear_weights, np.asarray(bst.predict(d))

    (w1, p1), (w2, p2) = run(), run()
    np.testing.assert_array_equal(w1, w2)  # seeded shuffle: reproducible
    np.testing.assert_array_equal(p1, p2)
    np.testing.assert_allclose(w1[:, 0], true_w, atol=0.05)
    # a different seed visits in a different order -> different f32 chain
    w3 = xtb.train({**params, "seed": 8}, d, 40,
                   verbose_eval=False).linear_weights
    assert not np.array_equal(w1, w3)
    np.testing.assert_allclose(w3[:, 0], true_w, atol=0.05)


def test_gblinear_random_selector_and_validation():
    X, y = make_regression(300, 5, seed=23)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"booster": "gblinear", "objective": "reg:squarederror",
                     "updater": "shotgun", "feature_selector": "random",
                     "seed": 3}, d, 20, verbose_eval=False)
    assert np.isfinite(bst.linear_weights).all()
    with pytest.raises(ValueError, match="feature_selector"):
        xtb.train({"booster": "gblinear", "objective": "reg:squarederror",
                   "feature_selector": "sideways"}, d, 1, verbose_eval=False)
    with pytest.raises(ValueError, match="updater"):
        xtb.train({"booster": "gblinear", "objective": "reg:squarederror",
                   "updater": "warp_drive"}, d, 1, verbose_eval=False)


def test_gblinear_gain_selector_orders():
    """The coordinate_common.h selector semantics, directly: thrifty ranks
    by |univariate weight change| from the round-start gradients; greedy's
    first pick is the same top coordinate (interleaved re-ranking)."""
    import jax.numpy as jnp

    from xgboost_tpu.models.gblinear import (effective_top_k,
                                             linear_update_greedy,
                                             selector_order, thrifty_order)

    rng = np.random.default_rng(31)
    X = rng.normal(size=(2000, 6)).astype(np.float32)
    y = 3.0 * X[:, 2] + 1.0 * X[:, 0] + 0.5 * X[:, 4]
    gpair = np.stack([-y, np.ones_like(y)], axis=1)  # squarederror at pred=0
    w0 = np.zeros(6, np.float32)
    order = thrifty_order(X, gpair, w0, top_k=0, alpha=0.0, lambda_=1.0)
    assert list(order[:3]) == [2, 0, 4]  # signal-strength ranking
    assert len(thrifty_order(X, gpair, w0, top_k=2, alpha=0.0,
                             lambda_=1.0)) == 2
    # exact-magnitude ties (duplicated column) resolve to the lower index
    Xt = np.concatenate([X[:, :1], X], axis=1)
    gt = thrifty_order(Xt, gpair, np.zeros(7, np.float32), top_k=0,
                       alpha=0.0, lambda_=1.0)
    assert list(gt).index(0) < list(gt).index(1)
    # greedy interleaves select-and-update; first pick == thrifty's top
    _, _, picked = linear_update_greedy(
        jnp.asarray(X), jnp.asarray(gpair, jnp.float32), jnp.asarray(w0),
        jnp.float32(0.0), steps=3, eta=0.5, lambda_=1.0, alpha=0.0)
    assert int(picked[0]) == 2
    assert len(set(int(p) for p in picked)) == 3  # no coordinate twice
    assert effective_top_k(0, 5) == 5
    assert effective_top_k(3, 5) == 3
    assert effective_top_k(10, 5) == 5
    # gain-ranked selectors have no gradient-free order
    with pytest.raises(ValueError, match="gain-ranked"):
        selector_order("greedy", 6, 0, 0)


@pytest.mark.parametrize("selector", ["greedy", "thrifty"])
def test_gblinear_gain_selectors_train_deterministic(selector):
    rng = np.random.default_rng(24)
    X = rng.normal(size=(800, 6)).astype(np.float32)
    true_w = np.array([1.0, -2.0, 0.5, 0.0, 3.0, -1.0], np.float32)
    y = X @ true_w + 0.05 * rng.normal(size=800).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    params = {"booster": "gblinear", "objective": "reg:squarederror",
              "eta": 0.7, "lambda": 0.01, "updater": "coord_descent",
              "feature_selector": selector}

    def run(extra):
        return xtb.train({**params, **extra}, d, 40,
                         verbose_eval=False).linear_weights

    w1, w2 = run({}), run({})
    np.testing.assert_array_equal(w1, w2)  # bitwise-deterministic
    np.testing.assert_allclose(w1[:, 0], true_w, atol=0.05)  # converges
    # top_k restricts each round to the k best coordinates; one round
    # from zero moves exactly k weights (plus the bias)
    wk = xtb.train({**params, "top_k": 2}, d, 1,
                   verbose_eval=False).linear_weights[:, 0]
    assert np.count_nonzero(wk) == 2
    # the shotgun updater accepts gain-ranked selectors too
    ws = xtb.train({**params, "updater": "shotgun"}, d, 40,
                   verbose_eval=False).linear_weights
    np.testing.assert_array_equal(ws, w1)  # same chain, updater-agnostic


def test_dart_trains_and_roundtrips(tmp_path):
    X, y = make_binary(500, 6, seed=3)
    d = xtb.DMatrix(X, label=y)
    res = {}
    bst = xtb.train({"booster": "dart", "objective": "binary:logistic",
                     "rate_drop": 0.4, "one_drop": 1, "max_depth": 3, "seed": 5},
                    d, 15, evals=[(d, "t")], evals_result=res, verbose_eval=False)
    ll = res["t"]["logloss"]
    assert ll[-1] < ll[0]
    assert any(w != 1.0 for w in bst.tree_weights)  # dropout actually fired
    f = str(tmp_path / "dart.json")
    bst.save_model(f)
    b2 = xtb.Booster()
    b2.load_model(f)
    np.testing.assert_allclose(b2.predict(d), bst.predict(d), rtol=1e-5)


def test_dart_weighted_sampling():
    X, y = make_binary(400, 5, seed=6)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"booster": "dart", "objective": "binary:logistic",
                     "rate_drop": 0.3, "sample_type": "weighted",
                     "normalize_type": "forest", "max_depth": 3}, d, 10,
                    verbose_eval=False)
    assert np.isfinite(bst.predict(d)).all()


def test_num_parallel_tree_forest():
    X, y = make_regression(500, 6, seed=4)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "reg:squarederror", "num_parallel_tree": 4,
                     "subsample": 0.8, "colsample_bynode": 0.8, "eta": 1.0,
                     "max_depth": 4, "seed": 9}, d, 3, verbose_eval=False)
    assert len(bst.trees) == 12
    assert bst.num_boosted_rounds() == 3
    # slicing respects rounds (4 trees each)
    b1 = bst[0:1]
    assert len(b1.trees) == 4
    # random forest (single round, eta=1) should fit decently
    rf = xtb.XGBRFRegressor(n_estimators=1, num_parallel_tree=20, max_depth=6,
                            random_state=0)
    rf.fit(X, y)
    pred = rf.predict(X)
    assert np.corrcoef(pred, y)[0, 1] > 0.9


def test_gradient_based_sampling():
    """(reference: src/tree/gpu_hist/sampler.cuh GradientBasedSampler)"""
    X, y = make_regression(1500, 6, seed=13)
    d = xtb.DMatrix(X, label=y)
    res_u, res_g = {}, {}
    xtb.train({"objective": "reg:squarederror", "subsample": 0.3,
               "sampling_method": "uniform", "max_depth": 4, "seed": 1}, d, 12,
              evals=[(d, "t")], evals_result=res_u, verbose_eval=False)
    xtb.train({"objective": "reg:squarederror", "subsample": 0.3,
               "sampling_method": "gradient_based", "max_depth": 4, "seed": 1},
              d, 12, evals=[(d, "t")], evals_result=res_g, verbose_eval=False)
    assert np.isfinite(res_g["t"]["rmse"]).all()
    # both must learn; gradient-based usually at least matches uniform
    assert res_g["t"]["rmse"][-1] < res_g["t"]["rmse"][0] * 0.7
    assert res_u["t"]["rmse"][-1] < res_u["t"]["rmse"][0] * 0.7
