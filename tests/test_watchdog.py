"""Stall watchdog semantics (docs/reliability.md "Coordinator failover &
watchdog"): the escalation ladder fires deterministically past a budget,
NEVER on legitimate slowness under it, and the tracker-side liveness
monitor distinguishes heartbeat loss from progress loss — a slow but
progressing peer must not be declared dead.
"""
import os
import socket
import threading
import time

import pytest

from xgboost_tpu.reliability import watchdog as wd
from xgboost_tpu.tracker import RabitTracker, recv_msg, send_msg


@pytest.fixture(autouse=True)
def _clean_watchdog():
    wd.reset()
    yield
    wd.reset()


# ---------------------------------------------------------------------------
# guard ladder
# ---------------------------------------------------------------------------

def test_ladder_escalates_in_order_and_runs_on_stall():
    stalled = []
    with wd.guard("collective.wait", budget_s=0.01,
                  on_stall=stalled.append) as g:
        time.sleep(0.05)
        fired = wd.check_now()
    assert [s for _seam, s in fired] == ["warn", "dump", "stall"]
    assert g.stalled
    assert len(stalled) == 1
    # the dump stage left an all-thread faulthandler dump
    assert g.stack_path and os.path.exists(g.stack_path)
    with open(g.stack_path, encoding="utf-8") as fh:
        text = fh.read()
    assert "=== stacks pid=" in text and "Thread" in text


def test_ladder_stages_are_monotonic_and_fire_once():
    with wd.guard("collective.wait", budget_s=0.02) as g:
        time.sleep(0.025)
        assert [s for _n, s in wd.check_now()] == ["warn"]
        assert wd.check_now() == []  # no re-fire at the same stage
        time.sleep(0.03)  # past 2x budget: dump then stall, in order
        assert [s for _n, s in wd.check_now()] == ["dump", "stall"]
        assert wd.check_now() == []
        assert g.stage == 3


def test_legitimately_slow_op_under_budget_never_escalates():
    """The false-positive contract: a slow round under budget is NOT a
    stall — nothing fires, nothing is dumped."""
    with wd.guard("collective.wait", budget_s=5.0) as g:
        time.sleep(0.05)
        assert wd.check_now() == []
    assert g.stage == 0 and not g.stalled


def test_slow_but_progressing_stream_never_escalates():
    """Per-op guards model per-page/per-request budgets: N sequential
    waits each under budget must never trip, however long they total —
    only ONE op wedged past the budget does."""
    for page in range(10):
        with wd.guard("extmem.decode", budget_s=0.05, page=page) as g:
            time.sleep(0.01)  # 10 x 0.01 = 2x budget in total, all fine
            assert wd.check_now() == []
            assert not g.stalled
        wd.progress("extmem.page", page=page)


def test_exit_unregisters_op():
    with wd.guard("collective.wait", budget_s=0.01):
        pass
    time.sleep(0.02)
    assert wd.check_now() == []  # completed op cannot escalate late


def test_disabled_guard_is_noop():
    wd.configure(enabled=False)
    with wd.guard("collective.wait", budget_s=0.001) as g:
        time.sleep(0.01)
        assert wd.check_now() == []
    assert not g.stalled and g.stage == 0


def test_budget_env_override(monkeypatch):
    monkeypatch.setenv("XGBOOST_TPU_WATCHDOG_COLLECTIVE_WAIT_S", "42.5")
    assert wd.budget_for("collective.wait") == 42.5
    monkeypatch.delenv("XGBOOST_TPU_WATCHDOG_COLLECTIVE_WAIT_S")
    assert wd.budget_for("collective.wait") \
        == wd.DEFAULT_BUDGETS["collective.wait"]
    assert wd.budget_for("no.such.seam") > 0  # fallback, never unbudgeted


def test_on_stall_exception_does_not_kill_the_monitor():
    def boom(_op):
        raise RuntimeError("poke failed")

    with wd.guard("collective.wait", budget_s=0.001, on_stall=boom):
        time.sleep(0.01)
        fired = wd.check_now()
    assert [s for _n, s in fired] == ["warn", "dump", "stall"]
    # a subsequent guard still works
    with wd.guard("collective.wait", budget_s=0.001):
        time.sleep(0.005)
        assert wd.check_now()


# ---------------------------------------------------------------------------
# heartbeat-loss vs progress-loss semantics
# ---------------------------------------------------------------------------

def test_progress_markers_advance_only_on_payload_change():
    wd.progress("train.round", round=1)
    m1 = wd.markers()
    time.sleep(0.01)
    wd.progress("train.round", round=1)  # re-shipped identical marker
    m2 = wd.markers()
    # a heartbeat (same payload, newer timestamp) is NOT progress
    assert not wd.advanced(m1, m2)
    wd.progress("train.round", round=2)
    assert wd.advanced(m2, wd.markers())
    # a NEW marker key is progress too
    wd.progress("extmem.page", page=0)
    assert wd.advanced(m2, wd.markers())
    # empty/missing current markers are never progress
    assert not wd.advanced(m1, {})
    assert wd.advanced(None, m1)


def test_marker_age_uses_newest_marker():
    wd.progress("a", v=1)
    time.sleep(0.02)
    wd.progress("b", v=1)
    age = wd.marker_age(wd.markers())
    assert age is not None and age < 0.02
    assert wd.marker_age({}) is None and wd.marker_age(None) is None


def test_tracker_liveness_clock_resets_only_on_progress():
    """The tracker-side half of the semantics: ingesting an IDENTICAL
    marker set (heartbeat) must not reset the staleness clock; an
    advanced one must."""
    tr = RabitTracker(n_workers=2, host_ip="127.0.0.1", elastic=True)
    try:
        tr._ingest_progress(0, {"train.round": {"t_mono": 1.0, "round": 1}})
        t_first = tr._liveness[0]["t_advance"]
        time.sleep(0.02)
        tr._ingest_progress(0, {"train.round": {"t_mono": 2.0, "round": 1}})
        assert tr._liveness[0]["t_advance"] == t_first  # heartbeat only
        tr._ingest_progress(0, {"train.round": {"t_mono": 3.0, "round": 2}})
        assert tr._liveness[0]["t_advance"] > t_first   # real progress
        # the journal's per-rank resume round tracks the marker
        assert tr._progress_round[0] == 2
        # the shard map marker lands in journalable state
        tr._ingest_progress(0, {"shard_map": {
            "t_mono": 4.0, "map": {"num_shards": 4, "world": 2,
                                   "assign": [0, 1, 0, 1]}}})
        assert tr._shard_map == {"num_shards": 4, "world": 2,
                                 "assign": [0, 1, 0, 1]}
    finally:
        tr.free()


# ---------------------------------------------------------------------------
# tracker join ladder (the "declare the peer dead" recovery path)
# ---------------------------------------------------------------------------

def test_join_watchdog_dumps_then_declares_laggard_dead(monkeypatch):
    """A member that never reaches its round boundary during a pending
    regroup: warned, asked for a remote stack dump, then declared dead so
    the epoch forms with the remainder — the survivors get their
    assignment instead of waiting forever."""
    monkeypatch.setenv("XGBOOST_TPU_WATCHDOG_TRACKER_JOIN_S", "0.6")
    tr = RabitTracker(n_workers=2, host_ip="127.0.0.1", elastic=True)
    tr.start()
    socks = {}

    def fake_worker(tag, idx):
        s = socket.create_connection(("127.0.0.1", tr.port), timeout=30)
        send_msg(s, {"cmd": "start", "host": tag})
        reply = recv_msg(s)
        if reply.get("coordinator") is None:
            send_msg(s, {"cmd": "coordinator", "addr": "127.0.0.1:45678"})
        socks[idx] = s

    t0 = threading.Thread(target=fake_worker, args=("a", 0))
    t1 = threading.Thread(target=fake_worker, args=("b", 1))
    t0.start()
    t1.start()
    t0.join(30)
    t1.join(30)
    assert len(socks) == 2, "rendezvous did not complete"
    try:
        # rank 0 reaches its boundary and joins; rank 1 "stalls" (silent)
        send_msg(socks[0], {"cmd": "regroup_join", "round": 3})
        got = {}

        def drain(idx):
            while True:
                try:
                    m = recv_msg(socks[idx], timeout=15.0)
                except OSError:
                    m = None
                if m is None:
                    got.setdefault(idx, []).append("EOF")
                    return
                got.setdefault(idx, []).append(m)
                if m.get("cmd") == "regroup":
                    return

        d0 = threading.Thread(target=drain, args=(0,), daemon=True)
        d1 = threading.Thread(target=drain, args=(1,), daemon=True)
        d0.start()
        d1.start()
        d0.join(15)
        d1.join(15)
        # the laggard was asked for its stacks, then severed
        cmds1 = [m if m == "EOF" else m.get("cmd") for m in got.get(1, [])]
        assert "stackdump" in cmds1 and "EOF" in cmds1, cmds1
        # the survivor got the shrunken epoch with its reported round
        regroup = [m for m in got.get(0, [])
                   if m != "EOF" and m.get("cmd") == "regroup"]
        assert regroup and regroup[0]["world"] == 1
        assert regroup[0]["round"] == 3
    finally:
        for s in socks.values():
            try:
                s.close()
            except OSError:
                pass
        tr.free()
