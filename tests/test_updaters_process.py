"""prune / refresh / sync updaters + process_type=update
(reference: updater_prune.cc, updater_refresh.cc, updater_sync.cc,
tests/python/test_updaters.py::test_process_type)."""
import numpy as np
import pytest

import xgboost_tpu as xtb


def _data(seed=0, n=1500, f=6):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.3 * rng.normal(size=n)).astype(np.float32)
    return X, y


def test_refresh_recomputes_leafs_on_new_data():
    X, y = _data(seed=0)
    X2, y2 = _data(seed=1)
    d1 = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 4,
                     "eta": 0.5}, d1, 5, verbose_eval=False)
    dumps_before = bst.get_dump()
    preds_before = bst.predict(xtb.DMatrix(X2))

    # refresh the SAME model against new data: structure identical,
    # leaf values move toward the new labels
    d2 = xtb.DMatrix(X2, label=y2)
    bst.set_param({"process_type": "update", "updater": "refresh"})
    for it in range(5):
        bst.update(d2, it)
    dumps_after = bst.get_dump()
    assert len(dumps_after) == len(dumps_before)

    def structure(dump):
        return [ln.split("]")[0] for ln in dump.splitlines() if "[" in ln]

    for a, b in zip(dumps_before, dumps_after):
        assert structure(a) == structure(b)
    preds_after = bst.predict(xtb.DMatrix(X2))
    mse_before = np.mean((preds_before - y2) ** 2)
    mse_after = np.mean((preds_after - y2) ** 2)
    assert mse_after < mse_before, (mse_before, mse_after)


def test_refresh_leaf_false_keeps_predictions():
    X, y = _data(seed=2)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 3}, d, 3,
                    verbose_eval=False)
    p0 = bst.predict(d)
    bst.set_param({"process_type": "update", "updater": "refresh",
                   "refresh_leaf": "0"})
    for it in range(3):
        bst.update(d, it)
    np.testing.assert_allclose(bst.predict(d), p0, rtol=1e-6)


def test_prune_collapses_low_gain_splits():
    X, y = _data(seed=3)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 6,
                     "gamma": 0.0}, d, 3, verbose_eval=False)
    leaves_before = [t.num_leaves for t in bst.trees]
    # re-prune with a large gamma: many splits fall below the bar
    bst.set_param({"process_type": "update", "updater": "prune",
                   "gamma": 1e6})
    for it in range(3):
        bst.update(d, it)
    leaves_after = [t.num_leaves for t in bst.trees]
    assert all(a < b for a, b in zip(leaves_after, leaves_before))
    # with an absurd gamma everything collapses to stumps
    assert all(a == 1 for a in leaves_after)
    # predictions remain finite and the model still works
    assert np.isfinite(bst.predict(d)).all()


def test_prune_respects_kept_gains():
    X, y = _data(seed=4)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 4,
                     "gamma": 0.0}, d, 2, verbose_eval=False)
    p0 = bst.predict(d)
    bst.set_param({"process_type": "update", "updater": "prune",
                   "gamma": 0.0})
    for it in range(2):
        bst.update(d, it)
    # nothing below gamma=0 (all recorded gains > 0): identical model
    np.testing.assert_allclose(bst.predict(d), p0, rtol=1e-6)


def test_update_requires_updater_param():
    X, y = _data(seed=5, n=300)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 3}, d, 2,
                    verbose_eval=False)
    bst.set_param("process_type", "update")
    with pytest.raises(ValueError, match="updater"):
        bst.update(d, 0)
    bst.set_param("updater", "refresh")
    bst.update(d, 0)
    bst.update(d, 1)
    with pytest.raises(ValueError, match="exceeds"):
        bst.update(d, 2)


def test_approx_tree_method():
    """tree_method='approx': per-iteration hessian-weighted re-sketch
    (reference: updater_approx.cc grow_histmaker) reaches hist-level quality
    and re-centers cuts as hessians concentrate (binary logistic)."""
    rng = np.random.default_rng(7)
    X = rng.normal(size=(2500, 6)).astype(np.float32)
    logits = X[:, 0] * 2 + X[:, 1]
    y = (logits + rng.normal(scale=0.5, size=2500) > 0).astype(np.float32)
    res_a, res_h = {}, {}
    xtb.train({"objective": "binary:logistic", "tree_method": "approx",
               "max_depth": 4, "eta": 0.3, "max_bin": 64,
               "eval_metric": "logloss"},
              xtb.DMatrix(X, label=y), 8,
              evals=[(xtb.DMatrix(X, label=y), "t")], evals_result=res_a,
              verbose_eval=False)
    xtb.train({"objective": "binary:logistic", "tree_method": "hist",
               "max_depth": 4, "eta": 0.3, "max_bin": 64,
               "eval_metric": "logloss"},
              xtb.DMatrix(X, label=y), 8,
              evals=[(xtb.DMatrix(X, label=y), "t")], evals_result=res_h,
              verbose_eval=False)
    la, lh = res_a["t"]["logloss"][-1], res_h["t"]["logloss"][-1]
    assert la < res_a["t"]["logloss"][0]
    assert abs(la - lh) < 0.05, (la, lh)
