"""Cluster training driver (reference: dask/__init__.py:722 _train_async —
tracker start, per-worker comm context, rank-0 booster + history back)."""
import numpy as np
import pytest

import xgboost_tpu as xtb


def _shards(n=2000, f=6, world=2, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
    return X, y, [(X[r::world], y[r::world]) for r in range(world)]


@pytest.mark.slow
def test_train_distributed_two_workers_end_to_end():
    X, y, parts = _shards()
    params = {"objective": "binary:logistic", "max_depth": 3, "max_bin": 32,
              "eta": 0.5, "eval_metric": "logloss"}
    out = xtb.train_distributed(params, parts, num_boost_round=3,
                                eval_train=True)
    bst = out["booster"]
    assert len(bst.trees) == 3
    # the driver returns the dask-train contract: booster + eval history
    assert "train" in out["history"] and "logloss" in out["history"]["train"]
    assert len(out["history"]["train"]["logloss"]) == 3
    # the distributed model separates the classes on the full data
    preds = bst.predict(xtb.DMatrix(X))
    acc = float(np.mean((preds > 0.5) == (y > 0.5)))
    assert acc > 0.9, acc


def _load_part(seed, rank):  # module-level: callable refs ship by pickle
    X, y, _ = _shards(n=1200, world=2, seed=seed)
    w = np.abs(X[:, 1]) + 0.5
    return {"data": X[rank::2], "label": y[rank::2], "weight": w[rank::2]}


@pytest.mark.slow
def test_train_distributed_dict_and_callable_parts():
    import functools

    X, y, parts = _shards(n=1200, world=2, seed=3)
    w = np.abs(X[:, 1]) + 0.5

    mixed = [{"data": X[0::2], "label": y[0::2], "weight": w[0::2]},
             functools.partial(_load_part, 3, 1)]
    out = xtb.train_distributed(
        {"objective": "binary:logistic", "max_depth": 3, "max_bin": 32},
        mixed, num_boost_round=2)
    assert len(out["booster"].trees) == 2


def test_train_distributed_rejects_empty_parts():
    with pytest.raises(ValueError):
        xtb.train_distributed({}, [], num_boost_round=1)


@pytest.mark.slow
def test_train_distributed_worker_failure_fails_fast():
    """One worker's bad part must abort the cohort via the tracker error
    fan-out and surface the worker's traceback — not hang to the timeout."""
    import time

    X, y, parts = _shards(n=800, world=2, seed=1)
    bad = [parts[0], "/nonexistent/shard.libsvm"]
    t0 = time.time()
    with pytest.raises(RuntimeError, match="distributed training failed"):
        xtb.train_distributed({"objective": "binary:logistic",
                               "max_depth": 2, "max_bin": 32},
                              bad, num_boost_round=2, timeout=300)
    assert time.time() - t0 < 120, "failure did not fan out promptly"
