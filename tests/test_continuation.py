"""Training continuation (xgb_model=) and the margin-sync paths behind it.

Reference: training.py resumes from a Booster; UpdatePredictionCache keeps
margins in lockstep with committed trees (include/xgboost/cache.h:26).  The
cached-margin rebuild has three routes — binned page (training matrix),
streamed raw windows (large CSR), dense raw — and continuation must produce
the same model through any of them.
"""
import numpy as np
import pytest

import xgboost_tpu as xtb


def _data(seed=0, n=1200, f=8):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    X[rng.random((n, f)) < 0.2] = np.nan
    y = (np.nan_to_num(X[:, 0]) + np.nan_to_num(X[:, 1]) ** 2 - 1 +
         0.3 * rng.normal(size=n) > 0).astype(np.float32)
    return X, y


PARAMS = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
          "max_bin": 64}


def _trees_equal(a, b):
    assert len(a) == len(b)
    for ta, tb in zip(a, b):
        np.testing.assert_array_equal(ta.left_children, tb.left_children)
        np.testing.assert_array_equal(ta.split_indices, tb.split_indices)
        np.testing.assert_allclose(ta.split_conditions, tb.split_conditions,
                                   rtol=0, atol=0)


def test_continuation_identity_same_booster():
    """5 + 5 rounds on the same Booster == 10 straight rounds: the binned
    margin sync must reproduce the training margins exactly."""
    X, y = _data()
    d = xtb.DMatrix(X, label=y)
    full = xtb.train(PARAMS, d, 10, verbose_eval=False)

    d2 = xtb.DMatrix(X, label=y)
    half = xtb.train(PARAMS, d2, 5, verbose_eval=False)
    # fresh DMatrix for the second leg -> a new cache whose margin is
    # rebuilt through _sync_margin (the binned route: ellpack + split_bins)
    d3 = xtb.DMatrix(X, label=y)
    cont = xtb.train(PARAMS, d3, 5, verbose_eval=False, xgb_model=half)
    _trees_equal(full.trees, cont.trees)


def test_continuation_identity_after_reload(tmp_path):
    """Loaded models carry no split_bins: continuation goes through the raw
    margin route and must still match (thr == cut values exactly)."""
    X, y = _data(seed=3)
    d = xtb.DMatrix(X, label=y)
    full = xtb.train(PARAMS, d, 8, verbose_eval=False)

    half = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 4, verbose_eval=False)
    p = tmp_path / "half.ubj"
    half.save_model(str(p))
    cont = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 4, verbose_eval=False,
                     xgb_model=str(p))
    _trees_equal(full.trees, cont.trees)


def test_continuation_exact_updater():
    X, y = _data(seed=5)
    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
              "tree_method": "exact"}
    full = xtb.train(params, xtb.DMatrix(X, label=y), 8, verbose_eval=False)
    half = xtb.train(params, xtb.DMatrix(X, label=y), 4, verbose_eval=False)
    cont = xtb.train(params, xtb.DMatrix(X, label=y), 4, verbose_eval=False,
                     xgb_model=half)
    _trees_equal(full.trees, cont.trees)


def test_eval_during_continuation():
    """eval_set on the training matrix stays consistent across the leg
    boundary (prediction-cache semantics)."""
    X, y = _data(seed=7)
    res = {}
    d = xtb.DMatrix(X, label=y)
    full = xtb.train({**PARAMS, "eval_metric": "logloss"}, d, 10,
                     evals=[(d, "t")], evals_result=res, verbose_eval=False)
    res2 = {}
    half = xtb.train({**PARAMS, "eval_metric": "logloss"},
                     xtb.DMatrix(X, label=y), 5, verbose_eval=False)
    xtb.train({**PARAMS, "eval_metric": "logloss"}, xtb.DMatrix(X, label=y),
              5, evals=[(xtb.DMatrix(X, label=y), "t")], evals_result=res2,
              verbose_eval=False, xgb_model=half)
    np.testing.assert_allclose(res["t"]["logloss"][-1],
                               res2["t"]["logloss"][-1], rtol=1e-5)


def test_continuation_different_max_bin_no_stale_bins(tmp_path):
    """Advisor (r2, high): in-memory continuation with a CHANGED max_bin must
    not route the old trees' split_bins through the new cache's ellpack —
    stale bins index different cuts and silently corrupt every gradient of
    the continued training.  Ground truth: the reloaded-model continuation,
    which carries no split_bins and always rebuilds via raw thresholds."""
    X, y = _data(seed=11)
    half = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 5, verbose_eval=False)
    p = tmp_path / "half.json"
    half.save_model(str(p))

    p2 = dict(PARAMS, max_bin=16)
    cont = xtb.train(p2, xtb.DMatrix(X, label=y), 3, verbose_eval=False,
                     xgb_model=half)
    cont_raw = xtb.train(p2, xtb.DMatrix(X, label=y), 3, verbose_eval=False,
                         xgb_model=str(p))
    _trees_equal(cont.trees, cont_raw.trees)


def test_continuation_fresh_dmatrix_keeps_binned_route():
    """Same data + same max_bin through a fresh DMatrix: the cuts objects
    differ but their values are identical, so split_bins must REBIND onto the
    new cuts (exact searchsorted) and keep the fast binned margin route."""
    X, y = _data(seed=13)
    half = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 5, verbose_eval=False)
    d3 = xtb.DMatrix(X, label=y)
    cont = xtb.train(PARAMS, d3, 1, verbose_eval=False, xgb_model=half)
    ell = d3.ensure_ellpack(max_bin=PARAMS["max_bin"])
    # the first 5 trees were rebound onto d3's cuts; the 6th was grown there
    assert all(t.cuts_token == ell.cuts.token for t in cont.trees)
