"""Federated training through the central gRPC relay (reference:
plugin/federated + tests/test_distributed/test_federated/test_federated.py —
in-process gRPC workers).  Workers hold disjoint row shards and exchange only
aggregate statistics through the tracker; trees must be identical on every
worker and match the plain multi-worker result."""
import threading

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu import collective

grpc = pytest.importorskip("grpc")


def _make(world):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(3000, 6)).astype(np.float32)
    X[rng.random(X.shape) < 0.05] = np.nan
    y = (np.nan_to_num(X[:, 0]) + np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
    return X, y


def _worker(rank, world, addr, results, errors):
    try:
        with collective.CommunicatorContext(
                dmlc_communicator="federated",
                federated_server_address=addr,
                federated_world_size=world, federated_rank=rank):
            assert collective.get_rank() == rank
            assert collective.get_world_size() == world
            X, y = _make(world)
            d = xtb.DMatrix(X[rank::world], label=y[rank::world])
            bst = xtb.train({"objective": "binary:logistic", "max_depth": 4,
                             "eta": 0.3, "max_bin": 64}, d, 3,
                            verbose_eval=False)
            results[rank] = "".join(bst.get_dump(dump_format="json"))
    except Exception as e:  # noqa: BLE001
        errors[rank] = e


def test_federated_training_identical_trees():
    from xgboost_tpu.federated import FederatedTracker

    world = 3
    tracker = FederatedTracker(world_size=world)
    try:
        results, errors = {}, {}
        threads = [threading.Thread(target=_worker,
                                    args=(r, world, tracker.address,
                                          results, errors), daemon=True)
                   for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in threads), "worker deadlocked"
        assert not errors, errors
        dumps = [results[r] for r in range(world)]
        assert all(d == dumps[0] for d in dumps[1:])
    finally:
        tracker.shutdown()


def test_federated_collective_primitives():
    from xgboost_tpu.federated import FederatedTracker

    world = 2
    tracker = FederatedTracker(world_size=world)
    out = {}

    def w(rank):
        with collective.CommunicatorContext(
                dmlc_communicator="federated",
                federated_server_address=tracker.address,
                federated_world_size=world, federated_rank=rank):
            s = collective.allreduce(np.asarray([rank + 1.0, 2.0]))
            g = collective.allgather(np.asarray([rank], np.int64))
            b = collective.broadcast("hello" if rank == 0 else None, 0)
            mx = collective.allreduce(np.asarray([rank], np.int64),
                                      collective.Op.MAX)
            out[rank] = (s.tolist(), g[:, 0].tolist(), b, int(mx[0]))

    try:
        ts = [threading.Thread(target=w, args=(r,), daemon=True)
              for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in ts), "worker deadlocked"
        assert out[0] == ([3.0, 4.0], [0, 1], "hello", 1)
        assert out[1] == out[0]
    finally:
        tracker.shutdown()


def test_federated_tls_round_trip():
    """TLS mode (reference: plugin/federated secure channel params): a
    self-signed server cert, secure tracker port, and workers dialing with
    federated_server_cert_path must exchange exactly like plaintext."""
    import datetime
    import tempfile

    pytest.importorskip("grpc")
    cryptography = pytest.importorskip("cryptography")  # noqa: F841
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName("localhost"),
                 x509.IPAddress(__import__("ipaddress").ip_address(
                     "127.0.0.1"))]), critical=False)
            .sign(key, hashes.SHA256()))
    key_pem = key.private_bytes(
        serialization.Encoding.PEM, serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)

    from xgboost_tpu.federated import FederatedBackend, FederatedTracker

    tracker = FederatedTracker(2, server_key=key_pem, server_cert=cert_pem)
    try:
        with tempfile.NamedTemporaryFile(suffix=".pem") as cf:
            cf.write(cert_pem)
            cf.flush()
            results = {}

            def worker(rank):
                b = FederatedBackend(f"localhost:{tracker.port}", 2, rank,
                                     server_cert_path=cf.name)
                try:
                    results[rank] = b.allgather(
                        np.arange(3, dtype=np.float64) + rank)
                finally:
                    b.shutdown()

            ts = [threading.Thread(target=worker, args=(r,))
                  for r in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in ts)
        want = np.stack([np.arange(3.0), np.arange(3.0) + 1])
        for r in range(2):
            np.testing.assert_array_equal(results[r], want)
    finally:
        tracker.shutdown()
