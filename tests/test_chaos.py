"""Composed-fault chaos harness (docs/reliability.md "Integrity &
chaos"): seeded schedule generation is pure, episodes run green under
their deadlines with every invariant checked, a red outcome is actually
detectable, and replaying a seed reproduces schedule and outcome
bit-for-bit.  The quick tier runs a 2-episode soak smoke; the nightly
soak (scripts/chaos_soak.py) runs >= 20 episodes across all the
scenario templates (extmem, fleet, lifecycle, elastic, tracker_kill,
stall)."""
import json

import pytest

from xgboost_tpu.reliability import chaos, faults


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.clear()
    yield
    faults.clear()


def test_generate_plan_pure_and_in_catalog():
    for name, sc in chaos.SCENARIOS.items():
        p1 = chaos.generate_plan(name, 12345)
        p2 = chaos.generate_plan(name, 12345)
        assert p1 == p2, f"{name}: schedule is not a pure function of seed"
        assert p1 != chaos.generate_plan(name, 12346)
        assert 1 <= len(p1["faults"]) <= sc.max_faults
        allowed = {(e.site, e.kind) for e in sc.catalog}
        for spec in p1["faults"]:
            assert (spec["site"], spec["kind"]) in allowed
        # every plan must install cleanly (sites in SEAMS, kinds known)
        faults.install(json.loads(json.dumps(p1)))
        faults.clear()


def test_plans_are_json_roundtrippable():
    p = chaos.generate_plan("extmem", 99)
    assert json.loads(json.dumps(p)) == p


def test_kill_kind_only_in_subprocess_scenarios():
    """A kill at a driver-side seam would take the soak harness down with
    it (os._exit): only scenarios whose seams fire in launcher-spawned
    subprocesses (workers, or the supervised tracker subprocess for
    ``tracker.journal``) may schedule kills."""
    for name, sc in chaos.SCENARIOS.items():
        for entry in sc.catalog:
            if entry.kind == "kill":
                assert name in ("elastic", "tracker_kill"), \
                    f"{name} schedules kill at driver-side seam {entry.site}"


def test_soak_two_episode_smoke():
    """The quick-tier smoke: two extmem episodes + the automatic replay
    of episode 0 — all green, schedule and outcome reproduced."""
    report = chaos.soak(20260804, budget_s=0.0, min_episodes=2,
                        scenarios=["extmem"])
    assert report["ok"], json.dumps(report, indent=1)
    # 2 scheduled episodes + 1 replay episode
    assert len(report["episodes"]) == 3
    assert report["green"] == 3
    rp = report["replay"]
    assert rp["schedule_identical"] and rp["outcome_identical"]
    for ep in report["episodes"]:
        assert ep["invariants"]["no_hang"] == "ok"
        assert ep["invariants"]["fault_accounting"] == "ok"


def test_episode_replay_bitwise():
    r1 = chaos.run_episode("extmem", 777)
    r2 = chaos.run_episode("extmem", 777)
    assert r1.plan == r2.plan
    assert r1.ok and r2.ok
    assert r1.artifacts["digest"] == r2.artifacts["digest"]
    assert r1.invariants == r2.invariants


def test_red_episode_is_detected():
    """An unsurvivable hand-written plan (a hard mid-stream page-load
    failure, which single-process training cannot absorb) must come back
    red with the failure named — the harness can actually fail."""
    plan = {"faults": [{"site": "extmem.page_load", "kind": "exception"}]}
    rep = chaos.run_episode("extmem", 1, plan=plan)
    assert not rep.ok
    assert "FaultInjected" in rep.invariants["completed"]
    assert rep.error


def test_repro_command_names_scenario_and_seed():
    rep = chaos.run_episode("extmem", 424242)
    assert "extmem 424242" in rep.repro


@pytest.mark.slow
def test_fleet_episode_green_and_replayable():
    """One full fleet-under-traffic episode: composed dispatch/wire
    faults, zero dropped requests, a flight dump per death, results
    bitwise vs the in-process twin — and the same seed reproduces it."""
    r1 = chaos.run_episode("fleet", 7)
    assert r1.ok, (r1.invariants, r1.error)
    r2 = chaos.run_episode("fleet", 7)
    assert r2.plan == r1.plan and r2.ok
    assert r2.artifacts["digest"] == r1.artifacts["digest"]


@pytest.mark.slow
def test_lifecycle_episode_deterministic_reject():
    """A lifecycle episode whose plan carries a reject-class fault must
    deterministically reject (incumbent untouched) — replayed twice."""
    seed = 5  # seed 5's plan includes lifecycle.* exception faults
    r1 = chaos.run_episode("lifecycle", seed)
    assert r1.ok, (r1.invariants, r1.error)
    r2 = chaos.run_episode("lifecycle", seed)
    assert r2.artifacts["reason"] == r1.artifacts["reason"]
    assert r2.artifacts["digest"] == r1.artifacts["digest"]
