"""deterministic_histogram: fixed-point limb histograms (ops/quantise.py).

The reference makes gpu_hist bitwise reproducible across worker topologies
by quantising gradients to integers so every reduction is exact
(src/tree/gpu_hist/quantiser.cuh; tests/cpp/tree/test_gpu_hist.cu
determinism cases).  These tests pin the same contract for the TPU design:
int8-limb one-hot matmuls with int32 accumulation, psum over integers,
int64 host allreduce — identical tree bits for ANY chip/process layout.
"""
import hashlib
import threading

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu import collective


def _data(n=3000, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) > 0).astype(
        np.float32)
    return X, y


def _dump_hash(bst):
    return hashlib.md5(
        "".join(bst.get_dump(dump_format="json")).encode()).hexdigest()


def test_quantised_hist_matches_int64_reference():
    """The limb histogram must equal an exact int64 reconstruction."""
    import jax.numpy as jnp

    from xgboost_tpu.ops.quantise import (QUANT_BITS, hist_accumulate_q,
                                          local_rho, quantise_gpair)

    rng = np.random.default_rng(3)
    R, F, B, N = 5000, 4, 16, 4
    bins = rng.integers(0, B + 1, size=(R, F)).astype(np.int32)  # B = missing
    gpair = rng.normal(size=(R, 2)).astype(np.float32)
    pos = rng.integers(-1, N, size=R).astype(np.int32)
    valid = np.ones(R, bool)

    rho = local_rho(jnp.asarray(gpair), jnp.asarray(valid))
    gq = np.asarray(quantise_gpair(jnp.asarray(gpair), rho))
    hist = np.asarray(hist_accumulate_q(
        jnp.asarray(bins), jnp.asarray(gq), jnp.asarray(pos),
        jnp.int32(0), N, B, chunk=512), np.int64)

    # exact integer reference from the limbs
    q = (gq[:, :, 0].astype(np.int64) + 256 * gq[:, :, 1].astype(np.int64)
         + 65536 * gq[:, :, 2].astype(np.int64))
    ref = np.zeros((N, F, B, 2), np.int64)
    for n in range(N):
        sel = pos == n
        for f in range(F):
            for b in range(B):
                m = sel & (bins[:, f] == b)
                ref[n, f, b] = q[m].sum(axis=0)
    got = (hist[..., 0] + 256 * hist[..., 1] + 65536 * hist[..., 2])
    np.testing.assert_array_equal(got, ref)
    # quantisation error bounded by one step of the fixed-point grid per
    # channel (half a step from rounding + up to half from the f32 g*scale
    # product itself)
    step = np.asarray(rho) / ((1 << QUANT_BITS) - 1)
    recon = q * step[None, :].astype(np.float64)
    assert (np.abs(recon - gpair) <= 1.0001 * step[None, :]).all()


def test_quantised_pallas_kernel_bitwise_matches_xla():
    """The int8 x int8 -> int32 Pallas kernel (interpret mode off-TPU) must
    produce bitwise-identical limb histograms to the XLA accumulation —
    integer sums are exact, so ANY disagreement is a bug, not noise."""
    import jax.numpy as jnp

    from xgboost_tpu.ops.hist_pallas import build_histogram_pallas_q
    from xgboost_tpu.ops.quantise import (hist_accumulate_q, local_rho,
                                          quantise_gpair)

    rng = np.random.default_rng(11)
    R, F, B, N = 2500, 5, 16, 4
    bins = rng.integers(0, B + 1, size=(R, F)).astype(np.int32)
    gpair = rng.normal(size=(R, 2)).astype(np.float32)
    valid = np.ones(R, bool)
    rho = local_rho(jnp.asarray(gpair), jnp.asarray(valid))
    gq = quantise_gpair(jnp.asarray(gpair), rho)
    for node0, n_nodes, stride in ((0, N, 1), (N - 1, N // 2, 2)):
        pos = jnp.asarray(
            rng.integers(node0 - 1, node0 + 2 * n_nodes, size=R), jnp.int32)
        ref = np.asarray(hist_accumulate_q(
            jnp.asarray(bins), gq, pos, jnp.int32(node0), n_nodes, B,
            chunk=512, stride=stride))
        got = np.asarray(build_histogram_pallas_q(
            jnp.asarray(bins), gq, pos, node0=node0, n_nodes=n_nodes,
            n_bin=B, stride=stride, interpret=True, row_tile=512,
            feat_group=2))
        np.testing.assert_array_equal(got, ref)


def test_quantised_pallas_training_bitwise():
    """deterministic_histogram=True with hist_impl='pallas' (the production
    TPU kernel) grows byte-identical trees to the XLA quantised path —
    VERDICT r4 #4: the determinism contract and the fast kernel at once."""
    X, y = _data(n=1200, f=5)

    def run(impl):
        p = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
             "max_bin": 16, "deterministic_histogram": True}
        if impl:
            p["_hist_impl"] = impl
        bst = xtb.train(p, xtb.DMatrix(X, label=y), 2, verbose_eval=False)
        return _dump_hash(bst)

    assert run("pallas") == run(None)


def test_quantised_bitwise_across_device_counts(eight_devices):
    """1 device vs 8-chip mesh: identical tree bits (the f32 path only
    guarantees this structurally at shallow depth)."""
    X, y = _data()

    def run(nd):
        bst = xtb.train({"objective": "binary:logistic", "max_depth": 5,
                         "eta": 0.3, "max_bin": 64, "n_devices": nd,
                         "deterministic_histogram": True},
                        xtb.DMatrix(X, label=y), 4, verbose_eval=False)
        return _dump_hash(bst), bst.predict(xtb.DMatrix(X))

    h1, p1 = run(1)
    h8, p8 = run(8)
    assert h1 == h8
    np.testing.assert_array_equal(p1, p8)


def test_quantised_bitwise_process_times_chip(eight_devices):
    """2 fake processes x 4-chip mesh vs 2 fake processes x 1 chip: the full
    composed topology must produce the same bits as the flat one — the
    cross-TOPOLOGY guarantee the f32 default cannot give (see
    test_multiprocess.py::test_two_process_chip_mesh_composed_identical)."""
    X, y = _data()
    results, errors = {}, {}

    def worker(rank, nd, tag):
        try:
            with collective.CommunicatorContext(
                    dmlc_communicator="in-memory", in_memory_world_size=2,
                    in_memory_rank=rank, in_memory_group=f"quant-{tag}"):
                Xs, ys = X[rank::2], y[rank::2]
                bst = xtb.train({"objective": "binary:logistic",
                                 "max_depth": 4, "eta": 0.3, "max_bin": 64,
                                 "n_devices": nd,
                                 "deterministic_histogram": True},
                                xtb.DMatrix(Xs, label=ys), 3,
                                verbose_eval=False)
                results[(tag, rank)] = _dump_hash(bst)
        except Exception as e:  # noqa: BLE001
            errors[(tag, rank)] = e

    for tag, nd in (("mesh", 4), ("flat", 1)):
        ts = [threading.Thread(target=worker, args=(r, nd, tag))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in ts), "worker deadlocked"
    assert not errors, errors
    # ranks agree within a topology AND the topologies agree with each other
    assert results[("mesh", 0)] == results[("mesh", 1)]
    assert results[("flat", 0)] == results[("flat", 1)]
    assert results[("mesh", 0)] == results[("flat", 0)]


def test_quantised_quality_matches_f32():
    """Fixed-point resolution (22 bits of the max-gradient scale) must not
    cost accuracy."""
    X, y = _data(seed=7)
    Xt, yt = _data(seed=8)

    def err(det):
        bst = xtb.train({"objective": "binary:logistic", "max_depth": 5,
                         "eta": 0.3, "max_bin": 64,
                         "deterministic_histogram": det},
                        xtb.DMatrix(X, label=y), 6, verbose_eval=False)
        return np.mean((bst.predict(xtb.DMatrix(Xt)) > 0.5) != yt)

    e_q, e_f = err(True), err(False)
    assert e_q <= e_f + 0.01, (e_q, e_f)


def test_quantised_unsupported_combinations_raise():
    X, y = _data(n=500)
    d = xtb.DMatrix(X, label=y)
    with pytest.raises(NotImplementedError):
        xtb.train({"deterministic_histogram": True, "tree_method": "exact",
                   "objective": "binary:logistic"}, d, 1, verbose_eval=False)
    with pytest.raises(NotImplementedError):
        xtb.train({"deterministic_histogram": True, "grow_policy": "lossguide",
                   "max_leaves": 8, "max_depth": 0,
                   "objective": "binary:logistic"}, d, 1, verbose_eval=False)


def test_quantised_extmem_bitwise_across_device_counts(eight_devices):
    """External-memory streaming x deterministic_histogram: page-order,
    chip-count, and process-count all reduce in exact integers, so extmem
    training is bit-identical across topologies too."""
    from xgboost_tpu.data.extmem import DataIter, ExtMemQuantileDMatrix

    X, y = _data(n=4096)

    class Pages(DataIter):
        def __init__(self):
            super().__init__()
            self._i = 0

        def next(self, input_data):
            if self._i >= 4:
                return 0
            lo = self._i * 1024
            input_data(data=X[lo:lo + 1024], label=y[lo:lo + 1024])
            self._i += 1
            return 1

        def reset(self):
            self._i = 0

    def run(nd):
        d = ExtMemQuantileDMatrix(Pages(), max_bin=32)
        bst = xtb.train({"objective": "binary:logistic", "max_depth": 4,
                         "eta": 0.3, "max_bin": 32, "n_devices": nd,
                         "deterministic_histogram": True}, d, 3,
                        verbose_eval=False)
        return _dump_hash(bst)

    assert run(1) == run(8)


def test_quantised_extmem_process_times_chip(eight_devices):
    """Extmem streaming under 2 fake processes x chips: the distributed
    quantised branches (rho MAX allreduce, per-level limb allreduce,
    quantised root) must keep topologies bit-identical, mirroring the
    in-memory composed test."""
    from xgboost_tpu.data.extmem import DataIter, ExtMemQuantileDMatrix

    X, y = _data(n=4096)
    results, errors = {}, {}

    def make_iter(Xs, ys):
        class Pages(DataIter):
            def __init__(self):
                super().__init__()
                self._i = 0

            def next(self, input_data):
                if self._i >= 2:
                    return 0
                lo = self._i * (len(ys) // 2)
                hi = lo + len(ys) // 2
                input_data(data=Xs[lo:hi], label=ys[lo:hi])
                self._i += 1
                return 1

            def reset(self):
                self._i = 0

        return Pages()

    def worker(rank, nd, tag):
        try:
            with collective.CommunicatorContext(
                    dmlc_communicator="in-memory", in_memory_world_size=2,
                    in_memory_rank=rank, in_memory_group=f"qext-{tag}"):
                d = ExtMemQuantileDMatrix(make_iter(X[rank::2], y[rank::2]),
                                          max_bin=32)
                bst = xtb.train({"objective": "binary:logistic",
                                 "max_depth": 3, "eta": 0.3, "max_bin": 32,
                                 "n_devices": nd,
                                 "deterministic_histogram": True}, d, 2,
                                verbose_eval=False)
                results[(tag, rank)] = _dump_hash(bst)
        except Exception as e:  # noqa: BLE001
            errors[(tag, rank)] = e

    for tag, nd in (("mesh", 4), ("flat", 1)):
        ts = [threading.Thread(target=worker, args=(r, nd, tag))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in ts), "worker deadlocked"
    assert not errors, errors
    assert results[("mesh", 0)] == results[("mesh", 1)]
    assert results[("flat", 0)] == results[("flat", 1)]
    assert results[("mesh", 0)] == results[("flat", 0)]
