"""Sliding-window coverage: FreshWindow's external-memory chunk route
(docs/serving.md "Online model lifecycle") and the online loop's
extmem-paged WindowStore (docs/online.md).

The FreshWindow extmem route existed since the lifecycle PR but was
nearly untested: these pin eviction order, weight passthrough, and the
chunked ExtMemQuantileDMatrix path — plus WindowStore's page sealing,
row/age eviction, and the DiskPage spill fallback (this container has no
zstandard, so the fallback IS the default path; the zstd leg gates on the
lib being importable).
"""
import os

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.lifecycle import FreshWindow
from xgboost_tpu.online import WindowStore
from xgboost_tpu.reliability import resources


def _batch(tag, rows=32, cols=4):
    """Identifiable rows: column 0 carries the batch tag."""
    rng = np.random.default_rng(100 + tag)
    X = rng.standard_normal((rows, cols)).astype(np.float32)
    X[:, 0] = tag
    y = (X[:, 1] > 0).astype(np.float32)
    return X, y


# ---------------------------------------------------------- FreshWindow

def test_freshwindow_extmem_chunk_route_matches_arrays():
    win = FreshWindow()
    for tag in range(4):
        win.append(*_batch(tag))
    X, y, w = win.arrays()
    d = win.to_dmatrix(extmem_chunk_rows=48, max_bin=32)
    assert d.num_row() == len(win) == 128
    np.testing.assert_array_equal(np.asarray(d.info.label), y)
    assert w is None
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 2},
                    d, 2, verbose_eval=False)
    preds = np.asarray(bst.predict(d))
    assert preds.shape == (128,) and np.all(np.isfinite(preds))


def test_freshwindow_eviction_order_through_extmem_route():
    win = FreshWindow(max_rows=80)
    for tag in range(4):  # 128 rows in, oldest 48 fall off
        win.append(*_batch(tag))
    X, y, _ = win.arrays()
    assert len(win) == 80
    # batch 0 fully evicted, batch 1 halved: oldest-first, partial slice
    np.testing.assert_array_equal(
        X[:, 0], np.concatenate([np.full(16, 1.0), np.full(32, 2.0),
                                 np.full(32, 3.0)]).astype(np.float32))
    d = win.to_dmatrix(extmem_chunk_rows=32, max_bin=32)
    assert d.num_row() == 80
    np.testing.assert_array_equal(np.asarray(d.info.label), y)


def test_freshwindow_weight_passthrough_extmem_route():
    win = FreshWindow()
    rng = np.random.default_rng(3)
    weights = []
    for tag in range(3):
        X, y = _batch(tag)
        w = rng.random(len(y)).astype(np.float32) + 0.5
        weights.append(w)
        win.append(X, y, weight=w)
    want = np.concatenate(weights)
    _, _, w_arr = win.arrays()
    np.testing.assert_array_equal(w_arr, want)
    d = win.to_dmatrix(extmem_chunk_rows=40, max_bin=32)
    np.testing.assert_allclose(np.asarray(d.info.weight), want, rtol=1e-6)


def test_freshwindow_weight_all_or_none():
    win = FreshWindow()
    X, y = _batch(0)
    win.append(X, y, weight=np.ones(len(y), np.float32))
    with pytest.raises(ValueError, match="every batch carries weights"):
        win.append(X, y)


# ---------------------------------------------------------- WindowStore

def test_windowstore_seals_exact_pages_with_odd_batches():
    ws = WindowStore(page_rows=50)
    for tag in range(5):
        ws.append(*_batch(tag, rows=32))  # 160 rows in 32-row batches
    st = ws.stats()
    assert st["rows"] == 160
    assert st["pages"] == 3 and st["staging_rows"] == 10
    X, y, w = ws.arrays()
    assert X.shape == (160, 4) and w is None
    # order preserved across seal/spill boundaries
    np.testing.assert_array_equal(
        X[:, 0], np.repeat(np.arange(5, dtype=np.float32), 32))
    ws.clear()


def test_windowstore_row_eviction_oldest_page_first():
    ws = WindowStore(max_rows=100, page_rows=32)
    for tag in range(6):
        ws.append(*_batch(tag, rows=32))
    # 192 rows appended; whole-page eviction holds <= max_rows (bounded
    # overshoot of at most one page above, never past the bound after)
    assert len(ws) <= 100
    X, _, _ = ws.arrays()
    tags = np.unique(X[:, 0])
    assert tags.min() >= 2.0, f"oldest pages must fall first, got {tags}"
    ws.clear()


def test_windowstore_age_eviction_with_injected_clock():
    now = [0.0]
    ws = WindowStore(max_age_s=10.0, page_rows=32, clock=lambda: now[0])
    ws.append(*_batch(0, rows=32))   # sealed at t=0
    now[0] = 20.0                    # ages past the horizon
    ws.append(*_batch(1, rows=32))   # append runs eviction
    X, _, _ = ws.arrays()
    assert np.all(X[:, 0] == 1.0), "aged page must be evicted"
    assert len(ws) == 32
    ws.clear()


def test_windowstore_weight_rules():
    ws = WindowStore(page_rows=16)
    X, y = _batch(0, rows=16)
    w = np.linspace(0.5, 1.5, 16).astype(np.float32)
    ws.append(X, y, weight=w)
    with pytest.raises(ValueError, match="every batch carries weights"):
        ws.append(X, y)
    _, _, got = ws.arrays()
    np.testing.assert_array_equal(got, w)
    with pytest.raises(ValueError, match="features"):
        ws.append(np.ones((4, 7), np.float32), np.ones(4, np.float32),
                  weight=np.ones(4, np.float32))
    ws.clear()


def test_windowstore_disk_fallback_pages_are_crc_gated_files(
        tmp_path, monkeypatch):
    from xgboost_tpu.data import extmem

    monkeypatch.setattr(extmem, "_zstd_available", lambda: False)
    spool = str(tmp_path / "spool")
    ws = WindowStore(page_rows=32, spool_dir=spool)
    for tag in range(3):
        ws.append(*_batch(tag, rows=32))
    st = ws.stats()
    assert st["pages_on_disk"] == 3 and st["spilled_bytes"] > 0
    files = sorted(os.listdir(spool))
    assert len(files) == 3 and all(f.endswith(".npy") for f in files)
    X, y, _ = ws.arrays()  # every page read passes the CRC gate
    assert X.shape == (96, 4) and y.shape == (96,)
    ws.clear()
    assert sorted(os.listdir(spool)) == []


def test_windowstore_zstd_pages_stay_resident(tmp_path):
    pytest.importorskip("zstandard")
    ws = WindowStore(page_rows=32, spool_dir=str(tmp_path / "spool"))
    ws.append(*_batch(0, rows=64))
    st = ws.stats()
    assert st["pages"] == 2 and st["pages_on_disk"] == 0
    assert st["spilled_bytes"] == 0
    ws.clear()


def test_windowstore_spills_resident_pages_under_memory_pressure(
        tmp_path):
    resources.reset()
    try:
        spool = str(tmp_path / "spool")
        ws = WindowStore(page_rows=32, spool_dir=spool)
        ws.append(*_batch(0, rows=64))
        before = ws.stats()
        gov = resources.get_governor()
        gov.degrade("memory", "test pressure")
        assert gov.memory_scale() < 1.0
        ws.append(*_batch(1, rows=64))   # append spills + seals to disk
        st = ws.stats()
        assert st["pages"] == 4
        assert st["pages_on_disk"] == 4, (before, st)
        X, y, _ = ws.arrays()
        assert X.shape == (128, 4)
        np.testing.assert_array_equal(
            X[:, 0], np.repeat([0.0, 1.0], 64).astype(np.float32))
        ws.clear()
    finally:
        resources.reset()


def test_windowstore_extmem_route_trains_with_weights():
    ws = WindowStore(page_rows=48)
    rng = np.random.default_rng(5)
    for tag in range(4):
        X, y = _batch(tag, rows=36)
        ws.append(X, y, weight=rng.random(36).astype(np.float32) + 0.5)
    d = ws.to_dmatrix(extmem_chunk_rows=1, max_bin=32)  # page-per-chunk
    assert d.num_row() == 144
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 2},
                    d, 2, verbose_eval=False)
    preds = np.asarray(bst.predict(d))
    assert preds.shape == (144,) and np.all(np.isfinite(preds))
    ws.clear()
