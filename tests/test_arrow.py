"""Arrow columnar ingestion (xgboost_tpu/data/arrow.py): pyarrow Table /
RecordBatch -> column-major float32 with null -> NaN, dictionary columns as
categoricals (ISSUE 1 satellite; reference: ColumnarAdapter
src/data/adapter.h:437 + python-package data.py arrow dispatch).
"""
import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

import xgboost_tpu as xtb  # noqa: E402
from xgboost_tpu.data.arrow import is_arrow  # noqa: E402


def test_is_arrow_detects_without_import():
    t = pa.table({"a": [1.0, 2.0]})
    assert is_arrow(t)
    assert is_arrow(t.to_batches()[0])
    assert not is_arrow(np.zeros((2, 2)))
    assert not is_arrow([[1.0, 2.0]])


def test_table_nulls_become_nan():
    t = pa.table({
        "x": pa.array([1.0, None, 3.0], pa.float64()),
        "y": pa.array([None, 5, 6], pa.int64()),
    })
    d = xtb.DMatrix(t)
    X = d.host_dense()
    assert X.dtype == np.float32 and X.shape == (3, 2)
    assert np.isnan(X[1, 0]) and np.isnan(X[0, 1])
    np.testing.assert_array_equal(X[[0, 2], 0], [1.0, 3.0])
    np.testing.assert_array_equal(X[1:, 1], [5.0, 6.0])
    assert d.feature_names == ["x", "y"]
    assert d.feature_types == ["q", "int"]


def test_record_batch_and_chunked_table_agree():
    data = {"a": [0.5, 1.5, 2.5, 3.5], "b": [1, 2, 3, 4]}
    table = pa.concat_tables(  # 2 chunks: exercises combine_chunks
        [pa.table({k: v[:2] for k, v in data.items()}),
         pa.table({k: v[2:] for k, v in data.items()})])
    batch = pa.table(data).to_batches()[0]
    np.testing.assert_array_equal(xtb.DMatrix(table).host_dense(),
                                  xtb.DMatrix(batch).host_dense())


def test_dictionary_column_is_categorical():
    cat = pa.array(["lo", "hi", "lo", None, "mid"]).dictionary_encode()
    t = pa.table({"level": cat, "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
    d = xtb.DMatrix(t)
    assert d.feature_types == ["c", "q"]
    codes = d.host_dense()[:, 0]
    assert np.isnan(codes[3])  # null category -> missing
    # physical codes index the dictionary values, exported by name
    cats = d.get_categories()
    assert cats == {"level": ["lo", "hi", "mid"]}
    np.testing.assert_array_equal(codes[[0, 1, 2, 4]], [0.0, 1.0, 0.0, 2.0])


def test_custom_missing_applies_to_numeric_only():
    cat = pa.array(["a", "b", "a"]).dictionary_encode()
    t = pa.table({"c": cat, "v": [-1.0, 2.0, -1.0]})
    X = xtb.DMatrix(t, missing=-1.0).host_dense()
    assert np.isnan(X[0, 1]) and np.isnan(X[2, 1])  # sentinel -> NaN
    np.testing.assert_array_equal(X[:, 0], [0.0, 1.0, 0.0])  # codes untouched


def test_train_predict_roundtrip_from_arrow():
    rng = np.random.default_rng(7)
    Xn = rng.normal(size=(128, 3)).astype(np.float32)
    y = (Xn[:, 0] > 0).astype(np.float32)
    t = pa.table({f"f{i}": Xn[:, i] for i in range(3)})
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3},
                    xtb.DMatrix(t, label=y), 3, verbose_eval=False)
    out_arrow = bst.predict(xtb.DMatrix(t))
    out_numpy = bst.predict(xtb.DMatrix(Xn))
    np.testing.assert_array_equal(out_arrow, out_numpy)
