"""Serving degradation: the engine must never wedge a caller.

Failure modes under test (docs/reliability.md): the micro-batcher worker
thread dying (submit fails fast with the original cause, pending futures
resolve exceptionally), bounded-queue load shedding (QueueFullError +
xtb_serve_shed_total), and per-request deadlines (predict raises
TimeoutError inside its SLO window instead of outliving it).
"""
import threading
import time

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.reliability import FaultInjected, faults
from xgboost_tpu.serving import (MicroBatcher, QueueFullError, ServingEngine,
                                 ServingMetrics, WorkerDiedError)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


def _train(seed=0, n=300, f=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3},
                    xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    return bst, X


def _wait_dead(batcher, timeout=5.0):
    deadline = time.monotonic() + timeout
    while batcher.worker_alive() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not batcher.worker_alive(), "worker did not die"


# =========================================================================
# worker-death liveness


def test_submit_fails_fast_after_worker_death():
    """Satellite: a dead worker must turn submit() into an immediate error
    carrying the original worker exception as the cause — never a future
    that no one will resolve."""
    b = MicroBatcher(lambda k, X, c: X, max_delay_us=0)
    faults.install({"faults": [{"site": "serve.worker", "kind": "exception",
                                "message": "worker bug"}]})
    # wake the worker so it passes the seam and dies
    try:
        b.submit("k", np.zeros((2, 2), np.float32)).result(timeout=5)
    except Exception:
        pass  # served or failed depending on who won the race — both fine
    _wait_dead(b)
    t0 = time.monotonic()
    with pytest.raises(WorkerDiedError) as ei:
        b.submit("k", np.zeros((2, 2), np.float32))
    assert time.monotonic() - t0 < 1.0  # fail FAST, no deadline needed
    assert isinstance(ei.value.__cause__, FaultInjected)
    assert "worker bug" in str(ei.value.__cause__)
    b.close()


def test_pending_requests_fail_when_worker_dies():
    """Requests already queued when the worker dies resolve exceptionally
    (they would otherwise hang their callers forever)."""
    gate = threading.Event()

    def execute(key, X, ctx):
        gate.wait(10.0)
        return X

    b = MicroBatcher(execute, max_batch=2, max_delay_us=0)
    f1 = b.submit("a", np.zeros((2, 2), np.float32))  # drained, running
    time.sleep(0.1)
    f2 = b.submit("b", np.zeros((2, 2), np.float32))  # queued behind it
    # die on the NEXT loop iteration (after batch "a" completes)
    faults.install({"faults": [{"site": "serve.worker",
                                "kind": "exception"}]})
    gate.set()
    assert f1.result(timeout=10) is not None  # in-flight batch completes
    with pytest.raises(WorkerDiedError):
        f2.result(timeout=10)  # pending one fails, promptly
    _wait_dead(b)
    b.close()


def test_engine_predict_raises_and_counts_after_worker_death():
    bst, X = _train()
    eng = ServingEngine(max_delay_us=100, warmup_buckets=(8,))
    eng.add_model("m", bst)
    assert eng.predict("m", X[:8]).shape == (8,)
    faults.install({"faults": [{"site": "serve.worker", "kind": "exception",
                                "message": "killed"}]})
    try:
        eng.predict("m", X[:8])  # wakes the worker into the seam
    except Exception:
        pass
    _wait_dead(eng._batcher)
    errors_before = eng.metrics.snapshot()["models"]["m"]["errors"]
    with pytest.raises(WorkerDiedError):
        eng.predict("m", X[:8])
    assert (eng.metrics.snapshot()["models"]["m"]["errors"]
            == errors_before + 1)
    eng.close()  # dead worker: close() must return, not hang


def test_direct_predict_survives_dead_worker():
    """direct=True bypasses the batcher: a degraded engine can still serve
    inline while the operator investigates."""
    bst, X = _train(seed=1)
    eng = ServingEngine(max_delay_us=100, warmup_buckets=(8,))
    eng.add_model("m", bst)
    faults.install({"faults": [{"site": "serve.worker", "kind": "exception"}]})
    try:
        eng.predict("m", X[:8])
    except Exception:
        pass
    _wait_dead(eng._batcher)
    faults.clear()
    out = eng.predict("m", X[:8], direct=True)
    assert out.shape == (8,) and np.all(np.isfinite(out))
    eng.close()


# =========================================================================
# bounded queue / load shedding


def test_queue_bound_sheds_and_counts():
    gate = threading.Event()

    def execute(key, X, ctx):
        gate.wait(10.0)
        return X

    m = ServingMetrics()
    b = MicroBatcher(execute, max_batch=4, max_delay_us=0, max_queue_rows=8,
                     metrics=m)
    f1 = b.submit(("mod",), np.zeros((4, 2), np.float32))
    time.sleep(0.05)  # let the worker drain f1 into a running batch
    f2 = b.submit(("mod",), np.zeros((8, 2), np.float32))  # fills the bound
    with pytest.raises(QueueFullError):
        b.submit(("mod",), np.zeros((1, 2), np.float32))
    snap = m.snapshot()
    assert snap["models"]["mod"]["shed"] == 1
    gate.set()
    f1.result(10)
    f2.result(10)
    b.close()
    from xgboost_tpu.telemetry import render_prometheus

    assert 'xtb_serve_shed_total{model="mod"}' in render_prometheus()


def test_oversized_single_request_admitted_on_empty_queue():
    """The bound sheds BACKLOG, not capability: one request larger than
    max_queue_rows still runs when nothing is queued."""
    b = MicroBatcher(lambda k, X, c: X, max_batch=4, max_delay_us=0,
                     max_queue_rows=8)
    out = b.submit("k", np.zeros((32, 2), np.float32)).result(timeout=10)
    assert out.shape == (32, 2)
    b.close()


# =========================================================================
# per-request deadline


def test_predict_deadline_raises_within_slo():
    bst, X = _train(seed=2)
    eng = ServingEngine(max_delay_us=100, warmup_buckets=(8,),
                        request_timeout_s=0.3)
    eng.add_model("m", bst)
    gate = threading.Event()
    real = eng._batcher._execute

    def stalled(key, Xb, ctx):
        gate.wait(10.0)
        return real(key, Xb, ctx)

    eng._batcher._execute = stalled
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="deadline"):
        eng.predict("m", X[:8])
    assert time.monotonic() - t0 < 2.0
    snap = eng.metrics.snapshot()["models"]["m"]
    assert snap["deadline"] == 1 and snap["errors"] == 1
    gate.set()
    eng._batcher._execute = real
    # engine recovers once the stall clears
    assert eng.predict("m", X[:8]).shape == (8,)
    eng.close()
    from xgboost_tpu.telemetry import render_prometheus

    assert 'xtb_serve_deadline_total{model="m"}' in render_prometheus()


def test_serve_config_validates_degradation_knobs():
    from xgboost_tpu.serving import ServeConfig

    with pytest.raises(ValueError):
        ServeConfig(request_timeout_s=0.0)
    with pytest.raises(ValueError):
        ServeConfig(max_queue_rows=0)
    cfg = ServeConfig(request_timeout_s=1.5, max_queue_rows=100)
    assert cfg.request_timeout_s == 1.5 and cfg.max_queue_rows == 100
