"""Zero-copy device / dlpack ingestion (reference: device adapters
src/data/device_adapter.cuh:67 CudfAdapter / :154 CupyAdapter, dlpack parsing
in src/data/array_interface.h): a jax.Array input stays on device (no host
round-trip before binning) and trains identically to the numpy path."""
import numpy as np
import pytest

import xgboost_tpu as xtb


def _make(n=600, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    return X, y


PARAMS = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3}


def test_jax_array_input_matches_numpy():
    import jax.numpy as jnp

    X, y = _make()
    bst_np = xtb.train(PARAMS, xtb.QuantileDMatrix(X, label=y),
                       num_boost_round=5, verbose_eval=False)
    dm = xtb.QuantileDMatrix(jnp.asarray(X), label=y)
    bst_jx = xtb.train(PARAMS, dm, num_boost_round=5, verbose_eval=False)
    p_np = bst_np.predict(xtb.DMatrix(X))
    p_jx = bst_jx.predict(xtb.DMatrix(X))
    np.testing.assert_array_equal(p_np, p_jx)


def test_jax_array_stays_on_device_until_needed():
    import jax.numpy as jnp

    X, y = _make()
    dm = xtb.QuantileDMatrix(jnp.asarray(X), label=y)
    assert dm._dense is None  # no host materialization during sketch+bin
    # a host path (raw predict) materializes lazily and exactly once
    h = dm.host_dense()
    np.testing.assert_allclose(h, X, rtol=1e-6)
    assert dm.host_dense() is h


def test_jax_array_custom_missing():
    import jax.numpy as jnp

    X, y = _make()
    Xm = X.copy()
    Xm[::7, 3] = -999.0
    dm_jx = xtb.DMatrix(jnp.asarray(Xm), label=y, missing=-999.0)
    dm_np = xtb.DMatrix(Xm, label=y, missing=-999.0)
    assert np.isnan(dm_jx.host_dense()[::7, 3]).all()
    np.testing.assert_array_equal(
        np.isnan(dm_jx.host_dense()), np.isnan(dm_np.host_dense()))


def test_single_device_upload_shared_between_sketch_and_bin(monkeypatch):
    from xgboost_tpu.data import dmatrix as dmx

    X, y = _make()
    uploads = []
    orig = dmx.DMatrix._device_dense

    def counting(self):
        first = self._jax_X is None
        out = orig(self)
        if first:
            uploads.append(1)
        return out

    monkeypatch.setattr(dmx.DMatrix, "_device_dense", counting)
    dm = xtb.QuantileDMatrix(X, label=y)
    assert sum(uploads) == 1  # sketch and bin shared ONE host->device upload
    # after eager binning the temporary device copy of raw X is released
    assert dm._jax_X is None
    assert dm._ellpack is not None


def test_torch_dlpack_input():
    torch = pytest.importorskip("torch")
    X, y = _make()
    t = torch.from_numpy(X)
    bst = xtb.train(PARAMS, xtb.QuantileDMatrix(t, label=y),
                    num_boost_round=5, verbose_eval=False)
    ref = xtb.train(PARAMS, xtb.QuantileDMatrix(X, label=y),
                    num_boost_round=5, verbose_eval=False)
    np.testing.assert_array_equal(
        bst.predict(xtb.DMatrix(X)), ref.predict(xtb.DMatrix(X)))
