"""Unified telemetry subsystem (xgboost_tpu/telemetry/): registry families,
span tracer, JSONL trace writer, Prometheus exposition, retrace accounting,
and the TelemetryCallback — plus the two SLO guard tests the ISSUE pins:
zero recompiles on a second identical train(), and negligible disabled-path
overhead (one flag check, shared no-op)."""
import json
import os
import threading

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu import telemetry
from xgboost_tpu.telemetry import spans as _spans
from xgboost_tpu.telemetry import trace as _trace
from xgboost_tpu.telemetry.registry import Registry


@pytest.fixture(autouse=True)
def _spans_off_after():
    """Span enabling is process-wide: restore the pre-test flag so telemetry
    tests cannot leak instrumentation overhead into the rest of the suite."""
    was = _spans.enabled()
    tr = _trace.path()
    yield
    _spans.enable(was)
    _trace.configure(tr)


def _data(r=300, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(r, f)).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float32)
    return xtb.DMatrix(X, label=y)


# ====================================================================
# registry

def test_counter_gauge_basic():
    reg = Registry()
    c = reg.counter("t_total", "help", ("op",))
    c.labels("a").inc()
    c.labels("a").inc(2.5)
    c.labels(op="b").inc()
    assert c.get("a") == 3.5 and c.get("b") == 1
    g = reg.gauge("t_gauge", "help")
    g.set(5)
    g.inc(2)
    g.dec()
    assert g.get() == 6
    with pytest.raises(ValueError):
        c.labels("a").inc(-1)  # counters only go up
    with pytest.raises(ValueError):
        c.labels("a", "b")  # wrong label arity


def test_registry_get_or_create_idempotent_and_type_checked():
    reg = Registry()
    c1 = reg.counter("t_x", "h", ("l",))
    assert reg.counter("t_x", "h", ("l",)) is c1
    with pytest.raises(ValueError):
        reg.gauge("t_x")  # same name, different kind
    with pytest.raises(ValueError):
        reg.counter("t_x", "h", ("other",))  # same name, different labels
    with pytest.raises(ValueError):
        reg.counter("bad name!")
    with pytest.raises(ValueError):
        reg.counter("2xx_total")  # exposition format: no leading digit
    with pytest.raises(ValueError):
        # explicit +Inf bound would duplicate the overflow le="+Inf" sample
        reg.histogram("t_inf", "h", buckets=(1.0, float("inf")))


def test_histogram_buckets_and_prometheus_render():
    reg = Registry()
    h = reg.histogram("t_seconds", "h", ("phase",), buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.labels("p").observe(v)
    text = reg.render_prometheus()
    assert '# TYPE t_seconds histogram' in text
    # cumulative le counts: 1 under 0.1, 3 under 1, 4 under 10, 5 total
    assert 't_seconds_bucket{phase="p",le="0.1"} 1' in text
    assert 't_seconds_bucket{phase="p",le="1"} 3' in text
    assert 't_seconds_bucket{phase="p",le="10"} 4' in text
    assert 't_seconds_bucket{phase="p",le="+Inf"} 5' in text
    assert 't_seconds_count{phase="p"} 5' in text
    (_, (count, total)), = h.snapshot_sums().items()
    assert count == 5 and total == pytest.approx(56.05)


def test_registry_thread_safety():
    reg = Registry()
    c = reg.counter("t_mt", "h", ("w",))

    def work(i):
        child = c.labels(str(i % 4))
        for _ in range(500):
            child.inc()

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(c.get(str(w)) for w in range(4)) == 4000


def test_prometheus_label_escaping():
    reg = Registry()
    reg.counter("t_esc", "h", ("p",)).labels('a"b\\c\nd').inc()
    line = [l for l in reg.render_prometheus().splitlines()
            if l.startswith("t_esc{")][0]
    assert line == 't_esc{p="a\\"b\\\\c\\nd"} 1'


# ====================================================================
# spans

def test_span_disabled_is_shared_noop():
    """The disabled-by-default overhead guard: span() behind the one
    module-level flag must return the SAME no-op object (no allocation, no
    clock read) and record nothing."""
    _spans.disable()
    s1 = _spans.span("grow.build_hist")
    s2 = _spans.span("anything.else")
    assert s1 is s2 is _spans._NULL
    before = _spans.phase_totals()
    with _spans.span("guard.phase"):
        pass
    assert "guard.phase" not in _spans.phase_totals()
    assert _spans.phase_totals() == before


def test_span_records_phase_histogram():
    _spans.enable()
    with _spans.span("t_unit.phase"):
        pass
    tot = _spans.phase_totals()["t_unit.phase"]
    assert tot["count"] >= 1 and tot["seconds"] >= 0
    assert 'phase="t_unit.phase"' in telemetry.render_prometheus()


def test_monitor_shim_reentrant_and_totals():
    """utils/timer.Monitor: stacked start/stop (the re-entrancy satellite)
    feeding the same phase histogram when telemetry is enabled."""
    from xgboost_tpu.utils.timer import Monitor

    _spans.enable()
    m = Monitor("t_mon")
    m.start("op")
    m.start("op")  # re-entrant: must NOT clobber the first bracket
    m.stop("op")
    m.stop("op")
    m.stop("op")  # unmatched: ignored
    assert m.counts["op"] == 2
    assert m.totals["op"] > 0
    tot = _spans.phase_totals()["t_mon.op"]
    assert tot["count"] >= 2


# ====================================================================
# trace writer

def test_trace_writer_jsonl_shape(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    _trace.configure(path)
    _spans.enable()
    try:
        with _spans.span("t_trace.alpha"):
            pass
        _spans.record_phase("t_trace.beta", 123_000, 456_000)
    finally:
        _trace.configure(None)
    lines = [json.loads(l) for l in open(path)]
    names = [l["name"] for l in lines]
    assert "t_trace.alpha" in names and "t_trace.beta" in names
    for rec in lines:
        assert rec["ph"] == "X"
        assert set(rec) >= {"name", "ph", "ts", "dur", "pid", "tid"}
        assert rec["pid"] == os.getpid()
    beta = lines[names.index("t_trace.beta")]
    assert beta["ts"] == pytest.approx(123.0) and beta["dur"] == pytest.approx(456.0)


# ====================================================================
# retrace accounting + train() integration

def test_compile_counter_counts_new_program_once():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return x * 3 + 1

    x = jnp.arange(7, dtype=jnp.float32)
    f(x)  # ensure compiled before the measured window
    c0 = telemetry.compiles_total()
    f(x)  # cache hit: no compile event
    assert telemetry.compiles_total() == c0
    with telemetry.compile_delta() as w:
        f(jnp.arange(13, dtype=jnp.float32))  # new shape: must compile
    assert w.count >= 1


@pytest.mark.quick
def test_second_identical_train_zero_recompiles():
    """The training no-retrace SLO (ISSUE acceptance): every level program,
    gradient kernel, and eval predict compiled in the first train() must be
    a cache hit in a second identical run."""
    d = _data(seed=3)
    dv = _data(r=100, seed=4)
    p = {"objective": "binary:logistic", "max_depth": 3}
    xtb.train(p, d, 3, evals=[(dv, "val")], verbose_eval=False)
    with telemetry.compile_delta() as w:
        xtb.train(p, d, 3, evals=[(dv, "val")], verbose_eval=False)
    assert w.count == 0, f"second identical train() compiled {w.count} programs"


def test_telemetry_callback_history_and_steady_counter():
    d = _data(seed=5)
    cb = telemetry.TelemetryCallback()
    xtb.train({"objective": "binary:logistic", "max_depth": 3}, d, 4,
              evals=[(d, "train")], callbacks=[cb], verbose_eval=False)
    assert len(cb.history) == 4
    for i, rec in enumerate(cb.history):
        assert rec["round"] == i
        assert rec["seconds"] > 0
        assert len(rec["trees"]) == 1
        t = rec["trees"][0]
        assert t["nodes"] >= 1 and t["leaves"] >= 1 and t["depth"] <= 3
    # phase attribution present once spans are on (round 0 enables them)
    later = cb.history[-1]["phases"]
    assert any("build_hist" in k for k in later)
    assert any(k.startswith("eval.") for k in later)
    assert "update.gradient" in later and "update.update_tree" in later
    # warm-up compiles land in round 0; identical later rounds must not
    # retrace (the steady SLO) — second run of this test is fully warm,
    # so only assert steadiness, not that round 0 compiled
    assert cb.compiles_steady == 0
    assert all(r["compiles"] == 0 for r in cb.history[1:])


def test_telemetry_callback_reused_across_trains_resets_warmup():
    """A reused callback must treat each train() run's first round as
    warm-up: a second run with new shapes compiles its own level programs,
    and those must NOT land in the steady (SLO: 0) counter."""
    d = _data(r=256, f=5, seed=7)
    cb = telemetry.TelemetryCallback()
    xtb.train({"objective": "binary:logistic", "max_depth": 2}, d, 2,
              callbacks=[cb], verbose_eval=False)
    # different depth: fresh level programs -> warm-up compiles in round 0
    xtb.train({"objective": "binary:logistic", "max_depth": 5}, d, 2,
              callbacks=[cb], verbose_eval=False)
    assert len(cb.history) == 4
    assert cb.compiles_steady == 0, (
        f"second run's warm-up misclassified steady: {cb.compiles_steady}")


def test_trace_configure_enables_spans(tmp_path):
    """trace.configure(path) is the programmatic XGBOOST_TPU_TRACE: it must
    turn the span tracer on, or the capture holds only compile events."""
    _spans.disable()
    path = str(tmp_path / "cfg.jsonl")
    _trace.configure(path)
    try:
        assert _spans.enabled()
        with _spans.span("t_cfg.phase"):
            pass
    finally:
        _trace.configure(None)
    assert "t_cfg.phase" in {json.loads(l)["name"] for l in open(path)}


def test_telemetry_callback_under_cv_records_phases():
    """cv() drives the full callback lifecycle (before/after_training), so
    TelemetryCallback's span enabling fires and phases populate."""
    rng = np.random.default_rng(13)
    X = rng.normal(size=(180, 5)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    cb = telemetry.TelemetryCallback()
    xtb.cv({"objective": "binary:logistic", "max_depth": 2}, d,
           num_boost_round=2, nfold=2, as_pandas=False, callbacks=[cb])
    assert len(cb.history) == 2
    assert cb.history[0]["phases"], "cv rounds recorded no phase spans"
    assert cb.history[0]["trees"] == []  # the cv aggregate has no .trees


def test_ten_round_train_prometheus_and_trace(tmp_path):
    """The ISSUE-2 end-to-end acceptance: 10 rounds with telemetry enabled
    produce per-phase histogram lines + compiles_total in the Prometheus
    text, and a parseable JSONL trace covering the phase vocabulary."""
    path = str(tmp_path / "train10.jsonl")
    _trace.configure(path)
    _spans.enable()
    try:
        d = _data(r=500, seed=6)
        xtb.train({"objective": "binary:logistic", "max_depth": 3}, d, 10,
                  evals=[(d, "train")], verbose_eval=False)
    finally:
        _trace.configure(None)
    prom = telemetry.render_prometheus()
    assert "xtb_phase_seconds_bucket" in prom
    assert "xtb_compiles_total" in prom
    assert 'phase="update.gradient"' in prom
    names = {json.loads(l)["name"] for l in open(path)}
    joined = "\n".join(names)
    for needle in ("build_hist", "eval_split", "update_tree", "eval."):
        assert needle in joined, f"{needle} missing from trace span names"


# ====================================================================
# serving rebase

def test_serving_metrics_feed_prometheus_registry():
    from xgboost_tpu.serving.metrics import ServingMetrics

    reg = telemetry.get_registry()
    req = reg.counter("xtb_serve_requests_total", "", ("model",))
    base = req.get("t_reg_model")
    m = ServingMetrics()
    m.observe_request("t_reg_model", rows=4, latency_ns=1_000_000)
    m.observe_batch("t_reg_model", rows=4, n_requests=1, exec_ns=2_000_000)
    m.observe_error("t_reg_model")
    m.queue_delta(16)
    m.queue_delta(-16)
    m.compiles_warmup += 2
    m.note_steady_compiles(1)
    snap = m.snapshot()
    assert snap["compiles_warmup"] == 2 and snap["compiles_steady"] == 1
    assert snap["models"]["t_reg_model"]["requests"] == 1
    assert req.get("t_reg_model") == base + 1
    prom = telemetry.render_prometheus()
    assert 'xtb_serve_rows_total{model="t_reg_model"} 4' in prom
    assert 'xtb_serve_errors_total{model="t_reg_model"} 1' in prom
    assert 'xtb_serve_batch_rows_bucket{model="t_reg_model",le="4"} 1' in prom
    assert 'xtb_compiles_steady{scope="serve"}' in prom


def test_trace_configure_truncates_previous_capture(tmp_path):
    """One capture = one process run: re-pointing the writer at a path must
    truncate, not append (perf_counter epochs differ across runs, so mixed
    captures render as garbage in chrome://tracing)."""
    path = str(tmp_path / "t.jsonl")
    _spans.enable()
    _trace.configure(path)
    _spans.record_phase("t_trunc.first", 0, 1000)
    _trace.configure(None)
    _trace.configure(path)  # a fresh capture at the same destination
    _spans.record_phase("t_trunc.second", 0, 1000)
    _trace.configure(None)
    names = [json.loads(l)["name"] for l in open(path)]
    assert names == ["t_trunc.second"]


def test_queue_gauge_sums_across_engines():
    """The process-wide queue gauge accumulates per-engine deltas: engine
    B going idle must not erase engine A's queued rows."""
    from xgboost_tpu.serving.metrics import ServingMetrics

    gauge = telemetry.get_registry().gauge("xtb_serve_queue_rows")
    base = gauge.get()
    a, b = ServingMetrics(), ServingMetrics()
    a.queue_delta(1000)
    b.queue_delta(5)
    b.queue_delta(-5)  # B drains: A's 1000 rows must stay visible
    assert gauge.get() == base + 1000
    a.queue_delta(-1000)
    assert gauge.get() == base


def test_serving_snapshot_shape_stable():
    """BENCH_SERVE.json contract: the snapshot dict shape survives the
    registry rebase bit-for-bit."""
    from xgboost_tpu.serving.metrics import ServingMetrics

    m = ServingMetrics()
    m.observe_request("m", rows=2, latency_ns=5_000_000)
    m.observe_batch("m", rows=2, n_requests=1, exec_ns=1_000_000)
    snap = m.snapshot()
    assert sorted(snap) == ["compiles_steady", "compiles_warmup", "models",
                            "queue_depth", "queue_peak"]
    assert sorted(snap["models"]["m"]) == [
        "batch_size_hist", "batches", "deadline", "errors", "latency_ms",
        "requests", "rows", "rows_per_s", "shed"]  # +degradation counters
    assert snap["models"]["m"]["shed"] == 0
    assert snap["models"]["m"]["deadline"] == 0
    assert sorted(snap["models"]["m"]["latency_ms"]) == ["p50", "p95", "p99"]


# ====================================================================
# EvaluationMonitor satellites

def test_evaluation_monitor_routes_through_logging(capsys):
    from xgboost_tpu.callback import EvaluationMonitor
    from xgboost_tpu.utils import logging as xlog

    lines = []
    xlog.register_log_callback(lines.append)
    try:
        mon = EvaluationMonitor()
        mon.after_iteration(None, 0, {"train": {"rmse": [0.5]}})
    finally:
        xlog.register_log_callback(None)
    assert lines == ["[0]\ttrain-rmse:0.50000"]
    assert capsys.readouterr().out == ""  # no bare print to stdout


def test_evaluation_monitor_show_stdv_and_tuple_scores():
    from xgboost_tpu.callback import EvaluationMonitor

    lines = []
    mon = EvaluationMonitor(show_stdv=True, logger=lines.append)
    mon.after_iteration(None, 0, {"test": {"rmse": [(0.5, 0.1)]}})
    assert lines == ["[0]\ttest-rmse:0.50000+0.10000"]
    lines.clear()
    mon = EvaluationMonitor(show_stdv=False, logger=lines.append)
    mon.after_iteration(None, 0, {"test": {"rmse": [(0.5, 0.1)]}})
    assert lines == ["[0]\ttest-rmse:0.50000"]


def test_evaluation_monitor_period_flushes_final_round():
    """period > 1 must still log the LAST round's scores (the reference
    caches the off-period line and flushes it in after_training)."""
    from xgboost_tpu.callback import EvaluationMonitor

    lines = []
    mon = EvaluationMonitor(period=5, logger=lines.append)
    for epoch in range(12):
        mon.after_iteration(None, epoch, {"t": {"rmse": [float(epoch)]}})
    mon.after_training(None)
    assert lines[-1] == "[11]\tt-rmse:11.00000"  # final round flushed
    assert [l.split("]")[0] + "]" for l in lines] == ["[0]", "[5]", "[10]",
                                                     "[11]"]


def test_evaluation_monitor_honours_rank():
    from xgboost_tpu.callback import EvaluationMonitor

    lines = []
    mon = EvaluationMonitor(rank=1, logger=lines.append)  # we are rank 0
    mon.after_iteration(None, 0, {"train": {"rmse": [0.5]}})
    assert lines == []


def test_cv_verbose_show_stdv_and_early_stopping():
    rng = np.random.default_rng(11)
    X = rng.normal(size=(240, 6)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    lines = []
    from xgboost_tpu.callback import EvaluationMonitor

    out = xtb.cv({"objective": "binary:logistic", "max_depth": 2}, d,
                 num_boost_round=4, nfold=3, as_pandas=False,
                 callbacks=[EvaluationMonitor(show_stdv=True,
                                              logger=lines.append)],
                 early_stopping_rounds=3)
    assert len(out["test-logloss-mean"]) >= 1
    assert lines and "+" in lines[0].split("\t")[1]  # mean+std rendered
