"""Survival objectives (reference: tests/python/test_survival.py,
tests/cpp/objective/test_aft_obj.cc)."""
import numpy as np
import pytest

import xgboost_tpu as xtb


@pytest.fixture(scope="module")
def surv_data():
    rng = np.random.default_rng(0)
    R = 600
    X = rng.normal(size=(R, 5)).astype(np.float32)
    t = np.exp(X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.normal(size=R)).astype(np.float32)
    cens = rng.random(R) < 0.3
    return X, t, cens


@pytest.mark.parametrize("dist", ["normal", "logistic", "extreme"])
def test_aft_improves_and_correlates(surv_data, dist):
    X, t, cens = surv_data
    lo = t.copy()
    hi = np.where(cens, np.inf, t).astype(np.float32)
    d = xtb.DMatrix(X, label=t, label_lower_bound=lo, label_upper_bound=hi)
    res = {}
    bst = xtb.train(
        {"objective": "survival:aft", "aft_loss_distribution": dist,
         "max_depth": 3, "eta": 0.3}, d, 15,
        evals=[(d, "t")], evals_result=res, verbose_eval=False,
    )
    nll = res["t"]["aft-nloglik"]
    assert np.isfinite(nll).all()
    assert nll[-1] < nll[0]
    p = bst.predict(d)
    assert np.corrcoef(np.log(p), np.log(t))[0, 1] > 0.85


def test_aft_interval_censored(surv_data):
    X, t, _ = surv_data
    # interval censoring: [0.8t, 1.3t]
    d = xtb.DMatrix(X, label=t, label_lower_bound=0.8 * t,
                    label_upper_bound=1.3 * t)
    res = {}
    xtb.train({"objective": "survival:aft", "eval_metric":
               "interval-regression-accuracy", "max_depth": 3}, d, 15,
              evals=[(d, "t")], evals_result=res, verbose_eval=False)
    acc = res["t"]["interval-regression-accuracy"]
    assert acc[-1] > 0.6
    assert acc[-1] > acc[0]


def test_cox_partial_likelihood(surv_data):
    X, t, cens = surv_data
    y = np.where(cens, -t, t).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    res = {}
    bst = xtb.train({"objective": "survival:cox", "max_depth": 3, "eta": 0.3},
                    d, 15, evals=[(d, "t")], evals_result=res, verbose_eval=False)
    nll = res["t"]["cox-nloglik"]
    assert np.isfinite(nll).all() and nll[-1] < nll[0]
    # higher survival time -> lower hazard
    hz = bst.predict(d)
    assert np.corrcoef(np.log(hz), np.log(t))[0, 1] < -0.5
