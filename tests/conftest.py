"""Test harness config: run JAX on a virtual 8-device CPU mesh
(SURVEY §4: single-process multi-device harness via
--xla_force_host_platform_device_count, mirroring the reference's in-process
multi-worker tests, tests/cpp/collective/test_worker.h:155).

The ambient environment registers the tunneled single TPU chip as platform
"axon" via sitecustomize (which imports jax at interpreter startup, freezing
JAX_PLATFORMS=axon into jax.config before this file runs).  Tests must never
touch the tunnel — initializing it can wedge the relay for the whole session —
so we force the platform through jax.config.update, which works post-import.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    # must be in the environment before the CPU backend initializes
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running multi-process tests")


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs


@pytest.fixture(scope="module", autouse=True)
def _bound_compile_cache():
    """Drop compiled executables between test modules.  A full-suite run
    accumulates hundreds of jitted level programs; XLA:CPU has been observed
    to segfault inside backend_compile_and_load near the end of the suite
    (whole-suite run 2026-07-29), and clearing per module bounds the live
    executable count at a small recompile cost."""
    yield
    jax.clear_caches()
