"""Test harness config: run JAX on a virtual 8-device CPU mesh
(SURVEY §4: single-process multi-device harness via
--xla_force_host_platform_device_count, mirroring the reference's in-process
multi-worker tests, tests/cpp/collective/test_worker.h:155).

The ambient environment registers the tunneled single TPU chip as platform
"axon" via sitecustomize (which imports jax at interpreter startup, freezing
JAX_PLATFORMS=axon into jax.config before this file runs).  Tests must never
touch the tunnel — initializing it can wedge the relay for the whole session —
so we force the platform through jax.config.update, which works post-import.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    # must be in the environment before the CPU backend initializes
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# arm the runtime lockdep witness for the whole suite (before the first
# xgboost_tpu import, so module-level locks are witnessed): every test
# doubles as a lock-order/seam-discipline probe, and the session fixture
# below asserts the suite produced zero reports.  Respect an explicit
# operator setting (e.g. XGBOOST_TPU_LOCKDEP=0 to profile witness cost).
os.environ.setdefault("XGBOOST_TPU_LOCKDEP", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running multi-process tests")
    config.addinivalue_line(
        "markers", "quick: fast smoke tier (`pytest -m quick` < 3 min) — "
        "the reference's marker-tier role (SURVEY §4); full suite nightly")


# Fast smoke tier: files whose tests are individually cheap, minus members
# measured slow (> ~8 s single-core).  Keep `pytest -m quick` under 3 min:
# it is the per-commit gate; the full suite is the nightly/per-milestone one.
_QUICK_FILES = {
    "test_basic.py", "test_model_io.py", "test_boosters.py",
    "test_bestfirst.py", "test_exact.py", "test_grower_parity.py",
    "test_collective_backend.py", "test_constraints.py",
    "test_continuation.py", "test_device_ingest.py", "test_hist_kernels.py",
    "test_multiquantile.py", "test_ranking.py", "test_survival.py",
    "test_categorical.py", "test_shap.py", "test_golden_models.py",
    "test_serving.py", "test_arrow.py", "test_telemetry.py",
    "test_timer_observer.py", "test_reliability.py",
    "test_serving_faults.py", "test_reliability_multiprocess.py",
    "test_analysis.py", "test_native_threads.py", "test_elastic.py",
    "test_lifecycle.py", "test_updaters_process.py", "test_extmem.py",
    "test_integrity.py", "test_chaos.py", "test_watchdog.py",
    "test_failover.py", "test_resources.py", "test_window_store.py",
    "test_online.py", "test_profiler.py", "test_lockdep.py",
}
_QUICK_DENY = {
    # measured > ~8 s (full-suite --durations)
    "test_streamed_sparse_predict_bounded_memory", "test_pandas_input",
    "test_base_margin_and_weights", "test_max_leaves_budget",
    "test_monotone_increasing_decreasing", "test_quantile_objective_coverage",
    "test_interaction_constraints_respected", "test_num_parallel_tree_forest",
    "test_bestfirst_matches_depthwise_on_balanced_data",
    "test_lossguide_distributed_global_bestfirst",
    "test_exact_close_to_hist", "test_exact_two_process_matches_single",
    "test_onehot_vs_partition_regimes", "test_categorical_training_improves",
    "test_category_recode_between_frames", "test_unseen_category_goes_left",
    "test_device_shap_throughput", "test_device_shap_matches_host",
    "test_jax_array_input_matches_numpy", "test_subtraction_trick_same_trees",
    "test_single_quantile_still_scalar", "test_multi_quantile_training",
    "test_multi_expectile_training", "test_rank_objectives_improve",
    "test_aft_improves_and_correlates", "test_inmemory_thread_workers_identical_trees",
    "test_feature_weights_bias_column_sampling",
    "test_config_roundtrip_continuation", "test_iteration_range_and_slice",
    "test_aft_interval_censored", "test_custom_objective",
    "test_categorical_save_load_exact", "test_torch_dlpack_input",
    "test_continuation_identity_same_booster",
    "test_bestfirst_budget_and_quality", "test_gradient_based_sampling",
    "test_deterministic_across_runs", "test_adaptive_leaf_mae",
    "test_rank_requires_groups", "test_dart_trains_and_roundtrips",
    "test_exact_oracle_parity", "test_continuation_identity_after_reload",
    "test_ranker_sklearn_with_eval", "test_dart_weighted_sampling",
    "test_categorical_nan_uses_default_direction",
    "test_cox_partial_likelihood",
    "test_inmemory_elastic_shrink_finishes_at_reduced_world",
    "test_two_process_elastic_shrink_to_single_worker",
    "test_manager_continuation_resumes_from_checkpoint",
    "test_lifecycle_end_to_end_fleet",
    "test_online_closed_loop_end_to_end",
    "test_chaos_online_episode_green_and_deterministic",
    "test_extmem_matches_incore", "test_extmem_multidevice_matches_single",
    "test_sparse_page_dmatrix_raw_predict_and_training",
    "test_sparse_page_dmatrix_scipy_batches_and_sentinel",
    "test_tracker_sigkill_mid_round_bitwise_parity",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        fname = os.path.basename(str(item.fspath))
        base = item.name.split("[")[0]
        if fname in _QUICK_FILES and base not in _QUICK_DENY:
            item.add_marker(pytest.mark.quick)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """A silently-skipping oracle must be LOUD (VERDICT r3 weak #2): every
    'oracle-verified' parity claim is unverifiable while the oracle binary
    is missing, so say so in the suite summary, unmissably."""
    from xgboost_tpu.testing import HAVE_ORACLE, ORACLE_PKG

    if not HAVE_ORACLE:
        terminalreporter.write_sep(
            "=", "ORACLE MISSING — parity UNVERIFIED", red=True, bold=True)
        terminalreporter.write_line(
            f"The reference-xgboost oracle is not built ({ORACLE_PKG}); every "
            "test_oracle_parity/test_exact oracle check SKIPPED.\n"
            "Rebuild with: bash oracle/build_oracle.sh   (~40 min, durable "
            "under /root/oracle_build)")


@pytest.fixture(scope="session", autouse=True)
def _lockdep_clean_session():
    """The whole suite must leave the lockdep witness silent: any
    lock-order inversion or lock-held-across-seam report from real test
    traffic is a concurrency bug, not noise.  Tests that provoke reports
    deliberately (test_lockdep.py) clear them before returning."""
    yield
    from xgboost_tpu.reliability import lockdep

    if lockdep.enabled():
        rs = lockdep.reports()
        assert not rs, "lockdep witness reports leaked from the suite: " \
            + "; ".join(f"[{r['kind']}] {r['msg']}" for r in rs)


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs


@pytest.fixture(scope="module", autouse=True)
def _bound_compile_cache():
    """Drop compiled executables between test modules.  A full-suite run
    accumulates hundreds of jitted level programs; XLA:CPU has been observed
    to segfault inside backend_compile_and_load near the end of the suite
    (whole-suite run 2026-07-29), and clearing per module bounds the live
    executable count at a small recompile cost."""
    yield
    jax.clear_caches()
