"""Resource-exhaustion robustness (docs/reliability.md "Resource pressure
& graceful degradation"): the governor's levels and ladders, the
resource-class fault kinds, checkpoint prune-retry-skip under ENOSPC with
bitwise model parity, journal forced compaction, clean publish aborts,
the extmem cache/prefetch ladder, and the fleet's AIMD admission +
SLO brownout — degradation changes how hard the machine works, never the
math.
"""
import errno
import json
import os
import warnings

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.reliability import faults, resources
from xgboost_tpu.reliability.checkpoint import (CheckpointCallback,
                                                CheckpointManager,
                                                latest_checkpoint)
from xgboost_tpu.reliability.journal import TrackerJournal
from xgboost_tpu.telemetry.registry import get_registry


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    resources.reset()
    yield
    faults.clear()
    resources.reset()


def _counter(name, *labels):
    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    for values, child in fam.collect():
        if values == tuple(str(x) for x in labels):
            return float(child.value)
    return 0.0


def _train_data(n=1500, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    return X, y


_PARAMS = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
           "max_bin": 32}


# ---------------------------------------------------------------------------
# governor
# ---------------------------------------------------------------------------

def test_governor_levels_ladders_and_reset():
    g = resources.get_governor()
    assert g.max_level() == 0 and not g.degraded()
    assert g.memory_scale() == 1.0 and g.prefetch_allowed()
    assert g.brownout_cutoff() is None
    assert g.degrade("memory", "test") == 1
    assert g.memory_scale() == 0.25 and not g.prefetch_allowed()
    assert g.brownout_cutoff() == 0
    assert g.degrade("memory", "test") == 2
    assert g.memory_scale() == 0.0
    assert g.brownout_cutoff() == 1
    # capped at MAX_LEVEL
    g.degrade("memory", "t")
    assert g.degrade("memory", "t") == resources.MAX_LEVEL
    assert g.restore("memory") == resources.MAX_LEVEL - 1
    resources.reset()
    assert g.max_level() == 0 and g.memory_scale() == 1.0


def test_note_os_error_classifies_and_degrades():
    g = resources.get_governor()
    before = _counter("xtb_resource_errors_total", "ENOSPC", "t.site")
    kind = resources.note_os_error(OSError(errno.ENOSPC, "full"), "t.site")
    assert kind == "ENOSPC"
    assert kind in resources.DISK_ERRNOS
    assert _counter("xtb_resource_errors_total", "ENOSPC",
                    "t.site") == before + 1
    assert g.level("disk") == 1
    assert resources.note_os_error(OSError(errno.EMFILE, "fds"),
                                   "t.site") == "EMFILE"
    assert g.level("fd") == 1
    # non-resource errno: classified, degrades nothing
    assert resources.note_os_error(OSError(errno.EACCES, "perm"),
                                   "t.site") == "EACCES"
    assert g.level("disk") == 1 and g.level("fd") == 1
    assert resources.note_os_error(ValueError("no errno"),
                                   "t.site") == "EUNKNOWN"


def test_real_headroom_poll_with_hysteresis(tmp_path, monkeypatch):
    monkeypatch.setenv("XGBOOST_TPU_RESOURCE_POLL_S", "0")
    g = resources.get_governor()
    # absurd floor: any real filesystem is "below" it -> degrade once
    monkeypatch.setenv("XGBOOST_TPU_DISK_MIN_MB", str(1 << 30))
    g.poll(str(tmp_path))
    assert g.level("disk") == 1
    g.poll(str(tmp_path))
    assert g.level("disk") == 1  # steady state: no re-degrade
    # floor back to sane: free >= 2x floor -> restore on the transition
    monkeypatch.setenv("XGBOOST_TPU_DISK_MIN_MB", "0.001")
    out = g.poll(str(tmp_path))
    assert g.level("disk") == 0
    assert out.get("disk_free_bytes", 0) > 0


def test_hysteresis_gradual_recovery_still_restores(monkeypatch):
    """The latch must survive the [floor, 2*floor) gray zone: a dip
    followed by a GRADUAL recovery restores once headroom reaches 2x the
    floor — not only on a single-poll jump (review regression)."""
    g = resources.get_governor()
    g._hysteresis("disk", free=50.0, floor=64.0)
    assert g.level("disk") == 1
    g._hysteresis("disk", free=100.0, floor=64.0)   # gray zone
    assert g.level("disk") == 1
    g._hysteresis("disk", free=100.0, floor=64.0)   # still gray: no churn
    assert g.level("disk") == 1
    g._hysteresis("disk", free=200.0, floor=64.0)   # healthy: restore
    assert g.level("disk") == 0


def test_errno_raised_level_restored_by_healthy_headroom():
    """A level raised by note_os_error (no latch involved) must walk
    back down once measured headroom is healthy — without this, one
    transient ENOSPC brownouts low-SLO tenants for the process lifetime
    (review regression)."""
    g = resources.get_governor()
    resources.note_os_error(OSError(errno.ENOSPC, "blip"), "t.site")
    assert g.level("disk") == 1
    g._hysteresis("disk", free=1e12, floor=64.0)
    assert g.level("disk") == 0


def test_is_resource_errno_classification():
    assert resources.is_resource_errno(OSError(errno.ENOSPC, "x"))
    assert resources.is_resource_errno(OSError(errno.EMFILE, "x"))
    assert not resources.is_resource_errno(OSError(errno.EACCES, "x"))
    assert not resources.is_resource_errno(ValueError("no errno"))


def test_pressure_seam_drives_governor_deterministically():
    faults.install({"faults": [
        {"site": "resource.pressure", "kind": "mem_pressure", "at": 0},
        {"site": "resource.pressure", "kind": "fd_exhaust", "at": 1},
    ]})
    g = resources.get_governor()
    g.poll()
    assert g.level("memory") == 1 and g.level("fd") == 0
    g.poll()  # fd_exhaust raises EMFILE into the classifier
    assert g.level("fd") == 1
    g.poll()  # plan exhausted: no further transitions
    assert g.level("memory") == 1 and g.level("fd") == 1


# ---------------------------------------------------------------------------
# resource fault kinds
# ---------------------------------------------------------------------------

def test_disk_full_and_fd_exhaust_kinds_raise_matching_errno():
    faults.install({"faults": [
        {"site": "checkpoint.write", "kind": "disk_full"},
        {"site": "serve.worker", "kind": "fd_exhaust"},
    ]})
    with pytest.raises(OSError) as ei:
        faults.maybe_inject("checkpoint.write")
    assert ei.value.errno == errno.ENOSPC
    with pytest.raises(OSError) as ei:
        faults.maybe_inject("serve.worker")
    assert ei.value.errno == errno.EMFILE


def test_slow_disk_kind_sleeps_and_returns_spec():
    import time

    faults.install({"faults": [
        {"site": "extmem.page_load", "kind": "slow_disk", "seconds": 0.05},
    ]})
    t0 = time.perf_counter()
    spec = faults.maybe_inject("extmem.page_load")
    assert spec is not None and spec.kind == "slow_disk"
    assert time.perf_counter() - t0 >= 0.045


# ---------------------------------------------------------------------------
# checkpoint ladder (satellite: keep-last-K pruning under disk_full)
# ---------------------------------------------------------------------------

def test_checkpoint_prune_keep_overrides_keep_last(tmp_path):
    from xgboost_tpu.reliability.checkpoint import CheckpointState

    mgr = CheckpointManager(str(tmp_path), keep_last=5)
    for r in range(1, 5):
        mgr.save(CheckpointState(round=r, booster_bytes=b"x" * 64,
                                 history={}, callback_state={}))
    assert len(mgr.files()) == 4
    mgr.prune(keep=1)
    files = mgr.files()
    assert len(files) == 1 and files[0].endswith("ckpt_00000004.xtbckpt")


def test_disk_full_once_heals_on_pruned_retry(tmp_path):
    """times=1: the first commit attempt hits ENOSPC, the ladder prunes
    to the newest snapshot and the retry lands — the round IS
    checkpointed, one degraded step counted."""
    X, y = _train_data()
    faults.install({"faults": [{"site": "checkpoint.write",
                                "kind": "disk_full", "round": 4}]})
    before = _counter("xtb_resource_degraded_total", "checkpoint")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        cb = CheckpointCallback(str(tmp_path), interval=2)
        xtb.train(dict(_PARAMS), xtb.DMatrix(X, label=y), 6,
                  callbacks=[cb], verbose_eval=False)
    assert cb.skipped_rounds == []
    assert cb.last_saved_round == 6
    st = latest_checkpoint(str(tmp_path))
    assert st is not None and st.round == 6
    assert _counter("xtb_resource_degraded_total",
                    "checkpoint") == before + 1


def test_disk_full_persistent_skips_snapshot_and_training_continues(
        tmp_path):
    X, y = _train_data()
    faults.install({"faults": [{"site": "checkpoint.write",
                                "kind": "disk_full", "round": 4,
                                "times": 2}]})
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        cb = CheckpointCallback(str(tmp_path), interval=2)
        bst = xtb.train(dict(_PARAMS), xtb.DMatrix(X, label=y), 6,
                        callbacks=[cb], verbose_eval=False)
    assert cb.skipped_rounds == [4]
    assert bst.num_boosted_rounds() == 6  # the run finished
    # the loud-warning contract
    assert any("degraded" in str(w.message) for w in caught)
    # rounds 2 and 6 still committed; prune-to-1 dropped round 2 so the
    # newest valid snapshot is round 6
    st = latest_checkpoint(str(tmp_path))
    assert st is not None and st.round == 6


def test_non_disk_oserror_still_raises(tmp_path):
    from xgboost_tpu.reliability.checkpoint import CheckpointState

    cb = CheckpointCallback(str(tmp_path), interval=1)

    class _Mgr(CheckpointManager):
        def save(self, state):
            raise OSError(errno.EACCES, "permission denied")

    cb.manager = _Mgr(str(tmp_path))
    with pytest.raises(OSError):
        cb._save_degradable(CheckpointState(
            round=1, booster_bytes=b"x", history={}, callback_state={}))


def test_mid_run_disk_full_bitwise_parity_and_flight_event(tmp_path):
    """THE acceptance case: a training run with a mid-run disk_full on
    the checkpoint directory completes with bitwise-identical model
    bytes to a fault-free twin, emits
    xtb_resource_degraded_total{subsystem="checkpoint"} >= 1 and a
    flight-recorder degradation event."""
    from xgboost_tpu.telemetry import flight

    X, y = _train_data()
    twin = xtb.train(dict(_PARAMS), xtb.DMatrix(X, label=y), 8,
                     verbose_eval=False)
    before = _counter("xtb_resource_degraded_total", "checkpoint")
    faults.install({"faults": [{"site": "checkpoint.write",
                                "kind": "disk_full", "round": 4,
                                "times": 2}]})
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        bst = xtb.train(dict(_PARAMS), xtb.DMatrix(X, label=y), 8,
                        callbacks=[CheckpointCallback(str(tmp_path),
                                                      interval=2)],
                        verbose_eval=False)
    assert bytes(bst.save_raw()) == bytes(twin.save_raw())
    assert _counter("xtb_resource_degraded_total",
                    "checkpoint") >= before + 1
    names = [e.get("name") for e in flight.events()]
    assert "resource.degraded" in names


def test_resume_after_degraded_run_bitwise_parity(tmp_path):
    """Resume-after-degradation: a run whose round-4 snapshot was lost to
    ENOSPC resumes from what DID commit and lands on the same bytes as
    an uninterrupted run."""
    X, y = _train_data()
    twin = xtb.train(dict(_PARAMS), xtb.DMatrix(X, label=y), 8,
                     verbose_eval=False)
    # leg 1: train 5 rounds; the round-4 snapshot is skipped (ENOSPC on
    # commit and on the pruned retry), so the newest snapshot is round 2
    faults.install({"faults": [{"site": "checkpoint.write",
                                "kind": "disk_full", "round": 4,
                                "times": 2}]})
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        xtb.train(dict(_PARAMS), xtb.DMatrix(X, label=y), 5,
                  callbacks=[CheckpointCallback(str(tmp_path), interval=2,
                                                keep_last=1)],
                  verbose_eval=False)
    faults.clear()
    st = latest_checkpoint(str(tmp_path))
    assert st is not None and st.round == 2  # the degradation gap is real
    # leg 2: resume to the full 8 rounds from the surviving snapshot
    bst = xtb.train(dict(_PARAMS), xtb.DMatrix(X, label=y), 8,
                    resume_from=str(tmp_path), verbose_eval=False)
    assert bytes(bst.save_raw()) == bytes(twin.save_raw())


# ---------------------------------------------------------------------------
# journal ladder (satellite: forced compaction under disk_full)
# ---------------------------------------------------------------------------

def test_journal_disk_full_forces_compaction_then_retries(tmp_path):
    path = str(tmp_path / "j.jrnl")
    j = TrackerJournal(path)
    for i in range(6):
        j.append({"epoch": i, "world": 2})
    grown = os.path.getsize(path)
    before = _counter("xtb_resource_degraded_total", "journal")
    faults.install({"faults": [{"site": "tracker.journal",
                                "kind": "disk_full"}]})
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        j.append({"epoch": 6, "world": 2})
    faults.clear()
    # the ladder compacted (single-record file is smaller than the grown
    # one even with the retried record appended after it) and the retry
    # committed the record
    assert os.path.getsize(path) < grown
    assert j.load() == {"epoch": 6, "world": 2}
    assert _counter("xtb_resource_degraded_total",
                    "journal") == before + 1


def test_journal_disk_full_persistent_skips_record_keeps_running(tmp_path):
    path = str(tmp_path / "j.jrnl")
    j = TrackerJournal(path)
    j.append({"epoch": 0, "world": 2})
    faults.install({"faults": [{"site": "tracker.journal",
                                "kind": "disk_full", "times": 2}]})
    before = _counter("xtb_resource_degraded_total", "journal")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        j.append({"epoch": 1, "world": 2})  # must NOT raise
    faults.clear()
    # both append attempts failed, but the forced compaction committed
    # the NEW state atomically on its own path — the transition survives
    # ENOSPC on the append framing entirely
    assert j.load() == {"epoch": 1, "world": 2}
    assert _counter("xtb_resource_degraded_total",
                    "journal") == before + 2  # compaction + append skip
    # and the journal still works once pressure clears
    j.append({"epoch": 2, "world": 2})
    assert j.load() == {"epoch": 2, "world": 2}


# ---------------------------------------------------------------------------
# model store / lifecycle
# ---------------------------------------------------------------------------

def test_publish_disk_full_aborts_cleanly_no_torn_files(tmp_path):
    from xgboost_tpu.serving.modelstore import ModelStore

    X, y = _train_data(400)
    bst = xtb.train(dict(_PARAMS), xtb.DMatrix(X, label=y), 3,
                    verbose_eval=False)
    store = ModelStore(str(tmp_path / "store"))
    v1 = store.publish("m", bst)
    listing = sorted(os.listdir(store.dir))
    faults.install({"faults": [{"site": "modelstore.publish",
                                "kind": "disk_full"}]})
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with pytest.raises(OSError):
            store.publish("m", bst)
    faults.clear()
    # no tmp litter, no torn version files, manifest untouched
    assert sorted(os.listdir(store.dir)) == listing
    assert store.latest_version("m") == v1
    assert store.scrub() == {"verified": [("m", v1)], "corrupt": []}


def test_lifecycle_cycle_rejects_with_reason_resource(tmp_path):
    """A publish-time ENOSPC fails the cycle CLEANLY: reason="resource",
    incumbent untouched (stub fleet, no processes)."""
    from xgboost_tpu.lifecycle import (GateConfig, LifecycleConfig,
                                       LifecycleManager)
    from xgboost_tpu.serving.modelstore import ModelStore

    X, y = _train_data(400)
    bst = xtb.train(dict(_PARAMS), xtb.DMatrix(X, label=y), 3,
                    verbose_eval=False)
    store = ModelStore(str(tmp_path / "store"))
    store.publish("m", bst)
    store.set_active("m", 1)

    class _StubFleet:
        store_dir = store.dir

        def active_version(self, name):
            return store.active_version(name)

        def load_version(self, *a, **k):
            return [{}]

        def activate_version(self, model, version, **k):
            store.set_active(model, version)
            return [{}]

        def retire_version(self, *a, **k):
            return [{}]

    mgr = LifecycleManager(_StubFleet(), "m", config=LifecycleConfig(
        rounds_per_cycle=1, gate=GateConfig(min_improvement=-1e9)))
    faults.install({"faults": [{"site": "modelstore.publish",
                                "kind": "disk_full"}]})
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        report = mgr.run_cycle((X, y))
    faults.clear()
    assert not report.swapped
    assert report.decision.reason == "resource"
    assert store.active_version("m") == 1  # incumbent untouched
    assert store.latest_version("m") == 1  # nothing half-published
    # a NON-exhaustion OSError is a bug and must raise, not masquerade
    # as transient pressure (review regression)
    faults.clear()
    resources.reset()
    real_publish = type(store).publish

    def _eacces_publish(self, *a, **k):
        raise OSError(errno.EACCES, "misconfigured store dir")

    type(store).publish = _eacces_publish
    try:
        with pytest.raises(OSError):
            mgr.run_cycle((X, y))
    finally:
        type(store).publish = real_publish


# ---------------------------------------------------------------------------
# extmem ladder
# ---------------------------------------------------------------------------

def test_extmem_ladder_prefetch_and_cache_budget(monkeypatch):
    from xgboost_tpu.data import extmem

    monkeypatch.setenv("XTB_EXTMEM_PREFETCH_PAGES", "3")
    monkeypatch.setenv("XTB_EXTMEM_HOST_CACHE_MB", "100")
    assert extmem.prefetch_lookahead() == 3
    assert extmem._host_cache_budget() == int(100 * 2**20)
    g = resources.get_governor()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        g.degrade("memory", "test")
        assert extmem.prefetch_lookahead() == 0
        assert extmem._host_cache_budget() == int(100 * 2**20 * 0.25)
        g.degrade("memory", "test")
        assert extmem._host_cache_budget() == 0  # recompute every touch
        resources.reset()
        assert extmem.prefetch_lookahead() == 3
        # fd pressure alone also parks the prefetch window
        g.degrade("fd", "test")
        assert extmem.prefetch_lookahead() == 0
    assert _counter("xtb_resource_degraded_total", "extmem") >= 2


def test_extmem_training_bitwise_under_memory_pressure(tmp_path):
    """Cache disabled + prefetch off must not change one model bit —
    the ladder changes how hard the machine works, never the math."""
    Xs = [c.astype(np.float32) for c in
          np.array_split(np.random.default_rng(3).normal(
              size=(1200, 6)), 3)]
    ys = [(x[:, 0] > 0).astype(np.float32) for x in Xs]

    class _It(xtb.DataIter):
        def __init__(self):
            super().__init__()
            self.i = 0

        def next(self, input_data):
            if self.i >= len(Xs):
                return 0
            input_data(data=Xs[self.i], label=ys[self.i])
            self.i += 1
            return 1

        def reset(self):
            self.i = 0

    def run():
        d = xtb.ExtMemQuantileDMatrix(_It(), max_bin=32)
        return bytes(xtb.train(dict(_PARAMS), d, 4,
                               verbose_eval=False).save_raw())

    clean = run()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        resources.get_governor().degrade("memory", "test")
        resources.get_governor().degrade("memory", "test")
        degraded = run()
    assert degraded == clean


# ---------------------------------------------------------------------------
# fleet: AIMD admission + brownout (pure units; E2E rides test_fleet)
# ---------------------------------------------------------------------------

def test_adaptive_admission_aimd_window():
    from xgboost_tpu.serving.fleet import AdaptiveAdmission

    a = AdaptiveAdmission(1024)
    assert a.limit() == 1024 and a.floor == 8
    assert a.on_pressure() is False  # 512: nowhere near the floor
    assert a.limit() == 512
    edges = [a.on_pressure() for _ in range(10)]
    assert a.limit() == 8
    assert edges.count(True) == 1  # exactly one onto-the-floor edge
    # additive recovery: one completion = +1
    assert a.on_ok() is False and a.limit() == 9
    for _ in range(1024):
        recovered = a.on_ok()
    assert a.limit() == 1024
    assert recovered is False  # the recovered edge fired once, earlier
    # edge fires once per excursion
    for _ in range(20):
        a.on_pressure()
    assert sum(a.on_pressure() for _ in range(3)) == 0


def test_adaptive_admission_small_queues_never_couple():
    from xgboost_tpu.serving.fleet import AdaptiveAdmission

    a = AdaptiveAdmission(4)  # floor clamps to the ceiling
    assert not a.coupled
    assert all(not a.on_pressure() for _ in range(10))
    assert a.limit() == 4  # toy queues keep their full bound
    # 9..31: the window works but governor coupling stays off — the
    # floor edge and the ceiling/2 recovery edge would be one
    # completion apart, flapping the overload level per request
    b = AdaptiveAdmission(16)
    assert not b.coupled
    assert all(not b.on_pressure() for _ in range(10))
    assert not b.on_ok()  # no recovered edge either: never floored-out
    c = AdaptiveAdmission(32)
    assert c.coupled  # first size where the edges are a doubling apart


def test_adaptive_admission_edges_are_a_doubling_apart():
    """On a coupled queue, recovering from the floor takes >= floor
    completions (8 -> 16 on max_queue=32), so overload cannot flap
    per-request under sustained saturation (review regression)."""
    from xgboost_tpu.serving.fleet import AdaptiveAdmission

    a = AdaptiveAdmission(32)
    edges = sum(a.on_pressure() for _ in range(10))
    assert edges == 1 and a.limit() == 8
    oks = [a.on_ok() for _ in range(8)]
    assert oks[:-1] == [False] * 7 and oks[-1] is True  # 8 -> 16: edge
    assert a.limit() == 16


def test_dispatch_queue_honors_admission_limit():
    from xgboost_tpu.serving.fleet import (DispatchQueue, SLOClass,
                                           _Request)

    q = DispatchQueue(max_queue=100)
    slo = SLOClass("t", priority=0)
    reqs = [_Request(i, "m", {}, b"", slo) for i in range(5)]
    assert q.push(reqs[0], limit=2) is None
    assert q.push(reqs[1], limit=2) is None
    victim = q.push(reqs[2], limit=2)  # window full: equal prio sheds self
    assert victim is reqs[2]
    assert q.push(reqs[3], limit=4) is None  # window re-opened


def test_brownout_cutoff_sheds_low_slo_first():
    g = resources.get_governor()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        assert g.brownout_cutoff() is None
        g.degrade("overload", "test")
        # level 1: below-default tenants shed, default (0) and up served
        assert g.brownout_cutoff() == 0
        assert not (-1 >= g.brownout_cutoff())
        g.degrade("overload", "test")
        # level 2: the default class sheds too; priority >= 1 serves
        assert g.brownout_cutoff() == 1
        g.degrade("disk", "test")  # the WORST resource drives the cutoff
        assert g.brownout_cutoff() == 1


def test_fleet_submit_brownout_path_without_processes():
    """submit()'s brownout admission check, driven directly on an
    unstarted fleet object (no replicas needed: the shed happens before
    any queue/socket work)."""
    from xgboost_tpu.serving.batcher import QueueFullError
    from xgboost_tpu.serving.fleet import FleetConfig, ServingFleet, SLOClass

    cfg = FleetConfig(n_replicas=1, slo_classes={
        "free": SLOClass("free", priority=-1),
        "gold": SLOClass("gold", priority=2)})
    fleet = ServingFleet({}, cfg)
    fleet._started = True  # bypass start() (no processes in this test)
    before = _counter("xtb_fleet_brownout_total", "free")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        resources.get_governor().degrade("overload", "test")
    fut = fleet.submit("m", np.zeros((1, 2), np.float32), tenant="free")
    with pytest.raises(QueueFullError, match="browned out"):
        fut.result(timeout=1)
    assert _counter("xtb_fleet_brownout_total", "free") == before + 1
    # a gold request passes admission (it queues; nothing serves it here)
    fut2 = fleet.submit("m", np.zeros((1, 2), np.float32), tenant="gold")
    assert not fut2.done()
    resources.reset()


# ---------------------------------------------------------------------------
# chaos: the resource scenario in the quick tier (>= 2 episodes + replay)
# ---------------------------------------------------------------------------

def test_resource_chaos_two_episodes_with_seeded_replay():
    from xgboost_tpu.reliability import chaos

    first = chaos.run_episode("resource", 11)
    assert first.ok, (first.invariants, first.error)
    second = chaos.run_episode("resource", 12)
    assert second.ok, (second.invariants, second.error)
    replay = chaos.run_episode("resource", 11)
    assert replay.plan == first.plan
    assert replay.artifacts.get("digest") == first.artifacts.get("digest")
    assert replay.ok


def test_resource_scenario_is_in_the_soak_rotation():
    from xgboost_tpu.reliability import chaos

    assert "resource" in chaos.SCENARIOS
    sc = chaos.SCENARIOS["resource"]
    kinds = {(e.site, e.kind) for e in sc.catalog}
    assert ("checkpoint.write", "disk_full") in kinds
    assert ("resource.pressure", "mem_pressure") in kinds
    assert sc.twin  # bitwise-vs-twin is the heart of the contract


# ---------------------------------------------------------------------------
# xtblint XTB801
# ---------------------------------------------------------------------------

def _lint(src, filename):
    from xgboost_tpu.analysis.core import lint_source

    return [f.code for f in lint_source(src, filename).findings
            if f.code.startswith("XTB8")]


def test_xtb801_flags_silent_swallow_in_scope():
    src = ("import os\n"
           "def f(p):\n"
           "    try:\n"
           "        os.unlink(p)\n"
           "    except OSError:\n"
           "        pass\n")
    assert _lint(src, "xgboost_tpu/reliability/x.py") == ["XTB801"]
    assert _lint(src, "xgboost_tpu/serving/x.py") == ["XTB801"]
    assert _lint(src, "xgboost_tpu/data/x.py") == ["XTB801"]
    # out of scope: telemetry etc. are not resource-critical modules
    assert _lint(src, "xgboost_tpu/telemetry/x.py") == []


def test_xtb801_accepts_the_four_compliant_shapes():
    route = ("import os\n"
             "from xgboost_tpu.reliability import resources\n"
             "def f(p):\n"
             "    try:\n"
             "        os.unlink(p)\n"
             "    except OSError as e:\n"
             "        resources.note_os_error(e, 's')\n")
    reraise = ("import os\n"
               "def f(p):\n"
               "    try:\n"
               "        os.unlink(p)\n"
               "    except OSError:\n"
               "        raise RuntimeError('x')\n")
    counts = ("import os\n"
              "def f(p, c):\n"
              "    try:\n"
              "        os.unlink(p)\n"
              "    except OSError:\n"
              "        c.labels('x').inc()\n")
    surfaces = ("import os, warnings\n"
                "def f(p):\n"
                "    try:\n"
                "        os.unlink(p)\n"
                "    except OSError as e:\n"
                "        warnings.warn(f'gone: {e}')\n")
    narrow = ("import os\n"
              "def f(p):\n"
              "    try:\n"
              "        os.unlink(p)\n"
              "    except FileNotFoundError:\n"
              "        pass\n")
    for src in (route, reraise, counts, surfaces, narrow):
        assert _lint(src, "xgboost_tpu/reliability/x.py") == [], src


def test_xtb801_tuple_catch_and_unused_binding_still_flagged():
    tup = ("import os\n"
           "def f(p):\n"
           "    try:\n"
           "        os.unlink(p)\n"
           "    except (ValueError, OSError):\n"
           "        return None\n")
    bound_unused = ("import os\n"
                    "def f(p):\n"
                    "    try:\n"
                    "        os.unlink(p)\n"
                    "    except OSError as e:\n"
                    "        print('oops')\n")
    assert _lint(tup, "xgboost_tpu/data/x.py") == ["XTB801"]
    assert _lint(bound_unused, "xgboost_tpu/data/x.py") == ["XTB801"]


def test_repo_is_xtb801_clean():
    from xgboost_tpu.analysis.core import run_lint

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = run_lint([os.path.join(root, "xgboost_tpu")],
                   select=["XTB801"])
    assert res.findings == [], [f.render() for f in res.findings]
    assert res.suppressed == []  # zero suppressions, per the satellite
