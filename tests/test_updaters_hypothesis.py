"""Property-based updater sweep (reference pattern:
tests/python-gpu/test_gpu_updaters.py:29-117 — hypothesis strategies over
training params x dataset shapes, asserting training sanity everywhere)."""
import numpy as np
import pytest

# environment-limited: without the hypothesis package this file was a
# tier-1 collection ERROR; skip cleanly instead
pytest.importorskip("hypothesis",
                    reason="hypothesis not installed in this container")
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

import xgboost_tpu as xtb

_params = st.fixed_dictionaries({
    "max_depth": st.integers(1, 5),
    "max_bin": st.sampled_from([4, 16, 64]),
    "eta": st.floats(0.05, 1.0),
    "lambda": st.floats(0.0, 5.0),
    "alpha": st.floats(0.0, 2.0),
    "gamma": st.floats(0.0, 2.0),
    "min_child_weight": st.floats(0.0, 5.0),
    "subsample": st.floats(0.5, 1.0),
    "colsample_bytree": st.floats(0.5, 1.0),
    "max_leaves": st.sampled_from([0, 4, 16]),
    "grow_policy": st.sampled_from(["depthwise", "lossguide"]),
})


def _dataset(seed: int, n: int = 300, f: int = 6, sparsity: float = 0.0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    if sparsity:
        X[rng.random((n, f)) < sparsity] = np.nan
    y = (np.nan_to_num(X[:, 0]) - np.nan_to_num(X[:, 1]) +
         0.2 * rng.normal(size=n)).astype(np.float32)
    return X, y


@settings(deadline=None, max_examples=12,
          suppress_health_check=[HealthCheck.too_slow])
@given(params=_params, seed=st.integers(0, 3),
       sparsity=st.sampled_from([0.0, 0.3]))
def test_hist_updater_param_sweep(params, seed, sparsity):
    X, y = _dataset(seed, sparsity=sparsity)
    d = xtb.DMatrix(X, label=y)
    res = {}
    bst = xtb.train({**params, "objective": "reg:squarederror"}, d, 8,
                    evals=[(d, "t")], evals_result=res, verbose_eval=False)
    rmse = res["t"]["rmse"]
    assert np.isfinite(rmse).all()
    # training must never diverge, and with a full-signal config must improve
    assert rmse[-1] <= rmse[0] * 1.05
    p = bst.predict(d)
    assert np.isfinite(p).all()
    for t in bst.trees:
        if params["max_leaves"]:
            assert t.num_leaves <= params["max_leaves"]
        assert t.max_depth <= max(params["max_depth"], 1)


@settings(deadline=None, max_examples=6,
          suppress_health_check=[HealthCheck.too_slow])
@given(params=_params, seed=st.integers(0, 2))
def test_binary_objective_sweep(params, seed):
    X, y = _dataset(seed)
    yb = (y > 0).astype(np.float32)
    d = xtb.DMatrix(X, label=yb)
    res = {}
    xtb.train({**params, "objective": "binary:logistic"}, d, 8,
              evals=[(d, "t")], evals_result=res, verbose_eval=False)
    ll = res["t"]["logloss"]
    assert np.isfinite(ll).all()
    assert ll[-1] <= ll[0] * 1.05


_objectives = st.sampled_from([
    ("binary:logistic", "logloss"),
    ("reg:squarederror", "rmse"),
    ("reg:absoluteerror", "mae"),
    ("reg:pseudohubererror", "mphe"),
    ("count:poisson", "poisson-nloglik"),
])


@settings(deadline=None, max_examples=10,
          suppress_health_check=[HealthCheck.too_slow])
@given(obj_metric=_objectives, params=_params, seed=st.integers(0, 2))
def test_objective_param_sweep(obj_metric, params, seed):
    """Objectives x tree params: adaptive-leaf (mae), CoV-transformed
    (poisson), and plain second-order objectives all stay finite and
    non-divergent under the full param grid."""
    obj, metric = obj_metric
    X, y = _dataset(seed, n=250)
    if obj == "binary:logistic":
        y = (y > 0).astype(np.float32)
    elif obj == "count:poisson":
        y = np.abs(y) + 0.1
    d = xtb.DMatrix(X, label=y)
    res = {}
    xtb.train({**params, "objective": obj}, d, 6,
              evals=[(d, "t")], evals_result=res, verbose_eval=False)
    vals = res["t"][metric]
    assert np.isfinite(vals).all()
    assert vals[-1] <= vals[0] * 1.1 + 1e-6


@settings(deadline=None, max_examples=8,
          suppress_health_check=[HealthCheck.too_slow])
@given(params=_params, seed=st.integers(0, 2),
       n_cat=st.sampled_from([3, 12, 40]))
def test_categorical_param_sweep(params, seed, n_cat):
    """Categorical features under the full param grid (one-hot and sorted
    partition regimes both exercised by varying cardinality vs
    max_cat_to_onehot); trees must respect depth/leaf caps and predictions
    must stay finite."""
    rng = np.random.default_rng(seed)
    n = 300
    Xn = rng.normal(size=(n, 3)).astype(np.float32)
    c = rng.integers(0, n_cat, size=n)
    effect = rng.normal(size=n_cat)[c].astype(np.float32)
    y = (Xn[:, 0] + effect + 0.2 * rng.normal(size=n)).astype(np.float32)
    X = np.column_stack([Xn, c.astype(np.float32)])
    d = xtb.DMatrix(X, label=y, feature_types=["q", "q", "q", "c"],
                    enable_categorical=True)
    res = {}
    # the bin table must hold every category (same rule as the reference)
    params = {**params, "max_bin": max(params["max_bin"], n_cat)}
    bst = xtb.train({**params, "objective": "reg:squarederror"}, d, 6,
                    evals=[(d, "t")], evals_result=res, verbose_eval=False)
    assert np.isfinite(res["t"]["rmse"]).all()
    assert np.isfinite(bst.predict(d)).all()
    for t in bst.trees:
        if params["max_leaves"]:
            assert t.num_leaves <= params["max_leaves"]


@settings(deadline=None, max_examples=8,
          suppress_health_check=[HealthCheck.too_slow])
@given(params=_params, seed=st.integers(0, 2))
def test_model_io_roundtrip_sweep(params, seed):
    """Every config's model must round-trip through BOTH serialization
    formats bit-exactly (reference: test_model_io.py round-trip sweep)."""
    import os
    import tempfile

    X, y = _dataset(seed, n=200)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({**params, "objective": "reg:squarederror"}, d, 3,
                    verbose_eval=False)
    p0 = bst.predict(d)
    with tempfile.TemporaryDirectory() as tmp:
        for ext in ("json", "ubj"):
            path = os.path.join(tmp, f"m.{ext}")
            bst.save_model(path)
            b2 = xtb.Booster()
            b2.load_model(path)
            np.testing.assert_array_equal(b2.predict(d), p0)
