"""Serving engine tests (xgboost_tpu/serving/): batching, registry
residency, metrics, and the concurrent-predict acceptance criteria —
N threads get bitwise-identical outputs with ZERO recompiles after
warm-up (ISSUE 1; reference: thread-safe Learner, src/c_api/c_api.cc).
"""
import threading
import time

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.ops.predict import bucket_rows, bucket_width
from xgboost_tpu.serving import (MicroBatcher, ModelRegistry, ServeConfig,
                                 ServingEngine)


def _train(seed=0, rounds=5, objective="binary:logistic", n=256, f=6,
           **params):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    if objective.startswith("multi"):
        y = rng.integers(0, params.get("num_class", 3), size=n).astype(
            np.float32)
    else:
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train(dict({"objective": objective, "max_depth": 4}, **params),
                    d, rounds, verbose_eval=False)
    return bst, X, y


# ====================================================================
# bucket policy

def test_bucket_policy():
    assert [bucket_rows(n) for n in (1, 8, 9, 100, 4096)] == [
        8, 8, 16, 128, 4096]
    assert bucket_rows(4097) == 8192
    assert bucket_rows(9000) == 12288  # multiples of 4096 past the ceiling
    assert bucket_width(3) == 4 and bucket_width(17) == 32


def test_predict_reuses_programs_across_row_counts():
    """The small-fix satellite: row counts in one bucket share one compiled
    program, so Booster.predict no longer retraces per distinct shape."""
    bst, X, _ = _train(seed=3)
    from xgboost_tpu.ops.predict import predict_cache_size

    bst.predict(xtb.DMatrix(X[:33]))  # compiles the 64-row bucket
    before = predict_cache_size()
    for r in (34, 40, 64):  # all bucket to 64
        bst.predict(xtb.DMatrix(X[:r]))
    assert predict_cache_size() == before


# ====================================================================
# engine basics

def test_engine_matches_booster_predict():
    bst, X, _ = _train(seed=1)
    with ServingEngine(max_delay_us=200, warmup_buckets=(8, 64)) as eng:
        eng.add_model("m", bst)
        for r in (1, 7, 33, 64):
            ref = bst.predict(xtb.DMatrix(X[:r]))
            np.testing.assert_array_equal(eng.predict("m", X[:r]), ref)
            np.testing.assert_array_equal(
                eng.predict("m", X[:r], direct=True), ref)
        # margin path too
        ref_m = bst.predict(xtb.DMatrix(X[:16]), output_margin=True)
        np.testing.assert_array_equal(
            eng.predict("m", X[:16], output_margin=True), ref_m)


def test_engine_multiclass_shape():
    bst, X, _ = _train(seed=2, objective="multi:softprob", num_class=3)
    with ServingEngine(use_batcher=False, warmup_buckets=(16,)) as eng:
        eng.add_model("mc", bst)
        out = eng.predict("mc", X[:10])
        assert out.shape == (10, 3)
        np.testing.assert_array_equal(out, bst.predict(xtb.DMatrix(X[:10])))


def test_engine_loads_model_files(tmp_path):
    bst, X, _ = _train(seed=4)
    ref = bst.predict(xtb.DMatrix(X[:20]))
    for ext in ("json", "ubj"):
        path = str(tmp_path / f"m.{ext}")
        bst.save_model(path)
        with ServingEngine(use_batcher=False) as eng:
            eng.add_model(f"m_{ext}", path, warmup=False)
            np.testing.assert_array_equal(
                eng.predict(f"m_{ext}", X[:20]), ref)


def test_engine_input_validation_and_error_metric():
    bst, X, _ = _train(seed=5)
    with ServingEngine(use_batcher=False) as eng:
        eng.add_model("m", bst, warmup=False)
        with pytest.raises(ValueError, match="feature shape mismatch"):
            eng.predict("m", X[:4, :3])
        with pytest.raises(KeyError):
            eng.predict("ghost", X[:4])
        assert eng.metrics.snapshot()["models"]["m"]["errors"] == 1
        # 1-D input is a single row
        assert eng.predict("m", X[0]).shape == (1,)
        # base_margin cannot ride a coalesced batch -> explicit rejection
        with pytest.raises(ValueError, match="base_margin"):
            eng.predict("m", xtb.DMatrix(
                X[:4], base_margin=np.zeros(4, np.float32)))
    with pytest.raises(RuntimeError, match="closed"):
        eng.predict("m", X[:4])


def test_serve_config_validation():
    with pytest.raises(ValueError):
        ServeConfig(max_batch=0)
    with pytest.raises(ValueError):
        ServingEngine(max_delay_us=-1)
    # default warm-up covers every bucket the admission policy can produce
    assert ServeConfig(max_batch=64).resolved_warmup_buckets() == (
        8, 16, 32, 64)
    assert ServeConfig(max_batch=1).resolved_warmup_buckets() == (8,)
    assert ServeConfig(max_batch=9000).resolved_warmup_buckets()[-3:] == (
        4096, 8192, 12288)
    assert ServeConfig(max_batch=64, warmup_buckets=(8,)
                       ).resolved_warmup_buckets() == (8,)


# ====================================================================
# registry: versions, pinning, LRU

def test_registry_versions_and_pinning():
    b1, X, _ = _train(seed=6, rounds=3)
    b2, _, _ = _train(seed=6, rounds=6)
    with ServingEngine(use_batcher=False) as eng:
        v1 = eng.add_model("m", b1, warmup=False)
        v2 = eng.add_model("m", b2, warmup=False)
        assert (v1, v2) == (1, 2)
        p1 = b1.predict(xtb.DMatrix(X[:16]))
        p2 = b2.predict(xtb.DMatrix(X[:16]))
        np.testing.assert_array_equal(eng.predict("m", X[:16]), p2)  # latest
        eng.pin("m", v1)  # rollback knob
        np.testing.assert_array_equal(eng.predict("m", X[:16]), p1)
        np.testing.assert_array_equal(
            eng.predict("m", X[:16], version=v2), p2)  # explicit wins
        eng.unpin("m")
        np.testing.assert_array_equal(eng.predict("m", X[:16]), p2)


def test_registry_lru_eviction():
    reg = ModelRegistry(max_models=2)
    boosters = [_train(seed=s, rounds=2, n=64)[0] for s in range(3)]
    reg.register("a", boosters[0])
    reg.register("b", boosters[1])
    reg.get("a")  # a is now more recently used than b
    reg.register("c", boosters[2])  # evicts b
    assert reg.names() == ["a", "c"] and reg.evictions == 1
    # every version of b was evicted, so the name itself is gone
    with pytest.raises(KeyError, match="unknown model"):
        reg.get("b")


def test_registry_evicting_latest_keeps_older_resolvable():
    reg = ModelRegistry(max_models=2)
    b0, _, _ = _train(seed=0, rounds=2, n=64)
    b1, _, _ = _train(seed=1, rounds=2, n=64)
    reg.register("m", b0)  # v1
    reg.register("m", b1)  # v2
    reg.get("m", 1)  # v2 becomes the LRU victim
    reg.register("other", b0)  # evicts (m, 2)
    assert reg.versions("m") == [1]
    _, v = reg.get("m")  # must fall back to the surviving version
    assert v == 1


def test_registry_pinned_never_evicted():
    reg = ModelRegistry(max_models=2)
    boosters = [_train(seed=s, rounds=2, n=64)[0] for s in range(3)]
    reg.register("a", boosters[0])
    reg.pin("a", 1)
    reg.register("b", boosters[1])
    reg.register("c", boosters[2])  # must evict b, not pinned a
    assert reg.names() == ["a", "c"]
    reg.register("d", boosters[2])  # evicts c
    reg.register("e", boosters[2])  # evicts d
    assert "a" in reg.names()
    # all-pinned registry refuses further loads loudly
    reg2 = ModelRegistry(max_models=1)
    reg2.register("x", boosters[0])
    reg2.pin("x", 1)
    with pytest.raises(RuntimeError, match="all pinned"):
        reg2.register("y", boosters[1])
    assert reg.resident_bytes() > 0


# ====================================================================
# snapshot semantics

def test_snapshot_immutable_under_continued_training():
    bst, X, y = _train(seed=7, rounds=3)
    snap_preds_before = None
    with ServingEngine(use_batcher=False) as eng:
        eng.add_model("m", bst, warmup=False)
        snap_preds_before = eng.predict("m", X[:32])
        # mutate the live booster: continue training 3 more rounds
        d = xtb.DMatrix(X, label=y)
        for it in (3, 4, 5):
            bst.update(d, it)
        after = bst.predict(xtb.DMatrix(X[:32]))
        served = eng.predict("m", X[:32])
        np.testing.assert_array_equal(served, snap_preds_before)
        assert not np.array_equal(served, after)  # booster moved on
        # re-registering picks up the new trees as a new version
        eng.add_model("m", bst, warmup=False)
        np.testing.assert_array_equal(eng.predict("m", X[:32]), after)


def test_snapshot_rejects_gblinear():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    bst = xtb.train({"booster": "gblinear", "objective": "binary:logistic"},
                    xtb.DMatrix(X, label=y), 2, verbose_eval=False)
    with pytest.raises(NotImplementedError, match="gblinear"):
        bst.inference_snapshot()


# ====================================================================
# micro-batcher

def test_batcher_coalesces_and_splits():
    """Requests queued while the worker is busy coalesce into ONE batch and
    split back per caller in FIFO order."""
    entered, release = threading.Event(), threading.Event()
    calls = []

    def execute(key, X, ctx):
        entered.set()
        release.wait(10)
        calls.append(len(X))
        return X * 2.0

    mb = MicroBatcher(execute, max_batch=100, max_delay_us=0)
    try:
        f0 = mb.submit("k", np.full((1, 2), 1.0))
        assert entered.wait(10)  # worker is now blocked inside batch 1
        fs = [mb.submit("k", np.full((i + 1, 2), float(i)))
              for i in range(4)]
        release.set()
        np.testing.assert_array_equal(f0.result(10), np.full((1, 2), 2.0))
        for i, f in enumerate(fs):
            np.testing.assert_array_equal(
                f.result(10), np.full((i + 1, 2), 2.0 * i))
    finally:
        mb.close()
    assert calls == [1, 10]  # batch 2 coalesced all four queued requests


def test_batcher_max_batch_admission():
    calls = []

    def execute(key, X, ctx):
        calls.append(len(X))
        return X

    mb = MicroBatcher(execute, max_batch=4, max_delay_us=500_000)
    try:
        # 2+2 rows reach max_batch -> launches immediately, not after 500ms
        t0 = time.perf_counter()
        f1 = mb.submit("k", np.zeros((2, 1)))
        f2 = mb.submit("k", np.zeros((2, 1)))
        f1.result(10), f2.result(10)
        assert time.perf_counter() - t0 < 0.4
        # one oversized request still runs (as its own batch)
        f3 = mb.submit("k", np.zeros((9, 1)))
        assert f3.result(10).shape == (9, 1)
    finally:
        mb.close()
    assert 9 in calls


def test_batcher_propagates_errors_to_all_waiters():
    def execute(key, X, ctx):
        raise RuntimeError("kaboom")

    mb = MicroBatcher(execute, max_batch=10, max_delay_us=0)
    try:
        fs = [mb.submit("k", np.zeros((1, 1))) for _ in range(3)]
        for f in fs:
            with pytest.raises(RuntimeError, match="kaboom"):
                f.result(10)
    finally:
        mb.close()
    with pytest.raises(RuntimeError, match="closed"):
        mb.submit("k", np.zeros((1, 1)))


# ====================================================================
# concurrency acceptance: bitwise equality + zero recompiles after warm-up

def _hammer(eng, jobs, n_threads):
    """Run ``jobs`` (callables) round-robin from ``n_threads`` threads;
    re-raise the first worker failure."""
    errors = []
    barrier = threading.Barrier(n_threads)

    def worker(tid):
        try:
            barrier.wait(10)
            for j in jobs[tid::n_threads]:
                j()
        except BaseException as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    if errors:
        raise errors[0]


def test_concurrent_single_model_bitwise_no_retrace():
    """Acceptance: >=4 threads hammer one model through the batcher; every
    result is bitwise-identical to the single-threaded reference and the
    compiled-program count does not move after warm-up."""
    bst, X, _ = _train(seed=8)
    row_counts = [1, 5, 8, 33, 64]  # all bucket to 8 or 64
    refs = {r: np.asarray(bst.predict(xtb.DMatrix(X[:r])))
            for r in row_counts}
    # max_batch bounds coalesced batches at 64 rows, so warming every bucket
    # up to it covers EVERY shape the batcher can produce — the knob pairing
    # docs/serving.md prescribes for a zero-recompile steady state
    with ServingEngine(max_delay_us=500, max_batch=64,
                       warmup_buckets=(8, 16, 32, 64)) as eng:
        eng.add_model("m", bst)  # warms all buckets, margin + transformed
        cache_before = eng.compile_cache_size()

        def make_job(r):
            def job():
                out = eng.predict("m", X[:r])
                assert np.array_equal(out, refs[r]), f"mismatch at rows={r}"
            return job

        jobs = [make_job(r) for r in row_counts * 12]  # 60 requests
        _hammer(eng, jobs, n_threads=6)

        assert eng.compile_cache_size() == cache_before  # zero recompiles
        snap = eng.metrics_snapshot()
        assert snap["compiles_steady"] == 0
        m = snap["models"]["m"]
        assert m["requests"] == len(jobs) and m["errors"] == 0
        assert m["rows"] == sum(row_counts) * 12
        assert m["batches"] >= 1
        lat = m["latency_ms"]
        assert all(lat[q] is not None for q in ("p50", "p95", "p99"))
        assert lat["p50"] <= lat["p95"] <= lat["p99"]


def test_concurrent_many_models_bitwise_no_retrace():
    """Acceptance: threads interleave requests across several resident
    models; per-model results stay bitwise-correct and warm."""
    models = {f"m{s}": _train(seed=20 + s, rounds=3) for s in range(3)}
    refs = {name: np.asarray(bst.predict(xtb.DMatrix(X[:32])))
            for name, (bst, X, _) in models.items()}
    with ServingEngine(max_delay_us=300, max_batch=32,
                       warmup_buckets=(8, 16, 32)) as eng:
        for name, (bst, _, _) in models.items():
            eng.add_model(name, bst)
        cache_before = eng.compile_cache_size()

        def make_job(name):
            X = models[name][1]

            def job():
                assert np.array_equal(eng.predict(name, X[:32]), refs[name])
            return job

        jobs = [make_job(name) for name in models for _ in range(10)]
        _hammer(eng, jobs, n_threads=5)

        assert eng.compile_cache_size() == cache_before
        snap = eng.metrics_snapshot()
        assert snap["compiles_steady"] == 0
        assert snap["resident_models"] == 3
        for name in models:
            assert snap["models"][name]["requests"] == 10
            assert snap["models"][name]["errors"] == 0


def test_direct_and_batched_paths_agree_bitwise():
    bst, X, _ = _train(seed=9)
    with ServingEngine(max_delay_us=200, warmup_buckets=(32,)) as eng:
        eng.add_model("m", bst)
        np.testing.assert_array_equal(
            eng.predict("m", X[:17]), eng.predict("m", X[:17], direct=True))


# ====================================================================
# metrics & observer

def test_metrics_snapshot_shape_and_observer(capsys, monkeypatch):
    # f=9 is unique in this suite: the jit cache (process-global) cannot have
    # the (bucket, 9) shapes yet, so the un-warmed predicts below MUST compile
    bst, X, _ = _train(seed=10, f=9)
    with ServingEngine(max_delay_us=100) as eng:
        eng.add_model("m", bst, warmup=False)
        for r in (3, 9, 30):
            eng.predict("m", X[:r])
        snap = eng.metrics_snapshot()
        m = snap["models"]["m"]
        assert m["rows"] == 42 and m["requests"] == 3
        assert sum(m["batch_size_hist"].values()) == m["batches"]
        assert m["rows_per_s"] is None or m["rows_per_s"] > 0
        assert snap["compiles_warmup"] == 0  # warmup=False: all steady
        assert snap["compiles_steady"] > 0
        assert snap["resident_bytes"] > 0
        assert snap["queue_depth"] == 0  # drained
        # observer streaming path (utils/observer.py observe_serving)
        monkeypatch.setenv("XTB_OBSERVER", "1")
        from xgboost_tpu.utils import observer

        monkeypatch.setattr(observer, "enabled", lambda: True)
        eng.metrics.export(tag="t")
        err = capsys.readouterr().err
        assert "[observer] t:" in err and "[observer] t.m:" in err


# ====================================================================
# review regressions

def test_engine_recodes_categorical_dmatrix():
    """A served DMatrix whose pandas category ordering differs from the
    training frame must recode onto the train-time codes, exactly like
    Booster.predict (encoder/ordinal.h Recode)."""
    pd = pytest.importorskip("pandas")
    rng = np.random.default_rng(0)
    n = 600
    colors = ["red", "green", "blue", "yellow"]
    col = rng.choice(colors, size=n)
    num = rng.normal(size=n).astype(np.float32)
    y = ((col == "red") | (col == "blue")).astype(np.float32) + 0.01 * num
    d = xtb.DMatrix(pd.DataFrame({
        "c": pd.Categorical(col, categories=colors), "x": num,
    }), label=y, enable_categorical=True)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 4,
                     "max_cat_to_onehot": 1}, d, 6, verbose_eval=False)
    # same DATA, categories declared reversed -> different physical codes
    d_flip = xtb.DMatrix(pd.DataFrame({
        "c": pd.Categorical(col, categories=colors[::-1]), "x": num,
    }), enable_categorical=True)
    ref = bst.predict(d_flip)
    with ServingEngine(use_batcher=False) as eng:
        eng.add_model("m", bst, warmup=False)
        np.testing.assert_array_equal(eng.predict("m", d_flip), ref)
        # a category unseen in training still fails loudly through the engine
        d_bad = xtb.DMatrix(pd.DataFrame({
            "c": pd.Categorical(["purple"] * 4, categories=["purple"]),
            "x": num[:4],
        }), enable_categorical=True)
        with pytest.raises(ValueError, match="not seen in training"):
            eng.predict("m", d_bad)


def test_batcher_worker_survives_prepare_failure():
    """An exception while PREPARING a batch (e.g. ragged concatenate) must
    fan out to the batch's callers and leave the worker alive for later
    submits — not kill the sole worker and hang every future caller."""
    entered, release = threading.Event(), threading.Event()

    def execute(key, X, ctx):
        entered.set()
        release.wait(10)
        return X

    mb = MicroBatcher(execute, max_batch=100, max_delay_us=0)
    try:
        f0 = mb.submit("k", np.zeros((1, 2)))
        assert entered.wait(10)  # worker blocked: next submits will coalesce
        bad = [mb.submit("k", np.zeros((2, 2))),
               mb.submit("k", np.zeros((2, 3)))]  # ragged widths
        release.set()
        assert f0.result(10).shape == (1, 2)
        for f in bad:
            with pytest.raises(ValueError):
                f.result(10)
        # the worker is still serving
        assert mb.submit("k", np.zeros((3, 2))).result(10).shape == (3, 2)
    finally:
        mb.close()


def test_registry_reregister_keeps_pin():
    reg = ModelRegistry(max_models=2)
    boosters = [_train(seed=s, rounds=2, n=64)[0] for s in range(3)]
    reg.register("m", boosters[0], version=1)
    reg.pin("m", 1)
    reg.register("m", boosters[1], version=1)  # hot-swap the pinned version
    reg.register("a", boosters[2])
    reg.register("b", boosters[2])  # capacity pressure: must not evict (m,1)
    snap, v = reg.get("m")
    assert v == 1 and "m" in reg.names()


def test_registry_remove_latest_keeps_older_versions():
    reg = ModelRegistry(max_models=4)
    b1 = _train(seed=0, rounds=2, n=64)[0]
    b2 = _train(seed=1, rounds=2, n=64)[0]
    reg.register("m", b1)  # v1
    reg.register("m", b2)  # v2
    reg.remove("m", 2)
    snap, v = reg.get("m")  # must fall back to the surviving version
    assert v == 1
    assert reg.register("m", b2) == 2  # numbering continues, no overwrite


def test_execute_serves_current_snapshot_after_hot_swap():
    """A coalesced batch resolves its snapshot at EXECUTE time: requests
    queued before a same-version hot-swap must be served by the replacement,
    not by whichever snapshot rode the first queued request's ctx."""
    b1, X, _ = _train(seed=0, rounds=2)
    b2, _, _ = _train(seed=30, rounds=4)
    with ServingEngine(use_batcher=False) as eng:
        eng.add_model("m", b1, version=1, warmup=False)
        stale_ctx = (eng.registry.get("m", 1)[0], False)
        eng.registry.register("m", b2, version=1)  # hot swap under v1
        out = eng._execute(("m", 1, False), X[:8], stale_ctx)
        np.testing.assert_array_equal(
            out[:, 0], b2.predict(xtb.DMatrix(X[:8])))
