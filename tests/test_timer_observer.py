"""Dedicated coverage for utils/timer.Monitor and utils/observer (neither
had a test file): totals/counts accumulation, start/stop re-entrancy,
verbosity-gated printing, and the observer's enable/disable + dump formats."""
import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.utils import observer
from xgboost_tpu.utils.timer import Monitor


@pytest.fixture(autouse=True)
def _observer_reset():
    """observer.observe() flips module state: restore the env-derived
    default afterwards so other tests see their expected gating."""
    yield
    observer._ENABLED = None


# ====================================================================
# Monitor

def test_monitor_totals_and_counts_accumulate():
    m = Monitor("t")
    for _ in range(3):
        m.start("phase")
        m.stop("phase")
    m.start("other")
    m.stop("other")
    assert m.counts["phase"] == 3 and m.counts["other"] == 1
    assert m.totals["phase"] >= 0 and m.totals["other"] >= 0


def test_monitor_reentrant_start_keeps_stack():
    """A second start(name) before stop(name) used to overwrite the open
    timestamp (and leak its TraceAnnotation); the per-label stack closes
    each bracket independently."""
    import time

    m = Monitor("t")
    m.start("op")
    time.sleep(0.01)
    m.start("op")  # nested bracket
    m.stop("op")   # closes the INNER (short) bracket
    inner = m.totals["op"]
    m.stop("op")   # closes the OUTER (>= 10ms) bracket
    assert m.counts["op"] == 2
    assert m.totals["op"] - inner >= 0.009
    assert not m._open["op"]  # nothing left open


def test_monitor_unmatched_stop_is_ignored():
    m = Monitor("t")
    m.stop("never_started")
    assert m.counts == {} or m.counts.get("never_started", 0) == 0


def test_monitor_print_gated_by_verbosity(capsys):
    m = Monitor("lbl")
    m.start("a")
    m.stop("a")
    with xtb.config_context(verbosity=1):
        m.print_statistics()
    assert capsys.readouterr().out == ""  # below the gate: silent
    with xtb.config_context(verbosity=3):
        m.print_statistics()
    out = capsys.readouterr().out
    assert "Monitor (lbl)" in out and "a:" in out and "1 calls" in out


def test_monitor_empty_prints_nothing_even_verbose(capsys):
    with xtb.config_context(verbosity=3):
        Monitor("empty").print_statistics()
    assert capsys.readouterr().out == ""


# ====================================================================
# observer

def test_observer_enable_disable_and_env(monkeypatch):
    observer.observe(True)
    assert observer.enabled()
    observer.observe(False)
    assert not observer.enabled()
    # unset state re-reads the environment
    observer._ENABLED = None
    monkeypatch.setenv("XGBOOST_TPU_DEBUG_OBSERVER", "1")
    assert observer.enabled()
    observer._ENABLED = None
    monkeypatch.setenv("XGBOOST_TPU_DEBUG_OBSERVER", "0")
    assert not observer.enabled()


def test_observer_gradient_and_margin_dump_format(capsys):
    observer.observe(True)
    gpair = np.stack([np.arange(4, dtype=np.float32),
                      np.ones(4, np.float32)], axis=-1)[:, None, :]
    observer.observe_gradients(gpair, iteration=2)
    observer.observe_margin(np.full(4, 0.5, np.float32), iteration=2)
    err = capsys.readouterr().err
    assert "[observer] iter2.grad: n=4 sum=6" in err
    assert "[observer] iter2.hess: n=4 sum=4" in err
    assert "[observer] iter2.margin: n=4 sum=2" in err
    observer.observe(False)
    observer.observe_margin(np.zeros(2), iteration=3)
    assert capsys.readouterr().err == ""  # disabled: no stream


def test_observer_tree_dump(capsys):
    observer.observe(True)
    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 5)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 2},
                    xtb.DMatrix(X, label=y), 1, verbose_eval=False)
    observer.observe_tree(bst.trees[-1], iteration=0)
    err = capsys.readouterr().err
    assert "[observer] iter0.tree nodes=" in err
    assert "leaves=" in err and "iter0.leaf_values" in err


def test_observer_serving_dump_format(capsys):
    observer.observe(True)
    snap = {"queue_depth": 0, "queue_peak": 3, "compiles_warmup": 2,
            "compiles_steady": 0,
            "models": {"m": {"requests": 5, "rows": 9, "errors": 0,
                             "batches": 2,
                             "latency_ms": {"p50": 1.0, "p95": 2.0,
                                            "p99": None}}}}
    observer.observe_serving(snap, tag="t")
    err = capsys.readouterr().err
    assert "[observer] t: queue_depth=0 queue_peak=3" in err
    assert "[observer] t.m: requests=5 rows=9" in err
    assert "p99=n/a" in err  # None renders as n/a


def test_observer_streams_during_training(capsys):
    observer.observe(True)
    rng = np.random.default_rng(1)
    X = rng.normal(size=(150, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    xtb.train({"objective": "binary:logistic", "max_depth": 2},
              xtb.DMatrix(X, label=y), 1, verbose_eval=False)
    err = capsys.readouterr().err
    assert "iter0.grad" in err and "iter0.margin" in err
    assert "iter0.tree" in err
