"""Two-process distributed training (reference: the dask launcher path,
dask/__init__.py:722 _train_async — every worker trains on its own rows and
rabit allreduces histograms).

Parent spawns 2 jax.distributed CPU processes; each holds a disjoint row
shard, builds shared cuts via the distributed sketch merge, and trains
through ProcessHistTreeGrower.  Both workers must produce bitwise-identical
trees, and the model must be as good as single-process training on the
union of the shards.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

CHILD = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
rank = int(sys.argv[1]); port = sys.argv[2]

from xgboost_tpu import collective
collective.init(coordinator_address=f"127.0.0.1:{port}",
                num_processes=2, process_id=rank)
assert collective.get_world_size() == 2
assert collective.get_rank() == rank

import numpy as np
import xgboost_tpu as xtb

rng = np.random.default_rng(0)          # same seed: both build the full set
X = rng.normal(size=(4000, 8)).astype(np.float32)
X[rng.random(X.shape) < 0.1] = np.nan
y = (np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
Xs, ys = X[rank::2], y[rank::2]          # disjoint shards

d = xtb.DMatrix(Xs, label=ys)
ev = {}
bst = xtb.train({"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
                 "max_bin": 64, "eval_metric": ["auc", "logloss"]}, d, 3,
                evals=[(d, "train")], evals_result=ev,
                early_stopping_rounds=5, verbose_eval=False)

ell = d._ellpack
dump = bst.get_dump(dump_format="json")
preds_local = bst.predict(d)

# exercise the flat collective API on the way out
s = collective.allreduce(np.asarray([float(rank) + 1.0]))
bc = collective.broadcast({"from": "rank0"} if rank == 0 else None, 0)

import hashlib
print("RESULT" + json.dumps({
    "rank": rank,
    "cut_values": np.asarray(ell.cuts.cut_values).tolist(),
    "dump_hash": hashlib.md5("".join(dump).encode()).hexdigest(),
    "dump0": dump[0],
    "allreduce_sum": float(s[0]),
    "broadcast_ok": bc == {"from": "rank0"},
    "preds_head": preds_local[:5].tolist(),
    "evals": ev,
    "best_iteration": bst.best_iteration,
}))
collective.finalize()
"""


def _run_two_process(child_src):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen([sys.executable, "-c", child_src, str(rank), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, env=env)
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=850)
        assert p.returncode == 0, f"worker failed:\n{err[-4000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT")][-1]
        outs.append(json.loads(line[len("RESULT"):]))
    return sorted(outs, key=lambda o: o["rank"])


def test_two_process_training_identical_trees(tmp_path):
    outs = _run_two_process(CHILD)

    r0, r1 = outs
    # shared cuts: the distributed sketch merge must agree bitwise
    np.testing.assert_array_equal(r0["cut_values"], r1["cut_values"])
    # identical trees on both workers (the reference's rabit guarantee)
    assert r0["dump_hash"] == r1["dump_hash"]
    assert r0["dump0"] == r1["dump0"]
    # collective API round-trips
    assert r0["allreduce_sum"] == 3.0 and r1["allreduce_sum"] == 3.0
    assert r0["broadcast_ok"] and r1["broadcast_ok"]
    # distributed eval: both ranks report the GLOBAL metric, so their eval
    # histories (and any early-stopping decision) agree exactly
    assert r0["evals"] == r1["evals"]
    assert r0["best_iteration"] == r1["best_iteration"]

    # quality: the distributed model should separate the classes on its shard
    rng = np.random.default_rng(0)
    X = rng.normal(size=(4000, 8)).astype(np.float32)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
    import xgboost_tpu as xtb

    bst = xtb.train({"objective": "binary:logistic", "max_depth": 4,
                     "eta": 0.3, "max_bin": 64},
                    xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    full_head = bst.predict(xtb.DMatrix(X[0::2]))[:5]
    # distributed (merged-sketch) cuts differ slightly from single-node cuts,
    # so trees need not match the single-process run — but predictions should
    # land in the same ballpark
    assert np.all(np.abs(np.asarray(r0["preds_head"]) - full_head) < 0.25)


CHILD_EXTMEM = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
rank = int(sys.argv[1]); port = sys.argv[2]

from xgboost_tpu import collective
collective.init(coordinator_address=f"127.0.0.1:{port}",
                num_processes=2, process_id=rank)

import numpy as np
import xgboost_tpu as xtb
from xgboost_tpu.data.extmem import DataIter, ExtMemQuantileDMatrix

rng = np.random.default_rng(0)
X = rng.normal(size=(4000, 8)).astype(np.float32)
X[rng.random(X.shape) < 0.1] = np.nan
y = (np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
Xs, ys = X[rank::2], y[rank::2]          # disjoint row shards

class ShardIter(DataIter):
    def __init__(self):
        super().__init__()
        self._i = 0
    def next(self, input_data):
        if self._i >= 2:                  # 2 pages per process
            return 0
        lo = self._i * 1000; hi = lo + 1000
        input_data(data=Xs[lo:hi], label=ys[lo:hi])
        self._i += 1
        return 1
    def reset(self):
        self._i = 0

d = ExtMemQuantileDMatrix(ShardIter(), max_bin=64)
bst = xtb.train({"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
                 "max_bin": 64}, d, 3, verbose_eval=False)
dump = bst.get_dump(dump_format="json")
preds = bst.predict(d)

import hashlib
print("RESULT" + json.dumps({
    "rank": rank,
    "cut_values": np.asarray(d._cuts.cut_values).tolist(),
    "dump_hash": hashlib.md5("".join(dump).encode()).hexdigest(),
    "dump0": dump[0],
    "preds_head": preds[:5].tolist(),
}))
collective.finalize()
"""


CHILD_MULTI = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
rank = int(sys.argv[1]); port = sys.argv[2]

from xgboost_tpu import collective
collective.init(coordinator_address=f"127.0.0.1:{port}",
                num_processes=2, process_id=rank)

import numpy as np
import xgboost_tpu as xtb

rng = np.random.default_rng(0)
X = rng.normal(size=(2000, 6)).astype(np.float32)
W = rng.normal(size=(6, 3)).astype(np.float32)
Y = (X @ W).astype(np.float32)
Xs, Ys = X[rank::2], Y[rank::2]

d = xtb.DMatrix(Xs, label=Ys)
bst = xtb.train({"objective": "reg:squarederror", "num_target": 3,
                 "multi_strategy": "multi_output_tree", "max_depth": 4,
                 "eta": 0.3, "max_bin": 64}, d, 3, verbose_eval=False)
dump = bst.get_dump(dump_format="json")

import hashlib
print("RESULT" + json.dumps({
    "rank": rank,
    "dump_hash": hashlib.md5("".join(dump).encode()).hexdigest(),
    "preds_head": bst.predict(d)[:3].tolist(),
}))
collective.finalize()
"""


def test_two_process_multitarget_identical_trees():
    """Vector-leaf trees x multi-process: the 2K-channel histogram allreduce
    must produce bitwise-identical trees on every rank."""
    r0, r1 = _run_two_process(CHILD_MULTI)
    assert r0["dump_hash"] == r1["dump_hash"]


def test_two_process_extmem_training_identical_trees():
    """extmem x multi-process: each worker streams its own page shard; the
    per-level histogram allreduce must make trees bitwise identical across
    ranks (the reference's extmem path runs unchanged under rabit —
    updater_gpu_hist.cu:601)."""
    r0, r1 = _run_two_process(CHILD_EXTMEM)
    np.testing.assert_array_equal(r0["cut_values"], r1["cut_values"])
    assert r0["dump_hash"] == r1["dump_hash"]
    assert r0["dump0"] == r1["dump0"]

    # quality: the 2-process extmem model must roughly match in-memory
    # training over the union of the shards
    rng = np.random.default_rng(0)
    X = rng.normal(size=(4000, 8)).astype(np.float32)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
    import xgboost_tpu as xtb

    bst = xtb.train({"objective": "binary:logistic", "max_depth": 4,
                     "eta": 0.3, "max_bin": 64},
                    xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    full_head = bst.predict(xtb.DMatrix(X[0::2]))[:5]
    assert np.all(np.abs(np.asarray(r0["preds_head"]) - full_head) < 0.25)
