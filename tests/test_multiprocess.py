"""Two-process distributed training (reference: the dask launcher path,
dask/__init__.py:722 _train_async — every worker trains on its own rows and
rabit allreduces histograms).

Parent spawns 2 jax.distributed CPU processes; each holds a disjoint row
shard, builds shared cuts via the distributed sketch merge, and trains
through ProcessHistTreeGrower.  Both workers must produce bitwise-identical
trees, and the model must be as good as single-process training on the
union of the shards.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

CHILD = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
rank = int(sys.argv[1]); port = sys.argv[2]

from xgboost_tpu import collective
# tracker rendezvous: rank assigned by the tracker; on CPU the
# collectives ride the tracker's socket relay (XLA:CPU cannot run
# multiprocess collectives — tracker.CollRelay, docs/reliability.md)
collective.init(dmlc_tracker_uri="127.0.0.1", dmlc_tracker_port=port,
                dmlc_nworker=2)
rank = collective.get_rank()
assert collective.get_world_size() == 2

import numpy as np
import xgboost_tpu as xtb

rng = np.random.default_rng(0)          # same seed: both build the full set
X = rng.normal(size=(4000, 8)).astype(np.float32)
X[rng.random(X.shape) < 0.1] = np.nan
y = (np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
Xs, ys = X[rank::2], y[rank::2]          # disjoint shards

d = xtb.DMatrix(Xs, label=ys)
ev = {}
bst = xtb.train({"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
                 "max_bin": 64, "eval_metric": ["auc", "logloss"]}, d, 3,
                evals=[(d, "train")], evals_result=ev,
                early_stopping_rounds=5, verbose_eval=False)

ell = d._ellpack
dump = bst.get_dump(dump_format="json")
preds_local = bst.predict(d)

# exercise the flat collective API on the way out
s = collective.allreduce(np.asarray([float(rank) + 1.0]))
bc = collective.broadcast({"from": "rank0"} if rank == 0 else None, 0)

import hashlib
print("RESULT" + json.dumps({
    "rank": rank,
    "cut_values": np.asarray(ell.cuts.cut_values).tolist(),
    "dump_hash": hashlib.md5("".join(dump).encode()).hexdigest(),
    "dump0": dump[0],
    "allreduce_sum": float(s[0]),
    "broadcast_ok": bc == {"from": "rank0"},
    "preds_head": preds_local[:5].tolist(),
    "evals": ev,
    "best_iteration": bst.best_iteration,
}))
collective.finalize()
"""


def _run_two_process(child_src, devices_per_process=None):
    from xgboost_tpu.tracker import RabitTracker

    tr = RabitTracker(n_workers=2, host_ip="127.0.0.1")
    tr.start()
    port = tr.port
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    if devices_per_process:
        # composed topology: each process sees its own virtual chip mesh
        env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                            f"{devices_per_process}")
    procs = [
        subprocess.Popen([sys.executable, "-c", child_src, str(rank), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, env=env)
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=850)
            assert p.returncode == 0, f"worker failed:\n{err[-4000:]}"
            line = [ln for ln in out.splitlines()
                    if ln.startswith("RESULT")][-1]
            outs.append(json.loads(line[len("RESULT"):]))
    finally:
        tr.free()
    return sorted(outs, key=lambda o: o["rank"])


@pytest.mark.slow
def test_two_process_training_identical_trees(tmp_path):
    outs = _run_two_process(CHILD)

    r0, r1 = outs
    # shared cuts: the distributed sketch merge must agree bitwise
    np.testing.assert_array_equal(r0["cut_values"], r1["cut_values"])
    # identical trees on both workers (the reference's rabit guarantee)
    assert r0["dump_hash"] == r1["dump_hash"]
    assert r0["dump0"] == r1["dump0"]
    # collective API round-trips
    assert r0["allreduce_sum"] == 3.0 and r1["allreduce_sum"] == 3.0
    assert r0["broadcast_ok"] and r1["broadcast_ok"]
    # distributed eval: both ranks report the GLOBAL metric, so their eval
    # histories (and any early-stopping decision) agree exactly
    assert r0["evals"] == r1["evals"]
    assert r0["best_iteration"] == r1["best_iteration"]

    # quality: the distributed model should separate the classes on its shard
    rng = np.random.default_rng(0)
    X = rng.normal(size=(4000, 8)).astype(np.float32)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
    import xgboost_tpu as xtb

    bst = xtb.train({"objective": "binary:logistic", "max_depth": 4,
                     "eta": 0.3, "max_bin": 64},
                    xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    full_head = bst.predict(xtb.DMatrix(X[0::2]))[:5]
    # distributed (merged-sketch) cuts differ slightly from single-node cuts,
    # so trees need not match the single-process run — but predictions should
    # land in the same ballpark
    assert np.all(np.abs(np.asarray(r0["preds_head"]) - full_head) < 0.25)


CHILD_EXTMEM = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
rank = int(sys.argv[1]); port = sys.argv[2]

from xgboost_tpu import collective
# tracker rendezvous: rank assigned by the tracker; on CPU the
# collectives ride the tracker's socket relay (XLA:CPU cannot run
# multiprocess collectives — tracker.CollRelay, docs/reliability.md)
collective.init(dmlc_tracker_uri="127.0.0.1", dmlc_tracker_port=port,
                dmlc_nworker=2)
rank = collective.get_rank()

import numpy as np
import xgboost_tpu as xtb
from xgboost_tpu.data.extmem import DataIter, ExtMemQuantileDMatrix

rng = np.random.default_rng(0)
X = rng.normal(size=(4000, 8)).astype(np.float32)
X[rng.random(X.shape) < 0.1] = np.nan
y = (np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
Xs, ys = X[rank::2], y[rank::2]          # disjoint row shards

class ShardIter(DataIter):
    def __init__(self):
        super().__init__()
        self._i = 0
    def next(self, input_data):
        if self._i >= 2:                  # 2 pages per process
            return 0
        lo = self._i * 1000; hi = lo + 1000
        input_data(data=Xs[lo:hi], label=ys[lo:hi])
        self._i += 1
        return 1
    def reset(self):
        self._i = 0

d = ExtMemQuantileDMatrix(ShardIter(), max_bin=64)
bst = xtb.train({"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
                 "max_bin": 64}, d, 3, verbose_eval=False)
dump = bst.get_dump(dump_format="json")
preds = bst.predict(d)

import hashlib
print("RESULT" + json.dumps({
    "rank": rank,
    "cut_values": np.asarray(d._cuts.cut_values).tolist(),
    "dump_hash": hashlib.md5("".join(dump).encode()).hexdigest(),
    "dump0": dump[0],
    "preds_head": preds[:5].tolist(),
}))
collective.finalize()
"""


CHILD_MULTI = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
rank = int(sys.argv[1]); port = sys.argv[2]

from xgboost_tpu import collective
# tracker rendezvous: rank assigned by the tracker; on CPU the
# collectives ride the tracker's socket relay (XLA:CPU cannot run
# multiprocess collectives — tracker.CollRelay, docs/reliability.md)
collective.init(dmlc_tracker_uri="127.0.0.1", dmlc_tracker_port=port,
                dmlc_nworker=2)
rank = collective.get_rank()

import numpy as np
import xgboost_tpu as xtb

rng = np.random.default_rng(0)
X = rng.normal(size=(2000, 6)).astype(np.float32)
W = rng.normal(size=(6, 3)).astype(np.float32)
Y = (X @ W).astype(np.float32)
Xs, Ys = X[rank::2], Y[rank::2]

d = xtb.DMatrix(Xs, label=Ys)
bst = xtb.train({"objective": "reg:squarederror", "num_target": 3,
                 "multi_strategy": "multi_output_tree", "max_depth": 4,
                 "eta": 0.3, "max_bin": 64}, d, 3, verbose_eval=False)
dump = bst.get_dump(dump_format="json")

import hashlib
print("RESULT" + json.dumps({
    "rank": rank,
    "dump_hash": hashlib.md5("".join(dump).encode()).hexdigest(),
    "preds_head": bst.predict(d)[:3].tolist(),
}))
collective.finalize()
"""


@pytest.mark.slow
def test_two_process_multitarget_identical_trees():
    """Vector-leaf trees x multi-process: the 2K-channel histogram allreduce
    must produce bitwise-identical trees on every rank."""
    r0, r1 = _run_two_process(CHILD_MULTI)
    assert r0["dump_hash"] == r1["dump_hash"]


@pytest.mark.slow
def test_two_process_extmem_training_identical_trees():
    """extmem x multi-process: each worker streams its own page shard; the
    per-level histogram allreduce must make trees bitwise identical across
    ranks (the reference's extmem path runs unchanged under rabit —
    updater_gpu_hist.cu:601)."""
    r0, r1 = _run_two_process(CHILD_EXTMEM)
    np.testing.assert_array_equal(r0["cut_values"], r1["cut_values"])
    assert r0["dump_hash"] == r1["dump_hash"]
    assert r0["dump0"] == r1["dump0"]

    # quality: the 2-process extmem model must roughly match in-memory
    # training over the union of the shards
    rng = np.random.default_rng(0)
    X = rng.normal(size=(4000, 8)).astype(np.float32)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
    import xgboost_tpu as xtb

    bst = xtb.train({"objective": "binary:logistic", "max_depth": 4,
                     "eta": 0.3, "max_bin": 64},
                    xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    full_head = bst.predict(xtb.DMatrix(X[0::2]))[:5]
    assert np.all(np.abs(np.asarray(r0["preds_head"]) - full_head) < 0.25)


def test_distributed_metric_partial_reduction_matches_single():
    """Per-metric partial-sum allreduce (aggregator.h GlobalSum/GlobalRatio
    role): evaluating a FIXED model on row shards reports the same
    elementwise/ranking metric values as full-data eval, with no
    full-prediction gather."""
    import threading

    import xgboost_tpu as xtb
    from xgboost_tpu import collective

    rng = np.random.default_rng(11)
    n, f = 1200, 6
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * rng.normal(size=n) > 0).astype(np.float32)
    w = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    yr = rng.integers(0, 4, size=n).astype(np.float32)

    def parse(msg):
        out = {}
        for tok in msg.split("\t")[1:]:
            k, v = tok.rsplit(":", 1)
            out[k] = float(v)
        return out

    metrics = ["logloss", "rmse", "mae", "error", "auc"]
    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.5,
              "max_bin": 64, "eval_metric": metrics}
    d_full = xtb.DMatrix(X, label=y, weight=w)
    bst = xtb.train(params, d_full, 2, verbose_eval=False)
    raw = bytes(bst.save_raw())
    single = parse(bst.eval_set([(d_full, "e")], 0))

    rank_metrics = ["ndcg", "map", "pre"]
    rank_params = {"objective": "rank:ndcg", "max_depth": 3, "eta": 0.3,
                   "max_bin": 64, "eval_metric": rank_metrics}
    d_rank = xtb.DMatrix(X, label=yr)
    d_rank.set_group(np.full(60, 20, np.int64))
    bst_r = xtb.train(rank_params, d_rank, 2, verbose_eval=False)
    raw_r = bytes(bst_r.save_raw())
    single_r = parse(bst_r.eval_set([(d_rank, "e")], 0))

    results, errors = {}, {}

    def worker(rank, world):
        try:
            with collective.CommunicatorContext(
                    dmlc_communicator="in-memory",
                    in_memory_world_size=world, in_memory_rank=rank,
                    in_memory_group="metric2"):
                _grp = collective._TLS.backend._group
                lo, hi = (0, n // 2) if rank == 0 else (n // 2, n)
                b = xtb.Booster(params)
                b.load_model(raw)
                d = xtb.DMatrix(X[lo:hi], label=y[lo:hi], weight=w[lo:hi])
                got = parse(b.eval_set([(d, "e")], 0))
                br = xtb.Booster(rank_params)
                br.load_model(raw_r)
                dr = xtb.DMatrix(X[lo:hi], label=yr[lo:hi])
                dr.set_group(np.full(30, 20, np.int64))
                got_r = parse(br.eval_set([(dr, "e")], 0))
                results[rank] = (got, got_r)
        except Exception as e:  # noqa: BLE001
            errors[rank] = e
            try:
                _grp.barrier.abort()
            except Exception:
                pass

    threads = [threading.Thread(target=worker, args=(r, 2), daemon=True)
               for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    assert not any(t.is_alive() for t in threads), "worker deadlocked"
    assert not errors, errors

    ev0, evr0 = results[0]
    ev1, evr1 = results[1]
    assert ev0 == ev1 and evr0 == evr1  # lockstep across ranks

    # partial-sum metrics on shards == full-data values (same fixed model)
    for m in ("e-logloss", "e-rmse", "e-mae", "e-error"):
        np.testing.assert_allclose(ev0[m], single[m], rtol=1e-5, err_msg=m)
    for m in ("e-ndcg", "e-map", "e-pre"):
        np.testing.assert_allclose(evr0[m], single_r[m], rtol=1e-5, err_msg=m)
    # AUC merges as GlobalRatio(area, pos*neg) — upstream's pair-weighted
    # average of per-rank AUCs: ranks agree exactly, and on well-mixed
    # shards it sits close to the global value
    np.testing.assert_allclose(ev0["e-auc"], single["e-auc"], rtol=0.05)


CHILD_COMPOSED = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
rank = int(sys.argv[1]); port = sys.argv[2]

from xgboost_tpu import collective
# tracker rendezvous: rank assigned by the tracker; on CPU the
# collectives ride the tracker's socket relay (XLA:CPU cannot run
# multiprocess collectives — tracker.CollRelay, docs/reliability.md)
collective.init(dmlc_tracker_uri="127.0.0.1", dmlc_tracker_port=port,
                dmlc_nworker=2)
rank = collective.get_rank()

import numpy as np
import xgboost_tpu as xtb

assert jax.local_device_count() == 4, jax.local_device_count()

rng = np.random.default_rng(0)
X = rng.normal(size=(4000, 8)).astype(np.float32)
X[rng.random(X.shape) < 0.1] = np.nan
y = (np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
Xs, ys = X[rank::2], y[rank::2]          # disjoint row shards

import hashlib
def structure(dump):
    # split structure only: leaf VALUES are reduction-order sensitive
    # across topologies (4-chunk psum vs 1-device sums differ in ulps)
    out = []
    def walk(n):
        out.append((n["nodeid"], n.get("split"), n.get("split_condition"),
                    n.get("yes"), n.get("no"), n.get("missing")))
        for c in n.get("children", []):
            walk(c)
    for t in dump:
        walk(json.loads(t))
    return hashlib.md5(json.dumps(out).encode()).hexdigest()

def run(nd, depth=4, rounds=3):
    d = xtb.DMatrix(Xs, label=ys)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": depth,
                     "eta": 0.3, "max_bin": 64, "n_devices": nd}, d, rounds,
                    verbose_eval=False)
    dump = bst.get_dump(dump_format="json")
    return (hashlib.md5("".join(dump).encode()).hexdigest(),
            structure(dump), bst.predict(xtb.DMatrix(Xs)))

# composed: rows sharded over this process's 4-chip local mesh (GSPMD psum)
# x host allreduce across the 2 processes — the reference's rabit x NCCL
# layering (src/collective/comm.cuh:51, dask one-GPU-per-worker generalized)
hash_mesh, struct_mesh, preds_mesh = run(4)
# flat: same 2-process collective, single chip per process
hash_flat, struct_flat, preds_flat = run(1)
# shallow: chip-psum ulps cannot compound into a near-tie flip, so even the
# split structure must agree across topologies
_, s_mesh_sh, _ = run(4, depth=2, rounds=1)
_, s_flat_sh, _ = run(1, depth=2, rounds=1)

print("RESULT" + json.dumps({
    "rank": rank,
    "hash_mesh": hash_mesh,
    "hash_flat": hash_flat,
    "struct_shallow_equal": s_mesh_sh == s_flat_sh,
    "preds_close": bool(np.allclose(preds_mesh, preds_flat,
                                    rtol=1e-3, atol=1e-5)),
    "preds_head": preds_mesh[:5].tolist(),
}))
collective.finalize()
"""


@pytest.mark.slow
def test_two_process_chip_mesh_composed_identical():
    """Process-DP x chip-DP (VERDICT r4 #2): 2 processes x 4 virtual chips
    each — each process GSPMD-shards its rows over its local mesh, and
    histograms cross processes via the ordered host allreduce.

    Guarantees checked for the default (fast f32) histogram: (i) both RANKS
    grow bitwise-identical trees under the composed topology (the rabit
    guarantee); (ii) vs the flat one-chip-per-process run, shallow trees are
    structure-identical and deep-tree predictions agree to float tolerance —
    the chip-level psum changes f32 reduction order, so deep near-tie splits
    may legitimately flip across TOPOLOGIES.  Cross-topology bitwise
    reproducibility is the quantised-histogram mode's contract
    (test_quantised_hist.py), the role of the reference's GradientQuantiser
    (src/tree/gpu_hist/quantiser.cuh)."""
    r0, r1 = _run_two_process(CHILD_COMPOSED, devices_per_process=4)
    # both ranks grow the same trees under the composed topology — bitwise
    assert r0["hash_mesh"] == r1["hash_mesh"]
    assert r0["hash_flat"] == r1["hash_flat"]
    # chip mesh is structurally transparent at shallow depth
    assert r0["struct_shallow_equal"] and r1["struct_shallow_equal"]
    assert r0["preds_close"] and r1["preds_close"]
