"""Lockstep class-batched grower parity (tree/grow_lockstep.py).

The K per-class trees of a multi:softprob round grown in lockstep (one
shared row pass per level) must be BITWISE identical to the sequential
per-class loop: the native multi-class hist kernel adds in the same row
order per class, and split decisions are per-(class, node) with unchanged
tie-breaking.
"""
import hashlib

import numpy as np

import xgboost_tpu as xtb


def _data(n=4000, f=8, k=5, seed=2):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    X[rng.random(X.shape) < 0.08] = np.nan
    z = np.nan_to_num(X[:, 0]) + 0.5 * np.nan_to_num(X[:, 1])
    y = np.clip(((z - z.min()) / (np.ptp(z) + 1e-9) * k), 0,
                k - 1).astype(np.int64).astype(np.float32)
    return X, y


def _h(bst):
    return hashlib.md5(
        "".join(bst.get_dump(dump_format="json")).encode()).hexdigest()


def _train(X, y, k, extra=None, rounds=3):
    # lockstep is opt-in (see core.py _boost_trees): bitwise-equivalent to
    # the sequential per-class loop, aimed at the TPU matmul path
    p = {"objective": "multi:softprob", "num_class": k, "max_depth": 4,
         "eta": 0.3, "max_bin": 32, "_lockstep": "1"}
    if extra:
        p.update(extra)
    return xtb.train(p, xtb.DMatrix(X, label=y), rounds, verbose_eval=False)


def test_lockstep_bitwise_matches_sequential():
    X, y = _data()
    a = _train(X, y, 5)
    b = _train(X, y, 5, {"_lockstep": "0"})
    assert _h(a) == _h(b)
    np.testing.assert_array_equal(
        np.asarray(a.predict(xtb.DMatrix(X))),
        np.asarray(b.predict(xtb.DMatrix(X))))


def test_lockstep_with_monotone_and_interaction():
    X, y = _data(f=6)
    extra = {"monotone_constraints": "(1,0,-1,0,0,0)",
             "interaction_constraints": "[[0, 1, 2], [3, 4, 5]]"}
    a = _train(X, y, 5, extra)
    b = _train(X, y, 5, {**extra, "_lockstep": "0"})
    assert _h(a) == _h(b)


def test_lockstep_subsample_and_leaves_budget():
    X, y = _data()
    extra = {"subsample": 0.7, "seed": 9, "max_leaves": 6,
             "grow_policy": "lossguide", "max_depth": 4}
    a = _train(X, y, 5, extra)
    b = _train(X, y, 5, {**extra, "_lockstep": "0"})
    assert _h(a) == _h(b)


def test_lockstep_softmax_quality():
    X, y = _data(n=6000)
    bst = _train(X, y, 5, {"objective": "multi:softmax"}, rounds=6)
    pred = np.asarray(bst.predict(xtb.DMatrix(X)))
    assert np.mean(pred != y) < 0.25
