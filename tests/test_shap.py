"""TreeSHAP correctness: local accuracy + brute-force Shapley parity
(reference: tests/cpp/predictor test coverage of PredictContribution)."""
import itertools
import math

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.testing.data import make_regression


def _expectation(tree, x, S):
    """E[f(x) | x_S] with path-dependent (cover-weighted) expectations."""
    t_left, t_right = tree.left_children, tree.right_children
    feat, thr, dl = tree.split_indices, tree.split_conditions, tree.default_left
    cover = np.maximum(tree.sum_hessian, 1e-16)

    def rec(n):
        if t_left[n] < 0:
            return tree.split_conditions[n]
        f = feat[n]
        if f in S:
            go_left = dl[n] if np.isnan(x[f]) else x[f] < thr[n]
            return rec(t_left[n] if go_left else t_right[n])
        l, r = t_left[n], t_right[n]
        w = cover[l] + cover[r]
        return (cover[l] * rec(l) + cover[r] * rec(r)) / w

    return rec(0)


def _brute_shapley(tree, x, n_features):
    used = sorted(set(tree.split_indices[tree.left_children >= 0].tolist()))
    phi = np.zeros(n_features + 1)
    M = len(used)
    for i in used:
        others = [f for f in used if f != i]
        for k in range(M):
            for S in itertools.combinations(others, k):
                w = math.factorial(len(S)) * math.factorial(M - len(S) - 1) / math.factorial(M)
                phi[i] += w * (_expectation(tree, x, set(S) | {i}) - _expectation(tree, x, set(S)))
    phi[n_features] = _expectation(tree, x, set())
    return phi


@pytest.fixture(scope="module")
def small_model():
    X, y = make_regression(300, 5, seed=21)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 3, "base_score": 0.0},
                    d, 3, verbose_eval=False)
    return bst, d, X


def test_shap_local_accuracy(small_model):
    bst, d, X = small_model
    contribs = bst.predict(d, pred_contribs=True)
    margin = bst.predict(d, output_margin=True)
    np.testing.assert_allclose(contribs.sum(axis=1), margin, rtol=1e-4, atol=1e-5)


def test_shap_matches_brute_force(small_model):
    bst, d, X = small_model
    from xgboost_tpu.interpret import shap_values_tree

    tree = bst.trees[0]
    rows = X[:5].astype(np.float64)
    fast = shap_values_tree(tree, rows)
    for r in range(5):
        brute = _brute_shapley(tree, rows[r], X.shape[1])
        np.testing.assert_allclose(fast[r], brute, rtol=1e-6, atol=1e-8)


def test_saabas_local_accuracy(small_model):
    bst, d, X = small_model
    contribs = bst.predict(d, pred_contribs=True, approx_contribs=True)
    margin = bst.predict(d, output_margin=True)
    np.testing.assert_allclose(contribs.sum(axis=1), margin, rtol=1e-4, atol=1e-5)


def test_shap_missing_values(small_model):
    bst, _, X = small_model
    Xm = X[:20].copy()
    Xm[np.random.default_rng(0).random(Xm.shape) < 0.4] = np.nan
    d = xtb.DMatrix(Xm)
    contribs = bst.predict(d, pred_contribs=True)
    margin = bst.predict(d, output_margin=True)
    np.testing.assert_allclose(contribs.sum(axis=1), margin, rtol=1e-4, atol=1e-5)


def test_interactions_sum_to_shap(small_model):
    bst, d, X = small_model
    inter = bst.predict(d.slice(range(8)), pred_interactions=True)
    contribs = bst.predict(d.slice(range(8)), pred_contribs=True)
    # contribs comes from the f32 device kernel, interactions from the host
    # f64 walk — tolerance covers the kernel's own f32 spec (see
    # test_device_shap_matches_host)
    np.testing.assert_allclose(inter.sum(axis=2), contribs, rtol=3e-4, atol=5e-5)


def test_device_shap_matches_host():
    """The batched device kernel (interpret/device.py) reproduces the host
    EXTEND/UNWIND recursion exactly (both implement path-dependent TreeSHAP)."""
    from xgboost_tpu.interpret import shap_values_tree
    from xgboost_tpu.interpret.device import shap_values_device

    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 6)).astype(np.float32)
    X[rng.random(X.shape) < 0.15] = np.nan
    y = (np.nan_to_num(X[:, 0]) * np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 5},
                    xtb.DMatrix(X, label=y), 4, verbose_eval=False)
    host = np.zeros((200, 7))
    for t in bst.trees:
        host += shap_values_tree(t, X.astype(np.float64))
    dev = shap_values_device(bst.trees, [1.0] * len(bst.trees), X)
    np.testing.assert_allclose(dev, host, rtol=2e-4, atol=2e-5)


def test_device_shap_throughput():
    """100k rows x a 40-tree ensemble completes in seconds (the round-1 host
    walk was ~minutes at this size — VERDICT 'unusable past 1e4 rows')."""
    import time

    rng = np.random.default_rng(1)
    X = rng.normal(size=(3000, 10)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] ** 2 > 0.5).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 6},
                    xtb.DMatrix(X, label=y), 40, verbose_eval=False)
    Xbig = rng.normal(size=(100_000, 10)).astype(np.float32)
    d = xtb.DMatrix(Xbig)
    t0 = time.time()
    contribs = bst.predict(d, pred_contribs=True)
    elapsed = time.time() - t0
    assert contribs.shape == (100_000, 11)
    # local accuracy at scale
    margins = bst.predict(d, output_margin=True)
    np.testing.assert_allclose(contribs.sum(1), margins, rtol=1e-3, atol=1e-3)
    assert elapsed < 120, f"device SHAP too slow: {elapsed:.1f}s"


def test_interactions_device_matches_host(small_model):
    """Batched device interaction kernel vs the python-loop host oracle
    (both verified cell-exact against the reference; see
    test_oracle_parity.py::test_interactions_parity)."""
    bst, d, X = small_model
    from xgboost_tpu.interpret import predict_interactions

    host = predict_interactions(bst, d, slice(None), use_device=False)
    dev = predict_interactions(bst, d, slice(None), use_device=True)
    np.testing.assert_allclose(dev, host, rtol=1e-4, atol=1e-5)


def test_interactions_categorical_host_path():
    """Categorical trees fall back to the cat-aware host implementation;
    rows must still sum to the SHAP contributions."""
    import pandas as pd

    rng = np.random.default_rng(4)
    n = 300
    codes = rng.integers(0, 5, n)
    num = rng.normal(size=n).astype(np.float32)
    y = ((codes % 2 == 0) + num * 0.5 + 0.1 * rng.normal(size=n)).astype(
        np.float32)
    df = pd.DataFrame({
        "c": pd.Categorical.from_codes(codes, list("abcde")),
        "x": num,
    })
    d = xtb.DMatrix(df, label=y, enable_categorical=True)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 3,
                     "max_cat_to_onehot": 1}, d, 3, verbose_eval=False)
    inter = bst.predict(d, pred_interactions=True)
    contribs = bst.predict(d, pred_contribs=True)
    np.testing.assert_allclose(inter.sum(axis=2), contribs,
                               rtol=1e-4, atol=1e-5)
