"""Performance observatory (ISSUE 17): sampling profiler, latency
exemplars, endpoint additions, and source staleness.

Quick tier: profiler lifecycle (idempotent start/stop, env gating,
fork-safe module state), folded-stack capture and the merged flame view,
exemplar observe -> render -> snapshot -> merged-render round-trip,
``/healthz`` + ``/flight`` endpoints and the unchanged 404 contract,
``stale="1"`` relabeling, and the headline determinism guarantee:
training with the profiler armed is bitwise-identical to training with
it off (sampling only reads frames).  Slow tier: a real 2-replica fleet
ships folded stacks from both replica processes into one merged view.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from xgboost_tpu.telemetry import distributed, profiler
from xgboost_tpu.telemetry.registry import Registry


@pytest.fixture(autouse=True)
def _profiler_reset():
    """Every test starts and ends with the sampler stopped and empty."""
    profiler.stop()
    profiler.clear()
    yield
    profiler.stop()
    profiler.clear()


# =========================================================================
# lifecycle


def test_start_stop_idempotent():
    assert profiler.start(hz=100) is True
    assert profiler.running()
    assert profiler.start(hz=100) is True  # second start: same sampler
    threads = [t for t in threading.enumerate()
               if t.name == "xtb-prof-sampler"]
    assert len(threads) == 1
    profiler.stop()
    assert not profiler.running()
    profiler.stop()  # second stop is a no-op
    assert not profiler.running()


def test_zero_hz_disables(monkeypatch):
    assert profiler.start(hz=0) is False
    assert not profiler.running()
    monkeypatch.setenv(profiler.ENV_HZ, "0")
    assert profiler.maybe_start() is False
    assert not profiler.running()


def test_configured_hz_parsing(monkeypatch):
    monkeypatch.delenv(profiler.ENV_HZ, raising=False)
    assert profiler.configured_hz() == profiler.DEFAULT_HZ
    monkeypatch.setenv(profiler.ENV_HZ, "2.5")
    assert profiler.configured_hz() == 2.5
    monkeypatch.setenv(profiler.ENV_HZ, "not-a-number")
    assert profiler.configured_hz() == profiler.DEFAULT_HZ
    monkeypatch.setenv(profiler.ENV_HZ, "-3")
    assert profiler.configured_hz() == 0.0


def test_sampler_captures_named_thread_stacks():
    stop = threading.Event()

    def very_distinctive_busy_fn():
        while not stop.is_set():
            sum(range(200))

    t = threading.Thread(target=very_distinctive_busy_fn,
                         name="busy-worker", daemon=True)
    t.start()
    try:
        profiler.start(hz=200)
        deadline = time.monotonic() + 5
        while profiler.samples() < 5 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        profiler.stop()
        stop.set()
        t.join(5)
    snap = profiler.folded_snapshot()
    assert snap is not None and snap["samples"] >= 5
    assert snap["pid"] > 0
    busy = [k for k in snap["stacks"] if k.startswith("busy-worker;")]
    assert busy, f"no busy-worker stacks in {list(snap['stacks'])[:5]}"
    assert any("very_distinctive_busy_fn" in k for k in busy)


def test_folded_snapshot_none_when_never_sampled():
    assert profiler.folded_snapshot() is None
    payload = distributed.snapshot_payload()
    assert "profile" not in payload


def test_clear_resets_but_keeps_sampler():
    profiler.start(hz=200)
    deadline = time.monotonic() + 5
    while profiler.samples() < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    profiler.clear()
    assert profiler.running()
    profiler.stop()


# =========================================================================
# merged flame view


def _fake_profile(pid, stacks):
    return {"pid": pid, "label": "x", "hz": 5.0,
            "samples": sum(stacks.values()), "stacks": stacks}


def test_merged_folded_prefixes_sources(tmp_path):
    m = distributed.get_merged()
    # hermetic against suite order: earlier fleet/distributed tests may
    # have left profile-bearing sources in the merged singleton
    for src in list(m.profiles()):
        m.forget(src)
    m.ingest_payload("replicaA", {
        "profile": _fake_profile(111, {"MainThread;a:f;b:g": 7})})
    m.ingest_payload("replicaB", {
        "profile": _fake_profile(222, {"MainThread;a:f;b:g": 3})})
    try:
        folded = profiler.merged_folded(include_local=False)
        assert folded["replicaA/111;MainThread;a:f;b:g"] == 7
        assert folded["replicaB/222;MainThread;a:f;b:g"] == 3
        text = profiler.render_folded(str(tmp_path / "folded.txt"),
                                      include_local=False)
        assert "10 weighted samples" in text
        lines = (tmp_path / "folded.txt").read_text().splitlines()
        assert "replicaA/111;MainThread;a:f;b:g 7" in lines
        assert "replicaB/222;MainThread;a:f;b:g 3" in lines
    finally:
        m.forget("replicaA")
        m.forget("replicaB")


def test_payload_ships_profile_when_sampled():
    profiler.start(hz=200)
    deadline = time.monotonic() + 5
    while profiler.samples() < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    profiler.stop()
    payload = distributed.snapshot_payload()
    assert payload["profile"]["samples"] >= 2
    json.dumps(payload)  # shippable as-is


# =========================================================================
# latency exemplars


def test_exemplar_renders_on_local_histogram():
    r = Registry()
    h = r.histogram("xtb_t_seconds", "latency", ("model",),
                    buckets=(0.015, 1.0))
    h.labels("m").observe(0.01, exemplar="tr-low")
    h.labels("m").observe(5.0, exemplar="tr-inf")
    text = r.render_prometheus()
    assert ('xtb_t_seconds_bucket{model="m",le="0.015"} 1 '
            '# {trace="tr-low"} 0.01') in text
    assert ('xtb_t_seconds_bucket{model="m",le="+Inf"} 2 '
            '# {trace="tr-inf"} 5') in text
    # the exemplar keeps the max-latency observation per bucket
    h.labels("m").observe(0.012, exemplar="tr-bigger")
    text = r.render_prometheus()
    assert '# {trace="tr-bigger"} 0.012' in text
    assert "tr-low" not in text


def test_exemplar_roundtrip_through_merged_registry():
    def mk(v, trace):
        r = Registry()
        r.histogram("xtb_t_seconds", "latency", ("model",),
                    buckets=(0.015, 1.0)).labels("m").observe(
                        v, exemplar=trace)
        return r.snapshot()

    m = distributed.MergedRegistry()
    m.ingest("r0", mk(0.2, "pid0-a"))
    m.ingest("r1", mk(0.9, "pid1-b"))
    text = m.render_prometheus(include_local=False)
    # per-process rows keep their own exemplars
    assert ('xtb_t_seconds_bucket{proc="r0",model="m",le="1"} 1 '
            '# {trace="pid0-a"} 0.2') in text
    assert ('xtb_t_seconds_bucket{proc="r1",model="m",le="1"} 1 '
            '# {trace="pid1-b"} 0.9') in text
    # the merged row carries the max-value exemplar across sources
    assert ('\nxtb_t_seconds_bucket{model="m",le="1"} 2 '
            '# {trace="pid1-b"} 0.9') in text


def test_histogram_without_exemplars_renders_unchanged():
    r = Registry()
    r.histogram("xtb_t_seconds", "latency", buckets=(1.0,)).observe(0.5)
    text = r.render_prometheus()
    assert '\nxtb_t_seconds_bucket{le="1"} 1\n' in text
    assert "trace=" not in text
    snap = r.snapshot()
    (fam,) = [f for f in snap["families"]
              if f["name"] == "xtb_t_seconds"]
    assert len(fam["children"][0]) == 4  # no 5th exemplar element


# =========================================================================
# endpoints: /healthz, /flight, 404 contract, staleness


def test_healthz_reports_source_staleness():
    m = distributed.MergedRegistry()
    m.ingest("fresh", Registry().snapshot())
    m.ingest("dead", Registry().snapshot())
    m._sources["dead"]["t"] = time.monotonic() - 10_000
    srv = distributed.MetricsServer(0, merged=m,
                                    include_local=False).start()
    try:
        body = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=10).read())
        assert body["status"] == "ok" and body["pid"] > 0
        assert body["stale_after_s"] == pytest.approx(
            3.0 * distributed.ship_interval())
        assert body["sources"]["fresh"]["stale"] is False
        assert body["sources"]["dead"]["stale"] is True
        assert body["sources"]["dead"]["age_s"] > 9_000
    finally:
        srv.close()


def test_flight_endpoint_serves_shipped_rings():
    m = distributed.MergedRegistry()
    m.ingest_payload("replica0", {
        "flight": [{"kind": "event", "name": "unit.flight", "t_mono": 1.0}]})
    srv = distributed.MetricsServer(0, merged=m,
                                    include_local=False).start()
    try:
        rings = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/flight", timeout=10).read())
        assert [e["name"] for e in rings["replica0"]] == ["unit.flight"]
    finally:
        srv.close()


def test_flight_endpoint_includes_local_ring():
    from xgboost_tpu.telemetry import flight

    flight.clear()
    flight.record("event", "unit.localflight")
    srv = distributed.MetricsServer(
        0, merged=distributed.MergedRegistry()).start()
    try:
        rings = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/flight", timeout=10).read())
        assert any(e["name"] == "unit.localflight"
                   for e in rings["driver"])
    finally:
        srv.close()
        flight.clear()


def test_unknown_route_still_404s():
    srv = distributed.MetricsServer(
        0, merged=distributed.MergedRegistry()).start()
    try:
        for route in ("/nope", "/healthz/extra", "/flightpath"):
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{route}", timeout=10)
    finally:
        srv.close()


def test_stale_source_gets_relabeled():
    m = distributed.MergedRegistry()

    def mk(v):
        r = Registry()
        r.counter("xtb_t_requests_total", "r", ("model",)).labels(
            "m").inc(v)
        return r.snapshot()

    m.ingest("live", mk(2))
    m.ingest("gone", mk(5))
    m._sources["gone"]["t"] = time.monotonic() - 10_000
    text = m.render_prometheus(include_local=False)
    assert ('xtb_t_requests_total{proc="live",model="m"} 2' in text)
    assert ('xtb_t_requests_total{proc="gone",stale="1",model="m"} 5'
            in text)
    # merged still includes the stale source (last-known-value semantics)
    assert '\nxtb_t_requests_total{model="m"} 7' in text


# =========================================================================
# determinism: profiler on == profiler off, bitwise


def test_training_bitwise_identical_with_profiler_on():
    import xgboost_tpu as xtb

    rng = np.random.default_rng(17)
    X = rng.normal(size=(600, 10)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    params = {"objective": "binary:logistic", "max_depth": 4,
              "seed": 17, "deterministic_histogram": 1}

    def run():
        bst = xtb.train(params, xtb.DMatrix(X, label=y), 4,
                        verbose_eval=False)
        return np.asarray(bst.predict(xtb.DMatrix(X))), bst.save_raw()

    profiler.stop()
    p_off, raw_off = run()
    assert profiler.start(hz=500)  # extreme rate: maximize interference
    try:
        p_on, raw_on = run()
        assert profiler.samples() > 0  # it really sampled during training
    finally:
        profiler.stop()
    assert raw_on == raw_off
    np.testing.assert_array_equal(p_on, p_off)


# =========================================================================
# slow: 2-replica fleet ships folded stacks from both processes


@pytest.mark.slow
def test_fleet_merged_profile_contains_both_replicas(monkeypatch):
    import xgboost_tpu as xtb
    from xgboost_tpu.serving import ServingFleet

    monkeypatch.setenv(profiler.ENV_HZ, "100")
    monkeypatch.setenv(distributed.ENV_INTERVAL, "0.2")
    m = distributed.get_merged()
    for src in list(m.profiles()):  # hermetic against suite order
        m.forget(src)
    rng = np.random.default_rng(5)
    X = rng.normal(size=(400, 8)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "seed": 5}, xtb.DMatrix(X, label=y), 3,
                    verbose_eval=False)
    with ServingFleet({"profm": bst}, n_replicas=2,
                      warmup_buckets=(64,)) as fleet:
        for _wave in range(3):
            futs = [fleet.submit("profm", X[:64]) for _ in range(12)]
            for f in futs:
                f.result(timeout=60)
            time.sleep(0.3)  # let periodic ships carry profiles
    # the close handshake ships each replica's final payload
    deadline = time.monotonic() + 30
    sources = set()
    while time.monotonic() < deadline:
        profs = distributed.get_merged().profiles()
        sources = {s for s in profs if s.startswith("replica")}
        if len(sources) >= 2:
            break
        time.sleep(0.05)
    assert len(sources) >= 2, f"profiles only from {sources}"
    profs = distributed.get_merged().profiles()
    pids = {profs[s]["pid"] for s in sources}
    assert len(pids) == 2  # genuinely two processes
    folded = profiler.merged_folded(include_local=False)
    for s in sources:
        tag = f"{s}/{profs[s]['pid']};"
        assert any(k.startswith(tag) for k in folded), f"no stacks for {s}"
    # and every shipped stack survived into the collapsed render
    text = profiler.render_folded(include_local=False)
    for s in sources:
        assert f"{s}/{profs[s]['pid']};" in text
