"""Learning-to-rank (reference: tests/python/test_ranking.py,
testing/data.py:813 make_ltr)."""
import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.metric import ndcg
from xgboost_tpu.testing.data import make_ltr


@pytest.fixture(scope="module")
def ltr():
    X, y, qid = make_ltr(40, 30, 8, seed=0)
    return X, y, qid


@pytest.mark.parametrize("obj", ["rank:ndcg", "rank:pairwise", "rank:map"])
def test_rank_objectives_improve(ltr, obj):
    X, y, qid = ltr
    d = xtb.DMatrix(X, label=y, qid=qid)
    res = {}
    xtb.train({"objective": obj, "max_depth": 4, "eta": 0.3,
               "lambdarank_num_pair_per_sample": 2}, d, 20,
              evals=[(d, "t")], evals_result=res, verbose_eval=False)
    metric = list(res["t"].keys())[0]
    vals = res["t"][metric]
    assert np.isfinite(vals).all()
    assert vals[-1] > vals[0]  # ndcg/map are maximized


def test_rank_requires_groups(ltr):
    X, y, _ = ltr
    d = xtb.DMatrix(X, label=y)  # no qid: degenerates to one big group
    bst = xtb.train({"objective": "rank:ndcg", "max_depth": 3}, d, 3,
                    verbose_eval=False)
    assert np.isfinite(bst.predict(d)).all()


def test_ranker_sklearn_with_eval(ltr):
    X, y, qid = ltr
    half = len(y) // 2
    rk = xtb.XGBRanker(n_estimators=10, max_depth=3)
    rk.fit(X[:half], y[:half], qid=qid[:half],
           eval_set=[(X[half:], y[half:])], eval_qid=[qid[half:]])
    assert rk.evals_result_  # eval history recorded
    d = xtb.DMatrix(X, label=y, qid=qid)
    score = ndcg(rk.predict(X), y, group_ptr=d.info.group_ptr)
    assert score > 0.85


def test_ndcg_at_k_metric(ltr):
    X, y, qid = ltr
    d = xtb.DMatrix(X, label=y, qid=qid)
    res = {}
    xtb.train({"objective": "rank:ndcg", "eval_metric": ["ndcg@5", "map@5"],
               "max_depth": 3}, d, 5, evals=[(d, "t")], evals_result=res,
              verbose_eval=False)
    assert "ndcg@5" in res["t"] and "map@5" in res["t"]
