"""Learning-to-rank (reference: tests/python/test_ranking.py,
testing/data.py:813 make_ltr)."""
import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.metric import ndcg
from xgboost_tpu.testing.data import make_ltr


@pytest.fixture(scope="module")
def ltr():
    X, y, qid = make_ltr(40, 30, 8, seed=0)
    return X, y, qid


@pytest.mark.parametrize("obj", ["rank:ndcg", "rank:pairwise", "rank:map"])
@pytest.mark.parametrize("method", ["topk", "mean"])
def test_rank_objectives_improve(ltr, obj, method):
    X, y, qid = ltr
    d = xtb.DMatrix(X, label=y, qid=qid)
    res = {}
    # defaults mirror the reference (ranking_utils.h): topk truncates at
    # k=32, mean samples 1 random different-label pair per doc per round
    xtb.train({"objective": obj, "max_depth": 4, "eta": 0.3,
               "lambdarank_pair_method": method}, d, 20,
              evals=[(d, "t")], evals_result=res, verbose_eval=False)
    metric = list(res["t"].keys())[0]
    vals = res["t"][metric]
    assert np.isfinite(vals).all()
    assert vals[-1] > vals[0]  # ndcg/map are maximized


def test_rank_requires_groups(ltr):
    X, y, _ = ltr
    d = xtb.DMatrix(X, label=y)  # no qid: degenerates to one big group
    bst = xtb.train({"objective": "rank:ndcg", "max_depth": 3}, d, 3,
                    verbose_eval=False)
    assert np.isfinite(bst.predict(d)).all()


def test_ranker_sklearn_with_eval(ltr):
    X, y, qid = ltr
    half = len(y) // 2
    rk = xtb.XGBRanker(n_estimators=10, max_depth=3)
    rk.fit(X[:half], y[:half], qid=qid[:half],
           eval_set=[(X[half:], y[half:])], eval_qid=[qid[half:]])
    assert rk.evals_result_  # eval history recorded
    d = xtb.DMatrix(X, label=y, qid=qid)
    score = ndcg(rk.predict(X), y, group_ptr=d.info.group_ptr)
    assert score > 0.85


def test_ndcg_at_k_metric(ltr):
    X, y, qid = ltr
    d = xtb.DMatrix(X, label=y, qid=qid)
    res = {}
    xtb.train({"objective": "rank:ndcg", "eval_metric": ["ndcg@5", "map@5"],
               "max_depth": 3}, d, 5, evals=[(d, "t")], evals_result=res,
              verbose_eval=False)
    assert "ndcg@5" in res["t"] and "map@5" in res["t"]


def test_metric_name_suffix_parsing():
    """``base[@n][-]`` parsing (reference: ranking_utils.cc:138
    ParseMetricName): truncation + the minus convention for degenerate
    groups (rank_metric.cc:382,:443)."""
    from xgboost_tpu.metric import create_metric

    s = np.array([0.9, 0.1, 0.8, 0.2], np.float64)
    # group 2 has no relevant doc: ndcg scores it 1 by default, 0 with '-'
    y = np.array([2.0, 1.0, 0.0, 0.0], np.float64)
    gp = np.array([0, 2, 4])
    for name, want in [("ndcg@2", 1.0), ("ndcg@2-", 0.5),
                       ("map", 1.0), ("map-", 0.5)]:
        fn, reported = create_metric(name)
        assert reported == name
        got = fn(s, y, None, group_ptr=gp)
        np.testing.assert_allclose(got, want, err_msg=name)

    fn, _ = create_metric("error@0.3")
    assert fn(np.array([0.4, 0.2]), np.array([1.0, 0.0]), None) == 0.0

    with pytest.raises(ValueError, match="Unknown metric"):
        create_metric("nope@2")


def test_aucpr_grouped_ranking_variant():
    """aucpr with query groups = mean of per-group PR areas over valid
    groups (auc.cc ranking Curve path), not one pooled curve."""
    from xgboost_tpu.metric import aucpr

    rng = np.random.default_rng(0)
    n_g, g_sz = 8, 30
    y = (rng.random(n_g * g_sz) < 0.3).astype(np.float64)
    s = y * 0.5 + rng.random(n_g * g_sz) * 0.5  # informative scores
    gp = np.arange(0, n_g * g_sz + 1, g_sz)
    grouped = aucpr(s, y, group_ptr=gp)
    pooled = aucpr(s, y)
    per_group = np.mean([aucpr(s[lo:hi], y[lo:hi])
                         for lo, hi in zip(gp[:-1], gp[1:])])
    np.testing.assert_allclose(grouped, per_group, rtol=1e-12)
    assert grouped != pooled  # actually a different quantity


def test_metric_suffix_validation_and_group_weights():
    from xgboost_tpu.metric import aucpr, create_metric

    # '-' only exists for rank metrics; '@' needs a number
    for bad in ("rmse-", "auc-", "error@0.3-", "ndcg@-"):
        with pytest.raises(ValueError):
            create_metric(bad)

    # grouped aucpr accepts per-group weights (the ndcg/map convention)
    rng = np.random.default_rng(2)
    y = (rng.random(60) < 0.4).astype(np.float64)
    s = y * 0.4 + rng.random(60) * 0.6
    gp = np.array([0, 20, 40, 60])
    wg = np.array([1.0, 2.0, 3.0])
    got = aucpr(s, y, weights=wg, group_ptr=gp)
    per = [aucpr(s[lo:hi], y[lo:hi]) for lo, hi in zip(gp[:-1], gp[1:])]
    np.testing.assert_allclose(got, np.average(per, weights=wg), rtol=1e-12)


def test_device_rank_parity():
    """Segment-vectorized device metrics (metric/device_rank.py) vs the
    python-loop host oracles, including @k and minus variants, group and
    per-row weights, all-irrelevant groups, and a size-1 group."""
    from xgboost_tpu.metric import map_metric, ndcg, precision_at

    rng = np.random.default_rng(5)
    G = 300
    sizes = rng.integers(1, 40, size=G)
    sizes[7] = 1
    ptr = np.concatenate([[0], np.cumsum(sizes)])
    R = ptr[-1]
    preds = rng.normal(size=R).astype(np.float32)
    labels = rng.integers(0, 5, size=R).astype(np.float32)
    labels[ptr[3]:ptr[4]] = 0.0          # all-irrelevant group
    gw = rng.uniform(0.5, 2.0, size=G).astype(np.float32)
    rw = rng.uniform(0.5, 2.0, size=R).astype(np.float32)

    for at in (0, 5):
        for minus in (False, True):
            for w in (None, gw, rw):
                for fn in (ndcg, map_metric):
                    host = fn(preds, labels, weights=w, group_ptr=ptr, at=at,
                              minus=minus, use_device_rank=False)
                    dev = fn(preds, labels, weights=w, group_ptr=ptr, at=at,
                             minus=minus, use_device_rank=True)
                    np.testing.assert_allclose(dev, host, rtol=2e-5,
                                               err_msg=f"{fn.__name__}@{at}"
                                               f" minus={minus}")
    for w in (None, gw, rw):
        host = precision_at(preds, labels, weights=w, group_ptr=ptr, at=7,
                            use_device_rank=False)
        dev = precision_at(preds, labels, weights=w, group_ptr=ptr, at=7,
                           use_device_rank=True)
        np.testing.assert_allclose(dev, host, rtol=2e-5)


def test_device_rank_mslr_scale_speed():
    """VERDICT r4 #6 bar: 30k groups x 100k docs evaluates in < 1 s/round
    once compiled (the python loop takes ~30s+ here)."""
    import time

    from xgboost_tpu.metric import ndcg

    rng = np.random.default_rng(6)
    G = 30_000
    sizes = rng.integers(1, 7, size=G)
    ptr = np.concatenate([[0], np.cumsum(sizes)])
    R = int(ptr[-1])
    preds = rng.normal(size=R).astype(np.float32)
    labels = rng.integers(0, 5, size=R).astype(np.float32)

    v1 = ndcg(preds, labels, group_ptr=ptr, at=10)   # warm-up (compile)
    t0 = time.perf_counter()
    v2 = ndcg(preds, labels, group_ptr=ptr, at=10)
    dt = time.perf_counter() - t0
    assert v1 == v2
    assert 0.0 < v2 <= 1.0
    assert dt < 1.0, f"device ndcg took {dt:.2f}s at MSLR scale"


def test_rank_mean_multi_pair_normalized(ltr):
    """mean method with num_pair > 1: gradients are averaged over the
    sampled pairs (1/n_pairs, lambdarank_obj.cc:230), so more pairs reduce
    sampling noise without inflating the step size — and training still
    improves the metric."""
    X, y, qid = ltr
    d = xtb.DMatrix(X, label=y, qid=qid)
    res = {}
    xtb.train({"objective": "rank:ndcg", "max_depth": 4, "eta": 0.3,
               "lambdarank_pair_method": "mean",
               "lambdarank_num_pair_per_sample": 4}, d, 20,
              evals=[(d, "t")], evals_result=res, verbose_eval=False)
    vals = res["t"]["ndcg"]
    assert np.isfinite(vals).all() and vals[-1] > vals[0]

    # the 1/n_pairs normalization bounds the per-round gradient magnitude:
    # a 4-pair gradient must not be ~4x the 1-pair gradient
    import jax.numpy as jnp

    from xgboost_tpu.objective import create_objective

    ptr = np.concatenate([[0], np.cumsum(np.bincount(qid))])
    g = {}
    for npair in (1, 4):
        obj = create_objective("rank:ndcg", {
            "lambdarank_pair_method": "mean",
            "lambdarank_num_pair_per_sample": npair})
        obj.set_group_info(ptr)
        gp = obj.get_gradient(jnp.zeros(len(y)), jnp.asarray(y), None, 0)
        g[npair] = float(jnp.abs(gp[:, 0, 0]).sum())
    assert g[4] < 2.0 * g[1], g
