"""Histogram kernel parity: Pallas (interpret mode on CPU) vs XLA vs numpy
(the per-kernel test pattern of the reference, tests/cpp/tree/gpu_hist/)."""
import numpy as np
import pytest

import jax.numpy as jnp

from xgboost_tpu.ops.histogram import build_histogram, node_sums
from xgboost_tpu.testing.reference import build_hist_np


def _mk(R=2048, F=6, B=16, n_nodes=4, node0=3, seed=0, with_missing=True):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, B + (1 if with_missing else 0), size=(R, F)).astype(np.int16)
    gpair = rng.normal(size=(R, 2)).astype(np.float32)
    pos = rng.integers(node0 - 1, node0 + n_nodes + 1, size=R).astype(np.int32)
    return bins, gpair, pos


def _np_hist(bins, gpair, pos, node0, n_nodes, B):
    N = n_nodes
    F = bins.shape[1]
    out = np.zeros((N, F, B, 2), np.float64)
    for n in range(N):
        rows = np.nonzero(pos == node0 + n)[0]
        out[n] = build_hist_np(bins, gpair.astype(np.float64), rows, B)
    return out


def test_xla_histogram_matches_numpy():
    bins, gpair, pos, = _mk()
    node0, n_nodes, B = 3, 4, 16
    hist = np.asarray(
        build_histogram(jnp.asarray(bins), jnp.asarray(gpair), jnp.asarray(pos),
                        node0=node0, n_nodes=n_nodes, n_bin=B, chunk=512)
    )
    ref = _np_hist(bins, gpair, pos, node0, n_nodes, B)
    np.testing.assert_allclose(hist, ref, rtol=1e-4, atol=1e-4)


def test_pallas_histogram_matches_xla_interpret():
    from xgboost_tpu.ops.hist_pallas import build_histogram_pallas

    bins, gpair, pos = _mk(R=1024, F=7, B=16, seed=3)  # F=7 exercises padding
    node0, n_nodes, B = 3, 4, 16
    xla = np.asarray(
        build_histogram(jnp.asarray(bins), jnp.asarray(gpair), jnp.asarray(pos),
                        node0=node0, n_nodes=n_nodes, n_bin=B)
    )
    pallas = np.asarray(
        build_histogram_pallas(jnp.asarray(bins), jnp.asarray(gpair),
                               jnp.asarray(pos), node0=node0, n_nodes=n_nodes,
                               n_bin=B, interpret=True)
    )
    np.testing.assert_allclose(pallas, xla, rtol=1e-4, atol=1e-4)


def test_node_sums_matches_numpy():
    bins, gpair, pos = _mk()
    sums = np.asarray(node_sums(jnp.asarray(gpair), jnp.asarray(pos), node0=3, n_nodes=4))
    for n in range(4):
        ref = gpair[pos == 3 + n].sum(axis=0)
        np.testing.assert_allclose(sums[n], ref, rtol=1e-4, atol=1e-4)


def test_missing_sentinel_excluded():
    R, F, B = 512, 3, 8
    bins = np.full((R, F), B, np.int16)  # everything missing
    gpair = np.ones((R, 2), np.float32)
    pos = np.zeros(R, np.int32)
    hist = np.asarray(
        build_histogram(jnp.asarray(bins), jnp.asarray(gpair), jnp.asarray(pos),
                        node0=0, n_nodes=1, n_bin=B)
    )
    assert np.all(hist == 0.0)
