"""Histogram kernel parity: Pallas (interpret mode on CPU) vs XLA vs numpy
(the per-kernel test pattern of the reference, tests/cpp/tree/gpu_hist/)."""
import numpy as np
import pytest

import jax.numpy as jnp

from xgboost_tpu.ops.histogram import build_histogram, node_sums
from xgboost_tpu.testing.reference import build_hist_np


def _mk(R=2048, F=6, B=16, n_nodes=4, node0=3, seed=0, with_missing=True):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, B + (1 if with_missing else 0), size=(R, F)).astype(np.int16)
    gpair = rng.normal(size=(R, 2)).astype(np.float32)
    pos = rng.integers(node0 - 1, node0 + n_nodes + 1, size=R).astype(np.int32)
    return bins, gpair, pos


def _np_hist(bins, gpair, pos, node0, n_nodes, B):
    N = n_nodes
    F = bins.shape[1]
    out = np.zeros((N, F, B, 2), np.float64)
    for n in range(N):
        rows = np.nonzero(pos == node0 + n)[0]
        out[n] = build_hist_np(bins, gpair.astype(np.float64), rows, B)
    return out


def test_xla_histogram_matches_numpy():
    bins, gpair, pos, = _mk()
    node0, n_nodes, B = 3, 4, 16
    hist = np.asarray(
        build_histogram(jnp.asarray(bins), jnp.asarray(gpair), jnp.asarray(pos),
                        node0=node0, n_nodes=n_nodes, n_bin=B, chunk=512)
    )
    ref = _np_hist(bins, gpair, pos, node0, n_nodes, B)
    np.testing.assert_allclose(hist, ref, rtol=1e-4, atol=1e-4)


def test_pallas_histogram_matches_xla_interpret():
    from xgboost_tpu.ops.hist_pallas import build_histogram_pallas

    bins, gpair, pos = _mk(R=1024, F=7, B=16, seed=3)  # F=7 exercises padding
    node0, n_nodes, B = 3, 4, 16
    xla = np.asarray(
        build_histogram(jnp.asarray(bins), jnp.asarray(gpair), jnp.asarray(pos),
                        node0=node0, n_nodes=n_nodes, n_bin=B)
    )
    pallas = np.asarray(
        build_histogram_pallas(jnp.asarray(bins), jnp.asarray(gpair),
                               jnp.asarray(pos), node0=node0, n_nodes=n_nodes,
                               n_bin=B, interpret=True)
    )
    np.testing.assert_allclose(pallas, xla, rtol=1e-4, atol=1e-4)


def test_node_sums_matches_numpy():
    bins, gpair, pos = _mk()
    sums = np.asarray(node_sums(jnp.asarray(gpair), jnp.asarray(pos), node0=3, n_nodes=4))
    for n in range(4):
        ref = gpair[pos == 3 + n].sum(axis=0)
        np.testing.assert_allclose(sums[n], ref, rtol=1e-4, atol=1e-4)


def test_stride_selects_left_children():
    """stride=2 (subtraction trick) == every other slot of the full build."""
    bins, gpair, pos = _mk(R=2048, F=5, B=16, n_nodes=8, node0=7, seed=5)
    full = np.asarray(
        build_histogram(jnp.asarray(bins), jnp.asarray(gpair), jnp.asarray(pos),
                        node0=7, n_nodes=8, n_bin=16)
    )
    left = np.asarray(
        build_histogram(jnp.asarray(bins), jnp.asarray(gpair), jnp.asarray(pos),
                        node0=7, n_nodes=4, n_bin=16, stride=2)
    )
    np.testing.assert_allclose(left, full[0::2], rtol=1e-5, atol=1e-5)


def test_pallas_row_padding():
    """Rows not a multiple of the 512 tile are padded internally (the round-1
    R % 512 assert is gone)."""
    from xgboost_tpu.ops.hist_pallas import build_histogram_pallas

    bins, gpair, pos = _mk(R=700, F=3, B=8, n_nodes=2, node0=1, seed=7)
    xla = np.asarray(
        build_histogram(jnp.asarray(bins), jnp.asarray(gpair), jnp.asarray(pos),
                        node0=1, n_nodes=2, n_bin=8)
    )
    pallas = np.asarray(
        build_histogram_pallas(jnp.asarray(bins), jnp.asarray(gpair),
                               jnp.asarray(pos), node0=1, n_nodes=2, n_bin=8,
                               interpret=True)
    )
    np.testing.assert_allclose(pallas, xla, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("sparsity", [0.0, 0.4])
def test_subtraction_trick_same_trees(sparsity):
    """Trees grown with the subtraction trick (right sibling = parent - left)
    choose the same splits as a direct rebuild of every node histogram
    (updater_gpu_hist.cu:309 SubtractHist)."""
    from xgboost_tpu.data.ellpack import build_ellpack
    from xgboost_tpu.data.quantile import sketch_dense
    from xgboost_tpu.ops.split import SplitParams
    from xgboost_tpu.tree.grow import HistTreeGrower

    rng = np.random.default_rng(11)
    R, F = 3000, 8
    X = rng.normal(size=(R, F)).astype(np.float32)
    if sparsity:
        X[rng.random((R, F)) < sparsity] = np.nan
    y = (np.nan_to_num(X[:, 0] * X[:, 1]) + np.nan_to_num(X[:, 2]) > 0)
    grad = (0.5 - y.astype(np.float32))
    gpair_np = np.stack([grad, np.full(R, 0.25, np.float32)], axis=1)

    cuts = sketch_dense(X, 16, use_device=False)
    ell = build_ellpack(X, cuts, row_align=64)
    gp = np.zeros((ell.n_padded, 2), np.float32)
    gp[:R] = gpair_np
    gp_j = jnp.asarray(gp)
    valid = jnp.arange(ell.n_padded) < R
    params = SplitParams(eta=0.3, gamma=0.0, min_child_weight=1.0,
                         lambda_=1.0, alpha=0.0, max_delta_step=0.0)

    states = {}
    for sub in (True, False):
        g = HistTreeGrower(6, params, subtract=sub)
        states[sub] = HistTreeGrower.to_host(
            g.grow(ell.bins, gp_j, valid, ell.cuts_pad, ell.n_bins))
    np.testing.assert_array_equal(states[True].feat, states[False].feat)
    np.testing.assert_array_equal(states[True].sbin, states[False].sbin)
    np.testing.assert_array_equal(states[True].is_leaf, states[False].is_leaf)
    np.testing.assert_allclose(states[True].leaf_val, states[False].leaf_val,
                               rtol=1e-4, atol=1e-5)


def test_missing_sentinel_excluded():
    R, F, B = 512, 3, 8
    bins = np.full((R, F), B, np.int16)  # everything missing
    gpair = np.ones((R, 2), np.float32)
    pos = np.zeros(R, np.int32)
    hist = np.asarray(
        build_histogram(jnp.asarray(bins), jnp.asarray(gpair), jnp.asarray(pos),
                        node0=0, n_nodes=1, n_bin=B)
    )
    assert np.all(hist == 0.0)


def test_matmul_and_scatter_impls_agree(monkeypatch):
    """Both histogram implementations stay CI-covered on any backend via
    the XTB_HIST_IMPL override, and agree to f32 rounding (bitwise for the
    quantised int path) — including stride, traced node0, and the
    above-chunk scan branch."""
    import jax.numpy as jnp

    # the UNJITTED accumulators: the env override is read at trace time, so
    # a cached jit entry point would ignore a flip between two calls
    from xgboost_tpu.ops.histogram import _hist_accumulate
    from xgboost_tpu.ops.quantise import (hist_accumulate_q, local_rho,
                                          quantise_gpair)

    rng = np.random.default_rng(9)
    R, F, B, N = 3000, 5, 16, 4
    bins = jnp.asarray(rng.integers(0, B + 1, size=(R, F)).astype(np.int32))
    gp = jnp.asarray(rng.normal(size=(R, 2)).astype(np.float32))
    pos = jnp.asarray(rng.integers(-1, 2 * N, size=R).astype(np.int32))
    rho = local_rho(gp, jnp.ones(R, bool))
    gq = quantise_gpair(gp, rho)

    outs = {}
    for impl in ("matmul", "scatter"):
        monkeypatch.setenv("XTB_HIST_IMPL", impl)
        outs[impl] = (
            np.asarray(_hist_accumulate(bins, gp, pos, jnp.int32(3), N, B,
                                        512, 2)),
            np.asarray(hist_accumulate_q(bins, gq, pos, jnp.int32(1), N, B,
                                         chunk=512)),
        )
    np.testing.assert_allclose(outs["matmul"][0], outs["scatter"][0],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(outs["matmul"][1], outs["scatter"][1])


def test_pallas_bench_shape_tiles_interpret():
    """VERDICT r4 #1a: exercise the EXACT tile geometry choose_tiles picks
    for the bench shapes (HIGGS 1Mx28 / covertype 58kx54, max_bin 256 ->
    B=257; (row_tile, feat_group) = (2048, 16) for both, verified below) in
    interpret mode — so the first real-TPU heal window runs a geometry the
    suite has already validated numerically, not a toy one.  Rows are
    reduced to 3 row tiles (tile geometry, padding and the cross-tile
    accumulate are row-count-invariant); the ragged final tile is included
    on purpose."""
    import numpy as np

    from xgboost_tpu.ops.hist_pallas import (build_histogram_pallas,
                                             choose_tiles)
    from xgboost_tpu.ops.histogram import build_histogram

    B = 257
    for F, n_nodes, stride in ((28, 16, 2), (28, 32, 1), (54, 64, 2)):
        T, FG = choose_tiles(F, B, n_nodes, 1)
        assert (T, FG) == (2048, 16), (F, n_nodes, T, FG)
        rng = np.random.default_rng(F)
        R = 2 * T + 517  # two full tiles + ragged remainder
        bins = jnp.asarray(rng.integers(0, B + 1, size=(R, F)), jnp.int32)
        gpair = jnp.asarray(rng.normal(size=(R, 2)), jnp.float32)
        node0 = n_nodes - 1 if stride == 1 else 2 * n_nodes - 1
        pos = jnp.asarray(
            rng.integers(node0, node0 + stride * n_nodes, size=R), jnp.int32)
        got = build_histogram_pallas(
            bins, gpair, pos, node0=node0, n_nodes=n_nodes, n_bin=B,
            stride=stride, interpret=True, row_tile=T, feat_group=FG)
        want = build_histogram(bins, gpair, pos, node0=node0,
                               n_nodes=n_nodes, n_bin=B, stride=stride)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-4)


def test_pallas_quantised_bench_shape_tiles_interpret():
    """Same geometry pin for the quantised (int8 limb) kernel — bitwise."""
    import numpy as np

    from xgboost_tpu.ops.hist_pallas import (build_histogram_pallas_q,
                                             choose_tiles)
    from xgboost_tpu.ops.quantise import (hist_accumulate_q, local_rho,
                                          quantise_gpair)

    B, F, n_nodes = 257, 28, 16
    T, FG = choose_tiles(F, B, n_nodes, 1, out_ch=6)
    assert (T, FG) == (2048, 16)
    rng = np.random.default_rng(3)
    R = 2 * T + 301
    bins = jnp.asarray(rng.integers(0, B + 1, size=(R, F)), jnp.int32)
    gpair = jnp.asarray(rng.normal(size=(R, 2)), jnp.float32)
    rho = local_rho(gpair, jnp.ones(R, bool))
    gq = quantise_gpair(gpair, rho)
    node0 = 2 * n_nodes - 1
    pos = jnp.asarray(rng.integers(node0, node0 + 2 * n_nodes, size=R),
                      jnp.int32)
    got = build_histogram_pallas_q(
        bins, gq, pos, node0=node0, n_nodes=n_nodes, n_bin=B, stride=2,
        interpret=True, row_tile=T, feat_group=FG)
    want = hist_accumulate_q(bins, gq, pos, jnp.int32(node0), n_nodes, B,
                             stride=2)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
