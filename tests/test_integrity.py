"""End-to-end data-integrity layer (docs/reliability.md "Integrity &
chaos"): every byte crossing a process or storage boundary is checksummed
and every ``corrupt``-kind injection at a wired boundary must be
*detected* — a typed error or a quarantined connection, never a silently
different result.  One test class per boundary: wire frames, tracker
messages, extmem pages, model arenas, checkpoints — plus the manifest
flock and the deterministic integrity-retry backoff."""
import json
import os
import socket
import struct
import threading
import zlib

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.reliability import faults
from xgboost_tpu.reliability.faults import FaultSpec, corrupt_bytes
from xgboost_tpu.serving import wire


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.clear()
    yield
    faults.clear()


def _counter_value(name, *labels):
    from xgboost_tpu.telemetry.registry import get_registry

    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    return sum(child.value for values, child in fam.collect()
               if not labels or tuple(values) == labels)


# ---------------------------------------------------------------------------
# corrupt_bytes: the one deterministic damage primitive
# ---------------------------------------------------------------------------

def test_corrupt_bytes_deterministic_and_parameterized():
    spec = FaultSpec("wire.frame", "corrupt")
    data = bytes(range(32))
    once = corrupt_bytes(data, spec)
    assert once == corrupt_bytes(data, spec), "must be a pure function"
    assert once != data and len(once) == len(data)
    assert once[16] == data[16] ^ 0xFF  # default: middle byte, full flip
    spec2 = FaultSpec("wire.frame", "corrupt", offset=3, xor_mask=0x01)
    assert corrupt_bytes(data, spec2)[3] == data[3] ^ 0x01
    # zero-effective mask falls back to 0xFF: never a silent no-op
    spec3 = FaultSpec("wire.frame", "corrupt", offset=0, xor_mask=0x100)
    assert corrupt_bytes(data, spec3)[0] == data[0] ^ 0xFF
    assert corrupt_bytes(b"", spec) == b""


# ---------------------------------------------------------------------------
# wire frames (fleet dispatcher <-> replica)
# ---------------------------------------------------------------------------

def _pair():
    a, b = socket.socketpair()
    return wire.configure(a), wire.configure(b)


def test_wire_crc_roundtrip_and_corrupt_detected():
    X = np.arange(24, dtype=np.float32).reshape(4, 6)
    fields, payload = wire.encode_raw(X)
    a, b = _pair()
    try:
        wire.send_frame(a, dict(fields, op="predict", id=1), payload)
        hdr, body = wire.recv_frame(wire.reader(b))
        np.testing.assert_array_equal(wire.decode_matrix(hdr, body), X)

        before = _counter_value("xtb_integrity_corrupt_total", "wire")
        faults.install({"faults": [
            {"site": "wire.frame", "kind": "corrupt"}]})
        wire.send_frame(a, dict(fields, op="predict", id=2), payload)
        faults.clear()
        with pytest.raises(wire.WireCorruptError):
            wire.recv_frame(b)
        assert _counter_value("xtb_integrity_corrupt_total",
                              "wire") == before + 1
    finally:
        a.close()
        b.close()


def test_wire_corrupt_header_region_detected():
    """A flip landing in the tiny JSON header (offset 0 of the covered
    region) is caught by the same CRC — the header is never decoded."""
    a, b = _pair()
    try:
        faults.install({"faults": [
            {"site": "wire.frame", "kind": "corrupt", "offset": 0}]})
        wire.send_frame(a, {"op": "predict", "id": 3})
        faults.clear()
        with pytest.raises(wire.WireCorruptError):
            wire.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_wire_fuzz_truncated_header():
    a, b = _pair()
    try:
        # prefix promises a 64-byte header; only 10 arrive before EOF
        a.sendall(wire._PREFIX.pack(64, 0, 0) + b"x" * 10)
        a.close()
        with pytest.raises(wire.WireError):
            wire.recv_frame(b)
    finally:
        b.close()


def test_wire_fuzz_oversized_length_prefixes():
    for hlen, plen in ((wire.MAX_HEADER + 1, 0),
                       (8, wire.MAX_PAYLOAD + 1),
                       (0xFFFFFFFF, 0), (8, 1 << 62)):
        a, b = _pair()
        try:
            a.sendall(wire._PREFIX.pack(hlen, plen, 0) + b"x" * 8)
            # the reader must refuse BEFORE allocating plen bytes
            with pytest.raises(wire.WireError):
                wire.recv_frame(b)
        finally:
            a.close()
            b.close()


def test_wire_fuzz_non_json_header_bytes():
    hdr = b"\xff\xfe\x00 not json at all"
    a, b = _pair()
    try:
        a.sendall(wire._PREFIX.pack(len(hdr), 0, zlib.crc32(hdr)) + hdr)
        with pytest.raises(wire.WireError):  # never a raw json exception
            wire.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_wire_fuzz_non_object_json_header():
    hdr = b"[1, 2, 3]"
    a, b = _pair()
    try:
        a.sendall(wire._PREFIX.pack(len(hdr), 0, zlib.crc32(hdr)) + hdr)
        with pytest.raises(wire.WireError):
            wire.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_wire_fuzz_mid_payload_eof():
    X = np.zeros((64, 8), np.float32)
    fields, payload = wire.encode_raw(X)
    a, b = _pair()
    try:
        hdr = json.dumps(dict(fields, op="predict")).encode()
        crc = zlib.crc32(payload, zlib.crc32(hdr))
        a.sendall(wire._PREFIX.pack(len(hdr), len(payload), crc) + hdr
                  + bytes(payload)[: len(payload) // 2])
        a.close()
        with pytest.raises(wire.WireError):
            wire.recv_frame(b)
    finally:
        b.close()


# ---------------------------------------------------------------------------
# tracker / relay messages
# ---------------------------------------------------------------------------

def test_tracker_msg_crc_roundtrip_and_corrupt():
    from xgboost_tpu import tracker as tr

    a, b = socket.socketpair()
    try:
        tr.send_msg(a, {"cmd": "coll", "seq": 4})
        assert tr.recv_msg(b) == {"cmd": "coll", "seq": 4}
        faults.install({"faults": [
            {"site": "tracker.message", "kind": "corrupt"}]})
        tr.send_msg(a, {"cmd": "coll", "seq": 5})
        faults.clear()
        # quarantined like a dropped connection: ConnectionError, which
        # every caller already treats as peer-gone
        with pytest.raises(ConnectionError):
            tr.recv_msg(b)
    finally:
        a.close()
        b.close()


def test_tracker_msg_oversized_length_prefix():
    from xgboost_tpu import tracker as tr

    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack(">II", tr.MAX_MSG + 1, 0))
        with pytest.raises(ConnectionError):
            tr.recv_msg(b)
    finally:
        a.close()
        b.close()


def test_relay_payload_crc_rejects_damaged_gather():
    """The relay's raw binary leg: a coll_result whose payload does not
    match the advertised CRC must fail the connection, never reach the
    histogram fold."""
    from xgboost_tpu import tracker as tr

    a, b = socket.socketpair()
    try:
        payload = np.arange(16, dtype=np.float64).tobytes()
        damaged = corrupt_bytes(payload, FaultSpec("tracker.message",
                                                   "corrupt"))
        tr.send_msg(a, {"cmd": "coll_result", "seq": 0,
                        "nbytes": len(payload),
                        "crc": zlib.crc32(payload)})
        a.sendall(damaged)
        hdr = tr.recv_msg(b)
        buf = tr._recv_exact(b, int(hdr["nbytes"]), timeout=5.0)
        assert zlib.crc32(buf) != hdr["crc"], \
            "the client-side check must be able to see the mismatch"
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# extmem pages
# ---------------------------------------------------------------------------

@pytest.fixture()
def _no_page_cache(monkeypatch):
    # disable the host page cache so every touch pays (and verifies) a
    # decode — the cache would otherwise serve the first verified copy
    monkeypatch.setenv("XTB_EXTMEM_HOST_CACHE_MB", "0")


def test_disk_page_transient_corruption_retries_clean(_no_page_cache,
                                                      tmp_path):
    from xgboost_tpu.data.extmem import DiskPage

    arr = np.arange(4096, dtype=np.uint8).reshape(64, 64)
    pg = DiskPage(arr, str(tmp_path / "p.npy"))
    before = _counter_value("xtb_integrity_retry_total", "page")
    faults.install({"faults": [
        {"site": "extmem.page_decode", "kind": "corrupt"}]})
    out = np.asarray(pg)  # attempt 0 corrupted -> detected -> re-read
    faults.clear()
    np.testing.assert_array_equal(out, arr)
    assert _counter_value("xtb_integrity_retry_total",
                          "page") == before + 1


def test_disk_page_persistent_corruption_fails_loud(_no_page_cache,
                                                    tmp_path):
    from xgboost_tpu.data.extmem import DiskPage, PageCorruptError

    arr = np.arange(4096, dtype=np.uint8).reshape(64, 64)
    path = str(tmp_path / "p.npy")
    pg = DiskPage(arr, path)
    with open(path, "r+b") as fh:  # damage a data byte on disk
        fh.seek(200)
        b = fh.read(1)
        fh.seek(200)
        fh.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(PageCorruptError):
        np.asarray(pg)


def test_disk_page_truncated_file_fails_loud(_no_page_cache, tmp_path):
    from xgboost_tpu.data.extmem import DiskPage, PageCorruptError

    arr = np.arange(4096, dtype=np.uint8).reshape(64, 64)
    path = str(tmp_path / "p.npy")
    pg = DiskPage(arr, path)
    with open(path, "r+b") as fh:
        fh.truncate(os.path.getsize(path) // 2)
    with pytest.raises(PageCorruptError):
        np.asarray(pg)


def test_extmem_training_with_transient_corruption_is_bitwise(tmp_path):
    """The whole-stack contract at this boundary: a transient decode
    corruption mid-training is detected, retried, and the final model is
    bitwise what an undisturbed run produces."""
    from xgboost_tpu.data.extmem import _zstd_available

    rng = np.random.default_rng(5)
    Xs = [rng.standard_normal((500, 6)).astype(np.float32)
          for _ in range(2)]
    ys = [(X[:, 0] > 0).astype(np.float32) for X in Xs]

    class It(xtb.DataIter):
        def __init__(self):
            super().__init__()
            self.i = 0

        def next(self, input_data):
            if self.i >= len(Xs):
                return 0
            input_data(data=Xs[self.i], label=ys[self.i])
            self.i += 1
            return 1

        def reset(self):
            self.i = 0

    params = {"objective": "binary:logistic", "max_depth": 3,
              "max_bin": 32}

    def run(with_fault):
        if with_fault:
            faults.install({"faults": [
                {"site": "extmem.page_decode", "kind": "corrupt"}]})
        try:
            d = xtb.ExtMemQuantileDMatrix(It(), max_bin=32, on_host=False,
                                          compress=_zstd_available())
            bst = xtb.train(params, d, 4, verbose_eval=False)
            return bytes(bst.serialize())
        finally:
            faults.clear()

    assert run(True) == run(False)


# --- compressed (zstd) page legs: importorskip-guarded like
# --- test_page_compression; the DiskPage legs above cover zstd-less envs
def test_zstd_page_truncated_stream_fails_loud(_no_page_cache, tmp_path):
    pytest.importorskip("zstandard",
                        reason="zstandard not installed: compressed-page "
                               "corruption path not reachable")
    from xgboost_tpu.data.extmem import CompressedPage, PageCorruptError

    arr = np.arange(8192, dtype=np.uint16).reshape(64, 128)
    pg = CompressedPage(arr)
    np.testing.assert_array_equal(np.asarray(pg), arr)
    pg._blob = pg._blob[: len(pg._blob) // 2]  # truncated zstd stream
    with pytest.raises(PageCorruptError):
        np.asarray(pg)


def test_zstd_page_bitflipped_stream_fails_loud(_no_page_cache, tmp_path):
    pytest.importorskip("zstandard",
                        reason="zstandard not installed: compressed-page "
                               "corruption path not reachable")
    from xgboost_tpu.data.extmem import CompressedPage, PageCorruptError

    arr = np.arange(8192, dtype=np.uint16).reshape(64, 128)
    path = str(tmp_path / "p.zst")
    pg = CompressedPage(arr, path=path)
    with open(path, "r+b") as fh:  # flip one byte mid-stream on disk
        fh.seek(os.path.getsize(path) // 2)
        b = fh.read(1)
        fh.seek(os.path.getsize(path) // 2)
        fh.write(bytes([b[0] ^ 0x10]))
    with pytest.raises(PageCorruptError):
        np.asarray(pg)


def test_zstd_page_transient_decode_corruption_retries(_no_page_cache):
    pytest.importorskip("zstandard",
                        reason="zstandard not installed: compressed-page "
                               "corruption path not reachable")
    from xgboost_tpu.data.extmem import CompressedPage

    arr = np.arange(8192, dtype=np.uint16).reshape(64, 128)
    pg = CompressedPage(arr)
    faults.install({"faults": [
        {"site": "extmem.page_decode", "kind": "corrupt"}]})
    out = np.asarray(pg)
    faults.clear()
    np.testing.assert_array_equal(out, arr)


# ---------------------------------------------------------------------------
# model arenas (store + replica attach)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def _booster():
    rng = np.random.default_rng(0)
    X = rng.standard_normal((200, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    return xtb.train({"objective": "binary:logistic", "max_depth": 2},
                     xtb.DMatrix(X, label=y), 2, verbose_eval=False)


def test_publish_corrupt_seam_detected_and_scrubbed(_booster, tmp_path):
    from xgboost_tpu.serving.modelstore import ModelStore

    store = ModelStore(str(tmp_path / "store"))
    v1 = store.publish("m", _booster)
    faults.install({"faults": [
        {"site": "modelstore.publish", "kind": "corrupt"}]})
    v2 = store.publish("m", _booster)
    faults.clear()
    assert store.verify_checksum("m", v1) is True
    assert store.verify_checksum("m", v2) is False
    scrub = store.scrub()
    assert ("m", v2) in scrub["corrupt"]
    assert ("m", v1) in scrub["verified"]


def test_replica_attach_refuses_corrupt_arena(_booster, tmp_path):
    from xgboost_tpu.serving.modelstore import ArenaCorruptError, ModelStore
    from xgboost_tpu.serving.replica import _verify_arena

    store = ModelStore(str(tmp_path / "store"))
    faults.install({"faults": [
        {"site": "modelstore.publish", "kind": "corrupt"}]})
    v = store.publish("m", _booster)
    faults.clear()
    with pytest.raises(ArenaCorruptError):
        _verify_arena(store, "m", v)


def test_arena_file_damage_detected_by_scrub(_booster, tmp_path):
    """Out-of-band damage (not the seam): flip one byte of a published
    arena file — the scrub and re-verification must catch it."""
    from xgboost_tpu.serving.modelstore import ModelStore

    store = ModelStore(str(tmp_path / "store"))
    v = store.publish("m", _booster)
    arena = str(tmp_path / "store" / f"m.v{v}.arena")
    with open(arena, "r+b") as fh:
        b = fh.read(1)
        fh.seek(0)
        fh.write(bytes([b[0] ^ 0xFF]))
    assert store.verify_checksum("m", v) is False
    assert ("m", v) in store.scrub()["corrupt"]


# ---------------------------------------------------------------------------
# manifest flock (concurrent lifecycle managers)
# ---------------------------------------------------------------------------

def test_manifest_flock_two_writer_contention(_booster, tmp_path):
    """Two concurrent publishers + activators over ONE store: every
    publish must get a distinct version and the final manifest must be
    internally consistent — the PR-9 follow-up that motivated the lock."""
    from xgboost_tpu.serving.modelstore import ModelStore

    store = ModelStore(str(tmp_path / "store"))
    versions, errors = [], []

    def manager(k):
        try:
            mine = []
            for _ in range(6):
                v = store.publish("m", _booster)
                mine.append(v)
                store.set_active("m", v)
            versions.extend(mine)
        except Exception as e:  # pragma: no cover - the failure signal
            errors.append(e)

    ts = [threading.Thread(target=manager, args=(k,)) for k in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors
    assert sorted(versions) == list(range(1, 13)), \
        "concurrent publishes interleaved into duplicate versions"
    assert store.latest_version("m") == 12
    active = store.active_version("m")
    assert active in versions
    # every version's files exist and verify (no overwrite corruption)
    assert store.scrub()["corrupt"] == []


def test_manifest_lock_gauge_returns_to_zero(_booster, tmp_path):
    from xgboost_tpu.serving.modelstore import ModelStore, _lock_ins

    store = ModelStore(str(tmp_path / "store"))
    store.publish("m", _booster)
    store.set_active("m", 1)
    held, _waited = _lock_ins()
    assert held.labels().value == 0.0, \
        "xtb_store_lock_held must drop back to 0 after every mutation"


# ---------------------------------------------------------------------------
# checkpoints
# ---------------------------------------------------------------------------

def test_checkpoint_corrupt_kind_and_scrubber(tmp_path):
    from xgboost_tpu.reliability.checkpoint import (CheckpointManager,
                                                    CheckpointState,
                                                    scrub_dir)

    mgr = CheckpointManager(str(tmp_path))
    faults.install({"faults": [
        {"site": "checkpoint.write", "kind": "corrupt", "round": 2}]})
    for r in (1, 2, 3):
        mgr.save(CheckpointState(round=r, booster_bytes=b"B" * 64,
                                 history={}, callback_state={}))
    faults.clear()
    scrub = scrub_dir(str(tmp_path))
    assert len(scrub["corrupt"]) == 1 and "00000002" in scrub["corrupt"][0]
    assert len(scrub["valid"]) == 2
    # load-side detection: the damaged round-2 file is skipped, round 3
    # (then round 1 if 3 were also bad) serves the resume
    with pytest.warns(RuntimeWarning, match="invalid checkpoint"):
        # walk starts at round 3 (valid): force it past the corrupt one
        files = mgr.files()
        os.unlink(files[-1])  # drop round 3 so the walk hits round 2
        state = mgr.load_latest()
    assert state is not None and state.round == 1


# ---------------------------------------------------------------------------
# deterministic integrity-retry backoff (regression pin)
# ---------------------------------------------------------------------------

def test_integrity_backoff_deterministic_per_op_and_attempt():
    from xgboost_tpu.reliability.retry import backoff_delays

    # pinned values: the page-retry stream (op="integrity.page", seed=0)
    pinned = [0.004951589, 0.0096470574, 0.0201784396, 0.0469169858]
    got = [round(d, 10) for d in backoff_delays(
        4, base=0.005, max_delay=0.05, op="integrity.page", seed=0)]
    assert got == pinned, got
    # per-(op, seed) streams are independent: interleaving draws from a
    # second generator (the fault plan's, another seam's) must not
    # perturb the sequence
    g1 = backoff_delays(4, base=0.005, max_delay=0.05,
                        op="integrity.page", seed=0)
    g2 = backoff_delays(4, op="extmem.page_decode", seed=3)
    interleaved = []
    for _ in range(4):
        interleaved.append(round(next(g1), 10))
        next(g2)
    assert interleaved == pinned
    # and the other stream is ITSELF deterministic
    assert [round(d, 10) for d in backoff_delays(
        4, op="extmem.page_decode", seed=3)] == \
        [0.0476010806, 0.0834965937, 0.2426515146, 0.4889151515]


# ---------------------------------------------------------------------------
# fleet-level: one poisoned connection never takes the fleet (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_survives_garbage_connection_and_scrub_quarantine(_booster):
    """Two fleet-level integrity contracts in one bring-up (they are
    expensive): (1) raw garbage thrown at the dispatcher's listener fails
    that one connection, not the fleet; (2) after on-disk arena damage, a
    broadcast scrub makes the replica quarantine itself — recorded with a
    reason, traffic rerouted to the death path, never served corrupt."""
    import time as _time

    from xgboost_tpu.serving.fleet import FleetConfig, ServingFleet
    from xgboost_tpu.launcher import WorkerFailedError

    cfg = FleetConfig(n_replicas=1, max_respawns=0, nthread_per_replica=1)
    fleet = ServingFleet({"m": _booster}, cfg).start()
    try:
        rng = np.random.default_rng(1)
        Q = rng.standard_normal((8, 4)).astype(np.float32)
        expected = fleet.predict("m", Q, timeout=120)

        # (1) garbage connections: oversized prefix, raw noise, instant EOF
        port = fleet._listener.getsockname()[1]
        for garbage in (wire._PREFIX.pack(wire.MAX_HEADER + 1, 0, 0),
                        b"\x00" * 64, b""):
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            if garbage:
                s.sendall(garbage)
            s.close()
        np.testing.assert_array_equal(
            fleet.predict("m", Q, timeout=120), expected)

        # (2) damage the arena on disk; the scrub broadcast must end in a
        # quarantine, not a wrong answer
        arena = os.path.join(fleet.store_dir, "m.v1.arena")
        with open(arena, "r+b") as fh:
            b = fh.read(1)
            fh.seek(0)
            fh.write(bytes([b[0] ^ 0xFF]))
        acks = fleet.scrub_replicas(timeout=120)
        assert acks == [], f"corrupt replica acked a scrub: {acks}"
        deadline = _time.monotonic() + 60
        while (not fleet.quarantined_replicas()
               and _time.monotonic() < deadline):
            _time.sleep(0.05)
        quarantined = fleet.quarantined_replicas()
        assert quarantined, "replica never quarantined itself"
        assert "checksum" in next(iter(quarantined.values()))
        # with no respawn budget the fleet is extinct — new work fails
        # FAST and LOUD, carrying the quarantine reason
        deadline = _time.monotonic() + 60
        while _time.monotonic() < deadline:
            try:
                fleet.predict("m", Q, timeout=5)
            except (WorkerFailedError, TimeoutError, RuntimeError):
                break
            _time.sleep(0.05)
        else:
            pytest.fail("corrupt fleet kept serving")
    finally:
        fleet.close()
