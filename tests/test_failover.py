"""Coordinator failover (docs/reliability.md "Coordinator failover &
watchdog"): the tracker journals its replayable state, a respawned
tracker recovers it and re-adopts the surviving workers, and a
SIGKILL'd coordinator mid-round costs a bounded pause — with model bytes
bitwise-identical to an undisturbed run.
"""
import functools
import json
import os
import socket
import threading

import pytest

from xgboost_tpu.reliability.journal import TrackerJournal
from xgboost_tpu.tracker import RabitTracker, recv_msg, send_msg


# ---------------------------------------------------------------------------
# journal format
# ---------------------------------------------------------------------------

def test_journal_roundtrip_last_record_wins(tmp_path):
    p = str(tmp_path / "t.xtbjrnl")
    j = TrackerJournal(p)
    assert j.load() is None
    j.append({"epoch": 0, "members": {"0": {"round": 0}}})
    j.append({"epoch": 1, "members": {"0": {"round": 3},
                                      "1": {"round": 3}}})
    st = TrackerJournal(p).load()
    assert st["epoch"] == 1 and st["members"]["1"]["round"] == 3


def test_journal_torn_tail_falls_back_to_previous_record(tmp_path):
    p = str(tmp_path / "t.xtbjrnl")
    j = TrackerJournal(p)
    j.append({"epoch": 0})
    j.append({"epoch": 1})
    with open(p, "r+b") as fh:  # SIGKILL mid-append: half a record
        fh.seek(-5, os.SEEK_END)
        fh.truncate()
    assert TrackerJournal(p).load()["epoch"] == 0


def test_journal_corrupt_record_fails_crc_walk(tmp_path):
    p = str(tmp_path / "t.xtbjrnl")
    j = TrackerJournal(p)
    j.append({"epoch": 0})
    j.append({"epoch": 1})
    blob = bytearray(open(p, "rb").read())
    blob[-3] ^= 0xFF  # bit rot inside the LAST record's payload
    open(p, "wb").write(bytes(blob))
    assert TrackerJournal(p).load()["epoch"] == 0


def test_journal_repair_makes_post_tear_appends_reachable(tmp_path):
    """Without the recovery-time truncation, a record appended after a
    torn tail would be permanently invisible to the next walk."""
    p = str(tmp_path / "t.xtbjrnl")
    j = TrackerJournal(p)
    j.append({"epoch": 0})
    j.append({"epoch": 1})
    with open(p, "r+b") as fh:  # tear the SECOND record's tail
        fh.seek(-4, os.SEEK_END)
        fh.truncate()
    j2 = TrackerJournal(p)
    assert j2.load(repair=True)["epoch"] == 0  # truncates the torn tail
    j2.append({"epoch": 5})
    assert TrackerJournal(p).load()["epoch"] == 5


def test_journal_corrupt_fault_seam_damages_exactly_one_record(tmp_path):
    from xgboost_tpu.reliability import faults

    p = str(tmp_path / "t.xtbjrnl")
    j = TrackerJournal(p)
    j.append({"epoch": 0})
    faults.install({"faults": [{"site": "tracker.journal",
                                "kind": "corrupt"}]})
    try:
        j.append({"epoch": 1})  # damaged on its way to disk
    finally:
        faults.clear()
    assert TrackerJournal(p).load()["epoch"] == 0
    j.append({"epoch": 2})  # next append without repair...
    # ...is unreachable past the damaged record: the repairing loader is
    # what recovery uses
    assert TrackerJournal(p).load()["epoch"] == 0
    assert TrackerJournal(p).load(repair=True)["epoch"] == 0
    j.append({"epoch": 3})
    assert TrackerJournal(p).load()["epoch"] == 3


def test_journal_compaction_preserves_last_state(tmp_path):
    from xgboost_tpu.reliability import journal as jmod

    p = str(tmp_path / "t.xtbjrnl")
    j = TrackerJournal(p)
    for i in range(jmod.COMPACT_EVERY + 3):
        j.append({"epoch": i})
    assert TrackerJournal(p).load()["epoch"] == jmod.COMPACT_EVERY + 2
    # compacted: far smaller than the record count implies
    assert os.path.getsize(p) < 80 * (jmod.COMPACT_EVERY + 3)


# ---------------------------------------------------------------------------
# recovery protocol (in-process, raw-socket fake workers)
# ---------------------------------------------------------------------------

def _rendezvous(tracker, n):
    """Fake-worker rendezvous; returns {rank: socket}."""
    socks = {}

    def worker(tag, idx):
        s = socket.create_connection(("127.0.0.1", tracker.port),
                                     timeout=30)
        send_msg(s, {"cmd": "start", "host": tag})
        reply = recv_msg(s)
        if reply.get("coordinator") is None:
            send_msg(s, {"cmd": "coordinator", "addr": "127.0.0.1:45678"})
        socks[reply["rank"]] = (s, reply)

    threads = [threading.Thread(target=worker, args=(chr(97 + idx), idx))
               for idx in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert len(socks) == n, "rendezvous did not complete"
    return socks


def test_recovered_tracker_readopts_and_forms_next_epoch(tmp_path):
    """The re-adoption protocol without subprocesses: rendezvous under a
    journaling tracker, hard-stop it (no clean shutdown), start a fresh
    tracker on the same journal + port, readopt both ranks, regroup —
    the epoch bumps and the resume round is the max of the joins."""
    journal = str(tmp_path / "t.xtbjrnl")
    tr = RabitTracker(n_workers=2, host_ip="127.0.0.1", elastic=True,
                      journal=journal)
    tr.start()
    socks = _rendezvous(tr, 2)
    assert all(r["failover"] for (_s, r) in socks.values())
    port = tr.port
    for s, _r in socks.values():
        s.close()  # the old channels die with the old tracker
    tr.free()  # hard stop: no shutdown messages were sent

    tr2 = RabitTracker(n_workers=2, host_ip="127.0.0.1", port=port,
                       elastic=True, journal=journal)
    assert tr2._recovered is not None
    assert tr2.port == port
    tr2.start()
    results = {}

    def readopt(rank, round_):
        s = socket.create_connection(("127.0.0.1", port), timeout=30)
        send_msg(s, {"cmd": "readopt", "rank": rank, "epoch": 0,
                     "round": round_})
        reply = recv_msg(s, timeout=30.0)
        assert reply["cmd"] == "readopted", reply
        send_msg(s, {"cmd": "regroup_join", "round": round_})
        while True:
            m = recv_msg(s, timeout=30.0)
            if m is None or m.get("cmd") == "regroup":
                results[rank] = (m, s)
                break

    ts = [threading.Thread(target=readopt, args=(r, 2 + r))
          for r in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    try:
        m0, m1 = results[0][0], results[1][0]
        assert m0 and m1
        assert {m0["rank"], m1["rank"]} == {0, 1}
        assert m0["world"] == 2
        assert m0["epoch"] == 1  # journaled epoch 0 + 1
        assert m0["round"] == 3  # max of the joins
        # the committed epoch is journaled for the NEXT respawn (read it
        # BEFORE the clean shutdowns shrink the roster again)
        st = TrackerJournal(journal).load()
        assert st["epoch"] == 1 and set(st["members"]) == {"0", "1"}
    finally:
        for _m, s in results.values():
            try:
                send_msg(s, {"cmd": "shutdown"})
                s.close()
            except OSError:
                pass
        tr2.free()


def test_readopt_refused_outside_recovery(tmp_path):
    """A rank declared dead (or a stray readopt to a healthy tracker)
    must not resurrect into a formed epoch."""
    journal = str(tmp_path / "t.xtbjrnl")
    tr = RabitTracker(n_workers=2, host_ip="127.0.0.1", elastic=True,
                      journal=journal)
    tr.start()
    socks = _rendezvous(tr, 2)
    try:
        s = socket.create_connection(("127.0.0.1", tr.port), timeout=30)
        send_msg(s, {"cmd": "readopt", "rank": 0, "epoch": 0})
        reply = recv_msg(s, timeout=30.0)
        assert reply and reply["cmd"] == "abort"
        s.close()
    finally:
        for sk, _r in socks.values():
            send_msg(sk, {"cmd": "shutdown"})
            sk.close()
        tr.free()


# ---------------------------------------------------------------------------
# end to end: SIGKILL the tracker mid-round, bitwise model parity
# ---------------------------------------------------------------------------

def _failover_worker(rank, world, *, ckpt_dir, out_path, rounds,
                     num_shards):
    import numpy as np

    import xgboost_tpu as xtb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(1500, 6)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)

    def data_fn(smap, rank, world):
        rows = np.sort(np.concatenate(
            [np.arange(s, len(X), smap.num_shards)
             for s in smap.shards_of(rank)]))
        return xtb.DMatrix(X[rows], label=y[rows])

    cfg = xtb.ElasticConfig(data_fn, ckpt_dir, num_shards=num_shards)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "eta": 0.3, "max_bin": 32}, None, rounds, elastic=cfg,
                    verbose_eval=False)
    from xgboost_tpu import collective as coll

    if coll.get_rank() == 0 and out_path:
        with open(out_path, "wb") as fh:
            fh.write(bytes(bst.save_raw()))


def _failover_run(tmp_path, tag, plan=None):
    from xgboost_tpu.launcher import run_distributed

    ckpt = str(tmp_path / f"ck_{tag}")
    out = str(tmp_path / f"{tag}.ubj")
    stats = run_distributed(
        functools.partial(_failover_worker, ckpt_dir=ckpt, out_path=out,
                          rounds=6, num_shards=6),
        num_workers=3, platform="cpu", timeout=600, rendezvous="tracker",
        elastic=True, fault_plan=json.dumps(plan) if plan else None,
        tracker_failover=True)
    return open(out, "rb").read(), stats


def test_tracker_sigkill_mid_round_bitwise_parity(tmp_path):
    """The acceptance flow: a 3-worker tracker-mode run whose supervised
    tracker is hard-killed mid-round (kill-kind = SIGKILL moral
    equivalent, no finalizers) completes after a respawn + re-adoption
    with model bytes BITWISE-identical to an undisturbed run, and the
    pause wall is recorded."""
    plan = {"faults": [
        {"site": "tracker.journal", "kind": "kill", "at": 2},
        # pace the rounds so the kill lands mid-run, not post-training
        {"site": "train.round", "kind": "delay", "seconds": 0.6,
         "times": 1000},
    ]}
    model_f, stats_f = _failover_run(tmp_path, "fault", plan)
    assert stats_f["tracker_respawns"] >= 1, stats_f
    assert stats_f["tracker_pauses_s"], stats_f
    assert stats_f["succeeded"] == 3, stats_f  # failover cost no worker
    model_c, stats_c = _failover_run(tmp_path, "clean")
    assert stats_c["tracker_respawns"] == 0
    assert model_c == model_f, (
        f"model bytes diverged across a tracker SIGKILL: "
        f"{len(model_c)} vs {len(model_f)} bytes")


def test_failover_requires_elastic_tracker_mode():
    from xgboost_tpu.launcher import run_distributed

    with pytest.raises(ValueError, match="tracker_failover requires"):
        run_distributed(_failover_worker, num_workers=2, platform="cpu",
                        rendezvous="tracker", elastic=False,
                        tracker_failover=True)
