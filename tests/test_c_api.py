"""C ABI tests (reference: include/xgboost/c_api.h surface,
demo/c-api/basic pattern, tests/python/test_basic.py ctypes round-trips).

Two layers: (a) ctypes against libxtb_capi.so loaded into this interpreter
(the shim detects the live interpreter and skips embedding), (b) a real
compiled C program driving train/eval/predict/save/load end-to-end.
"""
import ctypes
import os
import subprocess

import numpy as np
import pytest

NATIVE = os.path.join(os.path.dirname(__file__), os.pardir, "native")
LIB = os.path.abspath(os.path.join(NATIVE, "libxtb_capi.so"))


def _ensure_lib():
    if not os.path.exists(LIB):
        r = subprocess.run(["make", "libxtb_capi.so"], cwd=NATIVE,
                           capture_output=True, text=True)
        if r.returncode != 0:
            pytest.skip(f"cannot build libxtb_capi.so: {r.stderr[-500:]}")
    return LIB


@pytest.fixture(scope="module")
def capi():
    lib = ctypes.CDLL(_ensure_lib())
    lib.XGBGetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, rc):
    assert rc == 0, lib.XGBGetLastError().decode()


def test_ctypes_train_predict_roundtrip(capi, tmp_path):
    rng = np.random.default_rng(0)
    R, F = 300, 5
    X = rng.normal(size=(R, F)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)

    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromMat(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(R), ctypes.c_uint64(F), ctypes.c_float(np.nan),
        ctypes.byref(dmat)))
    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(R)))
    nrow = ctypes.c_uint64()
    _check(capi, capi.XGDMatrixNumRow(dmat, ctypes.byref(nrow)))
    assert nrow.value == R

    booster = ctypes.c_void_p()
    arr = (ctypes.c_void_p * 1)(dmat)
    _check(capi, capi.XGBoosterCreate(arr, ctypes.c_uint64(1),
                                      ctypes.byref(booster)))
    _check(capi, capi.XGBoosterSetParam(booster, b"objective",
                                        b"binary:logistic"))
    _check(capi, capi.XGBoosterSetParam(booster, b"max_depth", b"3"))
    for it in range(4):
        _check(capi, capi.XGBoosterUpdateOneIter(booster, it, dmat))

    msg = ctypes.c_char_p()
    names = (ctypes.c_char_p * 1)(b"train")
    _check(capi, capi.XGBoosterEvalOneIter(booster, 3, arr, names,
                                           ctypes.c_uint64(1),
                                           ctypes.byref(msg)))
    assert b"train-logloss" in msg.value

    out_len = ctypes.c_uint64()
    out_ptr = ctypes.POINTER(ctypes.c_float)()
    _check(capi, capi.XGBoosterPredict(booster, dmat, 0, 0, 0,
                                       ctypes.byref(out_len),
                                       ctypes.byref(out_ptr)))
    preds = np.ctypeslib.as_array(out_ptr, shape=(out_len.value,)).copy()
    assert preds.shape == (R,)

    # parity with the python API on the same data
    import xgboost_tpu as xtb

    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3}, d, 4,
                    verbose_eval=False)
    np.testing.assert_allclose(preds, bst.predict(d), rtol=1e-5, atol=1e-6)

    # save via C, load via python
    path = str(tmp_path / "capi.json").encode()
    _check(capi, capi.XGBoosterSaveModel(booster, path))
    b2 = xtb.Booster()
    b2.load_model(path.decode())
    np.testing.assert_allclose(b2.predict(d), preds, rtol=1e-6, atol=1e-7)

    # margin + leaf prediction option masks
    _check(capi, capi.XGBoosterPredict(booster, dmat, 1, 0, 0,
                                       ctypes.byref(out_len),
                                       ctypes.byref(out_ptr)))
    margins = np.ctypeslib.as_array(out_ptr, shape=(out_len.value,)).copy()
    np.testing.assert_allclose(
        1.0 / (1.0 + np.exp(-margins)), preds, rtol=1e-5, atol=1e-6)

    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(dmat))


def test_ctypes_error_contract(capi):
    booster = ctypes.c_void_p()
    _check(capi, capi.XGBoosterCreate(None, ctypes.c_uint64(0),
                                      ctypes.byref(booster)))
    rc = capi.XGBoosterLoadModel(booster, b"/nonexistent/model.json")
    assert rc == -1
    assert len(capi.XGBGetLastError()) > 0
    _check(capi, capi.XGBoosterFree(booster))


def test_c_program_end_to_end(tmp_path):
    """Compile and run the plain-C demo: the 'a C program trains and
    predicts' acceptance test."""
    _ensure_lib()
    demo = os.path.join(NATIVE, "capi_demo.c")
    exe = str(tmp_path / "capi_demo")
    r = subprocess.run(["gcc", demo, "-L" + NATIVE, "-lxtb_capi", "-o", exe],
                       capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"cc unavailable: {r.stderr[-400:]}")
    env = dict(os.environ, PYTHONPATH=os.path.dirname(NATIVE),
               LD_LIBRARY_PATH=NATIVE, JAX_PLATFORMS="cpu")
    out = subprocess.run([exe], env=env, capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "C API DEMO OK" in out.stdout
    assert "save/load predictions identical: yes" in out.stdout


def test_ctypes_model_buffer_roundtrip(capi):
    rng = np.random.default_rng(1)
    R, F = 200, 4
    X = rng.normal(size=(R, F)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromMat(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(R), ctypes.c_uint64(F), ctypes.c_float(np.nan),
        ctypes.byref(dmat)))
    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(R)))
    booster = ctypes.c_void_p()
    arr = (ctypes.c_void_p * 1)(dmat)
    _check(capi, capi.XGBoosterCreate(arr, ctypes.c_uint64(1),
                                      ctypes.byref(booster)))
    _check(capi, capi.XGBoosterSetParam(booster, b"objective",
                                        b"binary:logistic"))
    for it in range(3):
        _check(capi, capi.XGBoosterUpdateOneIter(booster, it, dmat))

    for cfg in (b'{"format": "ubj"}', b'{"format": "json"}'):
        blen = ctypes.c_uint64()
        bptr = ctypes.c_char_p()
        _check(capi, capi.XGBoosterSaveModelToBuffer(
            booster, cfg, ctypes.byref(blen), ctypes.byref(bptr)))
        raw = ctypes.string_at(bptr, blen.value)
        b2 = ctypes.c_void_p()
        _check(capi, capi.XGBoosterCreate(None, ctypes.c_uint64(0),
                                          ctypes.byref(b2)))
        _check(capi, capi.XGBoosterLoadModelFromBuffer(
            b2, raw, ctypes.c_uint64(len(raw))))
        n1, p1 = ctypes.c_uint64(), ctypes.POINTER(ctypes.c_float)()
        n2, p2 = ctypes.c_uint64(), ctypes.POINTER(ctypes.c_float)()
        _check(capi, capi.XGBoosterPredict(booster, dmat, 0, 0, 0,
                                           ctypes.byref(n1), ctypes.byref(p1)))
        _check(capi, capi.XGBoosterPredict(b2, dmat, 0, 0, 0,
                                           ctypes.byref(n2), ctypes.byref(p2)))
        a1 = np.ctypeslib.as_array(p1, shape=(n1.value,)).copy()
        a2 = np.ctypeslib.as_array(p2, shape=(n2.value,)).copy()
        np.testing.assert_array_equal(a1, a2)
        _check(capi, capi.XGBoosterFree(b2))
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(dmat))


# ===================================================================
# Round-3 surface: array-interface ingestion, inplace predict, slices,
# feature info, dumps, config IO, callbacks, collective, tracker.

def _aif(arr: np.ndarray) -> bytes:
    """JSON __array_interface__ for a contiguous numpy array."""
    import json
    arr = np.ascontiguousarray(arr)
    return json.dumps({"data": [arr.ctypes.data, True],
                       "shape": list(arr.shape),
                       "typestr": arr.dtype.str, "version": 3}).encode()


def _mkdata(seed=0, R=250, F=6):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(R, F)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    return X, y


def _train_booster(capi, dmat, rounds=4):
    booster = ctypes.c_void_p()
    arr = (ctypes.c_void_p * 1)(dmat)
    _check(capi, capi.XGBoosterCreate(arr, ctypes.c_uint64(1),
                                      ctypes.byref(booster)))
    _check(capi, capi.XGBoosterSetParam(booster, b"objective",
                                        b"binary:logistic"))
    _check(capi, capi.XGBoosterSetParam(booster, b"max_depth", b"3"))
    for it in range(rounds):
        _check(capi, capi.XGBoosterUpdateOneIter(booster, it, dmat))
    return booster


def test_ctypes_array_interface_dense_csr(capi):
    X, y = _mkdata(2)
    d1 = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromDense(
        _aif(X), b'{"missing": NaN}', ctypes.byref(d1)))
    nrow, ncol = ctypes.c_uint64(), ctypes.c_uint64()
    _check(capi, capi.XGDMatrixNumRow(d1, ctypes.byref(nrow)))
    _check(capi, capi.XGDMatrixNumCol(d1, ctypes.byref(ncol)))
    assert (nrow.value, ncol.value) == X.shape

    import scipy.sparse as sp
    csr = sp.csr_matrix(np.where(np.abs(X) < 1.0, 0, X))
    ip = csr.indptr.astype(np.uint64)  # keep buffers alive across the call
    ix = csr.indices.astype(np.uint32)
    d2 = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromCSR(
        _aif(ip), _aif(ix), _aif(csr.data),
        ctypes.c_uint64(X.shape[1]), b'{"missing": NaN}', ctypes.byref(d2)))
    _check(capi, capi.XGDMatrixNumRow(d2, ctypes.byref(nrow)))
    assert nrow.value == X.shape[0]
    nm = ctypes.c_uint64()
    _check(capi, capi.XGDMatrixNumNonMissing(d2, ctypes.byref(nm)))
    assert nm.value == csr.nnz
    mode = ctypes.c_uint64()
    _check(capi, capi.XGDMatrixDataSplitMode(d2, ctypes.byref(mode)))
    assert mode.value == 0
    _check(capi, capi.XGDMatrixFree(d1))
    _check(capi, capi.XGDMatrixFree(d2))


def test_ctypes_inplace_predict(capi):
    X, y = _mkdata(3)
    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromMat(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(X.shape[0]), ctypes.c_uint64(X.shape[1]),
        ctypes.c_float(np.nan), ctypes.byref(dmat)))
    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(len(y))))
    booster = _train_booster(capi, dmat)

    shape_p = ctypes.POINTER(ctypes.c_uint64)()
    dim = ctypes.c_uint64()
    res = ctypes.POINTER(ctypes.c_float)()
    # reference predict config (c_api.h PredictFromDense)
    cfg = b'{"type": 0, "training": false, "iteration_begin": 0, "iteration_end": 0, "missing": NaN}'
    _check(capi, capi.XGBoosterPredictFromDense(
        booster, _aif(X), cfg, None, ctypes.byref(shape_p),
        ctypes.byref(dim), ctypes.byref(res)))
    assert dim.value == 1 and shape_p[0] == X.shape[0]
    dense_preds = np.ctypeslib.as_array(res, shape=(X.shape[0],)).copy()

    _check(capi, capi.XGBoosterPredictFromDMatrix(
        booster, dmat, cfg, ctypes.byref(shape_p), ctypes.byref(dim),
        ctypes.byref(res)))
    dm_preds = np.ctypeslib.as_array(res, shape=(shape_p[0],)).copy()
    np.testing.assert_array_equal(dense_preds, dm_preds)

    import scipy.sparse as sp
    csr = sp.csr_matrix(X)  # same values, sparse route
    ip = csr.indptr.astype(np.uint64)  # keep buffers alive across the call
    ix = csr.indices.astype(np.uint32)
    _check(capi, capi.XGBoosterPredictFromCSR(
        booster, _aif(ip), _aif(ix), _aif(csr.data),
        ctypes.c_uint64(X.shape[1]), cfg, None, ctypes.byref(shape_p),
        ctypes.byref(dim), ctypes.byref(res)))
    csr_preds = np.ctypeslib.as_array(res, shape=(X.shape[0],)).copy()
    np.testing.assert_allclose(csr_preds, dense_preds, rtol=1e-6)

    # margin type through the config
    cfg_m = b'{"type": 1, "iteration_begin": 0, "iteration_end": 0}'
    _check(capi, capi.XGBoosterPredictFromDMatrix(
        booster, dmat, cfg_m, ctypes.byref(shape_p), ctypes.byref(dim),
        ctypes.byref(res)))
    margins = np.ctypeslib.as_array(res, shape=(shape_p[0],)).copy()
    np.testing.assert_allclose(1 / (1 + np.exp(-margins)), dense_preds,
                               rtol=1e-5, atol=1e-6)
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(dmat))


def test_ctypes_slice_and_info(capi):
    X, y = _mkdata(4)
    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromDense(
        _aif(X), b'{"missing": NaN}', ctypes.byref(dmat)))
    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(len(y))))
    idx = np.arange(0, 100, dtype=np.int32)
    sl = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixSliceDMatrix(
        dmat, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        ctypes.c_uint64(len(idx)), ctypes.byref(sl)))
    nrow = ctypes.c_uint64()
    _check(capi, capi.XGDMatrixNumRow(sl, ctypes.byref(nrow)))
    assert nrow.value == 100

    # float info get round-trips the label
    flen = ctypes.c_uint64()
    fptr = ctypes.POINTER(ctypes.c_float)()
    _check(capi, capi.XGDMatrixGetFloatInfo(sl, b"label", ctypes.byref(flen),
                                            ctypes.byref(fptr)))
    lab = np.ctypeslib.as_array(fptr, shape=(flen.value,)).copy()
    np.testing.assert_array_equal(lab, y[:100])

    # str feature info on the dmatrix
    names = [f"f{i}".encode() for i in range(X.shape[1])]
    arr = (ctypes.c_char_p * len(names))(*names)
    _check(capi, capi.XGDMatrixSetStrFeatureInfo(
        dmat, b"feature_name", arr, ctypes.c_uint64(len(names))))
    n = ctypes.c_uint64()
    sptr = ctypes.POINTER(ctypes.c_char_p)()
    _check(capi, capi.XGDMatrixGetStrFeatureInfo(
        dmat, b"feature_name", ctypes.byref(n), ctypes.byref(sptr)))
    assert [sptr[i] for i in range(n.value)] == names

    # booster slice: first 2 of 4 rounds
    booster = _train_booster(capi, dmat)
    half = ctypes.c_void_p()
    _check(capi, capi.XGBoosterSlice(booster, 0, 2, 1, ctypes.byref(half)))
    rounds = ctypes.c_int()
    _check(capi, capi.XGBoosterBoostedRounds(half, ctypes.byref(rounds)))
    assert rounds.value == 2
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGBoosterFree(half))
    _check(capi, capi.XGDMatrixFree(sl))
    _check(capi, capi.XGDMatrixFree(dmat))


def test_ctypes_save_binary_uri_roundtrip(capi, tmp_path):
    X, y = _mkdata(5)
    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromDense(
        _aif(X), b'{"missing": NaN}', ctypes.byref(dmat)))
    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(len(y))))
    path = str(tmp_path / "dm.bin")
    _check(capi, capi.XGDMatrixSaveBinary(dmat, path.encode(), 1))
    import json
    d2 = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromURI(
        json.dumps({"uri": path}).encode(), ctypes.byref(d2)))
    flen = ctypes.c_uint64()
    fptr = ctypes.POINTER(ctypes.c_float)()
    _check(capi, capi.XGDMatrixGetFloatInfo(d2, b"label", ctypes.byref(flen),
                                            ctypes.byref(fptr)))
    np.testing.assert_array_equal(
        np.ctypeslib.as_array(fptr, shape=(flen.value,)), y)
    _check(capi, capi.XGDMatrixFree(dmat))
    _check(capi, capi.XGDMatrixFree(d2))


def test_ctypes_dump_attrs_feature_score(capi):
    X, y = _mkdata(6)
    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromDense(
        _aif(X), b'{"missing": NaN}', ctypes.byref(dmat)))
    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(len(y))))
    booster = _train_booster(capi, dmat)

    n = ctypes.c_uint64()
    dumps = ctypes.POINTER(ctypes.c_char_p)()
    _check(capi, capi.XGBoosterDumpModelEx(booster, b"", 1, b"json",
                                           ctypes.byref(n),
                                           ctypes.byref(dumps)))
    assert n.value == 4
    import json
    tree0 = json.loads(dumps[0])
    assert "children" in tree0 or "leaf" in tree0

    fnames = [f"feat{i}".encode() for i in range(X.shape[1])]
    ftypes = [b"float"] * X.shape[1]
    fn = (ctypes.c_char_p * len(fnames))(*fnames)
    ft = (ctypes.c_char_p * len(ftypes))(*ftypes)
    _check(capi, capi.XGBoosterDumpModelExWithFeatures(
        booster, len(fnames), fn, ft, 0, b"text", ctypes.byref(n),
        ctypes.byref(dumps)))
    assert b"feat0" in dumps[0]

    _check(capi, capi.XGBoosterSetAttr(booster, b"best_iteration", b"3"))
    _check(capi, capi.XGBoosterGetAttrNames(booster, ctypes.byref(n),
                                            ctypes.byref(dumps)))
    assert b"best_iteration" in [dumps[i] for i in range(n.value)]

    nf = ctypes.c_uint64()
    feats = ctypes.POINTER(ctypes.c_char_p)()
    dim = ctypes.c_uint64()
    shape = ctypes.POINTER(ctypes.c_uint64)()
    scores = ctypes.POINTER(ctypes.c_float)()
    _check(capi, capi.XGBoosterFeatureScore(
        booster, b'{"importance_type": "gain"}', ctypes.byref(nf),
        ctypes.byref(feats), ctypes.byref(dim), ctypes.byref(shape),
        ctypes.byref(scores)))
    assert nf.value > 0 and shape[0] == nf.value
    vals = np.ctypeslib.as_array(scores, shape=(nf.value,))
    assert (vals > 0).all()
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(dmat))


def test_ctypes_config_serialize_roundtrip(capi):
    X, y = _mkdata(7)
    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromDense(
        _aif(X), b'{"missing": NaN}', ctypes.byref(dmat)))
    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(len(y))))
    booster = _train_booster(capi, dmat)

    clen = ctypes.c_uint64()
    cstr = ctypes.c_char_p()
    _check(capi, capi.XGBoosterSaveJsonConfig(booster, ctypes.byref(clen),
                                              ctypes.byref(cstr)))
    import json
    cfg = json.loads(ctypes.string_at(cstr, clen.value))
    assert cfg["learner"]["learner_train_param"]["objective"] == "binary:logistic"

    blen = ctypes.c_uint64()
    bptr = ctypes.c_char_p()
    _check(capi, capi.XGBoosterSerializeToBuffer(booster, ctypes.byref(blen),
                                                 ctypes.byref(bptr)))
    blob = ctypes.string_at(bptr, blen.value)
    b2 = ctypes.c_void_p()
    _check(capi, capi.XGBoosterCreate(None, ctypes.c_uint64(0),
                                      ctypes.byref(b2)))
    _check(capi, capi.XGBoosterUnserializeFromBuffer(
        b2, blob, ctypes.c_uint64(len(blob))))
    # restored booster predicts identically AND kept its config
    n1, p1 = ctypes.c_uint64(), ctypes.POINTER(ctypes.c_float)()
    _check(capi, capi.XGBoosterPredict(booster, dmat, 0, 0, 0,
                                       ctypes.byref(n1), ctypes.byref(p1)))
    a1 = np.ctypeslib.as_array(p1, shape=(n1.value,)).copy()
    n2, p2 = ctypes.c_uint64(), ctypes.POINTER(ctypes.c_float)()
    _check(capi, capi.XGBoosterPredict(b2, dmat, 0, 0, 0,
                                       ctypes.byref(n2), ctypes.byref(p2)))
    np.testing.assert_array_equal(
        a1, np.ctypeslib.as_array(p2, shape=(n2.value,)))
    _check(capi, capi.XGBoosterLoadJsonConfig(b2, json.dumps(cfg).encode()))
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGBoosterFree(b2))
    _check(capi, capi.XGDMatrixFree(dmat))


def test_ctypes_quantile_cut_and_csr_export(capi):
    X, y = _mkdata(8)
    import json
    import xgboost_tpu as xtb

    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromDense(
        _aif(X), b'{"missing": NaN}', ctypes.byref(dmat)))
    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(len(y))))
    booster = _train_booster(capi, dmat, rounds=2)

    ip_j, va_j = ctypes.c_char_p(), ctypes.c_char_p()
    _check(capi, capi.XGDMatrixGetQuantileCut(dmat, b"{}", ctypes.byref(ip_j),
                                              ctypes.byref(va_j)))
    ip_spec = json.loads(ip_j.value)
    va_spec = json.loads(va_j.value)
    n_ptrs = ip_spec["shape"][0]
    assert n_ptrs == X.shape[1] + 1
    cut_vals = np.ctypeslib.as_array(
        ctypes.cast(va_spec["data"][0], ctypes.POINTER(ctypes.c_float)),
        shape=(va_spec["shape"][0],)).copy()
    assert np.isfinite(cut_vals).all()

    nm = ctypes.c_uint64()
    _check(capi, capi.XGDMatrixNumNonMissing(dmat, ctypes.byref(nm)))
    indptr = np.zeros(X.shape[0] + 1, np.uint64)
    indices = np.zeros(nm.value, np.uint32)
    data = np.zeros(nm.value, np.float32)
    _check(capi, capi.XGDMatrixGetDataAsCSR(
        dmat, b"{}",
        indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_float))))
    assert indptr[-1] == nm.value
    # row 0 reconstructs exactly
    r0 = np.full(X.shape[1], np.nan, np.float32)
    r0[indices[: int(indptr[1])]] = data[: int(indptr[1])]
    np.testing.assert_array_equal(r0, X[0])
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(dmat))


def test_ctypes_iterator_callbacks(capi):
    """XGProxyDMatrixCreate + XGQuantileDMatrixCreateFromCallback +
    XGDMatrixCreateFromCallback + the extmem variant, driven by C function
    pointers created here via ctypes."""
    X, y = _mkdata(9, R=400)
    batches = [(X[:150], y[:150]), (X[150:300], y[150:300]),
               (X[300:], y[300:])]

    proxy = ctypes.c_void_p()
    _check(capi, capi.XGProxyDMatrixCreate(ctypes.byref(proxy)))

    state = {"i": 0, "keep": []}
    RESET = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
    NEXT = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)

    def _reset(_):
        state["i"] = 0

    def _next(_):
        if state["i"] >= len(batches):
            return 0
        bx, by = batches[state["i"]]
        bx = np.ascontiguousarray(bx)
        by = np.ascontiguousarray(by)
        state["keep"] = [bx, by]  # alive until the glue copies
        rc = capi.XGProxyDMatrixSetDataDense(proxy, _aif(bx))
        assert rc == 0, capi.XGBGetLastError()
        rc = capi.XGDMatrixSetFloatInfo(
            proxy, b"label", by.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_uint64(len(by)))
        assert rc == 0, capi.XGBGetLastError()
        state["i"] += 1
        return 1

    reset_cb, next_cb = RESET(_reset), NEXT(_next)
    cfg = b'{"missing": NaN, "max_bin": 32}'

    qdm = ctypes.c_void_p()
    _check(capi, capi.XGQuantileDMatrixCreateFromCallback(
        None, proxy, None, reset_cb, next_cb, cfg, ctypes.byref(qdm)))
    nrow = ctypes.c_uint64()
    _check(capi, capi.XGDMatrixNumRow(qdm, ctypes.byref(nrow)))
    assert nrow.value == 400

    raw = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromCallback(
        None, proxy, reset_cb, next_cb, b'{"missing": NaN}',
        ctypes.byref(raw)))
    _check(capi, capi.XGDMatrixNumRow(raw, ctypes.byref(nrow)))
    assert nrow.value == 400

    ext = ctypes.c_void_p()
    _check(capi, capi.XGExtMemQuantileDMatrixCreateFromCallback(
        None, proxy, None, reset_cb, next_cb, cfg, ctypes.byref(ext)))

    # training on the quantile matrix works and matches python QDM training
    booster = ctypes.c_void_p()
    arr = (ctypes.c_void_p * 1)(qdm)
    _check(capi, capi.XGBoosterCreate(arr, ctypes.c_uint64(1),
                                      ctypes.byref(booster)))
    _check(capi, capi.XGBoosterSetParam(booster, b"objective",
                                        b"binary:logistic"))
    _check(capi, capi.XGBoosterSetParam(booster, b"max_bin", b"32"))
    for it in range(3):
        _check(capi, capi.XGBoosterUpdateOneIter(booster, it, qdm))
    n1, p1 = ctypes.c_uint64(), ctypes.POINTER(ctypes.c_float)()
    _check(capi, capi.XGBoosterPredict(booster, qdm, 0, 0, 0,
                                       ctypes.byref(n1), ctypes.byref(p1)))
    preds = np.ctypeslib.as_array(p1, shape=(n1.value,)).copy()

    import xgboost_tpu as xtb
    qd = xtb.QuantileDMatrix(X, label=y, max_bin=32)
    bst = xtb.train({"objective": "binary:logistic", "max_bin": 32}, qd, 3,
                    verbose_eval=False)
    np.testing.assert_allclose(preds, bst.predict(qd), rtol=1e-5, atol=1e-6)

    for h in (qdm, raw, ext, proxy):
        _check(capi, capi.XGDMatrixFree(h))
    _check(capi, capi.XGBoosterFree(booster))


def test_ctypes_train_one_iter_custom_grad(capi):
    X, y = _mkdata(10)
    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromDense(
        _aif(X), b'{"missing": NaN}', ctypes.byref(dmat)))
    booster = ctypes.c_void_p()
    arr = (ctypes.c_void_p * 1)(dmat)
    _check(capi, capi.XGBoosterCreate(arr, ctypes.c_uint64(1),
                                      ctypes.byref(booster)))
    _check(capi, capi.XGBoosterSetParam(booster, b"max_depth", b"3"))
    pred = np.zeros(len(y), np.float32)
    for it in range(2):
        grad = (1 / (1 + np.exp(-pred)) - y).astype(np.float32)
        p = 1 / (1 + np.exp(-pred))
        hess = (p * (1 - p)).astype(np.float32)
        _check(capi, capi.XGBoosterTrainOneIter(booster, dmat, it,
                                                _aif(grad), _aif(hess)))
        shape_p = ctypes.POINTER(ctypes.c_uint64)()
        dim = ctypes.c_uint64()
        res = ctypes.POINTER(ctypes.c_float)()
        _check(capi, capi.XGBoosterPredictFromDMatrix(
            booster, dmat, b'{"type": 1}', ctypes.byref(shape_p),
            ctypes.byref(dim), ctypes.byref(res)))
        pred = np.ctypeslib.as_array(res, shape=(len(y),)).copy()
    rounds = ctypes.c_int()
    _check(capi, capi.XGBoosterBoostedRounds(booster, ctypes.byref(rounds)))
    assert rounds.value == 2
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(dmat))


def test_ctypes_globals_and_collective_single(capi):
    info = ctypes.c_char_p()
    _check(capi, capi.XGBuildInfo(ctypes.byref(info)))
    import json
    assert json.loads(info.value)["USE_TPU"] is True

    _check(capi, capi.XGBSetGlobalConfig(b'{"verbosity": 2}'))
    out = ctypes.c_char_p()
    _check(capi, capi.XGBGetGlobalConfig(ctypes.byref(out)))
    assert json.loads(out.value)["verbosity"] == 2
    _check(capi, capi.XGBSetGlobalConfig(b'{"verbosity": 1}'))

    # single-process communicator contract
    _check(capi, capi.XGCommunicatorInit(b"{}"))
    assert capi.XGCommunicatorGetRank() == 0
    assert capi.XGCommunicatorGetWorldSize() == 1
    assert capi.XGCommunicatorIsDistributed() == 0
    name = ctypes.c_char_p()
    _check(capi, capi.XGCommunicatorGetProcessorName(ctypes.byref(name)))
    assert len(name.value) > 0
    buf = np.arange(8, dtype=np.float64)
    _check(capi, capi.XGCommunicatorAllreduce(
        buf.ctypes.data_as(ctypes.c_void_p), ctypes.c_size_t(8), 2, 2))
    np.testing.assert_array_equal(buf, np.arange(8))  # sum over world=1
    bbuf = np.frombuffer(bytearray(b"hello-bc"), dtype=np.uint8).copy()
    _check(capi, capi.XGCommunicatorBroadcast(
        bbuf.ctypes.data_as(ctypes.c_void_p), ctypes.c_size_t(8), 0))
    assert bbuf.tobytes() == b"hello-bc"
    _check(capi, capi.XGCommunicatorFinalize())


def test_ctypes_tracker(capi):
    import json
    import threading

    tr = ctypes.c_void_p()
    _check(capi, capi.XGTrackerCreate(
        b'{"n_workers": 1, "host": "127.0.0.1"}', ctypes.byref(tr)))
    _check(capi, capi.XGTrackerRun(tr, b"{}"))
    args_p = ctypes.c_char_p()
    _check(capi, capi.XGTrackerWorkerArgs(tr, ctypes.byref(args_p)))
    args = json.loads(args_p.value)
    assert args["dmlc_tracker_uri"] == "127.0.0.1"

    from xgboost_tpu.tracker import TrackerClient

    def client():
        c = TrackerClient(args["dmlc_tracker_uri"],
                          int(args["dmlc_tracker_port"]))
        assert c.rank == 0 and c.world == 1
        c.shutdown()

    t = threading.Thread(target=client)
    t.start()
    _check(capi, capi.XGTrackerWaitFor(tr, b'{"timeout": 30}'))
    t.join(30)
    _check(capi, capi.XGTrackerFree(tr))


def test_ctypes_columnar_csc_inforef(capi):
    X, y = _mkdata(11)
    import json

    # columnar: one array-interface per column
    cols = [np.ascontiguousarray(X[:, j]) for j in range(X.shape[1])]
    col_json = json.dumps([json.loads(_aif(c)) for c in cols]).encode()
    d1 = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromColumnar(
        col_json, b'{"missing": NaN}', ctypes.byref(d1)))
    nrow = ctypes.c_uint64()
    _check(capi, capi.XGDMatrixNumRow(d1, ctypes.byref(nrow)))
    assert nrow.value == X.shape[0]

    import scipy.sparse as sp
    csc = sp.csc_matrix(np.where(np.abs(X) < 0.5, 0, X))
    ip = csc.indptr.astype(np.uint64)
    ix = csc.indices.astype(np.uint32)
    d2 = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromCSC(
        _aif(ip), _aif(ix), _aif(csc.data), ctypes.c_uint64(X.shape[0]),
        b'{"missing": NaN}', ctypes.byref(d2)))
    ncol = ctypes.c_uint64()
    _check(capi, capi.XGDMatrixNumCol(d2, ctypes.byref(ncol)))
    assert ncol.value == X.shape[1]

    # info from array interface + reference-counted view back out
    _check(capi, capi.XGDMatrixSetInfoFromInterface(d1, b"label", _aif(y)))
    ref = ctypes.c_char_p()
    _check(capi, capi.XGDMatrixGetInfoRef(d1, b"label", ctypes.byref(ref)))
    spec = json.loads(ref.value)
    back = np.ctypeslib.as_array(
        ctypes.cast(spec["data"][0], ctypes.POINTER(ctypes.c_float)),
        shape=tuple(spec["shape"])).copy()
    np.testing.assert_array_equal(back, y)

    # deprecated raw-pointer info setter
    w = np.abs(X[:, 1]) + 1
    _check(capi, capi.XGDMatrixSetDenseInfo(
        d1, b"weight", w.astype(np.float32).ctypes.data_as(ctypes.c_void_p),
        ctypes.c_uint64(len(w)), 1))
    flen = ctypes.c_uint64()
    fptr = ctypes.POINTER(ctypes.c_float)()
    _check(capi, capi.XGDMatrixGetFloatInfo(d1, b"weight", ctypes.byref(flen),
                                            ctypes.byref(fptr)))
    assert flen.value == len(w)

    # columnar inplace predict == dense inplace predict
    _check(capi, capi.XGDMatrixSetInfoFromInterface(d1, b"label", _aif(y)))
    booster = _train_booster(capi, d1, rounds=2)
    shape_p = ctypes.POINTER(ctypes.c_uint64)()
    dim = ctypes.c_uint64()
    res = ctypes.POINTER(ctypes.c_float)()
    _check(capi, capi.XGBoosterPredictFromColumnar(
        booster, col_json, b'{"type": 0}', None, ctypes.byref(shape_p),
        ctypes.byref(dim), ctypes.byref(res)))
    p_col = np.ctypeslib.as_array(res, shape=(X.shape[0],)).copy()
    _check(capi, capi.XGBoosterPredictFromDense(
        booster, _aif(X), b'{"type": 0}', None, ctypes.byref(shape_p),
        ctypes.byref(dim), ctypes.byref(res)))
    p_dense = np.ctypeslib.as_array(res, shape=(X.shape[0],)).copy()
    np.testing.assert_array_equal(p_col, p_dense)
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(d1))
    _check(capi, capi.XGDMatrixFree(d2))


def test_ctypes_csr_missing_filter_and_export_consistency(capi):
    """Regression: CSR entries that mean 'missing' (NaN, or == the missing
    sentinel) are filtered at construction (reference adapter.h
    IsValidFunctor), so XGDMatrixNumNonMissing sizes exactly what
    XGDMatrixGetDataAsCSR exports — callers allocate from the former."""
    import scipy.sparse as sp

    dense = np.array([[1.0, np.nan, 3.0],
                      [0.0, 5.0, np.nan],
                      [7.0, 0.0, 5.0]], np.float32)
    csr = sp.csr_matrix(dense)  # explicit entries incl. the NaNs
    ip = csr.indptr.astype(np.uint64)
    ix = csr.indices.astype(np.uint32)

    d = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromCSR(
        _aif(ip), _aif(ix), _aif(csr.data), ctypes.c_uint64(3),
        b'{"missing": NaN}', ctypes.byref(d)))
    nm = ctypes.c_uint64()
    _check(capi, capi.XGDMatrixNumNonMissing(d, ctypes.byref(nm)))
    assert nm.value == 5  # 7 stored minus 2 NaNs

    oip = ctypes.POINTER(ctypes.c_uint64)()
    oix = ctypes.POINTER(ctypes.c_uint32)()
    ova = ctypes.POINTER(ctypes.c_float)()
    # buffers sized from NumNonMissing per the reference contract
    out_ip = (ctypes.c_uint64 * 4)()
    out_ix = (ctypes.c_uint32 * 5)()
    out_va = (ctypes.c_float * 5)()
    _check(capi, capi.XGDMatrixGetDataAsCSR(
        d, b"{}", out_ip, out_ix, out_va))
    assert out_ip[3] == 5
    assert np.isfinite(np.ctypeslib.as_array(out_va, shape=(5,))).all()
    _check(capi, capi.XGDMatrixFree(d))

    # finite sentinel: 5.0 means missing -> dropped structurally
    d2 = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromCSR(
        _aif(ip), _aif(ix), _aif(csr.data), ctypes.c_uint64(3),
        b'{"missing": 5.0}', ctypes.byref(d2)))
    _check(capi, capi.XGDMatrixNumNonMissing(d2, ctypes.byref(nm)))
    assert nm.value == 3  # also drops the two 5.0 entries
    _check(capi, capi.XGDMatrixFree(d2))


def test_ctypes_iterator_callback_group_info(capi):
    """Regression: 'group' staged on the proxy via XGDMatrixSetUIntInfo
    must reach the assembled QuantileDMatrix (it was silently dropped)."""
    X, y = _mkdata(3, R=120, F=4)
    halves = [(X[:60], y[:60], np.array([20, 40], np.uint32)),
              (X[60:], y[60:], np.array([30, 30], np.uint32))]

    proxy = ctypes.c_void_p()
    _check(capi, capi.XGProxyDMatrixCreate(ctypes.byref(proxy)))
    state = {"i": 0, "keep": []}
    RESET = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
    NEXT = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)

    def _reset(_):
        state["i"] = 0

    def _next(_):
        if state["i"] >= len(halves):
            return 0
        bx, by, bg = halves[state["i"]]
        bx, by = np.ascontiguousarray(bx), np.ascontiguousarray(by)
        state["keep"] = [bx, by, bg]
        assert capi.XGProxyDMatrixSetDataDense(proxy, _aif(bx)) == 0
        assert capi.XGDMatrixSetFloatInfo(
            proxy, b"label",
            by.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_uint64(len(by))) == 0
        assert capi.XGDMatrixSetUIntInfo(
            proxy, b"group",
            bg.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            ctypes.c_uint64(len(bg))) == 0
        state["i"] += 1
        return 1

    reset_cb, next_cb = RESET(_reset), NEXT(_next)
    qdm = ctypes.c_void_p()
    _check(capi, capi.XGQuantileDMatrixCreateFromCallback(
        None, proxy, None, reset_cb, next_cb,
        b'{"missing": NaN, "max_bin": 32}', ctypes.byref(qdm)))

    ulen = ctypes.c_uint64()
    uptr = ctypes.POINTER(ctypes.c_uint32)()
    _check(capi, capi.XGDMatrixGetUIntInfo(qdm, b"group_ptr",
                                           ctypes.byref(ulen),
                                           ctypes.byref(uptr)))
    got = np.ctypeslib.as_array(uptr, shape=(ulen.value,)).copy()
    np.testing.assert_array_equal(got, [0, 20, 60, 90, 120])

    # a ranking objective actually trains on it
    booster = ctypes.c_void_p()
    arr = (ctypes.c_void_p * 1)(qdm)
    _check(capi, capi.XGBoosterCreate(arr, ctypes.c_uint64(1),
                                      ctypes.byref(booster)))
    _check(capi, capi.XGBoosterSetParam(booster, b"objective",
                                        b"rank:pairwise"))
    _check(capi, capi.XGBoosterUpdateOneIter(booster, 0, qdm))
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(qdm))
    _check(capi, capi.XGDMatrixFree(proxy))


def test_r_glue_sequence(tmp_path):
    """The R binding's exact C-ABI call sequence (r-package/src/xtb_R.c),
    driven from plain C: column-major double -> row-major float conversion,
    weight info, per-round EvalOneIter, predict, ubj buffer round-trip, and
    text dump.  Pins the ABI contract for machines without an R toolchain."""
    _ensure_lib()
    src = os.path.join(NATIVE, "r_glue_seq.c")
    exe = str(tmp_path / "r_glue_seq")
    r = subprocess.run(["gcc", src, "-L" + NATIVE, "-lxtb_capi", "-lm",
                        "-o", exe], capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"cc unavailable: {r.stderr[-400:]}")
    env = dict(os.environ, PYTHONPATH=os.path.dirname(NATIVE),
               LD_LIBRARY_PATH=NATIVE, JAX_PLATFORMS="cpu")
    out = subprocess.run([exe], env=env, capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    assert "R-GLUE-SEQ-OK" in out.stdout


def test_jni_glue_sequence(tmp_path):
    """The JVM binding's exact C-ABI call sequence
    (jvm-package/src/native/xgboost_tpu_jni.c), driven from plain C:
    row-major ingest, group info + rank:ndcg training with per-round eval,
    predict, ubj buffer round-trip.  Pins the ABI contract for machines
    without a JDK."""
    _ensure_lib()
    src = os.path.join(NATIVE, "jni_glue_seq.c")
    exe = str(tmp_path / "jni_glue_seq")
    r = subprocess.run(["gcc", src, "-L" + NATIVE, "-lxtb_capi", "-lm",
                        "-o", exe], capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"cc unavailable: {r.stderr[-400:]}")
    env = dict(os.environ, PYTHONPATH=os.path.dirname(NATIVE),
               LD_LIBRARY_PATH=NATIVE, JAX_PLATFORMS="cpu")
    out = subprocess.run([exe], env=env, capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    assert "JNI-GLUE-SEQ-OK" in out.stdout


# ===================================================================
# Serving-era surface: concurrency contract + categories export.

@pytest.mark.quick
def test_concurrent_predict_correct(capi):
    """Correctness half of the C ABI concurrency contract
    (native/xtb_capi.cc): predict entry points take the SHARED dispatch
    lock, so N host threads overlap — and must stay bitwise CORRECT.
    Each thread drives its own booster handle loaded from one shared model
    buffer; all predictions must be bitwise-identical to the
    single-threaded result.  The throughput half (no serialization) is
    test_concurrent_predict_parallel_throughput below."""
    import threading

    X, y = _mkdata(13)
    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromMat(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(X.shape[0]), ctypes.c_uint64(X.shape[1]),
        ctypes.c_float(np.nan), ctypes.byref(dmat)))
    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(len(y))))
    booster = _train_booster(capi, dmat)
    blen, bptr = ctypes.c_uint64(), ctypes.c_char_p()
    _check(capi, capi.XGBoosterSaveModelToBuffer(
        booster, b'{"format": "ubj"}', ctypes.byref(blen), ctypes.byref(bptr)))
    raw = ctypes.string_at(bptr, blen.value)

    n0, p0 = ctypes.c_uint64(), ctypes.POINTER(ctypes.c_float)()
    _check(capi, capi.XGBoosterPredict(booster, dmat, 0, 0, 0,
                                       ctypes.byref(n0), ctypes.byref(p0)))
    ref = np.ctypeslib.as_array(p0, shape=(n0.value,)).copy()

    N_THREADS, N_CALLS = 4, 6
    results, errors = {}, []
    barrier = threading.Barrier(N_THREADS)

    def worker(tid):
        try:
            h = ctypes.c_void_p()
            _check(capi, capi.XGBoosterCreate(None, ctypes.c_uint64(0),
                                              ctypes.byref(h)))
            _check(capi, capi.XGBoosterLoadModelFromBuffer(
                h, raw, ctypes.c_uint64(len(raw))))
            barrier.wait(30)
            outs = []
            for _ in range(N_CALLS):
                n, p = ctypes.c_uint64(), ctypes.POINTER(ctypes.c_float)()
                _check(capi, capi.XGBoosterPredict(h, dmat, 0, 0, 0,
                                                   ctypes.byref(n),
                                                   ctypes.byref(p)))
                outs.append(np.ctypeslib.as_array(
                    p, shape=(n.value,)).copy())
            results[tid] = outs
            _check(capi, capi.XGBoosterFree(h))
        except BaseException as e:
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(300)
    assert not errors, errors[0]
    assert len(results) == N_THREADS
    for outs in results.values():
        for out in outs:
            np.testing.assert_array_equal(out, ref)
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(dmat))


@pytest.mark.quick
def test_concurrent_predict_parallel_throughput(capi):
    """Throughput half of the narrowed dispatch contract
    (native/xtb_capi.cc API_BEGIN_READ + docs/native_threading.md):
    concurrent read-only predict callers must NOT be reduced to
    single-thread throughput.  4 threads x k predicts over a shared
    DMatrix must (a) stay bitwise-identical to the single-threaded
    reference and (b) beat the serialized wall-clock by a real margin —
    possible only if the shared lock + jax's GIL release actually overlap
    the native compute.  The pool is pinned to nthread=1 so per-call
    kernels leave cores free for the overlap itself."""
    import threading
    import time

    if (os.cpu_count() or 1) < 2:
        pytest.skip("needs >= 2 cores to demonstrate overlap")

    R, F = 200_000, 8
    rng = np.random.default_rng(21)
    X = rng.normal(size=(R, F)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] > 0).astype(np.float32)
    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromMat(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(R), ctypes.c_uint64(F), ctypes.c_float(np.nan),
        ctypes.byref(dmat)))
    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(len(y))))
    booster = _train_booster(capi, dmat, rounds=12)
    # single-threaded kernels: the overlap must come from concurrent
    # callers, not from the pool parallelizing each call internally
    _check(capi, capi.XGBoosterSetParam(booster, b"nthread", b"1"))

    def predict():
        n, p = ctypes.c_uint64(), ctypes.POINTER(ctypes.c_float)()
        _check(capi, capi.XGBoosterPredict(booster, dmat, 1, 0, 0,
                                           ctypes.byref(n), ctypes.byref(p)))
        return np.ctypeslib.as_array(p, shape=(n.value,)).copy()

    ref = predict()  # warm the jit cache + pin the reference bits
    N_THREADS, CALLS = 4, 2

    def measure():
        t0 = time.perf_counter()
        for _ in range(N_THREADS * CALLS):
            predict()
        serial_s = time.perf_counter() - t0

        results, errors = {}, []
        barrier = threading.Barrier(N_THREADS)

        def worker(tid):
            try:
                barrier.wait(30)
                results[tid] = [predict() for _ in range(CALLS)]
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(N_THREADS)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        concurrent_s = time.perf_counter() - t0
        assert not errors, errors[0]
        for outs in results.values():
            for out in outs:
                np.testing.assert_array_equal(out, ref)
        return serial_s / concurrent_s

    # repeated attempts damp scheduler noise on small/loaded CI boxes
    # (early-exit on success); demand a real overlap margin, far above
    # timing jitter yet below the 2x a 2-core host could ideally reach
    speedups = []
    for _ in range(5):
        speedups.append(measure())
        if speedups[-1] > 1.2:
            break
    assert max(speedups) > 1.2, (
        f"concurrent predict shows no overlap: speedups={speedups} "
        f"(serialized dispatch?)")
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(dmat))


def test_ctypes_get_categories(capi, tmp_path):
    """XGBoosterGetCategories / XGDMatrixGetCategories (reference:
    include/xgboost/c_api.h + src/data/cat_container.h; this ABI returns
    the mapping as JSON, "null" without categorical features)."""
    import json

    X, y = _mkdata(14)
    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromDense(
        _aif(X), b'{"missing": NaN}', ctypes.byref(dmat)))
    out = ctypes.c_char_p()
    _check(capi, capi.XGDMatrixGetCategories(dmat, ctypes.byref(out)))
    assert json.loads(out.value) is None  # purely numeric input

    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(len(y))))
    booster = _train_booster(capi, dmat, rounds=2)
    _check(capi, capi.XGBoosterGetCategories(booster, ctypes.byref(out)))
    assert json.loads(out.value) is None  # trained without categories
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(dmat))

    # a model trained on a categorical frame exports its mapping through
    # the ABI after a file round-trip
    pd = pytest.importorskip("pandas")
    import xgboost_tpu as xtb

    rng = np.random.default_rng(14)
    n = 400
    col = rng.choice(["red", "green", "blue"], size=n)
    df = pd.DataFrame({
        "c": pd.Categorical(col, categories=["red", "green", "blue"]),
        "x": rng.normal(size=n).astype(np.float32),
    })
    yy = (col == "red").astype(np.float32)
    d = xtb.DMatrix(df, label=yy, enable_categorical=True)
    assert d.get_categories() == {"c": ["red", "green", "blue"]}
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3}, d, 2,
                    verbose_eval=False)
    path = str(tmp_path / "cat.json")
    bst.save_model(path)

    b2 = ctypes.c_void_p()
    _check(capi, capi.XGBoosterCreate(None, ctypes.c_uint64(0),
                                      ctypes.byref(b2)))
    _check(capi, capi.XGBoosterLoadModel(b2, path.encode()))
    _check(capi, capi.XGBoosterGetCategories(b2, ctypes.byref(out)))
    assert json.loads(out.value) == {"c": ["red", "green", "blue"]}
    _check(capi, capi.XGBoosterFree(b2))
