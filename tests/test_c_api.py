"""C ABI tests (reference: include/xgboost/c_api.h surface,
demo/c-api/basic pattern, tests/python/test_basic.py ctypes round-trips).

Two layers: (a) ctypes against libxtb_capi.so loaded into this interpreter
(the shim detects the live interpreter and skips embedding), (b) a real
compiled C program driving train/eval/predict/save/load end-to-end.
"""
import ctypes
import os
import subprocess

import numpy as np
import pytest

NATIVE = os.path.join(os.path.dirname(__file__), os.pardir, "native")
LIB = os.path.abspath(os.path.join(NATIVE, "libxtb_capi.so"))


def _ensure_lib():
    if not os.path.exists(LIB):
        r = subprocess.run(["make", "libxtb_capi.so"], cwd=NATIVE,
                           capture_output=True, text=True)
        if r.returncode != 0:
            pytest.skip(f"cannot build libxtb_capi.so: {r.stderr[-500:]}")
    return LIB


@pytest.fixture(scope="module")
def capi():
    lib = ctypes.CDLL(_ensure_lib())
    lib.XGBGetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, rc):
    assert rc == 0, lib.XGBGetLastError().decode()


def test_ctypes_train_predict_roundtrip(capi, tmp_path):
    rng = np.random.default_rng(0)
    R, F = 300, 5
    X = rng.normal(size=(R, F)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)

    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromMat(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(R), ctypes.c_uint64(F), ctypes.c_float(np.nan),
        ctypes.byref(dmat)))
    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(R)))
    nrow = ctypes.c_uint64()
    _check(capi, capi.XGDMatrixNumRow(dmat, ctypes.byref(nrow)))
    assert nrow.value == R

    booster = ctypes.c_void_p()
    arr = (ctypes.c_void_p * 1)(dmat)
    _check(capi, capi.XGBoosterCreate(arr, ctypes.c_uint64(1),
                                      ctypes.byref(booster)))
    _check(capi, capi.XGBoosterSetParam(booster, b"objective",
                                        b"binary:logistic"))
    _check(capi, capi.XGBoosterSetParam(booster, b"max_depth", b"3"))
    for it in range(4):
        _check(capi, capi.XGBoosterUpdateOneIter(booster, it, dmat))

    msg = ctypes.c_char_p()
    names = (ctypes.c_char_p * 1)(b"train")
    _check(capi, capi.XGBoosterEvalOneIter(booster, 3, arr, names,
                                           ctypes.c_uint64(1),
                                           ctypes.byref(msg)))
    assert b"train-logloss" in msg.value

    out_len = ctypes.c_uint64()
    out_ptr = ctypes.POINTER(ctypes.c_float)()
    _check(capi, capi.XGBoosterPredict(booster, dmat, 0, 0, 0,
                                       ctypes.byref(out_len),
                                       ctypes.byref(out_ptr)))
    preds = np.ctypeslib.as_array(out_ptr, shape=(out_len.value,)).copy()
    assert preds.shape == (R,)

    # parity with the python API on the same data
    import xgboost_tpu as xtb

    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3}, d, 4,
                    verbose_eval=False)
    np.testing.assert_allclose(preds, bst.predict(d), rtol=1e-5, atol=1e-6)

    # save via C, load via python
    path = str(tmp_path / "capi.json").encode()
    _check(capi, capi.XGBoosterSaveModel(booster, path))
    b2 = xtb.Booster()
    b2.load_model(path.decode())
    np.testing.assert_allclose(b2.predict(d), preds, rtol=1e-6, atol=1e-7)

    # margin + leaf prediction option masks
    _check(capi, capi.XGBoosterPredict(booster, dmat, 1, 0, 0,
                                       ctypes.byref(out_len),
                                       ctypes.byref(out_ptr)))
    margins = np.ctypeslib.as_array(out_ptr, shape=(out_len.value,)).copy()
    np.testing.assert_allclose(
        1.0 / (1.0 + np.exp(-margins)), preds, rtol=1e-5, atol=1e-6)

    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(dmat))


def test_ctypes_error_contract(capi):
    booster = ctypes.c_void_p()
    _check(capi, capi.XGBoosterCreate(None, ctypes.c_uint64(0),
                                      ctypes.byref(booster)))
    rc = capi.XGBoosterLoadModel(booster, b"/nonexistent/model.json")
    assert rc == -1
    assert len(capi.XGBGetLastError()) > 0
    _check(capi, capi.XGBoosterFree(booster))


def test_c_program_end_to_end(tmp_path):
    """Compile and run the plain-C demo: the 'a C program trains and
    predicts' acceptance test."""
    _ensure_lib()
    demo = os.path.join(NATIVE, "capi_demo.c")
    exe = str(tmp_path / "capi_demo")
    r = subprocess.run(["gcc", demo, "-L" + NATIVE, "-lxtb_capi", "-o", exe],
                       capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"cc unavailable: {r.stderr[-400:]}")
    env = dict(os.environ, PYTHONPATH=os.path.dirname(NATIVE),
               LD_LIBRARY_PATH=NATIVE, JAX_PLATFORMS="cpu")
    out = subprocess.run([exe], env=env, capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "C API DEMO OK" in out.stdout
    assert "save/load predictions identical: yes" in out.stdout


def test_ctypes_model_buffer_roundtrip(capi):
    rng = np.random.default_rng(1)
    R, F = 200, 4
    X = rng.normal(size=(R, F)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    dmat = ctypes.c_void_p()
    _check(capi, capi.XGDMatrixCreateFromMat(
        X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(R), ctypes.c_uint64(F), ctypes.c_float(np.nan),
        ctypes.byref(dmat)))
    _check(capi, capi.XGDMatrixSetFloatInfo(
        dmat, b"label", y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_uint64(R)))
    booster = ctypes.c_void_p()
    arr = (ctypes.c_void_p * 1)(dmat)
    _check(capi, capi.XGBoosterCreate(arr, ctypes.c_uint64(1),
                                      ctypes.byref(booster)))
    _check(capi, capi.XGBoosterSetParam(booster, b"objective",
                                        b"binary:logistic"))
    for it in range(3):
        _check(capi, capi.XGBoosterUpdateOneIter(booster, it, dmat))

    for cfg in (b'{"format": "ubj"}', b'{"format": "json"}'):
        blen = ctypes.c_uint64()
        bptr = ctypes.c_char_p()
        _check(capi, capi.XGBoosterSaveModelToBuffer(
            booster, cfg, ctypes.byref(blen), ctypes.byref(bptr)))
        raw = ctypes.string_at(bptr, blen.value)
        b2 = ctypes.c_void_p()
        _check(capi, capi.XGBoosterCreate(None, ctypes.c_uint64(0),
                                          ctypes.byref(b2)))
        _check(capi, capi.XGBoosterLoadModelFromBuffer(
            b2, raw, ctypes.c_uint64(len(raw))))
        n1, p1 = ctypes.c_uint64(), ctypes.POINTER(ctypes.c_float)()
        n2, p2 = ctypes.c_uint64(), ctypes.POINTER(ctypes.c_float)()
        _check(capi, capi.XGBoosterPredict(booster, dmat, 0, 0, 0,
                                           ctypes.byref(n1), ctypes.byref(p1)))
        _check(capi, capi.XGBoosterPredict(b2, dmat, 0, 0, 0,
                                           ctypes.byref(n2), ctypes.byref(p2)))
        a1 = np.ctypeslib.as_array(p1, shape=(n1.value,)).copy()
        a2 = np.ctypeslib.as_array(p2, shape=(n2.value,)).copy()
        np.testing.assert_array_equal(a1, a2)
        _check(capi, capi.XGBoosterFree(b2))
    _check(capi, capi.XGBoosterFree(booster))
    _check(capi, capi.XGDMatrixFree(dmat))
