"""Native-kernel vs XLA-formulation parity (native/xtb_kernels.h).

The CPU backend swaps the XLA scatter/cumsum/scan formulations for native
C++ kernels behind XLA FFI custom calls.  These tests pin the contract the
swap relies on:

- histogram: BITWISE equality (same f32 add order);
- split scan: identical decisions (feature, bin, default direction) and
  last-ulp-close gains/sums — full bitwise equality is NOT promised (the
  cumsum reduction orders differ), which is exactly why distributed init
  reconciles kernel availability across ranks (utils/native.py);
- predict: BITWISE equality (rows-outer/trees-inner preserves the scan's
  per-row add order).

Env overrides force each side; jax.clear_caches() between sides keeps the
shape-keyed jit cache from serving the other implementation's executable.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from xgboost_tpu.ops.histogram import build_histogram
from xgboost_tpu.ops.split import SplitParams, evaluate_splits
from xgboost_tpu.utils import native

pytestmark = pytest.mark.skipif(not native.load_ffi(),
                                reason="FFI kernels unavailable")


def _with_impl(env_key, env_val, fn):
    old = os.environ.get(env_key)
    os.environ[env_key] = env_val
    jax.clear_caches()
    try:
        return fn()
    finally:
        if old is None:
            del os.environ[env_key]
        else:
            os.environ[env_key] = old
        jax.clear_caches()


def test_hist_native_bitwise_matches_scatter():
    rng = np.random.default_rng(0)
    for R, F, B, N, stride, dt in ((3000, 6, 17, 4, 1, np.int32),
                                   (5000, 3, 33, 8, 2, np.uint8),
                                   (2048, 5, 257, 2, 1, np.int16)):
        bins = jnp.asarray(rng.integers(0, B + 1, size=(R, F)).astype(dt))
        gpair = jnp.asarray(rng.normal(size=(R, 2)), jnp.float32)
        node0 = N - 1
        pos = jnp.asarray(
            rng.integers(node0 - 1, node0 + 2 * N, size=R), jnp.int32)

        def run():
            return np.asarray(build_histogram(
                bins, gpair, pos, node0=node0, n_nodes=N, n_bin=B,
                stride=stride))

        got = _with_impl("XTB_HIST_IMPL", "native", run)
        want = _with_impl("XTB_HIST_IMPL", "scatter", run)
        np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("params", [
    SplitParams(eta=0.3, gamma=0.0, min_child_weight=1.0, lambda_=1.0,
                alpha=0.0, max_delta_step=0.0),
    SplitParams(eta=0.3, gamma=0.0, min_child_weight=3.0, lambda_=0.5,
                alpha=0.3, max_delta_step=0.0),
    SplitParams(eta=0.3, gamma=0.0, min_child_weight=0.0, lambda_=1.0,
                alpha=0.0, max_delta_step=0.7),
])
def test_split_native_decisions_match_xla(params):
    rng = np.random.default_rng(7)
    for trial in range(6):
        N, F, B = int(rng.integers(1, 9)), int(rng.integers(1, 7)), 33
        hist = rng.normal(size=(N, F, B, 2)).astype(np.float32)
        hist[..., 1] = np.abs(hist[..., 1])  # hessians non-negative
        # zero out padding beyond per-feature widths incl. degenerate 0/1
        n_bins = rng.integers(0 if trial == 5 else 1, B, size=F).astype(
            np.int32)
        for f in range(F):
            hist[:, f, n_bins[f]:] = 0.0
        totals = hist.sum(axis=(1, 2)) / max(F, 1)
        totals[..., 1] += 0.5  # missing mass
        fmask = rng.random((N, F)) > 0.2
        fmask[:, 0] = True

        def run():
            return evaluate_splits(
                jnp.asarray(hist), jnp.asarray(totals),
                jnp.asarray(n_bins), params, jnp.asarray(fmask))

        a = _with_impl("XTB_NO_NATIVE_SPLIT", "", run)    # native
        b = _with_impl("XTB_NO_NATIVE_SPLIT", "1", run)   # XLA
        valid = np.isfinite(np.asarray(b.gain))
        np.testing.assert_array_equal(np.asarray(a.feature)[valid],
                                      np.asarray(b.feature)[valid])
        np.testing.assert_array_equal(np.asarray(a.bin)[valid],
                                      np.asarray(b.bin)[valid])
        np.testing.assert_array_equal(np.asarray(a.default_left)[valid],
                                      np.asarray(b.default_left)[valid])
        np.testing.assert_allclose(np.asarray(a.gain)[valid],
                                   np.asarray(b.gain)[valid], rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(a.left_sum)[valid],
                                   np.asarray(b.left_sum)[valid],
                                   rtol=1e-5, atol=1e-5)


def test_predict_native_bitwise_matches_xla():
    import xgboost_tpu as xtb

    rng = np.random.default_rng(3)
    X = rng.normal(size=(1200, 6)).astype(np.float32)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 4,
                     "eta": 0.3, "max_bin": 32},
                    xtb.DMatrix(X, label=y), 4, verbose_eval=False)

    def run():
        return np.asarray(bst.predict(xtb.DMatrix(X), output_margin=True))

    a = _with_impl("XTB_NO_NATIVE_PREDICT", "", run)
    b = _with_impl("XTB_NO_NATIVE_PREDICT", "1", run)
    np.testing.assert_array_equal(a, b)


def test_lambdarank_native_matches_xla():
    """Native CSR-group top-k lambda pass vs the padded XLA formulation:
    same pair set and weights -> f32-tolerance-equal gradients, across
    ragged group sizes (incl. singleton groups) and both weight modes."""
    from xgboost_tpu.objective.ranking import (_lambda_gradients_topk,
                                               _lambda_gradients_topk_native,
                                               make_group_layout)

    rng = np.random.default_rng(5)
    sizes = np.concatenate([rng.integers(1, 40, size=30), [1, 2, 200]])
    gptr = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int32)
    R = int(gptr[-1])
    pred = rng.normal(size=R).astype(np.float32)
    y = rng.integers(0, 5, size=R).astype(np.float32)
    idx, mask, inv = make_group_layout(gptr)

    for ndcg_w, snorm, gnorm, k in ((True, True, True, 8),
                                    (False, False, False, 3),
                                    (True, False, True, 256)):
        ga, ha = _lambda_gradients_topk_native(
            jnp.asarray(pred), jnp.asarray(y), jnp.asarray(gptr), k=k,
            ndcg_weight=ndcg_w, score_norm=snorm, group_norm=gnorm)
        gb, hb = _lambda_gradients_topk(
            jnp.asarray(pred), jnp.asarray(y), jnp.asarray(idx),
            jnp.asarray(mask), jnp.asarray(inv), k=k, ndcg_weight=ndcg_w,
            score_norm=snorm, group_norm=gnorm)
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                                   rtol=2e-4, atol=2e-6)
        np.testing.assert_allclose(np.asarray(ha), np.asarray(hb),
                                   rtol=2e-4, atol=2e-6)


def test_lambdarank_zero_spread_first_iteration():
    """All-equal scores (round 0 with base_score): score normalization must
    be skipped identically on both paths."""
    from xgboost_tpu.objective.ranking import (_lambda_gradients_topk,
                                               _lambda_gradients_topk_native,
                                               make_group_layout)

    rng = np.random.default_rng(1)
    gptr = np.array([0, 20, 50], np.int32)
    R = 50
    pred = np.full(R, 0.5, np.float32)
    y = rng.integers(0, 4, size=R).astype(np.float32)
    idx, mask, inv = make_group_layout(gptr)
    ga, ha = _lambda_gradients_topk_native(
        jnp.asarray(pred), jnp.asarray(y), jnp.asarray(gptr), k=32,
        ndcg_weight=True, score_norm=True, group_norm=True)
    gb, hb = _lambda_gradients_topk(
        jnp.asarray(pred), jnp.asarray(y), jnp.asarray(idx),
        jnp.asarray(mask), jnp.asarray(inv), k=32, ndcg_weight=True,
        score_norm=True, group_norm=True)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb), rtol=2e-4,
                               atol=2e-6)
    np.testing.assert_allclose(np.asarray(ha), np.asarray(hb), rtol=2e-4,
                               atol=2e-6)
