"""Categorical feature support (reference: tests/python/test_updaters.py
categorical cases; python-package/xgboost/testing/ordinal.py)."""
import numpy as np
import pandas as pd
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.testing.data import make_categorical


@pytest.fixture(scope="module")
def cat_data():
    df, y = make_categorical(800, num_f=3, cat_f=2, n_cats=8, seed=0)
    return df, y


def test_categorical_training_improves(cat_data):
    df, y = cat_data
    d = xtb.DMatrix(df, label=y)
    assert d.feature_types == ["q", "q", "q", "c", "c"]
    res = {}
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 4}, d, 15,
                    evals=[(d, "t")], evals_result=res, verbose_eval=False)
    assert res["t"]["rmse"][-1] < 0.3 * res["t"]["rmse"][0]
    assert sum(len(t.categories or {}) for t in bst.trees) > 0


def test_onehot_vs_partition_regimes(cat_data):
    df, y = cat_data
    d = xtb.DMatrix(df, label=y)
    oh = xtb.train({"objective": "reg:squarederror", "max_cat_to_onehot": 64,
                    "max_depth": 3}, d, 5, verbose_eval=False)
    sizes = {len(c) for t in oh.trees for c in (t.categories or {}).values()}
    assert sizes == {1}  # one-hot: single category routed right
    part = xtb.train({"objective": "reg:squarederror", "max_cat_to_onehot": 2,
                      "max_depth": 3}, d, 5, verbose_eval=False)
    sizes = {len(c) for t in part.trees for c in (t.categories or {}).values()}
    assert max(sizes) > 1  # partition splits use multi-category sets


def test_categorical_save_load_exact(cat_data, tmp_path):
    df, y = cat_data
    d = xtb.DMatrix(df, label=y)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 4}, d, 8,
                    verbose_eval=False)
    f = str(tmp_path / "cat.json")
    bst.save_model(f)
    b2 = xtb.Booster()
    b2.load_model(f)
    np.testing.assert_array_equal(bst.predict(d), b2.predict(d))
    f2 = str(tmp_path / "cat.ubj")
    bst.save_model(f2)
    b3 = xtb.Booster()
    b3.load_model(f2)
    np.testing.assert_array_equal(bst.predict(d), b3.predict(d))


def test_unseen_category_goes_left(cat_data):
    df, y = cat_data
    d = xtb.DMatrix(df, label=y)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 3}, d, 5,
                    verbose_eval=False)
    # craft rows with an out-of-range category code (common/categorical.h:
    # out-of-bitset -> not in set -> LEFT)
    X = d.host_dense()[:5].copy()
    X[:, 3] = 99.0
    p = bst.predict(xtb.DMatrix(X, feature_types=d.feature_types))
    assert np.isfinite(p).all()


def test_categorical_nan_uses_default_direction(cat_data):
    df, y = cat_data
    d = xtb.DMatrix(df, label=y)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 3}, d, 5,
                    verbose_eval=False)
    X = d.host_dense()[:10].copy()
    X[:, 3] = np.nan
    X[:, 4] = np.nan
    p = bst.predict(xtb.DMatrix(X, feature_types=d.feature_types))
    assert np.isfinite(p).all()


def test_categorical_matches_bruteforce_partition():
    """Partition split on a single categorical feature must find the optimal
    G/H-sorted prefix (oracle: enumerate all category subsets)."""
    rng = np.random.default_rng(7)
    n_cats = 6
    codes = rng.integers(0, n_cats, 400)
    effect = np.array([2.0, -1.0, 0.5, 3.0, -2.0, 0.0])
    y = effect[codes] + 0.01 * rng.normal(size=400)
    df = pd.DataFrame({"c": pd.Categorical.from_codes(codes, [f"x{i}" for i in range(n_cats)])})
    d = xtb.DMatrix(df, label=y.astype(np.float32))
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 1,
                     "max_cat_to_onehot": 2, "lambda": 0.0,
                     "min_child_weight": 0.0}, d, 1, verbose_eval=False)
    tree = bst.trees[0]
    assert tree.split_type[0] == 1
    right_set = set(tree.categories[0].tolist())
    # brute force best subset by squared-error gain
    import itertools

    g = (0.0 - y)  # grad at margin ~ mean? use raw: base = mean(y) subtracted
    base = y.mean()
    g = base - y
    h = np.ones_like(y)
    best_gain, best_set = -1, None
    for r in range(1, n_cats):
        for S in itertools.combinations(range(n_cats), r):
            m = np.isin(codes, S)
            GL, HL = g[~m].sum(), h[~m].sum()
            GR, HR = g[m].sum(), h[m].sum()
            if HL == 0 or HR == 0:
                continue
            gain = GL**2 / HL + GR**2 / HR - g.sum()**2 / h.sum()
            if gain > best_gain:
                best_gain, best_set = gain, set(S)
    assert right_set == best_set or (set(range(n_cats)) - right_set) == best_set


def test_category_recode_between_frames(tmp_path):
    """A frame whose category->code mapping differs from training must be
    recoded onto the training ordering (reference: encoder/ordinal.h:350
    Recode; round-1 verdict Missing #8: silent mis-routing)."""
    import pandas as pd

    rng = np.random.default_rng(0)
    n = 1200
    colors = ["red", "green", "blue", "yellow"]
    col = rng.choice(colors, size=n)
    num = rng.normal(size=n).astype(np.float32)
    y = ((col == "red") | (col == "blue")).astype(np.float32) + 0.01 * num

    df_train = pd.DataFrame({
        "c": pd.Categorical(col, categories=colors),
        "x": num,
    })
    d = xtb.DMatrix(df_train, label=y, enable_categorical=True)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 4,
                     "max_cat_to_onehot": 1}, d, 8, verbose_eval=False)
    p_train = bst.predict(d)

    # same DATA, categories declared in a different order -> different codes
    df_flip = pd.DataFrame({
        "c": pd.Categorical(col, categories=colors[::-1]),
        "x": num,
    })
    d_flip = xtb.DMatrix(df_flip, enable_categorical=True)
    p_flip = bst.predict(d_flip)
    np.testing.assert_allclose(p_flip, p_train, rtol=1e-6, atol=1e-6)

    # recode survives save/load
    fn = str(tmp_path / "cat.json")
    bst.save_model(fn)
    b2 = xtb.Booster()
    b2.load_model(fn)
    np.testing.assert_allclose(b2.predict(d_flip), p_train,
                               rtol=1e-6, atol=1e-6)

    # unseen category at inference raises (not silent misroute)
    df_bad = pd.DataFrame({
        "c": pd.Categorical(["purple"] + list(col[1:]),
                            categories=["purple"] + colors),
        "x": num,
    })
    with pytest.raises(ValueError, match="purple"):
        bst.predict(xtb.DMatrix(df_bad, enable_categorical=True))


def test_high_cardinality_partition_quality():
    """64-category feature through the sorted-set partition path: the
    learned right-set must capture the high-effect categories well enough
    to beat a numeric treatment of the same column (the reason the
    partition evaluator exists — evaluate_splits.cu sorted-gradient
    enumeration)."""
    rng = np.random.default_rng(7)
    n, n_cat = 4000, 64
    c = rng.integers(0, n_cat, size=n)
    effect = rng.normal(scale=2.0, size=n_cat)
    y = (effect[c] + 0.3 * rng.normal(size=n)).astype(np.float32)
    X = c.astype(np.float32)[:, None]

    d_cat = xtb.DMatrix(X, label=y, feature_types=["c"],
                        enable_categorical=True)
    d_num = xtb.DMatrix(X, label=y)
    p = {"objective": "reg:squarederror", "max_depth": 4, "eta": 0.5,
         "max_bin": 128}
    b_cat = xtb.train(p, d_cat, 8, verbose_eval=False)
    b_num = xtb.train(p, d_num, 8, verbose_eval=False)
    mse_cat = float(np.mean((b_cat.predict(d_cat) - y) ** 2))
    mse_num = float(np.mean((b_num.predict(d_num) - y) ** 2))
    assert mse_cat < mse_num * 0.8, (mse_cat, mse_num)


def test_categorical_model_json_schema_and_dump():
    """Categorical splits serialize with the reference schema fields
    (split_type=1, categories/categories_segments arrays) and dump with
    set-membership syntax, so oracle-side tooling can read our models."""
    import json as _json

    rng = np.random.default_rng(8)
    n = 1000
    c = rng.integers(0, 12, size=n)
    y = ((c % 3 == 0).astype(np.float32)
         + 0.1 * rng.normal(size=n)).astype(np.float32)
    d = xtb.DMatrix(c.astype(np.float32)[:, None], label=y,
                    feature_types=["c"], enable_categorical=True)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 3,
                     "max_bin": 32, "max_cat_to_onehot": 1}, d, 2,
                    verbose_eval=False)
    obj = _json.loads(bytes(bst.save_raw("json")))
    trees = obj["learner"]["gradient_booster"]["model"]["trees"]
    assert any(any(int(t) == 1 for t in tr.get("split_type", []))
               for tr in trees), "no categorical split recorded"
    assert any(tr.get("categories") for tr in trees)
    # text dump shows the set-membership condition
    dump = "\n".join(bst.get_dump())
    assert "{" in dump and "}" in dump


def test_categorical_distributed_matches_single():
    """Categorical splits under 2-thread process parallelism: identical
    trees on both ranks and close to single-process quality (the cat_set
    rides the same histogram allreduce as numeric splits)."""
    import hashlib
    import threading

    from xgboost_tpu import collective

    rng = np.random.default_rng(9)
    n = 2000
    Xn = rng.normal(size=(n, 2)).astype(np.float32)
    c = rng.integers(0, 8, size=n)
    y = (Xn[:, 0] + (c % 2) + 0.2 * rng.normal(size=n)).astype(np.float32)
    X = np.column_stack([Xn, c.astype(np.float32)])

    hashes, errors, preds_holder = {}, {}, {}

    def worker(rank):
        try:
            with collective.CommunicatorContext(
                    dmlc_communicator="in-memory", in_memory_world_size=2,
                    in_memory_rank=rank, in_memory_group="catdist"):
                Xs, ys = X[rank::2], y[rank::2]
                d = xtb.DMatrix(Xs, label=ys,
                                feature_types=["q", "q", "c"],
                                enable_categorical=True)
                bst = xtb.train({"objective": "reg:squarederror",
                                 "max_depth": 4, "max_bin": 32}, d, 3,
                                verbose_eval=False)
                hashes[rank] = hashlib.md5("".join(
                    bst.get_dump(dump_format="json")).encode()).hexdigest()
                if rank == 0:
                    da = xtb.DMatrix(X, feature_types=["q", "q", "c"],
                                     enable_categorical=True)
                    preds_holder["mse"] = float(
                        np.mean((bst.predict(da) - y) ** 2))
        except Exception as e:  # noqa: BLE001
            errors[rank] = e

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=600)
    assert not any(t.is_alive() for t in ts)
    assert not errors, errors
    assert hashes[0] == hashes[1]

    # and the distributed model must be near single-process quality on the
    # union (identical-but-wrong on both ranks would pass the hash check)
    d_all = xtb.DMatrix(X, label=y, feature_types=["q", "q", "c"],
                        enable_categorical=True)
    b_single = xtb.train({"objective": "reg:squarederror", "max_depth": 4,
                          "max_bin": 32}, d_all, 3, verbose_eval=False)
    mse_single = float(np.mean((b_single.predict(d_all) - y) ** 2))
    assert preds_holder, "rank 0 predictions missing"
    mse_dist = preds_holder["mse"]
    assert mse_dist <= mse_single * 1.3 + 1e-3, (mse_dist, mse_single)
