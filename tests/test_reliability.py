"""Reliability subsystem: retry/backoff, deterministic fault injection,
crash-safe checkpoints, and train(resume_from=) parity.

The contract under test (docs/reliability.md): a run interrupted at an
arbitrary round and resumed from its newest valid checkpoint produces the
SAME final model bytes as a run that was never interrupted; corrupt
checkpoint files are skipped with a warning, never trusted; retries and
faults are deterministic and visible in telemetry.
"""
import json
import os
import socket
import warnings

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.reliability import (CheckpointCallback, FaultInjected,
                                     RetriesExhausted, backoff_delays,
                                     faults, latest_checkpoint, retry_call)
from xgboost_tpu.reliability.checkpoint import (CheckpointManager,
                                                CheckpointState)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


# =========================================================================
# retry / backoff


def test_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    slept = []
    assert retry_call(flaky, op="t1", retries=5, sleep=slept.append) == "ok"
    assert len(calls) == 3 and len(slept) == 2
    assert slept[1] > slept[0]  # exponential growth survives the jitter


def test_retry_exhaustion_chains_last_error():
    def always():
        raise OSError("down")

    with pytest.raises(RetriesExhausted) as ei:
        retry_call(always, op="t2", retries=2, sleep=lambda d: None)
    assert isinstance(ei.value.__cause__, OSError)


def test_retry_does_not_swallow_undeclared_exceptions():
    def bug():
        raise KeyError("logic bug, not transience")

    with pytest.raises(KeyError):
        retry_call(bug, op="t3", retries=5, sleep=lambda d: None)


def test_backoff_jitter_is_deterministic_and_rank_staggered():
    a = list(backoff_delays(6, op="connect", seed=3))
    b = list(backoff_delays(6, op="connect", seed=3))
    c = list(backoff_delays(6, op="connect", seed=4))
    assert a == b          # same (op, seed) -> same schedule, every run
    assert a != c          # different ranks de-synchronize
    assert all(d <= 10.0 * 1.25 + 1e-9 for d in a)


def test_retries_counted_in_telemetry():
    from xgboost_tpu.telemetry.registry import get_registry

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise OSError("x")
        return 1

    retry_call(flaky, op="telemetry_probe", retries=3, sleep=lambda d: None)
    fam = get_registry().get("xtb_retries_total")
    assert fam is not None and fam.get("telemetry_probe") >= 1


# =========================================================================
# fault plan


def test_fault_plan_matchers_and_times():
    faults.install({"faults": [
        {"site": "s", "kind": "exception", "at": 2},
    ]})
    assert faults.maybe_inject("s") is None
    assert faults.maybe_inject("s") is None
    with pytest.raises(FaultInjected):
        faults.maybe_inject("s")
    # times=1 (default): exhausted even though `at` keeps matching nothing
    assert faults.maybe_inject("s") is None
    assert faults.active().fired("s") == 1


def test_fault_plan_round_and_rank_matchers():
    faults.install({"faults": [
        {"site": "r", "kind": "exception", "round": 5, "rank": 1},
    ]})
    assert faults.maybe_inject("r", rank=0, round=5) is None
    assert faults.maybe_inject("r", rank=1, round=4) is None
    with pytest.raises(FaultInjected):
        faults.maybe_inject("r", rank=1, round=5)


def test_fault_rank_callable_resolved_lazily():
    probed = []

    def rank():
        probed.append(1)
        return 0

    faults.install({"faults": [{"site": "a", "kind": "delay"}]})
    faults.maybe_inject("a", rank=rank)     # no rank-constrained spec
    assert not probed
    faults.install({"faults": [{"site": "a", "kind": "delay", "rank": 0}]})
    faults.maybe_inject("a", rank=rank)
    assert probed


def test_fault_plan_env_inline_and_file(tmp_path, monkeypatch):
    plan = {"faults": [{"site": "e", "kind": "exception"}]}
    monkeypatch.setenv(faults.ENV_VAR, json.dumps(plan))
    faults.clear()
    with pytest.raises(FaultInjected):
        faults.maybe_inject("e")
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(plan))
    monkeypatch.setenv(faults.ENV_VAR, str(p))
    faults.clear()
    with pytest.raises(FaultInjected):
        faults.maybe_inject("e")


def test_fault_plan_rejects_unknown_keys_and_kinds():
    with pytest.raises(ValueError):
        faults.install({"faults": [{"site": "x", "kind": "nuke"}]})
    with pytest.raises(ValueError):
        faults.install({"faults": [{"site": "x", "kind": "kill",
                                    "banana": 1}]})


def test_faults_counted_in_telemetry():
    from xgboost_tpu.telemetry.registry import get_registry

    faults.install({"faults": [{"site": "counted", "kind": "delay",
                                "seconds": 0.0}]})
    faults.maybe_inject("counted")
    fam = get_registry().get("xtb_faults_injected_total")
    assert fam is not None and fam.get("counted", "delay") >= 1


# =========================================================================
# degraded-network fault kinds (docs/reliability.md "Degraded networks")


def _spec_for(kind, site="s", **kw):
    faults.install({"faults": [dict({"site": site, "kind": kind}, **kw)]})
    return faults.active().specs[0]


def test_jitter_seconds_seeded_per_invocation():
    """latency-kind jitter is a pure function of (seed, invocation):
    frame N of a replay jitters by exactly what frame N drew last run."""
    spec = _spec_for("latency", seconds=0.5, jitter_seed=7)
    draws = [faults.jitter_seconds(spec, i) for i in range(64)]
    assert draws == [faults.jitter_seconds(spec, i) for i in range(64)]
    assert all(0.0 <= d < 0.5 for d in draws)
    assert len(set(draws)) > 32  # per-frame variation, not one constant
    other = _spec_for("latency", seconds=0.5, jitter_seed=8)
    assert [faults.jitter_seconds(other, i) for i in range(64)] != draws


def test_throttle_seconds_is_link_arithmetic():
    spec = _spec_for("throttle", bytes_per_s=1_000_000.0)
    assert faults.throttle_seconds(spec, 500_000) == pytest.approx(0.5)
    assert faults.throttle_seconds(spec, 0) == 0.0
    # an unshaped (rate <= 0) spec delays nothing rather than dividing
    assert faults.throttle_seconds(_spec_for("throttle"), 1 << 20) == 0.0


def test_partition_blocks_stable_seeded_bipartition():
    """One seed cuts a deterministic peer subset; the same seed at a
    different seam cuts an independent side (that independence is what
    makes a single plan produce asymmetric, half-open links); a peer
    unknown at the seam is never blocked."""
    peers = [f"replica{i}" for i in range(16)] + list(range(16))
    tx = _spec_for("partition", site="tx", jitter_seed=5)
    cut = {p for p in peers if faults.partition_blocks(tx, p)}
    assert cut == {p for p in peers if faults.partition_blocks(tx, p)}
    assert 0 < len(cut) < len(peers)
    assert faults.partition_blocks(tx, None) is False
    rx = _spec_for("partition", site="rx", jitter_seed=5)
    rx_cut = {p for p in peers if faults.partition_blocks(rx, p)}
    assert rx_cut != cut  # site-salted: each seam draws its own side
    # some peer's tx side is cut while its rx side is not: the half-open
    # wedge the degraded-network scenarios lean on
    assert any(p in cut and p not in rx_cut for p in peers)


def test_degraded_kinds_at_the_seam():
    """latency sleeps its seeded jitter inline (and is returned so the
    seam can log); the caller-applied kinds come back as specs, budgeted
    by ``times`` like every other kind."""
    faults.install({"faults": [{"site": "s", "kind": "latency",
                                "seconds": 0.0, "times": 2}]})
    assert faults.maybe_inject("s").kind == "latency"
    assert faults.maybe_inject("s").kind == "latency"
    assert faults.maybe_inject("s") is None  # budget spent
    assert faults.active().fired("s") == 2
    for kind in ("throttle", "blackhole_rx", "blackhole_tx", "partition"):
        spec = _spec_for(kind)
        assert faults.maybe_inject("s") is spec


# =========================================================================
# checkpoint manager (atomicity, keep-last-K, corruption fallback)


def _mk_state(round_, payload=b"model-bytes", hist=None):
    return CheckpointState(round=round_, booster_bytes=payload,
                           history=hist or {"t": {"rmse": [0.5]}},
                           callback_state={})


def test_checkpoint_roundtrip_and_keep_last(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last=2)
    for r in (1, 2, 3, 4):
        mgr.save(_mk_state(r, payload=bytes([r]) * 64))
    assert len(mgr.files()) == 2  # pruned to keep-last-K
    st = mgr.load_latest()
    assert st.round == 4 and st.booster_bytes == bytes([4]) * 64
    assert st.history == {"t": {"rmse": [0.5]}}


def test_checkpoint_write_leaves_no_tmp_droppings(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last=3)
    mgr.save(_mk_state(1))
    names = os.listdir(tmp_path)
    assert all(n.endswith(".xtbckpt") for n in names), names


@pytest.mark.parametrize("mutate", ["zero", "truncate_tail", "truncate_head",
                                    "bitflip", "garbage"])
def test_checkpoint_corruption_fallback_fuzz(tmp_path, mutate):
    """Style of test_model_io_fuzz: every damaged newest-file variant is
    skipped WITH a warning and load falls back to the older valid one."""
    mgr = CheckpointManager(str(tmp_path), keep_last=3)
    mgr.save(_mk_state(1, payload=b"a" * 200))
    mgr.save(_mk_state(2, payload=b"b" * 200))
    newest = mgr.files()[-1]
    blob = bytearray(open(newest, "rb").read())
    if mutate == "zero":
        blob = bytearray()
    elif mutate == "truncate_tail":
        blob = blob[: len(blob) // 2]
    elif mutate == "truncate_head":
        blob = blob[10:]
    elif mutate == "bitflip":
        blob[len(blob) // 2] ^= 0x40
    elif mutate == "garbage":
        blob = bytearray(os.urandom(len(blob)))
    with open(newest, "wb") as fh:
        fh.write(blob)
    with pytest.warns(RuntimeWarning, match="invalid checkpoint"):
        st = mgr.load_latest()
    assert st is not None and st.round == 1
    assert st.booster_bytes == b"a" * 200


def test_checkpoint_bitflip_sweep_never_half_loads(tmp_path):
    """Random single-byte corruptions anywhere in the file must either be
    rejected (fall back) — a flipped byte can never produce a 'valid' state
    with different bytes, the checksum guarantees it."""
    mgr = CheckpointManager(str(tmp_path), keep_last=2)
    mgr.save(_mk_state(7, payload=b"x" * 333))
    good = open(mgr.files()[-1], "rb").read()
    rng = np.random.default_rng(0)
    for _ in range(25):
        blob = bytearray(good)
        blob[int(rng.integers(0, len(blob)))] ^= int(rng.integers(1, 256))
        with open(mgr.files()[-1], "wb") as fh:
            fh.write(blob)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            st = mgr.load_latest()
        assert st is None  # the only file is damaged -> nothing to trust
    with open(mgr.files()[-1] if mgr.files() else
              os.path.join(str(tmp_path), "ckpt_00000007.xtbckpt"),
              "wb") as fh:
        fh.write(good)
    assert mgr.load_latest().round == 7  # pristine bytes still load


def test_checkpoint_all_corrupt_returns_none(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last=3)
    mgr.save(_mk_state(1))
    for p in mgr.files():
        with open(p, "wb") as fh:
            fh.write(b"")
    with pytest.warns(RuntimeWarning):
        assert mgr.load_latest() is None
    assert latest_checkpoint(str(tmp_path / "missing")) is None


def test_truncate_fault_seam_corrupts_then_falls_back(tmp_path):
    """The checkpoint.write truncate fault produces exactly the torn-write
    artifact load_latest must survive."""
    mgr = CheckpointManager(str(tmp_path), keep_last=3)
    mgr.save(_mk_state(1, payload=b"ok" * 100))
    faults.install({"faults": [{"site": "checkpoint.write",
                                "kind": "truncate", "round": 2}]})
    mgr.save(_mk_state(2, payload=b"no" * 100))
    faults.clear()
    assert len(mgr.files()) == 2  # the torn file DID commit under its name
    with pytest.warns(RuntimeWarning, match="invalid checkpoint"):
        st = mgr.load_latest()
    assert st.round == 1 and st.booster_bytes == b"ok" * 100


# =========================================================================
# CheckpointCallback + train(resume_from=) parity


def _data(seed=0, n=800, f=6):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    X[rng.random((n, f)) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) + np.nan_to_num(X[:, 1]) ** 2 > 0.5
         ).astype(np.float32)
    return X, y


PARAMS = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
          "max_bin": 32}


def test_kill_resume_parity_bitwise(tmp_path):
    """Interrupt at round 3 of 6 via an injected fault, resume from the
    checkpoint directory: the final model's UBJSON bytes equal the
    uninterrupted run's (the acceptance bit-parity contract, single
    process; test_reliability_multiprocess.py holds it multi-process)."""
    X, y = _data()
    full = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 6, verbose_eval=False)

    ckpt = str(tmp_path / "ckpt")
    faults.install({"faults": [{"site": "train.round", "kind": "exception",
                                "round": 3}]})
    with pytest.raises(FaultInjected):
        xtb.train(PARAMS, xtb.DMatrix(X, label=y), 6, verbose_eval=False,
                  callbacks=[CheckpointCallback(ckpt, interval=1)])
    faults.clear()
    st = latest_checkpoint(ckpt)
    assert st is not None and st.round == 3

    res = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 6, verbose_eval=False,
                    resume_from=ckpt,
                    callbacks=[CheckpointCallback(ckpt, interval=1)])
    assert res.num_boosted_rounds() == 6
    assert bytes(res.save_raw()) == bytes(full.save_raw())


def test_resume_total_round_semantics(tmp_path):
    """num_boost_round is the TOTAL target under resume: a relaunch whose
    checkpoint already reached it trains zero extra rounds."""
    X, y = _data(seed=2)
    ckpt = str(tmp_path / "c")
    bst = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 4, verbose_eval=False,
                    callbacks=[CheckpointCallback(ckpt)])
    res = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 4, verbose_eval=False,
                    resume_from=ckpt)
    assert res.num_boosted_rounds() == 4
    assert bytes(res.save_raw()) == bytes(bst.save_raw())


def test_resume_from_empty_dir_is_fresh_start(tmp_path):
    X, y = _data(seed=3)
    res = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 3, verbose_eval=False,
                    resume_from=str(tmp_path / "nothing_here"))
    full = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    assert bytes(res.save_raw()) == bytes(full.save_raw())


def test_resume_from_takes_precedence_over_xgb_model(tmp_path):
    """The documented precedence: when resume_from holds a valid
    checkpoint, it wins over xgb_model (and num_boost_round becomes the
    TOTAL target); an EMPTY resume_from falls through to the xgb_model
    continuation with additive round semantics.  The lifecycle manager's
    crash-safe continuation leans on exactly this contract."""
    X, y = _data(seed=5)
    ckpt_model = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 4,
                           verbose_eval=False)
    # a decoy continuation base, deliberately DIFFERENT from the
    # checkpointed model (other seed -> other trees)
    decoy = xtb.train({**PARAMS, "seed": 99},
                      xtb.DMatrix(X[::2], label=y[::2]), 2,
                      verbose_eval=False)

    ckpt = str(tmp_path / "ckpt")
    xtb.train(PARAMS, xtb.DMatrix(X, label=y), 4, verbose_eval=False,
              callbacks=[CheckpointCallback(ckpt)])
    # both passed: the checkpoint wins, the decoy is ignored, and 6 is the
    # TOTAL target (4 checkpointed + 2 more)
    res = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 6, verbose_eval=False,
                    xgb_model=decoy, resume_from=ckpt)
    assert res.num_boosted_rounds() == 6
    expect = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 2,
                       verbose_eval=False, xgb_model=ckpt_model)
    assert bytes(res.save_raw()) == bytes(expect.save_raw())

    # empty checkpoint dir: xgb_model is honored, rounds are ADDITIVE
    res2 = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 2, verbose_eval=False,
                     xgb_model=decoy,
                     resume_from=str(tmp_path / "never_written"))
    assert res2.num_boosted_rounds() == decoy.num_boosted_rounds() + 2
    cont = xtb.train(PARAMS, xtb.DMatrix(X, label=y), 2, verbose_eval=False,
                     xgb_model=decoy)
    assert bytes(res2.save_raw()) == bytes(cont.save_raw())


def test_resume_restores_eval_history_and_early_stopping(tmp_path):
    """History and EarlyStopping patience survive the crash: the resumed
    run's evals_result and stopping round match the uninterrupted run's."""
    X, y = _data(seed=4)
    dtrain = xtb.DMatrix(X, label=y)
    dval = xtb.DMatrix(X[:200], label=y[:200])
    kw = dict(evals=[(dval, "v")], early_stopping_rounds=3,
              verbose_eval=False)

    full_res = {}
    full = xtb.train({**PARAMS, "eval_metric": "logloss"}, dtrain, 8,
                     evals_result=full_res, **kw)

    ckpt = str(tmp_path / "es")
    faults.install({"faults": [{"site": "train.round", "kind": "exception",
                                "round": 4}]})
    with pytest.raises(FaultInjected):
        xtb.train({**PARAMS, "eval_metric": "logloss"},
                  xtb.DMatrix(X, label=y), 8,
                  callbacks=[CheckpointCallback(ckpt)], **kw)
    faults.clear()

    # ordering guard: the checkpoint must capture THIS round's EarlyStopping
    # decision (train() dispatches run-last callbacks after the rest) — a
    # one-round-stale state would resume with the wrong patience/best
    st = latest_checkpoint(ckpt)
    es_state = st.callback_state["EarlyStopping@0"]
    assert (len(es_state["best_scores"]) + es_state["current_rounds"]
            == st.round), (es_state, st.round)

    res_res = {}
    res = xtb.train({**PARAMS, "eval_metric": "logloss"},
                    xtb.DMatrix(X, label=y), 8, resume_from=ckpt,
                    evals_result=res_res,
                    callbacks=[CheckpointCallback(ckpt)], **kw)
    assert res.best_iteration == full.best_iteration
    assert res_res["v"]["logloss"] == full_res["v"]["logloss"]
    assert bytes(res.save_raw()) == bytes(full.save_raw())


def test_checkpoint_callback_interval_and_cv_safety(tmp_path):
    X, y = _data(seed=5)
    ckpt = str(tmp_path / "iv")
    xtb.train(PARAMS, xtb.DMatrix(X, label=y), 6, verbose_eval=False,
              callbacks=[CheckpointCallback(ckpt, interval=2, keep_last=2)])
    rounds = [int(os.path.basename(p)[5:13])
              for p in CheckpointManager(ckpt).files()]
    assert rounds == [4, 6]  # every 2nd round, pruned to keep-last 2
    # cv's aggregate stand-in has no serialize(); the callback must no-op,
    # not crash the fold loop
    xtb.cv(PARAMS, xtb.DMatrix(X, label=y), num_boost_round=2, nfold=2,
           callbacks=[CheckpointCallback(str(tmp_path / "cv"))])


def test_checkpoint_telemetry_series_present(tmp_path):
    X, y = _data(seed=6)
    xtb.train(PARAMS, xtb.DMatrix(X, label=y), 2, verbose_eval=False,
              callbacks=[CheckpointCallback(str(tmp_path / "t"))])
    from xgboost_tpu.telemetry import render_prometheus

    prom = render_prometheus()
    assert "xtb_checkpoint_seconds_bucket" in prom
    assert "xtb_checkpoints_total" in prom


# =========================================================================
# tracker robustness satellites


def test_get_host_ip_falls_back_with_warning(monkeypatch):
    from xgboost_tpu import tracker as tr

    class Boom:
        def __init__(self, *a, **k):
            raise OSError("no interfaces")

    monkeypatch.setattr(tr.socket, "socket", Boom)
    with pytest.warns(RuntimeWarning, match="127.0.0.1"):
        assert tr.get_host_ip("auto") == "127.0.0.1"
    # explicit addresses pass through untouched (and un-warned)
    assert tr.get_host_ip("10.0.0.5") == "10.0.0.5"


def test_recv_msg_timeout_is_a_detected_fault():
    """A peer that connects and then goes silent trips the per-operation
    timeout (an OSError) instead of wedging the reader forever."""
    from xgboost_tpu.tracker import recv_msg, send_msg

    a, b = socket.socketpair()
    try:
        with pytest.raises(OSError):
            recv_msg(a, timeout=0.2)
        # the timeout is per-operation: the socket still works afterwards
        send_msg(b, {"cmd": "ping"}, timeout=5.0)
        assert recv_msg(a, timeout=5.0) == {"cmd": "ping"}
    finally:
        a.close()
        b.close()


def test_recv_msg_slow_loris_trickle_bounded():
    """A peer drip-feeding one byte per interval must exhaust ONE
    cumulative message budget (clocked from the first byte's arrival),
    not reset the per-recv timeout on every byte."""
    import threading
    import time

    from xgboost_tpu.tracker import recv_msg, send_msg

    a, b = socket.socketpair()
    c, d = socket.socketpair()
    try:
        send_msg(b, {"cmd": "ping", "pad": "x" * 200}, timeout=5.0)
        b.shutdown(socket.SHUT_WR)
        blob = b"".join(iter(lambda: a.recv(4096), b""))

        def _trickle():
            try:
                for i in range(len(blob)):
                    c.sendall(blob[i:i + 1])
                    time.sleep(0.05)
            except OSError:
                pass  # the reader gave up and closed: expected

        threading.Thread(target=_trickle, daemon=True).start()
        t0 = time.monotonic()
        with pytest.raises(OSError):
            recv_msg(d, timeout=0.5)
        # one budget for the whole message, not budget * bytes
        assert time.monotonic() - t0 < 5.0
    finally:
        for s in (a, b, c, d):
            s.close()


def test_send_msg_trailing_rides_the_fault_decision():
    """A header announcing a payload and the payload itself are one
    atomic fault unit: a blackhole_tx that swallows the header must
    swallow the trailing bytes too — a swallowed header followed by
    loose payload bytes would desync the peer's framing (corruption,
    not a network fault)."""
    from xgboost_tpu.tracker import recv_msg, send_msg

    a, b = socket.socketpair()
    try:
        faults.install({"faults": [{"site": "tracker.message",
                                    "kind": "blackhole_tx", "times": 1}]})
        send_msg(a, {"cmd": "coll", "nbytes": 4}, timeout=5.0,
                 trailing=b"\x00\x01\x02\x03")
        # the frame vanished WITH its payload: the next message parses
        # cleanly instead of reading payload bytes as a length prefix
        send_msg(a, {"cmd": "ping"}, timeout=5.0)
        assert recv_msg(b, timeout=5.0) == {"cmd": "ping"}
    finally:
        faults.clear()
        a.close()
        b.close()


def test_tracker_connect_retries_through_injected_failures():
    """The connect seam: two injected failures, then the real connection
    succeeds — counted as retries, invisible to the caller."""
    from xgboost_tpu.tracker import RabitTracker, TrackerClient

    tr = RabitTracker(n_workers=1, host_ip="127.0.0.1")
    tr.start()
    faults.install({"faults": [{"site": "tracker.connect",
                                "kind": "exception", "times": 2}]})
    try:
        c = TrackerClient("127.0.0.1", tr.port, timeout=30)
        assert c.rank == 0 and c.world == 1
        if c.coordinator:  # rank 0 reports, completing the bootstrap
            pass
        c.shutdown()
        tr.wait_for(timeout=30)
    finally:
        faults.clear()
        tr.free()
