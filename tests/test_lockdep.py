"""Runtime lockdep witness (reliability/lockdep.py).

The suite-wide conftest arms the witness (XGBOOST_TPU_LOCKDEP=1 before
the first package import), so these tests exercise the REAL armed
configuration: patched factories, wrapped package locks, the seam hook
in faults.maybe_inject.  Tests that provoke reports deliberately clear
them (the session fixture asserts the suite ends report-free).
"""
import os
import subprocess
import sys
import threading
import time

import pytest

from xgboost_tpu.reliability import faults, lockdep

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_witness():
    lockdep.clear()
    yield
    lockdep.clear()


def _run_py(code, **env):
    return subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu",
             **env})


def test_armed_in_suite_and_package_locks_wrapped():
    assert lockdep.enabled()
    from xgboost_tpu.telemetry import flight

    key = getattr(flight._lock, "_xtb_key", None)
    assert key is not None and key.startswith("telemetry/flight.py:")


def test_off_by_default_nothing_patched():
    p = _run_py(
        "import threading, _thread\n"
        "import xgboost_tpu\n"
        "from xgboost_tpu.reliability import lockdep\n"
        "assert not lockdep.enabled()\n"
        "assert threading.Lock is _thread.allocate_lock\n"
        "print('raw')\n",
        XGBOOST_TPU_LOCKDEP="0")
    assert p.returncode == 0, p.stderr
    assert "raw" in p.stdout


def test_abba_inversion_reported_on_first_conflicting_acquire():
    a = lockdep.named_lock("t/abba_a")
    b = lockdep.named_lock("t/abba_b")

    def nest(first, second):
        with first:
            with second:
                pass

    t = threading.Thread(target=nest, args=(a, b))
    t.start(); t.join()
    assert lockdep.reports() == []  # one order established, no conflict
    t = threading.Thread(target=nest, args=(b, a))
    t.start(); t.join()
    kinds = [r["kind"] for r in lockdep.reports()]
    assert kinds == ["order"]
    msg = lockdep.reports()[0]["msg"]
    assert "t/abba_a" in msg and "t/abba_b" in msg


def test_consistent_order_stays_silent():
    a = lockdep.named_lock("t/cons_a")
    b = lockdep.named_lock("t/cons_b")
    for _ in range(3):
        with a:
            with b:
                pass
    assert lockdep.reports() == []


def test_bounded_acquire_adds_no_edges():
    # trylock/timeout acquires cannot deadlock: no edge, so the reversed
    # unbounded nesting later is a fresh (single) order, not an inversion
    a = lockdep.named_lock("t/bnd_a")
    b = lockdep.named_lock("t/bnd_b")
    with a:
        assert b.acquire(timeout=0.5)
        b.release()
    with b:
        with a:
            pass
    assert lockdep.reports() == []


def test_self_deadlock_check_plain_vs_rlock():
    c = lockdep.named_lock("t/self_c")
    c.acquire()
    lockdep._check_before_acquire("t/self_c", False)
    assert [r["kind"] for r in lockdep.reports()] == ["self-deadlock"]
    c.release()
    lockdep.clear()
    r = lockdep.named_lock("t/self_r", reentrant=True)
    with r:
        with r:  # real re-entrant acquire: legal, silent
            pass
    assert lockdep.reports() == []


def test_condition_on_wrapped_lock_works():
    cond = threading.Condition(lockdep.named_lock("t/cond", reentrant=True))
    woke = []

    def waiter():
        with cond:
            cond.wait(timeout=5.0)
            woke.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    with cond:
        cond.notify_all()
    t.join(5.0)
    assert woke == [1]
    assert lockdep.reports() == []


def test_seam_witness_fires_through_maybe_inject():
    lk = lockdep.named_lock("t/seam_lk")
    with lk:
        faults.maybe_inject("tracker.message")
    rs = lockdep.reports()
    assert [r["kind"] for r in rs] == ["seam"]
    assert "t/seam_lk" in rs[0]["msg"]
    assert "tracker.message" in rs[0]["msg"]
    # once per lock/seam pair: crossing again adds nothing
    with lk:
        faults.maybe_inject("tracker.message")
    assert len(lockdep.reports()) == 1


def test_mark_serial_waives_seam_and_ignores_raw_locks():
    lk = lockdep.mark_serial(lockdep.named_lock("t/serial_lk"))
    with lk:
        faults.maybe_inject("tracker.message")
    assert lockdep.reports() == []
    # raw (unwitnessed) lock: mark_serial is a harmless no-op
    import _thread

    raw = _thread.allocate_lock()
    assert lockdep.mark_serial(raw) is raw


def test_atexit_marker_printed_on_violation():
    p = _run_py(
        "from xgboost_tpu.reliability import lockdep, faults\n"
        "lk = lockdep.named_lock('t/x')\n"
        "with lk:\n"
        "    faults.maybe_inject('tracker.message')\n",
        XGBOOST_TPU_LOCKDEP="1")
    assert p.returncode == 0, p.stderr
    assert "XTB-LOCKDEP-VIOLATION: 1 report(s)" in p.stderr
    assert "t/x" in p.stderr


def test_raise_mode_raises_at_offending_acquire():
    p = _run_py(
        "from xgboost_tpu.reliability import lockdep, faults\n"
        "lk = lockdep.named_lock('t/x')\n"
        "try:\n"
        "    with lk:\n"
        "        faults.maybe_inject('tracker.message')\n"
        "except lockdep.LockdepViolation as e:\n"
        "    print('raised:', e)\n"
        "    lockdep.clear()\n",
        XGBOOST_TPU_LOCKDEP="1", XGBOOST_TPU_LOCKDEP_RAISE="1")
    assert p.returncode == 0, p.stderr
    assert "raised:" in p.stdout


def test_armed_training_run_stays_silent():
    # the tentpole acceptance shape in miniature: real training traffic
    # under the armed witness produces zero reports
    import numpy as np

    import xgboost_tpu as xtb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(120, 6))
    y = (X[:, 0] > 0).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3}, d,
                    num_boost_round=3)
    bst.predict(d)
    assert lockdep.reports() == []
