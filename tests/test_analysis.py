"""xtblint self-tests: every rule family fires on a violating fixture,
honors a line suppression, and stays quiet on a clean file — plus the
repo-gate test (`python -m xgboost_tpu.analysis xgboost_tpu/` exits 0)
and the no-blanket-suppressions sweep.

Fixtures are lint_source() snippets, so the tests pin the *detection
semantics* (what counts as traced, guarded, static, metric-shaped)
rather than whatever the tree happens to contain today.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from xgboost_tpu.analysis import lint_paths, lint_source, rule_catalog
from xgboost_tpu.analysis.reporters import render_json, render_text

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def codes(result):
    return [f.code for f in result.findings]


def src(text):
    return textwrap.dedent(text)


# ---------------------------------------------------------------------------
# XTB1xx retrace / host-sync hazards
# ---------------------------------------------------------------------------

def test_retrace_fires_on_host_sync_in_jit():
    r = lint_source(src("""
        import jax, jax.numpy as jnp, numpy as np

        @jax.jit
        def f(g, h):
            a = float(g)          # XTB101
            b = g.item()          # XTB102
            c = np.asarray(h)     # XTB103
            return a + b + c
    """))
    assert codes(r) == ["XTB101", "XTB102", "XTB103"]


def test_retrace_fires_in_function_passed_to_jit():
    # the parallel/grower.py pattern: closure handed to jax.jit(...)
    r = lint_source(src("""
        import jax

        def build():
            def level(state, x):
                return float(x)   # XTB101: traced via jax.jit(level)
            return jax.jit(level)
    """))
    assert codes(r) == ["XTB101"]


def test_retrace_static_args_and_locals_allowed():
    # static_argnames params, shape math, `is None`, and locals derived
    # from them are Python values at trace time — the FFI attribute
    # pattern in objective/ranking.py / ops/predict.py must stay clean
    r = lint_source(src("""
        import functools, jax, numpy as np

        @functools.partial(jax.jit, static_argnames=("k", "norm"))
        def f(x, y=None, *, k, norm):
            has_y = y is not None
            scale = float(k) / max(int(x.shape[0]), 1)
            return x * scale + np.int32(has_y) + np.int32(norm)
    """))
    assert codes(r) == []


def test_retrace_suppression_honored():
    r = lint_source(src("""
        import jax

        @jax.jit
        def f(g):
            return float(g)  # xtblint: disable=XTB101
    """))
    assert codes(r) == []
    assert [f.code for f in r.suppressed] == ["XTB101"]


def test_retrace_clean_outside_jit():
    # host-side driver code may sync freely (tree/bestfirst.py driver loop)
    r = lint_source(src("""
        import numpy as np

        def driver(gain):
            return float(gain) < 1e-6 and np.asarray(gain)
    """))
    assert codes(r) == []


# ---------------------------------------------------------------------------
# XTB2xx lock discipline
# ---------------------------------------------------------------------------

def test_locks_fire_on_unguarded_store():
    r = lint_source(src("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
            def bump(self):
                self.n += 1
            def sub(self, k):
                self.d[k] = 1
    """))
    assert codes(r) == ["XTB201", "XTB201"]


def test_locks_guarded_and_helper_fixpoint_clean():
    r = lint_source(src("""
        import threading

        class C:
            def __init__(self):
                self._cv = threading.Condition()
                self.n = 0
            def bump(self):
                with self._cv:
                    self._bump_locked()
            def _bump_locked(self):   # caller holds the lock: clean
                self.n += 1
    """))
    assert codes(r) == []


def test_locks_thread_target_does_not_inherit_guard():
    # a method whose reference escapes (Thread target) runs unlocked even
    # if some other call site is guarded
    r = lint_source(src("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = 0
            def start(self):
                threading.Thread(target=self._serve).start()
                with self._lock:
                    self._serve()
            def _serve(self):
                self.state = 1
    """))
    assert codes(r) == ["XTB201"]


def test_locks_lambda_wrapped_target_and_deferred_closures():
    # a closure runs whenever it is CALLED, not where it is written: no
    # credit for the ambient lock, and self.m() inside one is an escape
    r = lint_source(src("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = 0
            def start(self):
                with self._lock:
                    threading.Thread(target=lambda: self._serve()).start()
            def _serve(self):
                self.state = 1
            def deferred(self):
                with self._lock:
                    def cb():
                        self.state = 2        # runs later, unlocked
                    return cb
    """))
    assert codes(r) == ["XTB201", "XTB201"]


def test_locks_no_lock_no_findings():
    r = lint_source(src("""
        class Plain:
            def __init__(self):
                self.n = 0
            def bump(self):
                self.n += 1
    """))
    assert codes(r) == []


def test_locks_suppression_honored():
    r = lint_source(src("""
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.done = False
            def finish(self):
                self.done = True  # xtblint: disable=XTB201
    """))
    assert codes(r) == []
    assert [f.code for f in r.suppressed] == ["XTB201"]


# ---------------------------------------------------------------------------
# XTB3xx seam consistency
# ---------------------------------------------------------------------------

SEAM_DOCS = """# reliability\n\n| `train.round` | ... |\n"""


def test_seams_unknown_dead_and_undocumented(tmp_path):
    (tmp_path / "reliability.md").write_text(SEAM_DOCS)
    r = lint_source(src("""
        SEAMS = frozenset({"train.round", "ckpt.write"})

        def go(maybe_inject):
            maybe_inject("train.round")
            maybe_inject("train.rnd")        # XTB301 typo
            maybe_inject("x" + "y")          # XTB304 non-literal
        # ckpt.write: XTB302 dead + XTB303 undocumented
    """), docs_root=str(tmp_path))
    assert sorted(codes(r)) == ["XTB301", "XTB302", "XTB303", "XTB304"]


def test_seams_clean_and_suppression(tmp_path):
    (tmp_path / "reliability.md").write_text(SEAM_DOCS)
    clean = lint_source(src("""
        SEAMS = frozenset({"train.round"})

        def go(maybe_inject):
            maybe_inject("train.round")
    """), docs_root=str(tmp_path))
    assert codes(clean) == []
    sup = lint_source(src("""
        SEAMS = frozenset({"train.round"})

        def go(maybe_inject):
            maybe_inject("train.round")
            maybe_inject("oops")  # xtblint: disable=XTB301
    """), docs_root=str(tmp_path))
    assert codes(sup) == []
    assert [f.code for f in sup.suppressed] == ["XTB301"]


def test_seams_runtime_strict_mode(monkeypatch):
    # the runtime complement: XGBOOST_TPU_STRICT_SEAMS rejects unknown
    # seam names at the seam and at plan-install time
    from xgboost_tpu.reliability import faults

    monkeypatch.setenv(faults.STRICT_ENV, "1")
    faults.clear()
    try:
        assert faults.maybe_inject("train.round") is None
        with pytest.raises(ValueError, match="unknown fault seam"):
            faults.maybe_inject("train.rnd")
        with pytest.raises(ValueError, match="unknown fault seam"):
            faults.install({"faults": [{"site": "nope", "kind": "delay"}]})
        with pytest.raises(ValueError, match="unknown fault seam"):
            # pre-built plans must not bypass install-time validation
            faults.install(faults.FaultPlan(
                [faults.FaultSpec(site="tracker.connct", kind="delay")]))
    finally:
        monkeypatch.delenv(faults.STRICT_ENV)
        faults.clear()
    assert faults.maybe_inject("definitely.unknown") is None  # strict off


def test_seams_canonical_set_matches_call_sites():
    # every SEAMS member is fired somewhere in the package and vice versa
    # (the linter enforces this; assert it directly for a clearer failure)
    import re

    from xgboost_tpu.reliability.faults import SEAMS

    used = set()
    pkg = os.path.join(REPO, "xgboost_tpu")
    for root, _dirs, files in os.walk(pkg):
        if os.path.basename(root) == "analysis":
            continue  # the linter's own docs mention placeholder seams
        for f in files:
            if f.endswith(".py"):
                with open(os.path.join(root, f), encoding="utf-8") as fh:
                    used.update(re.findall(
                        r"maybe_inject\(\s*[\"']([^\"']+)[\"']", fh.read()))
    assert used == SEAMS


# ---------------------------------------------------------------------------
# XTB4xx metric-name consistency
# ---------------------------------------------------------------------------

def _metric_docs(tmp_path, observability="| `xtb_good_total` | counter |"):
    (tmp_path / "observability.md").write_text(observability)
    (tmp_path / "reliability.md").write_text("")
    return str(tmp_path)


def test_metrics_undocumented_conflict_and_dangling(tmp_path):
    docs = _metric_docs(tmp_path)
    r = lint_source(src("""
        def setup(reg):
            reg.counter("xtb_good_total", "ok")
            reg.counter("xtb_hidden_total", "undocumented")   # XTB401
            reg.gauge("xtb_good_total", "conflict")           # XTB402
            return "see xtb_ghost_seconds"                    # XTB403
    """), docs_root=docs)
    assert sorted(codes(r)) == ["XTB401", "XTB402", "XTB403"]


def test_metrics_clean_constants_and_histogram_series(tmp_path):
    docs = _metric_docs(
        tmp_path, "| `xtb_phasey_seconds` | histogram |\n"
                  "also mentions xtb_phasey_seconds_bucket\n")
    r = lint_source(src("""
        NAME = "xtb_phasey_seconds"

        def setup(reg):
            # registered through a module constant; _bucket/_sum/_count
            # exposition series derive from the histogram family
            return reg.histogram(NAME, "t", ("phase",))
    """), docs_root=docs)
    assert codes(r) == []


def test_metrics_native_symbols_not_metric_shaped(tmp_path):
    docs = _metric_docs(tmp_path)
    r = lint_source(src("""
        def setup(reg):
            reg.counter("xtb_good_total", "ok")
            return "calls xtb_csr_rows and xtb_parse_libsvm"  # native, clean
    """), docs_root=docs)
    assert codes(r) == []


def test_metrics_suppression_honored(tmp_path):
    docs = _metric_docs(tmp_path)
    r = lint_source(src("""
        def setup(reg):
            reg.counter("xtb_good_total", "ok")
            reg.counter("xtb_hidden_total", "x")  # xtblint: disable=XTB4
    """), docs_root=docs)
    assert codes(r) == []
    assert [f.code for f in r.suppressed] == ["XTB401"]


# ---------------------------------------------------------------------------
# XTB5xx nondeterminism
# ---------------------------------------------------------------------------

def test_nondet_fires_on_wall_clock_and_ambient_rng():
    r = lint_source(src("""
        import random, time
        import numpy as np

        def jitter():
            t = time.time()                  # XTB501
            a = random.random()              # XTB502
            b = np.random.permutation(4)     # XTB502
            return t, a, b
    """))
    assert codes(r) == ["XTB501", "XTB502", "XTB502"]


def test_nondet_seeded_generators_clean():
    r = lint_source(src("""
        import random, time
        import numpy as np

        def jitter(seed):
            rng = random.Random(seed)
            g = np.random.default_rng(seed)
            t0 = time.monotonic()
            return rng.random(), g.permutation(4), time.perf_counter() - t0
    """))
    assert codes(r) == []


def test_nondet_testing_paths_exempt():
    r = lint_source(src("""
        import time

        def now():
            return time.time()
    """), filename="xgboost_tpu/testing/helpers.py")
    assert codes(r) == []


def test_nondet_suppression_honored():
    r = lint_source(src("""
        import time

        def wall():
            return time.time()  # xtblint: disable=XTB501
    """))
    assert codes(r) == []
    assert [f.code for f in r.suppressed] == ["XTB501"]


# ---------------------------------------------------------------------------
# framework: catalog, reporters, file-level suppression, CLI, the gate
# ---------------------------------------------------------------------------

def test_rule_catalog_covers_all_families():
    cat = {code for code, _rule, _desc in rule_catalog()}
    assert {"XTB101", "XTB102", "XTB103", "XTB201", "XTB202", "XTB203",
            "XTB301", "XTB302", "XTB303", "XTB304", "XTB401", "XTB402",
            "XTB403", "XTB501", "XTB502", "XTB901", "XTB902", "XTB903",
            "XTB905", "XTB906"} <= cat


# ---------------------------------------------------------------------------
# XTB202/XTB203 — the native C-API dispatch-lock contract
# ---------------------------------------------------------------------------

def _capi_codes(cc_text):
    from xgboost_tpu.analysis.locks import CapiDispatchRule

    return [f.code for f in CapiDispatchRule().check_text(cc_text, "x.cc")]


def test_capi_dispatch_unguarded_entry_fires():
    assert _capi_codes(src("""
        XTB_DLL int XGBoosterNewThing(BoosterHandle h) {
          do_stuff();
          return 0;
        }
    """)) == ["XTB202"]


def test_capi_dispatch_wrong_mode_fires():
    # a predict entry downgraded off the shared read path re-serializes
    # concurrent readers — exactly the regression XTB203 pins
    assert _capi_codes(src("""
        XTB_DLL int XGBoosterPredict(BoosterHandle h) {
          API_BEGIN_MUT();
          return 0;
          API_END();
        }
        XTB_DLL int XGBoosterUpdateOneIter(BoosterHandle h) {
          API_BEGIN();
          return 0;
          API_END();
        }
    """)) == ["XTB203", "XTB203"]


def test_capi_dispatch_clean_and_delegation():
    assert _capi_codes(src("""
        XTB_DLL int XGBoosterPredict(BoosterHandle h) {
          API_BEGIN_READ();
          return 0;
          API_END();
        }
        XTB_DLL int XGBoosterSetParam(BoosterHandle h) {
          API_BEGIN_MUT();
          return 0;
          API_END();
        }
        XTB_DLL int XGDMatrixCreateFromMat(const float* d) {
          API_BEGIN();
          return 0;
          API_END();
        }
        XTB_DLL int XGDMatrixAlias(const float* d) {
          return XGDMatrixCreateFromMat(d);
        }
    """)) == []


def test_capi_dispatch_real_tree_contract_holds():
    """The committed xtb_capi.cc satisfies its own contract table."""
    from xgboost_tpu.analysis.locks import CapiDispatchRule

    cc = os.path.join(REPO, "native", "xtb_capi.cc")
    with open(cc, encoding="utf-8") as fh:
        findings = CapiDispatchRule().check_text(fh.read(), cc)
    assert findings == []


def _simd_codes(cc_text):
    from xgboost_tpu.analysis.simd_seam import SimdSeamRule

    return [f.code for f in SimdSeamRule().check_text(cc_text, "x.h")]


def test_simd_seam_intrinsics_fire():
    assert _simd_codes(src("""
        #include <immintrin.h>
        void f() { __m256 x = _mm256_setzero_ps(); }
    """)) == ["XTB601", "XTB601"]
    assert _simd_codes(src("""
        float32x4_t a = vaddq_f32(b, c);
    """)) == ["XTB601"]


def test_simd_seam_dispatch_calls_clean():
    # calls INTO the seam are the sanctioned surface
    assert _simd_codes(src("""
        if (vec_row) xtb_hist_sweep_avx2(bins, gpair, pos, R, F, f0, f1);
        xtb_simd_set(0);
        int lanes = xtb_simd_lanes_impl(xtb_simd_active());
    """)) == []


def test_simd_seam_real_tree_confined():
    """Every intrinsic in native/ lives in xtb_simd.h; the seam header
    itself is exempt (it IS the seam) and must actually contain them."""
    from xgboost_tpu.analysis.simd_seam import ALLOWED_BASENAME, SimdSeamRule

    rule = SimdSeamRule()
    nd = os.path.join(REPO, "native")
    for name in os.listdir(nd):
        if name.endswith((".cc", ".h", ".c")) and name != ALLOWED_BASENAME:
            with open(os.path.join(nd, name), encoding="utf-8") as fh:
                assert rule.check_text(fh.read(), name) == [], name
    with open(os.path.join(nd, ALLOWED_BASENAME), encoding="utf-8") as fh:
        assert rule.check_text(fh.read(), ALLOWED_BASENAME)


# ---------------------------------------------------------------------------
# XTB7xx unbounded blocking calls
# ---------------------------------------------------------------------------

def test_blocking_fires_on_untimed_wait_get_result_connect():
    r = lint_source(src("""
        import socket

        def f(ev, q, fut):
            ev.wait()
            fut.result()
            q.get()
            socket.create_connection(("h", 1))
    """), select=["XTB7"])
    assert codes(r) == ["XTB701", "XTB702", "XTB702", "XTB703"]


def test_blocking_clean_with_explicit_timeouts():
    """Explicit bounds — including a deliberate ``timeout=None`` — pass:
    the rule rejects IMPLICIT forever, not designed-forever."""
    r = lint_source(src("""
        import socket

        def f(ev, q, fut, d, gauge):
            ev.wait(timeout=None)
            ev.wait(5.0)
            fut.result(timeout=1)
            q.get(timeout=1)
            socket.create_connection(("h", 1), 5)
            socket.create_connection(("h", 1), timeout=None)
            d.get("key")         # dict.get: not a queue consume
            gauge.get()          # non-queue receiver: gauge read
    """), select=["XTB7"])
    assert codes(r) == []


def test_blocking_watchdog_module_exempt():
    """The watchdog module is the one place allowed to own unbounded
    blocking primitives — the real file must carry no XTB7xx findings
    BECAUSE of the exemption, not because it happens to be clean."""
    from xgboost_tpu.analysis.blocking import _EXEMPT_FILES

    assert "reliability/watchdog.py" in _EXEMPT_FILES
    path = os.path.join(REPO, "xgboost_tpu", "reliability", "watchdog.py")
    r = lint_paths([path], select=["XTB7"])
    assert codes(r) == []


def test_blocking_queue_receiver_naming():
    r = lint_source(src("""
        def f(self):
            self._queue.get()
            self.request_queue.get()
            self.q.get()
    """), select=["XTB7"])
    assert codes(r) == ["XTB702", "XTB702", "XTB702"]


def test_file_level_suppression_mechanism():
    # the mechanism works (and is what the gate forbids in-tree)
    r = lint_source(src("""
        # xtblint: disable-file=XTB501
        import time

        def a():
            return time.time()

        def b():
            return time.time()
    """))
    assert codes(r) == []
    assert [f.code for f in r.suppressed] == ["XTB501", "XTB501"]


def test_select_and_ignore_filters():
    snippet = src("""
        import time

        def f():
            return time.time()
    """)
    assert codes(lint_source(snippet, select=["XTB5"])) == ["XTB501"]
    assert codes(lint_source(snippet, select=["XTB1"])) == []
    assert codes(lint_source(snippet, ignore=["XTB501"])) == []


def test_reporters_shapes():
    r = lint_source("import time\nt = time.time()\n")
    text = render_text(r)
    assert "XTB501" in text and text.rstrip().endswith("files scanned")
    payload = json.loads(render_json(r))
    assert payload["tool"] == "xtblint" and payload["clean"] is False
    assert payload["counts"] == {"XTB501": 1}
    assert payload["findings"][0]["code"] == "XTB501"
    assert payload["suppressed"] == []


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import time\nt = time.time()\n")
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    env = dict(os.environ, PYTHONPATH=REPO)

    def run(*args):
        return subprocess.run(
            [sys.executable, "-m", "xgboost_tpu.analysis", *args],
            capture_output=True, text=True, env=env, cwd=str(tmp_path))

    assert run(str(ok)).returncode == 0
    p = run(str(bad), "--format", "json",
            "--json-out", str(tmp_path / "rep.json"))
    assert p.returncode == 1
    assert json.loads((tmp_path / "rep.json").read_text())["counts"] == {
        "XTB501": 1}
    assert run(str(tmp_path / "missing.py")).returncode == 2
    assert run("--list-rules").returncode == 0


def test_gate_package_lints_clean():
    """THE acceptance gate: the merged tree has zero findings."""
    result = lint_paths([os.path.join(REPO, "xgboost_tpu")],
                        docs_root=os.path.join(REPO, "docs"))
    assert result.errors == []
    assert result.findings == [], "\n".join(
        f.render() for f in result.findings)


def test_gate_cli_exits_zero():
    p = subprocess.run(
        [sys.executable, "-m", "xgboost_tpu.analysis", "xgboost_tpu/"],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, PYTHONPATH=REPO))
    assert p.returncode == 0, p.stdout + p.stderr


def test_no_blanket_suppressions_in_tree():
    pkg = os.path.join(REPO, "xgboost_tpu")
    offenders = []
    for root, _dirs, files in os.walk(pkg):
        for f in files:
            if f.endswith(".py"):
                path = os.path.join(root, f)
                with open(path, encoding="utf-8") as fh:
                    if "disable-file=" in fh.read():
                        offenders.append(path)
    # the analysis package itself documents/implements the marker — its
    # occurrences are string literals and docs, not suppressions in use
    offenders = [o for o in offenders
                 if os.sep + "analysis" + os.sep not in o]
    assert offenders == []


# ---------------------------------------------------------------------------
# XTB901/902/903 — lock-order and blocking-under-lock discipline
# ---------------------------------------------------------------------------

def test_lockorder_abba_inversion_fires():
    r = lint_source(src("""
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self.x = 0

            def one(self):
                with self._a:
                    with self._b:
                        self.x += 1
                        self.x += 2

            def two(self):
                with self._b:
                    with self._a:
                        self.x += 1
                        self.x += 2
    """), "xgboost_tpu/m.py", select=["XTB9"])
    assert codes(r) == ["XTB901"]
    # the report names both locks so the fix (pick ONE order) is obvious
    assert "S._a" in r.findings[0].message
    assert "S._b" in r.findings[0].message


def test_lockorder_consistent_nesting_clean():
    r = lint_source(src("""
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self.x = 0

            def one(self):
                with self._a:
                    with self._b:
                        self.x += 1
                        self.x += 2

            def two(self):
                with self._a:
                    with self._b:
                        self.x -= 1
                        self.x -= 2
    """), "xgboost_tpu/m.py", select=["XTB9"])
    assert codes(r) == []


def test_lockorder_transitive_cycle_through_helper():
    # one() holds _a and calls a helper that takes _b; two() nests the
    # other way — the inversion is only visible through the call graph
    r = lint_source(src("""
        import threading

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self.x = 0

            def _bump(self):
                with self._b:
                    self.x += 1
                    self.x += 2

            def one(self):
                with self._a:
                    self.x += 1
                    self._bump()

            def two(self):
                with self._b:
                    with self._a:
                        self.x += 1
                        self.x += 2
    """), "xgboost_tpu/m.py", select=["XTB9"])
    assert codes(r) == ["XTB901"]


def test_blocking_while_holding_lock_fires():
    r = lint_source(src("""
        import threading
        import time

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self.x = 0

            def one(self):
                with self._a:
                    time.sleep(1.0)
                    self.x += 1
    """), "xgboost_tpu/m.py", select=["XTB9"])
    assert codes(r) == ["XTB902"]


def test_blocking_declared_serialization_lock_exempt():
    # _XTB_SERIAL_LOCKS declares the contract: holding _tx across wire
    # I/O is the lock's purpose.  XTB902 waived; the lock stays in the
    # XTB901 order graph.
    r = lint_source(src("""
        import threading
        import time

        _XTB_SERIAL_LOCKS = ("S._tx",)

        class S:
            def __init__(self):
                self._tx = threading.Lock()
                self.x = 0

            def one(self):
                with self._tx:
                    time.sleep(1.0)
                    self.x += 1
    """), "xgboost_tpu/m.py", select=["XTB9"])
    assert codes(r) == []


def test_blocking_after_release_clean():
    r = lint_source(src("""
        import threading
        import time

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self.x = 0

            def one(self):
                with self._a:
                    n = self.x
                    self.x += 1
                time.sleep(n)
    """), "xgboost_tpu/m.py", select=["XTB9"])
    assert codes(r) == []


def test_handler_lock_acquire_fires_and_bounded_clean():
    fired = lint_source(src("""
        import atexit
        import threading

        _lock = threading.Lock()
        _buf = []

        @atexit.register
        def _flush():
            with _lock:
                _buf.clear()
                _buf.append(1)
    """), "xgboost_tpu/m.py", select=["XTB9"])
    assert codes(fired) == ["XTB903"]

    bounded = lint_source(src("""
        import atexit
        import threading

        _lock = threading.Lock()
        _buf = []

        @atexit.register
        def _flush():
            if not _lock.acquire(timeout=1.0):
                return
            try:
                _buf.clear()
                _buf.append(1)
            finally:
                _lock.release()
    """), "xgboost_tpu/m.py", select=["XTB9"])
    assert codes(bounded) == []


def test_lockorder_suppression_honored():
    r = lint_source(src("""
        import threading
        import time

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self.x = 0

            def one(self):
                with self._a:
                    time.sleep(1.0)  # xtblint: disable=XTB902
                    self.x += 1
    """), "xgboost_tpu/m.py", select=["XTB9"])
    assert codes(r) == []
    assert [f.code for f in r.suppressed] == ["XTB902"]


# ---------------------------------------------------------------------------
# XTB905/XTB906 — env-knob catalog
# ---------------------------------------------------------------------------

def _knob_docs(tmp_path, table):
    docs = tmp_path / "docs"
    docs.mkdir(exist_ok=True)
    (docs / "knobs.md").write_text(table)
    # the other doc contracts skip quietly when their files are absent
    return str(docs)


def test_envknob_undocumented_read_fires(tmp_path):
    r = lint_source(src("""
        import os

        V = os.environ.get("XGBOOST_TPU_MYSTERY_KNOB", "1")
    """), "xgboost_tpu/m.py", select=["XTB905"],
        docs_root=_knob_docs(tmp_path, "| `XGBOOST_TPU_OTHER` | x |\n"))
    assert codes(r) == ["XTB905"]
    assert "XGBOOST_TPU_MYSTERY_KNOB" in r.findings[0].message


def test_envknob_stale_row_fires_and_pattern_exempt(tmp_path):
    r = lint_source(src("""
        import os

        V = os.environ.get("XGBOOST_TPU_LIVE_KNOB")
    """), "xgboost_tpu/m.py", select=["XTB9"],
        docs_root=_knob_docs(tmp_path, src("""
            | `XGBOOST_TPU_LIVE_KNOB` | documented and read |
            | `XGBOOST_TPU_GONE_KNOB` | stale row |
            | `XGBOOST_TPU_WATCHDOG_<SEAM>_S` | pattern row, exempt |
        """)))
    assert codes(r) == ["XTB906"]
    assert "XGBOOST_TPU_GONE_KNOB" in r.findings[0].message


def test_envknob_const_reference_and_concat_resolved(tmp_path):
    # the ENV_X = "XGBOOST_TPU_..." constant idiom and the derived-name
    # concat (trace.py's _OWNER_VAR) both resolve to documented reads
    r = lint_source(src("""
        import os

        ENV_BASE = "XGBOOST_TPU_THING"
        _DERIVED = ENV_BASE + "_EXTRA"

        def f():
            return (os.environ.get(ENV_BASE),
                    os.environ.get(_DERIVED))
    """), "xgboost_tpu/m.py", select=["XTB9"],
        docs_root=_knob_docs(tmp_path, src("""
            | `XGBOOST_TPU_THING` | base |
            | `XGBOOST_TPU_THING_EXTRA` | derived |
        """)))
    assert codes(r) == []


# (no separate whole-package XTB905/906 reconciliation test: the gate
# test above lints the full package with EVERY rule enabled — an
# undocumented read or stale knobs.md row already fails it)


# ---------------------------------------------------------------------------
# CLI end-to-end: mixed families + suppressions through the JSON reporter
# ---------------------------------------------------------------------------

def test_cli_mixed_families_and_suppressions_e2e(tmp_path):
    mixed = tmp_path / "mixed.py"
    mixed.write_text(src("""
        import threading
        import time

        class S:
            def __init__(self):
                self._a = threading.Lock()
                self.x = 0

            def one(self):
                with self._a:
                    time.sleep(1.0)
                    self.x += 1

            def stamp(self):
                return time.time()

            def stamp_ok(self):
                return time.time()  # xtblint: disable=XTB501
    """))
    rep = tmp_path / "rep.json"
    p = subprocess.run(
        [sys.executable, "-m", "xgboost_tpu.analysis", str(mixed),
         "--format", "json", "--json-out", str(rep)],
        capture_output=True, text=True, env=dict(os.environ, PYTHONPATH=REPO),
        cwd=str(tmp_path))
    assert p.returncode == 1
    payload = json.loads(rep.read_text())
    assert payload["clean"] is False
    assert payload["counts"] == {"XTB902": 1, "XTB501": 1}
    # stdout carries the same JSON document as --json-out
    assert json.loads(p.stdout)["counts"] == payload["counts"]
    # the suppressed XTB501 is REPORTED (trend tracking), not dropped
    assert [f["code"] for f in payload["suppressed"]] == ["XTB501"]
    # exit-code contract: suppressing every finding makes the gate pass
    clean = tmp_path / "clean.py"
    clean.write_text("import time\nt = time.time()  "
                     "# xtblint: disable=XTB501\n")
    p2 = subprocess.run(
        [sys.executable, "-m", "xgboost_tpu.analysis", str(clean)],
        capture_output=True, text=True, env=dict(os.environ, PYTHONPATH=REPO),
        cwd=str(tmp_path))
    assert p2.returncode == 0, p2.stdout + p2.stderr


def test_gate_changed_mode_exits_zero():
    """scripts/lint_gate.sh --changed (the quick-tier fast mode) passes on
    the tree as committed/staged right now."""
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "lint_gate.sh"), "--changed"],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, PYTHONPATH=REPO))
    assert p.returncode == 0, p.stdout + p.stderr
    assert "lint_gate OK" in p.stdout
