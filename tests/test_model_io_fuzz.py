"""Model-IO robustness: corrupted / truncated / hostile model files must
raise clean errors, never crash the process or silently half-load
(reference pattern: tests/python/test_model_io.py + the UBJSON fuzz corpus
in tests/cpp/common/test_json.cc).
"""
import json
import os

import numpy as np
import pytest

import xgboost_tpu as xtb


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("io_fuzz")
    rng = np.random.default_rng(0)
    X = rng.normal(size=(400, 5)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3},
                    xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    pj = os.path.join(tmp, "m.json")
    pu = os.path.join(tmp, "m.ubj")
    bst.save_model(pj)
    bst.save_model(pu)
    return pj, pu, bst.predict(xtb.DMatrix(X)), X


def _expect_clean_failure(payload):
    """Loading hostile bytes must raise a python-level error."""
    b = xtb.Booster()
    with pytest.raises((ValueError, KeyError, TypeError, IndexError,
                        EOFError, json.JSONDecodeError)):
        b.load_model(payload)


def test_truncated_files_raise(model_files):
    pj, pu, _, _ = model_files
    for path in (pj, pu):
        blob = open(path, "rb").read()
        for frac in (0.0, 0.1, 0.5, 0.9, 0.999):
            _expect_clean_failure(bytearray(blob[: int(len(blob) * frac)]))


def test_bitflip_fuzz_never_crashes(model_files):
    """Random single-byte corruptions: every load either raises cleanly or
    produces a booster whose predictions are finite — no crashes, no
    exceptions outside the expected set."""
    pj, pu, _, X = model_files
    rng = np.random.default_rng(1)
    for path in (pj, pu):
        blob = bytearray(open(path, "rb").read())
        for _ in range(40):
            i = int(rng.integers(0, len(blob)))
            mut = bytearray(blob)
            mut[i] ^= int(rng.integers(1, 256))
            b = xtb.Booster()
            try:
                b.load_model(mut)
            except (ValueError, KeyError, TypeError, IndexError, EOFError,
                    OverflowError, MemoryError, json.JSONDecodeError,
                    UnicodeDecodeError, AssertionError):
                continue  # clean rejection
            preds = b.predict(xtb.DMatrix(X))
            assert preds.shape[0] == X.shape[0]


def test_wrong_schema_rejected(model_files):
    _expect_clean_failure(bytearray(b"{}"))
    _expect_clean_failure(bytearray(b'{"learner": {}}'))
    _expect_clean_failure(bytearray(b"\x00\x01\x02\x03garbage"))
    _expect_clean_failure(bytearray(b"[1, 2, 3]"))


def test_version_field_roundtrip(model_files):
    pj, _, preds, X = model_files
    obj = json.load(open(pj))
    assert "version" in obj
    # unknown EXTRA top-level fields are tolerated (forward compat — the
    # reference ignores unknown keys); the model still loads identically
    obj["future_extension"] = {"x": 1}
    b = xtb.Booster()
    b.load_model(bytearray(json.dumps(obj).encode()))
    np.testing.assert_array_equal(b.predict(xtb.DMatrix(X)), preds)


def test_nan_and_inf_in_leafs_load(model_files):
    """Inf/NaN smuggled into leaf values must not crash load; predict
    stays shape-correct (the reference loads them verbatim too)."""
    pj, _, _, X = model_files
    obj = json.load(open(pj))
    trees = obj["learner"]["gradient_booster"]["model"]["trees"]
    trees[0]["split_conditions"][0] = 1e308 * 10  # inf via json float
    b = xtb.Booster()
    try:
        b.load_model(bytearray(json.dumps(obj).encode()))
    except (ValueError, json.JSONDecodeError):
        return
    assert b.predict(xtb.DMatrix(X)).shape[0] == X.shape[0]
