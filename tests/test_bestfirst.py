"""Global best-first (lossguide) growth — tree/bestfirst.py
(reference: src/tree/driver.h priority queue; round-1 verdict Weak #10:
per-level budget approximation + depth-10 heap cap)."""
import numpy as np
import pytest

import xgboost_tpu as xtb


def _skewed_data(n=4000, seed=0):
    """Data that rewards a deep chain on one feature: best-first should
    follow the gain, not the level structure."""
    rng = np.random.default_rng(seed)
    X = rng.uniform(0, 1, size=(n, 4)).astype(np.float32)
    # piecewise-constant staircase in x0 with many steps -> deep chain
    y = np.floor(X[:, 0] * 40).astype(np.float32)
    return X, y


def test_bestfirst_exceeds_depth_ten():
    """With max_depth=0 (unbounded) and a leaf budget, lossguide can grow
    past the round-1 heap cap of 10 levels."""
    X, y = _skewed_data()
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 0,
                     "grow_policy": "lossguide", "max_leaves": 40,
                     "eta": 1.0, "max_bin": 64},
                    xtb.DMatrix(X, label=y), 1, verbose_eval=False)
    t = bst.trees[0]
    assert t.num_leaves <= 40
    assert t.max_depth > 10, t.max_depth  # impossible in the heap layout
    # and it actually fits the staircase
    p = bst.predict(xtb.DMatrix(X))
    assert np.mean((p - y) ** 2) < np.var(y) * 0.05


def test_bestfirst_budget_and_quality():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(3000, 8)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + X[:, 2] > 0).astype(np.float32)
    d = xtb.DMatrix(X, label=y)
    res = {}
    bst = xtb.train({"objective": "binary:logistic", "grow_policy": "lossguide",
                     "max_leaves": 16, "max_depth": 0, "eta": 0.3,
                     "eval_metric": "logloss"},
                    d, 10, evals=[(d, "t")], evals_result=res,
                    verbose_eval=False)
    for t in bst.trees:
        assert t.num_leaves <= 16
    assert res["t"]["logloss"][-1] < res["t"]["logloss"][0]


def test_bestfirst_respects_max_depth():
    X, y = _skewed_data(seed=2)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 4,
                     "grow_policy": "lossguide", "max_leaves": 64,
                     "max_bin": 64},
                    xtb.DMatrix(X, label=y), 1, verbose_eval=False)
    assert bst.trees[0].max_depth <= 4


def test_bestfirst_matches_depthwise_on_balanced_data():
    """With a generous budget, best-first should reach the quality of
    depthwise on data with no depth skew."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(2000, 6)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] ** 2 > 0.5).astype(np.float32)
    d1 = xtb.DMatrix(X, label=y)
    d2 = xtb.DMatrix(X, label=y)
    b_dw = xtb.train({"objective": "binary:logistic", "max_depth": 5,
                      "eta": 0.3}, d1, 8, verbose_eval=False)
    b_bf = xtb.train({"objective": "binary:logistic", "grow_policy":
                      "lossguide", "max_leaves": 32, "max_depth": 0,
                      "eta": 0.3}, d2, 8, verbose_eval=False)
    p1 = b_dw.predict(d1)
    p2 = b_bf.predict(d2)
    ll1 = -np.mean(y * np.log(np.clip(p1, 1e-7, 1))
                   + (1 - y) * np.log(np.clip(1 - p1, 1e-7, 1)))
    ll2 = -np.mean(y * np.log(np.clip(p2, 1e-7, 1))
                   + (1 - y) * np.log(np.clip(1 - p2, 1e-7, 1)))
    assert ll2 < ll1 * 1.25, (ll1, ll2)


def test_bestfirst_save_load_and_adaptive():
    """Serialization round-trip + adaptive (quantile) leaves on the
    best-first path."""
    X, y = _skewed_data(n=1500, seed=4)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "reg:quantileerror", "quantile_alpha": 0.5,
                     "grow_policy": "lossguide", "max_leaves": 12,
                     "max_depth": 0, "max_bin": 64},
                    d, 4, verbose_eval=False)
    p = bst.predict(d)
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        fn = td + "/bf.json"
        bst.save_model(fn)
        b2 = xtb.Booster()
        b2.load_model(fn)
        np.testing.assert_array_equal(b2.predict(xtb.DMatrix(X)), p)


def test_lossguide_distributed_global_bestfirst(eight_devices):
    """Global best-first lossguide under an 8-device mesh (GSPMD hist psum)
    and 2-process parallelism (host AllReduceHist per expansion): the
    driver queue is GLOBAL across shards (driver.h:30), growth is
    deterministic per configuration, ranks agree bitwise, and model quality
    matches single-device.  (Cross-configuration bitwise identity is not
    promised — f32 reduction grouping differs by device count, as in the
    reference's single- vs multi-GPU models.)"""
    import threading

    from xgboost_tpu import collective
    from xgboost_tpu.metric import logloss
    from xgboost_tpu.testing.data import make_binary

    X, y = make_binary(2048, 6, seed=3)
    params = {"objective": "binary:logistic", "grow_policy": "lossguide",
              "max_leaves": 24, "max_depth": 0, "eta": 0.4, "max_bin": 32}

    b1 = xtb.train(params, xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    ll1 = logloss(b1.predict(xtb.DMatrix(X)), y)
    # single-device lossguide really is best-first: some tree goes deeper
    # than balanced log2(max_leaves) growth would
    assert any(t.max_depth > 5 for t in b1.trees)

    # 8-device mesh: deterministic (two identical runs) + same quality
    b8a = xtb.train({**params, "n_devices": 8}, xtb.DMatrix(X, label=y), 3,
                    verbose_eval=False)
    b8b = xtb.train({**params, "n_devices": 8}, xtb.DMatrix(X, label=y), 3,
                    verbose_eval=False)
    d8a = "".join(b8a.get_dump(dump_format="json"))
    assert d8a == "".join(b8b.get_dump(dump_format="json"))
    assert any(t.max_depth > 5 for t in b8a.trees)  # unbounded depth
    ll8 = logloss(b8a.predict(xtb.DMatrix(X)), y)
    assert abs(ll8 - ll1) < 0.02, (ll8, ll1)

    # 2 processes (in-memory thread backend), disjoint contiguous shards
    results, errors = {}, {}

    def worker(rank):
        try:
            with collective.CommunicatorContext(
                    dmlc_communicator="in-memory", in_memory_world_size=2,
                    in_memory_rank=rank, in_memory_group="bf2"):
                _grp = collective._TLS.backend._group
                lo, hi = (0, 1024) if rank == 0 else (1024, 2048)
                d = xtb.DMatrix(X[lo:hi], label=y[lo:hi])
                b = xtb.train(params, d, 3, verbose_eval=False)
                results[rank] = ("".join(b.get_dump(dump_format="json")),
                                 bytes(b.save_raw()))
        except Exception as e:  # noqa: BLE001
            errors[rank] = e
            try:
                _grp.barrier.abort()
            except Exception:
                pass

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    assert not any(t.is_alive() for t in threads), "worker deadlocked"
    assert not errors, errors
    assert results[0] == results[1]  # ranks bitwise-identical
    b2 = xtb.Booster()
    b2.load_model(results[0][1])
    assert any(t.max_depth > 5 for t in b2.trees)
    ll2 = logloss(b2.predict(xtb.DMatrix(X)), y)
    assert abs(ll2 - ll1) < 0.02, (ll2, ll1)


def test_lossguide_distributed_adaptive_leaves_rank_identical():
    """Adaptive-leaf refit (UpdateTreeLeaf) under process parallelism must
    quantile the GLOBAL leaf population — ranks would otherwise refit from
    their local shards and diverge."""
    import threading

    from xgboost_tpu import collective
    from xgboost_tpu.testing.data import make_binary

    X, y01 = make_binary(1024, 5, seed=9)
    rng = np.random.default_rng(9)
    y = (X[:, 0] + 0.3 * rng.normal(size=len(X))).astype(np.float32)
    params = {"objective": "reg:absoluteerror", "grow_policy": "lossguide",
              "max_leaves": 8, "max_depth": 0, "eta": 0.5, "max_bin": 32}

    results, errors = {}, {}

    def worker(rank):
        try:
            with collective.CommunicatorContext(
                    dmlc_communicator="in-memory", in_memory_world_size=2,
                    in_memory_rank=rank, in_memory_group="bfad"):
                _grp = collective._TLS.backend._group
                lo, hi = (0, 512) if rank == 0 else (512, 1024)
                d = xtb.DMatrix(X[lo:hi], label=y[lo:hi])
                b = xtb.train(params, d, 2, verbose_eval=False)
                results[rank] = bytes(b.save_raw())
        except Exception as e:  # noqa: BLE001
            errors[rank] = e
            try:
                _grp.barrier.abort()
            except Exception:
                pass

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    assert not any(t.is_alive() for t in threads), "worker deadlocked"
    assert not errors, errors
    assert results[0] == results[1]
