"""Distributed-consistency tests: sharded grower over the 8-device CPU mesh
must produce bitwise-identical trees to single-device training
(SURVEY §4 distributed-consistency pattern; reference:
tests/cpp/tree/test_gpu_hist.cu, tests/python/test_collective.py)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from xgboost_tpu.data.ellpack import build_ellpack
from xgboost_tpu.data.quantile import sketch_dense
from xgboost_tpu.ops.split import SplitParams
from xgboost_tpu.parallel import ShardedHistTreeGrower, make_mesh
from xgboost_tpu.tree.grow import HistTreeGrower


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(0)
    R, F = 1000, 6
    X = rng.normal(size=(R, F)).astype(np.float32)
    y = (X[:, 0] + X[:, 1] ** 2 > 0.5).astype(np.float32)
    g = np.stack([0.5 - y, np.full(R, 0.25)], 1).astype(np.float32)
    cuts = sketch_dense(X, 16, use_device=False)
    ell = build_ellpack(X, cuts, row_align=1024)
    gp = np.zeros((ell.n_padded, 2), np.float32)
    gp[:R] = g
    valid = np.arange(ell.n_padded) < R
    return ell, jnp.asarray(gp), jnp.asarray(valid)


def test_sharded_tree_identical_to_single(problem, eight_devices):
    ell, gp, valid = problem
    params = SplitParams(0.3, 0.0, 1.0, 1.0, 0.0, 0.0)

    single = HistTreeGrower(4, params)
    s1 = single.grow(ell.bins, gp, valid, ell.cuts_pad, ell.n_bins)

    mesh = make_mesh(8)
    row2d = NamedSharding(mesh, P("data", None))
    row1d = NamedSharding(mesh, P("data"))
    bins_s = jax.device_put(ell.bins, row2d)
    gp_s = jax.device_put(gp, row2d)
    valid_s = jax.device_put(valid, row1d)

    multi = ShardedHistTreeGrower(4, params, mesh)
    s8 = multi.grow(bins_s, gp_s, valid_s, ell.cuts_pad, ell.n_bins)

    np.testing.assert_array_equal(np.asarray(s1.feat), np.asarray(s8.feat))
    np.testing.assert_array_equal(np.asarray(s1.sbin), np.asarray(s8.sbin))
    np.testing.assert_array_equal(np.asarray(s1.is_leaf), np.asarray(s8.is_leaf))
    np.testing.assert_array_equal(np.asarray(s1.pos), np.asarray(s8.pos))
    # f32 psum vs local sum: tiny accumulation-order differences allowed
    np.testing.assert_allclose(
        np.asarray(s1.leaf_val), np.asarray(s8.leaf_val), rtol=2e-4, atol=1e-6
    )


def test_dryrun_multichip_runs(eight_devices):
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_booster_n_devices_matches_single(eight_devices):
    """End-to-end train() over the 8-device mesh == single-device training."""
    import xgboost_tpu as xtb
    from xgboost_tpu.testing.data import make_binary

    X, y = make_binary(1200, 6, seed=11)
    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.5}
    b1 = xtb.train(params, xtb.DMatrix(X, label=y), 5, verbose_eval=False)
    b8 = xtb.train({**params, "n_devices": 8}, xtb.DMatrix(X, label=y), 5,
                   verbose_eval=False)
    p1, p8 = b1.predict(xtb.DMatrix(X)), b8.predict(xtb.DMatrix(X))
    np.testing.assert_allclose(p1, p8, rtol=5e-4, atol=1e-5)
    for t1, t8 in zip(b1.trees, b8.trees):
        np.testing.assert_array_equal(t1.split_indices, t8.split_indices)
        np.testing.assert_array_equal(t1.left_children, t8.left_children)


def test_booster_n_devices_non_pow2(eight_devices):
    """n_devices=3 (not a divisor of 1024): the page re-aligns to
    lcm(1024, 3) and training matches single-device (VERDICT r3 #10)."""
    import xgboost_tpu as xtb
    from xgboost_tpu.testing.data import make_binary

    X, y = make_binary(900, 5, seed=23)
    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.5}
    b1 = xtb.train(params, xtb.DMatrix(X, label=y), 3, verbose_eval=False)
    b3 = xtb.train({**params, "n_devices": 3}, xtb.DMatrix(X, label=y), 3,
                   verbose_eval=False)
    p1, p3 = b1.predict(xtb.DMatrix(X)), b3.predict(xtb.DMatrix(X))
    np.testing.assert_allclose(p1, p3, rtol=5e-4, atol=1e-5)
    for t1, t3 in zip(b1.trees, b3.trees):
        np.testing.assert_array_equal(t1.split_indices, t3.split_indices)


@pytest.mark.slow
def test_mesh_scan_chunking_above_chunk_size(eight_devices):
    """>2048 rows per device forces the chunked scan inside shard_map
    (regression: the scan carry must enter with the shard-varying type —
    seeding with zeros used to fail jax's varying-axes check, and this
    path was never reached by the small mesh tests)."""
    import xgboost_tpu as xtb
    from xgboost_tpu.testing.data import make_binary

    X, y = make_binary(8 * 2600, 6, seed=11)   # 2600 rows/device > chunk
    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.5,
              "max_bin": 32}
    b8 = xtb.train({**params, "n_devices": 8}, xtb.DMatrix(X, label=y), 2,
                   verbose_eval=False)
    b1 = xtb.train(params, xtb.DMatrix(X, label=y), 2, verbose_eval=False)
    for t1, t8 in zip(b1.trees, b8.trees):
        np.testing.assert_array_equal(t1.split_indices, t8.split_indices)
