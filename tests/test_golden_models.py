"""Committed golden-model compatibility (reference:
tests/python/test_model_compatibility.py + generate_models.py).

The models under tests/data/models/ were produced by the REAL reference
build (scripts/gen_golden_models.py records the version in MANIFEST.json)
and are committed, so format compatibility and predict parity are pinned on
every run — no oracle needed at test time.  This kills the "oracle missing
=> parity silently untested" failure mode and starts the cross-version
compatibility matrix (VERDICT r4 #7).
"""
import json
import os

import numpy as np
import pytest

import xgboost_tpu as xtb

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data",
                    "models")

pytestmark = pytest.mark.skipif(
    not os.path.exists(os.path.join(HERE, "MANIFEST.json")),
    reason="golden models not generated")


def _X():
    return np.load(os.path.join(HERE, "golden_X.npy"))


def _load(name):
    bst = xtb.Booster()
    bst.load_model(os.path.join(HERE, f"{name}.json"))
    return bst


def _golden_margin(name):
    return np.load(os.path.join(HERE, f"{name}_margin.npy"))


@pytest.mark.parametrize("name", ["binary", "dart", "rank_ndcg", "aft"])
def test_golden_scalar_margin_parity(name):
    bst = _load(name)
    got = np.asarray(bst.predict(xtb.DMatrix(_X()), output_margin=True))
    np.testing.assert_allclose(got, _golden_margin(name), rtol=1e-5,
                               atol=1e-5)


def test_golden_multiclass_margin_parity():
    bst = _load("multiclass")
    got = np.asarray(bst.predict(xtb.DMatrix(_X()), output_margin=True))
    np.testing.assert_allclose(got, _golden_margin("multiclass"), rtol=1e-5,
                               atol=1e-5)


def test_golden_multitarget_margin_parity():
    bst = _load("multitarget")
    got = np.asarray(bst.predict(xtb.DMatrix(_X()), output_margin=True))
    np.testing.assert_allclose(got, _golden_margin("multitarget"), rtol=1e-5,
                               atol=1e-5)


def test_golden_gblinear_margin_parity():
    bst = _load("gblinear")
    got = np.asarray(bst.predict(xtb.DMatrix(_X()), output_margin=True))
    np.testing.assert_allclose(
        got.reshape(-1), _golden_margin("gblinear").reshape(-1), rtol=1e-5,
        atol=1e-5)


def test_golden_categorical_margin_parity():
    pd = pytest.importorskip("pandas")
    df = pd.read_parquet(os.path.join(HERE, "categorical_X.parquet"))
    bst = _load("categorical")
    got = np.asarray(bst.predict(
        xtb.DMatrix(df, enable_categorical=True), output_margin=True))
    np.testing.assert_allclose(got, _golden_margin("categorical"), rtol=1e-5,
                               atol=1e-5)


def test_golden_roundtrip_preserves_bits():
    """Loading a reference model and re-saving must round-trip our own
    loader exactly (save format stays reference-loadable both ways)."""
    import tempfile

    bst = _load("binary")
    X = _X()
    p0 = np.asarray(bst.predict(xtb.DMatrix(X)))
    with tempfile.TemporaryDirectory() as td:
        for ext in ("json", "ubj"):
            path = os.path.join(td, f"m.{ext}")
            bst.save_model(path)
            b2 = xtb.Booster()
            b2.load_model(path)
            np.testing.assert_array_equal(
                np.asarray(b2.predict(xtb.DMatrix(X))), p0)


def test_manifest_lists_all_models():
    with open(os.path.join(HERE, "MANIFEST.json")) as fh:
        man = json.load(fh)
    assert set(man["models"]) == {
        "binary", "multiclass", "dart", "gblinear", "rank_ndcg",
        "categorical", "multitarget", "aft"}
    for name in man["models"]:
        assert os.path.exists(os.path.join(HERE, f"{name}.json")), name
