"""Distributed observability plane (telemetry/distributed.py + flight.py).

Quick tier covers the unit seams with no subprocess spawn: snapshot
round-trip, merged relabel + sum semantics, the HTTP scrape endpoint,
exposition-format escaping, catalog-sourced HELP text, the flight ring,
collective wait instrumentation, and the 2-rank in-memory straggler
report.  The slow tier runs a real 2-replica fleet and asserts the
acceptance contract: one scrape returns per-process-labeled AND merged
series, with the merged counter equal to the per-replica sum.
"""
import json
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from xgboost_tpu.telemetry import distributed, flight
from xgboost_tpu.telemetry.registry import Registry, get_registry


def _mk_registry(requests=3.0, lat=(0.01, 0.02)):
    r = Registry()
    r.counter("xtb_t_requests_total", "requests", ("model",)).labels(
        "m").inc(requests)
    r.gauge("xtb_t_live", "live things").set(1)
    h = r.histogram("xtb_t_seconds", "latency", ("model",),
                    buckets=(0.015, 1.0))
    for v in lat:
        h.labels("m").observe(v)
    return r


# =========================================================================
# snapshot + merge


def test_snapshot_roundtrip_is_json_serializable():
    snap = _mk_registry().snapshot()
    again = json.loads(json.dumps(snap))
    fams = {f["name"]: f for f in again["families"]}
    assert fams["xtb_t_requests_total"]["children"] == [[["m"], 3.0]]
    hist = fams["xtb_t_seconds"]
    assert hist["buckets"] == [0.015, 1.0]
    ((labels, counts, s, n),) = hist["children"]
    assert labels == ["m"] and counts == [1, 1, 0] and n == 2
    assert s == pytest.approx(0.03)


def test_merged_relabels_per_process_and_sums():
    m = distributed.MergedRegistry()
    m.ingest("replica0", _mk_registry(requests=2).snapshot())
    m.ingest("replica1", _mk_registry(requests=5).snapshot())
    text = m.render_prometheus(include_local=False)
    # per-process series carry proc=, the merged series does not
    assert 'xtb_t_requests_total{proc="replica0",model="m"} 2' in text
    assert 'xtb_t_requests_total{proc="replica1",model="m"} 5' in text
    assert '\nxtb_t_requests_total{model="m"} 7' in text
    # gauges merge by sum too (documented in the catalog scope column)
    assert '\nxtb_t_live 2' in text
    assert m.merged_totals("xtb_t_requests_total",
                           include_local=False) == {("m",): 7.0}


def test_merged_histogram_buckets_sum_bucketwise():
    m = distributed.MergedRegistry()
    m.ingest("a", _mk_registry(lat=(0.01,)).snapshot())
    m.ingest("b", _mk_registry(lat=(0.02, 0.02)).snapshot())
    text = m.render_prometheus(include_local=False)
    assert '\nxtb_t_seconds_bucket{model="m",le="0.015"} 1' in text
    assert '\nxtb_t_seconds_bucket{model="m",le="+Inf"} 3' in text
    assert '\nxtb_t_seconds_count{model="m"} 3' in text


def test_merged_retains_dead_sources_and_replaces_live_ones():
    m = distributed.MergedRegistry()
    m.ingest("replica0", _mk_registry(requests=1).snapshot())
    m.ingest("replica0", _mk_registry(requests=9).snapshot())  # newer wins
    assert m.merged_totals("xtb_t_requests_total",
                           include_local=False) == {("m",): 9.0}
    # nothing forgets a source on death — the last snapshot stays
    assert m.sources() == ["replica0"]


def test_merged_skips_conflicting_family_signature():
    m = distributed.MergedRegistry()
    m.ingest("a", _mk_registry().snapshot())
    bad = Registry()
    bad.counter("xtb_t_requests_total", "conflicting labels",
                ("other",)).labels("x").inc()
    m.ingest("b", bad.snapshot())
    text = m.render_prometheus(include_local=False)
    assert 'proc="a"' in text and 'other="x"' not in text


# =========================================================================
# scrape endpoint


def test_scrape_endpoint_serves_merged_view():
    m = distributed.MergedRegistry()
    m.ingest("rank0", _mk_registry(requests=4).snapshot())
    srv = distributed.MetricsServer(0, merged=m,
                                    include_local=False).start()
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10)
        assert resp.headers["Content-Type"].startswith("text/plain")
        body = resp.read().decode()
        assert 'xtb_t_requests_total{proc="rank0",model="m"} 4' in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=10)
    finally:
        srv.close()


def test_start_metrics_server_disabled_without_env(monkeypatch):
    monkeypatch.delenv(distributed.ENV_PORT, raising=False)
    assert distributed.start_metrics_server() is None


# =========================================================================
# exposition format (satellite: HELP escaping + catalog-sourced help)


def test_help_line_escapes_newlines_and_backslashes():
    r = Registry()
    r.counter("xtb_t_requests_total", 'first line\nsecond "quoted" \\x')
    text = r.render_prometheus()
    (help_line,) = [l for l in text.splitlines() if l.startswith("# HELP")]
    # one physical line, newline and backslash escaped per the format
    assert help_line == ('# HELP xtb_t_requests_total first line\\n'
                         'second "quoted" \\\\x')


def test_label_values_escape_quotes_and_newlines():
    r = Registry()
    r.counter("xtb_t_requests_total", "r", ("m",)).labels('a"b\nc').inc()
    text = r.render_prometheus()
    assert 'xtb_t_requests_total{m="a\\"b\\nc"} 1' in text


def test_empty_help_falls_back_to_docs_catalog():
    r = Registry()
    # registered with NO help; the docs catalog documents this family
    r.counter("xtb_serve_requests_total", "", ("model",)).labels("m").inc()
    text = r.render_prometheus()
    help_lines = [l for l in text.splitlines()
                  if l.startswith("# HELP xtb_serve_requests_total")]
    assert help_lines and "request" in help_lines[0]


# =========================================================================
# flight recorder


def test_flight_ring_records_bounds_and_dumps(tmp_path):
    flight.clear()
    for i in range(5):
        flight.record("event", "unit.test", i=i)
    evs = [e for e in flight.events() if e["name"] == "unit.test"]
    assert len(evs) == 5 and evs[0]["detail"] == {"i": 0}
    assert all(e["kind"] == "event" and "t_mono" in e for e in evs)
    path = flight.dump(str(tmp_path / "dump.json"))
    data = json.load(open(path))
    assert data["pid"] and data["wall_at_dump"]
    assert [e for e in data["events"] if e["name"] == "unit.test"]
    flight.clear()
    assert flight.events() == []


def test_flight_ring_is_bounded():
    flight.clear()
    cap = flight._ring.maxlen
    for i in range(cap + 500):
        flight.record("event", "flood", i=i)
    evs = flight.events()
    # the ring holds exactly its configured capacity: oldest events fell
    # off, the newest survived
    assert len(evs) == cap
    assert evs[-1]["detail"]["i"] == cap + 499
    assert evs[0]["detail"]["i"] == 500
    flight.clear()


def test_spans_feed_flight_ring():
    from xgboost_tpu.telemetry import spans

    flight.clear()
    was = spans.enabled()
    spans.enable()
    try:
        with spans.span("unit.flightspan"):
            pass
    finally:
        spans.enable(was)
    names = [e["name"] for e in flight.events() if e["kind"] == "span"]
    assert "unit.flightspan" in names
    flight.clear()


def test_snapshot_payload_carries_registry_and_flight():
    flight.clear()
    flight.record("event", "payload.test")
    payload = distributed.snapshot_payload()
    assert payload["pid"] > 0
    assert any(f["name"].startswith("xtb_")
               for f in payload["snapshot"]["families"])
    assert any(e["name"] == "payload.test" for e in payload["flight"])
    json.dumps(payload)  # shippable as-is
    flight.clear()


# =========================================================================
# collective wait instrumentation + straggler report


def test_allreduce_records_coll_wait_histogram():
    from xgboost_tpu import collective

    out = collective.allreduce(np.asarray([1.0, 2.0]))
    np.testing.assert_array_equal(out, [1.0, 2.0])
    hist = get_registry().get("xtb_coll_wait_seconds")
    assert hist is not None
    sums = hist.snapshot_sums()
    assert any(k[0] == "allreduce" for k in sums)


def test_inmemory_straggler_report_names_slow_rank():
    from xgboost_tpu import collective
    from xgboost_tpu.telemetry import TelemetryCallback

    results = {}
    errors = []

    def worker(rank):
        try:
            collective.init(dmlc_communicator="in-memory",
                            in_memory_world_size=2, in_memory_rank=rank,
                            in_memory_group="straggler-test")
            cb = TelemetryCallback(enable_spans=False, straggler=True)
            cb.before_iteration(None, 0, None)
            # the round's collective (what a real level allreduce is)
            collective.allgather(np.asarray([float(rank)]))
            if rank == 1:
                time.sleep(0.25)  # rank 1 is the deterministic straggler
            cb.after_iteration(object(), 0, None)
            results[rank] = cb.history[0]
        except BaseException as e:  # pragma: no cover - surfaced below
            errors.append((rank, repr(e)))
        finally:
            collective.finalize()

    threads = [threading.Thread(target=worker, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors
    for rank in (0, 1):
        st = results[rank]["straggler"]
        assert st["max_rank"] == 1 and st["min_rank"] == 0
        assert len(st["walls"]) == 2
        assert st["spread_s"] > 0.1
    # the round's collective landed in the per-rank wait accounting
    for rank in (0, 1):
        assert results[rank]["coll_wait"]["count"] >= 1


def test_callback_without_straggler_adds_no_collective(monkeypatch):
    from xgboost_tpu.telemetry import TelemetryCallback

    cb = TelemetryCallback(enable_spans=False)
    cb.before_iteration(None, 0, None)
    cb.after_iteration(object(), 0, None)
    assert "straggler" not in cb.history[0]


# =========================================================================
# slow: real 2-replica fleet, one scrape = per-process + merged series


@pytest.mark.slow
def test_fleet_scrape_merged_equals_per_replica_sum(tmp_path, monkeypatch):
    import xgboost_tpu as xtb
    from xgboost_tpu.serving import ServingFleet

    monkeypatch.setenv(distributed.ENV_INTERVAL, "0.2")
    rng = np.random.default_rng(3)
    X = rng.normal(size=(400, 8)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "seed": 3}, xtb.DMatrix(X, label=y), 3,
                    verbose_eval=False)
    total = 60
    with ServingFleet({"obsm": bst}, n_replicas=2,
                      warmup_buckets=(64,)) as fleet:
        # concurrent waves so BOTH replicas serve (window-1 dispatch gives
        # sequential blocking predicts to one free replica over and over)
        for _wave in range(3):
            futs = [fleet.submit("obsm", X[:64]) for _ in range(total // 3)]
            for f in futs:
                f.result(timeout=60)
            time.sleep(0.25)  # let a periodic ship fire mid-run
    # the close handshake makes each replica ship its final snapshot; the
    # rx threads ingest it — poll until the merged count catches up
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        tot = distributed.get_merged().merged_totals(
            "xtb_serve_requests_total").get(("obsm",), 0.0)
        if tot >= total:
            break
        time.sleep(0.05)
    assert tot == total
    srv = distributed.MetricsServer(0).start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10).read(
        ).decode()
    finally:
        srv.close()
    per_proc = {
        proc: float(v) for proc, v in re.findall(
            r'xtb_serve_requests_total\{proc="([^"]+)",model="obsm"\} '
            r'([0-9.e+-]+)', body)}
    (merged_v,) = re.findall(
        r'\nxtb_serve_requests_total\{model="obsm"\} ([0-9.e+-]+)', body)
    assert set(per_proc) == {"replica0", "replica1"}
    assert all(v > 0 for v in per_proc.values())  # both replicas served
    assert float(merged_v) == sum(per_proc.values()) == total
