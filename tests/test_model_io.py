"""Model IO: schema shape, UBJSON, pickling, file ingestion
(reference: tests/python/test_model_compatibility.py, test_pickling.py)."""
import json
import os
import pickle

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.testing.data import make_binary
from xgboost_tpu.utils.ubjson import dump_ubjson, load_ubjson


def test_json_schema_fields(tmp_path):
    X, y = make_binary(300, 5, seed=0)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3}, d, 3,
                    verbose_eval=False)
    f = str(tmp_path / "m.json")
    bst.save_model(f)
    with open(f) as fh:
        obj = json.load(fh)
    # xgboost model schema essentials (doc/model.schema)
    learner = obj["learner"]
    assert learner["objective"]["name"] == "binary:logistic"
    assert "base_score" in learner["learner_model_param"]
    model = learner["gradient_booster"]["model"]
    assert len(model["trees"]) == 3
    t0 = model["trees"][0]
    for key in ("left_children", "right_children", "parents", "split_indices",
                "split_conditions", "default_left", "base_weights",
                "loss_changes", "sum_hessian", "categories", "split_type"):
        assert key in t0, key
    assert int(t0["tree_param"]["num_nodes"]) == len(t0["left_children"])


def test_ubjson_roundtrip_types():
    obj = {"a": [1, 2, 3], "b": 1.5, "c": "hi", "d": True, "e": None,
           "f": {"g": [0.25, -1.0]}, "big": list(range(300))}
    from io import BytesIO

    buf = BytesIO()
    dump_ubjson(obj, buf)
    buf.seek(0)
    back = load_ubjson(buf)
    assert back["a"] == [1, 2, 3]
    assert back["b"] == 1.5
    assert back["c"] == "hi"
    assert back["d"] is True
    assert back["e"] is None
    assert back["big"][299] == 299


def test_pickle_roundtrip():
    X, y = make_binary(300, 5, seed=1)
    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3}, d, 4,
                    verbose_eval=False)
    blob = pickle.dumps(bst)
    b2 = pickle.loads(blob)
    np.testing.assert_allclose(b2.predict(xtb.DMatrix(X)), bst.predict(xtb.DMatrix(X)),
                               rtol=1e-6)


def test_sklearn_pickle():
    X, y = make_binary(200, 4, seed=2)
    clf = xtb.XGBClassifier(n_estimators=3, max_depth=2)
    clf.fit(X, y.astype(int))
    c2 = pickle.loads(pickle.dumps(clf))
    np.testing.assert_array_equal(c2.predict(X), clf.predict(X))


def test_libsvm_and_csv_ingestion(tmp_path):
    # libsvm with qid
    f = tmp_path / "d.libsvm"
    f.write_text("1 qid:0 0:1.5 2:2.0\n0 qid:0 1:0.5\n2 qid:1 0:-1 2:3\n")
    d = xtb.DMatrix(str(f))
    assert d.num_row() == 3 and d.num_col() == 3
    np.testing.assert_array_equal(d.get_label(), [1, 0, 2])
    assert d.info.group_ptr is not None  # qid became groups
    # csv
    c = tmp_path / "d.csv"
    c.write_text("1.0,2.0,3.0\n4.0,,6.0\n")
    dc = xtb.DMatrix(str(c))
    assert dc.num_row() == 2 and dc.num_col() == 3
    assert np.isnan(dc.host_dense()[1, 1])


@pytest.mark.skipif(
    not os.path.exists("/root/reference/demo/data/agaricus.txt.train"),
    reason="environment-limited: the reference checkout "
           "(/root/reference/demo/data) is not present in this container; "
           "test_libsvm_and_csv_ingestion covers the same parser on "
           "generated data")
def test_agaricus_from_reference_data():
    """BASELINE config #1: the reference's own demo file trains to ~0 error."""
    d = xtb.DMatrix("/root/reference/demo/data/agaricus.txt.train")
    dt = xtb.DMatrix("/root/reference/demo/data/agaricus.txt.test")
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3, "eta": 1.0},
                    d, 5, verbose_eval=False)
    p = bst.predict(dt)
    err = float(((p > 0.5) != dt.get_label()).mean())
    assert err < 0.01, err


def test_config_context():
    with xtb.config_context(verbosity=0):
        assert xtb.get_config()["verbosity"] == 0
    assert xtb.get_config()["verbosity"] == 1


def test_config_roundtrip_continuation():
    """learner.cc:625 SaveConfig / :570 LoadConfig + :987 full-state Save:
    train -> serialize -> restore in a fresh Booster -> continue == one
    uninterrupted run, bitwise."""
    rng = np.random.default_rng(21)
    X = rng.normal(size=(900, 7)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 2] > 0).astype(np.float32)
    params = {"objective": "binary:logistic", "max_depth": 5, "eta": 0.17,
              "max_bin": 48, "lambda": 2.5, "gamma": 0.1,
              "eval_metric": ["logloss", "auc"], "seed": 9}
    full = xtb.train(params, xtb.DMatrix(X, label=y), 10, verbose_eval=False)

    half = xtb.train(params, xtb.DMatrix(X, label=y), 5, verbose_eval=False)
    blob = half.serialize()
    fresh = xtb.Booster()
    fresh.unserialize(bytes(blob))
    # config restored: no params passed to the second leg at all
    cont = xtb.train({}, xtb.DMatrix(X, label=y), 5, verbose_eval=False,
                     xgb_model=fresh)
    assert len(cont.trees) == len(full.trees)
    for ta, tb in zip(full.trees, cont.trees):
        np.testing.assert_array_equal(ta.left_children, tb.left_children)
        np.testing.assert_array_equal(ta.split_conditions, tb.split_conditions)


def test_save_config_shape_and_values():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 4)).astype(np.float32)
    y = rng.normal(size=300).astype(np.float32)
    bst = xtb.train({"objective": "reg:squarederror", "max_depth": 3,
                     "eta": 0.11, "max_bin": 32}, xtb.DMatrix(X, label=y), 2,
                    verbose_eval=False)
    import json
    cfg = json.loads(bst.save_config())
    ln = cfg["learner"]
    assert ln["learner_train_param"]["objective"] == "reg:squarederror"
    assert ln["gradient_booster"]["name"] == "gbtree"
    hp = ln["gradient_booster"]["updater"]["grow_quantile_histmaker"]["hist_train_param"]
    assert hp["eta"] == "0.11" and hp["max_bin"] == "32"
    # load_config applies values onto a fresh booster
    b2 = xtb.Booster()
    b2.load_config(bst.save_config())
    assert b2.params["eta"] == "0.11" and int(b2.params["max_bin"]) == 32
