"""Parity against the real dmlc/xgboost (the oracle).

Round-1 verdict: the repo's numpy mirror shares this package's reading of
xgboost semantics, so agreement between them proves nothing (VERDICT.md
"parity tests are circular").  These tests compare against the actual
reference implementation, built CPU-only from /root/reference by
oracle/build_oracle.sh (see the dmlc shim there).  They skip when the oracle
has not been built.

Covers the reference's own strategy (tests/python/test_model_compatibility.py,
tests/python-gpu/test_gpu_updaters.py): (a) statistical parity of training
quality, (b) model-schema truth both directions — our save → oracle load,
oracle save → our load — with prediction equality.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from xgboost_tpu.testing import HAVE_ORACLE, ORACLE_PKG  # noqa: E402

pytestmark = pytest.mark.skipif(
    not HAVE_ORACLE, reason="oracle not built (run oracle/build_oracle.sh)")


def _run_oracle(code: str) -> dict:
    """Run a snippet against the reference package in a clean subprocess
    (its own libxgboost.so must not share state with our jax process)."""
    env = dict(os.environ, PYTHONPATH=ORACLE_PKG, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, f"oracle subprocess failed:\n{out.stderr[-4000:]}"
    return json.loads(out.stdout.strip().splitlines()[-1])


def _data(seed=0, n=2000, f=10, sparsity=0.0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    if sparsity:
        X[rng.random((n, f)) < sparsity] = np.nan
    logit = np.nan_to_num(X[:, 0]) * 1.5 + np.nan_to_num(X[:, 1]) ** 2 - 1.0
    y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(np.float32)
    return X, y


@pytest.mark.parametrize("sparsity", [0.0, 0.2])
def test_training_quality_parity(tmp_path, sparsity):
    """Same data, same params: held-out AUC within 0.01 of the reference
    (reference pattern: test_gpu_updaters.py hist-vs-gpu_hist parity)."""
    X, y = _data(seed=3, sparsity=sparsity)
    Xt, yt = _data(seed=17, sparsity=sparsity)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    np.save(tmp_path / "Xt.npy", Xt)
    np.save(tmp_path / "yt.npy", yt)
    params = {"objective": "binary:logistic", "max_depth": 5, "eta": 0.3,
              "eval_metric": "auc", "tree_method": "hist", "max_bin": 256}
    res = _run_oracle(f"""
import json, numpy as np, xgboost
X = np.load({str(tmp_path / 'X.npy')!r}); y = np.load({str(tmp_path / 'y.npy')!r})
Xt = np.load({str(tmp_path / 'Xt.npy')!r}); yt = np.load({str(tmp_path / 'yt.npy')!r})
dtrain = xgboost.DMatrix(X, label=y); dtest = xgboost.DMatrix(Xt, label=yt)
ev = {{}}
bst = xgboost.train({params!r}, dtrain, 20, evals=[(dtest, "t")],
                    evals_result=ev, verbose_eval=False)
print(json.dumps({{"auc": ev["t"]["auc"][-1]}}))
""")
    import xgboost_tpu as xtb

    dtrain = xtb.DMatrix(X, label=y)
    dtest = xtb.DMatrix(Xt, label=yt)
    ev = {}
    xtb.train(params, dtrain, 20, evals=[(dtest, "t")], evals_result=ev,
              verbose_eval=False)
    ours = ev["t"]["auc"][-1]
    assert abs(ours - res["auc"]) < 0.01, (ours, res["auc"])


def test_our_model_loads_in_oracle(tmp_path):
    """Schema truth: a model saved here must load in dmlc/xgboost and produce
    the same predictions (reference: test_model_compatibility.py)."""
    X, y = _data(seed=5)
    import xgboost_tpu as xtb

    d = xtb.DMatrix(X, label=y)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 4,
                     "eta": 0.3}, d, 8, verbose_eval=False)
    ours = bst.predict(d)
    model_path = tmp_path / "ours.json"
    bst.save_model(str(model_path))
    np.save(tmp_path / "X.npy", X)
    res = _run_oracle(f"""
import json, numpy as np, xgboost
bst = xgboost.Booster()
bst.load_model({str(model_path)!r})
X = np.load({str(tmp_path / 'X.npy')!r})
p = bst.predict(xgboost.DMatrix(X))
print(json.dumps({{"preds": p[:50].tolist()}}))
""")
    np.testing.assert_allclose(ours[:50], res["preds"], rtol=1e-5, atol=1e-6)


def test_oracle_model_loads_here(tmp_path):
    """Reverse direction: a dmlc/xgboost model loads here with prediction
    parity (binary + multiclass)."""
    X, y = _data(seed=7)
    ymc = (np.nan_to_num(X[:, 0]) > 0).astype(int) + (
        np.nan_to_num(X[:, 1]) > 0).astype(int)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    np.save(tmp_path / "ymc.npy", ymc)
    res = _run_oracle(f"""
import json, numpy as np, xgboost
X = np.load({str(tmp_path / 'X.npy')!r}); y = np.load({str(tmp_path / 'y.npy')!r})
ymc = np.load({str(tmp_path / 'ymc.npy')!r})
b1 = xgboost.train({{"objective": "binary:logistic", "max_depth": 4}},
                   xgboost.DMatrix(X, label=y), 8)
b1.save_model({str(tmp_path / 'bin.json')!r})
b2 = xgboost.train({{"objective": "multi:softprob", "num_class": 3,
                     "max_depth": 3}}, xgboost.DMatrix(X, label=ymc), 5)
b2.save_model({str(tmp_path / 'mc.json')!r})
p1 = b1.predict(xgboost.DMatrix(X))
p2 = b2.predict(xgboost.DMatrix(X))
print(json.dumps({{"p1": p1[:50].tolist(), "p2": p2[:20].tolist()}}))
""")
    import xgboost_tpu as xtb

    b1 = xtb.Booster()
    b1.load_model(str(tmp_path / "bin.json"))
    p1 = b1.predict(xtb.DMatrix(X))
    np.testing.assert_allclose(p1[:50], res["p1"], rtol=1e-5, atol=1e-6)

    b2 = xtb.Booster()
    b2.load_model(str(tmp_path / "mc.json"))
    p2 = b2.predict(xtb.DMatrix(X))
    np.testing.assert_allclose(p2[:20].reshape(-1),
                               np.asarray(res["p2"]).reshape(-1),
                               rtol=1e-5, atol=1e-6)


def test_split_semantics_vs_oracle(tmp_path):
    """Single-tree, exact-depth comparison: with deterministic data and one
    boosting round, our tree's (feature, threshold) choices must match the
    oracle's hist updater on identical 256-bin cuts."""
    X, y = _data(seed=11, n=4000, f=6)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 1.0,
              "tree_method": "hist", "max_bin": 256, "lambda": 1.0,
              "base_score": 0.5}
    res = _run_oracle(f"""
import json, numpy as np, xgboost
X = np.load({str(tmp_path / 'X.npy')!r}); y = np.load({str(tmp_path / 'y.npy')!r})
bst = xgboost.train({params!r}, xgboost.DMatrix(X, label=y), 1)
m = json.loads(bst.save_raw("json"))
tree = m["learner"]["gradient_booster"]["model"]["trees"][0]
print(json.dumps({{"split_indices": tree["split_indices"],
                   "split_conditions": tree["split_conditions"]}}))
""")
    import xgboost_tpu as xtb

    bst = xtb.train(params, xtb.DMatrix(X, label=y), 1, verbose_eval=False)
    tree = bst.trees[0]
    n = len(res["split_indices"])
    # identical tree SHAPE and split features; thresholds/leaves only
    # approximately — the two quantile sketches produce slightly different
    # 256-bin grids, so cut values (and hence boundary rows / leaf sums)
    # differ at the grid resolution, exactly as the reference's own
    # hist-vs-gpu_hist tests allow (test_gpu_updaters.py uses metric
    # tolerances, not bitwise trees)
    assert tree.n_nodes == n, (tree.n_nodes, n)
    np.testing.assert_array_equal(tree.split_indices, res["split_indices"])
    np.testing.assert_allclose(tree.split_conditions, res["split_conditions"],
                               rtol=0.25, atol=0.05)


def test_multi_target_model_loads_in_oracle(tmp_path):
    """Vector-leaf schema truth: a multi_output_tree model saved here loads
    in dmlc/xgboost (multi_target_tree_model.cc — leaf index lives in the
    right_children slot) with prediction parity."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(600, 8)).astype(np.float32)
    W = rng.normal(size=(8, 3)).astype(np.float32)
    Y = (X @ W).astype(np.float32)
    import xgboost_tpu as xtb

    d = xtb.DMatrix(X, label=Y)
    bst = xtb.train({"objective": "reg:squarederror", "num_target": 3,
                     "multi_strategy": "multi_output_tree", "max_depth": 4},
                    d, 5, verbose_eval=False)
    ours = bst.predict(d)
    model_path = tmp_path / "multi.json"
    bst.save_model(str(model_path))
    np.save(tmp_path / "X.npy", X)
    res = _run_oracle(f"""
import json, numpy as np, xgboost
bst = xgboost.Booster()
bst.load_model({str(model_path)!r})
X = np.load({str(tmp_path / 'X.npy')!r})
p = bst.predict(xgboost.DMatrix(X))
print(json.dumps({{"shape": list(p.shape), "head": p[:20].reshape(-1).tolist()}}))
""")
    assert res["shape"] == [600, 3]
    np.testing.assert_allclose(ours[:20].reshape(-1), res["head"],
                               rtol=1e-4, atol=1e-5)


def test_ranking_quality_parity(tmp_path):
    """LambdaMART rank:ndcg: final train ndcg@8 within 0.05 of the
    reference on identical grouped data."""
    rng = np.random.default_rng(23)
    n_groups, per = 120, 12
    n = n_groups * per
    X = rng.normal(size=(n, 8)).astype(np.float32)
    rel = np.clip((X[:, 0] + 0.5 * rng.normal(size=n)) * 1.2 + 1.5,
                  0, 3).astype(np.float32).round()
    groups = np.full(n_groups, per, np.int64)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", rel)
    np.save(tmp_path / "g.npy", groups)
    params = {"objective": "rank:ndcg", "max_depth": 4, "eta": 0.3,
              "eval_metric": "ndcg@8", "tree_method": "hist"}
    res = _run_oracle(f"""
import json, numpy as np, xgboost
X = np.load({str(tmp_path / 'X.npy')!r}); y = np.load({str(tmp_path / 'y.npy')!r})
g = np.load({str(tmp_path / 'g.npy')!r})
d = xgboost.DMatrix(X, label=y); d.set_group(g)
ev = {{}}
xgboost.train({params!r}, d, 15, evals=[(d, "t")], evals_result=ev,
              verbose_eval=False)
print(json.dumps({{"ndcg": ev["t"]["ndcg@8"][-1]}}))
""")
    import xgboost_tpu as xtb

    d = xtb.DMatrix(X, label=rel, group=groups)
    ev = {}
    xtb.train(params, d, 15, evals=[(d, "t")], evals_result=ev,
              verbose_eval=False)
    ours = ev["t"]["ndcg@8"][-1]
    # LambdaMART implementations differ in pair weighting details
    # (lambdarank_pair_method etc.); 0.05 still separates working vs broken.
    # Observed spread when this gate landed: |delta| ~= 0.02-0.04 across
    # seeds, entirely from pair-sampling differences — hence 0.05, not 0.03.
    assert abs(ours - res["ndcg"]) < 0.05, (ours, res["ndcg"])


def test_quantile_objective_parity(tmp_path):
    """reg:quantileerror at alpha 0.9: train pinball loss within 15% of the
    reference (adaptive-leaf quantile updates on both sides)."""
    rng = np.random.default_rng(29)
    n = 3000
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (X[:, 0] * 2 + rng.gumbel(size=n)).astype(np.float32)
    np.save(tmp_path / "X.npy", X)
    np.save(tmp_path / "y.npy", y)
    params = {"objective": "reg:quantileerror", "quantile_alpha": 0.9,
              "max_depth": 4, "eta": 0.3, "tree_method": "hist"}
    res = _run_oracle(f"""
import json, numpy as np, xgboost
X = np.load({str(tmp_path / 'X.npy')!r}); y = np.load({str(tmp_path / 'y.npy')!r})
bst = xgboost.train({params!r}, xgboost.DMatrix(X, label=y), 15)
p = bst.predict(xgboost.DMatrix(X))
u = y - p
pin = float(np.mean(np.where(u >= 0, 0.9 * u, -0.1 * u)))
print(json.dumps({{"pinball": pin, "coverage": float((y <= p).mean())}}))
""")
    import xgboost_tpu as xtb

    bst = xtb.train(params, xtb.DMatrix(X, label=y), 15, verbose_eval=False)
    p = bst.predict(xtb.DMatrix(X))
    u = y - p
    pin = float(np.mean(np.where(u >= 0, 0.9 * u, -0.1 * u)))
    cov = float((y <= p).mean())
    assert abs(pin - res["pinball"]) < 0.15 * max(pin, res["pinball"]), \
        (pin, res["pinball"])
    assert abs(cov - res["coverage"]) < 0.05, (cov, res["coverage"])


@pytest.mark.skipif(
    not HAVE_ORACLE, reason="oracle not built (run oracle/build_oracle.sh)")
def test_interactions_parity(tmp_path):
    """SHAP interaction values vs the reference oracle on the same model
    (regression: the previous conditional-walker implementation deviated
    from the reference's quadrature formulation by up to 0.67 per cell)."""
    src = r"""
import json, sys
import numpy as np
sys.path.insert(0, "%(oracle)s")
import xgboost as xgb

rng = np.random.default_rng(0)
X = rng.normal(size=(60, 5)).astype(np.float32)
X[rng.random(X.shape) < 0.1] = np.nan
bst = xgb.Booster(model_file="%(model)s")
out = bst.predict(xgb.DMatrix(X), pred_interactions=True)
np.save("%(out)s", out)
"""
    import subprocess
    import sys as _sys

    import xgboost_tpu as xtb

    rng = np.random.default_rng(0)
    X = rng.normal(size=(60, 5)).astype(np.float32)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) * np.nan_to_num(X[:, 1])
         + np.nan_to_num(X[:, 2]) > 0).astype(np.float32)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 4,
                     "eta": 0.3}, xtb.DMatrix(X, label=y), 4,
                    verbose_eval=False)
    model = str(tmp_path / "m.json")
    outp = str(tmp_path / "oi.npy")
    bst.save_model(model)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [_sys.executable, "-c",
         src % {"oracle": ORACLE_PKG, "model": model, "out": outp}],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    oracle = np.load(outp)

    from xgboost_tpu.interpret import predict_interactions

    for dev in (False, True):
        ours = predict_interactions(bst, xtb.DMatrix(X), slice(None),
                                    use_device=dev)
        np.testing.assert_allclose(ours, oracle, rtol=1e-4, atol=1e-5,
                                   err_msg=f"use_device={dev}")
