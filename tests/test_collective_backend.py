"""Swappable collective backend (reference: Coll trait src/collective/coll.h:23,
CommGroup backend select comm_group.cc:99, InMemoryCommunicator
in_memory_communicator.h:18 + thread-worker harness test_worker.h:155).

The in-memory backend runs N *threads* in one process, each with its own
rank and row shard, through the same ProcessHistTreeGrower code path that
real multi-process training uses — no sockets, no subprocesses."""
import threading

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu import collective


def test_op_coverage_single_process():
    a = np.asarray([3, 5], np.int64)
    np.testing.assert_array_equal(collective.allreduce(a, collective.Op.MAX), a)
    assert collective.get_rank() == 0
    assert not collective.is_distributed()


def _worker(rank, world, results, errors, group):
    try:
        with collective.CommunicatorContext(
                dmlc_communicator="in-memory",
                in_memory_world_size=world, in_memory_rank=rank,
                in_memory_group=group):
            _grp = collective._TLS.backend._group
            assert collective.get_rank() == rank
            assert collective.get_world_size() == world
            assert collective.is_distributed()

            # primitive round-trips
            s = collective.allreduce(np.asarray([rank + 1.0]))
            assert float(s[0]) == world * (world + 1) / 2
            obj = collective.broadcast(
                {"cuts": [1, 2, 3]} if rank == 0 else None, 0)
            assert obj == {"cuts": [1, 2, 3]}

            # end-to-end: disjoint row shards -> identical trees
            rng = np.random.default_rng(0)
            X = rng.normal(size=(2000, 6)).astype(np.float32)
            y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
            d = xtb.DMatrix(X[rank::world], label=y[rank::world])
            bst = xtb.train({"objective": "binary:logistic", "max_depth": 4,
                             "eta": 0.3, "max_bin": 64}, d, 3,
                            verbose_eval=False)
            results[rank] = "".join(bst.get_dump(dump_format="json"))
    except Exception as e:  # noqa: BLE001
        errors[rank] = e
        # unblock peers stuck on the barrier
        try:
            _grp.barrier.abort()
        except Exception:
            pass


def test_inmemory_thread_workers_identical_trees():
    world = 4
    results, errors = {}, {}
    threads = [
        threading.Thread(target=_worker,
                         args=(r, world, results, errors, "t4"), daemon=True)
        for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    assert not any(t.is_alive() for t in threads), "worker deadlocked"
    assert not errors, errors
    dumps = [results[r] for r in range(world)]
    assert all(d == dumps[0] for d in dumps[1:])


def test_aggregator_sugar():
    """GlobalSum/GlobalMax/GlobalRatio (aggregator.h role), single and
    2-worker in-memory."""
    # single-process identities
    np.testing.assert_array_equal(collective.global_sum(np.asarray([2.0, 3.0])),
                                  [2.0, 3.0])
    assert int(collective.global_max(np.asarray([7]))[0]) == 7
    assert collective.global_ratio(3.0, 4.0) == 0.75
    assert np.isnan(collective.global_ratio(1.0, 0.0))

    results, errors = {}, {}

    def worker(rank):
        try:
            with collective.CommunicatorContext(
                    dmlc_communicator="in-memory", in_memory_world_size=2,
                    in_memory_rank=rank, in_memory_group="agg"):
                _grp = collective._TLS.backend._group
                s = collective.global_sum(np.asarray([float(rank + 1)]))
                m = collective.global_max(np.asarray([rank]))
                r = collective.global_ratio(float(rank), 1.0)
                results[rank] = (float(s[0]), int(m[0]), r)
        except Exception as e:  # noqa: BLE001
            errors[rank] = e
            try:
                _grp.barrier.abort()
            except Exception:
                pass

    import threading
    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert results[0] == results[1] == (3.0, 1, 0.5)
