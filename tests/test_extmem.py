"""External-memory training (reference: tests/python/test_data_iterator.py,
tests/cpp/data/test_extmem_quantile_dmatrix.cc).

The key consistency oracle mirrors the reference: external-memory training
over batches must closely match in-core training on the concatenated data."""
import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.testing.data import make_binary


class NumpyBatchIter(xtb.DataIter):
    def __init__(self, Xs, ys):
        super().__init__()
        self.Xs, self.ys = Xs, ys
        self.i = 0

    def next(self, input_data):
        if self.i >= len(self.Xs):
            return 0
        input_data(data=self.Xs[self.i], label=self.ys[self.i])
        self.i += 1
        return 1

    def reset(self):
        self.i = 0


@pytest.fixture(scope="module")
def batches():
    X, y = make_binary(3000, 8, seed=0)
    splits = [0, 900, 2000, 2500, 3000]  # uneven batch sizes
    Xs = [X[a:b] for a, b in zip(splits, splits[1:])]
    ys = [y[a:b] for a, b in zip(splits, splits[1:])]
    return X, y, Xs, ys


def test_extmem_matches_incore(batches):
    X, y, Xs, ys = batches
    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
              "max_bin": 64}
    d_ext = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64)
    assert d_ext.num_row() == 3000
    res_e = {}
    bst_e = xtb.train(params, d_ext, 10, evals=[(d_ext, "t")],
                      evals_result=res_e, verbose_eval=False)

    d_in = xtb.QuantileDMatrix(X, label=y, max_bin=64)
    res_i = {}
    bst_i = xtb.train(params, d_in, 10, evals=[(d_in, "t")],
                      evals_result=res_i, verbose_eval=False)
    # sketches differ slightly (batch-merged quantiles), so require close
    # final quality rather than identical trees
    assert abs(res_e["t"]["logloss"][-1] - res_i["t"]["logloss"][-1]) < 0.02


def test_extmem_predict_consistent_with_train(batches):
    X, y, Xs, ys = batches
    d_ext = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "max_bin": 64}, d_ext, 5, verbose_eval=False)
    p = bst.predict(d_ext)
    assert p.shape == (3000,)
    assert ((p > 0.5) == y).mean() > 0.85
    # binned-page predict must agree with raw-value predict on the same rows
    p_raw = bst.predict(xtb.DMatrix(X))
    np.testing.assert_allclose(p, p_raw, atol=1e-5)


def test_extmem_disk_spill(batches, tmp_path):
    X, y, Xs, ys = batches
    d_ext = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=32,
                                      on_host=False)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "max_bin": 32}, d_ext, 3, verbose_eval=False)
    assert np.isfinite(bst.predict(d_ext)).all()


def test_extmem_multidevice_matches_single(batches):
    """extmem x n_devices: page rows sharded over the virtual 8-device mesh
    must reproduce the single-device extmem trees exactly (round-2 item:
    VERDICT removed-NotImplementedError path)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X, y, Xs, ys = batches
    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
              "max_bin": 64}
    d1 = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64)
    b1 = xtb.train(params, d1, 4, verbose_eval=False)
    d8 = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64)
    b8 = xtb.train({**params, "n_devices": 8}, d8, 4, verbose_eval=False)
    # identical-trees is only promised across workers of ONE config (see
    # test_multiprocess); across device counts the f32 reduction grouping
    # differs, so compare quality like the reference's 1-vs-N GPU tests do
    p1, p8 = b1.predict(d1), b8.predict(d8)
    assert np.mean((p1 > 0.5) != (p8 > 0.5)) < 0.01
    ll1 = -np.mean(y * np.log(np.clip(p1, 1e-7, 1)) +
                   (1 - y) * np.log(np.clip(1 - p1, 1e-7, 1)))
    ll8 = -np.mean(y * np.log(np.clip(p8, 1e-7, 1)) +
                   (1 - y) * np.log(np.clip(1 - p8, 1e-7, 1)))
    assert abs(ll1 - ll8) < 0.01, (ll1, ll8)


def test_extmem_single_batch_equals_incore_exactly():
    X, y = make_binary(1024, 6, seed=1)
    params = {"objective": "binary:logistic", "max_depth": 4, "max_bin": 32}
    d_ext = xtb.ExtMemQuantileDMatrix(NumpyBatchIter([X], [y]), max_bin=32)
    d_in = xtb.QuantileDMatrix(X, label=y, max_bin=32)
    bst_e = xtb.train(params, d_ext, 5, verbose_eval=False)
    bst_i = xtb.train(params, d_in, 5, verbose_eval=False)
    # identical cuts (single batch) -> identical trees
    for te, ti in zip(bst_e.trees, bst_i.trees):
        np.testing.assert_array_equal(te.split_indices, ti.split_indices)
        np.testing.assert_array_equal(te.left_children, ti.left_children)
        np.testing.assert_allclose(te.split_conditions, ti.split_conditions,
                                   rtol=1e-5, atol=1e-6)


def test_page_compression(tmp_path, batches):
    """Zstd-compressed pages (the nvCOMP/compressed_iterator role): same
    trees as uncompressed, real RAM savings on binned codes."""
    # environment-limited: without the zstandard package the extmem layer
    # (deliberately) falls back to uncompressed pages with a UserWarning,
    # so there is nothing to measure — the compression contract itself
    # cannot be exercised here
    pytest.importorskip("zstandard",
                        reason="zstandard not installed: pages stay "
                               "uncompressed (graceful-fallback path)")
    from xgboost_tpu.data.extmem import CompressedPage

    X, y, Xs, ys = batches
    params = {"objective": "binary:logistic", "max_depth": 4, "max_bin": 64}
    d_c = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64,
                                    compress=True)
    d_u = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64,
                                    compress=False)
    assert all(isinstance(p, CompressedPage) for p in d_c._pages)
    raw_bytes = sum(p.nbytes for p in d_u._pages)
    comp_bytes = sum(p.nbytes_compressed for p in d_c._pages)
    assert comp_bytes < raw_bytes * 0.8, (comp_bytes, raw_bytes)
    b_c = xtb.train(params, d_c, 4, verbose_eval=False)
    b_u = xtb.train(params, d_u, 4, verbose_eval=False)
    assert b_c.get_dump() == b_u.get_dump()
    np.testing.assert_array_equal(b_c.predict(d_c), b_u.predict(d_u))
    # disk-spilled compressed pages work too
    d_d = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64,
                                    compress=True, on_host=False)
    b_d = xtb.train(params, d_d, 4, verbose_eval=False)
    assert b_d.get_dump() == b_u.get_dump()


class _ArrayIter(xtb.DataIter):
    def __init__(self, batches):
        super().__init__()
        self._b, self._i = batches, 0

    def reset(self):
        self._i = 0

    def next(self, input_data):
        if self._i >= len(self._b):
            return 0
        input_data(**self._b[self._i])
        self._i += 1
        return 1


def test_sparse_page_dmatrix_raw_predict_and_training():
    """SparsePageDMatrix (sparse_page_dmatrix.h role): raw CSR pages spill,
    training runs through the binned replay, and prediction streams the RAW
    pages with exact float thresholds — including with a model trained on
    different cuts (the flow binned extmem cannot serve)."""
    rng = np.random.default_rng(4)
    X = rng.normal(size=(1200, 5)).astype(np.float32)
    X[rng.random(X.shape) < 0.15] = np.nan
    y = (np.nan_to_num(X[:, 0]) + np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
    batches = [{"data": X[i * 400:(i + 1) * 400],
                "label": y[i * 400:(i + 1) * 400]} for i in range(3)]

    d = xtb.SparsePageDMatrix(_ArrayIter(batches), max_bin=32)
    assert d.num_row() == 1200 and d.num_col() == 5
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "max_bin": 32}, d, 3, verbose_eval=False)
    np.testing.assert_array_equal(bst.predict(d),
                                  bst.predict(xtb.DMatrix(X)))

    # a model trained on DIFFERENT cuts predicts on the raw pages exactly
    other = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                       "max_bin": 17}, xtb.DMatrix(X, label=y), 2,
                      verbose_eval=False)
    np.testing.assert_array_equal(other.predict(d),
                                  other.predict(xtb.DMatrix(X)))


def test_sparse_page_dmatrix_scipy_batches_and_sentinel():
    """CSR batches keep explicit valid zeros; a finite missing sentinel is
    filtered structurally at ingestion."""
    import scipy.sparse as sp

    rng = np.random.default_rng(7)
    dense = rng.normal(size=(600, 4)).astype(np.float32)
    dense[rng.random(dense.shape) < 0.3] = 0.0  # explicit zeros stay valid
    y = (dense[:, 0] > 0).astype(np.float32)
    batches = [{"data": sp.csr_matrix(dense[:300]), "label": y[:300]},
               {"data": sp.csr_matrix(dense[300:]), "label": y[300:]}]
    d = xtb.SparsePageDMatrix(_ArrayIter(batches), max_bin=16)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 2,
                     "max_bin": 16}, d, 2, verbose_eval=False)
    # scipy ingest drops nothing finite; but CSR absent entries ARE missing
    Xnan = np.where(dense == 0.0, np.nan, dense)
    np.testing.assert_array_equal(bst.predict(d),
                                  bst.predict(xtb.DMatrix(Xnan)))

    # finite sentinel: -1 means missing, dropped at ingestion
    dense2 = np.abs(rng.normal(size=(200, 3)).astype(np.float32))
    dense2[rng.random(dense2.shape) < 0.2] = -1.0
    d2 = xtb.SparsePageDMatrix(
        _ArrayIter([{"data": dense2, "label": (dense2[:, 0] > 0.5).astype(np.float32)}]),
        missing=-1.0, max_bin=16)
    b2 = xtb.train({"objective": "binary:logistic", "max_depth": 2,
                    "max_bin": 16}, d2, 2, verbose_eval=False)
    X2 = np.where(dense2 == -1.0, np.nan, dense2)
    np.testing.assert_array_equal(b2.predict(d2),
                                  b2.predict(xtb.DMatrix(X2)))


@pytest.mark.slow
def test_extmem_twenty_pages_mesh_parity(eight_devices):
    """>= 20 zstd pages streamed through the 8-chip sharded grower
    (VERDICT r4 #9): training must match the in-memory mesh model on the
    same rows, and the prefetch=off mode must produce identical trees
    (overlap is a scheduling property, never a numerical one)."""
    import hashlib

    import xgboost_tpu as xtb
    from xgboost_tpu.data.extmem import DataIter, ExtMemQuantileDMatrix

    n_pages, rows_page, F = 20, 1024, 6
    rng = np.random.default_rng(3)
    w = rng.normal(size=F).astype(np.float32)
    X_all = rng.normal(size=(n_pages * rows_page, F)).astype(np.float32)
    y_all = (X_all @ w + rng.normal(scale=0.4, size=len(X_all)) > 0
             ).astype(np.float32)

    class Pages(DataIter):
        def __init__(self):
            super().__init__()
            self._i = 0

        def next(self, input_data):
            if self._i >= n_pages:
                return 0
            lo = self._i * rows_page
            input_data(data=X_all[lo:lo + rows_page],
                       label=y_all[lo:lo + rows_page])
            self._i += 1
            return 1

        def reset(self):
            self._i = 0

    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
              "max_bin": 32, "n_devices": 8}
    d = ExtMemQuantileDMatrix(Pages(), max_bin=32)
    assert len(d._pages) == n_pages

    def h(bst):
        return hashlib.md5(
            "".join(bst.get_dump(dump_format="json")).encode()).hexdigest()

    bst = xtb.train(params, d, 3, verbose_eval=False)
    bst_serial = xtb.train({**params, "_extmem_prefetch": "0"}, d, 3,
                           verbose_eval=False)
    assert h(bst) == h(bst_serial)  # prefetch is numerically transparent

    # quality parity vs in-memory mesh training on the same rows (cuts
    # differ: streamed sketch merges per-page grids), so compare quality
    bst_mem = xtb.train(params, xtb.DMatrix(X_all, label=y_all), 3,
                        verbose_eval=False)
    p_ext = bst.predict(d)
    p_mem = bst_mem.predict(xtb.DMatrix(X_all))
    err_ext = np.mean((p_ext > 0.5) != y_all)
    err_mem = np.mean((p_mem > 0.5) != y_all)
    assert err_ext <= err_mem + 0.02, (err_ext, err_mem)


def _split_events_by_level(events):
    levels = []
    cur = None
    for e in events:
        if e[0] == "level":
            cur = []
            levels.append(cur)
        elif cur is not None:
            cur.append(e)
    return levels


def test_prefetch_overlap_pipeline_deterministic(monkeypatch):
    """Prefetch must actually pipeline page staging under page compute
    (VERDICT r4 #6).  The old form of this test thresholded a wall-clock
    ratio, which flaked on time-shared hosts; the pipeline property is now
    pinned deterministically (ISSUE 12 satellite):

    - event ordering (main-thread program order, scheduling-independent):
      with prefetch on, page j+1's decode is SUBMITTED before the consumer
      blocks on page j — the decode is in flight under page j's compute —
      while the serialized baseline stages strictly synchronously;
    - the xtb_extmem_* counters account for the work: every page staged is
      counted, and decode seconds include the simulated transfer's exact
      floor (the sleep is inside the staged decode);
    - prefetch stays numerically transparent: identical trees either way.
    """
    from xgboost_tpu.data import extmem
    from xgboost_tpu.data.extmem import DataIter, ExtMemQuantileDMatrix

    rng = np.random.default_rng(5)
    n_pages, rows_page, F = 4, 2048, 16
    X_all = rng.normal(size=(n_pages * rows_page, F)).astype(np.float32)
    y_all = (X_all[:, 0] + 0.3 * rng.normal(size=len(X_all)) > 0).astype(
        np.float32)

    class Pages(DataIter):
        def __init__(self):
            super().__init__()
            self._i = 0

        def next(self, input_data):
            if self._i >= n_pages:
                return 0
            lo = self._i * rows_page
            input_data(data=X_all[lo:lo + rows_page],
                       label=y_all[lo:lo + rows_page])
            self._i += 1
            return 1

        def reset(self):
            self._i = 0

    # a synthetic per-byte transfer latency rides inside the staged decode
    # (and disables the CPU committed-page cache, preserving the TPU-like
    # stream-every-level shape); 20 ms/MB on 2048x16 u8 pages = 0.64 ms
    # per page load — a deterministic floor for the decode counter
    sim_ms_per_mb = 20.0
    monkeypatch.setenv("XTB_EXTMEM_SIM_TRANSFER_MS_PER_MB",
                       str(sim_ms_per_mb))
    monkeypatch.setenv("XTB_EXTMEM_EVENT_LOG", "1")
    d = ExtMemQuantileDMatrix(Pages(), max_bin=32)
    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
              "max_bin": 32}
    ins = extmem.instruments()

    def run(prefetch: str):
        extmem.PAGE_EVENT_LOG.clear()
        before = (ins[0].get(), ins[1].get(), ins[2].get(), ins[3].get())
        bst = xtb.train({**params, "_extmem_prefetch": prefetch}, d, 2,
                        verbose_eval=False)
        import jax

        jax.block_until_ready(bst._caches[id(d)].margin)
        delta = (ins[0].get() - before[0], ins[1].get() - before[1],
                 ins[2].get() - before[2], ins[3].get() - before[3])
        return bst, list(extmem.PAGE_EVENT_LOG), delta

    bst_pre, ev_pre, d_pre = run("1")
    bst_ser, ev_ser, d_ser = run("0")
    assert bst_pre.get_dump() == bst_ser.get_dump()  # transparency

    # prefetch on: within every level, the scheduler submits page j+1
    # before blocking on page j (main-thread program order — deterministic
    # however the host schedules the worker threads)
    levels_pre = _split_events_by_level(ev_pre)
    assert levels_pre, ev_pre[:8]
    waits = 0
    for lv in levels_pre:
        assert not any(n == "load_sync" for n, _ in lv)
        submit_at = {j: k for k, (n, j) in enumerate(lv) if n == "submit"}
        for k, (n, j) in enumerate(lv):
            if n == "wait" and j + 1 in submit_at:
                waits += 1
                assert submit_at[j + 1] < k, (j, lv)
    assert waits > 0  # the ordering property was actually exercised

    # serialized baseline: strictly synchronous staging, no window
    assert all(n == "load_sync" for lv in _split_events_by_level(ev_ser)
               for n, _ in lv)

    # counters: every page staged is accounted (4 levels x 4 pages x
    # 2 rounds per run), and decode seconds carry at least the simulated
    # transfer floor; overlap can only be claimed by the prefetch run
    page_mb = (rows_page * F) / 1e6
    n_loads = 4 * n_pages * 2
    for dd in (d_pre, d_ser):
        assert dd[3] == n_loads, (dd, n_loads)
        assert dd[0] >= n_loads * page_mb * sim_ms_per_mb / 1e3 * 0.99
    assert d_ser[2] == 0.0  # serialized: nothing overlaps by construction
    assert d_pre[2] >= 0.0 and d_pre[1] >= 0.0


# ---------------------------------------------------------------------------
# ISSUE 12: streaming out-of-core distributed training
# ---------------------------------------------------------------------------


def _cuts_bytes(cuts):
    return (cuts.cut_ptrs.tobytes(), cuts.cut_values.tobytes(),
            cuts.min_vals.tobytes())


def _run_thread_world(world, fn, group):
    """Run fn(rank) under an in-memory collective at `world` ranks; returns
    {rank: result} and re-raises the first worker error."""
    import threading

    from xgboost_tpu import collective

    out, errors = {}, {}

    def worker(rank):
        try:
            with collective.CommunicatorContext(
                    dmlc_communicator="in-memory", in_memory_group=group,
                    in_memory_world_size=world, in_memory_rank=rank):
                out[rank] = fn(rank)
        except Exception as e:  # noqa: BLE001 - reported to the main thread
            errors[rank] = e

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads), "worker deadlocked"
    if errors:
        raise errors[min(errors)]
    return out


def test_streaming_sketch_page_parity_fuzz():
    """The pinned streaming-sketch contract (docs/extmem.md): the page is
    the atomic sketch unit and the merge is order-insensitive — cuts are
    bitwise-identical however the pages are grouped onto ranks (world
    1/2/4) and in whatever order each rank pushes its pages, and equal to
    the one-shot sketch_distributed where each page is one rank's shard."""
    from xgboost_tpu.data.quantile import StreamingSketch, sketch_distributed

    rng = np.random.default_rng(11)
    n_pages, F = 8, 5
    pages = [rng.normal(size=(rng.integers(50, 300), F)).astype(np.float32)
             for _ in range(n_pages)]
    for p in pages:
        p[rng.random(p.shape) < 0.1] = np.nan
    weights = [rng.random(len(p)).astype(np.float32) + 0.1 for p in pages]

    def streaming(world, order_seed, group, weighted):
        def fn(rank):
            mine = [i for i in range(n_pages) if i % world == rank]
            np.random.default_rng(order_seed + rank).shuffle(mine)
            sk = StreamingSketch(F, 16)
            for i in mine:
                sk.push(pages[i], weights=weights[i] if weighted else None)
            return _cuts_bytes(sk.finalize(distributed=True))

        got = _run_thread_world(world, fn, group)
        assert all(v == got[0] for v in got.values())
        return got[0]

    for weighted in (False, True):
        tag = "w" if weighted else "u"
        ref = streaming(1, 3, f"sk1{tag}", weighted)
        assert streaming(2, 17, f"sk2{tag}", weighted) == ref
        assert streaming(4, 29, f"sk4{tag}", weighted) == ref
        # one-shot sketch_distributed, one page per rank == streaming
        oneshot = _run_thread_world(
            n_pages,
            lambda rank: _cuts_bytes(sketch_distributed(
                pages[rank], 16,
                weights=weights[rank] if weighted else None)),
            f"sk8{tag}")
        assert oneshot[0] == ref


def test_streaming_sketch_csr_parity():
    """push_csr: page-wise CSR streaming == one-shot
    sketch_csr(distributed=True) with one page per rank, across groupings
    — including categorical columns (identity cuts from the global max)."""
    import scipy.sparse as sp

    from xgboost_tpu.data.quantile import StreamingSketch, sketch_csr

    rng = np.random.default_rng(23)
    n_pages, F = 6, 4
    cat_mask = np.array([False, True, False, False])
    pages = []
    for _ in range(n_pages):
        R = int(rng.integers(40, 120))
        X = rng.normal(size=(R, F)).astype(np.float32)
        X[:, 1] = rng.integers(0, 5, size=R)  # categorical codes
        X[rng.random((R, F)) < 0.2] = 0.0     # implicit-zero candidates
        pages.append(sp.csr_matrix(X))

    def streaming(world, group):
        def fn(rank):
            mine = [i for i in range(n_pages) if i % world == rank]
            sk = StreamingSketch(F, 16, cat_mask=cat_mask)
            for i in reversed(mine):  # adversarial push order
                c = pages[i]
                sk.push_csr(c.indptr, c.indices, c.data)
            return _cuts_bytes(sk.finalize(distributed=True))

        got = _run_thread_world(world, fn, group)
        assert all(v == got[0] for v in got.values())
        return got[0]

    ref = streaming(1, "csr1")
    assert streaming(2, "csr2") == ref
    assert streaming(3, "csr3") == ref

    def oneshot(rank):
        c = pages[rank]
        return _cuts_bytes(sketch_csr(c.indptr, c.indices, c.data, F, 16,
                                      cat_mask=cat_mask, distributed=True))

    assert _run_thread_world(n_pages, oneshot, "csr6")[0] == ref


def _paged_iter(X, y, page_rows):
    class Pages(xtb.DataIter):
        def __init__(self, idx):
            super().__init__()
            self._idx, self._i = idx, 0

        def reset(self):
            self._i = 0

        def next(self, input_data):
            if self._i >= len(self._idx):
                return 0
            lo = self._idx[self._i] * page_rows
            input_data(data=X[lo:lo + page_rows], label=y[lo:lo + page_rows])
            self._i += 1
            return 1

    return Pages


def test_extmem_world2_bitwise_matches_incore_single():
    """The full-pipeline bitwise contract (ISSUE 12): a world-2 extmem run
    under deterministic_histogram (exact integer limb histograms: page
    accumulation and the cross-rank reduce are associative) produces the
    exact model bytes of the in-memory single-process run on the same
    binned data — same cuts injected via ref=."""
    n_pages, page_rows, F = 4, 1024, 6
    rng = np.random.default_rng(31)
    X = rng.normal(size=(n_pages * page_rows, F)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
              "max_bin": 16, "deterministic_histogram": 1}
    Pages = _paged_iter(X, y, page_rows)

    d_in = xtb.QuantileDMatrix(X, label=y, max_bin=16)
    ref_bytes = bytes(xtb.train(params, d_in, 3,
                                verbose_eval=False).save_raw())

    def fn(rank):
        d = xtb.ExtMemQuantileDMatrix(
            Pages([2 * rank, 2 * rank + 1]), max_bin=16, ref=d_in)
        return bytes(xtb.train(params, d, 3, verbose_eval=False).save_raw())

    got = _run_thread_world(2, fn, "extmem_bw2")
    assert got[0] == got[1], "ranks disagree"
    assert got[0] == ref_bytes, \
        "world-2 extmem != in-memory single-process model bytes"


def test_extmem_config_world_invariant():
    """train(params, ExtMemConfig(...)): the launcher-shaped composition.
    Under deterministic_histogram the model is world-invariant BITWISE:
    the page is the sketch unit (cuts identical at any world) and limb
    histograms reduce exactly, so world 1 == world 2 model bytes."""
    n_pages, page_rows, F = 4, 1024, 5
    rng = np.random.default_rng(37)
    X = rng.normal(size=(n_pages * page_rows, F)).astype(np.float32)
    y = (X[:, 0] - 0.3 * X[:, 2] > 0).astype(np.float32)
    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
              "max_bin": 16, "deterministic_histogram": 1}
    Pages = _paged_iter(X, y, page_rows)

    def make_cfg():
        def data_fn(smap, rank, world):
            return Pages(list(smap.shards_of(rank)))

        return xtb.ExtMemConfig(data_fn, num_shards=n_pages, max_bin=16)

    def fn(rank):
        return bytes(xtb.train(params, make_cfg(), 3,
                               verbose_eval=False).save_raw())

    single = _run_thread_world(1, fn, "excfg1")[0]
    got = _run_thread_world(2, fn, "excfg2")
    assert got[0] == got[1] == single


def test_extmem_page_load_fault_surfaces_cleanly():
    """A mid-stream decode failure at the extmem.page_load seam surfaces
    as a clean FaultInjected on the consumer — training fails loudly
    instead of wedging (the multi-process twin runs in
    scripts/extmem_smoke.py)."""
    from xgboost_tpu.reliability import faults

    assert "extmem.page_load" in faults.SEAMS
    n_pages, page_rows = 3, 1024
    rng = np.random.default_rng(41)
    X = rng.normal(size=(n_pages * page_rows, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    d = xtb.ExtMemQuantileDMatrix(
        _paged_iter(X, y, page_rows)(list(range(n_pages))), max_bin=16)
    faults.install({"faults": [{"site": "extmem.page_load",
                                "kind": "exception", "round": 1}]})
    try:
        with pytest.raises(faults.FaultInjected):
            xtb.train({"objective": "binary:logistic", "max_depth": 3,
                       "max_bin": 16}, d, 2, verbose_eval=False)
    finally:
        faults.clear()
    # the matrix is not poisoned: a clean retry trains through
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "max_bin": 16}, d, 2, verbose_eval=False)
    assert np.isfinite(bst.predict(d)).all()


def test_gradient_sampling_page_residency(monkeypatch):
    """Gradient-based sampling decides page residency: pages whose rows
    all sampled out (zero gpair) are loaded once per tree instead of once
    per level, and the routed-once positions leave the model identical to
    the skip-disabled run."""
    from xgboost_tpu.data import extmem

    n_pages, page_rows, F = 4, 1024, 5
    rng = np.random.default_rng(43)
    X = rng.normal(size=(n_pages * page_rows, F)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    d = xtb.ExtMemQuantileDMatrix(
        _paged_iter(X, y, page_rows)(list(range(n_pages))), max_bin=16)

    # a custom objective that zeroes the gradient on the last two pages:
    # gradient_based sampling then assigns them keep-probability 0, so
    # their gpair is exactly zero and the pages lose residency
    cut = 2 * page_rows

    def obj(preds, dmat):
        g = np.asarray(preds, np.float64) - y
        h = np.ones_like(g)
        g[cut:] = 0.0
        h[cut:] = 0.0
        return g, h

    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
              "max_bin": 16, "subsample": 0.9,
              "sampling_method": "gradient_based", "seed": 7}
    ins = extmem.instruments()
    monkeypatch.setenv("XTB_EXTMEM_SIM_TRANSFER_MS_PER_MB", "0.001")

    def run(skip: str):
        before = ins[3].get()
        bst = xtb.train({**params, "_extmem_page_skip": skip}, d, 2,
                        verbose_eval=False, obj=obj)
        return bst, ins[3].get() - before

    bst_skip, loads_skip = run("1")
    bst_full, loads_full = run("0")
    # 2 rounds x 4 levels x 4 pages when every page streams every level;
    # with residency the two zero-gradient pages stream once per tree
    assert loads_full == 2 * 4 * n_pages, loads_full
    assert loads_skip == 2 * (4 * 2 + 2), loads_skip
    assert bst_skip.get_dump() == bst_full.get_dump()
    p_skip, p_full = bst_skip.predict(d), bst_full.predict(d)
    np.testing.assert_array_equal(p_skip, p_full)


def test_extmem_empty_iterator_raises():
    class Empty(xtb.DataIter):
        def reset(self):
            pass

        def next(self, input_data):
            return 0

    with pytest.raises(ValueError, match="no batches"):
        xtb.ExtMemQuantileDMatrix(Empty(), max_bin=16)
