"""External-memory training (reference: tests/python/test_data_iterator.py,
tests/cpp/data/test_extmem_quantile_dmatrix.cc).

The key consistency oracle mirrors the reference: external-memory training
over batches must closely match in-core training on the concatenated data."""
import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.testing.data import make_binary


class NumpyBatchIter(xtb.DataIter):
    def __init__(self, Xs, ys):
        super().__init__()
        self.Xs, self.ys = Xs, ys
        self.i = 0

    def next(self, input_data):
        if self.i >= len(self.Xs):
            return 0
        input_data(data=self.Xs[self.i], label=self.ys[self.i])
        self.i += 1
        return 1

    def reset(self):
        self.i = 0


@pytest.fixture(scope="module")
def batches():
    X, y = make_binary(3000, 8, seed=0)
    splits = [0, 900, 2000, 2500, 3000]  # uneven batch sizes
    Xs = [X[a:b] for a, b in zip(splits, splits[1:])]
    ys = [y[a:b] for a, b in zip(splits, splits[1:])]
    return X, y, Xs, ys


def test_extmem_matches_incore(batches):
    X, y, Xs, ys = batches
    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
              "max_bin": 64}
    d_ext = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64)
    assert d_ext.num_row() == 3000
    res_e = {}
    bst_e = xtb.train(params, d_ext, 10, evals=[(d_ext, "t")],
                      evals_result=res_e, verbose_eval=False)

    d_in = xtb.QuantileDMatrix(X, label=y, max_bin=64)
    res_i = {}
    bst_i = xtb.train(params, d_in, 10, evals=[(d_in, "t")],
                      evals_result=res_i, verbose_eval=False)
    # sketches differ slightly (batch-merged quantiles), so require close
    # final quality rather than identical trees
    assert abs(res_e["t"]["logloss"][-1] - res_i["t"]["logloss"][-1]) < 0.02


def test_extmem_predict_consistent_with_train(batches):
    X, y, Xs, ys = batches
    d_ext = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "max_bin": 64}, d_ext, 5, verbose_eval=False)
    p = bst.predict(d_ext)
    assert p.shape == (3000,)
    assert ((p > 0.5) == y).mean() > 0.85
    # binned-page predict must agree with raw-value predict on the same rows
    p_raw = bst.predict(xtb.DMatrix(X))
    np.testing.assert_allclose(p, p_raw, atol=1e-5)


def test_extmem_disk_spill(batches, tmp_path):
    X, y, Xs, ys = batches
    d_ext = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=32,
                                      on_host=False)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "max_bin": 32}, d_ext, 3, verbose_eval=False)
    assert np.isfinite(bst.predict(d_ext)).all()


def test_extmem_multidevice_matches_single(batches):
    """extmem x n_devices: page rows sharded over the virtual 8-device mesh
    must reproduce the single-device extmem trees exactly (round-2 item:
    VERDICT removed-NotImplementedError path)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X, y, Xs, ys = batches
    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
              "max_bin": 64}
    d1 = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64)
    b1 = xtb.train(params, d1, 4, verbose_eval=False)
    d8 = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64)
    b8 = xtb.train({**params, "n_devices": 8}, d8, 4, verbose_eval=False)
    # identical-trees is only promised across workers of ONE config (see
    # test_multiprocess); across device counts the f32 reduction grouping
    # differs, so compare quality like the reference's 1-vs-N GPU tests do
    p1, p8 = b1.predict(d1), b8.predict(d8)
    assert np.mean((p1 > 0.5) != (p8 > 0.5)) < 0.01
    ll1 = -np.mean(y * np.log(np.clip(p1, 1e-7, 1)) +
                   (1 - y) * np.log(np.clip(1 - p1, 1e-7, 1)))
    ll8 = -np.mean(y * np.log(np.clip(p8, 1e-7, 1)) +
                   (1 - y) * np.log(np.clip(1 - p8, 1e-7, 1)))
    assert abs(ll1 - ll8) < 0.01, (ll1, ll8)


def test_extmem_single_batch_equals_incore_exactly():
    X, y = make_binary(1024, 6, seed=1)
    params = {"objective": "binary:logistic", "max_depth": 4, "max_bin": 32}
    d_ext = xtb.ExtMemQuantileDMatrix(NumpyBatchIter([X], [y]), max_bin=32)
    d_in = xtb.QuantileDMatrix(X, label=y, max_bin=32)
    bst_e = xtb.train(params, d_ext, 5, verbose_eval=False)
    bst_i = xtb.train(params, d_in, 5, verbose_eval=False)
    # identical cuts (single batch) -> identical trees
    for te, ti in zip(bst_e.trees, bst_i.trees):
        np.testing.assert_array_equal(te.split_indices, ti.split_indices)
        np.testing.assert_array_equal(te.left_children, ti.left_children)
        np.testing.assert_allclose(te.split_conditions, ti.split_conditions,
                                   rtol=1e-5, atol=1e-6)


def test_page_compression(tmp_path, batches):
    """Zstd-compressed pages (the nvCOMP/compressed_iterator role): same
    trees as uncompressed, real RAM savings on binned codes."""
    # environment-limited: without the zstandard package the extmem layer
    # (deliberately) falls back to uncompressed pages with a UserWarning,
    # so there is nothing to measure — the compression contract itself
    # cannot be exercised here
    pytest.importorskip("zstandard",
                        reason="zstandard not installed: pages stay "
                               "uncompressed (graceful-fallback path)")
    from xgboost_tpu.data.extmem import CompressedPage

    X, y, Xs, ys = batches
    params = {"objective": "binary:logistic", "max_depth": 4, "max_bin": 64}
    d_c = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64,
                                    compress=True)
    d_u = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64,
                                    compress=False)
    assert all(isinstance(p, CompressedPage) for p in d_c._pages)
    raw_bytes = sum(p.nbytes for p in d_u._pages)
    comp_bytes = sum(p.nbytes_compressed for p in d_c._pages)
    assert comp_bytes < raw_bytes * 0.8, (comp_bytes, raw_bytes)
    b_c = xtb.train(params, d_c, 4, verbose_eval=False)
    b_u = xtb.train(params, d_u, 4, verbose_eval=False)
    assert b_c.get_dump() == b_u.get_dump()
    np.testing.assert_array_equal(b_c.predict(d_c), b_u.predict(d_u))
    # disk-spilled compressed pages work too
    d_d = xtb.ExtMemQuantileDMatrix(NumpyBatchIter(Xs, ys), max_bin=64,
                                    compress=True, on_host=False)
    b_d = xtb.train(params, d_d, 4, verbose_eval=False)
    assert b_d.get_dump() == b_u.get_dump()


class _ArrayIter(xtb.DataIter):
    def __init__(self, batches):
        super().__init__()
        self._b, self._i = batches, 0

    def reset(self):
        self._i = 0

    def next(self, input_data):
        if self._i >= len(self._b):
            return 0
        input_data(**self._b[self._i])
        self._i += 1
        return 1


def test_sparse_page_dmatrix_raw_predict_and_training():
    """SparsePageDMatrix (sparse_page_dmatrix.h role): raw CSR pages spill,
    training runs through the binned replay, and prediction streams the RAW
    pages with exact float thresholds — including with a model trained on
    different cuts (the flow binned extmem cannot serve)."""
    rng = np.random.default_rng(4)
    X = rng.normal(size=(1200, 5)).astype(np.float32)
    X[rng.random(X.shape) < 0.15] = np.nan
    y = (np.nan_to_num(X[:, 0]) + np.nan_to_num(X[:, 1]) > 0).astype(np.float32)
    batches = [{"data": X[i * 400:(i + 1) * 400],
                "label": y[i * 400:(i + 1) * 400]} for i in range(3)]

    d = xtb.SparsePageDMatrix(_ArrayIter(batches), max_bin=32)
    assert d.num_row() == 1200 and d.num_col() == 5
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                     "max_bin": 32}, d, 3, verbose_eval=False)
    np.testing.assert_array_equal(bst.predict(d),
                                  bst.predict(xtb.DMatrix(X)))

    # a model trained on DIFFERENT cuts predicts on the raw pages exactly
    other = xtb.train({"objective": "binary:logistic", "max_depth": 3,
                       "max_bin": 17}, xtb.DMatrix(X, label=y), 2,
                      verbose_eval=False)
    np.testing.assert_array_equal(other.predict(d),
                                  other.predict(xtb.DMatrix(X)))


def test_sparse_page_dmatrix_scipy_batches_and_sentinel():
    """CSR batches keep explicit valid zeros; a finite missing sentinel is
    filtered structurally at ingestion."""
    import scipy.sparse as sp

    rng = np.random.default_rng(7)
    dense = rng.normal(size=(600, 4)).astype(np.float32)
    dense[rng.random(dense.shape) < 0.3] = 0.0  # explicit zeros stay valid
    y = (dense[:, 0] > 0).astype(np.float32)
    batches = [{"data": sp.csr_matrix(dense[:300]), "label": y[:300]},
               {"data": sp.csr_matrix(dense[300:]), "label": y[300:]}]
    d = xtb.SparsePageDMatrix(_ArrayIter(batches), max_bin=16)
    bst = xtb.train({"objective": "binary:logistic", "max_depth": 2,
                     "max_bin": 16}, d, 2, verbose_eval=False)
    # scipy ingest drops nothing finite; but CSR absent entries ARE missing
    Xnan = np.where(dense == 0.0, np.nan, dense)
    np.testing.assert_array_equal(bst.predict(d),
                                  bst.predict(xtb.DMatrix(Xnan)))

    # finite sentinel: -1 means missing, dropped at ingestion
    dense2 = np.abs(rng.normal(size=(200, 3)).astype(np.float32))
    dense2[rng.random(dense2.shape) < 0.2] = -1.0
    d2 = xtb.SparsePageDMatrix(
        _ArrayIter([{"data": dense2, "label": (dense2[:, 0] > 0.5).astype(np.float32)}]),
        missing=-1.0, max_bin=16)
    b2 = xtb.train({"objective": "binary:logistic", "max_depth": 2,
                    "max_bin": 16}, d2, 2, verbose_eval=False)
    X2 = np.where(dense2 == -1.0, np.nan, dense2)
    np.testing.assert_array_equal(b2.predict(d2),
                                  b2.predict(xtb.DMatrix(X2)))


@pytest.mark.slow
def test_extmem_twenty_pages_mesh_parity(eight_devices):
    """>= 20 zstd pages streamed through the 8-chip sharded grower
    (VERDICT r4 #9): training must match the in-memory mesh model on the
    same rows, and the prefetch=off mode must produce identical trees
    (overlap is a scheduling property, never a numerical one)."""
    import hashlib

    import xgboost_tpu as xtb
    from xgboost_tpu.data.extmem import DataIter, ExtMemQuantileDMatrix

    n_pages, rows_page, F = 20, 1024, 6
    rng = np.random.default_rng(3)
    w = rng.normal(size=F).astype(np.float32)
    X_all = rng.normal(size=(n_pages * rows_page, F)).astype(np.float32)
    y_all = (X_all @ w + rng.normal(scale=0.4, size=len(X_all)) > 0
             ).astype(np.float32)

    class Pages(DataIter):
        def __init__(self):
            super().__init__()
            self._i = 0

        def next(self, input_data):
            if self._i >= n_pages:
                return 0
            lo = self._i * rows_page
            input_data(data=X_all[lo:lo + rows_page],
                       label=y_all[lo:lo + rows_page])
            self._i += 1
            return 1

        def reset(self):
            self._i = 0

    params = {"objective": "binary:logistic", "max_depth": 4, "eta": 0.3,
              "max_bin": 32, "n_devices": 8}
    d = ExtMemQuantileDMatrix(Pages(), max_bin=32)
    assert len(d._pages) == n_pages

    def h(bst):
        return hashlib.md5(
            "".join(bst.get_dump(dump_format="json")).encode()).hexdigest()

    bst = xtb.train(params, d, 3, verbose_eval=False)
    bst_serial = xtb.train({**params, "_extmem_prefetch": "0"}, d, 3,
                           verbose_eval=False)
    assert h(bst) == h(bst_serial)  # prefetch is numerically transparent

    # quality parity vs in-memory mesh training on the same rows (cuts
    # differ: streamed sketch merges per-page grids), so compare quality
    bst_mem = xtb.train(params, xtb.DMatrix(X_all, label=y_all), 3,
                        verbose_eval=False)
    p_ext = bst.predict(d)
    p_mem = bst_mem.predict(xtb.DMatrix(X_all))
    err_ext = np.mean((p_ext > 0.5) != y_all)
    err_mem = np.mean((p_mem > 0.5) != y_all)
    assert err_ext <= err_mem + 0.02, (err_ext, err_mem)


def test_prefetch_overlap_under_simulated_transfer(monkeypatch):
    """Prefetch must actually overlap page transfer with page compute
    (VERDICT r4 #6).  The CPU backend has no real H2D DMA, so a synthetic
    per-byte latency (XTB_EXTMEM_SIM_TRANSFER_MS_PER_MB) stands in: the
    sleep in _put_page yields the core while XLA's async-dispatched page
    compute proceeds — the same concurrency shape as device compute under
    a real transfer.  The matmul hist impl keeps compute comparable to the
    simulated transfer (the TPU-like compute profile); gain is measured as
    serialized wall / prefetch wall over identical trees."""
    import time

    from xgboost_tpu.data.extmem import DataIter, ExtMemQuantileDMatrix

    rng = np.random.default_rng(5)
    n_pages, rows_page, F = 4, 16384, 64
    X_all = rng.normal(size=(n_pages * rows_page, F)).astype(np.float32)
    y_all = (X_all[:, 0] + 0.3 * rng.normal(size=len(X_all)) > 0).astype(
        np.float32)

    class Pages(DataIter):
        def __init__(self):
            super().__init__()
            self._i = 0

        def next(self, input_data):
            if self._i >= n_pages:
                return 0
            lo = self._i * rows_page
            input_data(data=X_all[lo:lo + rows_page],
                       label=y_all[lo:lo + rows_page])
            self._i += 1
            return 1

        def reset(self):
            self._i = 0

    # pages are uint8-binned (16384 x 64 = 1 MB); 400 ms/MB puts the
    # simulated transfer in the same band as the per-page matmul compute
    # (~0.4 s each) — the regime where overlap shows, like a TPU fed over
    # PCIe.  The sleep must dominate the (non-overlappable, host-side)
    # zstd decompress for the measurement to isolate transfer overlap.
    monkeypatch.setenv("XTB_HIST_IMPL", "matmul")
    monkeypatch.setenv("XTB_EXTMEM_SIM_TRANSFER_MS_PER_MB", "400")
    d = ExtMemQuantileDMatrix(Pages(), max_bin=64)
    params = {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
              "max_bin": 64}

    def run(prefetch: str):
        p = {**params, "_extmem_prefetch": prefetch}
        xtb.train(p, d, 1, verbose_eval=False)  # compile warmup
        t0 = time.perf_counter()
        bst = xtb.train(p, d, 2, verbose_eval=False)
        import jax

        jax.block_until_ready(bst._caches[id(d)].margin)
        return time.perf_counter() - t0, bst

    wall_pre, bst_pre = run("1")
    wall_ser, bst_ser = run("0")
    assert bst_pre.get_dump() == bst_ser.get_dump()  # transparency
    gain = wall_ser / wall_pre
    assert gain > 1.2, (wall_ser, wall_pre, gain)
