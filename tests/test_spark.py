"""PySpark frontend protocol (xgboost_tpu/spark.py) without pyspark.

The estimator's partition training body is the dask worker's (shared code
path tested end-to-end with real subprocess workers in tests/test_dask.py);
here we drive the spark-specific pieces — row marshaling, the barrier
mapPartitions body, and parameter plumbing — through the same
subprocess-pair harness, plus the clean gating error without pyspark.
Reference pattern: tests/test_distributed/test_with_spark/test_spark_local.py.
"""
import os
import pickle
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import xgboost_tpu as xtb
from xgboost_tpu.spark import (SparkXGBClassifier, SparkXGBRanker,
                               _partition_train_fn, _rows_to_parts)


def _rows(X, y, qid=None):
    # plain dicts: picklable into the worker subprocesses without this
    # test module on their path (pyspark Rows support the same [] access)
    out = []
    for i in range(len(y)):
        r = {"features": X[i], "label": float(y[i])}
        if qid is not None:
            r["qid"] = int(qid[i])
        out.append(r)
    return out


def test_rows_to_parts_marshaling():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(30, 4)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    qid = np.repeat([0, 1, 2], 10)
    part = _rows_to_parts(_rows(X, y, qid), "features", "label", None, "qid")
    np.testing.assert_array_equal(part["data"], X)
    np.testing.assert_array_equal(part["label"], y)
    np.testing.assert_array_equal(part["group"], [10, 10, 10])

    with pytest.raises(ValueError, match="empty partition"):
        _rows_to_parts([], "features", "label", None, None)
    with pytest.raises(ValueError, match="sorted"):
        _rows_to_parts(_rows(X, y, qid[::-1]), "features", "label", None,
                       "qid")


def test_estimator_param_plumbing():
    clf = SparkXGBClassifier(num_workers=2, max_depth=4, eta=0.3)
    p = clf._train_params()
    assert p["objective"] == "binary:logistic" and p["max_depth"] == 4
    with pytest.raises(ValueError, match="qid_col"):
        SparkXGBRanker(num_workers=1)
    with pytest.raises(ValueError, match="num_workers"):
        SparkXGBClassifier(num_workers=0)


_RUNNER = r"""
import pickle, sys
import jax
jax.config.update("jax_platforms", "cpu")
path = sys.argv[1]
with open(path, "rb") as fh:
    fn_path, args, rows = pickle.load(fh)
import importlib
mod = importlib.import_module("xgboost_tpu.spark")
fn = mod._partition_train_fn(*args)
out = list(fn(rows))
with open(path + ".out", "wb") as fh:
    pickle.dump(out, fh)
"""


@pytest.mark.slow
def test_barrier_partition_fn_two_workers():
    """The mapPartitions body run as two real processes rendezvousing
    through a real tracker: rank 0 yields the model, rank 1 yields
    nothing, and the model has learned."""
    from xgboost_tpu.tracker import RabitTracker

    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 6)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] > 0).astype(np.float32)

    tracker = RabitTracker(n_workers=2, host_ip="127.0.0.1")
    tracker.start()
    targs = tracker.worker_args()
    spec = {"eval_train": False, "verbose_eval": False, "train_kwargs": {},
            "dmatrix_kw": {}}
    fnargs = (str(targs["dmlc_tracker_uri"]),
              int(targs["dmlc_tracker_port"]), 2,
              {"objective": "binary:logistic", "max_depth": 3, "eta": 0.3,
               "max_bin": 32}, 3, spec, "features", "label", None, None)

    tmp = tempfile.mkdtemp(prefix="xtb_spark_")
    procs = []
    for rank in range(2):
        rows = _rows(X[rank::2], y[rank::2])
        path = os.path.join(tmp, f"p{rank}.pkl")
        with open(path, "wb") as fh:
            pickle.dump((None, fnargs, rows), fh)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))
        log = open(path + ".log", "w")
        procs.append((subprocess.Popen(
            [sys.executable, "-c", _RUNNER, path], stdout=log,
            stderr=subprocess.STDOUT, env=env), path))
    outs = []
    for p, path in procs:
        p.wait(timeout=600)
        assert p.returncode == 0, open(path + ".log").read()[-3000:]
        with open(path + ".out", "rb") as fh:
            outs.append(pickle.load(fh))
    tracker.free()

    models = [o for o in outs if o]
    assert len(models) == 1  # exactly rank 0 yields
    out = models[0][0]
    assert "history" in out and "best_iteration" in out
    bst = xtb.Booster()
    bst.load_model(bytearray(out["raw"]))
    preds = bst.predict(xtb.DMatrix(X))
    assert np.mean((preds > 0.5) != y) < 0.1


def test_missing_pyspark_is_clean():
    try:
        import pyspark  # noqa: F401

        pytest.skip("pyspark installed")
    except ImportError:
        pass
    clf = SparkXGBClassifier(num_workers=1)
    with pytest.raises(ImportError, match="pyspark"):
        clf.fit(None)
